"""Per-rank metrics aggregation for ``train_distributed`` gangs.

Each worker process owns its own registry; a gang-wide view needs a
merge. The reference's analog is the socket allreduce of evaluation
stats; here the snapshots are plain dicts, so the merge is host-side
arithmetic over rank-labeled JSONL files:

- every worker appends its end-of-run snapshot to
  ``<tpu_metrics_rank_dir>/rank_<r>.jsonl`` (envelope carries the
  rank);
- after the gang joins, the rank-0 side (the ``train_distributed``
  driver) merges the newest line of every rank file into one gang-wide
  snapshot (``merged.jsonl``) and derives the straggler gauge
  ``dist.round_time_spread`` = max/min of per-rank mean round time —
  a gang whose spread trends up has a straggling worker long before it
  has a timeout.

Merge semantics (MUST be associative — workers can die and relaunch,
so partial merges of partial gangs re-merge; tests pin
``(A ⊕ B) ⊕ C == A ⊕ (B ⊕ C)``):

- **counters** sum;
- **gauges** keep the latest by ``updated_monotonic`` (ties break on
  the larger value — a deterministic total order keeps the fold
  associative when two ranks stamp in the same monotonic instant);
- **histograms** bucket-add (counts/sums add, min-of-mins,
  max-of-maxes). Mismatched bucket layouts — impossible between ranks
  running the same code, possible across versions — degrade to the
  scalar fields with ``buckets: null``, and null propagates through
  further merges (still associative).
"""
from __future__ import annotations

import glob
import json
import os
import re
from typing import Any, Dict, List, Optional, Tuple

__all__ = ["merge_metrics", "merge_snapshots", "dump_rank_snapshot",
           "read_rank_snapshots", "merge_rank_dir", "round_time_spread",
           "merge_chrome_traces", "read_rank_traces"]

_RANK_FILE = "rank_{rank}.jsonl"
_MERGED_FILE = "merged.jsonl"
_RANK_TRACE_GLOB = "rank_*.trace.json"


def _key(m: Dict[str, Any]) -> Tuple[str, str, Tuple[Tuple[str, str], ...]]:
    labels = m.get("labels") or {}
    return (str(m.get("name")), str(m.get("type")),
            tuple(sorted((str(k), str(v)) for k, v in labels.items())))


def merge_metrics(a: Dict[str, Any], b: Dict[str, Any]) -> Dict[str, Any]:
    """Merge two snapshot entries of the same (name, type, labels)."""
    kind = a.get("type")
    out = dict(a)
    out["updated_monotonic"] = max(
        float(a.get("updated_monotonic", 0.0)),
        float(b.get("updated_monotonic", 0.0)))
    if kind == "counter":
        out["value"] = float(a.get("value", 0.0)) \
            + float(b.get("value", 0.0))
        return out
    if kind == "gauge":
        ka = (float(a.get("updated_monotonic", 0.0)),
              float(a.get("value", 0.0)))
        kb = (float(b.get("updated_monotonic", 0.0)),
              float(b.get("value", 0.0)))
        out["value"] = (a if ka >= kb else b).get("value", 0.0)
        return out
    if kind == "histogram":
        out["count"] = int(a.get("count", 0)) + int(b.get("count", 0))
        out["sum"] = float(a.get("sum", 0.0)) + float(b.get("sum", 0.0))
        mins = [v for v in (a.get("min"), b.get("min")) if v is not None]
        maxs = [v for v in (a.get("max"), b.get("max")) if v is not None]
        out["min"] = min(mins) if mins else None
        out["max"] = max(maxs) if maxs else None
        ba, bb = a.get("buckets"), b.get("buckets")
        if (ba is None or bb is None
                or [x[0] for x in ba] != [x[0] for x in bb]):
            # layout mismatch (or a prior mismatch): scalar-only; the
            # null marker propagates so any fold order converges
            out["buckets"] = None
        else:
            out["buckets"] = [[bound, int(ca) + int(cb)]
                              for (bound, ca), (_b2, cb) in zip(ba, bb)]
        return out
    # unknown kinds pass the newer entry through unchanged
    return dict(b) if (float(b.get("updated_monotonic", 0.0))
                       > float(a.get("updated_monotonic", 0.0))) else out


def merge_snapshots(snaps: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Fold rank snapshots into one gang-wide snapshot. Metric order is
    first-seen (rank order), so repeated merges are stable.

    Leaf (per-process) snapshots carry ``updated_monotonic`` stamps on
    each process's OWN monotonic clock — per-boot epochs that are NOT
    comparable across hosts (a 30-days-up host would win every
    latest-gauge tie against a freshly rebooted one). Each leaf
    snapshot's envelope records wall ``ts`` and ``monotonic`` taken at
    the same instant, so the stamps are rebased to wall clock
    (``ts - (monotonic - updated)``) before folding; merged snapshots
    (no ``monotonic`` envelope) are already rebased, keeping re-merges
    associative."""
    merged: Dict[Tuple, Dict[str, Any]] = {}
    ranks: List[int] = []
    ts = 0.0
    for snap in snaps:
        if not snap:
            continue
        ts = max(ts, float(snap.get("ts", 0.0)))
        r = snap.get("rank")
        if r is not None:
            ranks.append(int(r))
        # already-merged inputs keep their provenance (partial gang
        # merges re-merge associatively, envelope included)
        ranks.extend(int(x)
                     for x in snap.get("merged_from_ranks", []))
        mono = snap.get("monotonic")
        wall = snap.get("ts")
        for m in snap.get("metrics", []):
            if mono is not None and wall is not None:
                m = dict(m)
                m["updated_monotonic"] = float(wall) - (
                    float(mono) - float(m.get("updated_monotonic",
                                              mono)))
            k = _key(m)
            merged[k] = (merge_metrics(merged[k], m) if k in merged
                         else dict(m))
    return {
        "schema": "lightgbm-tpu-metrics-v1",
        "ts": ts,
        "merged_from_ranks": sorted(set(ranks)),
        "metrics": list(merged.values()),
    }


def round_time_spread(snaps: List[Dict[str, Any]]) -> Optional[float]:
    """Straggler gauge: max/min of per-rank MEAN ``train/round`` time.
    None when fewer than one rank carries round timings; 1.0 = a
    perfectly even gang."""
    means = []
    for snap in snaps or []:
        for m in snap.get("metrics", []):
            if (m.get("name") == "train/round"
                    and m.get("type") == "histogram"
                    and not m.get("labels") and int(m.get("count", 0))):
                means.append(float(m.get("sum", 0.0))
                             / int(m.get("count")))
    if not means or min(means) <= 0:
        return None
    return max(means) / min(means)


# ---------------------------------------------------------------------------
# rank-file plumbing (workers dump, the driver merges)
# ---------------------------------------------------------------------------
def dump_rank_snapshot(directory: str, rank: int,
                       snap: Optional[Dict[str, Any]] = None) -> str:
    """Append this process's snapshot (rank-tagged envelope) to
    ``<directory>/rank_<rank>.jsonl``."""
    from . import snapshot as take_snapshot
    from .metrics import registry
    if snap is None:
        snap = take_snapshot()
    snap = dict(snap)
    snap["rank"] = int(rank)
    path = os.path.join(str(directory),
                        _RANK_FILE.format(rank=int(rank)))
    return registry().dump_jsonl(path, snap)


def read_rank_snapshots(directory: str) -> List[Dict[str, Any]]:
    """Newest snapshot line of every ``rank_*.jsonl`` in ``directory``
    (rank order). Unreadable/corrupt files are skipped — a rank killed
    mid-write must not poison the gang view."""
    out: List[Dict[str, Any]] = []
    pattern = os.path.join(str(directory), "rank_*.jsonl")

    def _rank_of(path: str) -> int:
        m = re.search(r"rank_(\d+)\.jsonl$", path)
        return int(m.group(1)) if m else 1 << 30
    for path in sorted(glob.glob(pattern), key=_rank_of):
        try:
            with open(path) as f:
                lines = [ln for ln in f.read().splitlines()
                         if ln.strip()]
            if lines:
                out.append(json.loads(lines[-1]))
        except (OSError, ValueError):
            continue
    return out


def merge_rank_dir(directory: str,
                   write: bool = True) -> Optional[Dict[str, Any]]:
    """Merge the newest per-rank snapshots under ``directory`` into one
    gang-wide snapshot; append it to ``merged.jsonl`` when ``write``.
    The merge itself runs under a span (it IS this layer's histogram
    allreduce) and the straggler gauge rides the merged snapshot AND
    the live registry so a scrape of the driver sees it."""
    import time

    from . import registry, span
    snaps = read_rank_snapshots(directory)
    if not snaps:
        return None
    with span("obs/rank_merge", force=True, ranks=len(snaps)):
        merged = merge_snapshots(snaps)
        spread = round_time_spread(snaps)
        if spread is not None:
            reg = registry()
            reg.gauge("dist.round_time_spread").set(spread)
            entry = reg.get("dist.round_time_spread").snapshot()
            # merged snapshots carry WALL-rebased stamps (see
            # merge_snapshots); the driver-local monotonic stamp would
            # lose latest-wins re-merges to any longer-booted driver
            entry["updated_monotonic"] = time.time()
            merged["metrics"].append(entry)
    if write:
        registry().dump_jsonl(
            os.path.join(str(directory), _MERGED_FILE), merged)
    return merged


# ---------------------------------------------------------------------------
# cross-rank Chrome-trace merge (scripts/trace_merge.py drives this)
# ---------------------------------------------------------------------------
def read_rank_traces(directory: str) -> List[str]:
    """Paths of every ``rank_*.trace.json`` under ``directory``, rank
    order (the per-rank exports obs/tracing.py writes when a trace
    rank is set)."""
    pattern = os.path.join(str(directory), _RANK_TRACE_GLOB)

    def _rank_of(path: str) -> int:
        m = re.search(r"rank_(\d+)\.trace\.json$", path)
        return int(m.group(1)) if m else 1 << 30
    return sorted(glob.glob(pattern), key=_rank_of)


def merge_chrome_traces(paths: List[str]) -> Dict[str, Any]:
    """Merge per-rank Chrome-trace exports into ONE Perfetto-loadable
    timeline.

    Each rank's event ``ts`` values sit on that process's OWN
    monotonic clock — per-boot epochs that are NOT comparable across
    hosts. Each export's envelope (``otherData``) records a wall
    ``ts`` and ``monotonic`` stamp taken at the same instant — the
    SAME rebase contract :func:`merge_snapshots` uses for gauge
    stamps — so every event rebases to wall microseconds
    (``(wall - monotonic) * 1e6 + ts``) before merging. The merged
    document then shifts to a zero base (Perfetto renders offsets, not
    epochs), keeps each rank's ``process_name``/``process_sort_index``
    metadata rows (rank-named process rows), and sums the per-rank
    dropped-event counts. A file without the envelope cannot rebase:
    it overlays from the merged zero point (its own earliest event)
    and is flagged in ``otherData.unrebased_ranks`` — visibly
    misaligned beats silently dropped, and it must never anchor the
    zero base (its raw monotonic epoch would shove the rebased ranks
    decades off-screen).

    Raises ValueError when no readable trace file was given."""
    docs: List[Tuple[str, Dict[str, Any]]] = []
    for path in paths:
        try:
            with open(path) as f:
                docs.append((path, json.load(f)))
        except (OSError, ValueError):
            continue
    if not docs:
        raise ValueError("no readable Chrome-trace files to merge")
    merged_events: List[Dict[str, Any]] = []
    per_rank_events: List[Tuple[bool, List[Dict[str, Any]]]] = []
    ranks: List[int] = []
    unrebased: List[int] = []
    dropped = 0
    for idx, (path, doc) in enumerate(docs):
        other = doc.get("otherData") or {}
        rank = other.get("rank")
        if rank is None:
            # pre-rank-tagging export (or a hand-made file): key the
            # process row off the file order so rows never collide
            m = re.search(r"rank_(\d+)\.trace\.json$", path)
            rank = int(m.group(1)) if m else idx
        rank = int(rank)
        ranks.append(rank)
        dropped += int(other.get("dropped_events", 0) or 0)
        wall, mono = other.get("ts"), other.get("monotonic")
        rebased = wall is not None and mono is not None
        if not rebased:
            unrebased.append(rank)
        off_us = ((float(wall) - float(mono)) * 1e6 if rebased
                  else 0.0)
        seen_process_name = False
        timed: List[Dict[str, Any]] = []
        for ev in doc.get("traceEvents", []):
            ev = dict(ev, pid=rank)
            if ev.get("ph") == "M":
                if ev.get("name") == "process_name":
                    seen_process_name = True
            elif "ts" in ev:
                ev["ts"] = float(ev["ts"]) + off_us
                timed.append(ev)
            merged_events.append(ev)
        per_rank_events.append((rebased, timed))
        if not seen_process_name:
            merged_events.append({
                "name": "process_name", "ph": "M", "pid": rank,
                "args": {"name": f"rank {rank}"}})
    # zero-base the timeline over the REBASED ranks only: Perfetto
    # displays offsets, and epoch wall microseconds would render as a
    # useless 50-year pan — while an envelope-less rank's raw
    # monotonic stamps, if allowed to anchor the minimum, would push
    # every GOOD rank's events that same 50 years out the other way
    rebased_ts = [e["ts"] for ok, timed in per_rank_events if ok
                  for e in timed]
    t0 = min(rebased_ts, default=None)
    if t0 is None:
        # nothing rebased: fall back to the global minimum
        t0 = min((e["ts"] for _ok, timed in per_rank_events
                  for e in timed), default=0.0)
    for ok, timed in per_rank_events:
        # an unrebased rank overlays from the zero point (its own
        # earliest event) — visibly misaligned beats unviewable
        base = t0 if ok else min((e["ts"] for e in timed),
                                 default=t0)
        for ev in timed:
            ev["ts"] -= base
    return {
        "displayTimeUnit": "ms",
        "traceEvents": merged_events,
        "otherData": {
            "producer": "lightgbm-tpu obs trace_merge",
            "merged_from_ranks": sorted(set(ranks)),
            "dropped_events": dropped,
            "unrebased_ranks": sorted(set(unrebased)),
            # epoch of the zero point: wall seconds when any rank
            # carried the envelope (absolute time is recoverable),
            # the first rank's raw monotonic otherwise
            "ts": t0 / 1e6,
        },
    }
