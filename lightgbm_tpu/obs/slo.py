"""Windowed SLIs and SLO evaluation: rolling quantiles over a time ring.

PR 4's registry is *passive* — cumulative counters and histograms you
snapshot after the fact. A load balancer probing ``/readyz`` or an
alert on serving latency needs *windowed* signals: "p99 predict latency
over the last five minutes", not "p99 since process start". The pieces:

- :class:`SlidingHistogram` — a ring of time-bucketed sub-histograms
  (same value-bucket ladder the cumulative :class:`~.metrics.Histogram`
  uses). Each ``observe`` lands in the ring slot for its time bucket;
  slots are lazily recycled as the clock advances, so memory is
  ``O(slots × value_buckets)`` forever. ``quantile(q, window_s)``
  aggregates the live slots and interpolates inside the selected value
  bucket — estimates are within one value-bucket width of the exact
  windowed percentile (tests pin this against ``numpy.percentile``).
- :class:`SlidingCounter` — the same ring for counts, giving windowed
  rates/ratios (error ratio, cache hit ratio).
- :class:`SloTracker` — feeds the watched metric names (wired under the
  EXISTING span/histogram names — ``predict/call``, ``train/round`` —
  so the SLI and the cumulative metric can never measure different
  events), derives SLO gauges into the registry on :meth:`evaluate`,
  and compares them against configured thresholds: a breach flips the
  ``slo.breached{slo=...}`` gauge to 1 and increments the
  ``slo.breaches{slo=...}`` counter on each transition into breach.

Off by default (``obs.enable(slo=True)`` / ``tpu_metrics_port`` /
``tpu_slo_*`` knobs turn it on); when off, the hot-path cost is the
metrics pillar's existing one-bool check — the feed call sites are
never reached. Every method takes an optional ``now`` (monotonic
seconds) so tests drive the clock deterministically.

SLO state is process-local by design: windows describe *this
process's* recent behavior, so it does not ride checkpoints
(``obs.export_state`` excludes ``slo.*``/``heartbeat.*``), unlike the
cumulative metrics that resume bit-exact.
"""
from __future__ import annotations

import threading
import time
from bisect import bisect_left
from typing import Any, Callable, Dict, List, Optional, Tuple

from .metrics import DEFAULT_BUCKETS, registry

__all__ = ["SlidingHistogram", "SlidingCounter", "SloTracker",
           "tracker", "enable", "enabled", "reset", "feed_hist",
           "feed_count", "evaluate", "set_queue_depth_provider",
           "clear_queue_depth_provider",
           "DEFAULT_WINDOW_S", "DEFAULT_SLOTS"]

# ``slo.queue_depth`` provider: the serving service (serve/service.py)
# registers a zero-arg callable returning its live request-queue depth;
# compute() samples it per evaluation period. None (no service in this
# process) reads as an empty queue — the gauge stays 0 so dashboards
# wired before the service starts keep rendering.
_queue_depth_provider: Optional[Callable[[], float]] = None


def set_queue_depth_provider(
        fn: Optional[Callable[[], float]]) -> None:
    """Register (or, with None, clear) the live queue-depth source for
    the ``slo.queue_depth`` gauge."""
    global _queue_depth_provider
    _queue_depth_provider = fn


def clear_queue_depth_provider(fn: Callable[[], float]) -> bool:
    """Clear the provider only if it is still ``fn``: a dying service
    must not zero out the gauge a NEWER service (blue/green restart in
    one process) has since registered."""
    global _queue_depth_provider
    if _queue_depth_provider is fn:
        _queue_depth_provider = None
        return True
    return False

# 5-minute default window in 10 s slots: the Prometheus-default scrape
# cadence (15 s) sees each slot a few times before it recycles
DEFAULT_WINDOW_S = 300.0
DEFAULT_SLOTS = 30


class _TimeRing:
    """Shared ring bookkeeping: ``slots`` recycled sub-accumulators,
    each covering ``bucket_s = window_s / slots`` of wall time. A slot
    is valid for a window ending at ``now`` iff its epoch (absolute
    time-bucket index) is within the trailing window."""

    def __init__(self, window_s: float, slots: int):
        if window_s <= 0 or slots <= 0:
            raise ValueError("window_s and slots must be positive")
        self.window_s = float(window_s)
        self.slots = int(slots)
        self.bucket_s = self.window_s / self.slots
        self._epochs = [-1] * self.slots
        self._lock = threading.Lock()

    def _slot_for(self, now: float) -> int:
        """Return the ring index for ``now``, recycling the slot if a
        previous epoch still occupies it. Caller holds the lock."""
        epoch = int(now // self.bucket_s)
        s = epoch % self.slots
        if self._epochs[s] != epoch:
            self._clear_slot(s)
            self._epochs[s] = epoch
        return s

    def _valid_slots(self, window_s: Optional[float],
                     now: float) -> List[int]:
        """Ring indices whose epoch falls inside the trailing window
        ``(now - window_s, now]``. Caller holds the lock."""
        w = self.window_s if window_s is None else min(float(window_s),
                                                      self.window_s)
        epoch_now = int(now // self.bucket_s)
        n_back = max(1, int(-(-w // self.bucket_s)))   # ceil
        return [s for s, e in enumerate(self._epochs)
                if e >= 0 and epoch_now - e < n_back]

    def _clear_slot(self, s: int) -> None:  # pragma: no cover - abstract
        raise NotImplementedError


class SlidingHistogram(_TimeRing):
    """Rolling distribution: time ring of value-bucket count vectors."""

    def __init__(self, bounds: Optional[Tuple[float, ...]] = None,
                 window_s: float = DEFAULT_WINDOW_S,
                 slots: int = DEFAULT_SLOTS):
        b = tuple(bounds or DEFAULT_BUCKETS)
        if b[-1] != float("inf"):
            b = b + (float("inf"),)
        self.bounds = b
        super().__init__(window_s, slots)
        self._counts = [[0] * len(b) for _ in range(self.slots)]
        # exact per-slot value sums ride along with the bucket counts:
        # windowed RATIOS of durations (e.g. slo.device_share =
        # dispatch busy over batch busy) need sums, and deriving them
        # from bucket midpoints would compound two bucket-width errors
        self._sums = [0.0] * self.slots

    def _clear_slot(self, s: int) -> None:
        """Recycle one ring slot. Caller holds the lock (_slot_for)."""
        self._counts[s] = [0] * len(self.bounds)
        self._sums[s] = 0.0

    def observe(self, v: float, now: Optional[float] = None) -> None:
        now = time.monotonic() if now is None else now
        b = bisect_left(self.bounds, float(v))
        with self._lock:
            s = self._slot_for(now)
            self._counts[s][b] += 1
            self._sums[s] += float(v)

    def total(self, window_s: Optional[float] = None,
              now: Optional[float] = None) -> float:
        """Windowed sum of observed values (exact, not bucket-derived)."""
        now = time.monotonic() if now is None else now
        with self._lock:
            return sum(self._sums[s]
                       for s in self._valid_slots(window_s, now))

    def _window_counts(self, window_s: Optional[float],
                       now: float) -> List[int]:
        with self._lock:
            agg = [0] * len(self.bounds)
            for s in self._valid_slots(window_s, now):
                row = self._counts[s]
                for i in range(len(agg)):
                    agg[i] += row[i]
            return agg

    def count(self, window_s: Optional[float] = None,
              now: Optional[float] = None) -> int:
        now = time.monotonic() if now is None else now
        return sum(self._window_counts(window_s, now))

    def quantile(self, q: float, window_s: Optional[float] = None,
                 now: Optional[float] = None) -> Optional[float]:
        """Windowed quantile estimate (``q`` in [0, 1]); None when the
        window holds no observations. Linear interpolation inside the
        selected value bucket bounds the error to one bucket width; the
        open-ended +Inf bucket degrades to its finite lower bound (the
        ladder tops out at 60 s — minutes-long predict calls saturate
        rather than extrapolate)."""
        return self.quantiles((q,), window_s=window_s, now=now)[0]

    def quantiles(self, qs, window_s: Optional[float] = None,
                  now: Optional[float] = None) -> List[Optional[float]]:
        """Several quantiles from ONE ring aggregation (one lock hold
        per scrape instead of one per percentile — the scrape path
        contends with ``observe`` on the hot predict path)."""
        now = time.monotonic() if now is None else now
        counts = self._window_counts(window_s, now)
        total = sum(counts)
        if total == 0:
            return [None] * len(qs)
        out: List[Optional[float]] = []
        for q in qs:
            target = max(0.0, min(1.0, float(q))) * total
            cum = 0
            value: Optional[float] = None
            for i, c in enumerate(counts):
                if c == 0:
                    continue
                if cum + c >= target:
                    lo = self.bounds[i - 1] if i > 0 else 0.0
                    hi = self.bounds[i]
                    if hi == float("inf"):
                        value = self.bounds[i - 1] if i > 0 else 0.0
                    else:
                        value = lo + (hi - lo) * ((target - cum) / c)
                    break
                cum += c
            out.append(value)
        return out


class SlidingCounter(_TimeRing):
    """Rolling sum: time ring of per-slot float accumulators."""

    def __init__(self, window_s: float = DEFAULT_WINDOW_S,
                 slots: int = DEFAULT_SLOTS):
        super().__init__(window_s, slots)
        self._sums = [0.0] * self.slots

    def _clear_slot(self, s: int) -> None:
        """Recycle one ring slot. Caller holds the lock (_slot_for)."""
        self._sums[s] = 0.0

    def inc(self, n: float = 1.0, now: Optional[float] = None) -> None:
        now = time.monotonic() if now is None else now
        with self._lock:
            self._sums[self._slot_for(now)] += float(n)

    def total(self, window_s: Optional[float] = None,
              now: Optional[float] = None) -> float:
        now = time.monotonic() if now is None else now
        with self._lock:
            return sum(self._sums[s]
                       for s in self._valid_slots(window_s, now))


# ---------------------------------------------------------------------------
# the tracker: watched names -> windows -> derived SLO gauges
# ---------------------------------------------------------------------------
# histogram feeds ride the EXISTING span names so the SLI and the
# cumulative histogram measure the same events by construction. The
# serve/* stages are the request-lifecycle decomposition the serving
# dispatch loop records (serve/service.py): per-request queue wait
# and end-to-end latency, per-batch coalesce/checkout/dispatch/
# postprocess — windowing them is what turns "p99 breached" into
# "p99 breached BECAUSE queue wait doubled" on a live /metrics scrape
WATCHED_HISTOGRAMS = ("predict/call", "train/round",
                      "serve/queue_wait", "serve/e2e", "serve/batch",
                      "serve/coalesce", "serve/registry_checkout",
                      "serve/dispatch", "serve/postprocess",
                      "serve/explain")
WATCHED_COUNTERS = ("predict.requests", "predict.errors",
                    "predict.stack_cache_hits",
                    "predict.stack_cache_misses")
# threshold key -> the SLI gauge it compares against; unknown keys are
# rejected at enable time (a typo'd threshold must not silently watch
# the wrong signal)
THRESHOLD_SLIS = {"predict_p99_ms": "slo.predict_p99_ms",
                  "error_ratio": "slo.error_ratio"}


class SloTracker:
    """Windowed SLI state + threshold evaluation for one process.

    ``thresholds`` keys (each 0/absent = no threshold, gauge-only):

    - ``predict_p99_ms`` — breach when the rolling predict p99 exceeds
      this many milliseconds (``tpu_slo_predict_p99_ms``);
    - ``error_ratio`` — breach when windowed
      ``predict.errors / predict.requests`` exceeds this fraction
      (``tpu_slo_error_ratio``).
    """

    def __init__(self, window_s: float = DEFAULT_WINDOW_S,
                 slots: int = DEFAULT_SLOTS,
                 thresholds: Optional[Dict[str, float]] = None):
        self.window_s = float(window_s)
        self.hists = {name: SlidingHistogram(window_s=window_s,
                                             slots=slots)
                      for name in WATCHED_HISTOGRAMS}
        self.counters = {name: SlidingCounter(window_s=window_s,
                                              slots=slots)
                         for name in WATCHED_COUNTERS}
        self._breached: Dict[str, bool] = {}
        self._lock = threading.Lock()
        self.thresholds: Dict[str, float] = {}
        for k, v in (thresholds or {}).items():
            self.set_threshold(k, v)

    def set_threshold(self, key: str, value) -> None:
        """Add/replace one SLO threshold; unknown keys are rejected
        loudly (<=0 values are ignored — the config's "no threshold").
        Locked against evaluate(): a mid-run Config can add a
        threshold while a scrape thread iterates them."""
        if key not in THRESHOLD_SLIS:
            from ..utils import log
            log.warning(f"unknown SLO threshold {key!r} ignored "
                        f"(known: {sorted(THRESHOLD_SLIS)})")
            return
        if value and float(value) > 0:
            with self._lock:
                self.thresholds[key] = float(value)

    # -- feeds (called from obs.span/inc/observe when slo is on) -------
    def feed_hist(self, name: str, value: float,
                  now: Optional[float] = None) -> None:
        h = self.hists.get(name)
        if h is not None:
            h.observe(value, now=now)

    def feed_count(self, name: str, n: float = 1.0,
                   now: Optional[float] = None) -> None:
        c = self.counters.get(name)
        if c is not None:
            c.inc(n, now=now)

    # -- evaluation ----------------------------------------------------
    def compute(self, now: Optional[float] = None) -> Dict[str, Any]:
        """Current SLI values (None where the window is empty) without
        touching the registry."""
        now = time.monotonic() if now is None else now
        p50, p95, p99 = self.hists["predict/call"].quantiles(
            (0.50, 0.95, 0.99), now=now)
        r50, r99 = self.hists["train/round"].quantiles(
            (0.50, 0.99), now=now)
        qw50, qw99 = self.hists["serve/queue_wait"].quantiles(
            (0.50, 0.99), now=now)
        d99 = self.hists["serve/dispatch"].quantile(0.99, now=now)
        # explain (pred_contrib) riders' end-to-end latency: their own
        # window, so a mixed predict+explain workload's p99 target can
        # be held per kind (serve/service.py feeds serve/explain
        # alongside serve/e2e for contrib batches only)
        x99 = self.hists["serve/explain"].quantile(0.99, now=now)

        def ms(v):
            return None if v is None else v * 1000.0
        requests = self.counters["predict.requests"].total(now=now)
        errors = self.counters["predict.errors"].total(now=now)
        hits = self.counters["predict.stack_cache_hits"].total(now=now)
        misses = self.counters[
            "predict.stack_cache_misses"].total(now=now)
        # device share: windowed dispatch busy over batch busy — of
        # the dispatch loop's per-batch processing wall, the fraction
        # spent inside the bucketed predict (the device-bound stage)
        # vs host-side coalesce/checkout/postprocess. Same-unit sums
        # (both per batch); queue pressure is the queue_wait gauges'
        # separate axis.
        disp_sum = self.hists["serve/dispatch"].total(now=now)
        batch_sum = self.hists["serve/batch"].total(now=now)
        out: Dict[str, Any] = {
            "slo.predict_p50_ms": ms(p50),
            "slo.predict_p95_ms": ms(p95),
            "slo.predict_p99_ms": ms(p99),
            "slo.round_p50_s": r50,
            "slo.round_p99_s": r99,
            "slo.queue_wait_p50_ms": ms(qw50),
            "slo.queue_wait_p99_ms": ms(qw99),
            "slo.dispatch_p99_ms": ms(d99),
            "slo.explain_p99_ms": ms(x99),
            "slo.device_share": (min(disp_sum / batch_sum, 1.0)
                                 if batch_sum > 0 else None),
            "slo.error_ratio": (errors / requests if requests else None),
            "predict.cache_hit_ratio": (hits / (hits + misses)
                                        if (hits + misses) else None),
            # live queue depth from the serving service's registered
            # provider (serve/service.py); 0 when no service runs in
            # this process — never None, so the dashboard panel exists
            # from the first scrape
            "slo.queue_depth": self._queue_depth(),
        }
        return out

    @staticmethod
    def _queue_depth() -> float:
        fn = _queue_depth_provider
        if fn is None:
            return 0.0
        try:
            return float(fn())
        except Exception:
            # a dying service must not take the scrape path down
            return 0.0

    def evaluate(self, now: Optional[float] = None) -> Dict[str, Any]:
        """Refresh the SLO gauges in the process registry and run the
        threshold comparisons. Called before every snapshot/scrape (one
        evaluation period == one scrape), or directly."""
        now = time.monotonic() if now is None else now
        slis = self.compute(now=now)
        reg = registry()
        for name, v in slis.items():
            if v is not None:
                reg.gauge(name).set(v)
            elif reg.get(name) is not None:
                # the window drained: a frozen last-value gauge would
                # read as live forever — drop it so the exposition says
                # "no data" instead of "still 800 ms"
                reg.reset(prefix=name, kind="gauge")
        with self._lock:
            for key, limit in self.thresholds.items():
                current = slis.get(THRESHOLD_SLIS[key])
                breached = current is not None and current > limit
                reg.gauge("slo.breached", slo=key).set(
                    1.0 if breached else 0.0)
                if breached and not self._breached.get(key, False):
                    reg.counter("slo.breaches", slo=key).inc()
                self._breached[key] = breached
        return slis


# ---------------------------------------------------------------------------
# process-wide singleton + module-level funnels (obs/__init__ calls these)
# ---------------------------------------------------------------------------
_lock = threading.Lock()
_tracker: Optional[SloTracker] = None


def tracker() -> Optional[SloTracker]:
    return _tracker


def enabled() -> bool:
    return _tracker is not None


def enable(window_s: Optional[float] = None,
           thresholds: Optional[Dict[str, float]] = None,
           slots: int = DEFAULT_SLOTS) -> SloTracker:
    """Create (or update) the process tracker. Enable-only and
    additive, like the rest of the obs config wiring: a later enable
    merges thresholds into the live tracker instead of dropping its
    window state; a DIFFERENT window on a live tracker warns and keeps
    the first (the rings are sized at creation)."""
    global _tracker
    with _lock:
        if _tracker is None:
            _tracker = SloTracker(
                window_s=window_s or DEFAULT_WINDOW_S, slots=slots,
                thresholds=thresholds)
        else:
            if window_s and float(window_s) != _tracker.window_s:
                from ..utils import log
                log.warning(
                    f"tpu_slo_window_s={window_s} ignored: SLO windows "
                    f"are already sized at {_tracker.window_s:g}s "
                    f"(process-global; restart to resize)")
            for k, v in (thresholds or {}).items():
                _tracker.set_threshold(k, v)
        return _tracker


def reset() -> None:
    """Drop the tracker (window state AND thresholds) and any
    registered queue-depth provider. Tests only."""
    global _tracker, _queue_depth_provider
    with _lock:
        _tracker = None
        _queue_depth_provider = None


def feed_hist(name: str, value: float,
              now: Optional[float] = None) -> None:
    t = _tracker
    if t is not None:
        t.feed_hist(name, value, now=now)


def feed_count(name: str, n: float = 1.0,
               now: Optional[float] = None) -> None:
    t = _tracker
    if t is not None:
        t.feed_count(name, n, now=now)


def evaluate(now: Optional[float] = None) -> Optional[Dict[str, Any]]:
    t = _tracker
    return None if t is None else t.evaluate(now=now)
