"""Device/compile telemetry: jax-side probes promoted to obs metrics.

Three classes of device signal become first-class metrics here instead
of test-only assertions:

- **Compile events.** ``utils/debug.py``'s :class:`CompileWatch` counts
  XLA compile requests inside a scoped test block; production needs the
  same signal continuously — a recompile storm in warm serving is an
  outage precursor. One process-wide ``jax.monitoring`` listener feeds
  the ``compile.requests`` counter (same event prefix CompileWatch
  keys on, imported so the two can never drift).
- **Program cache sizes.** The bounded-compile-cache guarantees
  (predict bucketing, ingest fixed-shape chunking) become gauges:
  ``compile.predict_programs`` / ``compile.ingest_programs``.
- **HBM occupancy.** ``utils/hbm.py``'s limit probe plus the runtime's
  ``memory_stats`` become ``hbm.bytes_limit`` / ``hbm.bytes_in_use`` /
  ``hbm.peak_bytes_in_use``, so HBM creep shows up as a metric trend,
  not a device OOM.

Everything here tolerates jax being absent/uninitialized (CPU CI,
pre-import probes): failures degrade to missing gauges, never raise.
"""
from __future__ import annotations

from typing import Optional

from .metrics import registry

# the compile-request event prefix — imported from utils/debug.py so
# CompileWatch (scoped, test-facing) and this listener (continuous,
# metric-facing) count the same thing by construction
try:
    from ..utils.debug import _COMPILE_EVENT_PREFIX as COMPILE_EVENT_PREFIX
except Exception:  # pragma: no cover - debug.py is a sibling module
    COMPILE_EVENT_PREFIX = "/jax/compilation_cache/compile_requests"

__all__ = ["COMPILE_EVENT_PREFIX", "ensure_compile_listener",
           "compile_requests", "refresh_device_gauges"]

_listener_registered = False
_listener_active = False


def _listener(event: str, **kwargs) -> None:
    if _listener_active and event.startswith(COMPILE_EVENT_PREFIX):
        registry().counter("compile.requests").inc()


def ensure_compile_listener() -> bool:
    """Register the process-wide compile-event listener (idempotent).
    Returns True when the listener is live. The listener itself is
    gated by an active flag so ``obs.disable()`` makes it inert without
    touching jax's listener list (we never unregister — other watchers'
    listeners are not ours to reorder)."""
    global _listener_registered, _listener_active
    if not _listener_registered:
        try:
            from jax import monitoring
            monitoring.register_event_listener(_listener)
            _listener_registered = True
        except Exception:
            return False
    _listener_active = True
    return True


def pause_compile_listener() -> None:
    global _listener_active
    _listener_active = False


def compile_requests() -> float:
    """Compile requests counted since the listener went live."""
    m = registry().get("compile.requests")
    return float(getattr(m, "value", 0.0))


def _memory_stats() -> Optional[dict]:
    try:
        import jax
        return jax.devices()[0].memory_stats() or {}
    except Exception:
        return None


def refresh_device_gauges() -> None:
    """Refresh the point-in-time device gauges (called before every
    snapshot export). Each probe is independently best-effort."""
    reg = registry()
    stats = _memory_stats()
    if stats:
        for key, gname in (("bytes_limit", "hbm.bytes_limit"),
                           ("bytes_in_use", "hbm.bytes_in_use"),
                           ("peak_bytes_in_use", "hbm.peak_bytes_in_use")):
            v = stats.get(key)
            if v is not None:
                reg.gauge(gname).set(float(v))
    try:
        from ..utils.debug import predict_program_cache_size
        reg.gauge("compile.predict_programs").set(
            float(predict_program_cache_size()))
    except Exception:
        pass
    try:
        from ..utils.debug import ingest_program_cache_size
        reg.gauge("compile.ingest_programs").set(
            float(ingest_program_cache_size()))
    except Exception:
        pass
    try:
        from .tracing import dropped_events, tracing_enabled
        if tracing_enabled():
            reg.gauge("trace.dropped_events").set(float(dropped_events()))
    except Exception:
        pass
