"""Trace-level attribution: raw XSpace (``*.xplane.pb``) op aggregation.

docs/perf.md's "Trace-level attribution" table (the r5 measurement
that pins ~67% of device busy on the histogram scan, ~9% on loop-state
``%copy`` and a ~10 ms/iter wall-vs-busy gap) was built from a ~20-line
ad-hoc parse of ``jax.profiler``'s xplane dump — the TensorBoard
converter is protobuf-incompatible in this environment. This module
promotes that parse into the obs plane proper:

- a dependency-free protobuf **wire-format** reader (stdlib only — the
  obs package's import-light constraint; no ``protobuf``, no jax) for
  the XSpace schema subset the attribution needs: ``XSpace.planes``,
  ``XPlane.name/lines/event_metadata``, ``XLine.name/timestamp_ns/
  events``, ``XEvent.metadata_id/offset_ps/duration_ps/
  num_occurrences``, ``XEventMetadata.id/name``;
- per-op busy aggregation over the device plane's "XLA Ops" line,
  the ``%copy`` share (the loop-state-copy signal the donation pass
  exists to squeeze), the collective share (the all-reduce busy the
  ``tpu_stream_overlap`` pipeline hides behind compute), and the
  per-iteration wall-vs-busy gap;
- :func:`profile_gauges` feeds the result into the metrics registry as
  ``train.copy_share`` / ``train.comm_share`` /
  ``train.wall_busy_gap_ms`` — the same obs
  plane scripts/check.sh snapshots and scripts/obs_trend.py guards, so
  a ``%copy`` regression fails CI like an iters/sec regression does.

Consumed by ``engine.train`` (after a ``tpu_profile_dir`` trace stops),
``bench.py --profile-dir``, and the ``scripts/trace_attr.py`` CLI.
CPU-backend traces carry no device op line (host threads only); every
entry point degrades to "no device plane found" instead of failing the
run that produced the trace.
"""
from __future__ import annotations

import os
from typing import Any, Dict, Iterator, List, Optional, Tuple

__all__ = ["parse_xspace", "aggregate_ops", "attribute",
           "newest_xplane", "profile_gauges"]

# ops counted as loop-state / buffer copies in the share metric: HLO
# names like "copy.1234", "%copy", "copy-start.5"/"copy-done.5" (async
# copy pairs) — matched on the base name before the ".N" suffix
_COPY_BASES = ("copy", "copy-start", "copy-done")

# ops counted as cross-device communication in the comm share metric:
# the collectives the sharded trainer/predictor can emit (sync forms
# plus the async -start/-done pairs XLA splits them into). comm_share
# is the number the tpu_stream_overlap pipeline moves: overlapped
# collectives show the same comm busy but a smaller wall-vs-busy gap.
_COMM_BASES = ("all-reduce", "all-reduce-start", "all-reduce-done",
               "reduce-scatter", "all-gather", "all-gather-start",
               "all-gather-done", "collective-permute",
               "collective-permute-start", "collective-permute-done",
               "all-to-all")


# ---------------------------------------------------------------------------
# protobuf wire format (the ~20 lines, hardened)
# ---------------------------------------------------------------------------
def _varint(buf: bytes, i: int) -> Tuple[int, int]:
    x = 0
    s = 0
    while True:
        b = buf[i]
        i += 1
        x |= (b & 0x7F) << s
        if not b & 0x80:
            return x, i
        s += 7


def _fields(buf: bytes) -> Iterator[Tuple[int, int, Any]]:
    """(field_number, wire_type, value) triples of one message."""
    i, n = 0, len(buf)
    while i < n:
        tag, i = _varint(buf, i)
        fnum, wt = tag >> 3, tag & 7
        if wt == 0:                       # varint
            v, i = _varint(buf, i)
        elif wt == 2:                     # length-delimited
            ln, i = _varint(buf, i)
            v = buf[i:i + ln]
            i += ln
        elif wt == 5:                     # 32-bit
            v = buf[i:i + 4]
            i += 4
        elif wt == 1:                     # 64-bit
            v = buf[i:i + 8]
            i += 8
        else:
            raise ValueError(f"unsupported wire type {wt} at byte {i}")
        yield fnum, wt, v


def _parse_event(buf: bytes) -> Tuple[int, int, int, int]:
    """XEvent -> (metadata_id, offset_ps, duration_ps, occurrences)."""
    mid = off = dur = 0
    occ = 1
    for fnum, _wt, v in _fields(buf):
        if fnum == 1:
            mid = v
        elif fnum == 2:
            off = v
        elif fnum == 3:
            dur = v
        elif fnum == 5:
            occ = v
    return mid, off, dur, occ


def _parse_line(buf: bytes) -> Dict[str, Any]:
    """XLine -> {name, timestamp_ns, events}."""
    out: Dict[str, Any] = {"name": "", "timestamp_ns": 0, "events": []}
    for fnum, _wt, v in _fields(buf):
        if fnum == 2:
            out["name"] = v.decode("utf-8", "replace")
        elif fnum == 11 and not out["name"]:
            out["name"] = v.decode("utf-8", "replace")
        elif fnum == 3:
            out["timestamp_ns"] = v
        elif fnum == 4:
            out["events"].append(_parse_event(v))
    return out


def _parse_plane(buf: bytes) -> Dict[str, Any]:
    """XPlane -> {name, lines, event_names (metadata_id -> op name)}."""
    out: Dict[str, Any] = {"name": "", "lines": [], "event_names": {}}
    for fnum, _wt, v in _fields(buf):
        if fnum == 2:
            out["name"] = v.decode("utf-8", "replace")
        elif fnum == 3:
            out["lines"].append(_parse_line(v))
        elif fnum == 4:
            # map<int64, XEventMetadata> entry: key=1, value=2
            key, name, disp = 0, "", ""
            for f2, _w2, v2 in _fields(v):
                if f2 == 1:
                    key = v2
                elif f2 == 2:
                    for f3, _w3, v3 in _fields(v2):
                        if f3 == 1:
                            key = key or v3
                        elif f3 == 2:
                            name = v3.decode("utf-8", "replace")
                        elif f3 == 4:
                            disp = v3.decode("utf-8", "replace")
            out["event_names"][key] = name or disp
    return out


def parse_xspace(data: bytes) -> List[Dict[str, Any]]:
    """XSpace bytes -> list of plane dicts (schema subset above)."""
    return [_parse_plane(v) for fnum, _wt, v in _fields(data)
            if fnum == 1]


# ---------------------------------------------------------------------------
# aggregation
# ---------------------------------------------------------------------------
def _is_device_plane(name: str) -> bool:
    return "/device:" in name


def _base_op(name: str) -> str:
    """HLO op base name: "%copy.123" -> "copy", "fusion.7" -> "fusion"."""
    base = name.lstrip("%")
    head = base.split(".", 1)[0]
    return head


def aggregate_ops(planes: List[Dict[str, Any]]
                  ) -> Optional[Dict[str, Any]]:
    """Per-op busy totals over the device plane's op line.

    Picks the device plane (name contains "/device:") with the most op
    events; within it the "XLA Ops" line when present, else every
    line. Returns None when no device plane carries events — the CPU
    backend's trace has host threads only.
    """
    best: Optional[Tuple[int, Dict[str, Any], List[Dict[str, Any]]]] = None
    for plane in planes:
        if not _is_device_plane(plane["name"]):
            continue
        lines = [ln for ln in plane["lines"] if ln["name"] == "XLA Ops"]
        if not lines:
            lines = [ln for ln in plane["lines"] if ln["events"]]
        n_ev = sum(len(ln["events"]) for ln in lines)
        if n_ev and (best is None or n_ev > best[0]):
            best = (n_ev, plane, lines)
    if best is None:
        return None
    _n, plane, lines = best
    ops: Dict[str, List[float]] = {}
    t0 = None
    t1 = None
    for ln in lines:
        base_ps = ln["timestamp_ns"] * 1000
        for mid, off, dur, occ in ln["events"]:
            name = plane["event_names"].get(mid, f"op#{mid}")
            ent = ops.setdefault(name, [0.0, 0])
            ent[0] += dur * max(occ, 1)
            ent[1] += max(occ, 1)
            start = base_ps + off
            end = start + dur
            t0 = start if t0 is None else min(t0, start)
            t1 = end if t1 is None else max(t1, end)
    busy_ps = sum(v[0] for v in ops.values())
    copy_ps = sum(v[0] for name, v in ops.items()
                  if _base_op(name) in _COPY_BASES)
    comm_ps = sum(v[0] for name, v in ops.items()
                  if _base_op(name) in _COMM_BASES)
    return {
        "device_plane": plane["name"],
        "ops": ops,                              # name -> [ps, calls]
        "busy_ps": busy_ps,
        "copy_ps": copy_ps,
        "comm_ps": comm_ps,
        "window_ps": (t1 - t0) if t0 is not None else 0,
    }


def newest_xplane(path: str) -> Optional[str]:
    """``path`` itself if it is a file, else the newest ``*.xplane.pb``
    under it (jax.profiler writes <dir>/plugins/profile/<ts>/...)."""
    if os.path.isfile(path):
        return path
    newest, newest_m = None, -1.0
    for dirpath, _dirs, files in os.walk(path):
        for fn in files:
            if fn.endswith(".xplane.pb"):
                full = os.path.join(dirpath, fn)
                m = os.path.getmtime(full)
                if m > newest_m:
                    newest, newest_m = full, m
    return newest


def attribute(path: str, iters: Optional[int] = None,
              wall_ms: Optional[float] = None) -> Dict[str, Any]:
    """Full attribution of one profile dump.

    Args:
      path: an ``.xplane.pb`` file or a ``tpu_profile_dir`` tree (the
        newest dump inside is used).
      iters: boosting iterations the traced window covered — enables
        the per-iteration wall-vs-busy gap.
      wall_ms: host-measured wall time of the traced window; defaults
        to the device op line's first-start..last-end span.

    Returns a dict with ``found`` False (and ``reason``) when there is
    nothing to attribute; else ``ops`` (sorted descending by time,
    each ``{name, ms, calls, share}``), ``busy_ms``, ``wall_ms``,
    ``copy_ms``, ``copy_share``, ``comm_ms``, ``comm_share`` and —
    with ``iters`` — ``wall_busy_gap_ms`` per iteration.
    """
    f = newest_xplane(path)
    if f is None:
        return {"found": False, "reason": f"no .xplane.pb under {path}"}
    try:
        planes = parse_xspace(open(f, "rb").read())
    except (OSError, ValueError, IndexError) as e:
        return {"found": False,
                "reason": f"cannot parse {f}: {type(e).__name__}: {e}"}
    agg = aggregate_ops(planes)
    if agg is None:
        return {"found": False, "source": f,
                "reason": "no device plane with op events (CPU/host "
                          "trace?)"}
    busy_ms = agg["busy_ps"] / 1e9
    wall = wall_ms if wall_ms is not None else agg["window_ps"] / 1e9
    out: Dict[str, Any] = {
        "found": True,
        "source": f,
        "device_plane": agg["device_plane"],
        "busy_ms": busy_ms,
        "wall_ms": wall,
        "copy_ms": agg["copy_ps"] / 1e9,
        "copy_share": (agg["copy_ps"] / agg["busy_ps"]
                       if agg["busy_ps"] else 0.0),
        "comm_ms": agg["comm_ps"] / 1e9,
        "comm_share": (agg["comm_ps"] / agg["busy_ps"]
                       if agg["busy_ps"] else 0.0),
        "ops": [
            {"name": name, "ms": ps / 1e9, "calls": calls,
             "share": (ps / agg["busy_ps"] if agg["busy_ps"] else 0.0)}
            for name, (ps, calls) in sorted(
                agg["ops"].items(), key=lambda kv: -kv[1][0])],
    }
    if iters:
        out["iters"] = int(iters)
        out["wall_busy_gap_ms"] = max(wall - busy_ms, 0.0) / int(iters)
    return out


def profile_gauges(profile_dir: str, iters: Optional[int] = None,
                   wall_ms: Optional[float] = None) -> Dict[str, Any]:
    """Attribute a finished ``tpu_profile_dir`` dump into the metrics
    registry: ``train.copy_share`` (fraction of device busy spent in
    copy ops), ``train.comm_share`` (fraction spent in cross-device
    collectives) and — when ``iters`` is known —
    ``train.wall_busy_gap_ms`` (per-iteration wall-vs-busy gap).
    Forced gauges: asking for a
    profiler trace IS opting into its attribution, tpu_metrics or not.
    Never raises — a malformed dump warns and returns the reason; the
    training/bench run that produced it must not fail on telemetry."""
    from ..utils import log
    try:
        res = attribute(profile_dir, iters=iters, wall_ms=wall_ms)
    except Exception as e:   # defense in depth: attribution is telemetry
        res = {"found": False,
               "reason": f"{type(e).__name__}: {e}"}
    if not res.get("found"):
        log.debug(f"trace_attr: nothing to attribute under "
                  f"{profile_dir!r}: {res.get('reason')}")
        return res
    from . import set_gauge
    set_gauge("train.copy_share", float(res["copy_share"]), force=True)
    set_gauge("train.comm_share", float(res["comm_share"]), force=True)
    if "wall_busy_gap_ms" in res:
        set_gauge("train.wall_busy_gap_ms",
                  float(res["wall_busy_gap_ms"]), force=True)
    return res
