"""Live metrics exposition: a stdlib-only HTTP endpoint for scraping.

The JSONL dumps and ``Booster.metrics()`` are after-the-fact views; a
production deployment scrapes *mid-run*. One background daemon thread
serves four routes (Prometheus-shaped, the layout SNIPPETS.md's serving
idioms assume):

- ``GET /metrics`` — Prometheus text exposition of the live registry
  (device gauges refreshed, SLO gauges re-evaluated per scrape — one
  scrape == one SLO evaluation period);
- ``GET /metrics.json`` — the same snapshot as JSON (schema
  ``lightgbm-tpu-metrics-v1``);
- ``GET /healthz`` — liveness: 200 while the process responds and no
  previously-live heartbeat has gone silent; 503 when every stamped
  heartbeat is older than the staleness timeout (a wedged round loop /
  serving path looks exactly like this);
- ``GET /readyz`` — readiness: 200 only when at least one heartbeat
  (``heartbeat.train`` from the round loop, ``heartbeat.serve`` from
  the predict path) is fresh; 503 before the first stamp, so a load
  balancer only routes traffic at a process that has proven it can do
  work.

Safety posture: binds ``127.0.0.1`` ONLY (scrape through a sidecar /
SSH tunnel — metrics often leak model and data shape details); a port
already in use logs a warning and disables the server instead of
crashing the training run that asked for it; the thread is a daemon
and its shutdown is ExitStack-registered + atexit-hooked, matching the
crashed-run export guarantees (a dying process never hangs on the
scrape thread).
"""
from __future__ import annotations

import atexit
import contextlib
import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional, Tuple

from .metrics import registry

__all__ = ["MetricsServer", "start_server", "stop_server", "server"]

DEFAULT_HEARTBEAT_TIMEOUT_S = 60.0
_PROM_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def _heartbeat_ages(now: Optional[float] = None) -> Dict[str, float]:
    """Age in seconds of every stamped ``heartbeat.*`` gauge."""
    now = time.monotonic() if now is None else now
    ages: Dict[str, float] = {}
    for m in registry().metrics():
        if (m.kind == "gauge" and not m.labels
                and m.name.startswith("heartbeat.")):
            ages[m.name[len("heartbeat."):]] = now - float(m.value)
    return ages


def health_payload(ready: bool, timeout_s: float,
                   now: Optional[float] = None) -> Tuple[int, Dict[str, Any]]:
    """(status_code, body) for /healthz (``ready=False``) or /readyz.

    Liveness tolerates "no heartbeat yet" (the server answering IS the
    liveness proof at startup); readiness does not — a gang member that
    joined but never completed a round must not take traffic.
    """
    now = time.monotonic() if now is None else now
    ages = _heartbeat_ages(now)
    fresh = {k: a <= timeout_s for k, a in ages.items()}
    any_fresh = any(fresh.values())
    if ready:
        ok = any_fresh
        status = "ok" if ok else ("stale" if ages else "no_heartbeat")
    else:
        ok = any_fresh or not ages
        status = "ok" if ok else "stale"
    body = {
        "status": status,
        "heartbeats": {k: round(a, 3) for k, a in sorted(ages.items())},
        "stale_after_s": timeout_s,
    }
    return (200 if ok else 503), body


class _Handler(BaseHTTPRequestHandler):
    server_version = "lightgbm-tpu-obs"
    protocol_version = "HTTP/1.1"

    def log_message(self, *args) -> None:   # scrapes must not spam logs
        pass

    def _send(self, code: int, body: bytes, ctype: str) -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self) -> None:           # noqa: N802 (stdlib API name)
        from . import prometheus_from_snapshot, snapshot
        path = self.path.split("?", 1)[0]
        try:
            if path == "/metrics":
                text = prometheus_from_snapshot(snapshot())
                self._send(200, text.encode(), _PROM_CONTENT_TYPE)
            elif path == "/metrics.json":
                self._send(200, json.dumps(snapshot()).encode(),
                           "application/json")
            elif path in ("/healthz", "/readyz"):
                code, body = health_payload(
                    ready=(path == "/readyz"),
                    timeout_s=self.server.heartbeat_timeout_s)
                self._send(code, json.dumps(body).encode(),
                           "application/json")
            else:
                self._send(404, b'{"error": "not found"}',
                           "application/json")
        except BrokenPipeError:         # scraper went away mid-reply
            pass
        except Exception as e:          # a scrape must never kill serving
            try:
                self._send(500, json.dumps(
                    {"error": f"{type(e).__name__}: {e}"}).encode(),
                    "application/json")
            except Exception:
                pass


class _Server(ThreadingHTTPServer):
    daemon_threads = True               # per-request handler threads
    heartbeat_timeout_s = DEFAULT_HEARTBEAT_TIMEOUT_S


class MetricsServer:
    """One bound endpoint + its serve-forever daemon thread."""

    def __init__(self, port: int,
                 heartbeat_timeout_s: float = DEFAULT_HEARTBEAT_TIMEOUT_S):
        # localhost ONLY — see module docstring's safety posture
        self._httpd = _Server(("127.0.0.1", int(port)), _Handler)
        self._httpd.heartbeat_timeout_s = float(heartbeat_timeout_s)
        self.port = int(self._httpd.server_address[1])
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name="lightgbm-tpu-metrics-server", daemon=True)
        self._thread.start()

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5.0)


_lock = threading.Lock()
_server: Optional[MetricsServer] = None
# ExitStack so shutdown composes with the crashed-run export
# guarantees: atexit closes the stack, the stack stops the server
_exit_stack = contextlib.ExitStack()
atexit.register(_exit_stack.close)


def server() -> Optional[MetricsServer]:
    return _server


def start_server(port: int,
                 heartbeat_timeout_s: Optional[float] = None,
                 required: bool = False,
                 ) -> Optional[MetricsServer]:
    """Start (or return) the process metrics endpoint. ``port=0`` binds
    an ephemeral port (the ACTUALLY-bound port is on the returned
    server's ``.port`` — fleet replicas bind 0 and publish what they
    got); the config path only calls this with ``tpu_metrics_port >
    0``. Idempotent and process-global: a second DIFFERENT port warns
    and keeps the first, while an EXPLICIT ``heartbeat_timeout_s``
    (None = keep current / default) applies to the live server in
    place — a later Config's tpu_heartbeat_timeout must not be
    silently dropped, nor an unset one clobber an earlier explicit
    choice. A port already in use warns and returns None — the
    training/serving run continues without live exposition rather than
    crashing — UNLESS ``required=True``: a fleet replica whose
    endpoint cannot bind is invisible to its router (the supervisor
    would route around a silently-blind replica forever), so the fleet
    path raises instead of degrading."""
    from ..utils import log
    global _server
    with _lock:
        if _server is not None:
            if port not in (0, _server.port):
                log.warning(
                    f"tpu_metrics_port={port} ignored: the metrics "
                    f"server is already live on {_server.port} "
                    f"(process-global; restart to move it)")
            if heartbeat_timeout_s is not None:
                _server._httpd.heartbeat_timeout_s = float(
                    heartbeat_timeout_s)
            return _server
        try:
            srv = MetricsServer(
                port,
                heartbeat_timeout_s=(DEFAULT_HEARTBEAT_TIMEOUT_S
                                     if heartbeat_timeout_s is None
                                     else heartbeat_timeout_s))
        except OSError as e:
            if required:
                raise RuntimeError(
                    f"metrics endpoint REQUIRED but cannot bind port "
                    f"{port}: {e} (a replica without /metrics+/readyz "
                    f"cannot join a fleet — pick a free port or "
                    f"port=0 for ephemeral)") from e
            log.warning(
                f"tpu_metrics_port={port}: cannot bind the metrics "
                f"endpoint ({e}); live exposition disabled for this "
                f"run (JSONL dumps and Booster.metrics() still work)")
            return None
        _server = srv
        _exit_stack.callback(stop_server)
        log.info(f"metrics endpoint live at {srv.url}/metrics "
                 f"(localhost only)")
        return srv


def stop_server() -> None:
    """Stop the endpoint (idempotent; safe from atexit)."""
    global _server
    with _lock:
        srv, _server = _server, None
    if srv is not None:
        try:
            srv.stop()
        except Exception:
            pass
