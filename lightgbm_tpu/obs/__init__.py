"""Observability subsystem: metrics registry + phase-scoped tracing +
device/compile telemetry + active SLO/serving plane
(docs/observability.md).

Pillars, one import:

- **Metrics** (obs/metrics.py): process-wide counters / gauges /
  histograms with labels, exported as JSONL snapshots
  (``dump_jsonl``), Prometheus-style text (``prometheus_text``), or
  the ``Booster.metrics()`` / ``GBDT.metrics_snapshot()`` APIs.
- **Tracing** (obs/tracing.py): ``with obs.span("train/round",
  round=i):`` — nested spans that record wall time (plus optional
  device-synced time) into a Chrome-trace JSON viewable in Perfetto;
  the serving dispatch loop adds per-batch span trees with rider
  flow events, and rank-tagged exports merge into one gang-wide
  timeline via ``scripts/trace_merge.py`` (obs/aggregate.py).
- **Device telemetry** (obs/telemetry.py): compile-request counting,
  program-cache-size and HBM gauges refreshed into the registry.
- **Active plane** (obs/slo.py + obs/server.py + obs/aggregate.py):
  windowed SLIs (rolling p50/p99 under the same span names) with
  threshold evaluation, a live localhost ``/metrics`` + ``/healthz`` /
  ``/readyz`` endpoint driven by :func:`heartbeat` stamps, and
  per-rank snapshot aggregation for ``train_distributed`` gangs.

OFF BY DEFAULT and engineered for ~zero cost when off: every
instrumented hot path funnels through :func:`span` / :func:`inc` /
:func:`observe`, whose disabled path is one bool check and a shared
no-op context manager — no locks, no clocks, no allocation. Enabled
via ``Config`` knobs (``tpu_metrics=true``, ``tpu_trace_dir=DIR``,
``tpu_metrics_dump=PATH``, ``tpu_metrics_port=N``, ``tpu_slo_*``) or
programmatically with :func:`enable`.

Cold paths that must record regardless (restart/retry accounting, the
benches, the utils/timer back-compat shim) pass ``force=True``.
"""
from __future__ import annotations

import contextlib
import os
import time
from typing import Any, Callable, Dict, Optional

from . import metrics as _metrics
from . import slo as _slo
from . import tracing as _tracing
from .metrics import prometheus_from_snapshot, registry
from .tracing import (export_chrome_trace, set_trace_rank, span_stack,
                      trace_dir, trace_rank, tracing_enabled)

__all__ = [
    "enable", "disable", "enabled", "any_enabled", "tracing_enabled",
    "slo_enabled", "span", "inc", "set_gauge", "observe", "counter",
    "gauge", "histogram", "heartbeat", "retire_heartbeat",
    "set_heartbeat_file",
    "predict_instrumented", "registry", "snapshot", "dump_jsonl",
    "prometheus_text", "prometheus_from_snapshot",
    "export_chrome_trace", "export_state", "import_state", "reset",
    "configure_from_config", "flush_from_config", "span_stack",
    "trace_dir", "set_trace_rank", "trace_rank",
]


class _State:
    __slots__ = ("metrics", "device_time", "slo")

    def __init__(self) -> None:
        self.metrics = False
        self.device_time = False
        self.slo = False


_state = _State()

# metric-name prefixes that never ride checkpoints: monotonic-clock
# heartbeat stamps and windowed SLO gauges (slo.* plus the windowed
# cache-hit ratio) describe THIS process's recent behavior — importing
# them into a resumed process would be stale at best and wrong-clock
# at worst (heartbeats must resume from live stamping, not from saved
# state; a resumed process whose tracker is off would otherwise expose
# the dead process's frozen ratios forever)
_EPHEMERAL_PREFIXES = ("heartbeat.", "slo.", "predict.cache_hit_ratio")

# shared no-op context manager for disabled spans: nullcontext is
# reentrant and reusable, so ONE instance serves every disabled site
_NULL_CM = contextlib.nullcontext()


def enable(metrics: bool = True, trace_dir: Optional[str] = None,
           trace: Optional[bool] = None,
           device_time: Optional[bool] = None,
           slo: Optional[bool] = None,
           slo_window_s: Optional[float] = None,
           slo_thresholds: Optional[Dict[str, float]] = None) -> None:
    """Turn observability on (idempotent; never turns anything off —
    a later Config that leaves ``tpu_metrics`` at its default must not
    silently disable what an earlier one enabled).

    ``slo=True`` (or any ``slo_window_s`` / ``slo_thresholds``) starts
    the windowed-SLI tracker (obs/slo.py); SLIs derive from the metric
    feeds, so enabling SLOs implies the metrics pillar.
    """
    if metrics:
        _state.metrics = True
        from .telemetry import ensure_compile_listener
        ensure_compile_listener()
    if trace or trace_dir:
        _tracing.enable_tracing(trace_dir)
    if device_time is not None:
        _state.device_time = bool(device_time)
    if slo or slo_window_s or slo_thresholds:
        _slo.enable(window_s=slo_window_s, thresholds=slo_thresholds)
        _state.slo = True
        enable(metrics=True)


def disable() -> None:
    """Turn instrumentation off (collected metrics/events persist until
    :func:`reset`). Primarily for tests."""
    _state.metrics = False
    _state.slo = False
    _tracing.disable_tracing()
    from .telemetry import pause_compile_listener
    pause_compile_listener()


def enabled() -> bool:
    """Is the METRICS pillar live (the gate hot paths check)?"""
    return _state.metrics


def slo_enabled() -> bool:
    """Is the windowed-SLI tracker live?"""
    return _state.slo and _slo.enabled()


def any_enabled() -> bool:
    return _state.metrics or _tracing.tracing_enabled()


# ---------------------------------------------------------------------------
# spans
# ---------------------------------------------------------------------------
class _Span:
    """Reentrant-per-instance span context manager (one per call)."""

    __slots__ = ("_t", "_force")

    def __init__(self, name: str, args: Dict[str, Any], sync,
                 force: bool) -> None:
        self._t = _tracing._SpanTimer(name, args, sync)
        self._force = force

    def __enter__(self) -> "_Span":
        self._t.start()
        return self

    def set(self, **attrs) -> None:
        """Attach attributes discovered mid-span (e.g. whether a
        registry checkout was a cache hit) — they land in the trace
        event recorded at exit. Callers must null-check the ``as``
        value first: a disabled span is the shared nullcontext, whose
        ``__enter__`` yields None."""
        self._t.args.update(attrs)

    def __exit__(self, *exc) -> None:
        self._t.stop(_tracing.tracing_enabled(),
                     _observe_span if (_state.metrics or self._force)
                     else None)


def _observe_span(name: str, dur: float) -> None:
    _metrics.registry().histogram(name).observe(dur)
    if _state.slo:
        _slo.feed_hist(name, dur)


def span(name: str, sync: Optional[Callable[[], Any]] = None,
         force: bool = False, **attrs):
    """Scoped phase timer: records a duration histogram under ``name``
    (when metrics are on) and a Chrome-trace event (when tracing is on).

    ``sync``: optional callable (e.g. ``lambda:
    jax.block_until_ready(x)``) invoked before the span closes when
    ``device_time`` is enabled, splitting dispatch wall time from
    device completion time in the trace args.

    ``force=True`` records even when observability is globally off
    (explicit-measurement callers: utils/timer shim, benches).

    No-op (a shared null context manager) when everything is off.
    """
    if not (force or _state.metrics or _tracing.tracing_enabled()):
        return _NULL_CM
    return _Span(name, attrs,
                 sync if (sync is not None and _state.device_time)
                 else None, force)


# ---------------------------------------------------------------------------
# metric helpers (hot-path funnels; force bypasses the global gate)
# ---------------------------------------------------------------------------
def inc(name: str, n: float = 1.0, force: bool = False,
        **labels) -> None:
    if _state.metrics or force:
        _metrics.registry().counter(name, **labels).inc(n)
        if _state.slo and not labels:
            _slo.feed_count(name, n)


def set_gauge(name: str, value: float, force: bool = False,
              **labels) -> None:
    if _state.metrics or force:
        _metrics.registry().gauge(name, **labels).set(value)


def observe(name: str, value: float, force: bool = False,
            **labels) -> None:
    if _state.metrics or force:
        _metrics.registry().histogram(name, **labels).observe(value)
        if _state.slo and not labels:
            _slo.feed_hist(name, value)


# cross-process heartbeat FILE sinks: kind -> [path, min_interval_s,
# last_stamp_monotonic]. The obs gauges above are process-local; the
# distributed launcher's watchdog lives in ANOTHER process, so workers
# stamp a file (mtime = the heartbeat) it can stat. Registered by
# engine.train from ``tpu_heartbeat_dir``; stamping is throttled to
# min_interval so a sub-millisecond round loop costs one clock read,
# not one syscall, per round. The file is created lazily on the FIRST
# stamp — a worker still compiling has no file, which the watchdog
# reads as "starting up" (covered by the gang timeout), never "stale".
_HB_FILES: Dict[str, list] = {}


def set_heartbeat_file(kind: str, path: Optional[str],
                       min_interval: float = 1.0) -> None:
    """Register (or, with ``path=None``, drop) a heartbeat file for
    ``kind``: every :func:`heartbeat` call refreshes the file's mtime
    (rate-limited to ``min_interval`` seconds). Works with the metrics
    pillar OFF — watchdog liveness must not depend on the user opting
    into metrics."""
    if path is None:
        _HB_FILES.pop(kind, None)
        return
    _HB_FILES[kind] = [str(path), float(min_interval), 0.0]


def heartbeat(kind: str) -> None:
    """Stamp the ``heartbeat.<kind>`` gauge with the current monotonic
    time. The round loop stamps ``train``, the predict path ``serve``;
    /healthz and /readyz (obs/server.py) compare these stamps against
    the staleness timeout, and the launcher watchdog compares the
    registered heartbeat FILE's mtime (:func:`set_heartbeat_file`).
    One gauge set when metrics are on, a single bool check when off —
    heartbeat call sites ride the hot loops."""
    if _state.metrics:
        _metrics.registry().gauge(f"heartbeat.{kind}").set(
            time.monotonic())
    if _HB_FILES:
        ent = _HB_FILES.get(kind)
        if ent is not None:
            now = time.monotonic()
            if now - ent[2] >= ent[1]:
                ent[2] = now
                try:
                    with open(ent[0], "a"):
                        pass
                    os.utime(ent[0])
                except OSError:
                    pass


def predict_instrumented(call: Callable[[], Any], data) -> Any:
    """The ONE serve-instrumentation sequence every predict entry point
    shares (engine path in boosting/gbdt.py, host-model path in
    basic.py — two copies WOULD drift and split the SLO feeds):
    ``predict.requests`` counts the ATTEMPT, the ``predict/call`` span
    times it (feeding the rolling SLO window), ``predict.errors``
    counts a raise, the serve heartbeat stamps on attempt (liveness is
    "the loop runs", not "requests succeed"), and ``predict.rows``
    lands on success. Callers gate on :func:`any_enabled` first — the
    off path must stay one bool check."""
    try:
        n_rows = int(data.shape[0])
    except Exception:
        n_rows = len(data) if hasattr(data, "__len__") else 0
    inc("predict.requests")
    try:
        with span("predict/call", rows=n_rows):
            out = call()
    except BaseException:
        inc("predict.errors")
        raise
    finally:
        heartbeat("serve")
    inc("predict.rows", n_rows)
    return out


def retire_heartbeat(kind: str) -> None:
    """Remove a heartbeat stamp at the CLEAN end of the loop it
    tracked. A retired heartbeat is *absent* — /healthz stays green
    for a process that finished its work and went idle — while a
    crashed or wedged loop leaves its last stamp behind to go stale
    (the 503 signal). The same contract applies to the heartbeat FILE:
    a clean finish unlinks it (absent = finished), a wedge leaves it
    to go stale under the launcher watchdog. Serving heartbeats are
    never retired: a serving process with no traffic for the staleness
    timeout IS the signal a load balancer probes for."""
    reg = _metrics.registry()
    if reg.get(f"heartbeat.{kind}") is not None:
        reg.reset(prefix=f"heartbeat.{kind}", kind="gauge")
    ent = _HB_FILES.pop(kind, None)
    if ent is not None:
        try:
            os.unlink(ent[0])
        except OSError:
            pass


def counter(name: str, **labels) -> _metrics.Counter:
    return _metrics.registry().counter(name, **labels)


def gauge(name: str, **labels) -> _metrics.Gauge:
    return _metrics.registry().gauge(name, **labels)


def histogram(name: str, **labels) -> _metrics.Histogram:
    return _metrics.registry().histogram(name, **labels)


# ---------------------------------------------------------------------------
# exporters / state
# ---------------------------------------------------------------------------
def snapshot(refresh_device: bool = True) -> Dict[str, Any]:
    """Full registry snapshot; refreshes the device/compile gauges
    first so HBM and program-cache numbers are current, and re-derives
    the SLO gauges from the sliding windows (one snapshot/scrape ==
    one SLO evaluation period)."""
    if refresh_device and any_enabled():
        from .telemetry import refresh_device_gauges
        refresh_device_gauges()
    if _state.slo:
        _slo.evaluate()
    return _metrics.registry().snapshot()


def dump_jsonl(path: str, snap: Optional[Dict[str, Any]] = None) -> str:
    """Append one snapshot line to ``path``. Pass ``snap`` to dump an
    already-taken snapshot (the benches print their metric line and
    dump from the SAME dict so the two can never disagree); otherwise
    a fresh device-gauge-refreshed snapshot is taken."""
    return _metrics.registry().dump_jsonl(
        path, snap if snap is not None else snapshot())


def prometheus_text() -> str:
    return prometheus_from_snapshot(snapshot())


def export_state() -> Dict[str, Any]:
    """Serializable metrics state for checkpoints (metrics pillar only;
    trace events are a per-process artifact, not training state, and
    heartbeat stamps / windowed SLO gauges are process-local monotonic
    state that must NOT resume from a checkpoint — the live round loop
    re-stamps them)."""
    state = _metrics.registry().export_state()
    state["metrics"] = [
        m for m in state["metrics"]
        if not str(m.get("name", "")).startswith(_EPHEMERAL_PREFIXES)]
    return state


def import_state(state: Optional[Dict[str, Any]]) -> int:
    return _metrics.registry().import_state(state)


def reset(prefix: Optional[str] = None) -> None:
    """Clear collected metrics (all, or a name prefix) and — when
    clearing everything — the trace buffer and the windowed-SLI
    tracker. Enable flags persist (except SLO, whose state IS the
    tracker)."""
    _metrics.registry().reset(prefix)
    if prefix is None:
        _tracing.reset_events()
        _slo.reset()
        _state.slo = False


# ---------------------------------------------------------------------------
# Config wiring (called from Config._post_process; see config.py knobs)
# ---------------------------------------------------------------------------
def configure_from_config(cfg) -> None:
    """Engage pillars the config asks for. Enable-only: a Config built
    with default knobs mid-run (train() builds several) never disables
    what an earlier explicit config enabled."""
    want_metrics = bool(getattr(cfg, "tpu_metrics", False))
    tdir = str(getattr(cfg, "tpu_trace_dir", "") or "").strip()
    dump = str(getattr(cfg, "tpu_metrics_dump", "") or "").strip()
    rank_dir = str(getattr(cfg, "tpu_metrics_rank_dir", "") or "").strip()
    port = int(getattr(cfg, "tpu_metrics_port", 0) or 0)
    thresholds = {
        "predict_p99_ms": float(
            getattr(cfg, "tpu_slo_predict_p99_ms", 0.0) or 0.0),
        "error_ratio": float(
            getattr(cfg, "tpu_slo_error_ratio", 0.0) or 0.0),
    }
    thresholds = {k: v for k, v in thresholds.items() if v > 0}
    if want_metrics or dump or rank_dir:
        enable(metrics=True)
    if tdir:
        enable(metrics=False, trace_dir=tdir)
    # any SLO knob — a threshold, an explicit window, or the live
    # endpoint (whose whole point is rolling SLO gauges) — starts the
    # windowed-SLI tracker; tpu_slo_window_s alone must not be inert
    win = float(getattr(cfg, "tpu_slo_window_s", 0.0) or 0.0)
    if thresholds or port > 0 or win > 0:
        enable(slo=True, slo_window_s=win or None,
               slo_thresholds=thresholds or None)
    if port > 0:
        from .server import start_server
        hb = float(getattr(cfg, "tpu_heartbeat_timeout", 0.0) or 0.0)
        # None = knob unset: keep the live server's timeout (or the
        # default on first start) — enable-only like every other knob
        start_server(port, heartbeat_timeout_s=(hb if hb > 0 else None))


def flush_from_config(cfg) -> None:
    """End-of-run exports the config asked for: the JSONL metrics
    snapshot (``tpu_metrics_dump``) and the Chrome trace file
    (``tpu_trace_dir``). Idempotent and exception-safe — a failed
    export warns, it never fails the training run that produced it."""
    from ..utils import log
    dump = str(getattr(cfg, "tpu_metrics_dump", "") or "").strip()
    if dump:
        try:
            dump_jsonl(dump)
        except Exception as e:
            log.warning(f"tpu_metrics_dump: cannot write {dump!r}: {e}")
    if _tracing.tracing_enabled() and _tracing.trace_dir():
        try:
            export_chrome_trace()
        except Exception as e:
            log.warning(f"tpu_trace_dir: cannot export trace: {e}")
