"""Observability subsystem: metrics registry + phase-scoped tracing +
device/compile telemetry (docs/observability.md).

Three pillars, one import:

- **Metrics** (obs/metrics.py): process-wide counters / gauges /
  histograms with labels, exported as JSONL snapshots
  (``dump_jsonl``), Prometheus-style text (``prometheus_text``), or
  the ``Booster.metrics()`` / ``GBDT.metrics_snapshot()`` APIs.
- **Tracing** (obs/tracing.py): ``with obs.span("train/round",
  round=i):`` — nested spans that record wall time (plus optional
  device-synced time) into a Chrome-trace JSON viewable in Perfetto.
- **Device telemetry** (obs/telemetry.py): compile-request counting,
  program-cache-size and HBM gauges refreshed into the registry.

OFF BY DEFAULT and engineered for ~zero cost when off: every
instrumented hot path funnels through :func:`span` / :func:`inc` /
:func:`observe`, whose disabled path is one bool check and a shared
no-op context manager — no locks, no clocks, no allocation. Enabled
via ``Config`` knobs (``tpu_metrics=true``, ``tpu_trace_dir=DIR``,
``tpu_metrics_dump=PATH``) or programmatically with :func:`enable`.

Cold paths that must record regardless (restart/retry accounting, the
benches, the utils/timer back-compat shim) pass ``force=True``.
"""
from __future__ import annotations

import contextlib
from typing import Any, Callable, Dict, Optional

from . import metrics as _metrics
from . import tracing as _tracing
from .metrics import prometheus_from_snapshot, registry
from .tracing import (export_chrome_trace, span_stack, trace_dir,
                      tracing_enabled)

__all__ = [
    "enable", "disable", "enabled", "any_enabled", "tracing_enabled",
    "span", "inc", "set_gauge", "observe", "counter", "gauge",
    "histogram", "registry", "snapshot", "dump_jsonl",
    "prometheus_text", "prometheus_from_snapshot",
    "export_chrome_trace", "export_state", "import_state", "reset",
    "configure_from_config", "flush_from_config", "span_stack",
    "trace_dir",
]


class _State:
    __slots__ = ("metrics", "device_time")

    def __init__(self) -> None:
        self.metrics = False
        self.device_time = False


_state = _State()

# shared no-op context manager for disabled spans: nullcontext is
# reentrant and reusable, so ONE instance serves every disabled site
_NULL_CM = contextlib.nullcontext()


def enable(metrics: bool = True, trace_dir: Optional[str] = None,
           trace: Optional[bool] = None,
           device_time: Optional[bool] = None) -> None:
    """Turn observability on (idempotent; never turns anything off —
    a later Config that leaves ``tpu_metrics`` at its default must not
    silently disable what an earlier one enabled)."""
    if metrics:
        _state.metrics = True
        from .telemetry import ensure_compile_listener
        ensure_compile_listener()
    if trace or trace_dir:
        _tracing.enable_tracing(trace_dir)
    if device_time is not None:
        _state.device_time = bool(device_time)


def disable() -> None:
    """Turn instrumentation off (collected metrics/events persist until
    :func:`reset`). Primarily for tests."""
    _state.metrics = False
    _tracing.disable_tracing()
    from .telemetry import pause_compile_listener
    pause_compile_listener()


def enabled() -> bool:
    """Is the METRICS pillar live (the gate hot paths check)?"""
    return _state.metrics


def any_enabled() -> bool:
    return _state.metrics or _tracing.tracing_enabled()


# ---------------------------------------------------------------------------
# spans
# ---------------------------------------------------------------------------
class _Span:
    """Reentrant-per-instance span context manager (one per call)."""

    __slots__ = ("_t", "_force")

    def __init__(self, name: str, args: Dict[str, Any], sync,
                 force: bool) -> None:
        self._t = _tracing._SpanTimer(name, args, sync)
        self._force = force

    def __enter__(self) -> "_Span":
        self._t.start()
        return self

    def __exit__(self, *exc) -> None:
        self._t.stop(_tracing.tracing_enabled(),
                     _observe_span if (_state.metrics or self._force)
                     else None)


def _observe_span(name: str, dur: float) -> None:
    _metrics.registry().histogram(name).observe(dur)


def span(name: str, sync: Optional[Callable[[], Any]] = None,
         force: bool = False, **attrs):
    """Scoped phase timer: records a duration histogram under ``name``
    (when metrics are on) and a Chrome-trace event (when tracing is on).

    ``sync``: optional callable (e.g. ``lambda:
    jax.block_until_ready(x)``) invoked before the span closes when
    ``device_time`` is enabled, splitting dispatch wall time from
    device completion time in the trace args.

    ``force=True`` records even when observability is globally off
    (explicit-measurement callers: utils/timer shim, benches).

    No-op (a shared null context manager) when everything is off.
    """
    if not (force or _state.metrics or _tracing.tracing_enabled()):
        return _NULL_CM
    return _Span(name, attrs,
                 sync if (sync is not None and _state.device_time)
                 else None, force)


# ---------------------------------------------------------------------------
# metric helpers (hot-path funnels; force bypasses the global gate)
# ---------------------------------------------------------------------------
def inc(name: str, n: float = 1.0, force: bool = False,
        **labels) -> None:
    if _state.metrics or force:
        _metrics.registry().counter(name, **labels).inc(n)


def set_gauge(name: str, value: float, force: bool = False,
              **labels) -> None:
    if _state.metrics or force:
        _metrics.registry().gauge(name, **labels).set(value)


def observe(name: str, value: float, force: bool = False,
            **labels) -> None:
    if _state.metrics or force:
        _metrics.registry().histogram(name, **labels).observe(value)


def counter(name: str, **labels) -> _metrics.Counter:
    return _metrics.registry().counter(name, **labels)


def gauge(name: str, **labels) -> _metrics.Gauge:
    return _metrics.registry().gauge(name, **labels)


def histogram(name: str, **labels) -> _metrics.Histogram:
    return _metrics.registry().histogram(name, **labels)


# ---------------------------------------------------------------------------
# exporters / state
# ---------------------------------------------------------------------------
def snapshot(refresh_device: bool = True) -> Dict[str, Any]:
    """Full registry snapshot; refreshes the device/compile gauges
    first so HBM and program-cache numbers are current."""
    if refresh_device and any_enabled():
        from .telemetry import refresh_device_gauges
        refresh_device_gauges()
    return _metrics.registry().snapshot()


def dump_jsonl(path: str, snap: Optional[Dict[str, Any]] = None) -> str:
    """Append one snapshot line to ``path``. Pass ``snap`` to dump an
    already-taken snapshot (the benches print their metric line and
    dump from the SAME dict so the two can never disagree); otherwise
    a fresh device-gauge-refreshed snapshot is taken."""
    return _metrics.registry().dump_jsonl(
        path, snap if snap is not None else snapshot())


def prometheus_text() -> str:
    return prometheus_from_snapshot(snapshot())


def export_state() -> Dict[str, Any]:
    """Serializable metrics state for checkpoints (metrics pillar only;
    trace events are a per-process artifact, not training state)."""
    return _metrics.registry().export_state()


def import_state(state: Optional[Dict[str, Any]]) -> int:
    return _metrics.registry().import_state(state)


def reset(prefix: Optional[str] = None) -> None:
    """Clear collected metrics (all, or a name prefix) and — when
    clearing everything — the trace buffer. Enable flags persist."""
    _metrics.registry().reset(prefix)
    if prefix is None:
        _tracing.reset_events()


# ---------------------------------------------------------------------------
# Config wiring (called from Config._post_process; see config.py knobs)
# ---------------------------------------------------------------------------
def configure_from_config(cfg) -> None:
    """Engage pillars the config asks for. Enable-only: a Config built
    with default knobs mid-run (train() builds several) never disables
    what an earlier explicit config enabled."""
    want_metrics = bool(getattr(cfg, "tpu_metrics", False))
    tdir = str(getattr(cfg, "tpu_trace_dir", "") or "").strip()
    dump = str(getattr(cfg, "tpu_metrics_dump", "") or "").strip()
    if want_metrics or dump:
        enable(metrics=True)
    if tdir:
        enable(metrics=False, trace_dir=tdir)


def flush_from_config(cfg) -> None:
    """End-of-run exports the config asked for: the JSONL metrics
    snapshot (``tpu_metrics_dump``) and the Chrome trace file
    (``tpu_trace_dir``). Idempotent and exception-safe — a failed
    export warns, it never fails the training run that produced it."""
    from ..utils import log
    dump = str(getattr(cfg, "tpu_metrics_dump", "") or "").strip()
    if dump:
        try:
            dump_jsonl(dump)
        except Exception as e:
            log.warning(f"tpu_metrics_dump: cannot write {dump!r}: {e}")
    if _tracing.tracing_enabled() and _tracing.trace_dir():
        try:
            export_chrome_trace()
        except Exception as e:
            log.warning(f"tpu_trace_dir: cannot export trace: {e}")
