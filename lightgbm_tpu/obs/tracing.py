"""Phase-scoped tracing: nested spans exported as Chrome-trace JSON.

The deep kernel story belongs to ``jax.profiler`` (xprof/TensorBoard,
wired via ``tpu_profile_dir``); these spans cover the HOST orchestration
the device profiler does not attribute — round loops, chunked predict,
ingest streaming, checkpoint writes — and export to the Chrome trace
event format, loadable directly in Perfetto (ui.perfetto.dev) or
chrome://tracing.

Span bookkeeping is thread-local (a per-thread stack gives nesting
depth and parent names); the event buffer is process-global, bounded,
and lock-protected. Every event is a ``ph: "X"`` complete event with
microsecond ``ts``/``dur`` on a monotonic base, so nesting renders as
containment per thread row. The serving dispatch loop additionally
records flow events (``ph: "s"``/``"f"``) so a coalesced rider's
submit visually connects to the batch that carried it.

Multi-rank runs: :func:`set_trace_rank` tags the export with the
process's rank — events get ``pid = rank`` plus a ``process_name``
metadata row ("rank N"), the default filename becomes
``rank_<r>.trace.json``, and the export envelope carries a wall/
monotonic clock pair taken at the same instant so
``scripts/trace_merge.py`` (obs/aggregate.py) can rebase every rank
onto one wall-clock timeline.
"""
from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Any, Deque, Dict, List, Optional

__all__ = ["tracing_enabled", "enable_tracing", "disable_tracing",
           "record_event", "record_flow", "events", "dropped_events",
           "reset_events", "export_chrome_trace", "span_stack",
           "trace_dir", "set_trace_rank", "trace_rank", "track_tid"]

# bound the buffer: a runaway span site must degrade to dropped-event
# accounting, never to unbounded host memory. Overflow drops the
# OLDEST events (a long-lived serving process keeps its most recent
# window — the one the p99 postmortem needs), counted in _dropped.
MAX_EVENTS = 200_000

_lock = threading.Lock()
_enabled = False
_dir: Optional[str] = None
_events: Deque[tuple] = deque()
_dropped = 0
_rank: Optional[int] = None
_tls = threading.local()


def tracing_enabled() -> bool:
    return _enabled


def trace_dir() -> Optional[str]:
    return _dir


def enable_tracing(directory: Optional[str] = None) -> None:
    """Start collecting span events; ``directory`` (optional) is where
    ``export_chrome_trace`` writes by default. A second different
    directory keeps the first (one trace stream per process)."""
    global _enabled, _dir
    with _lock:
        _enabled = True
        if directory:
            if _dir and _dir != str(directory):
                from ..utils import log
                log.warning(
                    f"tpu_trace_dir={directory!r} ignored: tracing is "
                    f"already exporting to {_dir!r} (process-global)")
            else:
                _dir = str(directory)


def disable_tracing() -> None:
    global _enabled
    with _lock:
        _enabled = False


def set_trace_rank(rank: Optional[int]) -> None:
    """Tag this process's trace stream with a gang rank (None clears).
    Called by the distributed worker body once ``jax.process_index()``
    is known; single-process runs stay untagged (pid-keyed export)."""
    global _rank
    _rank = None if rank is None else int(rank)


def trace_rank() -> Optional[int]:
    return _rank


def span_stack() -> List[str]:
    """This thread's open span names, outermost first."""
    return list(getattr(_tls, "stack", ()))


# named virtual tracks: stable synthetic tids OUTSIDE the 31-bit
# range real thread idents are masked into (& 0x7FFFFFFF), so a
# retroactive/asynchronous event's row can never collide with a real
# thread's and corrupt its nesting
_tracks: Dict[str, int] = {}
_TRACK_BASE = 0x80000000


def track_tid(name: str) -> int:
    """Stable synthetic tid for a named virtual track (registered so
    the export names the row, e.g. "serve queue")."""
    with _lock:
        t = _tracks.get(name)
        if t is None:
            t = _TRACK_BASE + len(_tracks)
            _tracks[name] = t
        return t


def _push(name: str) -> int:
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    stack.append(name)
    return len(stack) - 1


def _pop() -> None:
    stack = getattr(_tls, "stack", None)
    if stack:
        stack.pop()


def record_event(name: str, start_monotonic: float, dur_s: float,
                 args: Optional[Dict[str, Any]] = None,
                 device_s: Optional[float] = None,
                 tid: Optional[int] = None) -> None:
    """Append one complete event (called by ``obs.span`` on exit).
    ``tid`` overrides the recording thread's ident — retroactive
    events (e.g. the serving queue-wait, recorded at dispatch time
    but SPANNING the enqueue window) go on a :func:`track_tid`
    virtual row so they do not overlap real spans on this thread.

    The buffer holds RAW TUPLES, not Chrome-trace dicts: recording
    rides the serving dispatch loop (~10 events per coalesced batch),
    and a tuple append under the GIL costs a fraction of a dict build
    + lock round-trip — dict materialization happens once, on the
    cold export/read path (:func:`events`). Shapes:
    ``("X", name, ts_s, dur_s, tid, args|None, parent|None, depth,
    device_s|None)`` and ``("s"|"f", name, flow_id, ts_s, tid,
    args|None)``."""
    stack = getattr(_tls, "stack", ())
    depth = len(stack) - 1
    _append(("X", name, start_monotonic, dur_s,
             (int(tid) if tid is not None
              else threading.get_ident() & 0x7FFFFFFF),
             args, stack[-2] if depth > 0 else None, depth, device_s))


def _append(rec: tuple) -> None:
    """Buffer one raw record, dropping the OLDEST past MAX_EVENTS.
    The append itself is a single GIL-atomic deque op; only the
    (amortized) overflow trim takes the lock."""
    global _dropped
    _events.append(rec)
    if len(_events) > MAX_EVENTS:
        with _lock:
            while len(_events) > MAX_EVENTS:
                _events.popleft()
                _dropped += 1


def record_flow(name: str, flow_id: int, phase: str,
                args: Optional[Dict[str, Any]] = None) -> None:
    """Append one flow event (``phase`` = "s" start / "f" finish):
    Perfetto draws an arrow from the "s" point to the "f" point with
    the same ``id``/``name`` — the serving path uses it to connect a
    coalesced rider's submit to the batch that carried it."""
    _append(("f" if phase == "f" else "s", str(name), int(flow_id),
             time.monotonic(),
             threading.get_ident() & 0x7FFFFFFF, args))


def _materialize(rec: tuple, pid: int) -> Dict[str, Any]:
    """One raw buffer tuple -> Chrome-trace event dict (cold path)."""
    kind = rec[0]
    if kind == "X":
        _k, name, ts, dur, tid, args, parent, depth, device_s = rec
        ev: Dict[str, Any] = {
            "name": str(name), "ph": "X", "ts": ts * 1e6,
            "dur": max(dur, 0.0) * 1e6, "pid": pid, "tid": tid,
        }
        a = dict(args) if args else {}
        if parent is not None:
            a["parent"] = parent
            a["depth"] = depth
        if device_s is not None:
            a["device_s"] = device_s
        if a:
            ev["args"] = a
        return ev
    _k, name, flow_id, ts, tid, args = rec
    ev = {"name": name, "cat": name, "ph": kind, "id": flow_id,
          "ts": ts * 1e6, "pid": pid, "tid": tid}
    if kind == "f":
        # bind to the ENCLOSING slice's end, so the arrow lands on the
        # batch span rather than a zero-width point
        ev["bp"] = "e"
    if args:
        ev["args"] = dict(args)
    return ev


def events() -> List[Dict[str, Any]]:
    """The buffered events as Chrome-trace dicts (cold path: tests,
    benches, the export)."""
    pid = os.getpid()
    with _lock:
        raw = list(_events)
    return [_materialize(r, pid) for r in raw]


def dropped_events() -> int:
    return _dropped


def reset_events() -> None:
    global _dropped
    with _lock:
        _events.clear()
        _dropped = 0


def export_chrome_trace(path: Optional[str] = None) -> Optional[str]:
    """Write the collected events as Chrome-trace JSON and return the
    path (None when there is nowhere to write). Default filename is
    ``rank_<r>.trace.json`` when a rank is set (multi-rank gangs must
    not collide on pid-keyed names across hosts), else
    ``trace_<pid>.json``, under the configured trace dir; repeat
    exports overwrite (the buffer only grows within a process).

    The export rank-tags the stream: every event's ``pid`` becomes the
    rank (all buffered events belong to THIS process — the buffer is
    process-global), a ``process_name`` metadata row names the
    Perfetto process track, and the envelope records a wall/monotonic
    clock pair taken at the same instant so the cross-rank merger can
    rebase per-boot monotonic timestamps onto one shared timeline —
    the same envelope contract obs/aggregate.py's gauge merge uses."""
    rank = _rank
    if path is None:
        if not _dir:
            return None
        name = (f"rank_{rank}.trace.json" if rank is not None
                else f"trace_{os.getpid()}.json")
        path = os.path.join(_dir, name)
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    pid = os.getpid()
    out_pid = rank if rank is not None else pid
    proc_label = (f"rank {rank} (pid {pid})" if rank is not None
                  else f"lightgbm-tpu (pid {pid})")
    # wall/monotonic envelope pair, read back-to-back: the rebase error
    # is bounded by the gap between these two clock reads
    wall, mono = time.time(), time.monotonic()
    with _lock:
        raw = list(_events)
        dropped = _dropped
        # snapshot under the same lock track_tid mutates under — an
        # unlocked dict-comprehension could catch a concurrent first
        # registration mid-iteration
        track_names = {t: n for n, t in _tracks.items()}
    events = [_materialize(r, out_pid) for r in raw]
    tids = sorted({e["tid"] for e in events if "tid" in e})
    meta: List[Dict[str, Any]] = [{
        "name": "process_name", "ph": "M", "pid": out_pid,
        "args": {"name": proc_label},
    }]
    if rank is not None:
        # rank order == row order in the merged Perfetto view
        meta.append({"name": "process_sort_index", "ph": "M",
                     "pid": out_pid, "args": {"sort_index": rank}})
    meta.extend({"name": "thread_name", "ph": "M", "pid": out_pid,
                 "tid": t,
                 "args": {"name": track_names.get(t, f"thread {t}")}}
                for t in tids)
    doc = {
        "displayTimeUnit": "ms",
        "traceEvents": meta + events,
        "otherData": {
            "producer": "lightgbm-tpu obs",
            "dropped_events": dropped,
            "pid": pid,
            "rank": rank,
            # envelope clock pair for cross-rank monotonic rebase
            "ts": wall,
            "monotonic": mono,
        },
    }
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f)
    os.replace(tmp, path)
    return path


class _SpanTimer:
    """Internal helper used by ``obs.span``: measures wall (and
    optionally device-synced) duration and feeds trace + metrics."""

    __slots__ = ("name", "args", "sync", "t0", "depth")

    def __init__(self, name: str, args: Dict[str, Any], sync) -> None:
        self.name = name
        self.args = args
        self.sync = sync
        self.t0 = 0.0
        self.depth = 0

    def start(self) -> None:
        self.depth = _push(self.name)
        self.t0 = time.monotonic()

    def stop(self, record_trace: bool, observe) -> None:
        device_s = None
        if self.sync is not None:
            t_dispatch = time.monotonic() - self.t0
            try:
                self.sync()
            except Exception:
                pass
            device_s = time.monotonic() - self.t0 - t_dispatch
        dur = time.monotonic() - self.t0
        if record_trace:
            record_event(self.name, self.t0, dur, self.args, device_s)
        _pop()
        if observe is not None:
            observe(self.name, dur)
