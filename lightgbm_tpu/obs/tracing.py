"""Phase-scoped tracing: nested spans exported as Chrome-trace JSON.

The deep kernel story belongs to ``jax.profiler`` (xprof/TensorBoard,
wired via ``tpu_profile_dir``); these spans cover the HOST orchestration
the device profiler does not attribute — round loops, chunked predict,
ingest streaming, checkpoint writes — and export to the Chrome trace
event format, loadable directly in Perfetto (ui.perfetto.dev) or
chrome://tracing.

Span bookkeeping is thread-local (a per-thread stack gives nesting
depth and parent names); the event buffer is process-global, bounded,
and lock-protected. Every event is a ``ph: "X"`` complete event with
microsecond ``ts``/``dur`` on a monotonic base, so nesting renders as
containment per thread row.
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

__all__ = ["tracing_enabled", "enable_tracing", "disable_tracing",
           "record_event", "events", "dropped_events", "reset_events",
           "export_chrome_trace", "span_stack", "trace_dir"]

# bound the buffer: a runaway span site must degrade to dropped-event
# accounting, never to unbounded host memory
MAX_EVENTS = 200_000

_lock = threading.Lock()
_enabled = False
_dir: Optional[str] = None
_events: List[Dict[str, Any]] = []
_dropped = 0
_tls = threading.local()


def tracing_enabled() -> bool:
    return _enabled


def trace_dir() -> Optional[str]:
    return _dir


def enable_tracing(directory: Optional[str] = None) -> None:
    """Start collecting span events; ``directory`` (optional) is where
    ``export_chrome_trace`` writes by default. A second different
    directory keeps the first (one trace stream per process)."""
    global _enabled, _dir
    with _lock:
        _enabled = True
        if directory:
            if _dir and _dir != str(directory):
                from ..utils import log
                log.warning(
                    f"tpu_trace_dir={directory!r} ignored: tracing is "
                    f"already exporting to {_dir!r} (process-global)")
            else:
                _dir = str(directory)


def disable_tracing() -> None:
    global _enabled
    with _lock:
        _enabled = False


def span_stack() -> List[str]:
    """This thread's open span names, outermost first."""
    return list(getattr(_tls, "stack", ()))


def _push(name: str) -> int:
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    stack.append(name)
    return len(stack) - 1


def _pop() -> None:
    stack = getattr(_tls, "stack", None)
    if stack:
        stack.pop()


def record_event(name: str, start_monotonic: float, dur_s: float,
                 args: Optional[Dict[str, Any]] = None,
                 device_s: Optional[float] = None) -> None:
    """Append one complete event (called by ``obs.span`` on exit)."""
    global _dropped
    ev: Dict[str, Any] = {
        "name": str(name),
        "ph": "X",
        "ts": start_monotonic * 1e6,
        "dur": max(dur_s, 0.0) * 1e6,
        "pid": os.getpid(),
        "tid": threading.get_ident() & 0x7FFFFFFF,
    }
    a = dict(args or {})
    stack = getattr(_tls, "stack", ())
    if len(stack) > 1:
        a["parent"] = stack[-2]
        a["depth"] = len(stack) - 1
    if device_s is not None:
        a["device_s"] = device_s
    if a:
        ev["args"] = a
    with _lock:
        if len(_events) >= MAX_EVENTS:
            _dropped += 1
            return
        _events.append(ev)


def events() -> List[Dict[str, Any]]:
    with _lock:
        return list(_events)


def dropped_events() -> int:
    return _dropped


def reset_events() -> None:
    global _dropped
    with _lock:
        _events.clear()
        _dropped = 0


def export_chrome_trace(path: Optional[str] = None) -> Optional[str]:
    """Write the collected events as Chrome-trace JSON and return the
    path (None when there is nowhere to write). Default filename is
    ``trace_<pid>.json`` under the configured trace dir; repeat exports
    overwrite (the buffer only grows within a process)."""
    if path is None:
        if not _dir:
            return None
        path = os.path.join(_dir, f"trace_{os.getpid()}.json")
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    with _lock:
        doc = {
            "displayTimeUnit": "ms",
            "traceEvents": list(_events),
            "otherData": {
                "producer": "lightgbm-tpu obs",
                "dropped_events": _dropped,
            },
        }
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f)
    os.replace(tmp, path)
    return path


class _SpanTimer:
    """Internal helper used by ``obs.span``: measures wall (and
    optionally device-synced) duration and feeds trace + metrics."""

    __slots__ = ("name", "args", "sync", "t0", "depth")

    def __init__(self, name: str, args: Dict[str, Any], sync) -> None:
        self.name = name
        self.args = args
        self.sync = sync
        self.t0 = 0.0
        self.depth = 0

    def start(self) -> None:
        self.depth = _push(self.name)
        self.t0 = time.monotonic()

    def stop(self, record_trace: bool, observe) -> None:
        device_s = None
        if self.sync is not None:
            t_dispatch = time.monotonic() - self.t0
            try:
                self.sync()
            except Exception:
                pass
            device_s = time.monotonic() - self.t0 - t_dispatch
        dur = time.monotonic() - self.t0
        if record_trace:
            record_event(self.name, self.t0, dur, self.args, device_s)
        _pop()
        if observe is not None:
            observe(self.name, dur)
