"""Process-wide metrics registry: counters, gauges, histograms.

Reference: the reference ships per-iteration eval logging plus global
timing counters around its tree learners (utils/log.h timer macros,
UNVERIFIED — empty mount, see SURVEY.md banner). The TPU-native
equivalent must also see DEVICE health — compile storms, HBM creep —
so the registry here is the one sink every subsystem feeds
(obs/telemetry.py promotes the jax-side probes into gauges) and every
exporter reads (JSONL snapshots, a Prometheus-style text dump, the
``Booster.metrics()`` API).

Design constraints:

- dependency-free and import-light: stdlib only, never imports jax
  (obs/telemetry.py owns the jax-touching probes);
- thread-safe: serving is threaded, so metric creation takes the
  registry lock and every update takes the metric's own lock
  (tests/test_obs.py hammers one counter from many threads);
- label support: one logical name fans out per label set
  (``counter("predict.requests", model="a")``), Prometheus-style;
- monotonic timestamps: wall clocks step (NTP), so freshness fields
  (``updated``) use ``time.monotonic`` and only snapshot envelopes
  carry a wall ``ts``.
"""
from __future__ import annotations

import json
import os
import threading
import time
from bisect import bisect_left
from typing import Any, Dict, List, Optional, Tuple

__all__ = ["Counter", "Gauge", "Histogram", "MetricsRegistry",
           "registry", "DEFAULT_BUCKETS", "prometheus_from_snapshot"]

# latency-oriented exponential-ish bucket ladder (seconds), the usual
# Prometheus shape: sub-ms serving calls up to minute-scale constructs
DEFAULT_BUCKETS: Tuple[float, ...] = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0, float("inf"))


def _label_key(labels: Dict[str, Any]) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class _Metric:
    kind = "?"

    def __init__(self, name: str, labels: Dict[str, str]):
        self.name = name
        self.labels = dict(labels)
        self._lock = threading.Lock()
        self.updated = time.monotonic()

    # subclasses fill these
    def value_dict(self) -> Dict[str, Any]:  # pragma: no cover - abstract
        raise NotImplementedError

    def load_value(self, payload: Dict[str, Any]) -> None:  # pragma: no cover
        raise NotImplementedError

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            out = {"name": self.name, "type": self.kind,
                   "updated_monotonic": self.updated}
            if self.labels:
                out["labels"] = dict(self.labels)
            out.update(self.value_dict())
            return out


class Counter(_Metric):
    """Monotonically increasing count (resets only with the process /
    an explicit registry reset)."""

    kind = "counter"

    def __init__(self, name: str, labels: Dict[str, str]):
        super().__init__(name, labels)
        self.value = 0.0

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self.value += n
            self.updated = time.monotonic()

    def value_dict(self) -> Dict[str, Any]:
        return {"value": self.value}

    def load_value(self, payload: Dict[str, Any]) -> None:
        with self._lock:
            self.value = float(payload.get("value", 0.0))
            self.updated = time.monotonic()


class Gauge(_Metric):
    """Point-in-time value (HBM bytes, cache sizes, process count)."""

    kind = "gauge"

    def __init__(self, name: str, labels: Dict[str, str]):
        super().__init__(name, labels)
        self.value = 0.0

    def set(self, v: float) -> None:
        with self._lock:
            self.value = float(v)
            self.updated = time.monotonic()

    def add(self, n: float = 1.0) -> None:
        with self._lock:
            self.value += n
            self.updated = time.monotonic()

    def value_dict(self) -> Dict[str, Any]:
        return {"value": self.value}

    def load_value(self, payload: Dict[str, Any]) -> None:
        with self._lock:
            self.value = float(payload.get("value", 0.0))
            self.updated = time.monotonic()


class Histogram(_Metric):
    """Distribution: count / sum / min / max plus cumulative bucket
    counts over fixed upper bounds (``le`` semantics, last bound +inf)."""

    kind = "histogram"

    def __init__(self, name: str, labels: Dict[str, str],
                 buckets: Optional[Tuple[float, ...]] = None):
        super().__init__(name, labels)
        b = tuple(buckets or DEFAULT_BUCKETS)
        if b[-1] != float("inf"):
            b = b + (float("inf"),)
        self.bounds = b
        self.bucket_counts = [0] * len(b)
        self.count = 0
        self.sum = 0.0
        self.min: Optional[float] = None
        self.max: Optional[float] = None

    def observe(self, v: float) -> None:
        v = float(v)
        with self._lock:
            self.count += 1
            self.sum += v
            self.min = v if self.min is None else min(self.min, v)
            self.max = v if self.max is None else max(self.max, v)
            # first bound with v <= bound ("le" semantics); binary
            # search — this sits on the hot predict path per request
            self.bucket_counts[bisect_left(self.bounds, v)] += 1
            self.updated = time.monotonic()

    def value_dict(self) -> Dict[str, Any]:
        return {"count": self.count, "sum": self.sum,
                "min": self.min, "max": self.max,
                "buckets": [[b if b != float("inf") else "+Inf", c]
                            for b, c in zip(self.bounds,
                                            self.bucket_counts)]}

    def load_value(self, payload: Dict[str, Any]) -> None:
        with self._lock:
            self.count = int(payload.get("count", 0))
            self.sum = float(payload.get("sum", 0.0))
            self.min = payload.get("min")
            self.max = payload.get("max")
            saved = payload.get("buckets") or []
            if len(saved) == len(self.bounds):
                self.bucket_counts = [int(c) for _b, c in saved]
            self.updated = time.monotonic()


class MetricsRegistry:
    """Get-or-create store of metrics keyed on (name, labels)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._metrics: Dict[Tuple[str, Tuple[Tuple[str, str], ...]],
                            _Metric] = {}

    def _get_or_create(self, cls, name: str, labels: Dict[str, Any],
                       **kwargs) -> _Metric:
        key = (str(name), _label_key(labels))
        with self._lock:
            m = self._metrics.get(key)
            if m is None:
                m = cls(str(name), dict(labels), **kwargs)
                self._metrics[key] = m
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r} already registered as {m.kind}, "
                    f"requested {cls.kind}")
            return m

    def counter(self, name: str, **labels) -> Counter:
        return self._get_or_create(Counter, name, labels)

    def gauge(self, name: str, **labels) -> Gauge:
        return self._get_or_create(Gauge, name, labels)

    def histogram(self, name: str,
                  buckets: Optional[Tuple[float, ...]] = None,
                  **labels) -> Histogram:
        return self._get_or_create(Histogram, name, labels,
                                   buckets=buckets)

    def get(self, name: str, **labels) -> Optional[_Metric]:
        return self._metrics.get((str(name), _label_key(labels)))

    def metrics(self) -> List[_Metric]:
        with self._lock:
            return list(self._metrics.values())

    def reset(self, prefix: Optional[str] = None,
              kind: Optional[str] = None) -> None:
        """Drop metrics: all of them, only those whose name starts with
        ``prefix``, and/or only those of ``kind``
        ("counter"/"gauge"/"histogram"). The timer-shim back-compat
        path resets ``kind="histogram"`` so clearing phase timers never
        zeroes the compile/restart counters or device gauges."""
        with self._lock:
            if prefix is None and kind is None:
                self._metrics.clear()
                return
            for key in [k for k, m in self._metrics.items()
                        if (prefix is None or k[0].startswith(prefix))
                        and (kind is None or m.kind == kind)]:
                del self._metrics[key]

    # -- exporters ------------------------------------------------------
    def snapshot(self) -> Dict[str, Any]:
        """One self-describing JSON-able snapshot of every metric."""
        return {
            "schema": "lightgbm-tpu-metrics-v1",
            "ts": time.time(),
            "monotonic": time.monotonic(),
            "pid": os.getpid(),
            "metrics": [m.snapshot() for m in self.metrics()],
        }

    def dump_jsonl(self, path: str,
                   snap: Optional[Dict[str, Any]] = None) -> str:
        """Append one snapshot line to ``path`` (JSONL); pass ``snap``
        to write an already-taken snapshot. The ONE writer every dump
        path (obs.dump_jsonl, flush_from_config, the benches) funnels
        through."""
        if snap is None:
            snap = self.snapshot()
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        with open(path, "a") as f:
            f.write(json.dumps(snap) + "\n")
        return path

    def prometheus_text(self) -> str:
        return prometheus_from_snapshot(self.snapshot())

    # -- state persistence (checkpoint/restore) -------------------------
    def export_state(self) -> Dict[str, Any]:
        """Serializable registry state for checkpoints (metric values
        only — bucket layouts re-derive from the metric definitions)."""
        return {"version": 1, "metrics": [m.snapshot()
                                          for m in self.metrics()]}

    def import_state(self, state: Optional[Dict[str, Any]]) -> int:
        """Adopt a saved registry state: each saved metric is re-created
        (or found) and its value OVERWRITTEN with the saved payload —
        the resume contract is "continue the interrupted run's metrics",
        not "merge two runs". Returns the number of metrics restored."""
        if not state:
            return 0
        restored = 0
        for m in state.get("metrics", []):
            name = m.get("name")
            kind = m.get("type")
            labels = m.get("labels") or {}
            if not name or kind not in ("counter", "gauge", "histogram"):
                continue
            try:
                if kind == "counter":
                    target = self.counter(name, **labels)
                elif kind == "gauge":
                    target = self.gauge(name, **labels)
                else:
                    bounds = tuple(
                        float("inf") if b == "+Inf" else float(b)
                        for b, _c in (m.get("buckets") or [])) or None
                    target = self.histogram(name, buckets=bounds,
                                            **labels)
                target.load_value(m)
                restored += 1
            except TypeError:
                # kind collision with a live metric: keep the live one
                continue
        return restored


def _prom_name(name: str) -> str:
    out = []
    for ch in str(name):
        out.append(ch if ch.isalnum() or ch == "_" else "_")
    s = "".join(out)
    if s and s[0].isdigit():
        s = "_" + s
    return s


def _prom_label_value(v: Any) -> str:
    """Escape one label VALUE per the Prometheus text-format spec:
    backslash, double-quote and newline must be escaped or the
    exposition is unparseable (a model name containing ``"`` would
    otherwise terminate the label early)."""
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _prom_labels(labels: Dict[str, Any]) -> str:
    if not labels:
        return ""
    return "{" + ",".join(
        f'{_prom_name(k)}="{_prom_label_value(v)}"'
        for k, v in sorted(labels.items())) + "}"


def prometheus_from_snapshot(snap: Dict[str, Any]) -> str:
    """Prometheus-style text exposition built from a snapshot dict (the
    live registry and ``task=dump_metrics``' file reader share this)."""
    lines: List[str] = []
    typed: Dict[str, str] = {}
    for m in snap.get("metrics", []):
        name = _prom_name(m.get("name", ""))
        kind = m.get("type", "gauge")
        if typed.get(name) is None:
            lines.append(f"# TYPE {name} {kind}")
            typed[name] = kind
        labels = m.get("labels") or {}
        lab = _prom_labels(labels)
        if kind in ("counter", "gauge"):
            lines.append(f"{name}{lab} {m.get('value', 0.0):g}")
            continue
        # histogram: cumulative buckets + _sum/_count. `buckets` can be
        # present-but-null (a cross-version gang merge degrades
        # mismatched layouts to the scalar fields — obs/aggregate.py);
        # render what remains instead of crashing the exposition
        cum = 0
        for bound, c in (m.get("buckets") or []):
            cum += int(c)
            le = bound if bound == "+Inf" else f"{float(bound):g}"
            lines.append(f"{name}_bucket{_prom_labels(dict(labels, le=le))}"
                         f" {cum}")
        lines.append(f"{name}_sum{lab} {m.get('sum', 0.0):g}")
        lines.append(f"{name}_count{lab} {m.get('count', 0)}")
    return "\n".join(lines) + ("\n" if lines else "")


_REGISTRY = MetricsRegistry()


def registry() -> MetricsRegistry:
    """The process-wide registry every subsystem feeds."""
    return _REGISTRY
