"""CLI application: config-file-driven train / predict.

Reference: ``Application`` (src/application/application.cpp, src/main.cpp,
UNVERIFIED — empty mount, see SURVEY.md banner): parse ``key=value`` args
(first positional = config file), dispatch on ``task``:

- ``task=train``: load data/valid files, train, save ``output_model``
  (+ ``snapshot_freq`` checkpoints handled by engine.train)
- ``task=predict``: load ``input_model``, predict ``data``, write
  ``output_result``
- ``task=convert_model``: load + re-save a model (format passthrough)
- ``task=save_binary``: bin the data file and write the binary dataset

Invoke as ``python -m lightgbm_tpu config=train.conf`` or with inline
``key=value`` pairs.
"""
from __future__ import annotations

import sys
from typing import Any, Dict, List, Optional

import numpy as np

from .basic import Booster, Dataset
from .config import parse_config_file
from .engine import train
from .utils import log


def parse_cli_args(argv: List[str]) -> Dict[str, Any]:
    """key=value args; ``config=FILE`` pulls in a reference-style config
    file (k=v lines, '#' comments); CLI pairs override the file."""
    cli: Dict[str, Any] = {}
    config_path = None
    for tok in argv:
        if "=" not in tok:
            config_path = tok          # bare positional = config file
            continue
        k, _, v = tok.partition("=")
        if k.strip() == "config":
            config_path = v.strip()
        else:
            cli[k.strip()] = v.strip()
    params: Dict[str, Any] = {}
    if config_path:
        params.update(parse_config_file(config_path))
    params.update(cli)
    return params


def _cli_file_shard(data_path: str, params: Dict[str, Any],
                    rank: int, nproc: int):
    """Per-worker shard loader for the distributed CLI: every process
    parses the data file (each machine reads the file in the
    reference's pre-partition=false mode) and keeps its contiguous row
    slice. Module-level so spawn can pickle it via functools.partial."""
    from .io.text_loader import load_text
    loaded = load_text(
        data_path,
        label_column=params.get("label_column", "auto"),
        weight_column=params.get("weight_column"),
        group_column=params.get("group_column"),
        ignore_column=params.get("ignore_column"))
    if loaded.group is not None:
        log.fatal("num_machines>1 does not shard ranking groups; "
                  "use lightgbm_tpu.run_worker with a group-aligned "
                  "data_fn")
    n = len(loaded.X)
    if n < nproc:
        log.fatal(f"num_machines={nproc} exceeds the data file's row "
                  f"count ({n}): every worker needs at least one row "
                  f"(contiguous sharding would hand rank(s) an empty "
                  f"shard) — lower num_machines or provide more data")
    blk = n // nproc
    lo = rank * blk
    hi = n if rank == nproc - 1 else lo + blk
    return {"data": loaded.X[lo:hi],
            "label": None if loaded.label is None
            else loaded.label[lo:hi],
            "weight": None if loaded.weight is None
            else loaded.weight[lo:hi]}


def run(argv: Optional[List[str]] = None) -> int:
    params = parse_cli_args(list(sys.argv[1:] if argv is None else argv))
    task = str(params.pop("task", "train")).lower()
    data_path = params.pop("data", None)
    valid_spec = params.pop("valid", params.pop("valid_data", None))
    output_model = params.get("output_model", "LightGBM_model.txt")
    input_model = params.pop("input_model", None)
    output_result = params.pop("output_result", "LightGBM_predict_result.txt")
    num_round = int(params.pop("num_iterations",
                               params.pop("num_boost_round", 100)))

    if task in ("refit", "refit_tree"):
        # Application task=refit (gbdt.cpp::RefitTree): RE-FIT the loaded
        # model's existing leaf values on the new data — does NOT add
        # trees (that is task=train with input_model= continuation)
        if input_model is None:
            log.fatal("task=refit needs input_model=FILE")
        if data_path is None:
            log.fatal("No refit data: pass data=FILE")
        from .io.text_loader import load_text
        loaded = load_text(
            data_path,
            label_column=params.get("label_column", "auto"),
            weight_column=params.get("weight_column"),
            group_column=params.get("group_column"),
            ignore_column=params.get("ignore_column"))
        if loaded.label is None:
            log.fatal("task=refit data has no label column")
        bst = Booster(model_file=input_model, params=dict(params))
        decay = params.get("refit_decay_rate")
        new_bst = bst.refit(loaded.X, loaded.label, weight=loaded.weight,
                            group=loaded.group,
                            decay_rate=(None if decay is None
                                        else float(decay)))
        new_bst.save_model(output_model)
        log.info(f"Finished refit; model saved to {output_model}")
        return 0

    if task == "train":
        if data_path is None:
            log.fatal("No training data: pass data=FILE")
        # distributed CLI (application.cpp's Network::Init-from-config
        # flow, SURVEY §3.2 — UNVERIFIED): num_machines=N forks N
        # localhost jax.distributed workers, each loading the data file
        # and keeping its contiguous row shard (the reference's
        # rank-aware pre_partition load). Real pods should call
        # lightgbm_tpu.run_worker once per host instead — the machine
        # list lives in jax.distributed, not a machine_list file.
        n_machines = int(params.pop(
            "num_machines", params.pop("num_machine", 1)))
        if n_machines > 1:
            if valid_spec:
                log.warning("valid sets are ignored under "
                            "num_machines>1 (evaluate task=predict "
                            "on the saved model instead)")
            from functools import partial

            from .parallel.launch import train_distributed
            data_fn = partial(_cli_file_shard, data_path, dict(params))
            bst = train_distributed(params, data_fn,
                                    n_processes=n_machines,
                                    num_boost_round=num_round)
            bst.save_model(output_model)
            log.info(f"Finished distributed training "
                     f"({n_machines} processes); model saved to "
                     f"{output_model}")
            return 0
        ds = Dataset(data_path, params=dict(params))
        valid_sets, valid_names = [], []
        if valid_spec:
            for i, vp in enumerate(str(valid_spec).split(",")):
                valid_sets.append(Dataset(vp, reference=ds,
                                          params=dict(params)))
                valid_names.append(vp)
        params.setdefault("verbosity", 1)
        bst = train(params, ds, num_boost_round=num_round,
                    valid_sets=valid_sets or None,
                    valid_names=valid_names or None,
                    init_model=input_model)
        bst.save_model(output_model)
        log.info(f"Finished training; model saved to {output_model}")
        return 0

    if task in ("predict", "prediction", "test"):
        if input_model is None:
            log.fatal("task=predict needs input_model=FILE")
        if data_path is None:
            log.fatal("No data to predict: pass data=FILE")
        bst = Booster(model_file=input_model)
        from .config import coerce_bool
        from .io.text_loader import load_text
        # the SAME column layout as training: weight/group/ignore columns
        # must be dropped from X or every feature shifts
        loaded = load_text(
            data_path,
            label_column=params.get("label_column", "auto"),
            weight_column=params.get("weight_column"),
            group_column=params.get("group_column"),
            ignore_column=params.get("ignore_column"))
        X = loaded.X
        n_feat = bst.num_feature()
        if X.shape[1] < n_feat:
            # libsvm files size by max PRESENT index; pad to the model's
            # feature count (the reference pads parsed rows the same way)
            X = np.concatenate(
                [X, np.zeros((len(X), n_feat - X.shape[1]))], axis=1)
        elif X.shape[1] > n_feat:
            if coerce_bool(params.get("predict_disable_shape_check",
                                      False)):
                X = X[:, :n_feat]
            else:
                log.fatal(f"The number of features in data ({X.shape[1]})"
                          f" is not the same as it was in training data "
                          f"({n_feat}); set predict_disable_shape_check="
                          f"true to ignore")
        n_iter_p = int(params.get("num_iteration_predict", -1))
        pred = bst.predict(
            X,
            start_iteration=int(params.get("start_iteration_predict", 0)),
            num_iteration=(None if n_iter_p <= 0 else n_iter_p),
            raw_score=coerce_bool(params.get("predict_raw_score", False)),
            pred_leaf=coerce_bool(params.get("predict_leaf_index", False)),
            pred_contrib=coerce_bool(params.get("predict_contrib",
                                                False)))
        np.savetxt(output_result, np.atleast_1d(pred), fmt="%.10g",
                   delimiter="\t")
        log.info(f"Finished prediction; results saved to {output_result}")
        return 0

    if task == "convert_model":
        if input_model is None:
            log.fatal("task=convert_model needs input_model=FILE")
        bst = Booster(model_file=input_model)
        out = params.get("convert_model", "gbdt_prediction.cpp")
        lang = str(params.get("convert_model_language", "")).lower()
        if lang in ("", "cpp", "c", "c++"):
            # the reference's convert_model emits standalone C++
            # if-else prediction code (application.cpp task taxonomy;
            # cpp is the only — and therefore default — target)
            with open(out, "w") as f:
                f.write(bst.model_to_c())
        else:
            log.fatal(f"Unknown convert_model_language {lang!r} "
                      f"(only cpp is supported)")
        return 0

    if task == "dump_metrics":
        # observability hook (docs/observability.md): render a metrics
        # snapshot as Prometheus-style text (default) or pretty JSON.
        # data=FILE reads the newest line of a tpu_metrics_dump JSONL;
        # without data= the LIVE process registry is dumped (useful
        # when chained programmatically, mostly empty from a fresh CLI)
        import json
        from . import obs
        from .obs.metrics import prometheus_from_snapshot
        if data_path:
            try:
                with open(data_path) as f:
                    lines = [ln for ln in f.read().splitlines()
                             if ln.strip()]
            except OSError as e:
                log.fatal(f"task=dump_metrics: cannot read "
                          f"{data_path}: {e}")
            if not lines:
                log.fatal(f"task=dump_metrics: {data_path} holds no "
                          f"snapshot lines")
            try:
                snap = json.loads(lines[-1])
            except ValueError as e:
                log.fatal(f"task=dump_metrics: {data_path} last line "
                          f"is not valid JSON: {e}")
        else:
            snap = obs.snapshot()
        fmt = str(params.get("format", "prometheus")).lower()
        if fmt in ("prometheus", "prom", "text"):
            sys.stdout.write(prometheus_from_snapshot(snap))
        elif fmt == "json":
            sys.stdout.write(json.dumps(snap, indent=2) + "\n")
        else:
            log.fatal(f"task=dump_metrics: unknown format {fmt!r} "
                      f"(prometheus or json)")
        return 0

    if task == "save_binary":
        if data_path is None:
            log.fatal("task=save_binary needs data=FILE")
        out = params.pop("output_data", data_path + ".bin")
        Dataset(data_path, params=dict(params)).save_binary(out)
        log.info(f"Binary dataset saved to {out}")
        return 0

    log.fatal(f"Unknown task {task}")
    return 1


def main() -> None:
    sys.exit(run())
