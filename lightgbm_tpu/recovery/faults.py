"""Fault injection for fault-tolerance CI (``tpu_fault_inject``) — the
chaos harness the recovery subsystem is attacked with.

Spec syntax: one or more ``;``-separated specs, each
``"<kind>:key=value,key=value"`` with kind one of

* ``kill``    — SIGKILL this process (preemption / OOM-kill),
* ``exn``     — raise ``LightGBMError`` (in-band failure),
* ``hang``    — wedge the process in an uninterruptible-looking sleep
  BEFORE it reaches the next collective (a stuck DMA / dead peer /
  deadlocked rank; only the launcher's heartbeat watchdog — or SIGKILL
  — gets it unstuck),
* ``slow``    — inject ``ms`` of delay before every matching iteration
  from ``iter`` on (a straggler rank; shows up in the
  ``dist.round_time_spread`` gauge, never kills anything),
* ``corrupt`` — flip bytes in the newest checkpoint in ``dir`` (or
  clobber its ``latest`` pointer with ``target=latest``, or both with
  ``target=both``) — a torn write / bad disk the loader must fall back
  past,
* ``port``    — raise an error shaped like the coordinator bind race
  ("address already in use"), so relaunch paths exercise their
  fresh-port bind retry deterministically,
* ``resize``  — a PERMANENT host loss (autoscale-down, a machine
  pulled from the fleet): SIGKILL the ranks named by ``ranks`` AND
  write a ``.host_gone.rank<r>`` marker per named rank, which the
  launcher's degrade-and-continue path reads as "this host is not
  coming back — relaunch the gang narrower instead of burning
  ``max_restarts`` retrying at full strength"
  (docs/robustness.md "Elastic topology").

Keys: ``iter`` (required; 0-based boosting iteration — the fault fires
BEFORE that iteration runs; ``slow`` keeps firing every iteration >=
``iter``), ``rank`` (optional ``jax`` process index; default: every
process), ``ms`` (``slow``/``hang``: delay per fire / max wedge time,
default 200 / wedge-forever), ``target`` and ``nbytes`` (``corrupt``:
what to damage and how many bytes to flip, default ``ckpt`` / 8),
``ranks`` (``resize``, required: ``+``-separated rank list whose hosts
go away, e.g. ``ranks=1`` or ``ranks=2+3``).
Examples: ``"kill:rank=1,iter=10"``, ``"hang:rank=0,iter=6"``,
``"corrupt:iter=8,target=both"``, ``"slow:iter=3,ms=250;exn:iter=9"``,
``"resize:iter=4,ranks=1"``.

Determinism: every random choice a fault makes (which bytes ``corrupt``
flips) is drawn from a PRNG seeded by the spec text itself
(:func:`spec_seed`), so a CI failure replays byte-for-byte from the
spec alone.

Fire-once semantics: when a marker directory is available (explicit
``tpu_fault_marker``, else ``checkpoint_dir``), firing a TERMINAL or
DAMAGING fault (kill/exn/hang/corrupt/port/resize) writes a marker file keyed
by (spec, rank); a restarted process that replays the same iteration
skips the fault instead of dying forever in a restart loop. ``slow``
never writes markers (it is not terminal and must keep firing to model
a persistently slow rank). Without a marker directory terminal faults
fire on every matching pass — fine for single-shot tests, wrong for
restart loops (documented in docs/robustness.md). Fresh (non-resume)
runs clear THEIR rank's stale markers at setup so yesterday's marker
cannot suppress today's injected fault (:func:`clear_fault_markers`);
gang relaunches keep them (the launcher marks relaunched workers via
``LGBM_TPU_GANG_RELAUNCH``).
"""
from __future__ import annotations

import hashlib
import os
import signal
import time
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional

from ..utils import log
from ..utils.log import LightGBMError

__all__ = ["FaultPlan", "parse_fault_spec", "parse_fault_specs",
           "fault_injection_callback", "clear_fault_markers",
           "host_gone_ranks", "clear_host_gone_markers",
           "spec_seed", "FAULT_KINDS"]

FAULT_KINDS = ("kill", "exn", "hang", "slow", "corrupt", "port",
               "resize")

# keys each kind accepts beyond the required ``iter`` (+ optional
# ``rank``); unknown keys are a spec typo the user must hear about
_KIND_KEYS: Dict[str, tuple] = {
    "kill": (),
    "exn": (),
    "hang": ("ms",),
    "slow": ("ms",),
    "corrupt": ("target", "nbytes"),
    "port": (),
    "resize": ("ranks",),
}

# what a ``resize`` fault leaves behind for the launcher: one
# ``.host_gone.rank<r>`` marker per permanently-lost rank. Consumed
# (deleted) by the degrade-and-continue path when it narrows the gang;
# cleared by fresh (non-resuming) launcher runs.
_HOST_GONE_PREFIX = ".host_gone.rank"

# message shaped to match recovery/restart.py's _BIND_TOKENS so the
# launcher's bind-retry path (fresh port, no restart attempt consumed)
# triggers exactly as it would on the real _free_port race
_PORT_MSG = ("tpu_fault_inject: injected coordinator bind conflict "
             "(address already in use) before iteration {it} ({spec!r})")


def _current_rank() -> int:
    try:
        import jax
        return int(jax.process_index())
    except Exception:
        return 0


def spec_seed(spec: str) -> int:
    """Deterministic 32-bit seed derived from the spec text — every
    random draw a fault makes keys on this, so a CI failure replays
    from the logged spec alone."""
    return int(hashlib.sha1(str(spec).encode("utf-8")).hexdigest()[:8],
               16)


def clear_fault_markers(directory, rank: Optional[int] = None) -> int:
    """Remove stale ``.fault_fired.*`` markers from ``directory`` —
    THIS rank's only when ``rank`` is given (worker-side fresh-run
    hygiene: each rank owns its own markers, so a slow-starting rank
    can never clear a marker a faster rank just wrote), every rank's
    when None (driver-side, before any worker exists). Returns the
    count removed."""
    directory = str(directory or "")
    if not directory:
        return 0
    suffix = None if rank is None else f".rank{int(rank)}"
    removed = 0
    try:
        names = os.listdir(directory)
    except OSError:
        return 0
    for name in names:
        if not name.startswith(".fault_fired."):
            continue
        if suffix is not None and not name.endswith(suffix):
            continue
        try:
            os.unlink(os.path.join(directory, name))
            removed += 1
        except OSError:
            pass
    return removed


def host_gone_ranks(directory) -> List[int]:
    """Ranks with a ``.host_gone.rank<r>`` marker in ``directory`` —
    hosts the chaos harness (or an operator touch-file) declared
    permanently lost. The launcher reads these to degrade-and-continue
    instead of relaunching at full width."""
    directory = str(directory or "")
    if not directory:
        return []
    out = []
    try:
        names = os.listdir(directory)
    except OSError:
        return []
    for name in names:
        if name.startswith(_HOST_GONE_PREFIX):
            tail = name[len(_HOST_GONE_PREFIX):]
            if tail.isdigit():
                out.append(int(tail))
    return sorted(set(out))


def clear_host_gone_markers(directory,
                            ranks: Optional[List[int]] = None) -> int:
    """Remove host-gone markers from ``directory`` — every rank's when
    ``ranks`` is None (fresh-run hygiene), the named ranks' when given
    (the degrade path CONSUMES the markers it acted on, so a later
    unrelated failure cannot re-apply yesterday's loss). Returns the
    count removed."""
    directory = str(directory or "")
    if not directory:
        return 0
    wanted = None if ranks is None else {int(r) for r in ranks}
    removed = 0
    for r in host_gone_ranks(directory):
        if wanted is not None and r not in wanted:
            continue
        try:
            os.unlink(os.path.join(directory,
                                   f"{_HOST_GONE_PREFIX}{r}"))
            removed += 1
        except OSError:
            pass
    return removed


def write_host_gone_marker(directory, rank: int,
                           note: str = "") -> Optional[str]:
    """Declare ``rank``'s host permanently lost: write the
    ``.host_gone.rank<r>`` marker the degrade paths consume (the gang
    launcher narrows past it; the serving-fleet supervisor retires the
    replica instead of relaunching). The ``resize`` chaos fault and
    the fleet kill helpers both route through here so the marker name
    lives in one place. Returns the marker path (None on I/O
    failure — the caller logs, the kill still proceeds)."""
    directory = str(directory or "")
    if not directory:
        return None
    try:
        os.makedirs(directory, exist_ok=True)
        path = os.path.join(directory,
                            f"{_HOST_GONE_PREFIX}{int(rank)}")
        with open(path, "w") as f:
            f.write((str(note) + "\n") if note else "")
        return path
    except OSError:
        return None


@dataclass
class FaultPlan:
    kind: str                   # one of FAULT_KINDS
    iteration: int              # fires before this 0-based iteration
    rank: Optional[int]         # None = every process
    marker_dir: str             # "" = no fire-once marker
    spec: str                   # original spec text (for messages)
    ms: int = 0                 # slow: delay per fire; hang: wall-
    #                             clock cap when > 0 (tests), else
    #                             wedge forever
    target: str = "ckpt"        # corrupt: ckpt | latest | both
    nbytes: int = 8             # corrupt: bytes flipped per file
    ckpt_dir: str = ""          # corrupt: where checkpoints live
    ranks: tuple = ()           # resize: ranks whose hosts go away

    def marker_path(self, rank: int) -> str:
        h = hashlib.sha1(self.spec.encode("utf-8")).hexdigest()[:10]
        return os.path.join(self.marker_dir,
                            f".fault_fired.{h}.rank{rank}")

    # -- per-kind behaviors ---------------------------------------------
    def _fire_kill(self, rank: int) -> None:
        log.warning(f"tpu_fault_inject: killing process (rank "
                    f"{rank}) before iteration {self.iteration} "
                    f"({self.spec!r})")
        os.kill(os.getpid(), signal.SIGKILL)

    def _fire_hang(self, rank: int) -> None:
        """Wedge: stop stamping heartbeats and sleep. Models a rank
        stuck pre-collective — from outside it is alive (the process
        exists, no exit code) but makes no progress; only the
        watchdog's SIGKILL (or the optional ``ms`` cap, for tests
        without a watchdog) ends it."""
        cap = self.ms / 1000.0 if self.ms > 0 else float("inf")
        log.warning(
            f"tpu_fault_inject: hanging process (rank {rank}) before "
            f"iteration {self.iteration} ({self.spec!r}; "
            + (f"max {cap:.1f}s)" if cap != float("inf")
               else "until killed)"))
        t0 = time.monotonic()
        while time.monotonic() - t0 < cap:
            time.sleep(0.25)
        raise LightGBMError(
            f"tpu_fault_inject: hang released after {self.ms} ms "
            f"without being killed ({self.spec!r})")

    def _fire_slow(self, rank: int) -> None:
        time.sleep(max(self.ms, 1) / 1000.0)

    def _fire_resize(self, rank: int) -> None:
        """Permanent host loss: every firing process writes the
        ``.host_gone.rank<r>`` markers (idempotent file creates — the
        launcher must see them even when a listed rank is too wedged
        to write its own), then the LISTED ranks SIGKILL themselves.
        Survivors return to training and die in the gang teardown —
        exactly the shape of a machine vanishing mid-collective. No
        random draws, so the spec text alone replays it."""
        d = self.marker_dir or self.ckpt_dir
        if d:
            for q in self.ranks:
                if write_host_gone_marker(d, q, note=self.spec) is None:
                    log.warning(f"tpu_fault_inject: cannot write "
                                f"host-gone marker for rank {q}")
        else:
            log.warning(
                f"tpu_fault_inject: resize fault has no marker/"
                f"checkpoint dir to signal the launcher through "
                f"({self.spec!r}); the ranks still die but the gang "
                f"can only restart at full width")
        if rank in self.ranks:
            log.warning(f"tpu_fault_inject: resize — host of rank "
                        f"{rank} is gone before iteration "
                        f"{self.iteration} ({self.spec!r})")
            os.kill(os.getpid(), signal.SIGKILL)

    def _fire_corrupt(self, rank: int) -> None:
        """Flip ``nbytes`` PAYLOAD bytes of the newest checkpoint (and/
        or clobber the ``latest`` pointer) — deterministic offsets from
        :func:`spec_seed`. Damages rank 0's files (the restart-decision
        source of truth) so the loader's fallback walk is what gets
        exercised."""
        import random

        from .checkpoint import CheckpointManager
        d = self.ckpt_dir or self.marker_dir
        if not d or not os.path.isdir(d):
            log.warning(f"tpu_fault_inject: corrupt fault has no "
                        f"checkpoint dir to damage ({self.spec!r}); "
                        f"skipping")
            return
        rng = random.Random(spec_seed(self.spec))
        mgr = CheckpointManager(d, rank=0)
        if self.target in ("ckpt", "both"):
            its = mgr.iterations()
            if its:
                p = mgr.path(its[-1])
                try:
                    with open(p, "r+b") as f:
                        f.seek(0, os.SEEK_END)
                        size = f.tell()
                        # flip bytes in the back half: always payload,
                        # never just the header line a cheap parse
                        # would catch — the sha256 must do the work
                        for _ in range(max(1, self.nbytes)):
                            off = rng.randrange(size // 2, size)
                            f.seek(off)
                            b = f.read(1)
                            f.seek(off)
                            f.write(bytes([b[0] ^ 0xFF]))
                    log.warning(
                        f"tpu_fault_inject: corrupted checkpoint {p} "
                        f"({self.nbytes} byte(s) flipped, seed "
                        f"{spec_seed(self.spec):#x}; {self.spec!r})")
                except OSError as e:
                    log.warning(f"tpu_fault_inject: cannot corrupt "
                                f"{p}: {e}")
        if self.target in ("latest", "both"):
            try:
                with open(mgr.latest_pointer, "w") as f:
                    f.write("ckpt_99999999.rank0.ckpt\n")
                log.warning(
                    f"tpu_fault_inject: clobbered latest pointer "
                    f"{mgr.latest_pointer} ({self.spec!r})")
            except OSError as e:
                log.warning(f"tpu_fault_inject: cannot clobber latest "
                            f"pointer: {e}")

    # -- dispatch -------------------------------------------------------
    def maybe_fire(self, iteration: int) -> None:
        """Fire the fault if ``iteration`` matches and it has not fired
        before (per the marker file). ``kill`` does not return; ``hang``
        returns only when capped; ``slow`` fires on EVERY iteration >=
        its target and never writes markers."""
        it = int(iteration)
        if self.kind == "slow":
            if it >= self.iteration:
                rank = _current_rank()
                if self.rank is None or rank == self.rank:
                    self._fire_slow(rank)
            return
        if it != self.iteration:
            return
        rank = _current_rank()
        if self.rank is not None and rank != self.rank:
            return
        if self.marker_dir:
            mp = self.marker_path(rank)
            if os.path.exists(mp):
                log.debug(f"tpu_fault_inject: {self.spec!r} already "
                          f"fired (marker {mp}); skipping")
                return
            os.makedirs(self.marker_dir, exist_ok=True)
            with open(mp, "w") as f:
                f.write(self.spec + "\n")
        if self.kind == "kill":
            self._fire_kill(rank)
        if self.kind == "hang":
            self._fire_hang(rank)
        if self.kind == "corrupt":
            self._fire_corrupt(rank)
            return                       # damage done; training goes on
        if self.kind == "resize":
            self._fire_resize(rank)
            return                       # survivors keep training
        if self.kind == "port":
            raise LightGBMError(
                _PORT_MSG.format(it=self.iteration, spec=self.spec))
        raise LightGBMError(
            f"tpu_fault_inject: injected failure before iteration "
            f"{self.iteration} ({self.spec!r})")


def parse_fault_spec(spec: str, marker_dir: str = "",
                     ckpt_dir: str = "") -> FaultPlan:
    """Parse ONE ``kind:key=value,...`` spec (see module docstring)."""
    s = str(spec).strip()
    kind, _, rest = s.partition(":")
    kind = kind.strip().lower()
    if kind not in FAULT_KINDS:
        log.fatal(f"tpu_fault_inject: unknown fault kind {kind!r} in "
                  f"{spec!r} (expected one of "
                  f"{'|'.join(FAULT_KINDS)})")
    allowed = ("iter", "rank") + _KIND_KEYS[kind]
    fields: Dict[str, object] = {}
    for tok in rest.split(","):
        tok = tok.strip()
        if not tok:
            continue
        k, _, v = tok.partition("=")
        k, v = k.strip(), v.strip()
        if k not in allowed:
            log.fatal(f"tpu_fault_inject: cannot parse {tok!r} in "
                      f"{spec!r} (a {kind!r} fault takes "
                      f"{', '.join(allowed)})")
        if k == "target":
            if v not in ("ckpt", "latest", "both"):
                log.fatal(f"tpu_fault_inject: target must be ckpt, "
                          f"latest or both (got {v!r} in {spec!r})")
            fields[k] = v
        elif k == "ranks":
            parts = [p.strip() for p in v.split("+")]
            if not parts or not all(p.isdigit() for p in parts):
                log.fatal(f"tpu_fault_inject: cannot parse {tok!r} in "
                          f"{spec!r} (ranks=<r> or ranks=<r>+<r>+... "
                          f"expected)")
            fields[k] = tuple(sorted({int(p) for p in parts}))
        else:
            if not v.lstrip("-").isdigit():
                log.fatal(f"tpu_fault_inject: cannot parse {tok!r} in "
                          f"{spec!r} ({k}=<int> expected)")
            fields[k] = int(v)
    if "iter" not in fields:
        log.fatal(f"tpu_fault_inject: {spec!r} needs an iter=<n> field")
    if kind == "resize" and not fields.get("ranks"):
        log.fatal(f"tpu_fault_inject: a resize fault needs "
                  f"ranks=<r>[+<r>...] naming the hosts that go away "
                  f"({spec!r})")
    if kind == "corrupt" and "rank" not in fields:
        # corrupt damages rank 0's files; with rank unset EVERY rank
        # would run the same spec-seeded XOR flips on the same bytes —
        # an even number of passes RESTORES the file (and interleaved
        # writes break the byte-for-byte replay guarantee). One rank
        # fires, deterministically.
        fields["rank"] = 0
    return FaultPlan(kind=kind, iteration=int(fields["iter"]),
                     rank=fields.get("rank"),
                     marker_dir=str(marker_dir or ""), spec=s,
                     ms=int(fields.get("ms",
                                       200 if kind == "slow" else 0)),
                     target=str(fields.get("target", "ckpt")),
                     nbytes=int(fields.get("nbytes", 8)),
                     ckpt_dir=str(ckpt_dir or ""),
                     ranks=tuple(fields.get("ranks", ())))


def parse_fault_specs(spec: str, marker_dir: str = "",
                      ckpt_dir: str = "") -> List[FaultPlan]:
    """Parse a ``;``-separated fault MATRIX into its plans (order
    preserved — a ``slow`` delay runs before an ``exn`` raise at the
    same iteration when written that way)."""
    return [parse_fault_spec(part, marker_dir, ckpt_dir)
            for part in str(spec).split(";") if part.strip()]


def fault_injection_callback(spec: str, marker_dir: str = "",
                             ckpt_dir: str = "") -> Callable:
    """Before-iteration training callback wrapping the parsed fault
    plan(s) (wired by ``engine.train`` when ``tpu_fault_inject`` is
    set). ``ckpt_dir`` tells ``corrupt`` faults where the checkpoints
    live (defaults to the marker dir)."""
    plans = parse_fault_specs(spec, marker_dir, ckpt_dir)
    if not plans:
        log.fatal(f"tpu_fault_inject: {spec!r} holds no fault spec")

    def _callback(env) -> None:
        for plan in plans:
            plan.maybe_fire(env.iteration)
    _callback.before_iteration = True
    _callback.order = -100          # fire before any real callback work
    _callback.fault_plan = plans[0]
    _callback.fault_plans = plans
    return _callback
