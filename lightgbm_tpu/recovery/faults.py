"""Fault injection for fault-tolerance CI (``tpu_fault_inject``).

Spec syntax: ``"<kind>:key=value,key=value"`` with kind one of

* ``kill`` — SIGKILL this process (simulates preemption / OOM-kill),
* ``exn``  — raise ``LightGBMError`` (simulates an in-band failure).

Keys: ``iter`` (required; 0-based boosting iteration — the fault fires
BEFORE that iteration runs) and ``rank`` (optional ``jax`` process
index; default: every process). Examples: ``"kill:rank=1,iter=10"``,
``"exn:iter=5"``.

Fire-once semantics: when a marker directory is available (explicit
``tpu_fault_marker``, else ``checkpoint_dir``), firing writes a marker
file keyed by (spec, rank); a restarted process that replays the same
iteration skips the fault instead of dying forever in a restart loop.
Without a marker directory the fault fires on every matching pass —
fine for single-shot tests, wrong for restart loops (documented in
docs/robustness.md).
"""
from __future__ import annotations

import hashlib
import os
import signal
from dataclasses import dataclass
from typing import Callable, Optional

from ..utils import log
from ..utils.log import LightGBMError

__all__ = ["FaultPlan", "parse_fault_spec", "fault_injection_callback"]


def _current_rank() -> int:
    try:
        import jax
        return int(jax.process_index())
    except Exception:
        return 0


@dataclass
class FaultPlan:
    kind: str                   # "kill" | "exn"
    iteration: int              # fires before this 0-based iteration
    rank: Optional[int]         # None = every process
    marker_dir: str             # "" = no fire-once marker
    spec: str                   # original spec text (for messages)

    def marker_path(self, rank: int) -> str:
        h = hashlib.sha1(self.spec.encode("utf-8")).hexdigest()[:10]
        return os.path.join(self.marker_dir,
                            f".fault_fired.{h}.rank{rank}")

    def maybe_fire(self, iteration: int) -> None:
        """Fire the fault if ``iteration`` matches and it has not fired
        before (per the marker file). ``kill`` does not return."""
        if int(iteration) != self.iteration:
            return
        rank = _current_rank()
        if self.rank is not None and rank != self.rank:
            return
        if self.marker_dir:
            mp = self.marker_path(rank)
            if os.path.exists(mp):
                log.debug(f"tpu_fault_inject: {self.spec!r} already "
                          f"fired (marker {mp}); skipping")
                return
            os.makedirs(self.marker_dir, exist_ok=True)
            with open(mp, "w") as f:
                f.write(self.spec + "\n")
        if self.kind == "kill":
            log.warning(f"tpu_fault_inject: killing process (rank "
                        f"{rank}) before iteration {self.iteration} "
                        f"({self.spec!r})")
            os.kill(os.getpid(), signal.SIGKILL)
        raise LightGBMError(
            f"tpu_fault_inject: injected failure before iteration "
            f"{self.iteration} ({self.spec!r})")


def parse_fault_spec(spec: str, marker_dir: str = "") -> FaultPlan:
    s = str(spec).strip()
    kind, _, rest = s.partition(":")
    kind = kind.strip().lower()
    if kind not in ("kill", "exn"):
        log.fatal(f"tpu_fault_inject: unknown fault kind {kind!r} in "
                  f"{spec!r} (expected 'kill:...' or 'exn:...')")
    fields = {}
    for tok in rest.split(","):
        tok = tok.strip()
        if not tok:
            continue
        k, _, v = tok.partition("=")
        k, v = k.strip(), v.strip()
        if k not in ("iter", "rank") or not v.lstrip("-").isdigit():
            log.fatal(f"tpu_fault_inject: cannot parse {tok!r} in "
                      f"{spec!r} (expected iter=<n> and optional "
                      f"rank=<n>)")
        fields[k] = int(v)
    if "iter" not in fields:
        log.fatal(f"tpu_fault_inject: {spec!r} needs an iter=<n> field")
    return FaultPlan(kind=kind, iteration=fields["iter"],
                     rank=fields.get("rank"),
                     marker_dir=str(marker_dir or ""), spec=s)


def fault_injection_callback(spec: str, marker_dir: str = "") -> Callable:
    """Before-iteration training callback wrapping a parsed fault plan
    (wired by ``engine.train`` when ``tpu_fault_inject`` is set)."""
    plan = parse_fault_spec(spec, marker_dir)

    def _callback(env) -> None:
        plan.maybe_fire(env.iteration)
    _callback.before_iteration = True
    _callback.order = -100          # fire before any real callback work
    _callback.fault_plan = plan
    return _callback
