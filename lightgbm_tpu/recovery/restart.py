"""Gang-restart policy helpers for the distributed launcher.

``parallel.launch.train_distributed`` owns the actual restart loop
(terminate the gang, pick a fresh coordinator port, resume every rank
from the newest valid rank-0 checkpoint); this module keeps the policy
pieces — exponential backoff with decorrelated jitter, bind-failure
classification for the coordinator-port race, and the "is there
anything to resume from" check — separately testable.
"""
from __future__ import annotations

__all__ = ["backoff_seconds", "is_bind_failure",
           "has_resumable_checkpoint"]

# substrings (lowercased) that identify a coordinator bind failure —
# the _free_port() race where the probed port is reclaimed between
# close() and jax.distributed's coordinator bind
_BIND_TOKENS = (
    "address already in use",
    "address in use",
    "failed to bind",
    "bind failed",
    "could not bind",
    "errno 98",           # EADDRINUSE
    "eaddrinuse",
)


def backoff_seconds(attempt: int, base: float = 1.0,
                    cap: float = 30.0, rng=None,
                    prev: float = 0.0) -> float:
    """Backoff for restart attempt N (1-based).

    Without ``rng``: plain exponential ``base * 2**(N-1)``, capped —
    deterministic, for single callers and tests.

    With ``rng`` (a ``random.Random``): DECORRELATED JITTER
    (``uniform(base, 3 * prev)``, capped; ``prev`` is the previous
    returned delay, ``base`` when first). N gang drivers (or N ranks
    each re-running the same call after a shared preemption) would
    otherwise sleep IDENTICAL exponential delays and stampede the
    coordinator port in lockstep on every attempt — the exact
    ``_free_port`` bind race the bind-retry counter exists to absorb;
    jitter spreads the relaunches so most attempts never collide at
    all. Deterministic for a seeded rng, so tests replay."""
    if attempt <= 0:
        return 0.0
    if rng is None:
        return float(min(cap, base * (2.0 ** (attempt - 1))))
    lo = min(base, cap)
    hi = max(lo, 3.0 * (prev if prev > 0.0 else base))
    return float(min(cap, rng.uniform(lo, hi)))


def is_bind_failure(err_text: str) -> bool:
    """True when a worker error payload looks like the coordinator
    failed to bind its port (retry on a fresh port, don't burn a
    restart attempt)."""
    low = str(err_text).lower()
    return any(tok in low for tok in _BIND_TOKENS)


def has_resumable_checkpoint(directory) -> bool:
    """True when ``directory`` holds at least one VALID rank-0
    checkpoint (the launcher's restart decision: resume vs from
    scratch)."""
    from .checkpoint import CheckpointManager
    try:
        mgr = CheckpointManager(directory, rank=0)
        return mgr.latest_valid_iteration() is not None
    except Exception:
        return False
