"""Durable training checkpoints: atomic writes, checksums, bounded
retention, and resume-time loading with corruption fallback.

File format (version 1, binary; see docs/robustness.md):

    LGBMTPU-CKPT-v1\\n
    sha256:<hex digest of the payload>\\n
    bytes:<payload byte count>\\n
    <pickled payload>

The payload is a pickled dict holding the complete training state —
model text, iteration counter, host RNG states (bagging / feature
fraction / DART drop), the exact device score arrays, early-stopping
best-score state — assembled by ``callback.checkpoint``.

Atomicity: the blob is written to a temp file in the same directory,
fsync'd, then ``os.replace``d into place, so a reader never observes a
half-written checkpoint under POSIX rename semantics. A kill mid-write
leaves at worst a stale ``.tmp.*`` file and the previous checkpoints
intact; a checkpoint truncated by any other means fails the length or
sha256 check at load time and the loader falls back to the previous
valid one.

Multi-process layout: every process writes its OWN per-rank file
(``ckpt_<iter>.rank<r>.ckpt``) because the exact score arrays are
row-shards local to each process; the rank-0 file is the source of
truth for restart decisions. A per-rank ``latest.rank<r>`` pointer file
names the newest checkpoint for quick lookup (the scan-based fallback
wins when the pointer is stale or its target is corrupt).
"""
from __future__ import annotations

import hashlib
import os
import pickle
import re
import tempfile
from typing import Any, Dict, List, Optional, Union

from .. import obs
from ..utils import log
from ..utils.log import LightGBMError

MAGIC = b"LGBMTPU-CKPT-v1"
CHECKPOINT_VERSION = 1
_FILE_RE = re.compile(r"^ckpt_(\d{8})\.rank(\d+)\.ckpt$")

__all__ = ["CheckpointError", "CheckpointManager", "load_for_resume",
           "latest_complete_iteration", "MAGIC", "CHECKPOINT_VERSION"]


class CheckpointError(LightGBMError):
    """A checkpoint file is missing, truncated, or corrupt."""


def _default_rank() -> int:
    try:
        import jax
        return int(jax.process_index())
    except Exception:
        return 0


class CheckpointManager:
    """Owns one checkpoint directory for one process rank."""

    def __init__(self, directory: Union[str, os.PathLike], keep_n: int = 3,
                 rank: Optional[int] = None):
        self.dir = str(directory)
        self.keep_n = max(1, int(keep_n))
        self.rank = _default_rank() if rank is None else int(rank)
        os.makedirs(self.dir, exist_ok=True)

    # -- naming ---------------------------------------------------------
    def filename(self, iteration: int, rank: Optional[int] = None) -> str:
        r = self.rank if rank is None else int(rank)
        return f"ckpt_{int(iteration):08d}.rank{r}.ckpt"

    def path(self, iteration: int, rank: Optional[int] = None) -> str:
        return os.path.join(self.dir, self.filename(iteration, rank))

    @property
    def latest_pointer(self) -> str:
        return os.path.join(self.dir, f"latest.rank{self.rank}")

    # -- write ----------------------------------------------------------
    def save(self, state: Dict[str, Any], iteration: int) -> str:
        """Atomically persist ``state`` as this rank's checkpoint for
        ``iteration``; updates the ``latest`` pointer and prunes old
        checkpoints beyond ``keep_n``."""
        with obs.span("checkpoint/save", iteration=int(iteration)):
            state = dict(state)
            state.setdefault("version", CHECKPOINT_VERSION)
            state.setdefault("iteration", int(iteration))
            payload = pickle.dumps(state, protocol=4)
            digest = hashlib.sha256(payload).hexdigest()
            blob = b"\n".join([
                MAGIC,
                b"sha256:" + digest.encode("ascii"),
                b"bytes:" + str(len(payload)).encode("ascii"),
                payload,
            ])
            final = self.path(iteration)
            self._atomic_write(final, blob)
            self._atomic_write(
                self.latest_pointer,
                self.filename(iteration).encode("ascii") + b"\n")
            self._prune(current=int(iteration))
        obs.inc("checkpoint.saves")
        obs.set_gauge("checkpoint.last_save_bytes", len(payload))
        log.debug(f"checkpoint saved: {final} "
                  f"({len(payload)} bytes, sha256 {digest[:12]}…)")
        return final

    def _atomic_write(self, final: str, blob: bytes) -> None:
        fd, tmp = tempfile.mkstemp(dir=self.dir, prefix=".tmp.",
                                   suffix=".part")
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(blob)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, final)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise

    def _prune(self, current: int) -> None:
        """Keep the newest ``keep_n`` iterations up to ``current``.
        Iterations ABOVE the one just saved can only be leftovers from
        a previous run in a reused directory — delete them too (they
        would otherwise win every resume/restart and silently continue
        the OLD run), and never let them push the just-written
        checkpoint out of the retention window."""
        its = self.iterations()
        stale = [it for it in its if it > current]
        if stale:
            log.warning(
                f"checkpoint dir {self.dir} held higher-iteration "
                f"checkpoints {stale} from a previous run; removing "
                f"them (rank {self.rank})")
        live = [it for it in its if it <= current]
        for it in stale + live[:-self.keep_n]:
            try:
                os.unlink(self.path(it))
            except OSError:
                pass

    def clear_rank_files(self) -> int:
        """Delete THIS rank's checkpoint files and latest pointer (a
        fresh, non-resuming run claiming a reused directory — stale
        checkpoints would otherwise be picked up by a later restart).
        Fault fire-once markers are left alone. Returns the count of
        removed checkpoints."""
        its = self.iterations()
        for it in its:
            try:
                os.unlink(self.path(it))
            except OSError:
                pass
        try:
            os.unlink(self.latest_pointer)
        except OSError:
            pass
        return len(its)

    # -- read -----------------------------------------------------------
    def iterations(self) -> List[int]:
        """Iterations with a checkpoint file for THIS rank, ascending
        (no validity check — see :meth:`latest_valid_iteration`)."""
        out = []
        try:
            names = os.listdir(self.dir)
        except OSError:
            return []
        for name in names:
            m = _FILE_RE.match(name)
            if m and int(m.group(2)) == self.rank:
                out.append(int(m.group(1)))
        return sorted(out)

    def load_file(self, path: str,
                  verify_only: bool = False) -> Optional[Dict[str, Any]]:
        """Read + verify one checkpoint file (magic, length, sha256,
        version); raises :class:`CheckpointError` on any mismatch.
        ``verify_only`` skips the (potentially large) unpickle and
        returns None — checkpoints carry full score arrays, so validity
        scans must not deserialize every candidate."""
        import time
        t0 = time.monotonic()
        try:
            with open(path, "rb") as f:
                blob = f.read()
        except OSError as e:
            raise CheckpointError(f"{path}: cannot read checkpoint: {e}")
        parts = blob.split(b"\n", 3)
        if len(parts) != 4 or parts[0] != MAGIC:
            raise CheckpointError(
                f"{path}: not a lightgbm-tpu checkpoint (bad magic)")
        digest = parts[1].partition(b":")[2].decode("ascii", "replace")
        try:
            nbytes = int(parts[2].partition(b":")[2])
        except ValueError:
            raise CheckpointError(f"{path}: corrupt header")
        payload = parts[3]
        if len(payload) != nbytes:
            raise CheckpointError(
                f"{path}: truncated (expected {nbytes} payload bytes, "
                f"found {len(payload)})")
        if hashlib.sha256(payload).hexdigest() != digest:
            raise CheckpointError(
                f"{path}: checksum mismatch (truncated or corrupt write)")
        if verify_only:
            return None
        try:
            state = pickle.loads(payload)
        except Exception as e:
            raise CheckpointError(f"{path}: cannot unpickle payload: {e}")
        if not isinstance(state, dict) \
                or int(state.get("version", -1)) != CHECKPOINT_VERSION:
            ver = state.get("version") if isinstance(state, dict) else "?"
            raise CheckpointError(
                f"{path}: unsupported checkpoint version {ver!r} "
                f"(this build reads version {CHECKPOINT_VERSION})")
        state["_checkpoint_path"] = path
        # read+verify+unpickle is the restore cost a restarting gang
        # pays per attempt — a trending restore duration is the early
        # signal that checkpoints outgrew their write/read budget
        obs.observe("checkpoint/restore", time.monotonic() - t0)
        obs.inc("checkpoint.restores")
        return state

    def load(self, iteration: Optional[int] = None) -> Dict[str, Any]:
        """Load a checkpoint for this rank. With ``iteration``: that
        exact one (no fallback). Without: the ``latest`` pointer first,
        then newest-to-oldest scan, skipping corrupt files with a
        warning."""
        if iteration is not None:
            return self.load_file(self.path(iteration))
        tried: List[str] = []
        try:
            with open(self.latest_pointer) as f:
                name = f.read().strip()
            if name and os.sep not in name and _FILE_RE.match(name):
                p = os.path.join(self.dir, name)
                tried.append(p)
                return self.load_file(p)
        except OSError:
            pass
        except CheckpointError as e:
            log.warning(f"checkpoint 'latest' pointer target is invalid "
                        f"({e}); scanning {self.dir} for the newest "
                        f"valid checkpoint")
        for it in reversed(self.iterations()):
            p = self.path(it)
            if p in tried:
                continue
            try:
                return self.load_file(p)
            except CheckpointError as e:
                log.warning(f"skipping invalid checkpoint: {e}; falling "
                            f"back to the previous one")
        raise CheckpointError(
            f"no valid checkpoint for rank {self.rank} in {self.dir}")

    def latest_valid_iteration(self) -> Optional[int]:
        """Newest iteration whose checkpoint verifies (checksum only —
        no unpickle), or None."""
        for it in reversed(self.iterations()):
            try:
                self.load_file(self.path(it), verify_only=True)
                return it
            except CheckpointError:
                continue
        return None


def latest_complete_iteration(
        directory: Union[str, os.PathLike]) -> Optional[int]:
    """Newest iteration at which EVERY rank file of the writing gang
    verifies — "valid under the new topology": the rank files present
    form a contiguous ``0..P-1`` set (P = however wide the WRITING
    gang was; the reader's width is irrelevant) and each passes the
    checksum scan. The elastic resume agreement uses this for ranks
    that have NO own-rank files (a gang relaunched wider than the
    writer) so elastic growth does not force every rank back to
    scratch. Caveat: the writing width is inferred from the files
    PRESENT, so an iteration whose highest-numbered rank file was
    never written still looks complete — load_for_resume's min fold
    over the old ranks' own-latest values clamps that overshoot (and
    rows a lost file leaves uncovered replay bit-exactly from the
    trees regardless). Returns None when no iteration is complete."""
    directory = str(directory)
    try:
        names = os.listdir(directory)
    except OSError:
        return None
    by_iter: Dict[int, List[int]] = {}
    for name in names:
        m = _FILE_RE.match(name)
        if m:
            by_iter.setdefault(int(m.group(1)), []).append(
                int(m.group(2)))
    mgr = CheckpointManager(directory, rank=0)
    for it in sorted(by_iter, reverse=True):
        ranks = sorted(set(by_iter[it]))
        if ranks != list(range(len(ranks))):
            continue        # a gap means some old rank's file is gone
        try:
            for r in ranks:
                mgr.load_file(mgr.path(it, rank=r), verify_only=True)
        except CheckpointError:
            continue
        return it
    return None


def clear_checkpoint_dir(directory: Union[str, os.PathLike]) -> int:
    """Remove EVERY rank's checkpoint files and latest pointers from
    ``directory`` (driver-side fresh-run hygiene — worker-side clearing
    can be skipped when a gang dies before reaching it, and a later
    restart would then adopt the stale run). Fault fire-once markers
    are left alone. Returns the count of removed checkpoints."""
    directory = str(directory)
    removed = 0
    try:
        names = os.listdir(directory)
    except OSError:
        return 0
    for name in names:
        if _FILE_RE.match(name) or name.startswith("latest.rank"):
            try:
                os.unlink(os.path.join(directory, name))
                removed += bool(_FILE_RE.match(name))
            except OSError:
                pass
    return removed


def load_for_resume(path: Union[str, os.PathLike],
                    keep_n: int = 3) -> Optional[Dict[str, Any]]:
    """Resolve ``lgb.train(resume_from=...)``: a checkpoint FILE loads
    directly (raising on corruption — the user named it explicitly); a
    DIRECTORY loads the newest valid checkpoint for this process's
    rank, or None when the directory holds no valid checkpoint yet
    (fresh start).

    Multi-process: ranks agree on one iteration by all-gathering each
    rank's newest valid iteration and resuming from the MINIMUM, so a
    rank whose newest write was interrupted cannot desync the gang. If
    any rank has no valid checkpoint, every rank starts fresh together.
    """
    path = str(path)
    if os.path.isfile(path):
        mgr = CheckpointManager(os.path.dirname(path) or ".",
                                keep_n=keep_n)
        return mgr.load_file(path)
    if not os.path.isdir(path) and (
            _FILE_RE.match(os.path.basename(path))
            or path.endswith(".ckpt")):
        # a nonexistent path that LOOKS like a checkpoint file is a
        # typo the user must hear about — silently training from
        # scratch (and creating a junk directory named like a file)
        # would discard the run they asked to continue. A nonexistent
        # DIRECTORY stays a valid fresh start (and must still join the
        # multi-rank agreement gather below).
        raise CheckpointError(f"{path}: checkpoint file does not exist")
    mgr = CheckpointManager(path, keep_n=keep_n)
    try:
        import jax
        nproc = int(jax.process_count())
    except Exception:
        nproc = 1
    if nproc > 1:
        import numpy as np
        from jax.experimental import multihost_utils

        def _gather(value: int) -> "np.ndarray":
            mine = np.asarray([value], np.int64)
            return np.asarray(
                multihost_utils.process_allgather(mine)).reshape(-1)

        # topology-aware agreement: ranks WITH their own files keep
        # the proven min-over-own-latest semantics (correct on shared
        # AND per-host checkpoint dirs, and the min already walks past
        # an iteration a crashed trailing rank never finished
        # writing); a rank with NO own files — a gang relaunched
        # WIDER than the writer — contributes the newest
        # topology-complete iteration from the (necessarily shared)
        # directory instead of -1, so elastic growth no longer forces
        # every rank back to scratch. The min fold also clamps any
        # overshoot in the completeness scan's width inference (it
        # cannot see a trailing rank file that was never written; an
        # old rank's own-latest can, and wins the min).
        latest = mgr.latest_valid_iteration()
        if latest is None:
            comp = latest_complete_iteration(path)
            latest = comp if comp is not None else None
        gathered = _gather(latest if latest is not None else -1)
        target = int(gathered.min())
        if target < 0:
            if latest is not None:
                log.warning(
                    "resume: some ranks have no valid checkpoint in "
                    f"{path}; all ranks restart from scratch to stay "
                    f"consistent")
            return None
        if latest is not None and target != latest:
            log.warning(f"resume: ranks disagree on the newest valid "
                        f"checkpoint ({sorted(set(gathered.tolist()))}); "
                        f"resuming all ranks from iteration {target}")
        # two-phase agreement: a rank may have already PRUNED (or hold
        # a corrupt copy of) the agreed older iteration; loading must
        # succeed on EVERY rank or no rank may resume, else the gang
        # desyncs (and a crash here would repeat on every restart)
        try:
            try:
                state = mgr.load(iteration=target)
            except CheckpointError:
                if mgr.rank == 0:
                    raise
                # a gang WIDER than the writer: ranks beyond the old
                # width have no own-rank file — adopt rank 0's state
                # (trees/RNG are rank-identical; the streaming
                # engine's elastic import re-cuts the scores and
                # reads sibling rank files itself)
                log.warning(
                    f"resume: rank {mgr.rank} has no valid own "
                    f"checkpoint at the agreed iteration {target}; "
                    f"adopting rank 0's state for an elastic re-cut")
                state = CheckpointManager(path, keep_n=keep_n,
                                          rank=0).load(
                                              iteration=target)
            ok = 1
        except CheckpointError as e:
            log.warning(f"resume: cannot load the gang-agreed "
                        f"checkpoint iteration {target} ({e})")
            state, ok = None, 0
        if int(_gather(ok).min()) == 0:
            log.warning(
                "resume: not every rank could load the agreed "
                f"checkpoint iteration {target}; all ranks restart "
                f"from scratch to stay consistent")
            return None
        return state
    # single process: one pass — newest valid checkpoint with
    # corruption fallback, None when the directory holds nothing valid
    try:
        return mgr.load()
    except CheckpointError:
        return None
