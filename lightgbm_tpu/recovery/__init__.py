"""Fault-tolerant training: durable checkpoints, gang restart, fault
injection.

The production training-stack answer to worker death / OOM / preemption
(the reference's socket-collective reconnect story, SURVEY.md
§distributed — UNVERIFIED): periodically persist *complete* training
state to disk (``checkpoint``), restart the worker gang from the newest
valid checkpoint on failure (``restart``, wired into
``parallel.launch.train_distributed``), and exercise the whole loop in
CI by killing live workers mid-training (``faults``). See
docs/robustness.md for the file format, atomicity guarantees, and
restart semantics.
"""
from .checkpoint import CheckpointError, CheckpointManager, load_for_resume
from .faults import FaultPlan, fault_injection_callback, parse_fault_spec
from .restart import backoff_seconds, has_resumable_checkpoint, is_bind_failure

__all__ = [
    "CheckpointError", "CheckpointManager", "load_for_resume",
    "FaultPlan", "fault_injection_callback", "parse_fault_spec",
    "backoff_seconds", "has_resumable_checkpoint", "is_bind_failure",
]
