"""``python -m lightgbm_tpu config=train.conf`` — the CLI entry point
(reference: src/main.cpp lightgbm executable)."""
from .app import main

main()
