"""Training entry points: ``train()`` and ``cv()``.

Reference: python-package/lightgbm/engine.py (UNVERIFIED — empty mount,
see SURVEY.md banner): the callback loop around Booster.update, valid-set
registration, early stopping via EarlyStopException, CV fold construction
(group-aware for ranking) and aggregated eval.
"""
from __future__ import annotations

import copy
from typing import Any, Callable, Dict, List, Optional, Union

import numpy as np

from . import callback as callback_mod
from . import capabilities, obs
from .basic import Booster, Dataset
from .config import Config
from .utils import log

__all__ = ["train", "cv", "CVBooster"]


def _resolve_num_boost_round(params: Dict[str, Any],
                             num_boost_round: int) -> int:
    cfg_alias = Config.canonical_name
    for key in list(params):
        if cfg_alias(key) == "num_iterations":
            return int(params.pop(key))
    return num_boost_round


def train(params: Dict[str, Any], train_set: Dataset,
          num_boost_round: int = 100,
          valid_sets: Optional[List[Dataset]] = None,
          valid_names: Optional[List[str]] = None,
          feval: Optional[Callable] = None,
          init_model: Optional[Union[str, Booster]] = None,
          keep_training_booster: bool = False,
          callbacks: Optional[List[Callable]] = None,
          fobj: Optional[Callable] = None,
          resume_from: Optional[str] = None) -> Booster:
    """Train a model (mirrors lightgbm.train).

    ``resume_from``: a checkpoint directory (or file) written by the
    ``checkpoint_dir``/``checkpoint_interval`` params or
    ``callback.checkpoint``. Restores the COMPLETE training state —
    model, RNG streams, exact scores, early-stopping state — and runs
    the REMAINING iterations up to ``num_boost_round`` (a total-round
    target, unlike ``init_model`` which always adds ``num_boost_round``
    more). An interrupted-then-resumed run is bit-exact vs an
    uninterrupted one (docs/robustness.md). A directory with no valid
    checkpoint yet starts fresh — so restart loops can pass it
    unconditionally.
    """
    params = copy.deepcopy(params)
    num_boost_round = _resolve_num_boost_round(params, num_boost_round)
    cfg = Config(params)
    if callable(params.get("objective")):
        fobj = params["objective"]
        params["objective"] = "custom"
        cfg = Config(params)

    resume_state = None
    if resume_from is not None:
        from .recovery.checkpoint import load_for_resume
        resume_state = load_for_resume(resume_from)
        if resume_state is None:
            log.info(f"resume_from={str(resume_from)!r}: no valid "
                     f"checkpoint yet; starting fresh")
        elif init_model is not None:
            log.warning("resume_from and init_model were both given; "
                        "resume_from wins (the checkpoint carries its "
                        "own model)")
            init_model = None

    # training continuation (gbdt.cpp: load existing models, rebuild
    # scores, keep boosting): accept a file path, Booster, or HostModel.
    # A checkpoint resume does NOT go through init_forest: the engine is
    # constructed fresh (identical to the original run's construction)
    # and import_train_state adopts the checkpoint's exact pickled
    # trees + scores + RNG streams afterwards.
    init_forest = None
    if init_model is not None and resume_state is None:
        import os
        if isinstance(init_model, Booster):
            init_forest = (init_model._from_model
                           if init_model._from_model is not None
                           else init_model._to_host_model())
        elif isinstance(init_model, (str, os.PathLike)):
            from .io.model_text import load_model_string
            with open(init_model) as f:
                init_forest = load_model_string(f.read())
        else:
            init_forest = init_model

    with obs.span("train/setup"):
        booster = Booster(params=params, train_set=train_set,
                          init_forest=init_forest)
    if valid_sets:
        valid_names = valid_names or [f"valid_{i}"
                                      for i in range(len(valid_sets))]
        for vs, name in zip(valid_sets, valid_names):
            if vs is train_set:
                continue  # the train set is evaluated via eval_train
            booster.add_valid(vs, name)

    callbacks = list(callbacks or [])
    if cfg.early_stopping_round and cfg.early_stopping_round > 0:
        callbacks.append(callback_mod.early_stopping(
            cfg.early_stopping_round, cfg.first_metric_only,
            verbose=cfg.verbosity >= 1,
            min_delta=cfg.early_stopping_min_delta))
    if cfg.checkpoint_dir and cfg.checkpoint_interval > 0:
        ckpt_cb = callback_mod.checkpoint(
            cfg.checkpoint_dir, interval=cfg.checkpoint_interval,
            keep_n=cfg.checkpoint_keep)
        if resume_state is None:
            # fresh run claiming this directory: stale checkpoints from
            # a previous run would otherwise be adopted by a later
            # restart/resume and silently continue the OLD run
            cleared = ckpt_cb.checkpoint_manager.clear_rank_files()
            if cleared:
                log.warning(
                    f"checkpoint_dir {cfg.checkpoint_dir} held "
                    f"{cleared} checkpoint(s) from a previous run; "
                    f"cleared for this fresh run")
        callbacks.append(ckpt_cb)
    if str(cfg.tpu_fault_inject).strip():
        import os as _os

        from .recovery.faults import (_current_rank, clear_fault_markers,
                                      fault_injection_callback)
        marker_dir = cfg.tpu_fault_marker or cfg.checkpoint_dir
        if resume_from is None and marker_dir \
                and not _os.environ.get("LGBM_TPU_GANG_RELAUNCH"):
            # fresh (non-resume) run claiming the marker dir clears
            # THIS rank's stale fire-once markers (mirrors the
            # checkpoint clear_rank_files above) — yesterday's marker
            # must not suppress today's injected fault. Gated on the
            # resume_from ARGUMENT (the user's intent), not on whether
            # a valid checkpoint exists yet: a supervisor re-running
            # train(resume_from=dir) after a fault that fired BEFORE
            # the first checkpoint gets resume_state None, and clearing
            # then would delete the marker the dying attempt just wrote
            # — an infinite kill loop. Gang RELAUNCHES are exempt too
            # (LGBM_TPU_GANG_RELAUNCH, set by the launcher, which owns
            # marker hygiene driver-side)
            cleared = clear_fault_markers(marker_dir,
                                          rank=_current_rank())
            if cleared:
                log.warning(
                    f"tpu_fault_inject: cleared {cleared} stale "
                    f"fire-once marker(s) from {marker_dir} for this "
                    f"fresh run")
        callbacks.append(fault_injection_callback(
            cfg.tpu_fault_inject, marker_dir=marker_dir,
            ckpt_dir=cfg.checkpoint_dir))

    # launcher watchdog liveness: stamp a per-rank heartbeat FILE the
    # driver can see (obs gauges are process-local); created on the
    # first round's stamp so startup compiles don't read as stale
    hb_dir = str(getattr(cfg, "tpu_heartbeat_dir", "") or "").strip()
    if hb_dir:
        import os as _os

        from .recovery.faults import _current_rank
        obs.set_heartbeat_file(
            "train",
            _os.path.join(hb_dir,
                          f"heartbeat.train.rank{_current_rank()}"))

    start_iter = 0
    if resume_state is not None:
        eng = booster.engine
        if not hasattr(eng, "import_train_state"):
            log.fatal(f"resume_from is not supported by the "
                      f"{type(eng).__name__} engine")
        eng_state = resume_state["engine"]
        ckpt_path = resume_state.get("_checkpoint_path")
        if isinstance(eng_state, dict) and ckpt_path:
            # elastic resume (boosting/streaming.py _import_recut): a
            # topology-changed import may need sibling old ranks'
            # checkpoint files from the same directory
            import os as _os
            eng_state.setdefault("_checkpoint_dir",
                                 _os.path.dirname(str(ckpt_path)))
        eng.import_train_state(eng_state)
        bstate = resume_state.get("booster") or {}
        booster.best_iteration = int(bstate.get("best_iteration", -1))
        booster.best_score = {k: dict(v) for k, v in
                              (bstate.get("best_score") or {}).items()}
        cb_states = resume_state.get("callbacks") or {}
        for cb in callbacks:
            key = getattr(cb, "state_key", None)
            if key and key in cb_states and hasattr(cb, "set_state"):
                cb.set_state(cb_states[key])
        start_iter = eng.iter_
        # metrics survive checkpoint/restore: adopt the interrupted
        # run's registry state, then count this resume (the restart
        # counter keeps incrementing across resume_from cycles). Only
        # when the metrics pillar is on — a resume with tpu_metrics off
        # must leave the registry as empty as any other disabled run.
        # The checkpoint LOAD just above already recorded this
        # process's restore count/duration; the saved state predates
        # that restore, so fold the live values back on top of it
        # (count==1 on this path makes the histogram re-observe exact)
        if obs.enabled():
            saved = resume_state.get("obs") or {}
            saved_names = {m.get("name")
                           for m in saved.get("metrics", [])}
            live_restores = obs.registry().get("checkpoint.restores")
            live_restores = getattr(live_restores, "value", 0.0)
            live_hist = obs.registry().get("checkpoint/restore")
            live_obs = ([live_hist.sum / live_hist.count]
                        * live_hist.count if live_hist else [])
            obs.import_state(saved)
            # fold back ONLY what the import actually overwrote — a
            # first resume's saved state lacks these metrics, so the
            # live values survived the import untouched
            if "checkpoint.restores" in saved_names and live_restores:
                obs.counter("checkpoint.restores").inc(live_restores)
            if "checkpoint/restore" in saved_names:
                for dur in live_obs:
                    obs.registry().histogram("checkpoint/restore") \
                        .observe(dur)
        obs.inc("train.resumes", force=True)
        # heartbeats resume from LIVE stamping, never from the saved
        # state (export_state excludes them): mark the training loop
        # alive the moment the resume is adopted so /readyz flips
        # before the first post-resume round completes
        obs.heartbeat("train")
        log.info(f"resumed training from checkpoint "
                 f"{resume_state.get('_checkpoint_path', '?')} at "
                 f"iteration {start_iter}")
        if start_iter >= num_boost_round:
            log.warning(f"checkpoint is already at iteration "
                        f"{start_iter} >= num_boost_round "
                        f"{num_boost_round}; nothing left to train")
    # hand the checkpoint callback the full callback list so it can
    # snapshot peers' state (early stopping) into each checkpoint
    for cb in callbacks:
        if hasattr(cb, "bind_callbacks"):
            cb.bind_callbacks(callbacks)

    callbacks_before = [cb for cb in callbacks
                        if getattr(cb, "before_iteration", False)]
    callbacks_after = [cb for cb in callbacks
                       if not getattr(cb, "before_iteration", False)]
    callbacks_before.sort(key=lambda cb: getattr(cb, "order", 0))
    callbacks_after.sort(key=lambda cb: getattr(cb, "order", 0))

    train_as_valid = valid_sets and any(vs is train_set
                                        for vs in valid_sets)

    # optional jax.profiler trace around the whole boosting run
    # (tpu_profile_dir; SURVEY.md §5 tracing subsystem)
    import contextlib
    with contextlib.ExitStack() as _prof_stack:
        # registered FIRST so it runs on EVERY exit — including a
        # raising iteration: a crashed run must still write the metrics
        # snapshot / Chrome trace the config asked for (those artifacts
        # matter MOST on the runs that die)
        _prof_stack.callback(_finish_train_obs, cfg)
        if cfg.tpu_profile_dir:
            import jax
            # registered BEFORE stop_trace (LIFO: stop runs first) so
            # the attribution reads the freshly written dump and its
            # train.copy_share / train.wall_busy_gap_ms gauges land in
            # the snapshot _finish_train_obs flushes afterwards
            _it0 = booster.current_iteration()
            _prof_stack.callback(
                lambda: _attr_profile_obs(cfg, booster, _it0))
            jax.profiler.start_trace(cfg.tpu_profile_dir)
            _prof_stack.callback(jax.profiler.stop_trace)
        # fused fast path: with no per-iteration host work (callbacks, eval,
        # custom fobj), run the whole training as chunked device dispatches —
        # identical models, one dispatch per tpu_fuse_iters iterations
        if (not callbacks_before and not callbacks_after and not valid_sets
                and not cfg.is_provide_training_metric and fobj is None
                and cfg.tpu_fuse_iters > 1 and cfg.snapshot_freq <= 0
                and booster.engine.can_fuse_iters()):
            with obs.span("train/fused",
                          rounds=num_boost_round - start_iter):
                booster.engine.train_chunk(num_boost_round - start_iter)
            booster.best_iteration = booster.current_iteration()
            # clean completion: an absent heartbeat is "finished", a
            # stale one is "wedged/crashed" — /healthz tells them apart
            obs.retire_heartbeat("train")
            return booster

        for it in range(start_iter, num_boost_round):
            env_pre = callback_mod.CallbackEnv(
                model=booster, params=params, iteration=it,
                begin_iteration=0, end_iteration=num_boost_round,
                evaluation_result_list=None)
            for cb in callbacks_before:
                cb(env_pre)
            with obs.span("train/round", round=it):
                with obs.span("train/update"):
                    booster.update(fobj=fobj)
                # liveness stamp: the STREAMING engine has no in-loop
                # stamp of its own (the resident engine's
                # train_one_iter/train_chunk stamp too — a second
                # gauge set per round is noise-free overlap, and each
                # layer uniquely covers a path: this one streaming,
                # the engine-level ones hand-rolled update() loops)
                obs.heartbeat("train")
                if cfg.snapshot_freq > 0 \
                        and (it + 1) % cfg.snapshot_freq == 0:
                    # mid-training checkpoint (Application snapshot_freq
                    # semantics)
                    booster.save_model(
                        f"{cfg.output_model}.snapshot_iter_{it + 1}")

                eval_results = []
                should_eval = ((booster.engine.valid_data or train_as_valid
                                or cfg.is_provide_training_metric)
                               and (it + 1) % cfg.metric_freq == 0)
                if should_eval:
                    with obs.span("train/eval"):
                        if cfg.is_provide_training_metric or train_as_valid:
                            eval_results.extend(booster.eval_train(feval))
                        eval_results.extend(booster.eval_valid(feval))
                env = callback_mod.CallbackEnv(
                    model=booster, params=params, iteration=it,
                    begin_iteration=0, end_iteration=num_boost_round,
                    evaluation_result_list=eval_results)
                try:
                    for cb in callbacks_after:
                        cb(env)
                except callback_mod.EarlyStopException as e:
                    booster.best_iteration = e.best_iteration + 1
                    for name, metric, value, _ in (e.best_score or []):
                        booster.best_score.setdefault(name, {})[metric] \
                            = value
                    break
        if booster.best_iteration < 0:
            booster.best_iteration = booster.current_iteration()
        obs.retire_heartbeat("train")
        return booster


def _attr_profile_obs(cfg: Config, booster: "Booster",
                      start_iter: int) -> None:
    """Attribute the just-stopped ``tpu_profile_dir`` trace into the
    ``train.copy_share`` / ``train.wall_busy_gap_ms`` gauges
    (obs/trace_attr.py). Telemetry only — never fails the run."""
    from .obs.trace_attr import profile_gauges
    iters = max(booster.current_iteration() - start_iter, 0)
    profile_gauges(cfg.tpu_profile_dir, iters=iters or None)


def _finish_train_obs(cfg: Config) -> None:
    """End-of-training observability housekeeping: debug-log the span
    totals (the old timer-table behavior) and write the exports the
    config asked for (JSONL metrics snapshot, Chrome trace)."""
    from .utils.timer import log_timers
    log_timers()
    obs.flush_from_config(cfg)


class CVBooster:
    """Container of per-fold boosters (mirrors lightgbm.CVBooster)."""

    def __init__(self, boosters: Optional[List[Booster]] = None):
        self.boosters = list(boosters or [])
        self.best_iteration = -1

    def append(self, booster: Booster) -> None:
        self.boosters.append(booster)

    def __getattr__(self, name: str):
        def handler(*args, **kwargs):
            return [getattr(b, name)(*args, **kwargs)
                    for b in self.boosters]
        return handler


def _make_folds(full_data: Dataset, nfold: int, stratified: bool,
                shuffle: bool, seed: int):
    full_data.construct()
    n = full_data.num_data
    qb = full_data.metadata.query_boundaries
    rng = np.random.default_rng(seed)
    if qb is not None:
        # group-aware folds: split whole queries
        nq = len(qb) - 1
        q_idx = rng.permutation(nq) if shuffle else np.arange(nq)
        for k in range(nfold):
            test_q = q_idx[k::nfold]
            test_rows = np.concatenate(
                [np.arange(qb[q], qb[q + 1]) for q in test_q]) \
                if len(test_q) else np.array([], dtype=np.int64)
            mask = np.zeros(n, dtype=bool)
            mask[test_rows] = True
            yield np.flatnonzero(~mask), np.flatnonzero(mask)
        return
    label = full_data.metadata.label
    if stratified and label is not None:
        order = []
        for cls in np.unique(label):
            idx = np.flatnonzero(label == cls)
            if shuffle:
                idx = rng.permutation(idx)
            order.append(idx)
        interleaved = np.concatenate(order)
        folds = [interleaved[k::nfold] for k in range(nfold)]
    else:
        idx = rng.permutation(n) if shuffle else np.arange(n)
        folds = [idx[k::nfold] for k in range(nfold)]
    for k in range(nfold):
        mask = np.zeros(n, dtype=bool)
        mask[folds[k]] = True
        yield np.flatnonzero(~mask), np.flatnonzero(mask)


def cv(params: Dict[str, Any], train_set: Dataset,
       num_boost_round: int = 100, folds=None, nfold: int = 5,
       stratified: bool = True, shuffle: bool = True,
       metrics: Optional[Union[str, List[str]]] = None,
       feval: Optional[Callable] = None, seed: int = 0,
       callbacks: Optional[List[Callable]] = None,
       eval_train_metric: bool = False,
       return_cvbooster: bool = False) -> Dict[str, List[float]]:
    """K-fold cross-validation (mirrors lightgbm.cv)."""
    params = copy.deepcopy(params)
    num_boost_round = _resolve_num_boost_round(params, num_boost_round)
    if metrics is not None:
        params["metric"] = metrics
    cfg = Config(params)
    if cfg.objective not in capabilities.STRATIFIABLE_OBJECTIVES:
        stratified = False
    train_set.construct()

    if folds is not None:
        fold_iter = list(folds)
    else:
        fold_iter = list(_make_folds(train_set, nfold, stratified, shuffle,
                                     seed))

    cvbooster = CVBooster()
    fold_valid = []
    for train_idx, test_idx in fold_iter:
        dtrain = train_set.subset(train_idx)
        dtest = train_set.subset(test_idx)
        bst = Booster(params=params, train_set=dtrain)
        bst.add_valid(dtest, "valid")
        cvbooster.append(bst)
        fold_valid.append(dtest)

    callbacks = list(callbacks or [])
    if cfg.early_stopping_round and cfg.early_stopping_round > 0:
        callbacks.append(callback_mod.early_stopping(
            cfg.early_stopping_round, cfg.first_metric_only,
            verbose=cfg.verbosity >= 1))
    callbacks.sort(key=lambda cb: getattr(cb, "order", 0))

    results: Dict[str, List[float]] = {}
    for it in range(num_boost_round):
        per_metric: Dict[str, List[float]] = {}
        for bst in cvbooster.boosters:
            bst.update()
            for name, metric, value, hb in bst.eval_valid(feval):
                per_metric.setdefault((metric, hb), []).append(value)
        agg = []
        for (metric, hb), values in per_metric.items():
            mean, std = float(np.mean(values)), float(np.std(values))
            results.setdefault(f"valid {metric}-mean", []).append(mean)
            results.setdefault(f"valid {metric}-stdv", []).append(std)
            agg.append(("cv_agg", metric, mean, hb))
        env = callback_mod.CallbackEnv(
            model=cvbooster, params=params, iteration=it,
            begin_iteration=0, end_iteration=num_boost_round,
            evaluation_result_list=agg)
        try:
            for cb in callbacks:
                cb(env)
        except callback_mod.EarlyStopException as e:
            cvbooster.best_iteration = e.best_iteration + 1
            for key in results:
                results[key] = results[key][:cvbooster.best_iteration]
            break
    if return_cvbooster:
        results["cvbooster"] = cvbooster
    return results
