"""Leaf-ordered device row partition (tpu_hist_partition).

Reference: ``CUDADataPartition`` / ``CUDALeafSplits``
(src/treelearner/cuda/cuda_data_partition.cu, UNVERIFIED — empty mount,
see SURVEY.md banner): the reference keeps each leaf's row indices
CONTIGUOUS, so constructing the smaller child's histogram scans only
that child's rows and the sibling comes free by subtraction. Our masked
formulation scans all n rows per round; this module supplies the
structural "fewer rows" lever the round-5 trace attribution named
(docs/perf.md "Partitioned histograms").

Design (all fixed-shape, jit/while_loop/shard_map-safe):

- The binned matrix + value channels + a per-POSITION leaf-id vector
  are carried REORDERED so every leaf occupies one contiguous span,
  described by per-leaf ``(offset, count)`` tables.
- After each split batch the rows of the just-split leaves two-way
  partition in ONE stable global move: rows that route to a right
  child go (stably) to the back of the array, everything else packs
  (stably) to the front. One global ``cumsum`` of the "moved" mask
  yields every row's destination — and because rows of one leaf always
  share a key, the move preserves per-leaf contiguity AND within-leaf
  source order (the stability the tests pin).
- Offsets/counts update from the same prefix sums, gathered at the
  (few) leaf boundaries — no per-row gathers.
- On TPU the move itself rides the ``compact_rows`` block machinery
  (ops/compact.py): two compaction passes (front keys, back keys), the
  back buffer rolled to its start position, one ``where`` blend. Off
  TPU a computed-index scatter is cheap and exact.
- Each growth round then histograms only the K smaller children's
  spans: a ``lax.switch`` over a static pow2 ladder of span budgets
  keeps every shape static and the compile footprint bounded (the same
  trick as predict's batch-shape bucketing); rounds whose largest
  elected child would make ``K * budget >= n`` take a full masked-scan
  fallback branch instead (the span path can never scan MORE rows than
  the masked formulation). Rows sliced from a neighbouring leaf inside
  a span are sentinel-masked, so each row contributes exactly once.

Bit-exactness: the span histogram sums exactly the same per-row terms
as the masked scan, in a different accumulation order — EXACT under
quantized gradients (integer sums are order-free; the flagship config),
float-accumulation-order-close otherwise, mirroring the GOSS
compaction contract (tests pin model-text equality under quantized and
closeness under f32).
"""
from __future__ import annotations

from typing import List, Tuple

import jax
import jax.numpy as jnp

i32 = jnp.int32


def plan_split_move(moved: jax.Array
                    ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Stable front/back destinations for one split batch.

    Args:
      moved: ``[n]`` bool — True for rows that route to a RIGHT child
        this round (their leaf id changed).

    Returns:
      (dest ``[n]`` int32 destination positions — a permutation,
      n_front int32 scalar — first back-region position,
      cum ``[n]`` int32 — inclusive prefix counts of ``moved``).
    """
    n = moved.shape[0]
    mi = moved.astype(i32)
    cum = jnp.cumsum(mi)
    exc = cum - mi                       # moved rows strictly before i
    n_front = n - cum[-1]
    iota = jnp.arange(n, dtype=i32)
    dest = jnp.where(moved, n_front + exc, iota - exc)
    return dest, n_front, cum


def prefix_at(cum: jax.Array, pos: jax.Array) -> jax.Array:
    """``# moved rows strictly before position pos`` for positions in
    ``[0, n]`` (a tiny gather — O(#leaves), not O(n))."""
    cum_p = jnp.concatenate([jnp.zeros(1, i32), cum])
    return cum_p[jnp.clip(pos, 0, cum.shape[0])]


def update_tables(off: jax.Array, cnt: jax.Array, cum: jax.Array,
                  n_front: jax.Array, parents: jax.Array,
                  new_ids: jax.Array, valid: jax.Array
                  ) -> Tuple[jax.Array, jax.Array]:
    """New per-leaf (offset, count) tables after ``plan_split_move``.

    Every non-right-child leaf (untouched leaves, left children — which
    keep the parent's slot) shifts left by the number of moved rows
    before its old offset; right children land in the back region in
    parent-position order.

    Args:
      off / cnt: ``[L+1]`` old tables (slot L = trash).
      cum: inclusive moved-prefix from ``plan_split_move``.
      parents: ``[K]`` split leaf slots (trash slot for invalid lanes).
      new_ids: ``[K]`` right-child slots (trash slot for invalid lanes).
      valid: ``[K]`` bool lane validity.
    """
    s_all = prefix_at(cum, off)                        # [L+1]
    new_off = off - s_all
    s_par = prefix_at(cum, off[parents])               # [K]
    e_par = prefix_at(cum, off[parents] + cnt[parents])
    n_right = jnp.where(valid, e_par - s_par, 0)
    new_off = new_off.at[new_ids].set(n_front + s_par)
    new_cnt = cnt.at[parents].add(-n_right)
    new_cnt = new_cnt.at[new_ids].set(n_right)
    return new_off, new_cnt


def move_rows_xla(arrays: List[jax.Array], dest: jax.Array,
                  axis: int = 0) -> List[jax.Array]:
    """Apply the permutation by computed-index scatter (exact for any
    dtype). Cheap off-TPU; ON TPU computed scatters serialize
    (docs/perf.md) — use :func:`move_cols_tpu` there."""
    out = []
    for a in arrays:
        if axis == 0:
            out.append(jnp.zeros_like(a).at[dest].set(a))
        else:
            out.append(jnp.zeros_like(a).at[:, dest].set(a))
    return out


def move_cols_tpu(bins_fm: jax.Array, vals_fm: jax.Array,
                  moved: jax.Array, n_front: jax.Array,
                  rows_per_block: int
                  ) -> Tuple[jax.Array, jax.Array]:
    """The same stable front/back move via TWO ``compact_rows`` kernel
    passes (ops/compact.py): pass 1 packs the not-moved columns exactly
    to the front, pass 2 packs the moved columns, which are then rolled
    to start at ``n_front`` and blended in. Value channels move
    bit-exactly (the kernel's bf16x3 significand split), so an integer
    channel (e.g. leaf ids) round-trips exactly through float32.

    Args:
      bins_fm: ``[F, n]`` int8 feature-major binned matrix.
      vals_fm: ``[C, n]`` float32 channel-major values.
      moved / n_front: from ``plan_split_move``.
      rows_per_block: compaction block size (<= 1024, divides n).
    """
    from .compact import (compact_rows, compaction_out_cols,
                          plan_compaction)
    n = bins_fm.shape[1]
    out_cols = compaction_out_cols(n, rows_per_block, rows_per_block)
    keep_front = ~moved
    d1, a1, r1 = plan_compaction(keep_front, rows_per_block, out_cols)
    fb, fv = compact_rows(bins_fm, vals_fm, d1, a1, r1,
                          out_cols=out_cols,
                          rows_per_block=rows_per_block)
    d2, a2, r2 = plan_compaction(moved, rows_per_block, out_cols)
    bb, bv = compact_rows(bins_fm, vals_fm, d2, a2, r2,
                          out_cols=out_cols,
                          rows_per_block=rows_per_block)
    sel = (jnp.arange(n, dtype=i32) < n_front)[None, :]
    bb_r = jnp.roll(bb[:, :n], n_front, axis=1)
    bv_r = jnp.roll(bv[:, :n], n_front, axis=1)
    return (jnp.where(sel, fb[:, :n], bb_r),
            jnp.where(sel, fv[:, :n], bv_r))


def span_budgets(n_rows: int, n_spans: int, min_budget: int = 256
                 ) -> Tuple[int, ...]:
    """Static pow2 span-budget ladder for the ``lax.switch``: budgets S
    with ``n_spans * S < n_rows`` (a span round never scans more rows
    than the masked full scan it replaces — the caller's final branch).
    The ladder is O(log n) entries, so the compile footprint stays
    bounded exactly like predict's pow2 batch buckets."""
    budgets = []
    s = min_budget
    while s < n_rows and n_spans * s < n_rows:
        budgets.append(s)
        s *= 2
    return tuple(budgets)


def slice_spans(bins_p: jax.Array, vals_p: jax.Array, leaf_p: jax.Array,
                offs: jax.Array, cnts: jax.Array, budget: int,
                feature_major: bool
                ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Assemble the K children's padded row spans into one histogram
    input: K static-width ``dynamic_slice``s (starts clamped into
    range), concatenated along the row axis. Positions inside a span
    that belong to a NEIGHBOURING leaf (the pow2 padding) get leaf id
    -1, so they match no histogram lane — each row of each elected
    child contributes exactly once, and only to its own lane.
    """
    n = leaf_p.shape[0]
    K = int(offs.shape[0])
    S = int(budget)
    starts = jnp.clip(offs, 0, n - S)
    rel = jnp.arange(S, dtype=i32)
    bs, vs, ls = [], [], []
    for k in range(K):
        st = starts[k]
        if feature_major:
            bk = jax.lax.dynamic_slice(
                bins_p, (i32(0), st), (bins_p.shape[0], S))
            vk = jax.lax.dynamic_slice(
                vals_p, (i32(0), st), (vals_p.shape[0], S))
        else:
            bk = jax.lax.dynamic_slice(
                bins_p, (st, i32(0)), (S, bins_p.shape[1]))
            vk = jax.lax.dynamic_slice(
                vals_p, (st, i32(0)), (S, vals_p.shape[1]))
        lk = jax.lax.dynamic_slice(leaf_p, (st,), (S,))
        keep = (rel >= offs[k] - st) & (rel < offs[k] - st + cnts[k])
        ls.append(jnp.where(keep, lk, -1))
        bs.append(bk)
        vs.append(vk)
    axis = 1 if feature_major else 0
    return (jnp.concatenate(bs, axis=axis),
            jnp.concatenate(vs, axis=axis),
            jnp.concatenate(ls))
