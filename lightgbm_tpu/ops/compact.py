"""Pallas TPU kernel: mask-driven row compaction (stream compaction).

Reference context: LightGBM's sampled training paths scan index subsets
(``bag_data_indices_`` in goss.hpp / bagging.hpp — upstream paths
UNVERIFIED, empty mount, see SURVEY.md banner). XLA has no fast
equivalent: ``jnp.nonzero`` + computed-index gathers serialize on the
scalar unit (~1 s at 1M rows, docs/perf.md), and the round-3 substitute
— one multi-operand ``lax.sort`` — compiles superlinearly in operand
count, capping it at F≲32 packed columns.

This kernel removes both limits with the TPU's two strong units:

- per row-block, the kept rows' within-block destinations (a cheap XLA
  segmented cumsum, computed OUTSIDE the kernel) become a one-hot
  permutation matrix ``P_T[d, s] = [dest[s] + rem == d]`` generated on
  the VPU in natural [sublane=dst, lane=src] layout;
- the block's columns are moved by ONE MXU matmul per operand group
  (int8 for bins — wrap-exact; bf16 for value channels — exact for the
  histogram operands, which are themselves bf16/int-level downstream);
- the compacted block is DMA'd to HBM at the 128-aligned floor of its
  exact stream position. The ≤127 columns of *partial* output group at
  that position are first DMA'd back in and re-emitted (the grid is
  sequential on TPU, so the read sees the predecessor's write), which
  makes the packing EXACT — kept rows land contiguously, no per-block
  padding waste.

Cost is O(n·R) compares + O(n·R·F) int8 MACs — independent of F's
*operand packing*, so wide datasets (Bosch F=200, Criteo F=199) compact
as cheaply per byte as the Higgs shape. Measured numbers live in
docs/perf.md ("Row compaction kernel").
"""
from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_LANE = 128  # TPU lane width; output DMAs land on these boundaries


def compaction_out_cols(max_selected: int, rows_per_block: int,
                        multiple: int) -> int:
    """Static output width for ``compact_rows``: the kept rows plus one
    block of write slack, rounded up to ``multiple`` (the histogram
    kernel's rows_per_block) so the compacted buffer feeds
    ``multi_leaf_histogram`` directly."""
    m = max_selected + rows_per_block + _LANE
    return -(-m // multiple) * multiple


def plan_compaction(mask: jax.Array, rows_per_block: int,
                    out_cols: int
                    ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Within-block destinations + per-block aligned write positions.

    Args:
      mask: ``[n]`` bool/int keep mask; n % rows_per_block == 0.
      rows_per_block: source block size R.
      out_cols: static output width (``compaction_out_cols``); write
        positions are clamped so the kernel's ``R + 128``-wide writes
        stay in bounds even if the caller's ``max_selected`` bound is
        violated (clamping corrupts the tail instead of faulting —
        callers must size ``out_cols`` from a true upper bound).

    Returns:
      (dest ``[n]`` int32 within-block destination or -1 for dropped
      rows, aligned ``[nb]`` int32 block write positions in 128-lane
      GROUP units, rem ``[nb]`` int32 partial-group length at each
      block's start).
    """
    n = mask.shape[0]
    R = rows_per_block
    nb = n // R
    mb = mask.reshape(nb, R).astype(jnp.int32)
    within = jnp.cumsum(mb, axis=1)
    cnt = within[:, -1]
    dest = jnp.where(mb > 0, within - 1, -1).reshape(n)
    stream = jnp.concatenate([jnp.zeros(1, jnp.int32),
                              jnp.cumsum(cnt)[:-1].astype(jnp.int32)])
    aligned = jnp.minimum(stream // _LANE,
                          (out_cols - R - _LANE) // _LANE)
    rem = stream - aligned * _LANE
    return dest, aligned, rem


def _compact_kernel(algn_ref, rem_ref, dest_ref, bins_ref, vals_ref,
                    bins_out, vals_out, bins_vmem, vals_vmem,
                    bins_head, vals_head, sem_b, sem_v, sem_hb, sem_hv,
                    *, rows_per_block: int):
    b = pl.program_id(0)
    R = rows_per_block
    W = R + _LANE
    off = algn_ref[b] * _LANE
    rem = rem_ref[b]
    # read back the predecessor's partial output group at this block's
    # aligned position (sequential grid -> the write has landed); at
    # b == 0 this reads uninitialized columns, masked off below (rem=0)
    rb = pltpu.make_async_copy(
        bins_out.at[:, pl.ds(off, _LANE)], bins_head, sem_hb)
    rv = pltpu.make_async_copy(
        vals_out.at[:, pl.ds(off, _LANE)], vals_head, sem_hv)
    rb.start()
    rv.start()
    # one-hot permutation, transposed layout [dst(sublane), src(lane)]:
    # dropped rows (dest == -1) match no destination; kept rows land
    # after the rem carried-over columns (the shift must not touch the
    # -1 sentinel, which rem > 0 would otherwise lift to a real column)
    d0 = dest_ref[...]
    dest = jnp.where(d0 >= 0, d0 + rem, -1)                 # [1, R]
    iota_d = jax.lax.broadcasted_iota(jnp.int32, (W, R), 0)
    eq = iota_d == dest                                     # [W, R]
    moved = jax.lax.dot_general(
        bins_ref[...], eq.astype(jnp.int8),
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.int32)                   # [F, W]
    # value channels move EXACTLY via a 3-way bf16 significand split
    # (8+8+8 >= f32's 24 mantissa bits — the bf16x3 decomposition XLA
    # itself uses for f32 emulation): each one-hot product selects one
    # chunk unrounded, and the f32 chunk sum reconstructs the value
    # bit-for-bit. A single bf16 pass would RE-ROUND grads and GOSS
    # amplification weights; f32-HIGHEST costs +4.4 ms (measured).
    p_bf = eq.astype(jnp.bfloat16)
    v = vals_ref[...]
    h1 = v.astype(jnp.bfloat16)
    r1 = v - h1.astype(jnp.float32)
    h2 = r1.astype(jnp.bfloat16)
    h3 = (r1 - h2.astype(jnp.float32)).astype(jnp.bfloat16)
    _dn = (((1,), (1,)), ((), ()))
    vmoved = (jax.lax.dot_general(h1, p_bf, dimension_numbers=_dn,
                                  preferred_element_type=jnp.float32)
              + jax.lax.dot_general(h2, p_bf, dimension_numbers=_dn,
                                    preferred_element_type=jnp.float32)
              + jax.lax.dot_general(h3, p_bf, dimension_numbers=_dn,
                                    preferred_element_type=jnp.float32))
    rb.wait()
    rv.wait()
    head_ok = (jax.lax.broadcasted_iota(jnp.int32, (1, _LANE), 1)
               < rem)
    zero_w = jnp.zeros((bins_head.shape[0], R), jnp.int32)
    head_b = jnp.concatenate(
        [jnp.where(head_ok, bins_head[...].astype(jnp.int32), 0),
         zero_w], axis=1)
    # signed-wrap back to the int8 storage convention (uint8 values
    # stored with wraparound; a plain astype would CLAMP 128..255)
    m8 = (moved + head_b) & 0xFF
    bins_vmem[...] = (m8 - ((m8 >> 7) << 8)).astype(jnp.int8)
    zero_vw = jnp.zeros((vals_head.shape[0], R), jnp.float32)
    vals_vmem[...] = vmoved + jnp.concatenate(
        [jnp.where(head_ok, vals_head[...], 0.0), zero_vw], axis=1)
    cb = pltpu.make_async_copy(
        bins_vmem, bins_out.at[:, pl.ds(off, W)], sem_b)
    cv = pltpu.make_async_copy(
        vals_vmem, vals_out.at[:, pl.ds(off, W)], sem_v)
    cb.start()
    cv.start()
    cb.wait()
    cv.wait()


@functools.partial(jax.jit,
                   static_argnames=("out_cols", "rows_per_block"))
def compact_rows(bins_t: jax.Array, vals_t: jax.Array, dest: jax.Array,
                 aligned: jax.Array, rem: jax.Array, *, out_cols: int,
                 rows_per_block: int = 1024
                 ) -> Tuple[jax.Array, jax.Array]:
    """Compact kept columns of feature-major arrays (TPU Pallas path).

    Args:
      bins_t: ``[F, n]`` int8 feature-major binned matrix.
      vals_t: ``[C, n]`` float32 channel-major per-row values (grad,
        hess, count-mask, optionally leaf_id+1 — any C). Moved
        bit-exactly (bf16x3 significand split in the kernel).
      dest / aligned / rem: from ``plan_compaction`` (same
        rows_per_block).
      out_cols: static output width (``compaction_out_cols``).

    Returns:
      (``[F, out_cols]`` int8, ``[C, out_cols]`` float32): kept columns
      packed contiguously left-to-right in source order; the tail is
      zeros, so downstream histogram scans see zero contributions
      there (and a leaf_id+1 channel decodes the tail to -1).
    """
    F, n = bins_t.shape
    C = vals_t.shape[0]
    R = rows_per_block
    assert n % R == 0, f"n={n} must be a multiple of rows_per_block={R}"
    # the [R+128, R] permutation's bf16 copy + the streamed operands
    # fit comfortably at R=1024 (~3.5 MB); R=2048 measured slower
    # anyway (P generation cost scales n*R)
    assert R <= 1024, f"rows_per_block={R} exceeds the VMEM-safe 1024"
    assert out_cols >= R + _LANE, "out_cols below one write window"
    nb = n // R
    W = R + _LANE
    # the manual output DMAs slice dim 0 whole, which Mosaic requires
    # 8-sublane aligned — pad the channel dims with zero rows
    F_pad = -(-F // 8) * 8
    C_pad = -(-C // 8) * 8
    if F_pad > F:
        bins_t = jnp.concatenate(
            [bins_t, jnp.zeros((F_pad - F, n), bins_t.dtype)])
    if C_pad > C:
        vals_t = jnp.concatenate(
            [vals_t, jnp.zeros((C_pad - C, n), vals_t.dtype)])
    # NO input_output_aliases on the output windows (examined, round 7
    # — docs/perf.md "Iteration floor"): out_cols != n by construction
    # (compaction_out_cols adds one block of write slack + lane
    # padding), so neither [F_pad, out_cols] output can alias its
    # [F_pad, n] input; and even at equal widths the kernel reads
    # block b's source columns AFTER earlier blocks wrote their packed
    # output left of them — in-place would clobber unread sources.
    out_b, out_v = pl.pallas_call(
        functools.partial(_compact_kernel, rows_per_block=R),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(nb,),
            in_specs=[
                pl.BlockSpec((1, R), lambda b, a, r: (0, b)),
                pl.BlockSpec((F_pad, R), lambda b, a, r: (0, b)),
                pl.BlockSpec((C_pad, R), lambda b, a, r: (0, b)),
            ],
            out_specs=[
                pl.BlockSpec(memory_space=pl.ANY),
                pl.BlockSpec(memory_space=pl.ANY),
            ],
            scratch_shapes=[
                pltpu.VMEM((F_pad, W), jnp.int8),
                pltpu.VMEM((C_pad, W), jnp.float32),
                pltpu.VMEM((F_pad, _LANE), jnp.int8),
                pltpu.VMEM((C_pad, _LANE), jnp.float32),
                pltpu.SemaphoreType.DMA,
                pltpu.SemaphoreType.DMA,
                pltpu.SemaphoreType.DMA,
                pltpu.SemaphoreType.DMA,
            ],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((F_pad, out_cols), jnp.int8),
            jax.ShapeDtypeStruct((C_pad, out_cols), jnp.float32),
        ],
    )(aligned, rem, dest.reshape(1, n), bins_t, vals_t)
    # Pallas outputs are uninitialized; zero everything past the last
    # block's write window so downstream scans see zero contributions
    col_ok = (jnp.arange(out_cols, dtype=jnp.int32)
              < aligned[-1] * _LANE + W)[None, :]
    return (jnp.where(col_ok, out_b[:F], jnp.int8(0)),
            jnp.where(col_ok, out_v[:C], jnp.float32(0.0)))


@functools.partial(jax.jit,
                   static_argnames=("out_cols", "rows_per_block"))
def compact_rows_xla(bins_t: jax.Array, vals_t: jax.Array,
                     dest: jax.Array, aligned: jax.Array,
                     rem: jax.Array, *, out_cols: int,
                     rows_per_block: int = 1024
                     ) -> Tuple[jax.Array, jax.Array]:
    """XLA scatter fallback (CPU tests / non-TPU backends): identical
    output layout to ``compact_rows`` (exact contiguous packing), any
    bins dtype, exact f32 values. Scatters serialize on TPU
    (docs/perf.md) — use only off-TPU."""
    R = rows_per_block
    stream = aligned * _LANE + rem                       # [nb] exact
    gd = jnp.where(dest >= 0,
                   jnp.repeat(stream, R) + dest,
                   out_cols).astype(jnp.int32)
    out_b = jnp.zeros((bins_t.shape[0], out_cols + 1),
                      bins_t.dtype).at[:, gd].set(bins_t, mode="drop")
    out_v = jnp.zeros((vals_t.shape[0], out_cols + 1),
                      vals_t.dtype).at[:, gd].set(vals_t, mode="drop")
    return out_b[:, :out_cols], out_v[:, :out_cols]
