"""Pallas TPU kernel: fused multi-leaf histogram construction.

Reference: the CUDA histogram kernel
(src/treelearner/cuda/cuda_histogram_constructor.cu, UNVERIFIED — empty
mount, see SURVEY.md banner) builds per-leaf histograms with shared-memory
atomic adds. TPUs have no fast scatter-atomics; the MXU formulation is

    hist[k, f, b, c] = sum_r [bin(r,f) == b] * [leaf(r) == small_k] * vals[r, c]

One grid step processes a row block: the bin one-hot ``[F*B, R]`` is
generated in VMEM (never staged through HBM — the failure mode of the XLA
einsum formulation) and contracted on the MXU in ONE large
``[F*B, R] x [R, K*C]`` matmul.

The K axis is the TPU-specific trick: packing K candidate leaves' masks
into the matmul N dimension amortizes the MXU's 128-wide N padding, so one
data scan yields K leaf histograms (K*C ≈ 128 → negligible padding waste).
The batched tree grower (learner/serial.py) exploits this by expanding the
top-K leaves per round.

Measured on v5e (1M rows, F=28, B=256): ~23ms/scan at K=8, ~34ms at K=42 —
the floor is the VPU one-hot generation (int32 compares; int8/bf16 vector
compares are unsupported by this target), not the matmul.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _hist_kernel(bins_ref, vals_ref, leaf_ref, small_ref, out_ref, *,
                 num_bins: int, n_feat: int, n_leaves: int, n_chan: int,
                 int_mode: bool = False):
    i = pl.program_id(1)      # row-block index (feature block is dim 0)
    # bins stored int8 to halve HBM traffic; wrapped values are restored
    # with & 0xFF after widening (cheap at [F, R])
    bins_blk = bins_ref[...].astype(jnp.int32) & 0xFF    # [F, R]
    vals_blk = vals_ref[...]                             # [C, R]
    lid = leaf_ref[...]                                  # [1, R]
    small = small_ref[...]                               # [K, 1]

    mask = (lid == small).astype(jnp.float32)            # [K, R]
    prod = (mask[:, None, :] * vals_blk[None, :, :]) \
        .reshape(n_leaves * n_chan, -1)
    # int_mode (use_quantized_grad): grad/hess are small integer levels,
    # so the contraction rides the MXU's 2x-rate int8 path with EXACT
    # int32 accumulation (the reference's integer-histogram design,
    # cuda_gradient_discretizer.cu; measured 1.25x/scan on v5e)
    rhs = prod.astype(jnp.int8 if int_mode else jnp.bfloat16)

    # [B*F, R] one-hot in tiled layout (pltpu.repeat tiles the F rows B
    # times: row q corresponds to (b = q // F, f = q % F))
    big = pltpu.repeat(bins_blk, num_bins, axis=0)
    iota_b = (jax.lax.broadcasted_iota(jnp.int32, (n_feat * num_bins, 1),
                                       0) // n_feat)
    onehot = (big == iota_b).astype(jnp.int8 if int_mode
                                    else jnp.bfloat16)

    contrib = jax.lax.dot_general(
        onehot, rhs, dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=(jnp.int32 if int_mode
                                else jnp.float32))       # [B*F, K*C]

    @pl.when(i == 0)
    def _():
        out_ref[...] = contrib

    @pl.when(i > 0)
    def _():
        out_ref[...] += contrib


@functools.partial(jax.jit,
                   static_argnames=("num_bins", "rows_per_block",
                                    "int_mode"))
def multi_leaf_histogram(bins_t: jax.Array, vals_t: jax.Array,
                         leaf_id: jax.Array, small_ids: jax.Array, *,
                         num_bins: int,
                         rows_per_block: int = 2048,
                         int_mode: bool = False) -> jax.Array:
    """Histograms of K leaves in one fused scan (TPU Pallas path).

    Args:
      bins_t: ``[F, n]`` int8 FEATURE-MAJOR binned matrix (transposed once
        at setup so row blocks are lane-contiguous; uint8 values stored
        with int8 wraparound).
      vals_t: ``[C, n]`` float32 channel-major per-row values
        (grad*m, hess*m, count-mask) — bagging masks pre-applied.
      leaf_id: ``[n]`` int32 current leaf of each row.
      small_ids: ``[K]`` int32 leaf ids to histogram (-1 entries match no
        row, giving zero histograms for inactive slots).
      num_bins: static histogram width B.

    Returns:
      ``[K, F, B, C]`` float32.
    """
    F, n = bins_t.shape
    C = vals_t.shape[0]
    K = small_ids.shape[0]
    R = rows_per_block
    assert n % R == 0, f"n={n} must be a multiple of rows_per_block={R}"

    # feature blocking keeps the [B*F_blk, K*C] VMEM accumulator (and the
    # transient one-hot) bounded for wide datasets (MSLR F=136+); at
    # F*B <= 8192 this is a single block, identical to the unblocked
    # form. Blocked (wide-F) layouts use a half-size block: [8192, R]
    # streaming exceeds the 16MB scoped-vmem budget at K*C ~ 96+
    # (measured: 16.25M at F_blk=32, B=256, R=2048 on v5e).
    if F * num_bins <= 8192:
        F_blk = F
    else:
        F_blk = max(1, 4096 // num_bins)
    n_fb = (F + F_blk - 1) // F_blk
    F_pad = n_fb * F_blk
    if F_pad > F:
        bins_t = jnp.concatenate(
            [bins_t, jnp.zeros((F_pad - F, n), bins_t.dtype)])

    kernel = functools.partial(_hist_kernel, num_bins=num_bins,
                               n_feat=F_blk, n_leaves=K, n_chan=C,
                               int_mode=int_mode)
    # NO input_output_aliases here (examined, round 7 — docs/perf.md
    # "Iteration floor"): the [B*F_pad, K*C] accumulator is an
    # output-only carry across the sequential row-block grid, already
    # accumulated in place in VMEM by the @pl.when(i>0) add; no input
    # operand shares its shape/dtype, and threading a caller-supplied
    # zeroed buffer just to alias it would ADD an HBM zero-fill per
    # call — strictly worse than the status quo.
    out = pl.pallas_call(
        kernel,
        grid=(n_fb, n // R),
        in_specs=[
            pl.BlockSpec((F_blk, R), lambda j, i: (j, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((C, R), lambda j, i: (0, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, R), lambda j, i: (0, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((K, 1), lambda j, i: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((num_bins * F_blk, K * C),
                               lambda j, i: (j, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((num_bins * F_pad, K * C),
                                       jnp.int32 if int_mode
                                       else jnp.float32),
        cost_estimate=pl.CostEstimate(
            flops=2 * F_pad * num_bins * n * K * C,
            bytes_accessed=bins_t.size + vals_t.size * 4 + leaf_id.size * 4,
            transcendentals=0),
    )(bins_t, vals_t, leaf_id.reshape(1, n), small_ids.reshape(K, 1))
    if int_mode:
        out = out.astype(jnp.float32)
    # per block j, row q = b * F_blk + f_local
    out = out.reshape(n_fb, num_bins, F_blk, K, C)
    out = out.transpose(3, 0, 2, 1, 4).reshape(K, F_pad, num_bins, C)
    return out[:, :F]


def multi_leaf_histogram_xla(bins: jax.Array, vals: jax.Array,
                             leaf_id: jax.Array, small_ids: jax.Array, *,
                             num_bins: int,
                             rows_per_block: int = 1024,
                             precise: bool = False) -> jax.Array:
    """XLA fallback (CPU tests / non-TPU backends): same contract via the
    einsum-based build_histogram with leaf masks packed into channels.
    ``precise`` keeps grad/hess in float32 (tpu_double_precision_hist)
    instead of the default bfloat16 operands."""
    from .histogram import build_histogram
    K = small_ids.shape[0]
    n, _F = bins.shape
    C = vals.shape[1]
    mask = (leaf_id[:, None] == small_ids[None, :]).astype(vals.dtype)
    packed = (mask[:, :, None] * vals[:, None, :]).reshape(n, K * C)
    hist = build_histogram(bins, packed, num_bins=num_bins,
                           rows_per_block=rows_per_block, precise=precise)
    F, B, _ = hist.shape
    return hist.reshape(F, B, K, C).transpose(2, 0, 1, 3)
