"""Subpackage: ops."""
