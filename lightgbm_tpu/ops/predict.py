"""Device-side tree traversal for prediction / score updates.

Reference: ``GBDT::PredictRaw`` + ``Tree::Predict`` (src/boosting/
gbdt_prediction.cpp, src/io/tree.cpp, UNVERIFIED — empty mount, see
SURVEY.md banner): per-row node walk by threshold comparisons.

TPU-first: all rows traverse in lockstep — a ``while_loop`` over tree
depth where each step gathers (feature, threshold, children) for every
row's current node and advances; rows that reached a leaf (negative node
encoding) freeze. Trees stack along a leading axis and are folded with
``lax.scan``, so predicting a whole model is one jitted program.
"""
from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp


def tree_predict_binned(tree: Dict[str, jax.Array], bins: jax.Array,
                        feat_num_bin: jax.Array,
                        feat_has_nan: jax.Array) -> Tuple[jax.Array,
                                                          jax.Array]:
    """Route every row of ``bins`` through one tree.

    Args:
      tree: dict of flat tree arrays (device), as produced by grow_tree.
      bins: ``[n, F]`` binned features.

    Returns:
      (leaf_value per row ``[n]`` float32, leaf index per row ``[n]`` int32)
    """
    n = bins.shape[0]
    num_leaves = tree["num_leaves"]
    # node >= 0: internal node index; node < 0: ~leaf
    node0 = jnp.where(num_leaves > 1, jnp.zeros(n, jnp.int32),
                      jnp.full(n, -1, jnp.int32))

    def cond(node):
        return jnp.any(node >= 0)

    def body(node):
        nd = jnp.maximum(node, 0)
        feat = tree["split_feature"][nd]
        thr = tree["threshold_bin"][nd]
        dleft = tree["default_left"][nd]
        col = jnp.take_along_axis(bins, feat[:, None].astype(jnp.int32),
                                  axis=1)[:, 0].astype(jnp.int32)
        missing = feat_has_nan[feat] & (col == feat_num_bin[feat] - 1)
        go_left = jnp.where(missing, dleft, col <= thr)
        if "is_cat" in tree:
            # categorical: bin-membership test in the node's bitset
            # (bin 0 / unseen categories miss every bitset -> right)
            bitset = tree["cat_bitset"][nd]            # [n, W]
            word = jnp.take_along_axis(
                bitset, (col >> 5)[:, None], axis=1)[:, 0]
            cat_left = ((word >> (col & 31).astype(jnp.uint32))
                        & jnp.uint32(1)) > 0
            go_left = jnp.where(tree["is_cat"][nd], cat_left, go_left)
        nxt = jnp.where(go_left, tree["left_child"][nd],
                        tree["right_child"][nd])
        return jnp.where(node >= 0, nxt, node)

    node = jax.lax.while_loop(cond, body, node0)
    leaf = (-node - 1).astype(jnp.int32)
    return tree["leaf_value"][leaf], leaf


def forest_predict_binned(stacked: Dict[str, jax.Array], bins: jax.Array,
                          feat_num_bin: jax.Array, feat_has_nan: jax.Array,
                          class_index: jax.Array,
                          num_class: int) -> Tuple[jax.Array, jax.Array]:
    """Sum leaf outputs of a stacked forest into per-class raw scores.

    Args:
      stacked: tree arrays with a leading ``[T]`` axis (trees padded to a
        common ``num_leaves`` capacity).
      class_index: ``[T]`` int32 — class each tree contributes to
        (``t % num_class`` for multiclass round-robin, zeros for K=1).

    Returns:
      (raw scores ``[n, num_class]``, leaf indices ``[T, n]``)
    """
    n = bins.shape[0]

    def body(carry, xs):
        tree, cls = xs
        vals, leaf = tree_predict_binned(tree, bins, feat_num_bin,
                                         feat_has_nan)
        return carry.at[:, cls].add(vals), leaf

    init = jnp.zeros((n, num_class), jnp.float32)
    scores, leaves = jax.lax.scan(body, init, (stacked, class_index))
    return scores, leaves
