"""Device-side tree traversal for prediction / score updates.

Reference: ``GBDT::PredictRaw`` + ``Tree::Predict`` (src/boosting/
gbdt_prediction.cpp, src/io/tree.cpp, UNVERIFIED — empty mount, see
SURVEY.md banner): per-row node walk by threshold comparisons.

TPU-first: all rows traverse in lockstep — a ``while_loop`` over tree
depth where each step gathers (feature, threshold, children) for every
row's current node and advances; rows that reached a leaf (negative node
encoding) freeze. Trees stack along a leading axis and are folded with
``lax.scan``, so predicting a whole model is one jitted program.
"""
from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp


def tree_predict_binned(tree: Dict[str, jax.Array], bins: jax.Array,
                        feat_num_bin: jax.Array,
                        feat_has_nan: jax.Array) -> Tuple[jax.Array,
                                                          jax.Array]:
    """Route every row of ``bins`` through one tree.

    Args:
      tree: dict of flat tree arrays (device), as produced by grow_tree.
      bins: ``[n, F]`` binned features.

    Returns:
      (leaf_value per row ``[n]`` float32, leaf index per row ``[n]`` int32)
    """
    n, F = bins.shape
    num_leaves = tree["num_leaves"]
    # node >= 0: internal node index; node < 0: ~leaf
    node0 = jnp.where(num_leaves > 1, jnp.zeros(n, jnp.int32),
                      jnp.full(n, -1, jnp.int32))

    # TPU note: per-row gathers from the per-node tables run on the
    # scalar unit (~9 ms per gather per Mrow — 5-6 of them per depth
    # level made a 1M-row traversal cost ~300 ms). Instead the node
    # attributes are packed into a [Ln, C] matrix contracted against
    # the [n, Ln] node-membership one-hot each level — all values
    # (feature ids, bin thresholds, child links, 16-bit bitset halves)
    # are small integers, exact in f32 at HIGHEST precision. The
    # one-hot operand is O(n * Ln), so very wide trees fall back to
    # the O(n)-memory gather formulation (same cutoff as the score
    # update in boosting/gbdt.py).
    sf = tree["split_feature"].astype(jnp.int32)
    Ln = sf.shape[0]
    if Ln > 512:
        return _tree_predict_binned_gather(tree, bins, feat_num_bin,
                                           feat_has_nan, node0)
    node_nan_bin = jnp.where(feat_has_nan[sf],
                             feat_num_bin[sf] - 1, -1)   # [Ln]
    has_cat = "is_cat" in tree
    attr_cols = [sf.astype(jnp.float32),
                 tree["threshold_bin"].astype(jnp.float32),
                 tree["default_left"].astype(jnp.float32),
                 node_nan_bin.astype(jnp.float32),
                 tree["left_child"].astype(jnp.float32),
                 tree["right_child"].astype(jnp.float32)]
    if has_cat:
        bs = tree["cat_bitset"]                          # [Ln, W]
        W = bs.shape[1]
        attr_cols.append(tree["is_cat"].astype(jnp.float32))
        attr_cols.extend(jnp.moveaxis(
            (bs & jnp.uint32(0xFFFF)).astype(jnp.float32), 1, 0))
        attr_cols.extend(jnp.moveaxis(
            (bs >> jnp.uint32(16)).astype(jnp.float32), 1, 0))
    packed = jnp.stack(attr_cols, axis=1)                # [Ln, C]
    node_ids = jnp.arange(Ln, dtype=jnp.int32)
    col_ids = jnp.arange(F, dtype=jnp.int32)

    def cond(node):
        return jnp.any(node >= 0)

    def body(node):
        nd = jnp.maximum(node, 0)
        oh = (nd[:, None] == node_ids[None, :]).astype(jnp.float32)
        attr = jax.lax.dot_general(
            oh, packed, dimension_numbers=(((1,), (0,)), ((), ())),
            precision=jax.lax.Precision.HIGHEST)         # [n, C]
        feat_r = attr[:, 0].astype(jnp.int32)
        thr_r = attr[:, 1].astype(jnp.int32)
        dl_r = attr[:, 2] > 0.5
        nan_r = attr[:, 3].astype(jnp.int32)
        oh_f = feat_r[:, None] == col_ids[None, :]
        col = jnp.sum(jnp.where(oh_f, bins.astype(jnp.int32), 0), axis=1)
        go_left = jnp.where(col == nan_r, dl_r, col <= thr_r)
        if has_cat:
            # categorical: bin-membership test in the node's bitset
            # (bin 0 / unseen categories miss every bitset -> right)
            oh_w = ((col >> 5)[:, None]
                    == jnp.arange(W, dtype=jnp.int32)[None, :])
            lo16 = jnp.sum(jnp.where(oh_w, attr[:, 7:7 + W], 0.0),
                           axis=1).astype(jnp.uint32)
            hi16 = jnp.sum(jnp.where(oh_w, attr[:, 7 + W:7 + 2 * W],
                                     0.0), axis=1).astype(jnp.uint32)
            word = lo16 | (hi16 << jnp.uint32(16))
            cat_left = ((word >> (col & 31).astype(jnp.uint32))
                        & jnp.uint32(1)) > 0
            go_left = jnp.where(attr[:, 6] > 0.5, cat_left, go_left)
        nxt = jnp.where(go_left, attr[:, 4], attr[:, 5]) \
            .astype(jnp.int32)
        return jnp.where(node >= 0, nxt, node)

    node = jax.lax.while_loop(cond, body, node0)
    leaf = (-node - 1).astype(jnp.int32)
    L = tree["leaf_value"].shape[0]
    oh_leaf = (leaf[:, None]
               == jnp.arange(L, dtype=jnp.int32)[None, :])
    vals = jax.lax.dot_general(
        oh_leaf.astype(jnp.float32), tree["leaf_value"][:, None],
        dimension_numbers=(((1,), (0,)), ((), ())),
        precision=jax.lax.Precision.HIGHEST)[:, 0]
    return vals, leaf


def _tree_predict_binned_gather(tree, bins, feat_num_bin, feat_has_nan,
                                node0):
    """O(n)-memory per-row gather traversal — the fallback for trees too
    wide for the one-hot matmul formulation (num_leaves > 512)."""

    def cond(node):
        return jnp.any(node >= 0)

    def body(node):
        nd = jnp.maximum(node, 0)
        feat = tree["split_feature"][nd]
        thr = tree["threshold_bin"][nd]
        dleft = tree["default_left"][nd]
        col = jnp.take_along_axis(bins, feat[:, None].astype(jnp.int32),
                                  axis=1)[:, 0].astype(jnp.int32)
        missing = feat_has_nan[feat] & (col == feat_num_bin[feat] - 1)
        go_left = jnp.where(missing, dleft, col <= thr)
        if "is_cat" in tree:
            bitset = tree["cat_bitset"][nd]            # [n, W]
            word = jnp.take_along_axis(
                bitset, (col >> 5)[:, None], axis=1)[:, 0]
            cat_left = ((word >> (col & 31).astype(jnp.uint32))
                        & jnp.uint32(1)) > 0
            go_left = jnp.where(tree["is_cat"][nd], cat_left, go_left)
        nxt = jnp.where(go_left, tree["left_child"][nd],
                        tree["right_child"][nd])
        return jnp.where(node >= 0, nxt, node)

    node = jax.lax.while_loop(cond, body, node0)
    leaf = (-node - 1).astype(jnp.int32)
    return tree["leaf_value"][leaf], leaf


def forest_predict_binned(stacked: Dict[str, jax.Array], bins: jax.Array,
                          feat_num_bin: jax.Array, feat_has_nan: jax.Array,
                          class_index: jax.Array,
                          num_class: int) -> Tuple[jax.Array, jax.Array]:
    """Sum leaf outputs of a stacked forest into per-class raw scores.

    Args:
      stacked: tree arrays with a leading ``[T]`` axis (trees padded to a
        common ``num_leaves`` capacity).
      class_index: ``[T]`` int32 — class each tree contributes to
        (``t % num_class`` for multiclass round-robin, zeros for K=1).

    Returns:
      (raw scores ``[n, num_class]``, leaf indices ``[T, n]``)
    """
    n = bins.shape[0]

    def body(carry, xs):
        tree, cls = xs
        vals, leaf = tree_predict_binned(tree, bins, feat_num_bin,
                                         feat_has_nan)
        return carry.at[:, cls].add(vals), leaf

    init = jnp.zeros((n, num_class), jnp.float32)
    scores, leaves = jax.lax.scan(body, init, (stacked, class_index))
    return scores, leaves
