"""Device-side tree traversal for prediction / score updates.

Reference: ``GBDT::PredictRaw`` + ``Tree::Predict`` (src/boosting/
gbdt_prediction.cpp, src/io/tree.cpp, UNVERIFIED — empty mount, see
SURVEY.md banner): per-row node walk by threshold comparisons.

TPU-first: all rows traverse in lockstep — a ``while_loop`` over tree
depth where each step gathers (feature, threshold, children) for every
row's current node and advances; rows that reached a leaf (negative node
encoding) freeze.

Two forest formulations share the per-level step logic:

- ``mode="scan"`` (the original, kept as the reference path): trees fold
  sequentially with ``lax.scan`` — O(T·depth) small steps.
- ``mode="level"`` (default, the serving fast path): LEVEL-SYNCHRONOUS
  tree-parallel traversal — a ``[T, n]`` node-state advances every tree
  one level per step, so the program runs O(max_depth) steps of large
  batched contractions instead of O(T·depth) small ones. On TPU the step
  is a batched ``[T, n, Ln] x [T, Ln, C]`` MXU matmul (the node
  attributes packed exactly as in the per-tree formulation); off-TPU and
  for trees wider than ``ONEHOT_MAX_NODES`` it is a batched gather (the
  same O(n)-memory fallback the per-tree path uses).

Both modes produce bit-identical outputs: each tree's per-row leaf value
is exact under either formulation (the one-hot contraction selects a
single element at HIGHEST precision), and the per-class score
accumulation replays the reference scan's sequential tree order.
"""
from __future__ import annotations

import functools
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

# Widest per-tree node table the one-hot matmul formulation handles;
# wider trees use the O(n)-memory gather formulation (same cutoff as the
# score update in boosting/gbdt.py).
ONEHOT_MAX_NODES = 512

# Peak per-level one-hot operand budget (elements) for the tree-parallel
# step: trees scan in power-of-two blocks so T_blk * n * max(Ln, F)
# stays bounded (~1 GiB f32 for the [T, n, Ln] membership one-hot).
LEVEL_ONEHOT_BUDGET = 256 * 1024 * 1024


def tree_predict_binned(tree: Dict[str, jax.Array], bins: jax.Array,
                        feat_num_bin: jax.Array,
                        feat_has_nan: jax.Array) -> Tuple[jax.Array,
                                                          jax.Array]:
    """Route every row of ``bins`` through one tree.

    Args:
      tree: dict of flat tree arrays (device), as produced by grow_tree.
      bins: ``[n, F]`` binned features.

    Returns:
      (leaf_value per row ``[n]`` float32, leaf index per row ``[n]`` int32)
    """
    n, F = bins.shape
    num_leaves = tree["num_leaves"]
    # node >= 0: internal node index; node < 0: ~leaf
    node0 = jnp.where(num_leaves > 1, jnp.zeros(n, jnp.int32),
                      jnp.full(n, -1, jnp.int32))

    # TPU note: per-row gathers from the per-node tables run on the
    # scalar unit (~9 ms per gather per Mrow — 5-6 of them per depth
    # level made a 1M-row traversal cost ~300 ms). Instead the node
    # attributes are packed into a [Ln, C] matrix contracted against
    # the [n, Ln] node-membership one-hot each level — all values
    # (feature ids, bin thresholds, child links, 16-bit bitset halves)
    # are small integers, exact in f32 at HIGHEST precision. The
    # one-hot operand is O(n * Ln), so very wide trees fall back to
    # the O(n)-memory gather formulation (same cutoff as the score
    # update in boosting/gbdt.py).
    sf = tree["split_feature"].astype(jnp.int32)
    Ln = sf.shape[0]
    if Ln > ONEHOT_MAX_NODES:
        return _tree_predict_binned_gather(tree, bins, feat_num_bin,
                                           feat_has_nan, node0)
    node_nan_bin = jnp.where(feat_has_nan[sf],
                             feat_num_bin[sf] - 1, -1)   # [Ln]
    has_cat = "is_cat" in tree
    attr_cols = [sf.astype(jnp.float32),
                 tree["threshold_bin"].astype(jnp.float32),
                 tree["default_left"].astype(jnp.float32),
                 node_nan_bin.astype(jnp.float32),
                 tree["left_child"].astype(jnp.float32),
                 tree["right_child"].astype(jnp.float32)]
    if has_cat:
        bs = tree["cat_bitset"]                          # [Ln, W]
        W = bs.shape[1]
        attr_cols.append(tree["is_cat"].astype(jnp.float32))
        attr_cols.extend(jnp.moveaxis(
            (bs & jnp.uint32(0xFFFF)).astype(jnp.float32), 1, 0))
        attr_cols.extend(jnp.moveaxis(
            (bs >> jnp.uint32(16)).astype(jnp.float32), 1, 0))
    packed = jnp.stack(attr_cols, axis=1)                # [Ln, C]
    node_ids = jnp.arange(Ln, dtype=jnp.int32)
    col_ids = jnp.arange(F, dtype=jnp.int32)

    def cond(node):
        return jnp.any(node >= 0)

    def body(node):
        nd = jnp.maximum(node, 0)
        oh = (nd[:, None] == node_ids[None, :]).astype(jnp.float32)
        attr = jax.lax.dot_general(
            oh, packed, dimension_numbers=(((1,), (0,)), ((), ())),
            precision=jax.lax.Precision.HIGHEST)         # [n, C]
        feat_r = attr[:, 0].astype(jnp.int32)
        thr_r = attr[:, 1].astype(jnp.int32)
        dl_r = attr[:, 2] > 0.5
        nan_r = attr[:, 3].astype(jnp.int32)
        oh_f = feat_r[:, None] == col_ids[None, :]
        col = jnp.sum(jnp.where(oh_f, bins.astype(jnp.int32), 0), axis=1)
        go_left = jnp.where(col == nan_r, dl_r, col <= thr_r)
        if has_cat:
            # categorical: bin-membership test in the node's bitset
            # (bin 0 / unseen categories miss every bitset -> right)
            oh_w = ((col >> 5)[:, None]
                    == jnp.arange(W, dtype=jnp.int32)[None, :])
            lo16 = jnp.sum(jnp.where(oh_w, attr[:, 7:7 + W], 0.0),
                           axis=1).astype(jnp.uint32)
            hi16 = jnp.sum(jnp.where(oh_w, attr[:, 7 + W:7 + 2 * W],
                                     0.0), axis=1).astype(jnp.uint32)
            word = lo16 | (hi16 << jnp.uint32(16))
            cat_left = ((word >> (col & 31).astype(jnp.uint32))
                        & jnp.uint32(1)) > 0
            go_left = jnp.where(attr[:, 6] > 0.5, cat_left, go_left)
        nxt = jnp.where(go_left, attr[:, 4], attr[:, 5]) \
            .astype(jnp.int32)
        return jnp.where(node >= 0, nxt, node)

    node = jax.lax.while_loop(cond, body, node0)
    leaf = (-node - 1).astype(jnp.int32)
    L = tree["leaf_value"].shape[0]
    oh_leaf = (leaf[:, None]
               == jnp.arange(L, dtype=jnp.int32)[None, :])
    vals = jax.lax.dot_general(
        oh_leaf.astype(jnp.float32), tree["leaf_value"][:, None],
        dimension_numbers=(((1,), (0,)), ((), ())),
        precision=jax.lax.Precision.HIGHEST)[:, 0]
    return vals, leaf


def _tree_predict_binned_gather(tree, bins, feat_num_bin, feat_has_nan,
                                node0):
    """O(n)-memory per-row gather traversal — the fallback for trees too
    wide for the one-hot matmul formulation (num_leaves > 512)."""

    def cond(node):
        return jnp.any(node >= 0)

    def body(node):
        nd = jnp.maximum(node, 0)
        feat = tree["split_feature"][nd]
        thr = tree["threshold_bin"][nd]
        dleft = tree["default_left"][nd]
        col = jnp.take_along_axis(bins, feat[:, None].astype(jnp.int32),
                                  axis=1)[:, 0].astype(jnp.int32)
        missing = feat_has_nan[feat] & (col == feat_num_bin[feat] - 1)
        go_left = jnp.where(missing, dleft, col <= thr)
        if "is_cat" in tree:
            bitset = tree["cat_bitset"][nd]            # [n, W]
            word = jnp.take_along_axis(
                bitset, (col >> 5)[:, None], axis=1)[:, 0]
            cat_left = ((word >> (col & 31).astype(jnp.uint32))
                        & jnp.uint32(1)) > 0
            go_left = jnp.where(tree["is_cat"][nd], cat_left, go_left)
        nxt = jnp.where(go_left, tree["left_child"][nd],
                        tree["right_child"][nd])
        return jnp.where(node >= 0, nxt, node)

    node = jax.lax.while_loop(cond, body, node0)
    leaf = (-node - 1).astype(jnp.int32)
    return tree["leaf_value"][leaf], leaf


# ---------------------------------------------------------------------------
# Level-synchronous tree-parallel traversal (the serving fast path)
# ---------------------------------------------------------------------------

def _level_traverse(stacked, bins, feat_num_bin, feat_has_nan,
                    formulation):
    """Advance ALL T trees one level per step.

    Returns (leaf value per (tree, row) ``[T, n]`` f32,
             leaf index per (tree, row) ``[T, n]`` int32).
    """
    sf = stacked["split_feature"].astype(jnp.int32)      # [T, Ln]
    T, Ln = sf.shape
    n, F = bins.shape
    node0 = jnp.where(stacked["num_leaves"][:, None] > 1,
                      jnp.zeros((T, n), jnp.int32),
                      jnp.full((T, n), -1, jnp.int32))
    has_cat = "is_cat" in stacked

    def cond(node):
        return jnp.any(node >= 0)

    if formulation == "onehot":
        # batched variant of the per-tree packed-attribute matmul: one
        # [T, n, Ln] x [T, Ln, C] contraction per level (batch dim = T)
        node_nan_bin = jnp.where(feat_has_nan[sf],
                                 feat_num_bin[sf] - 1, -1)   # [T, Ln]
        attr_cols = [sf.astype(jnp.float32),
                     stacked["threshold_bin"].astype(jnp.float32),
                     stacked["default_left"].astype(jnp.float32),
                     node_nan_bin.astype(jnp.float32),
                     stacked["left_child"].astype(jnp.float32),
                     stacked["right_child"].astype(jnp.float32)]
        if has_cat:
            bs = stacked["cat_bitset"]                   # [T, Ln, W]
            W = bs.shape[2]
            attr_cols.append(stacked["is_cat"].astype(jnp.float32))
            attr_cols.extend(jnp.moveaxis(
                (bs & jnp.uint32(0xFFFF)).astype(jnp.float32), 2, 0))
            attr_cols.extend(jnp.moveaxis(
                (bs >> jnp.uint32(16)).astype(jnp.float32), 2, 0))
        packed = jnp.stack(attr_cols, axis=2)            # [T, Ln, C]
        node_ids = jnp.arange(Ln, dtype=jnp.int32)
        col_ids = jnp.arange(F, dtype=jnp.int32)
        bins_i = bins.astype(jnp.int32)

        def body(node):
            nd = jnp.maximum(node, 0)                    # [T, n]
            oh = (nd[:, :, None] == node_ids).astype(jnp.float32)
            attr = jax.lax.dot_general(                  # [T, n, C]
                oh, packed,
                dimension_numbers=(((2,), (1,)), ((0,), (0,))),
                precision=jax.lax.Precision.HIGHEST)
            feat_r = attr[..., 0].astype(jnp.int32)
            thr_r = attr[..., 1].astype(jnp.int32)
            dl_r = attr[..., 2] > 0.5
            nan_r = attr[..., 3].astype(jnp.int32)
            oh_f = feat_r[:, :, None] == col_ids         # [T, n, F]
            col = jnp.sum(jnp.where(oh_f, bins_i[None], 0), axis=2)
            go_left = jnp.where(col == nan_r, dl_r, col <= thr_r)
            if has_cat:
                W = stacked["cat_bitset"].shape[2]
                oh_w = ((col >> 5)[..., None]
                        == jnp.arange(W, dtype=jnp.int32))
                lo16 = jnp.sum(
                    jnp.where(oh_w, attr[..., 7:7 + W], 0.0),
                    axis=2).astype(jnp.uint32)
                hi16 = jnp.sum(
                    jnp.where(oh_w, attr[..., 7 + W:7 + 2 * W], 0.0),
                    axis=2).astype(jnp.uint32)
                word = lo16 | (hi16 << jnp.uint32(16))
                cat_left = ((word >> (col & 31).astype(jnp.uint32))
                            & jnp.uint32(1)) > 0
                go_left = jnp.where(attr[..., 6] > 0.5, cat_left,
                                    go_left)
            nxt = jnp.where(go_left, attr[..., 4], attr[..., 5]) \
                .astype(jnp.int32)
            return jnp.where(node >= 0, nxt, node)

        node = jax.lax.while_loop(cond, body, node0)
        leaf = (-node - 1).astype(jnp.int32)
        L = stacked["leaf_value"].shape[1]
        oh_leaf = (leaf[..., None]
                   == jnp.arange(L, dtype=jnp.int32)).astype(jnp.float32)
        vals = jax.lax.dot_general(
            oh_leaf, stacked["leaf_value"][..., None],
            dimension_numbers=(((2,), (1,)), ((0,), (0,))),
            precision=jax.lax.Precision.HIGHEST)[..., 0]
        return vals, leaf

    # gather formulation: batched per-(tree, row) table lookups — the
    # O(n)-memory path (CPU backend, or trees wider than the one-hot
    # cutoff; identical routing math, identical results)
    thr_t = stacked["threshold_bin"].astype(jnp.int32)
    dl_t = stacked["default_left"]
    lc_t = stacked["left_child"].astype(jnp.int32)
    rc_t = stacked["right_child"].astype(jnp.int32)

    def take(a, idx):                                    # [T, Ln] x [T, n]
        return jnp.take_along_axis(a, idx, axis=1)

    def body(node):
        nd = jnp.maximum(node, 0)                        # [T, n]
        feat = take(sf, nd)
        thr = take(thr_t, nd)
        dleft = take(dl_t, nd)
        col = jax.vmap(lambda f: jnp.take_along_axis(
            bins, f[:, None], axis=1)[:, 0])(feat).astype(jnp.int32)
        missing = feat_has_nan[feat] & (col == feat_num_bin[feat] - 1)
        go_left = jnp.where(missing, dleft, col <= thr)
        if has_cat:
            bitset = jnp.take_along_axis(
                stacked["cat_bitset"], nd[..., None], axis=1)  # [T,n,W]
            word = jnp.take_along_axis(
                bitset, (col >> 5)[..., None], axis=2)[..., 0]
            cat_left = ((word >> (col & 31).astype(jnp.uint32))
                        & jnp.uint32(1)) > 0
            go_left = jnp.where(take(stacked["is_cat"], nd), cat_left,
                                go_left)
        nxt = jnp.where(go_left, take(lc_t, nd), take(rc_t, nd))
        return jnp.where(node >= 0, nxt, node)

    node = jax.lax.while_loop(cond, body, node0)
    leaf = (-node - 1).astype(jnp.int32)
    vals = jnp.take_along_axis(stacked["leaf_value"], leaf, axis=1)
    return vals, leaf


def default_formulation(num_nodes: int) -> str:
    """Backend-appropriate level-step formulation: the batched one-hot
    matmul on TPU (gathers are scalar-unit poison, docs/perf.md), the
    batched gather elsewhere and for trees wider than the one-hot
    cutoff."""
    return ("onehot" if num_nodes <= ONEHOT_MAX_NODES
            and jax.default_backend() == "tpu" else "gather")


def _forest_traverse(stacked, bins, feat_num_bin, feat_has_nan,
                     formulation):
    """Level-synchronous traversal with the one-hot operand bounded:
    when T * n * max(Ln, F) would exceed LEVEL_ONEHOT_BUDGET, trees
    scan in equal power-of-two blocks, each block level-synchronous."""
    T, Ln = stacked["split_feature"].shape
    n, F = bins.shape
    if formulation == "onehot":
        width = max(Ln, F, 1)
        cap = max(LEVEL_ONEHOT_BUDGET // max(n * width, 1), 1)
        if T > cap:
            # largest divisor of T within budget (lax.scan needs equal
            # blocks; worst case tb=1 degrades to a per-tree scan,
            # never an unbounded operand)
            tb = next(d for d in range(min(cap, T), 0, -1) if T % d == 0)
        else:
            tb = T
        if tb < T:
            blocks = jax.tree.map(
                lambda a: a.reshape((T // tb, tb) + a.shape[1:]),
                stacked)

            def blk(carry, s):
                return carry, _level_traverse(
                    s, bins, feat_num_bin, feat_has_nan, "onehot")

            _, (vals, leaf) = jax.lax.scan(blk, None, blocks)
            return vals.reshape(T, n), leaf.reshape(T, n)
    return _level_traverse(stacked, bins, feat_num_bin, feat_has_nan,
                           formulation)


def _class_accumulate(vals, class_index, num_class):
    """Per-class score sums in the EXACT sequential tree order the
    reference per-tree scan used (f32 addition is order-sensitive; this
    keeps mode="level" bit-identical to mode="scan")."""
    n = vals.shape[1]

    def body(carry, xs):
        v, cls = xs
        return carry.at[:, cls].add(v), None

    init = jnp.zeros((n, num_class), jnp.float32)
    scores, _ = jax.lax.scan(body, init, (vals, class_index))
    return scores


def _forest_predict_scan(stacked, bins, feat_num_bin, feat_has_nan,
                         class_index, num_class):
    """The original per-tree lax.scan fold (reference traversal order)."""
    n = bins.shape[0]

    def body(carry, xs):
        tree, cls = xs
        vals, leaf = tree_predict_binned(tree, bins, feat_num_bin,
                                         feat_has_nan)
        return carry.at[:, cls].add(vals), leaf

    init = jnp.zeros((n, num_class), jnp.float32)
    scores, leaves = jax.lax.scan(body, init, (stacked, class_index))
    return scores, leaves


@functools.partial(jax.jit,
                   static_argnames=("num_class", "mode", "formulation"))
def _forest_predict_impl(stacked, bins, feat_num_bin, feat_has_nan,
                         class_index, num_class, mode, formulation):
    if mode == "scan":
        return _forest_predict_scan(stacked, bins, feat_num_bin,
                                    feat_has_nan, class_index, num_class)
    vals, leaves = _forest_traverse(stacked, bins, feat_num_bin,
                                    feat_has_nan, formulation)
    return _class_accumulate(vals, class_index, num_class), leaves


def onehot_bounded_rows(width: int, floor: int = 1024) -> int:
    """Largest row count whose ``[rows, width]`` one-hot style operand
    stays within LEVEL_ONEHOT_BUDGET — the same peak-operand bound the
    level traversal applies per tree block, reused by the SHAP scan's
    chunk planner to cap its ``[rows, L*D]`` path-pick operand."""
    return max(int(LEVEL_ONEHOT_BUDGET // max(int(width), 1)), int(floor))


def predict_program_cache_size() -> int:
    """Number of distinct compiled forest-predict programs this process
    holds — the quantity the batch-shape bucketing bounds (tests pin it
    via utils/debug.py)."""
    return _forest_predict_impl._cache_size()


# ---------------------------------------------------------------------------
# Tree-sharded traversal (serve/shard.py places the operands)
# ---------------------------------------------------------------------------
# One jitted traversal per (mesh, formulation): inputs arrive committed
# — stacked arrays NamedSharding-split on the tree axis, rows/feature
# tables replicated — and out_shardings forces the per-(tree, row) leaf
# VALUES back to REPLICATED, so the class accumulation below replays
# the exact global sequential tree order on gathered values. That is
# the bit-identity argument: per-tree traversal is pure selection
# (exact under any batch split), and the f32 score accumulation runs
# the same scan over the same values in the same order as the
# single-device path — no cross-shard partial sums whose reassociation
# could flip low bits. The [T, n] leaf INDICES stay tree-sharded: only
# the rare pred_leaf request reads them (host fetch gathers then), and
# replicating them would all-gather T*n int32 per warm dispatch for
# nothing.
_SHARDED_TRAVERSE: Dict[Tuple, Any] = {}


def _sharded_traverse_fn(mesh, formulation: str):
    key = (mesh, formulation)
    fn = _SHARDED_TRAVERSE.get(key)
    if fn is None:
        from jax.sharding import NamedSharding, PartitionSpec
        repl = NamedSharding(mesh, PartitionSpec())
        leaf_shard = NamedSharding(
            mesh, PartitionSpec(mesh.axis_names[0], None))

        def run(stacked, bins, feat_num_bin, feat_has_nan):
            # _level_traverse directly (not _forest_traverse): the
            # budget tree-blocking would reshape the sharded axis; the
            # per-device operand is already 1/D of the forest
            return _level_traverse(stacked, bins, feat_num_bin,
                                   feat_has_nan, formulation)

        fn = jax.jit(run, out_shardings=(repl, leaf_shard))
        _SHARDED_TRAVERSE[key] = fn
    return fn


@functools.partial(jax.jit, static_argnames=("num_class",))
def _class_accumulate_jit(vals, class_index, num_class):
    return _class_accumulate(vals, class_index, num_class)


def forest_predict_sharded(stacked: Dict[str, jax.Array],
                           bins: jax.Array, feat_num_bin: jax.Array,
                           feat_has_nan: jax.Array,
                           class_index: jax.Array, num_class: int,
                           mesh,
                           formulation: Optional[str] = None
                           ) -> Tuple[jax.Array, jax.Array]:
    """Tree-sharded forest predict: same signature and bit-identical
    outputs as :func:`forest_predict_binned`, for stacked arrays
    already placed with their ``[T]`` axis NamedSharding-split over
    ``mesh`` (serve/shard.py ``place_tree_sharded``) and everything
    else replicated. Always level-synchronous — the per-tree scan mode
    has no tree axis to shard."""
    T, Ln = stacked["split_feature"].shape
    if formulation is None:
        formulation = default_formulation(Ln)
    if formulation == "onehot":
        # no tree-blocking on the sharded path (_forest_traverse's
        # reshape would cut the sharded axis), so bound the one-hot
        # operand the other way: past the per-DEVICE budget, fall back
        # to the memory-lean gather step instead of materializing an
        # unbounded [T/D, n, Ln] membership tensor
        n, F = bins.shape
        per_dev_T = -(-T // max(int(mesh.devices.size), 1))
        if per_dev_T * n * max(Ln, F, 1) > LEVEL_ONEHOT_BUDGET:
            formulation = "gather"
    vals, leaves = _sharded_traverse_fn(mesh, formulation)(
        stacked, bins, feat_num_bin, feat_has_nan)
    scores = _class_accumulate_jit(vals, class_index, num_class)
    return scores, leaves


def forest_predict_binned(stacked: Dict[str, jax.Array], bins: jax.Array,
                          feat_num_bin: jax.Array, feat_has_nan: jax.Array,
                          class_index: jax.Array,
                          num_class: int,
                          mode: Optional[str] = None,
                          formulation: Optional[str] = None,
                          mesh=None
                          ) -> Tuple[jax.Array, jax.Array]:
    """Sum leaf outputs of a stacked forest into per-class raw scores.

    Args:
      stacked: tree arrays with a leading ``[T]`` axis (trees padded to a
        common ``num_leaves`` capacity).
      class_index: ``[T]`` int32 — class each tree contributes to
        (``t % num_class`` for multiclass round-robin, zeros for K=1).
      mode: "level" (default; tree-parallel level-synchronous) or
        "scan" (the original per-tree fold — kept as the reference path
        and the ``tpu_predict_parallel_trees=false`` escape hatch).
      formulation: level-step kind, "onehot" | "gather"; None picks by
        backend and tree width (``default_formulation``).
      mesh: when set, the stacked arrays arrive tree-axis sharded over
        this mesh (serve/shard.py) and the sharded level path runs —
        it takes precedence over ``mode`` (the per-tree scan has no
        tree axis to shard).

    Returns:
      (raw scores ``[n, num_class]``, leaf indices ``[T, n]``)

    The whole program is jitted; its compile cache is keyed on the
    operand shapes, which the engine keeps bounded via stacked-forest
    padding and batch-shape bucketing (boosting/gbdt.py::predict).
    """
    if mode is None or mode == "auto":
        mode = "level"
    if mesh is not None:
        mode = "level"
    if mode == "scan":
        formulation = None
    elif formulation is None:
        formulation = default_formulation(stacked["split_feature"].shape[1])

    def dispatch():
        if mesh is not None:
            return forest_predict_sharded(
                stacked, bins, feat_num_bin, feat_has_nan, class_index,
                num_class, mesh, formulation)
        return _forest_predict_impl(stacked, bins, feat_num_bin,
                                    feat_has_nan, class_index, num_class,
                                    mode, formulation)

    from .. import obs
    if not obs.any_enabled():
        return dispatch()
    # serving dispatch span: wall time covers trace/compile + enqueue
    # (execution is async — completion shows up where the caller blocks
    # on the device->host copy)
    with obs.span("predict/forest_dispatch", rows=int(bins.shape[0]),
                  trees=int(stacked["split_feature"].shape[0]),
                  mode=("sharded" if mesh is not None else mode)):
        return dispatch()
