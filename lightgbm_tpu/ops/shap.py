"""TreeSHAP feature contributions (predict_contrib).

Reference: ``Tree::PredictContrib`` / TreeSHAP recursion (src/io/tree.cpp
`TreeSHAP` + include/LightGBM/tree.h PathElement, UNVERIFIED — empty
mount, see SURVEY.md banner). Implements the Lundberg & Lee
path-dependent TreeSHAP: exact Shapley values under the tree's own
cover distribution; last output column is the expected value (bias).

Two implementations:

- :func:`forest_shap_batch` (default path) — rows-vectorized and
  device-resident: per-node routing decisions are evaluated once on
  the host (exact f64 threshold compares, NaN defaults, categorical
  bitsets) and bit-packed; everything else — per-leaf path matching,
  the SHAP ``extend`` recurrences, the per-feature unwound sums — runs
  as one jitted ``lax.scan`` over the stacked per-tree path tables
  (matmuls + elementwise, no per-row gathers). The enabling identity:
  extending a decision path with ``(zero=1, one=1)`` dummy elements
  leaves every unwound sum invariant (verified numerically), so all
  leaf paths pad to ONE uniform length and the recurrences need no
  masking. The reference's ``PredictContrib`` parallelizes the same
  per-row recursion over OpenMP threads; this is its MXU/VPU shape.
- :func:`tree_shap_batch` — the original per-row recursion, kept as
  the slow exact oracle (f64) the vectorized path is tested against.

Measured (100k rows x 100 nl=127 trees, v5e + 1-core host): recursive
~17 h extrapolated (122.7 s for 200 rows) -> vectorized 28.8 s
(~2000x), of which ~10 s is the host routing-bit pass (vectorized
numpy; scales with host cores elsewhere). Precision: CPU backend runs
f64 (matches the oracle to ~1e-13); TPU runs f32 with the scatter
matmul at HIGHEST precision — measured ~3e-5 vs the f64 oracle and
~5e-6 local-accuracy error at the 100-tree flagship shape (use
force_f64 on a CPU backend for exact values).
"""
from __future__ import annotations

import numpy as np


class _Path:
    """Decision path state: parallel arrays of (feature, zero, one, w)."""

    __slots__ = ("feature", "zero_fraction", "one_fraction", "pweight")

    def __init__(self, depth_cap: int):
        self.feature = np.zeros(depth_cap, dtype=np.int64)
        self.zero_fraction = np.zeros(depth_cap, dtype=np.float64)
        self.one_fraction = np.zeros(depth_cap, dtype=np.float64)
        self.pweight = np.zeros(depth_cap, dtype=np.float64)

    def copy(self, length: int) -> "_Path":
        p = _Path(len(self.feature))
        p.feature[:length] = self.feature[:length]
        p.zero_fraction[:length] = self.zero_fraction[:length]
        p.one_fraction[:length] = self.one_fraction[:length]
        p.pweight[:length] = self.pweight[:length]
        return p


def _extend(p: _Path, length: int, zero: float, one: float,
            feat: int) -> int:
    p.feature[length] = feat
    p.zero_fraction[length] = zero
    p.one_fraction[length] = one
    p.pweight[length] = 1.0 if length == 0 else 0.0
    for i in range(length - 1, -1, -1):
        p.pweight[i + 1] += one * p.pweight[i] * (i + 1) / (length + 1)
        p.pweight[i] = zero * p.pweight[i] * (length - i) / (length + 1)
    return length + 1


def _unwind(p: _Path, length: int, idx: int) -> int:
    length -= 1
    one = p.one_fraction[idx]
    zero = p.zero_fraction[idx]
    n = p.pweight[length]
    for i in range(length - 1, -1, -1):
        if one != 0.0:
            t = p.pweight[i]
            p.pweight[i] = n * (length + 1) / ((i + 1) * one)
            n = t - p.pweight[i] * zero * (length - i) / (length + 1)
        else:
            p.pweight[i] = p.pweight[i] * (length + 1) / (
                zero * (length - i))
    for i in range(idx, length):
        p.feature[i] = p.feature[i + 1]
        p.zero_fraction[i] = p.zero_fraction[i + 1]
        p.one_fraction[i] = p.one_fraction[i + 1]
    return length


def _unwound_sum(p: _Path, length: int, idx: int) -> float:
    one = p.one_fraction[idx]
    zero = p.zero_fraction[idx]
    total = 0.0
    n = p.pweight[length - 1]
    for i in range(length - 2, -1, -1):
        if one != 0.0:
            t = n * length / ((i + 1) * one)
            total += t
            n = p.pweight[i] - t * zero * (length - 1 - i) / length
        else:
            total += p.pweight[i] * length / (zero * (length - 1 - i))
    return total


def _node_cover(tree, node: int) -> float:
    if node < 0:
        return float(tree.leaf_count[-node - 1])
    return float(tree.internal_count[node])


def _route_left(tree, node: int, v: np.ndarray) -> np.ndarray:
    """Numerical toward-left routing for a batch of values at one node
    — the SAME ``node_missing_type`` semantics as
    ``Tree._leaf_index_raw`` (mt=none converts NaN to 0.0; mt=zero
    routes |x|<=1e-35 and NaN by default direction; mt=nan routes NaN
    by default direction), so SHAP hot paths agree with prediction."""
    thr = tree.threshold_real[node]
    dl = bool(tree.default_left[node])
    miss = np.isnan(v)
    nmt = getattr(tree, "node_missing_type", None)
    if nmt is None:
        return np.where(miss, dl, v <= thr)
    mt = int(nmt[node])
    if mt == 2:
        return np.where(miss, dl, v <= thr)
    v0 = np.where(miss, 0.0, v)
    if mt == 1:
        return np.where(miss | (np.abs(v0) <= 1e-35), dl, v0 <= thr)
    return v0 <= thr


def _tree_shap_row(tree, x: np.ndarray, phi: np.ndarray) -> None:
    max_depth = int(tree.leaf_depths().max()) + 2 if tree.num_leaves > 1 \
        else 1

    def recurse(node: int, p: _Path, length: int, zero: float, one: float,
                feat: int) -> None:
        length = _extend(p, length, zero, one, feat)
        if node < 0:  # leaf
            leaf_val = float(tree.leaf_value[-node - 1])
            for i in range(1, length):
                w = _unwound_sum(p, length, i)
                phi[p.feature[i]] += w * (p.one_fraction[i]
                                          - p.zero_fraction[i]) * leaf_val
            return
        f = int(tree.split_feature[node])
        v = x[f]
        thr = tree.threshold_real[node]
        if tree.is_categorical is not None and tree.is_categorical[node]:
            go_left = bool(tree._cat_go_left(np.array([thr]),
                                             np.array([v]))[0])
        else:
            go_left = bool(_route_left(tree, node, np.array([v]))[0])
        hot = int(tree.left_child[node] if go_left
                  else tree.right_child[node])
        cold = int(tree.right_child[node] if go_left
                   else tree.left_child[node])
        cover = _node_cover(tree, node)
        hot_r = _node_cover(tree, hot) / cover if cover > 0 else 0.0
        cold_r = _node_cover(tree, cold) / cover if cover > 0 else 0.0
        iz, io = 1.0, 1.0
        k = -1
        for i in range(1, length):
            if p.feature[i] == f:
                k = i
                break
        if k >= 0:
            iz = p.zero_fraction[k]
            io = p.one_fraction[k]
            length = _unwind(p, length, k)
        recurse(hot, p.copy(length), length, iz * hot_r, io, f)
        recurse(cold, p.copy(length), length, iz * cold_r, 0.0, f)

    if tree.num_leaves <= 1:
        return
    recurse(0, _Path(max_depth + 2), 0, 1.0, 1.0, -1)


def tree_shap_batch(tree, X: np.ndarray, n_feat: int) -> np.ndarray:
    """SHAP contributions for one tree over a batch.

    Returns ``[n, n_feat + 1]``; the last column is the tree's expected
    value (bias term).
    """
    n = X.shape[0]
    out = np.zeros((n, n_feat + 1), dtype=np.float64)
    if tree.num_leaves <= 1:
        out[:, -1] = tree.leaf_value[0] if len(tree.leaf_value) else 0.0
        return out
    total = float(tree.leaf_count.sum())
    expected = float(np.sum(tree.leaf_value[:tree.num_leaves]
                            * tree.leaf_count[:tree.num_leaves]) / total) \
        if total > 0 else 0.0
    for r in range(n):
        phi = np.zeros(n_feat + 1, dtype=np.float64)
        _tree_shap_row(tree, X[r], phi)
        out[r, :n_feat] = phi[:n_feat]
        out[r, -1] = expected
    return out


# ---------------------------------------------------------------------------
# rows-vectorized forest TreeSHAP (round 4)
# ---------------------------------------------------------------------------
def _walk_paths(tree):
    """DFS all root->leaf paths. Returns a list over leaves of
    ``(leaf_idx, entries)`` where entries = [(node, toward_left,
    feature, cover_ratio), ...] along the path."""
    out = []
    stack = [(0, [])]
    while stack:
        node, path = stack.pop()
        if node < 0:
            out.append((-node - 1, path))
            continue
        cover = _node_cover(tree, node)
        for child, toward_left in ((int(tree.left_child[node]), True),
                                   (int(tree.right_child[node]), False)):
            r = (_node_cover(tree, child) / cover) if cover > 0 else 0.0
            stack.append((child, path + [(node, toward_left,
                                          int(tree.split_feature[node]),
                                          r)]))
    return out


def _path_tables(tree, L, D, U, n_feat, paths=None):
    """Host prep: padded per-tree path tables for the device scan.

    Returns dict of arrays — entry level: node_id/dir/active [L, D],
    slot membership M [L, D, U]; slot level: z/slot_feat [L, U];
    leaf values [L]; expected value scalar. Pad slots carry the
    (z=1, o=1) dummy identity, so they contribute exactly zero.
    """
    node_id = np.zeros((L, D), np.int32)
    dirs = np.zeros((L, D), np.float32)
    e_act = np.zeros((L, D), np.float32)
    M = np.zeros((L, D, U), np.float32)
    z = np.ones((L, U), np.float64)
    s_act = np.zeros((L, U), bool)
    s_feat = np.full((L, U), n_feat, np.int32)   # pad -> bias column
    vleaf = np.zeros(L, np.float64)
    if tree.num_leaves > 1:
        if paths is None:
            paths = _walk_paths(tree)
        for leaf, entries in paths:
            slots = {}
            for e, (nd, tl, f, r) in enumerate(entries):
                node_id[leaf, e] = nd
                dirs[leaf, e] = 1.0 if tl else 0.0
                e_act[leaf, e] = 1.0
                u = slots.setdefault(f, len(slots))
                M[leaf, e, u] = 1.0
                z[leaf, u] = z[leaf, u] * r if s_act[leaf, u] else r
                s_act[leaf, u] = True
                s_feat[leaf, u] = f
            vleaf[leaf] = float(tree.leaf_value[leaf])
    total = float(tree.leaf_count[:tree.num_leaves].sum())
    expected = (float(np.sum(tree.leaf_value[:tree.num_leaves]
                             * tree.leaf_count[:tree.num_leaves]) / total)
                if total > 0 else
                (float(tree.leaf_value[0]) if len(tree.leaf_value)
                 else 0.0))
    return dict(node_id=node_id, dirs=dirs, e_act=e_act, M=M,
                z=z, s_act=s_act.astype(np.float32), s_feat=s_feat,
                vleaf=vleaf, expected=np.float64(expected))


def _host_cond_bits(tree, X, NN):
    """Per-node toward-left routing of every row, bit-packed
    ``[n, ceil(NN/8)]``. Exact f64 compares + the same NaN/categorical
    semantics as the recursive implementation — all nodes of a tree in
    one vectorized pass (the per-node loop was the 100-tree
    bottleneck, 25 of 33 s at 100k rows)."""
    n = X.shape[0]
    nn = tree.num_nodes
    nb = max((NN + 7) // 8, 1)
    if nn == 0:
        return np.zeros((n, nb), np.uint8)
    sf = np.asarray(tree.split_feature[:nn], np.int64)
    thr = np.asarray(tree.threshold_real[:nn], np.float64)
    dl = np.asarray(tree.default_left[:nn], bool)
    V = X[:, sf]                                       # [n, nn]
    miss = np.isnan(V)
    nmt = getattr(tree, "node_missing_type", None)
    if nmt is None:
        cl = np.where(miss, dl[None, :], V <= thr[None, :])
    else:
        # node_missing_type semantics, vectorized (see _route_left)
        mt = np.asarray(nmt[:nn])[None, :]
        V0 = np.where(miss, 0.0, V)
        zeroish = miss | (np.abs(V0) <= 1e-35)
        cl = np.where(
            mt == 2, np.where(miss, dl[None, :], V <= thr[None, :]),
            np.where(mt == 1, np.where(zeroish, dl[None, :],
                                       V0 <= thr[None, :]),
                     V0 <= thr[None, :]))
    if tree.is_categorical is not None:
        for nd in np.flatnonzero(tree.is_categorical[:nn]):
            cl[:, nd] = tree._cat_go_left(
                np.full(n, tree.threshold_real[nd]), X[:, sf[nd]])
    if nn < nb * 8:
        cl = np.concatenate(
            [cl, np.zeros((n, nb * 8 - nn), bool)], axis=1)
    return np.packbits(cl, axis=1, bitorder="little")


import functools as _functools


def _dims_from(trees, all_paths):
    L = max((t.num_leaves for t in trees), default=1)
    D = max((int(t.leaf_depths().max()) if t.num_leaves > 1 else 0
             for t in trees), default=0)
    NN = max((t.num_nodes for t in trees), default=0)
    U = max((len({f for _, _, f, _ in es})
             for paths in all_paths for _, es in paths), default=0)
    return L, D, U, NN


def shap_path_dims(trees):
    """Actual table dimensions ``(L, D, U, NN)`` for a tree list, plus
    the DFS paths (the U computation needs the full root->leaf walk, so
    callers reuse it for :func:`build_shap_tables` instead of walking
    twice)."""
    all_paths = [_walk_paths(t) if t.num_leaves > 1 else []
                 for t in trees]
    return _dims_from(trees, all_paths), all_paths


def _inert_tables(L, D, U, n_feat, K):
    """Pad-tree tables: e_act=0 makes every entry match, z=1/s_act=0
    makes every slot the (zero=1, one=1) dummy, so contrib = total *
    (o - z) == 0 exactly, and cls=0 zeroes the class scatter on top —
    a pad tree contributes nothing in any dtype."""
    return dict(node_id=np.zeros((L, D), np.int32),
                dirs=np.zeros((L, D), np.float32),
                e_act=np.zeros((L, D), np.float32),
                M=np.zeros((L, D, U), np.float32),
                z=np.ones((L, U), np.float64),
                s_act=np.zeros((L, U), np.float32),
                s_feat=np.full((L, U), n_feat, np.int32),
                vleaf=np.zeros(L, np.float64),
                expected=np.float64(0.0),
                cls=np.zeros(K, np.float32))


def build_shap_tables(trees, n_feat, K, dims=None, pad_trees=0,
                      paths=None):
    """Host prep for the whole forest, hoisted out of
    :func:`forest_shap_batch` so callers (the engine's device-resident
    SHAP cache, ``HostModel``'s per-slice cache) can build once and
    reuse across calls.

    Returns ``(stacked, (L, D, U, NN))`` where ``stacked`` maps table
    name -> ``[T + pad_trees, ...]`` numpy array, or ``None`` when
    there is nothing to scan (empty / all-stump forest — callers take
    the bias-only path). ``dims`` caps are lower bounds: actual tree
    dims are maxed in, so bucketed callers get stable shapes without
    ever truncating a tree. ``pad_trees`` appends inert pad trees
    (see :func:`_inert_tables`) so the stacked tree axis can be padded
    to a pow2 / mesh-divisible length."""
    if not trees or all(t.num_leaves <= 1 for t in trees):
        return None
    if paths is None:
        actual, paths = shap_path_dims(trees)
    else:
        actual = _dims_from(trees, paths)
    if dims is None:
        L, D, U, NN = actual
    else:
        L, D, U, NN = (max(a, b) for a, b in zip(actual, dims))
    tables = []
    for ti, (t, tree_paths) in enumerate(zip(trees, paths)):
        tab = _path_tables(t, L, D, U, n_feat, paths=tree_paths)
        cls = np.zeros(K, np.float32)
        cls[ti % K] = 1.0
        tab["cls"] = cls
        tables.append(tab)
    if pad_trees:
        tables.extend([_inert_tables(L, D, U, n_feat, K)] *
                      int(pad_trees))
    stacked = {k: np.stack([tab[k] for tab in tables])
               for k in tables[0]}
    return stacked, (L, D, U, NN)


def stump_only_contrib(trees, n, n_feat, K):
    """Bias-only output for forests with no splits anywhere — nothing
    to scan, every row gets each stump's constant in the bias column."""
    out = np.zeros((n, K, n_feat + 1), np.float64)
    for i, t in enumerate(trees):
        out[:, i % K, -1] += (float(t.leaf_value[0])
                              if len(t.leaf_value) else 0.0)
    return out


def _scan_body(D, U, NN, n_feat, K, dtype):
    """The per-chunk forest scan, unjitted — shared by the
    single-device kernel (:func:`_scan_kernel`) and the tree-sharded
    wrapper (:func:`sharded_scan_kernel`), which runs it per shard and
    psums the per-tree phi sums (order-free per feature)."""
    import jax
    import jax.numpy as jnp

    def one_tree(phi, t):
        cb = t["cond"]                                  # [n, nb] uint8
        n = cb.shape[0]
        idx = jnp.arange(NN, dtype=jnp.int32)
        cond = ((cb[:, idx >> 3] >> (idx & 7)) & 1).astype(dtype)
        # path-entry match: did this row go the path's way at each
        # entry's node? one-hot matmul (0/1 exact at any precision)
        oh_node = (t["node_id"].reshape(-1)[None, :]
                   == idx[:, None]).astype(dtype)       # [NN, L*D]
        pick = jax.lax.dot_general(
            cond, oh_node, (((1,), (0,)), ((), ())),
            preferred_element_type=dtype)               # [n, L*D]
        L = t["node_id"].shape[0]
        pick = pick.reshape(n, L, D)
        dirs = t["dirs"][None]
        match = jnp.where(t["e_act"][None] > 0,
                          jnp.where(dirs > 0, pick, 1.0 - pick), 1.0)
        # o[slot] = AND over the slot's entries (miss count == 0)
        miss = jnp.einsum("nld,ldu->nlu", 1.0 - match, t["M"],
                          preferred_element_type=dtype)
        o = jnp.where(t["s_act"][None] > 0, (miss < 0.5).astype(dtype),
                      jnp.asarray(1.0, dtype))          # [n, L, U]
        z = t["z"].astype(dtype)[None]                  # [1, L, U]
        # SHAP extend: uniform length (pads are (1,1) dummies — an
        # exact invariance of the unwound sums), dummy root first.
        # The inner position loop is one shifted-add per path element
        # on the whole [n, L, U+2] coefficient array.
        Lf = U + 1
        pw = jnp.zeros((n, L, U + 2), dtype).at[:, :, 0].set(1.0)
        pos = jnp.arange(U + 2, dtype=dtype)
        for j in range(U):
            length = j + 1
            wz = jnp.clip((length - pos) / (length + 1.0), 0.0, None)
            wo = pos / (length + 1.0)
            shifted = jnp.concatenate(
                [jnp.zeros((n, L, 1), dtype), pw[:, :, :-1]], axis=2)
            pw = (z[:, :, j:j + 1] * pw * wz
                  + o[:, :, j:j + 1] * shifted * wo)
        # unwound sums for ALL slots at once: the backward recurrence
        # is sequential in path position but independent across slots
        zs, os_ = z, o                                  # [*, L, U]
        hot = os_ > 0
        total = jnp.zeros((n, L, U), dtype)
        nrun = jnp.broadcast_to(pw[:, :, Lf - 1:Lf], (n, L, U))
        for i in range(Lf - 2, -1, -1):
            pwi = pw[:, :, i:i + 1]
            t1 = nrun * Lf / ((i + 1.0) * jnp.maximum(os_, 1e-30))
            t0 = pwi * Lf / (jnp.maximum(zs, 1e-30) * (Lf - 1.0 - i))
            total = total + jnp.where(hot, t1, t0)
            nrun = jnp.where(hot, pwi - t1 * zs * ((Lf - 1.0 - i) / Lf),
                             nrun)
        contrib = total * (os_ - zs)                    # [n, L, U]
        contrib = contrib * t["vleaf"].astype(dtype)[None, :, None]
        oh_feat = (t["s_feat"].reshape(-1)[:, None]
                   == jnp.arange(n_feat + 1)[None, :]).astype(dtype)
        # HIGHEST precision: contrib entries are large with cancelling
        # signs while their per-feature sums are small — the TPU's
        # default bf16 operand rounding here cost 0.6 ABSOLUTE error
        # (measured); with exact f32 products the sum is exact-f32
        phi_t = jax.lax.dot_general(
            contrib.reshape(n, L * U), oh_feat,
            (((1,), (0,)), ((), ())),
            precision=jax.lax.Precision.HIGHEST,
            preferred_element_type=dtype)               # [n, n_feat+1]
        phi_t = phi_t.at[:, n_feat].add(t["expected"].astype(dtype))
        phi = phi + t["cls"].astype(dtype)[None, :, None] \
            * phi_t[:, None, :]
        return phi, 0.0

    def run(stacked):
        n = stacked["cond"].shape[1]
        phi0 = jnp.zeros((n, K, n_feat + 1), dtype)
        phi, _ = jax.lax.scan(one_tree, phi0, stacked)
        return phi

    return run


@_functools.lru_cache(maxsize=32)
def _scan_kernel(D, U, NN, n_feat, K, dtype):
    """Jitted single-device forest scan (shapes static; cached so
    repeated pred_contrib calls reuse the compiled executable)."""
    import jax
    return jax.jit(_scan_body(D, U, NN, n_feat, K, dtype))


# (mesh, shape signature) -> jitted sharded scan; same lifetime pattern
# as ops/predict.py's _SHARDED_TRAVERSE (meshes are few and long-lived)
_SHARDED_SCAN: dict = {}


def sharded_scan_kernel(mesh, D, U, NN, n_feat, K, dtype):
    """Tree-sharded forest scan over ``mesh``'s tree axis.

    Each device scans only its shard of the stacked ``[T, ...]`` path
    tables (and the routing bits, sharded the same way), then one
    ``psum`` over the tree axis combines the per-shard phi sums —
    per-tree contributions are order-free per feature, so the reduce is
    exact in f64 and only reassociates an already-documented-tolerance
    sum in f32. Output is replicated (like ``forest_predict_sharded``).
    """
    key = (mesh, D, U, NN, n_feat, K, dtype)
    fn = _SHARDED_SCAN.get(key)
    if fn is None:
        import jax
        from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec
        from ..serve.shard import TREE_AXIS

        body = _scan_body(D, U, NN, n_feat, K, dtype)

        def local(stacked):
            return jax.lax.psum(body(stacked), TREE_AXIS)

        def run(stacked):
            specs = {k: PartitionSpec(TREE_AXIS) for k in stacked}
            return shard_map(local, mesh=mesh, in_specs=(specs,),
                             out_specs=PartitionSpec())(stacked)

        fn = jax.jit(run)
        _SHARDED_SCAN[key] = fn
    return fn


def forest_shap_batch(trees, X, n_feat, K=1, row_chunk=131072,
                      force_f64=None, tables=None):
    """Vectorized TreeSHAP over a whole forest: ``[n, K, n_feat+1]``.

    ``force_f64``: run the scan in float64. Defaults to True on CPU
    backends; on a TPU host setting it True routes the scan to the
    host CPU device (slower, exact) — the escape hatch for exact-f64
    parity with stock LightGBM's double-precision TreeSHAP.

    ``tables``: a prebuilt :func:`build_shap_tables` result for these
    exact trees — callers that hold a table cache (``HostModel``)
    skip the per-call path walk entirely.
    """
    import jax

    X = np.ascontiguousarray(np.asarray(X, np.float64))
    n = X.shape[0]
    if tables is None:
        tables = build_shap_tables(trees, n_feat, K)
    if tables is None:
        return stump_only_contrib(trees, n, n_feat, K)
    stacked, (L, D, U, NN) = tables

    if force_f64 is None:
        force_f64 = jax.default_backend() == "cpu"
    import contextlib
    ctx = contextlib.ExitStack()
    if force_f64:
        # jax.enable_x64 only exists on newer jax; the pinned runtime
        # ships it under jax.experimental
        x64_ctx = getattr(jax, "enable_x64", None)
        if x64_ctx is None:
            from jax.experimental import enable_x64 as x64_ctx
        ctx.enter_context(x64_ctx())
        if jax.default_backend() != "cpu":
            ctx.enter_context(
                jax.default_device(jax.devices("cpu")[0]))
    out = np.zeros((n, K, n_feat + 1), np.float64)
    with ctx:
        import jax.numpy as jnp
        dtype = jnp.float64 if force_f64 else jnp.float32
        run = _scan_kernel(D, U, NN, n_feat, K,
                           np.dtype(dtype).name)
        dev = {k: jnp.asarray(v) for k, v in stacked.items()
               if k != "cond"}
        for lo in range(0, n, row_chunk):
            hi = min(lo + row_chunk, n)
            conds = np.stack([_host_cond_bits(t, X[lo:hi], NN)
                              for t in trees])
            dev["cond"] = jnp.asarray(conds)
            out[lo:hi] = np.asarray(run(dev), np.float64)
    return out
