"""TreeSHAP feature contributions (predict_contrib).

Reference: ``Tree::PredictContrib`` / TreeSHAP recursion (src/io/tree.cpp
`TreeSHAP` + include/LightGBM/tree.h PathElement, UNVERIFIED — empty
mount, see SURVEY.md banner). Implements the Lundberg & Lee
path-dependent TreeSHAP: exact Shapley values under the tree's own
cover distribution; last output column is the expected value (bias).

Host-side NumPy: contributions are an explanation path, not a training
hot loop. A batched device formulation can come later if profiling
demands it.
"""
from __future__ import annotations

import numpy as np


class _Path:
    """Decision path state: parallel arrays of (feature, zero, one, w)."""

    __slots__ = ("feature", "zero_fraction", "one_fraction", "pweight")

    def __init__(self, depth_cap: int):
        self.feature = np.zeros(depth_cap, dtype=np.int64)
        self.zero_fraction = np.zeros(depth_cap, dtype=np.float64)
        self.one_fraction = np.zeros(depth_cap, dtype=np.float64)
        self.pweight = np.zeros(depth_cap, dtype=np.float64)

    def copy(self, length: int) -> "_Path":
        p = _Path(len(self.feature))
        p.feature[:length] = self.feature[:length]
        p.zero_fraction[:length] = self.zero_fraction[:length]
        p.one_fraction[:length] = self.one_fraction[:length]
        p.pweight[:length] = self.pweight[:length]
        return p


def _extend(p: _Path, length: int, zero: float, one: float,
            feat: int) -> int:
    p.feature[length] = feat
    p.zero_fraction[length] = zero
    p.one_fraction[length] = one
    p.pweight[length] = 1.0 if length == 0 else 0.0
    for i in range(length - 1, -1, -1):
        p.pweight[i + 1] += one * p.pweight[i] * (i + 1) / (length + 1)
        p.pweight[i] = zero * p.pweight[i] * (length - i) / (length + 1)
    return length + 1


def _unwind(p: _Path, length: int, idx: int) -> int:
    length -= 1
    one = p.one_fraction[idx]
    zero = p.zero_fraction[idx]
    n = p.pweight[length]
    for i in range(length - 1, -1, -1):
        if one != 0.0:
            t = p.pweight[i]
            p.pweight[i] = n * (length + 1) / ((i + 1) * one)
            n = t - p.pweight[i] * zero * (length - i) / (length + 1)
        else:
            p.pweight[i] = p.pweight[i] * (length + 1) / (
                zero * (length - i))
    for i in range(idx, length):
        p.feature[i] = p.feature[i + 1]
        p.zero_fraction[i] = p.zero_fraction[i + 1]
        p.one_fraction[i] = p.one_fraction[i + 1]
    return length


def _unwound_sum(p: _Path, length: int, idx: int) -> float:
    one = p.one_fraction[idx]
    zero = p.zero_fraction[idx]
    total = 0.0
    n = p.pweight[length - 1]
    for i in range(length - 2, -1, -1):
        if one != 0.0:
            t = n * length / ((i + 1) * one)
            total += t
            n = p.pweight[i] - t * zero * (length - 1 - i) / length
        else:
            total += p.pweight[i] * length / (zero * (length - 1 - i))
    return total


def _node_cover(tree, node: int) -> float:
    if node < 0:
        return float(tree.leaf_count[-node - 1])
    return float(tree.internal_count[node])


def _tree_shap_row(tree, x: np.ndarray, phi: np.ndarray) -> None:
    max_depth = int(tree.leaf_depths().max()) + 2 if tree.num_leaves > 1 \
        else 1

    def recurse(node: int, p: _Path, length: int, zero: float, one: float,
                feat: int) -> None:
        length = _extend(p, length, zero, one, feat)
        if node < 0:  # leaf
            leaf_val = float(tree.leaf_value[-node - 1])
            for i in range(1, length):
                w = _unwound_sum(p, length, i)
                phi[p.feature[i]] += w * (p.one_fraction[i]
                                          - p.zero_fraction[i]) * leaf_val
            return
        f = int(tree.split_feature[node])
        v = x[f]
        thr = tree.threshold_real[node]
        if tree.is_categorical is not None and tree.is_categorical[node]:
            go_left = bool(tree._cat_go_left(np.array([thr]),
                                             np.array([v]))[0])
        elif np.isnan(v):
            go_left = bool(tree.default_left[node])
        else:
            go_left = v <= thr
        hot = int(tree.left_child[node] if go_left
                  else tree.right_child[node])
        cold = int(tree.right_child[node] if go_left
                   else tree.left_child[node])
        cover = _node_cover(tree, node)
        hot_r = _node_cover(tree, hot) / cover if cover > 0 else 0.0
        cold_r = _node_cover(tree, cold) / cover if cover > 0 else 0.0
        iz, io = 1.0, 1.0
        k = -1
        for i in range(1, length):
            if p.feature[i] == f:
                k = i
                break
        if k >= 0:
            iz = p.zero_fraction[k]
            io = p.one_fraction[k]
            length = _unwind(p, length, k)
        recurse(hot, p.copy(length), length, iz * hot_r, io, f)
        recurse(cold, p.copy(length), length, iz * cold_r, 0.0, f)

    if tree.num_leaves <= 1:
        return
    recurse(0, _Path(max_depth + 2), 0, 1.0, 1.0, -1)


def tree_shap_batch(tree, X: np.ndarray, n_feat: int) -> np.ndarray:
    """SHAP contributions for one tree over a batch.

    Returns ``[n, n_feat + 1]``; the last column is the tree's expected
    value (bias term).
    """
    n = X.shape[0]
    out = np.zeros((n, n_feat + 1), dtype=np.float64)
    if tree.num_leaves <= 1:
        out[:, -1] = tree.leaf_value[0] if len(tree.leaf_value) else 0.0
        return out
    total = float(tree.leaf_count.sum())
    expected = float(np.sum(tree.leaf_value[:tree.num_leaves]
                            * tree.leaf_count[:tree.num_leaves]) / total) \
        if total > 0 else 0.0
    for r in range(n):
        phi = np.zeros(n_feat + 1, dtype=np.float64)
        _tree_shap_row(tree, X[r], phi)
        out[r, :n_feat] = phi[:n_feat]
        out[r, -1] = expected
    return out
