"""Device-side dataset ingest: chunked on-accelerator bin assignment.

Reference: ``Dataset::Construct`` + ``BinMapper::ValueToBin`` +
``DenseBin::Push`` (src/io/dataset.cpp, include/LightGBM/bin.h,
UNVERIFIED — empty mount, see SURVEY.md banner): the reference binds the
full raw matrix on CPU, one value at a time, as a one-time load cost.

TPU-first inversion: bin-boundary *finding* stays host-side (it runs on
a bounded sample and is semantics-heavy — binning.py), but bin
*assignment* of the full ``[n, F]`` raw matrix moves onto the
accelerator. Raw float32 row chunks stream host→device with async
dispatch double-buffering (the ``copy_to_host_async`` discipline of
``GBDT._run_forest_chunks``, inverted), every feature is bucketized at
once against a padded ``[F, B]`` boundary matrix (a vectorized
``searchsorted``), missing/zero/categorical mapping applies on device,
and the kernel emits BOTH layouts the training engine consumes — the
row-major uint8/uint16 block and the feature-major int8 ``bins_t`` tile
— so the host transpose in ``_DeviceData`` disappears entirely.

Exactness contract (pinned by tests/test_ingest.py): device-assigned
bins are bit-identical to the host ``BinMapper.values_to_bins`` path for
every input value that is exactly float32-representable (float32 inputs
always; float64 inputs whose values round-trip through float32 — e.g.
any f32-generated matrix). The trick making a float32 compare exact
against float64 boundaries: each boundary ``b`` is replaced by the
smallest float32 STRICTLY greater than ``b`` (``_f32_exclusive``), so
``count(b < v)`` over f64 equals ``count(b32' <= v)`` over f32 — a
``side="right"`` searchsorted. Genuinely-f64 values within half an f32
ulp of a boundary may land one bin off; ``tpu_ingest_device=auto`` still
takes the device path for f64 input (bin edges are themselves sample
quantiles — a half-ulp edge shift is far below the binning noise floor),
and ``false`` restores the host path for strict f64 semantics.
"""
from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence

import numpy as np

MT_CODE = {"none": 0, "zero": 1, "nan": 2}

# int32 pad value for the SORTED categorical table: sorts past every
# real id (cat_device_safe guarantees real ids are < 2**31 - 128, the
# largest float32 below 2**31) and can never equal a candidate value
_CAT_PAD = np.int32(2**31 - 1)


def cat_device_safe(bin_mappers, used_features: Sequence[int]) -> bool:
    """True when every categorical feature's seen category ids survive
    the device path EXACTLY: raw chunks stream as float32 and the
    lookup table is int32, so each id must be int32-range and exactly
    float32-representable. ``Dataset._want_device_ingest`` gates on
    this (ids outside the window — e.g. 64-bit hashes — keep the host
    int64 path, which handles them exactly)."""
    from ..io.binning import BIN_TYPE_CATEGORICAL
    for f in used_features:
        m = bin_mappers[f]
        if m.bin_type != BIN_TYPE_CATEGORICAL or m.bin_to_cat is None:
            continue
        cv = np.asarray(m.bin_to_cat[1:], dtype=np.int64)
        if not len(cv):
            continue
        if ((cv >= 2**31) | (cv <= -2**31)).any():
            return False
        if (cv.astype(np.float32).astype(np.int64) != cv).any():
            return False
    return True


def _f32_exclusive(bounds: np.ndarray) -> np.ndarray:
    """Smallest float32 strictly greater than each float64 bound.

    For a float32 value v and float64 bound b:  (b < v)  <=>  (b32' <= v)
    where b32' = min{float32 x : x > b}. This turns the host's f64
    ``searchsorted(side="left")`` (count of bounds < v) into an exact
    f32 ``searchsorted(side="right")`` (count of b32' <= v) for every
    f32-representable v. +inf maps to +inf (the terminator bin catches
    +inf values via the final clip, matching the host clip).
    """
    b = np.asarray(bounds, dtype=np.float64)
    c = b.astype(np.float32)
    # where the round-to-nearest f32 is <= b, step up one ulp
    need_up = c.astype(np.float64) <= b
    up = np.nextafter(c, np.float32(np.inf), dtype=np.float32)
    out = np.where(need_up, up, c)
    out[np.isposinf(b)] = np.inf
    return out.astype(np.float32)


@dataclasses.dataclass
class IngestTables:
    """Padded per-used-feature mapping tables for the device kernel.

    All arrays are host numpy; ``device_ingest`` uploads them once per
    construct (they are tiny: F x max_bin floats).
    """

    ub: np.ndarray          # [Fu, B] f32 exclusive upper bounds (+inf pad)
    n_ub: np.ndarray        # [Fu] int32 — real bound count per feature
    mt: np.ndarray          # [Fu] int32 missing_type code (MT_CODE)
    default_bin: np.ndarray  # [Fu] int32
    num_bin: np.ndarray     # [Fu] int32
    is_cat: np.ndarray      # [Fu] bool
    cat_sorted: np.ndarray  # [Fu, C] int32 category values, ASCENDING
    cat_perm: np.ndarray    # [Fu, C] int32 bin index per sorted slot
    out_dtype: np.dtype     # uint8 / uint16 row-major bin dtype


def build_tables(bin_mappers, used_features: Sequence[int],
                 out_dtype) -> IngestTables:
    """Flatten the used features' BinMappers into padded device tables."""
    from ..io.binning import BIN_TYPE_CATEGORICAL
    used = list(used_features)
    if not cat_device_safe(bin_mappers, used):
        raise ValueError(
            "categorical ids outside the exact float32/int32 device "
            "window — the host path must bin this dataset "
            "(Dataset._want_device_ingest gates on cat_device_safe)")
    Fu = len(used)
    n_ub = np.ones(Fu, dtype=np.int32)
    mt = np.zeros(Fu, dtype=np.int32)
    dbin = np.zeros(Fu, dtype=np.int32)
    nbin = np.ones(Fu, dtype=np.int32)
    is_cat = np.zeros(Fu, dtype=bool)
    ubs: List[np.ndarray] = []
    cats: List[np.ndarray] = []
    for j, f in enumerate(used):
        m = bin_mappers[f]
        mt[j] = MT_CODE.get(m.missing_type, 0)
        dbin[j] = int(m.default_bin)
        nbin[j] = int(m.num_bin)
        if m.bin_type == BIN_TYPE_CATEGORICAL:
            is_cat[j] = True
            # bin_to_cat[0] is the NaN/unseen slot; slots 1.. hold the
            # raw category values, bin index = slot index
            cats.append(np.asarray(m.bin_to_cat[1:], dtype=np.int64))
            ubs.append(np.asarray([np.inf]))
            n_ub[j] = 1
        else:
            ub = np.asarray(m.bin_upper_bound, dtype=np.float64)
            n_ub[j] = len(ub)
            ubs.append(ub)
            cats.append(np.empty(0, dtype=np.int64))
    B = max((len(u) for u in ubs), default=1)
    C = max((len(c) for c in cats), default=0)
    ub_pad = np.full((Fu, B), np.inf, dtype=np.float32)
    for j, u in enumerate(ubs):
        ub_pad[j, :len(u)] = _f32_exclusive(u)
    # sorted table + permutation: slot k of bin_to_cat[1:] is bin k+1,
    # so the kernel binary-searches cat_sorted and maps the hit position
    # through cat_perm back to the bin index
    cat_sorted = np.full((Fu, max(C, 1)), _CAT_PAD, dtype=np.int32)
    cat_perm = np.zeros((Fu, max(C, 1)), dtype=np.int32)
    for j, cv in enumerate(cats):
        if len(cv):
            order = np.argsort(cv, kind="stable")
            cat_sorted[j, :len(cv)] = cv[order].astype(np.int32)
            cat_perm[j, :len(cv)] = order.astype(np.int32) + 1
    return IngestTables(ub=ub_pad, n_ub=n_ub, mt=mt, default_bin=dbin,
                        num_bin=nbin, is_cat=is_cat, cat_sorted=cat_sorted,
                        cat_perm=cat_perm, out_dtype=np.dtype(out_dtype))


def _assign_chunk_impl(raw, ub, n_ub, mt, default_bin, num_bin, is_cat,
                       cat_sorted, cat_perm, out_dtype, emit_transposed,
                       any_cat):
    """One chunk of rows through the full mapping, on device.

    raw: ``[R, Fu]`` float32 (NaN = missing). Returns the row-major
    ``[R, Fu]`` bin block and (optionally) the feature-major ``[Fu, R]``
    int8 tile (uint8 bits bitcast — the wraparound layout the Pallas
    histogram kernel reads).
    """
    import jax
    import jax.numpy as jnp
    nanm = jnp.isnan(raw)
    v = jnp.where(nanm, jnp.float32(0.0), raw)
    # vectorized searchsorted(side="right") against the exclusive-f32
    # bounds: one batched binary search per feature column (padding
    # bounds are +inf, so they only count for v=+inf — removed by the
    # same clip the host applies)
    cnt = jax.vmap(
        lambda bnd, col: jnp.searchsorted(bnd, col, side="right"),
        in_axes=(0, 1), out_axes=1)(ub, v).astype(jnp.int32)
    vb = jnp.minimum(cnt, n_ub[None, :] - 1)
    miss = jnp.where(mt[None, :] == 2, num_bin[None, :] - 1,
                     default_bin[None, :])
    out = jnp.where(nanm, jnp.broadcast_to(miss, vb.shape), vb)
    if any_cat:
        # categorical: truncate-toward-zero int cast (the host's
        # .astype(int64)); NaN -> -1 (the host's missing sentinel),
        # inf / out-of-int32-range -> INT32_MIN (matches no table entry
        # — build_tables guarantees real ids are int32-safe via
        # cat_device_safe). Lookup is a per-feature binary search over
        # the SORTED category table (O(R*Fu*log C), no [R, Fu, C]
        # broadcast); a hit maps through cat_perm to its bin, a miss to
        # the unseen bin 0.
        inr = (raw >= jnp.float32(-2**31)) & (raw < jnp.float32(2**31))
        iv = jnp.where(jnp.isnan(raw), jnp.float32(-1.0),
                       jnp.where(inr, raw,
                                 jnp.float32(-2**31))).astype(jnp.int32)
        C = cat_sorted.shape[1]
        pos = jnp.minimum(
            jax.vmap(lambda tbl, col: jnp.searchsorted(tbl, col,
                                                       side="left"),
                     in_axes=(0, 1), out_axes=1)(cat_sorted, iv)
            .astype(jnp.int32), C - 1)
        found = jnp.take_along_axis(cat_sorted, pos.T, axis=1).T
        cb = jnp.where(found == iv,
                       jnp.take_along_axis(cat_perm, pos.T, axis=1).T, 0)
        out = jnp.where(is_cat[None, :], cb, out)
    row = out.astype(out_dtype)
    if not emit_transposed:
        return row, None
    bt = jax.lax.bitcast_convert_type(out.T.astype(jnp.uint8), jnp.int8)
    return row, bt


_ASSIGN_JIT = None


def _assign_chunk(*args, **kwargs):
    """Jit wrapper built lazily so importing this module never touches
    jax (io/dataset.py imports stay accelerator-free until used)."""
    global _ASSIGN_JIT
    if _ASSIGN_JIT is None:
        import functools

        import jax
        _ASSIGN_JIT = functools.partial(
            jax.jit, static_argnames=("out_dtype", "emit_transposed",
                                      "any_cat"))(_assign_chunk_impl)
    return _ASSIGN_JIT(*args, **kwargs)


def ingest_program_cache_size() -> int:
    """Distinct compiled bin-assignment programs held by this process
    (the warm-start contract: a second same-shape construct adds zero)."""
    return 0 if _ASSIGN_JIT is None else _ASSIGN_JIT._cache_size()


@dataclasses.dataclass
class DeviceIngestResult:
    """Device-resident binned matrix produced by ``device_ingest``.

    ``bins``: ``[n, Fu]`` uint8/uint16 row-major (device).
    ``bins_t``: ``[Fu, n]`` int8 feature-major (device) or None.
    The host copy is NOT materialized here — ``Dataset.binned``'s lazy
    property pulls it only for checkpoint / model-text / EFB paths.
    """

    bins: object
    bins_t: Optional[object]
    n_rows: int
    chunk_rows: int

    def host_binned(self) -> np.ndarray:
        # slice defensively: the engine swaps its row-PADDED device
        # array back into ``bins`` after adoption (so the unpadded
        # original's HBM is released) — host consumers always see
        # exactly the real rows
        return np.asarray(self.bins[:self.n_rows])


def device_ingest(X: np.ndarray, bin_mappers, used_features,
                  out_dtype, chunk_rows: int = 262_144,
                  emit_transposed: bool = False) -> DeviceIngestResult:
    """Bin the full raw matrix on the accelerator, chunk by chunk.

    ``X``: ``[n, F]`` float32/float64 host matrix (original feature
    indexing; only ``used_features`` columns are read). Chunks are cast
    to float32 on host (cheap, parallel with device compute thanks to
    async dispatch) and streamed H2D double-buffered: while the device
    bucketizes chunk i, the host slices/casts chunk i+1 — the inverse of
    the predict path's ``copy_to_host_async`` overlap. Every chunk is
    the SAME padded shape, so the kernel compiles exactly once per
    (chunk_rows, Fu, B) family — and with a persistent compilation cache
    (``tpu_compile_cache_dir``) only once per machine.
    """
    import jax
    import jax.numpy as jnp

    from .. import obs
    used = list(used_features)
    n = int(X.shape[0])
    Fu = len(used)
    tables = build_tables(bin_mappers, used, out_dtype)
    out_jdtype = jnp.uint8 if tables.out_dtype == np.uint8 else jnp.uint16
    dev_tables = (jnp.asarray(tables.ub), jnp.asarray(tables.n_ub),
                  jnp.asarray(tables.mt), jnp.asarray(tables.default_bin),
                  jnp.asarray(tables.num_bin), jnp.asarray(tables.is_cat),
                  jnp.asarray(tables.cat_sorted),
                  jnp.asarray(tables.cat_perm))
    any_cat = bool(tables.is_cat.any())
    R = max(min(int(chunk_rows), max(n, 1)), 1)
    # single-chunk jobs skip the chunk-shape padding entirely
    col_sel = np.asarray(used, dtype=np.intp)
    take_all = Fu == X.shape[1] and np.array_equal(col_sel,
                                                   np.arange(Fu))

    def host_prep(s: int, e: int) -> np.ndarray:
        blk = X[s:e] if take_all else X[s:e][:, col_sel]
        blk = np.ascontiguousarray(blk, dtype=np.float32)
        if e - s < R:
            blk = np.concatenate(
                [blk, np.zeros((R - (e - s), Fu), np.float32)])
        return blk

    row_parts = []
    t_parts = []
    pending = None
    track = obs.any_enabled()
    with obs.span("ingest/device", rows=n, features=Fu):
        for s in range(0, max(n, 1), R):
            e = min(s + R, n)
            blk = host_prep(s, e)
            chunk_dev = jax.device_put(blk)
            if track:
                # H2D traffic accounting: every streamed raw chunk
                # (padded f32) crosses the host->device link once
                obs.inc("ingest.h2d_bytes", int(blk.nbytes))
                obs.inc("ingest.chunks")
            res = _assign_chunk(chunk_dev, *dev_tables,
                                out_dtype=out_jdtype,
                                emit_transposed=emit_transposed,
                                any_cat=any_cat)
            row_parts.append(res[0])
            if emit_transposed:
                t_parts.append(res[1])
            # double buffer: keep at most two chunks in flight so host
            # prep overlaps device compute without unbounded queueing
            if pending is not None:
                pending.block_until_ready()
            pending = res[0]
    bins = (row_parts[0] if len(row_parts) == 1
            else jnp.concatenate(row_parts, axis=0))[:n]
    bins_t = None
    if emit_transposed:
        bins_t = (t_parts[0] if len(t_parts) == 1
                  else jnp.concatenate(t_parts, axis=1))[:, :n]
    return DeviceIngestResult(bins=bins, bins_t=bins_t, n_rows=n,
                              chunk_rows=R)
