"""Histogram construction — the inner hot loop of GBDT training.

Reference: ``Bin::ConstructHistogram`` (src/io/dense_bin.hpp, UNVERIFIED —
empty mount, see SURVEY.md banner): for every row in a leaf,
``hist[bin] += (grad, hess)`` — an 8-way unrolled scalar gather-add on CPU,
a shared-memory atomic-add kernel on CUDA
(src/treelearner/cuda/cuda_histogram_constructor.cu, UNVERIFIED).

TPU-first design: TPUs have no fast scatter-add, but they have the MXU.
The scatter becomes a ONE-HOT MATMUL: for a block of R rows,

    contrib[f, b, c] = sum_r onehot(bin[r, f] == b) * vals[r, c]

which is a single ``[F*B, R] x [R, C]`` matmul per block, accumulated in
float32 over a ``lax.scan`` of row blocks. The one-hot is generated inline
(iota-compare) so XLA fuses it into the matmul operand load — no
materialized one-hot in HBM. Channels ``C = (grad, hess, count)``; row
masking (leaf membership / bagging) is folded into ``vals`` by the caller,
so a leaf histogram is a masked full scan. Inputs are cast to bfloat16
(exact for the 0/1 one-hot and the count channel; ~8-bit mantissa for
grad/hess — cf. the reference's int8 quantized-gradient mode,
cuda_gradient_discretizer.cu) with float32 MXU accumulation.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def _round_up(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


@functools.partial(jax.jit, static_argnames=("num_bins", "rows_per_block",
                                             "precise"))
def build_histogram(bins: jax.Array, vals: jax.Array, *, num_bins: int,
                    rows_per_block: int = 1024,
                    precise: bool = False) -> jax.Array:
    """Compute ``hist[f, b, c] = sum_r [bins[r, f] == b] * vals[r, c]``.

    Args:
      bins: ``[n_rows, n_features]`` integer bin matrix (uint8/uint16).
        ``n_rows`` must be a multiple of ``rows_per_block`` (pad with rows
        whose ``vals`` are zero).
      vals: ``[n_rows, n_channels]`` float32 per-row values, already
        multiplied by any row mask.
      num_bins: static histogram width ``B`` (>= max bin value + 1).
      rows_per_block: scan block size; bounds the transient one-hot to
        ``R * F * B`` bf16 elements so it stays VMEM-resident when fused.
      precise: use float32 operands (slower) instead of bfloat16.

    Returns:
      ``[n_features, num_bins, n_channels]`` float32 histogram.
    """
    n_rows, n_features = bins.shape
    n_channels = vals.shape[1]
    assert n_rows % rows_per_block == 0, (
        f"n_rows={n_rows} must be a multiple of rows_per_block="
        f"{rows_per_block}; pad the dataset")
    n_blocks = n_rows // rows_per_block
    dtype = jnp.float32 if precise else jnp.bfloat16

    bins_b = bins.reshape(n_blocks, rows_per_block, n_features)
    vals_b = vals.reshape(n_blocks, rows_per_block, n_channels).astype(dtype)
    iota = jnp.arange(num_bins, dtype=jnp.int32)

    def body(acc, block):
        bblock, vblock = block
        # [R, F, B] one-hot, generated inline (fuses into the matmul)
        onehot = (bblock.astype(jnp.int32)[:, :, None]
                  == iota[None, None, :]).astype(dtype)
        contrib = jnp.einsum(
            "rfb,rc->fbc", onehot, vblock,
            preferred_element_type=jnp.float32,
            precision=(jax.lax.Precision.HIGHEST if precise
                       else jax.lax.Precision.DEFAULT))
        return acc + contrib, None

    init = jnp.zeros((n_features, num_bins, n_channels), dtype=jnp.float32)
    hist, _ = jax.lax.scan(body, init, (bins_b, vals_b))
    return hist


def pad_rows(n_rows: int, rows_per_block: int) -> int:
    """Padded row count so the scan covers the data in whole blocks."""
    return _round_up(max(n_rows, rows_per_block), rows_per_block)
