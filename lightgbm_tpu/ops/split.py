"""Best-split search over bin histograms.

Reference: ``FeatureHistogram::FindBestThreshold*`` + ``SplitInfo``
(src/treelearner/feature_histogram.hpp, split_info.hpp, UNVERIFIED — empty
mount, see SURVEY.md banner). The reference scans each feature's bins
left-to-right and right-to-left (the two scans realize missing-value
default-left vs default-right); gain is the L1/L2-regularized variance
reduction; constraints: ``min_data_in_leaf``, ``min_sum_hessian_in_leaf``,
``min_gain_to_split``.

TPU-first design: the per-feature sequential scans become one vectorized
``cumsum`` over the bin axis for ALL features at once, with BOTH missing
directions evaluated as a stacked axis; the argmax over
``[features, bins, directions]`` replaces the reference's OpenMP
per-feature loop + reduction. Everything is fixed-shape and jit-safe, so it
runs inside the tree-growth ``while_loop`` and under ``shard_map`` for the
distributed learners.
"""
from __future__ import annotations

import dataclasses
from typing import Dict

import jax
import jax.numpy as jnp

# a plain Python float (weak-typed -> f32 under jnp ops), NOT a device
# array: materializing an array at import time would initialize the XLA
# backend and break jax.distributed.initialize for multi-host users
NEG_INF = float("-inf")


def _cumsum_bins(hist_vals: jax.Array) -> jax.Array:
    """Inclusive cumsum over the bin axis of ``[F, B, C]`` as a
    triangular-matrix product. XLA lowers ``jnp.cumsum`` to a VPU
    reduce-window (~10 ms per 64-child round at B=256 on v5e); the same
    O(F*B^2*C) MACs ride the MXU in microseconds. Exactness holds for
    the COUNT channel only: counts are integers < 2^24, and 0/1-weighted
    f32 sums of such values are exact in any summation order at HIGHEST
    precision. The f32 grad/hess channels are accumulated in a different
    order than ``jnp.cumsum``, so their prefix sums can differ in ULPs
    between the TPU matmul path and the CPU/wide-B path — enough to flip
    near-tied split choices across backends.

    TPU-only: the matmul trades O(F*B*C) adds for O(F*B^2*C) MACs — a
    win only where the MXU makes MACs ~free. The CPU/XLA path (and the
    B > 512 wide-histogram route) keeps ``jnp.cumsum``."""
    f, b, c = hist_vals.shape
    if jax.default_backend() != "tpu" or b > 512:
        return jnp.cumsum(hist_vals, axis=1)
    tri = (jnp.arange(b, dtype=jnp.int32)[:, None]
           <= jnp.arange(b, dtype=jnp.int32)[None, :])
    cum = jax.lax.dot_general(
        hist_vals, tri.astype(hist_vals.dtype),
        dimension_numbers=(((1,), (0,)), ((), ())),
        precision=jax.lax.Precision.HIGHEST)       # [F, C, B]
    return cum.transpose(0, 2, 1)


@dataclasses.dataclass(frozen=True)
class SplitConfig:
    """Static split-search hyperparameters (subset of Config)."""

    lambda_l1: float = 0.0
    lambda_l2: float = 0.0
    min_data_in_leaf: int = 20
    min_sum_hessian_in_leaf: float = 1e-3
    min_gain_to_split: float = 0.0
    max_delta_step: float = 0.0
    # categorical split search (FindBestThresholdCategorical):
    # one-hot below max_cat_to_onehot distinct values, else sorted
    # many-vs-many by grad/(hess+cat_smooth) with cat_l2 regularization
    has_categorical: bool = False
    # static tuple of categorical feature indices: when non-empty, the
    # categorical scan slices these rows out of the histogram before its
    # per-feature argsorts (sorting all F rows costs ~4x the whole
    # numerical search at Criteo shape: 26 cats of 199 features). Left
    # empty for dynamically-sliced search spaces (scatter/feature-
    # parallel shards, voting-elected subsets).
    cat_positions: tuple = ()
    max_cat_threshold: int = 32
    cat_smooth: float = 10.0
    cat_l2: float = 10.0
    max_cat_to_onehot: int = 4
    min_data_per_group: int = 100
    # monotone constraints, "basic" method (monotone_constraints.hpp
    # BasicLeafConstraints): child outputs are clipped to the leaf's
    # inherited [lower, upper] range, gains are evaluated at the clipped
    # outputs, and thresholds whose outputs violate the feature's
    # direction are vetoed
    has_monotone: bool = False
    # CEGB (cost_effective_gradient_boosting.hpp): split gains are
    # discounted by tradeoff * (penalty_split * n_rows_in_leaf +
    # per-feature coupled penalty for model-unused features)
    has_cegb: bool = False
    cegb_tradeoff: float = 1.0
    cegb_penalty_split: float = 0.0
    # monotone_penalty (monotone_constraints.hpp
    # ComputeMonotoneSplitGainPenalty): gains of splits on constrained
    # features are scaled by a depth-dependent factor < 1, discouraging
    # them near the root; needs the `depth` argument
    monotone_penalty: float = 0.0
    # path smoothing (feature_histogram.hpp CalculateSplittedLeafOutput
    # USE_SMOOTHING): child outputs shrink toward the parent leaf's
    # output by n/(n+path_smooth); gains evaluated at smoothed outputs
    path_smooth: float = 0.0
    # extremely randomized trees (feature_histogram.hpp USE_RAND_SEED):
    # the numerical scan evaluates ONE random threshold per feature per
    # node (categorical search is not randomized here — extension gap,
    # documented)
    extra_trees: bool = False
    # feature_contri: per-feature split-gain multiplier (read from the
    # `contri` array argument when True)
    has_contri: bool = False


def threshold_l1(s: jax.Array, l1: float) -> jax.Array:
    if l1 <= 0.0:
        return s
    return jnp.sign(s) * jnp.maximum(jnp.abs(s) - l1, 0.0)


def leaf_gain(sum_g: jax.Array, sum_h: jax.Array, l1: float,
              l2: float) -> jax.Array:
    """Variance-reduction leaf gain: ThresholdL1(g)^2 / (h + l2)."""
    t = threshold_l1(sum_g, l1)
    denom = sum_h + l2
    return jnp.where(denom > 0.0, t * t / jnp.maximum(denom, 1e-30), 0.0)


def calc_leaf_output(sum_g: jax.Array, sum_h: jax.Array, l1: float,
                     l2: float, max_delta_step: float = 0.0) -> jax.Array:
    """Leaf output: -ThresholdL1(g) / (h + l2), optionally clipped."""
    denom = sum_h + l2
    out = jnp.where(denom > 0.0,
                    -threshold_l1(sum_g, l1) / jnp.maximum(denom, 1e-30),
                    0.0)
    if max_delta_step > 0.0:
        out = jnp.clip(out, -max_delta_step, max_delta_step)
    return out


def leaf_gain_at_output(sum_g: jax.Array, sum_h: jax.Array, l1: float,
                        l2: float, output: jax.Array) -> jax.Array:
    """Leaf gain evaluated at a GIVEN (possibly clipped) output —
    ``GetLeafSplitGainGivenOutput`` (feature_histogram.hpp): equals
    ``leaf_gain`` when the output is the unconstrained optimum."""
    t = threshold_l1(sum_g, l1)
    return -(2.0 * t * output + (sum_h + l2) * output * output)


def smooth_output(raw: jax.Array, count: jax.Array, parent_out,
                  alpha: float) -> jax.Array:
    """Path smoothing (feature_histogram.hpp USE_SMOOTHING):
    ``raw * n/(n+alpha) + parent_out * alpha/(n+alpha)``."""
    w = count / (count + alpha)
    return raw * w + parent_out * (1.0 - w)


def monotone_penalty_factor(depth, penalization: float) -> jax.Array:
    """Gain multiplier for splits on monotone-constrained features
    (monotone_constraints.hpp ComputeMonotoneSplitGainPenalty):
    ~0 while depth + 1 <= penalization, then decays toward 1."""
    eps = 1e-10
    d = depth.astype(jnp.float32) if hasattr(depth, "astype") else float(depth)
    f_small = 1.0 - penalization / (2.0 ** d) + eps        # pen <= 1
    f_large = 1.0 - 2.0 ** (penalization - 1.0 - d) + eps  # pen > 1
    f = jnp.where(jnp.asarray(penalization) <= 1.0, f_small, f_large)
    return jnp.where(penalization >= d + 1.0, eps, f)


def _pack_bitset(inset: jax.Array, n_words: int) -> jax.Array:
    """Pack a ``[B]`` bool left-set into ``[n_words]`` uint32 words."""
    b = inset.shape[0]
    pad = n_words * 32 - b
    if pad > 0:
        inset = jnp.concatenate([inset, jnp.zeros(pad, inset.dtype)])
    weights = (jnp.uint32(1) << jnp.arange(32, dtype=jnp.uint32))[None, :]
    return jnp.sum(inset.reshape(n_words, 32).astype(jnp.uint32) * weights,
                   axis=1, dtype=jnp.uint32)


def _categorical_candidates(hist, parent_sums, num_bin, allowed_feature,
                            is_cat, cfg: SplitConfig,
                            out_lower=None, out_upper=None,
                            cegb_pen=None, parent_out=None, contri=None):
    """Candidate categorical gains: ``(all_gain [F, 3, B], orders
    [F, 2, B], cum [F, 2, B, 3], valid_bin [F, B])`` — modes are
    (one-hot, sorted-asc, sorted-desc). With monotone bounds active,
    gains are evaluated at range-clipped outputs like the numerical
    scan, so the cat-vs-numerical comparison stays fair in bounded
    leaves (categorical features themselves carry no direction)."""
    f, b, _ = hist.shape
    bin_idx = jnp.arange(b, dtype=jnp.int32)[None, :]
    cnt = hist[..., 2]
    l1, l2c = cfg.lambda_l1, cfg.lambda_l2 + cfg.cat_l2
    pg, ph, pc = parent_sums[0], parent_sums[1], parent_sums[2]
    bounded = cfg.has_monotone and out_lower is not None
    smoothed = cfg.path_smooth > 0.0 and parent_out is not None
    if bounded or smoothed:
        p_out = calc_leaf_output(pg, ph, l1, l2c, cfg.max_delta_step)
        if smoothed:
            p_out = smooth_output(p_out, pc, parent_out, cfg.path_smooth)
        if bounded:
            p_out = jnp.clip(p_out, out_lower, out_upper)
        parent_gain = leaf_gain_at_output(pg, ph, l1, l2c, p_out)
    else:
        parent_gain = leaf_gain(pg, ph, l1, l2c)
    min_cnt = float(max(cfg.min_data_in_leaf, cfg.min_data_per_group))

    cat_ok = is_cat & allowed_feature
    valid_bin = ((bin_idx >= 1) & (bin_idx < num_bin[:, None])
                 & (cnt > 0) & cat_ok[:, None])               # [F, B]

    def child_gain(lg, lh, lc):
        rg, rh, rc = pg - lg, ph - lh, pc - lc
        if bounded or smoothed:
            lo = calc_leaf_output(lg, lh, l1, l2c, cfg.max_delta_step)
            ro = calc_leaf_output(rg, rh, l1, l2c, cfg.max_delta_step)
            if smoothed:
                lo = smooth_output(lo, lc, parent_out, cfg.path_smooth)
                ro = smooth_output(ro, rc, parent_out, cfg.path_smooth)
            if bounded:
                lo = jnp.clip(lo, out_lower, out_upper)
                ro = jnp.clip(ro, out_lower, out_upper)
            g = (leaf_gain_at_output(lg, lh, l1, l2c, lo)
                 + leaf_gain_at_output(rg, rh, l1, l2c, ro)
                 - parent_gain)
        else:
            g = (leaf_gain(lg, lh, l1, l2c) + leaf_gain(rg, rh, l1, l2c)
                 - parent_gain)
        ok = ((lc >= min_cnt) & (rc >= min_cnt)
              & (lh >= cfg.min_sum_hessian_in_leaf)
              & (rh >= cfg.min_sum_hessian_in_leaf)
              & (g > cfg.min_gain_to_split))
        return jnp.where(ok, g, NEG_INF)

    # ---- one-hot (one category vs rest) ------------------------------
    use_onehot = (num_bin - 1) <= cfg.max_cat_to_onehot       # [F]
    gain_oh = child_gain(hist[..., 0], hist[..., 1], cnt)
    gain_oh = jnp.where(valid_bin & use_onehot[:, None], gain_oh, NEG_INF)

    # ---- sorted many-vs-many -----------------------------------------
    ratio = jnp.where(valid_bin,
                      hist[..., 0] / (hist[..., 1] + cfg.cat_smooth),
                      jnp.inf)
    # two scan directions; invalid bins sort to the end in both
    order_asc = jnp.argsort(ratio, axis=1)
    order_desc = jnp.argsort(jnp.where(valid_bin, -ratio, jnp.inf), axis=1)
    orders = jnp.stack([order_asc, order_desc], axis=1)       # [F, 2, B]
    sorted_hist = jnp.take_along_axis(hist[:, None], orders[..., None],
                                      axis=2)                 # [F, 2, B, 3]
    sorted_valid = jnp.take_along_axis(valid_bin[:, None], orders, axis=2)
    cum = jnp.cumsum(sorted_hist, axis=2)
    prefix_ok = (jnp.cumprod(sorted_valid.astype(jnp.int32), axis=2) > 0)
    k_idx = bin_idx[None]                                     # prefix len-1
    gain_sorted = child_gain(cum[..., 0], cum[..., 1], cum[..., 2])
    gain_sorted = jnp.where(
        prefix_ok & (k_idx < cfg.max_cat_threshold)
        & ~use_onehot[:, None, None] & cat_ok[:, None, None],
        gain_sorted, NEG_INF)                                 # [F, 2, B]

    all_gain = jnp.concatenate(
        [gain_oh[:, None, :], gain_sorted], axis=1)           # [F, 3, B]
    if cfg.has_contri and contri is not None:
        all_gain = jnp.where(jnp.isfinite(all_gain),
                             all_gain * contri[:, None, None], all_gain)
    if cfg.has_cegb:
        # penalize BEFORE the argmax so the per-feature selection sees
        # the discounted gains, mirroring the numerical path
        pen = cfg.cegb_tradeoff * cfg.cegb_penalty_split * pc
        if cegb_pen is not None:
            pen = pen + cegb_pen
            all_gain = all_gain - pen[:, None, None]
        else:
            all_gain = all_gain - pen
        all_gain = jnp.where(all_gain > cfg.min_gain_to_split, all_gain,
                             NEG_INF)
    return all_gain, orders, cum, valid_bin


def _categorical_best(hist, parent_sums, num_bin, allowed_feature, is_cat,
                      cfg: SplitConfig, out_lower=None, out_upper=None,
                      cegb_pen=None, parent_out=None, contri=None):
    """Best categorical split (one-hot + sorted many-vs-many).

    Reference: ``FindBestThresholdCategoricalInner``
    (src/treelearner/feature_histogram.hpp, UNVERIFIED): features with
    few categories scan one-vs-rest; otherwise categories are sorted by
    ``sum_grad / (sum_hess + cat_smooth)`` and prefixes of the sorted
    order (both directions, capped at ``max_cat_threshold``) form the
    left set, with ``cat_l2`` added to the L2 term.
    ``min_data_per_group`` is applied to both children of a categorical
    split. Bin 0 (the NaN/unseen-category bin) is never elected into a
    left set — unseen categories route right at predict, matching the
    bitset-miss semantics of the reference.

    Returns (gain [scalar], feature, left_sums, inset [B] bool over bins).
    """
    f, b, _ = hist.shape
    bin_idx = jnp.arange(b, dtype=jnp.int32)[None, :]
    all_gain, orders, cum, valid_bin = _categorical_candidates(
        hist, parent_sums, num_bin, allowed_feature, is_cat, cfg,
        out_lower=out_lower, out_upper=out_upper, cegb_pen=cegb_pen,
        parent_out=parent_out, contri=contri)
    flat = all_gain.reshape(-1)
    best = jnp.argmax(flat)
    best_gain = flat[best]
    feature = (best // (3 * b)).astype(jnp.int32)
    mode = ((best // b) % 3).astype(jnp.int32)                # 0=oh,1=asc,2=desc
    j = (best % b).astype(jnp.int32)

    onehot_inset = bin_idx[0] == j                            # [B]
    order_w = orders[feature, jnp.maximum(mode - 1, 0)]       # [B]
    inv = jnp.zeros(b, jnp.int32).at[order_w].set(
        jnp.arange(b, dtype=jnp.int32))
    sorted_inset = (inv <= j) & valid_bin[feature]
    inset = jnp.where(mode == 0, onehot_inset, sorted_inset)

    left_oh = hist[feature, j]
    left_sorted = cum[feature, jnp.maximum(mode - 1, 0), j]
    left_sums = jnp.where(mode == 0, left_oh, left_sorted)
    return best_gain, feature, left_sums, inset


def _numerical_candidates(hist, parent_sums, num_bin, has_nan,
                          num_allowed, cfg: SplitConfig,
                          mono=None, out_lower=None, out_upper=None,
                          parent_out=None, extra_u=None, contri=None,
                          depth=None):
    """Numerical threshold-scan gains: ``(gain [F, B, 2],
    left [F, B, 2, 3])`` — dir 0: missing right, dir 1: missing left.

    With ``cfg.has_monotone``: ``mono [F]`` in {-1, 0, +1} and the
    leaf's inherited output range ``[out_lower, out_upper]`` (scalars);
    candidate outputs are clipped to the range, gains evaluated at the
    clipped outputs, and direction-violating thresholds vetoed.
    With ``cfg.path_smooth > 0``: candidate outputs shrink toward
    ``parent_out`` (the leaf's stored output) before any clipping.
    With ``cfg.extra_trees``: ``extra_u [F]`` uniforms pick ONE random
    threshold per feature; all others are vetoed.
    With ``cfg.has_contri``: valid gains scale by ``contri [F]``
    (validity is checked on the unscaled gain, like the reference's
    penalty-after-threshold-check order)."""
    f, b, _ = hist.shape
    bin_idx = jnp.arange(b, dtype=jnp.int32)[None, :]          # [1, B]
    nan_bin = (num_bin - 1)[:, None]                           # [F, 1]
    is_nan_bin = has_nan[:, None] & (bin_idx == nan_bin)       # [F, B]

    hist_vals = jnp.where(is_nan_bin[..., None], 0.0, hist)
    nan_sums = jnp.sum(jnp.where(is_nan_bin[..., None], hist, 0.0),
                       axis=1)                                 # [F, 3]
    cum = _cumsum_bins(hist_vals)                              # [F, B, 3]
    parent = parent_sums[None, None, :]

    # direction 0: missing goes right; direction 1: missing goes left
    left = jnp.stack([cum, cum + nan_sums[:, None, :]], axis=2)  # [F,B,2,3]
    right = parent[:, :, None, :] - left

    lg, lh, lc = left[..., 0], left[..., 1], left[..., 2]
    rg, rh, rc = right[..., 0], right[..., 1], right[..., 2]

    use_mono = cfg.has_monotone and mono is not None
    use_smooth = cfg.path_smooth > 0.0 and parent_out is not None
    violates = None
    if use_mono or use_smooth:
        l1, l2 = cfg.lambda_l1, cfg.lambda_l2
        l_out = calc_leaf_output(lg, lh, l1, l2, cfg.max_delta_step)
        r_out = calc_leaf_output(rg, rh, l1, l2, cfg.max_delta_step)
        p_out = calc_leaf_output(parent_sums[0], parent_sums[1],
                                 l1, l2, cfg.max_delta_step)
        if use_smooth:
            a = cfg.path_smooth
            l_out = smooth_output(l_out, lc, parent_out, a)
            r_out = smooth_output(r_out, rc, parent_out, a)
            p_out = smooth_output(p_out, parent_sums[2], parent_out, a)
        if use_mono:
            # the parent's gain must be evaluated at ITS clipped output
            # too, or clipped leaves have every candidate gain deflated
            l_out = jnp.clip(l_out, out_lower, out_upper)
            r_out = jnp.clip(r_out, out_lower, out_upper)
            p_out = jnp.clip(p_out, out_lower, out_upper)
        parent_gain_c = leaf_gain_at_output(parent_sums[0],
                                            parent_sums[1], l1, l2, p_out)
        gain = (leaf_gain_at_output(lg, lh, l1, l2, l_out)
                + leaf_gain_at_output(rg, rh, l1, l2, r_out)
                - parent_gain_c)
        if use_mono:
            # veto thresholds that violate the feature's direction:
            # +1 (increasing): left (smaller values) must not exceed right
            violates = (mono[:, None, None].astype(jnp.float32)
                        * (l_out - r_out)) > 0
    else:
        parent_gain = leaf_gain(parent_sums[0], parent_sums[1],
                                cfg.lambda_l1, cfg.lambda_l2)
        gain = (leaf_gain(lg, lh, cfg.lambda_l1, cfg.lambda_l2)
                + leaf_gain(rg, rh, cfg.lambda_l1, cfg.lambda_l2)
                - parent_gain)

    n_value_bins = num_bin - has_nan.astype(jnp.int32)
    # thresholds t split value-bins {<=t} | {>t}; the extra slot when a NaN
    # bin exists realizes the "all values vs NaN" split
    valid_t = bin_idx < (n_value_bins[:, None] - 1
                         + has_nan.astype(jnp.int32)[:, None])
    valid = (valid_t[:, :, None]
             & num_allowed[:, None, None]
             & (lc >= cfg.min_data_in_leaf) & (rc >= cfg.min_data_in_leaf)
             & (lh >= cfg.min_sum_hessian_in_leaf)
             & (rh >= cfg.min_sum_hessian_in_leaf)
             & (gain > cfg.min_gain_to_split))
    if violates is not None:
        valid = valid & ~violates
    if cfg.extra_trees and extra_u is not None:
        # one random threshold per feature (valid thresholds occupy
        # bin_idx < num_bin - 1 regardless of the NaN bin)
        t_extra = (extra_u * (num_bin - 1).astype(jnp.float32)
                   ).astype(jnp.int32)                         # [F]
        valid = valid & (bin_idx == t_extra[:, None])[:, :, None]
    if cfg.has_contri and contri is not None:
        gain = gain * contri[:, None, None]
    if cfg.monotone_penalty > 0.0 and mono is not None \
            and depth is not None:
        # applied AFTER the min_gain validity check, like the
        # reference's post-FindBestThreshold gain scaling
        pf = monotone_penalty_factor(depth, cfg.monotone_penalty)
        gain = jnp.where((mono != 0)[:, None, None], gain * pf, gain)
    return jnp.where(valid, gain, NEG_INF), left


def per_feature_gains(hist: jax.Array, parent_sums: jax.Array,
                      num_bin: jax.Array, has_nan: jax.Array,
                      allowed_feature: jax.Array, cfg: SplitConfig,
                      is_cat: jax.Array = None, mono=None,
                      out_lower=None, out_upper=None,
                      cegb_pen=None, parent_out=None, extra_u=None,
                      contri=None, depth=None) -> jax.Array:
    """Best achievable gain per feature (``[F]``) — the local VOTE metric
    of the voting-parallel learner (PV-Tree,
    voting_parallel_tree_learner.cpp: machines propose their top-k
    features by local best gain)."""
    num_allowed = allowed_feature
    if cfg.has_categorical and is_cat is not None:
        num_allowed = allowed_feature & ~is_cat
    gain, _ = _numerical_candidates(hist, parent_sums, num_bin, has_nan,
                                    num_allowed, cfg, mono=mono,
                                    out_lower=out_lower,
                                    out_upper=out_upper,
                                    parent_out=parent_out,
                                    extra_u=extra_u, contri=contri,
                                    depth=depth)
    pf = jnp.max(gain, axis=(1, 2))                            # [F]
    if cfg.has_cegb:
        # vote on PENALIZED gains (the coupled term changes feature
        # ranking); categorical gains below are already penalized
        # inside _categorical_candidates
        pen = cfg.cegb_tradeoff * cfg.cegb_penalty_split * parent_sums[2]
        if cegb_pen is not None:
            pen = pen + cegb_pen
        pf = jnp.where(jnp.isfinite(pf), pf - pen, pf)
    if cfg.has_categorical and is_cat is not None:
        if cfg.cat_positions:
            ca = jnp.asarray(cfg.cat_positions, jnp.int32)
            all_gain_c, _, _, _ = _categorical_candidates(
                hist[ca], parent_sums, num_bin[ca], allowed_feature[ca],
                jnp.ones(len(cfg.cat_positions), jnp.bool_), cfg,
                out_lower=out_lower, out_upper=out_upper,
                cegb_pen=(None if cegb_pen is None else cegb_pen[ca]),
                parent_out=parent_out,
                contri=(None if contri is None else contri[ca]))
            pf_cat = jnp.full(pf.shape[0], NEG_INF).at[ca].set(
                jnp.max(all_gain_c, axis=(1, 2)))
            pf = jnp.maximum(pf, pf_cat)
        else:
            all_gain, _, _, _ = _categorical_candidates(
                hist, parent_sums, num_bin, allowed_feature, is_cat, cfg,
                out_lower=out_lower, out_upper=out_upper,
                cegb_pen=cegb_pen, parent_out=parent_out, contri=contri)
            pf = jnp.maximum(pf, jnp.max(all_gain, axis=(1, 2)))
    return pf


def elect_best(best: Dict[str, jax.Array],
               axis_name: str) -> Dict[str, jax.Array]:
    """Cross-device election of per-child best splits: all_gather the
    records over the mesh axis and keep the max-gain device's entry per
    child — the reference's ``SyncUpGlobalBestSplit`` (AllGather of
    serialized SplitInfo + max-gain pick, parallel_tree_learner.h).
    ``best`` fields carry a leading child dim ``[C]``; ``feature`` must
    already be a GLOBAL index."""
    gathered = jax.lax.all_gather(best, axis_name)             # [D, C, ...]
    win = jnp.argmax(gathered["gain"], axis=0)                 # [C]

    def take(a):
        idx = win.reshape((1, -1) + (1,) * (a.ndim - 2))
        return jnp.take_along_axis(a, idx.astype(jnp.int32), axis=0)[0]

    return jax.tree.map(take, gathered)


def find_best_split(hist: jax.Array, parent_sums: jax.Array,
                    num_bin: jax.Array, has_nan: jax.Array,
                    allowed_feature: jax.Array,
                    cfg: SplitConfig,
                    is_cat: jax.Array = None, mono=None,
                    out_lower=None, out_upper=None,
                    cegb_pen: jax.Array = None,
                    parent_out=None, extra_u=None, contri=None,
                    depth=None) -> Dict[str, jax.Array]:
    """Best split for one leaf given its histogram.

    Args:
      hist: ``[F, B, 3]`` float32 — (sum_grad, sum_hess, count) per bin.
      parent_sums: ``[3]`` — leaf totals (grad, hess, count).
      num_bin: ``[F]`` int32 — bins actually used per feature (incl. NaN bin).
      has_nan: ``[F]`` bool — whether the LAST used bin is the NaN bin.
      allowed_feature: ``[F]`` bool — column-sampling / interaction mask.
      cfg: static hyperparameters.
      is_cat: ``[F]`` bool — categorical features (scanned by
        ``_categorical_best`` instead of the threshold scan). Only read
        when ``cfg.has_categorical``.

    Returns dict of scalars: ``gain`` (−inf if no valid split), ``feature``,
    ``threshold_bin`` (split sends ``bin <= t`` left), ``default_left``,
    ``left_sums``/``right_sums`` (each ``[3]``), ``is_cat`` (categorical
    split?) and ``cat_bitset`` (``[ceil(B/32)]`` uint32 left-set over bins).
    """
    f, b, _ = hist.shape
    n_words = (b + 31) // 32

    num_allowed = allowed_feature
    if cfg.has_categorical and is_cat is not None:
        num_allowed = allowed_feature & ~is_cat

    gain, left = _numerical_candidates(hist, parent_sums, num_bin,
                                       has_nan, num_allowed, cfg,
                                       mono=mono, out_lower=out_lower,
                                       out_upper=out_upper,
                                       parent_out=parent_out,
                                       extra_u=extra_u, contri=contri,
                                       depth=depth)
    if cfg.has_cegb:
        # CEGB gain discount; candidates whose PENALIZED gain no longer
        # clears min_gain_to_split are rejected (the actual pruning)
        pen = cfg.cegb_tradeoff * cfg.cegb_penalty_split * parent_sums[2]
        if cegb_pen is not None:
            pen = pen + cegb_pen                    # [F] coupled penalty
            gain = gain - pen[:, None, None]
        else:
            gain = gain - pen
        gain = jnp.where(gain > cfg.min_gain_to_split, gain, NEG_INF)
    flat = gain.reshape(-1)
    best = jnp.argmax(flat)
    best_gain = flat[best]
    feature = (best // (b * 2)).astype(jnp.int32)
    threshold_bin = ((best // 2) % b).astype(jnp.int32)
    default_left = (best % 2).astype(jnp.bool_)
    left_best = left[feature, threshold_bin,
                     default_left.astype(jnp.int32)]

    if cfg.has_categorical and is_cat is not None:
        if cfg.cat_positions:
            ca = jnp.asarray(cfg.cat_positions, jnp.int32)
            cgain, cfeat_l, cleft, cinset = _categorical_best(
                hist[ca], parent_sums, num_bin[ca], allowed_feature[ca],
                jnp.ones(len(cfg.cat_positions), jnp.bool_), cfg,
                out_lower=out_lower, out_upper=out_upper,
                cegb_pen=(None if cegb_pen is None else cegb_pen[ca]),
                parent_out=parent_out,
                contri=(None if contri is None else contri[ca]))
            cfeat = ca[cfeat_l]
        else:
            cgain, cfeat, cleft, cinset = _categorical_best(
                hist, parent_sums, num_bin, allowed_feature, is_cat, cfg,
                out_lower=out_lower, out_upper=out_upper,
                cegb_pen=cegb_pen, parent_out=parent_out, contri=contri)
        take_cat = cgain > best_gain
        best_gain = jnp.maximum(best_gain, cgain)
        feature = jnp.where(take_cat, cfeat, feature)
        threshold_bin = jnp.where(take_cat, 0, threshold_bin)
        default_left = jnp.where(take_cat, False, default_left)
        left_best = jnp.where(take_cat, cleft, left_best)
        cat_bitset = jnp.where(take_cat,
                               _pack_bitset(cinset, n_words),
                               jnp.zeros(n_words, jnp.uint32))
        is_cat_split = take_cat
    else:
        cat_bitset = jnp.zeros(n_words, jnp.uint32)
        is_cat_split = jnp.array(False)

    right_best = parent_sums - left_best
    return {
        "gain": best_gain,
        "feature": feature,
        "threshold_bin": threshold_bin,
        "default_left": default_left,
        "left_sums": left_best,
        "right_sums": right_best,
        "is_cat": is_cat_split,
        "cat_bitset": cat_bitset,
    }
