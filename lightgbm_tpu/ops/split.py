"""Best-split search over bin histograms.

Reference: ``FeatureHistogram::FindBestThreshold*`` + ``SplitInfo``
(src/treelearner/feature_histogram.hpp, split_info.hpp, UNVERIFIED — empty
mount, see SURVEY.md banner). The reference scans each feature's bins
left-to-right and right-to-left (the two scans realize missing-value
default-left vs default-right); gain is the L1/L2-regularized variance
reduction; constraints: ``min_data_in_leaf``, ``min_sum_hessian_in_leaf``,
``min_gain_to_split``.

TPU-first design: the per-feature sequential scans become one vectorized
``cumsum`` over the bin axis for ALL features at once, with BOTH missing
directions evaluated as a stacked axis; the argmax over
``[features, bins, directions]`` replaces the reference's OpenMP
per-feature loop + reduction. Everything is fixed-shape and jit-safe, so it
runs inside the tree-growth ``while_loop`` and under ``shard_map`` for the
distributed learners.
"""
from __future__ import annotations

import dataclasses
from typing import Dict

import jax
import jax.numpy as jnp

NEG_INF = jnp.float32(-jnp.inf)


@dataclasses.dataclass(frozen=True)
class SplitConfig:
    """Static split-search hyperparameters (subset of Config)."""

    lambda_l1: float = 0.0
    lambda_l2: float = 0.0
    min_data_in_leaf: int = 20
    min_sum_hessian_in_leaf: float = 1e-3
    min_gain_to_split: float = 0.0
    max_delta_step: float = 0.0


def threshold_l1(s: jax.Array, l1: float) -> jax.Array:
    if l1 <= 0.0:
        return s
    return jnp.sign(s) * jnp.maximum(jnp.abs(s) - l1, 0.0)


def leaf_gain(sum_g: jax.Array, sum_h: jax.Array, l1: float,
              l2: float) -> jax.Array:
    """Variance-reduction leaf gain: ThresholdL1(g)^2 / (h + l2)."""
    t = threshold_l1(sum_g, l1)
    denom = sum_h + l2
    return jnp.where(denom > 0.0, t * t / jnp.maximum(denom, 1e-30), 0.0)


def calc_leaf_output(sum_g: jax.Array, sum_h: jax.Array, l1: float,
                     l2: float, max_delta_step: float = 0.0) -> jax.Array:
    """Leaf output: -ThresholdL1(g) / (h + l2), optionally clipped."""
    denom = sum_h + l2
    out = jnp.where(denom > 0.0,
                    -threshold_l1(sum_g, l1) / jnp.maximum(denom, 1e-30),
                    0.0)
    if max_delta_step > 0.0:
        out = jnp.clip(out, -max_delta_step, max_delta_step)
    return out


def find_best_split(hist: jax.Array, parent_sums: jax.Array,
                    num_bin: jax.Array, has_nan: jax.Array,
                    allowed_feature: jax.Array,
                    cfg: SplitConfig) -> Dict[str, jax.Array]:
    """Best split for one leaf given its histogram.

    Args:
      hist: ``[F, B, 3]`` float32 — (sum_grad, sum_hess, count) per bin.
      parent_sums: ``[3]`` — leaf totals (grad, hess, count).
      num_bin: ``[F]`` int32 — bins actually used per feature (incl. NaN bin).
      has_nan: ``[F]`` bool — whether the LAST used bin is the NaN bin.
      allowed_feature: ``[F]`` bool — column-sampling / interaction mask.
      cfg: static hyperparameters.

    Returns dict of scalars: ``gain`` (−inf if no valid split), ``feature``,
    ``threshold_bin`` (split sends ``bin <= t`` left), ``default_left``,
    ``left_sums``/``right_sums`` (each ``[3]``).
    """
    f, b, _ = hist.shape
    bin_idx = jnp.arange(b, dtype=jnp.int32)[None, :]          # [1, B]
    nan_bin = (num_bin - 1)[:, None]                           # [F, 1]
    is_nan_bin = has_nan[:, None] & (bin_idx == nan_bin)       # [F, B]

    hist_vals = jnp.where(is_nan_bin[..., None], 0.0, hist)
    nan_sums = jnp.sum(jnp.where(is_nan_bin[..., None], hist, 0.0),
                       axis=1)                                 # [F, 3]
    cum = jnp.cumsum(hist_vals, axis=1)                        # [F, B, 3]
    parent = parent_sums[None, None, :]

    # direction 0: missing goes right; direction 1: missing goes left
    left = jnp.stack([cum, cum + nan_sums[:, None, :]], axis=2)  # [F,B,2,3]
    right = parent[:, :, None, :] - left

    lg, lh, lc = left[..., 0], left[..., 1], left[..., 2]
    rg, rh, rc = right[..., 0], right[..., 1], right[..., 2]

    gain = (leaf_gain(lg, lh, cfg.lambda_l1, cfg.lambda_l2)
            + leaf_gain(rg, rh, cfg.lambda_l1, cfg.lambda_l2)
            - leaf_gain(parent_sums[0], parent_sums[1],
                        cfg.lambda_l1, cfg.lambda_l2))

    n_value_bins = num_bin - has_nan.astype(jnp.int32)
    # thresholds t split value-bins {<=t} | {>t}; the extra slot when a NaN
    # bin exists realizes the "all values vs NaN" split
    valid_t = bin_idx < (n_value_bins[:, None] - 1
                         + has_nan.astype(jnp.int32)[:, None])
    valid = (valid_t[:, :, None]
             & allowed_feature[:, None, None]
             & (lc >= cfg.min_data_in_leaf) & (rc >= cfg.min_data_in_leaf)
             & (lh >= cfg.min_sum_hessian_in_leaf)
             & (rh >= cfg.min_sum_hessian_in_leaf)
             & (gain > cfg.min_gain_to_split))
    gain = jnp.where(valid, gain, NEG_INF)

    flat = gain.reshape(-1)
    best = jnp.argmax(flat)
    best_gain = flat[best]
    feature = (best // (b * 2)).astype(jnp.int32)
    threshold_bin = ((best // 2) % b).astype(jnp.int32)
    default_left = (best % 2).astype(jnp.bool_)

    left_best = left[feature, threshold_bin,
                     default_left.astype(jnp.int32)]
    right_best = parent_sums - left_best
    return {
        "gain": best_gain,
        "feature": feature,
        "threshold_bin": threshold_bin,
        "default_left": default_left,
        "left_sums": left_best,
        "right_sums": right_best,
    }
