#!/usr/bin/env bash
# ASAN/UBSAN fuzz of the C ABI model parser (VERDICT r4 item 5).
#
# Builds native/c_api.cpp + native/fuzz_main.cpp with
# -fsanitize=address,undefined, generates the truncation/bit-flip
# corpus via the Python helper, and runs every file through the
# driver. Any OOB read, UB, leak, or crash exits nonzero.
set -euo pipefail
cd "$(dirname "$0")/.."

BUILD=$(mktemp -d)
trap 'rm -rf "$BUILD"' EXIT

g++ -O1 -g -std=c++17 -fsanitize=address,undefined \
    -fno-omit-frame-pointer -fopenmp \
    lightgbm_tpu/native/c_api.cpp lightgbm_tpu/native/fuzz_main.cpp \
    -o "$BUILD/fuzz_main"

python - "$BUILD" << 'EOF'
import os, sys
import numpy as np
os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.getcwd())
import lightgbm_tpu as lgb
rng = np.random.default_rng(23)
X = rng.normal(size=(400, 5))
y = (X[:, 0] + 0.5 * X[:, 1] * X[:, 2]
     + rng.normal(scale=0.3, size=400) > 0).astype(np.float64)
Xc = X.copy(); Xc[:, 4] = np.floor(np.abs(Xc[:, 4]) * 7) % 12
ds = lgb.Dataset(Xc, label=y, categorical_feature=[4])
bst = lgb.train({"verbosity": -1, "num_leaves": 15,
                 "objective": "binary"}, ds, num_boost_round=4)
s = bst.model_to_string()
out = sys.argv[1]
corpus = []
for cut in np.linspace(10, len(s) - 1, 60).astype(int):
    corpus.append(s[:cut])
body = s.find("Tree=")
rng = np.random.default_rng(99)
for _ in range(300):
    pos = int(rng.integers(body, len(s)))
    ch = chr(int(rng.integers(32, 127)))
    corpus.append(s[:pos] + ch + s[pos + 1:])
for tok in ("threshold=", "cat_boundaries=", "left_child=",
            "split_feature=", "num_leaves=", "num_cat="):
    corpus.append(s.replace(tok, tok + "1e300 ", 1))
    corpus.append(s.replace(tok, tok + "-999999999 ", 1))
for i, m in enumerate(corpus):
    with open(os.path.join(out, f"m{i:04d}.txt"), "w") as f:
        f.write(m)
print(f"corpus: {len(corpus)} files")
EOF

"$BUILD/fuzz_main" "$BUILD"/m*.txt
echo "fuzz_c_api: OK (ASAN+UBSAN clean)"
