#!/usr/bin/env python
"""Perf-regression sentinel over scripts/check_timings.log obs lines.

scripts/check.sh appends one machine-readable ``obs {...}`` JSON line
per run (dots, seconds, bench iters/sec, compile requests, peak-HBM).
This sentinel turns that log from a thing a reviewer *may* eyeball into
a gate: compare the NEWEST run against the trailing median of the
previous runs (same mode) and exit non-zero when a watched signal
regressed past its threshold —

- ``bench_iters_per_sec`` DOWN by more than ``--max-ips-drop``
  (default 15%: a 20% regression must fail, run-to-run noise on the
  tunneled chip must not);
- ``compile_requests`` UP by more than ``--max-compile-up`` (fraction)
  plus ``--compile-slack`` absolute requests (cold-cache runs jitter
  by a couple);
- ``peak_hbm_gib`` UP by more than ``--max-hbm-up``;
- ``copy_share`` (fraction of device busy in loop-state ``%copy`` ops,
  the signal the ``tpu_donate`` pass squeezes — docs/perf.md
  "Iteration floor") UP by more than ``--max-copy-up`` (fraction)
  plus ``--copy-slack`` absolute (the share sits near zero once
  donation lands; a pure ratio would flag noise);
- ``wall_busy_gap_ms`` (the per-iteration wall-vs-device-busy gap from
  trace attribution — the dispatch/collective stall residue the
  ``tpu_stream_overlap`` pipeline hides; docs/perf.md "Communication/
  compute overlap") UP by more than ``--max-gap-up`` (fraction) plus
  ``--gap-slack-ms`` absolute — the copy_share guard's shape: the gap
  sits near zero once overlap lands, so a pure ratio would flag timer
  noise while a pure absolute would miss a doubling;
- ``queue_wait_p99_ms`` (the serving smoke's windowed queue-wait p99,
  docs/observability.md "Request tracing") UP by more than
  ``--max-qw-up`` (fraction) plus ``--qw-slack-ms`` absolute — the
  same near-zero-slack shape as the copy_share guard: the p99 sits
  near the micro-batch budget, so a pure ratio would flag timer
  jitter while a pure absolute would miss a doubling;
- ``secs`` (suite wall clock) UP by more than ``--max-secs-up`` at a
  non-lower dot count (fewer dots = different suite, not a slowdown);
- ``stream_dryrun`` == 0 in the NEWEST run (absolute, no baseline
  needed): the streamed-sharded dryrun check.sh runs diverged from
  single-shard streaming or crashed;
- ``chaos_smoke`` == 0 in the NEWEST run (absolute, like
  stream_dryrun): the kill + resume + hot-swap chaos smoke check.sh
  runs lost bit-equality, dropped a request, or crashed;
- ``elastic_smoke`` == 0 in the NEWEST run (absolute, like
  chaos_smoke): the elastic resize cycle riding the same smoke
  (kill -> resume the gang NARROWER -> topology re-cut;
  docs/robustness.md "Elastic topology") lost bit-equality with the
  uninterrupted full-width run, dropped a predict, or crashed;
- ``serve_smoke`` == 0 in the NEWEST run (absolute, like chaos_smoke):
  the concurrent serving smoke (``benchmarks/serve_bench.py --smoke``
  — coalesce + LRU-evict + mid-traffic hot-swap under load) dropped a
  request, compiled a warm-path program, or crashed;
- ``shap_smoke`` == 0 in the NEWEST run (absolute, like serve_smoke):
  the mixed predict+explain leg of the same smoke (device SHAP
  through the service's ``(model, kind)`` lanes; docs/serving.md
  "Mixed predict + explain workloads") dropped a request, compiled a
  warm-path program, or served wrong contributions;
- ``fleet_smoke`` == 0 in the NEWEST run (absolute, like
  elastic_smoke): the serving-fleet kill/join cycle riding the chaos
  smoke (3 replicas behind the router, one SIGKILLed mid-load →
  relaunch + degrade; docs/serving.md "Fleet deployment") dropped a
  request, admitted traffic at an unready replica, or crashed;
- ``lint_findings`` != 0 in the NEWEST run (absolute): the static
  analysis suite (``python -m tools.analyze``;
  docs/static-analysis.md) reported drift findings — or crashed
  (recorded as -1). A drifted gate literal / raw knob read /
  branch-wrapped collective is broken NOW, whatever the history says.

No (or not enough) history exits 0 — the first run after a wipe stays
green. A signal missing from either side of the comparison is skipped
(benches evolve), and malformed obs lines are warned about and
skipped, never crash the gate.

A FAILING run writes a ``trend-reject {...}`` marker (keyed on the
entry's ts/rev/mode) back into the log, and rejected entries are
excluded from every later baseline — re-running the gate against a
persistent regression cannot launder the regressed numbers into the
trailing median it is compared against.

Usage (scripts/check.sh runs it behind CHECK_TREND=1):
    python scripts/obs_trend.py [--log scripts/check_timings.log]
        [--window 5] [--max-ips-drop 0.15] [--max-compile-up 0.5]
        [--compile-slack 2] [--max-hbm-up 0.2] [--max-secs-up 0.35]
        [--max-copy-up 0.5] [--copy-slack 0.005]
        [--max-gap-up 0.5] [--gap-slack-ms 3.0]
        [--max-qw-up 0.5] [--qw-slack-ms 2.0]
Exit codes: 0 = no regression (or no history), 1 = regression, 2 = bad
invocation (unreadable log path given explicitly).
"""
from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
from typing import Any, Dict, List, Optional

DEFAULT_LOG = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "check_timings.log")


def _entry_key(entry: Dict[str, Any]) -> tuple:
    return (entry.get("ts"), entry.get("rev"), entry.get("mode"))


def parse_obs_lines(text: str) -> List[Dict[str, Any]]:
    """All well-formed ``obs {...}`` entries, oldest first, minus
    entries covered by a ``trend-reject`` marker (a previous sentinel
    failure — they must not become baseline). Malformed entries warn
    to stderr and are skipped."""
    # markers are APPENDED after the entries they reject, so collect
    # them in a first pass before flagging entries
    rejected = set()
    for line in text.splitlines():
        line = line.strip()
        if line.startswith("trend-reject "):
            try:
                rejected.add(_entry_key(
                    json.loads(line[len("trend-reject "):])))
            except ValueError:
                pass
    out: List[Dict[str, Any]] = []
    for i, line in enumerate(text.splitlines(), 1):
        line = line.strip()
        if not line.startswith("obs "):
            continue
        try:
            entry = json.loads(line[len("obs "):])
            if not isinstance(entry, dict):
                raise ValueError("not a JSON object")
        except ValueError as e:
            sys.stderr.write(f"obs_trend: skipping malformed obs line "
                             f"{i} ({e})\n")
            continue
        entry["_rejected"] = _entry_key(entry) in rejected
        out.append(entry)
    return out


def _num(entry: Dict[str, Any], key: str) -> Optional[float]:
    v = entry.get(key)
    if isinstance(v, bool) or not isinstance(v, (int, float)):
        return None
    return float(v)


def _median_of(history: List[Dict[str, Any]],
               key: str) -> Optional[float]:
    vals = [v for v in (_num(e, key) for e in history) if v is not None]
    return statistics.median(vals) if vals else None


def check_trend(entries: List[Dict[str, Any]], window: int,
                max_ips_drop: float, max_compile_up: float,
                compile_slack: float, max_hbm_up: float,
                max_secs_up: float, max_copy_up: float = 0.5,
                copy_slack: float = 0.005, max_qw_up: float = 0.5,
                qw_slack_ms: float = 2.0, max_gap_up: float = 0.5,
                gap_slack_ms: float = 3.0) -> List[str]:
    """Regression messages for the newest entry vs the trailing median
    of up to ``window`` earlier same-mode entries; [] = green."""
    if not entries:
        return []
    newest = entries[-1]
    failures: List[str] = []
    # the streamed-sharded dryrun pin needs no baseline: a 0 in the
    # newest run means sharded streaming diverged from single-shard
    # (or crashed) — an absolute failure, not a trend
    if _num(newest, "stream_dryrun") == 0.0:
        failures.append(
            "streamed-sharded dryrun FAILED (stream_dryrun=0): the "
            "2-device streaming case diverged from single-shard "
            "streaming or crashed")
    # the chaos-smoke pin is absolute for the same reason: a resume
    # that lost bit-equality or a hot-swap that dropped/corrupted a
    # request is broken NOW, whatever the trailing median says
    if _num(newest, "chaos_smoke") == 0.0:
        failures.append(
            "chaos smoke FAILED (chaos_smoke=0): kill + resume + "
            "hot-swap lost bit-equality or crashed "
            "(benchmarks/chaos_bench.py --smoke)")
    # elastic resume is absolute too: a resize cycle that resumed the
    # gang narrower and lost bit-equality (or dropped a predict) is a
    # broken topology re-cut NOW, whatever the trailing median says
    if _num(newest, "elastic_smoke") == 0.0:
        failures.append(
            "elastic smoke FAILED (elastic_smoke=0): the resize cycle "
            "(kill -> resume narrower -> topology re-cut) lost "
            "bit-equality, dropped a predict, or crashed "
            "(benchmarks/chaos_bench.py --smoke; docs/robustness.md "
            "'Elastic topology')")
    # the fleet smoke is absolute like the elastic one: a replica kill
    # that dropped a request, or traffic routed at a replica that
    # never passed /readyz, is a broken failover NOW
    if _num(newest, "fleet_smoke") == 0.0:
        failures.append(
            "fleet smoke FAILED (fleet_smoke=0): the serving-fleet "
            "kill/join cycle (3 replicas, kill one mid-load -> "
            "relaunch + degrade) dropped a request or crashed "
            "(benchmarks/chaos_bench.py --smoke; docs/serving.md "
            "'Fleet deployment')")
    # the serving smoke is absolute the same way: a dropped request or
    # a warm-path compile under coalesce + evict + swap load is broken
    # NOW, whatever the trailing median says
    if _num(newest, "serve_smoke") == 0.0:
        failures.append(
            "serving smoke FAILED (serve_smoke=0): concurrent "
            "coalesce + LRU-evict + mid-traffic-swap load dropped a "
            "request, compiled a warm-path program, or crashed "
            "(benchmarks/serve_bench.py --smoke)")
    # the explain leg of the same smoke is absolute too: a warm SHAP
    # dispatch that compiles, a mixed-lane drop, or served
    # contributions diverging from the published model is broken NOW
    if _num(newest, "shap_smoke") == 0.0:
        failures.append(
            "mixed predict+explain smoke FAILED (shap_smoke=0): the "
            "device-SHAP serving leg dropped a request, compiled a "
            "warm-path program, or served wrong contributions "
            "(benchmarks/serve_bench.py --smoke; docs/serving.md "
            "'Mixed predict + explain workloads')")
    # static analysis is absolute the same way: findings are drift
    # bugs NOW (gate literal outside the capability table, raw knob
    # read, collective inside a lax.switch branch...), and -1 means
    # the analyzer itself crashed
    lint = _num(newest, "lint_findings")
    if lint is not None and lint != 0.0:
        failures.append(
            f"static analysis FAILED (lint_findings={lint:g}): "
            f"run `python -m tools.analyze` and fix (or explicitly "
            f"allowlist) every finding — docs/static-analysis.md")
    mode = newest.get("mode")
    # rejected entries (previous sentinel failures) never become
    # baseline — a persistent regression re-run N times must keep
    # failing against the last GREEN history, not against itself
    history = [e for e in entries[:-1]
               if e.get("mode") == mode and not e.get("_rejected")]
    history = history[-window:]
    if not history:
        # first run (or first in this mode): no trend baseline — only
        # the absolute checks above apply
        return failures

    ips_now = _num(newest, "bench_iters_per_sec")
    ips_med = _median_of(history, "bench_iters_per_sec")
    if ips_now is not None and ips_med:
        floor = ips_med * (1.0 - max_ips_drop)
        if ips_now < floor:
            failures.append(
                f"bench_iters_per_sec regressed: {ips_now:.3g} < "
                f"{floor:.3g} (trailing median {ips_med:.3g} over "
                f"{len(history)} run(s), -{max_ips_drop:.0%} allowed)")

    comp_now = _num(newest, "compile_requests")
    comp_med = _median_of(history, "compile_requests")
    if comp_now is not None and comp_med is not None:
        ceil = comp_med * (1.0 + max_compile_up) + compile_slack
        if comp_now > ceil:
            failures.append(
                f"compile_requests regressed: {comp_now:g} > {ceil:g} "
                f"(trailing median {comp_med:g}; a compile-count jump "
                f"is a warm-path recompile leak)")

    cs_now = _num(newest, "copy_share")
    cs_med = _median_of(history, "copy_share")
    if cs_now is not None and cs_med is not None:
        ceil = cs_med * (1.0 + max_copy_up) + copy_slack
        if cs_now > ceil:
            failures.append(
                f"copy_share regressed: {cs_now:.4f} > {ceil:.4f} "
                f"(trailing median {cs_med:.4f} over {len(history)} "
                f"run(s)): loop-state %copy crept back — a donation "
                f"gate dropped a carry (docs/perf.md 'Iteration "
                f"floor')")

    gap_now = _num(newest, "wall_busy_gap_ms")
    gap_med = _median_of(history, "wall_busy_gap_ms")
    if gap_now is not None and gap_med is not None:
        ceil = gap_med * (1.0 + max_gap_up) + gap_slack_ms
        if gap_now > ceil:
            failures.append(
                f"wall_busy_gap_ms regressed: {gap_now:.3g} > "
                f"{ceil:.3g} (trailing median {gap_med:.3g} over "
                f"{len(history)} run(s)): the per-iter wall-vs-busy "
                f"gap crept back — a host sync snuck into the "
                f"overlapped stream path (docs/perf.md "
                f"'Communication/compute overlap')")

    qw_now = _num(newest, "queue_wait_p99_ms")
    qw_med = _median_of(history, "queue_wait_p99_ms")
    if qw_now is not None and qw_med is not None:
        ceil = qw_med * (1.0 + max_qw_up) + qw_slack_ms
        if qw_now > ceil:
            failures.append(
                f"queue_wait_p99_ms regressed: {qw_now:.3g} > "
                f"{ceil:.3g} (trailing median {qw_med:.3g} over "
                f"{len(history)} run(s)): serving queue pressure "
                f"crept up — budget misconfig, dispatch slowdown, or "
                f"LRU thrash (docs/observability.md 'Request "
                f"tracing')")

    hbm_now = _num(newest, "peak_hbm_gib")
    hbm_med = _median_of(history, "peak_hbm_gib")
    if hbm_now is not None and hbm_med:
        ceil = hbm_med * (1.0 + max_hbm_up)
        if hbm_now > ceil:
            failures.append(
                f"peak_hbm_gib regressed: {hbm_now:.3g} > {ceil:.3g} "
                f"(trailing median {hbm_med:.3g})")

    secs_now = _num(newest, "secs")
    secs_med = _median_of(history, "secs")
    dots_now = _num(newest, "dots")
    dots_med = _median_of(history, "dots")
    if (secs_now is not None and secs_med
            and dots_now is not None and dots_med is not None
            and dots_now >= dots_med):
        ceil = secs_med * (1.0 + max_secs_up)
        if secs_now > ceil:
            failures.append(
                f"suite wall clock regressed: {secs_now:g}s > "
                f"{ceil:.0f}s (trailing median {secs_med:g}s at "
                f"dots>={dots_med:g})")
    return failures


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="perf-regression sentinel over check_timings.log "
                    "obs lines (see module docstring)")
    ap.add_argument("--log", default=DEFAULT_LOG)
    ap.add_argument("--window", type=int, default=5,
                    help="trailing same-mode runs the median is over")
    ap.add_argument("--max-ips-drop", type=float, default=0.15)
    ap.add_argument("--max-compile-up", type=float, default=0.5)
    ap.add_argument("--compile-slack", type=float, default=2.0)
    ap.add_argument("--max-hbm-up", type=float, default=0.2)
    ap.add_argument("--max-secs-up", type=float, default=0.35)
    ap.add_argument("--max-copy-up", type=float, default=0.5)
    ap.add_argument("--copy-slack", type=float, default=0.005,
                    help="absolute copy_share headroom on top of the "
                         "ratio (the share sits near zero once "
                         "donation lands)")
    ap.add_argument("--max-gap-up", type=float, default=0.5)
    ap.add_argument("--gap-slack-ms", type=float, default=3.0,
                    help="absolute wall_busy_gap_ms headroom on top "
                         "of the ratio (the gap sits near zero once "
                         "overlap lands; pure ratios would flag "
                         "host-timer noise)")
    ap.add_argument("--max-qw-up", type=float, default=0.5)
    ap.add_argument("--qw-slack-ms", type=float, default=2.0,
                    help="absolute queue_wait_p99_ms headroom on top "
                         "of the ratio (the p99 sits near the "
                         "micro-batch budget; pure ratios would flag "
                         "timer jitter)")
    args = ap.parse_args(argv)

    try:
        with open(args.log) as f:
            text = f.read()
    except OSError as e:
        if args.log != DEFAULT_LOG:
            sys.stderr.write(f"obs_trend: cannot read {args.log}: "
                             f"{e}\n")
            return 2
        print("obs_trend: no timings log yet; nothing to compare")
        return 0

    entries = parse_obs_lines(text)
    if not entries:
        print(f"obs_trend: no obs lines in {args.log}; nothing to "
              f"compare")
        return 0
    # a single entry has no trend baseline, but the absolute checks
    # (the stream_dryrun pin) still apply to it
    failures = check_trend(entries, args.window, args.max_ips_drop,
                           args.max_compile_up, args.compile_slack,
                           args.max_hbm_up, args.max_secs_up,
                           args.max_copy_up, args.copy_slack,
                           args.max_qw_up, args.qw_slack_ms,
                           args.max_gap_up, args.gap_slack_ms)
    if failures:
        for msg in failures:
            print(f"obs_trend: REGRESSION — {msg}")
        print(f"obs_trend: newest run vs trailing median FAILED "
              f"({len(failures)} signal(s)); see {args.log}")
        # mark the failed entry so re-runs cannot launder it into the
        # baseline (best-effort: a read-only log still fails the gate)
        newest = entries[-1]
        if not newest.get("_rejected"):
            try:
                with open(args.log, "a") as f:
                    f.write("trend-reject " + json.dumps(
                        {"ts": newest.get("ts"),
                         "rev": newest.get("rev"),
                         "mode": newest.get("mode")}) + "\n")
            except OSError as e:
                sys.stderr.write(f"obs_trend: cannot write reject "
                                 f"marker: {e}\n")
        return 1
    print("obs_trend: newest run within thresholds of the trailing "
          "median — OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
