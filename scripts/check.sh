#!/usr/bin/env bash
# Pre-snapshot gate (VERDICT r4 item 1): a <2-minute smoke that MUST be
# green before any end-of-round snapshot or milestone commit.
#
#   bash scripts/check.sh          # smoke tests + tiny bench
#   bash scripts/check.sh --full   # full suite instead of the smoke set
#
# Rationale: round 4's final commit shipped an undefined variable in
# GBDT.predict() that failed 111/249 tests and blanked BENCH_r04. This
# script is the discipline that prevents a recurrence.
#
# Wall-clock guard: every run appends "date git-rev mode dots seconds"
# to scripts/check_timings.log (also summarized in the verify skill,
# .claude/skills/verify/SKILL.md). A suite that suddenly takes longer
# at the same dot count is a perf regression in the library the tests
# exercise (e.g. an ingest slowdown taxing every construct) — review
# the log's trend, not just the green.
set -euo pipefail
cd "$(dirname "$0")/.."

LOG=/tmp/_check_run.log
MODE=smoke
RC=0
T0=$(date +%s)
if [[ "${1:-}" == "--full" ]]; then
  MODE=full
  python -m pytest tests/ -x -q 2>&1 | tee "$LOG" || RC=$?
else
  python -m pytest tests/test_smoke_gate.py tests/test_engine.py \
    tests/test_ingest.py -x -q 2>&1 | tee "$LOG" || RC=$?
fi
T1=$(date +%s)
# log EVERY run, green or red — a failing/slow run is exactly the
# datapoint the trend review needs
DOTS=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' "$LOG" | tr -cd . | wc -c || true)
REV=$(git rev-parse --short HEAD 2>/dev/null || echo nogit)
printf '%s %s %s dots=%s secs=%s rc=%s\n' \
  "$(date -u +%Y-%m-%dT%H:%M:%SZ)" "$REV" "$MODE" "$DOTS" "$((T1 - T0))" \
  "$RC" >> scripts/check_timings.log
if [[ "$RC" != 0 ]]; then
  echo "check.sh: tests FAILED (rc=$RC; timing logged)"
  exit "$RC"
fi

# tiny bench: exercises the real flagship path end to end (train +
# predict + AUC) and proves bench.py emits its JSON line with rc=0.
# --metrics-json doubles as the obs-subsystem gate: the run must
# produce a well-formed metrics snapshot (docs/observability.md)
OBS_JSON=/tmp/_check_obs_metrics.jsonl
rm -f "$OBS_JSON"
python bench.py --rows 300000 --iters 5 --smoke --metrics-json "$OBS_JSON"

# streamed x sharded dryrun (docs/perf.md "Streamed x sharded"): the
# 2-device streaming case must stay BIT-EQUAL to single-shard
# streaming with one collective per level; its status rides the obs
# line below so scripts/obs_trend.py watches it run-over-run
STREAM_DRYRUN=1
XLA_FLAGS="--xla_force_host_platform_device_count=2 ${XLA_FLAGS:-}" \
JAX_COMPILATION_CACHE_DIR="${JAX_COMPILATION_CACHE_DIR:-/tmp/lightgbm_tpu_jax_cache}" \
python -c "import __graft_entry__ as g; g.dryrun_multichip(2, only=('streaming',))" \
  || STREAM_DRYRUN=0

# chaos smoke (docs/robustness.md "Chaos harness"): kill + resume +
# hot-swap in one process — streamed resume must stay BIT-EQUAL to the
# uninterrupted run, the swap must compile nothing, and a corrupted
# publish must degrade gracefully; its status rides the obs line so
# scripts/obs_trend.py fails absolutely on chaos_smoke=0. The smoke
# also runs the ELASTIC RESIZE cycle (kill -> resume narrower ->
# verify bit-equality + zero dropped predicts; docs/robustness.md
# "Elastic topology") and reports it as elastic_smoke in its final
# JSON record — parsed below onto the obs line, enforced absolutely
# by obs_trend.py and by exit 8 here
CHAOS_SMOKE=1
CHAOS_JSON=/tmp/_check_chaos_smoke.log
rm -f "$CHAOS_JSON"
JAX_COMPILATION_CACHE_DIR="${JAX_COMPILATION_CACHE_DIR:-/tmp/lightgbm_tpu_jax_cache}" \
python benchmarks/chaos_bench.py --smoke 2>&1 | tee "$CHAOS_JSON" \
  || CHAOS_SMOKE=0
ELASTIC_SMOKE=$(python - "$CHAOS_JSON" elastic_smoke <<'PY'
import json, sys
v = 0
try:
    for ln in open(sys.argv[1]):
        ln = ln.strip()
        if ln.startswith("{"):
            d = json.loads(ln)
            if sys.argv[2] in d:
                v = int(d[sys.argv[2]])
except Exception:
    v = 0
print(v)
PY
)
# serving-fleet kill/join cycle riding the same smoke (3 replicas,
# kill one mid-load, relaunch + degrade, ZERO dropped requests;
# docs/serving.md "Fleet deployment") — enforced absolutely by
# obs_trend.py and by exit 9 here
FLEET_SMOKE=$(python - "$CHAOS_JSON" fleet_smoke <<'PY'
import json, sys
v = 0
try:
    for ln in open(sys.argv[1]):
        ln = ln.strip()
        if ln.startswith("{"):
            d = json.loads(ln)
            if sys.argv[2] in d:
                v = int(d[sys.argv[2]])
except Exception:
    v = 0
print(v)
PY
)

# serving smoke (docs/serving.md): N concurrent clients through the
# micro-batching service with a 1-model LRU and a mid-traffic hot-swap
# — zero dropped requests, zero warm-path compiles, tracing overhead
# under 3%, stage decomposition summing to end-to-end; its status
# rides the obs line so scripts/obs_trend.py fails absolutely on
# serve_smoke=0, and its windowed queue-wait p99 rides along as
# queue_wait_p99_ms= so the sentinel catches queue-pressure creep
SERVE_SMOKE=1
SERVE_JSON=/tmp/_check_serve_smoke.log
rm -f "$SERVE_JSON"
JAX_COMPILATION_CACHE_DIR="${JAX_COMPILATION_CACHE_DIR:-/tmp/lightgbm_tpu_jax_cache}" \
python benchmarks/serve_bench.py --smoke 2>&1 | tee "$SERVE_JSON" \
  || SERVE_SMOKE=0
# mixed predict+explain leg riding the same smoke (device SHAP through
# the service: contrib warmup, half-explain load, zero drops + zero
# warm compiles; docs/serving.md "Mixed predict + explain workloads")
# — enforced absolutely by obs_trend.py and by exit 10 here
SHAP_SMOKE=$(python - "$SERVE_JSON" shap_smoke <<'PY'
import json, sys
v = 0
try:
    for ln in open(sys.argv[1]):
        ln = ln.strip()
        if ln.startswith("{"):
            d = json.loads(ln)
            if sys.argv[2] in d:
                v = int(d[sys.argv[2]])
except Exception:
    v = 0
print(v)
PY
)

# static analysis (docs/static-analysis.md): the five drift linters —
# capability-gate / config-knobs / obs-names / collective-safety /
# lock-discipline — must report ZERO findings. The count rides the obs
# line (lint_findings=) so scripts/obs_trend.py fails absolutely on
# lint_findings>0, and a non-zero count exits 6 below. A crash of the
# analyzer itself (no count file) records -1 — also a failure.
LINT_COUNT_FILE=/tmp/_check_lint_count
rm -f "$LINT_COUNT_FILE"
python -m tools.analyze --emit-count "$LINT_COUNT_FILE" || true
LINT_FINDINGS=$(cat "$LINT_COUNT_FILE" 2>/dev/null || echo -1)

# machine-readable obs line appended next to the plain timing line:
# dots/seconds from this run plus compile count and peak-HBM estimate
# read back from the snapshot. A malformed dump FAILS the gate — a
# check that silently skips its own telemetry is how telemetry rots.
python - "$OBS_JSON" "$MODE" "$DOTS" "$((T1 - T0))" "$REV" "$STREAM_DRYRUN" "$CHAOS_SMOKE" "$LINT_FINDINGS" "$SERVE_SMOKE" "$SERVE_JSON" "$ELASTIC_SMOKE" "$FLEET_SMOKE" "$SHAP_SMOKE" <<'PY' >> scripts/check_timings.log
import json, sys, time
path, mode, dots, secs, rev, stream_ok, chaos_ok, lint, serve_ok = sys.argv[1:10]
serve_json = sys.argv[10] if len(sys.argv) > 10 else ""
elastic_ok = sys.argv[11] if len(sys.argv) > 11 else "0"
fleet_ok = sys.argv[12] if len(sys.argv) > 12 else "0"
shap_ok = sys.argv[13] if len(sys.argv) > 13 else "0"
try:
    lines = [ln for ln in open(path).read().splitlines() if ln.strip()]
    snap = json.loads(lines[-1])
    if snap.get("schema") != "lightgbm-tpu-metrics-v1":
        raise ValueError(f"unexpected schema {snap.get('schema')!r}")
except Exception as e:
    sys.stderr.write(f"check.sh: MALFORMED obs metrics dump {path}: "
                     f"{type(e).__name__}: {e}\n")
    sys.exit(3)

def gauge(name):
    for m in snap.get("metrics", []):
        if m.get("name") == name and not m.get("labels"):
            return m.get("value")
    return None

def serve_stat(key):
    """Read one field off the serving smoke's final JSON record (the
    queue-wait p99 decomposition signal); a failed/absent smoke run
    yields None — obs_trend skips missing signals, never crashes."""
    try:
        lines = [ln for ln in open(serve_json).read().splitlines()
                 if ln.strip().startswith("{")]
        return json.loads(lines[-1]).get(key)
    except Exception:
        return None

print("obs " + json.dumps({
    "ts": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    "rev": rev, "mode": mode, "dots": int(dots), "secs": int(secs),
    "compile_requests": gauge("compile.requests"),
    "peak_hbm_gib": gauge("bench.peak_hbm_gib"),
    "bench_iters_per_sec": gauge("bench.iters_per_sec"),
    "predict_programs": gauge("compile.predict_programs"),
    # rows the training histogram scans touched (hist.rows_scanned is a
    # counter, but the snapshot reader is name-based either way):
    # masked = n_pad x rounds; a partition regression shows up here as
    # this number jumping back to the masked product
    "hist_rows_scanned": gauge("hist.rows_scanned"),
    "hist_partition": gauge("bench.hist_partition"),
    # loop-state %copy share of device busy (trace attribution,
    # scripts/trace_attr.py) — present when the bench ran with
    # --profile-dir; obs_trend.py fails on it regressing above its
    # trailing median like iters/sec
    "copy_share": gauge("train.copy_share"),
    # collective share of device busy (same trace attribution) and the
    # per-iter wall-vs-busy gap the tpu_stream_overlap pipeline
    # shrinks; obs_trend.py guards the gap like copy_share
    "comm_share": gauge("train.comm_share"),
    "wall_busy_gap_ms": gauge("train.wall_busy_gap_ms"),
    # streamed-training trajectory + the sharded-streaming dryrun pin
    "stream_rows_per_sec": gauge("bench.stream_rows_per_sec"),
    "stream_shards": gauge("bench.stream_shards"),
    "stream_dryrun": int(stream_ok),
    # kill + resume + hot-swap loop (benchmarks/chaos_bench.py --smoke)
    "chaos_smoke": int(chaos_ok),
    # elastic resize cycle riding the same smoke: kill -> resume
    # NARROWER -> bit-equality + zero dropped predicts
    "elastic_smoke": int(elastic_ok),
    # serving-fleet kill/join cycle riding the same smoke: 3 replicas,
    # kill one mid-load -> relaunch + degrade -> zero dropped requests
    "fleet_smoke": int(fleet_ok),
    # concurrent serving: coalesce + evict + swap under load with zero
    # drops and zero warm compiles (benchmarks/serve_bench.py --smoke)
    "serve_smoke": int(serve_ok),
    # mixed predict+explain leg of the same smoke: device SHAP through
    # the service lanes with zero drops and zero warm compiles
    "shap_smoke": int(shap_ok),
    # windowed serving queue-wait p99 from the smoke's SLO plane —
    # obs_trend.py flags it regressing past its trailing median
    # (queue-pressure creep: budget misconfig, dispatch slowdown)
    "queue_wait_p99_ms": serve_stat("queue_wait_p99_ms"),
    # drift-linter findings (python -m tools.analyze; -1 = analyzer
    # crashed). obs_trend.py fails absolutely on anything but 0
    "lint_findings": int(lint),
}))
PY

if [[ "$STREAM_DRYRUN" != 1 ]]; then
  echo "check.sh: streamed-sharded dryrun FAILED (status logged)"
  exit 4
fi
if [[ "$CHAOS_SMOKE" != 1 ]]; then
  echo "check.sh: chaos smoke FAILED (kill+resume+swap; status logged)"
  exit 5
fi
if [[ "$ELASTIC_SMOKE" != 1 ]]; then
  echo "check.sh: elastic smoke FAILED (kill+resume-narrower re-cut;" \
       "status logged)"
  exit 8
fi
if [[ "$FLEET_SMOKE" != 1 ]]; then
  echo "check.sh: serving-fleet smoke FAILED (kill/join cycle under" \
       "load; status logged)"
  exit 9
fi
if [[ "$LINT_FINDINGS" != 0 ]]; then
  echo "check.sh: static analysis FAILED ($LINT_FINDINGS finding(s);" \
       "run python -m tools.analyze — docs/static-analysis.md)"
  exit 6
fi
if [[ "$SERVE_SMOKE" != 1 ]]; then
  echo "check.sh: serving smoke FAILED (coalesce+evict+swap under" \
       "load; status logged)"
  exit 7
fi
if [[ "$SHAP_SMOKE" != 1 ]]; then
  echo "check.sh: mixed predict+explain smoke FAILED (device SHAP" \
       "through the service; status logged)"
  exit 10
fi

# perf-regression sentinel (CHECK_TREND=1 to enforce): compare the obs
# line just appended against the trailing same-mode median; a >15%
# iters/sec drop, compile-count jump, or peak-HBM creep FAILS the gate.
# First run (no history) stays green — the sentinel needs >= 2 lines.
if [[ "${CHECK_TREND:-0}" == "1" ]]; then
  python scripts/obs_trend.py
fi
echo "check.sh: OK (timing + obs line logged to scripts/check_timings.log)"
