#!/usr/bin/env bash
# Pre-snapshot gate (VERDICT r4 item 1): a <2-minute smoke that MUST be
# green before any end-of-round snapshot or milestone commit.
#
#   bash scripts/check.sh          # smoke tests + tiny bench
#   bash scripts/check.sh --full   # full suite instead of the smoke set
#
# Rationale: round 4's final commit shipped an undefined variable in
# GBDT.predict() that failed 111/249 tests and blanked BENCH_r04. This
# script is the discipline that prevents a recurrence.
set -euo pipefail
cd "$(dirname "$0")/.."

if [[ "${1:-}" == "--full" ]]; then
  python -m pytest tests/ -x -q
else
  python -m pytest tests/test_smoke_gate.py tests/test_engine.py -x -q
fi

# tiny bench: exercises the real flagship path end to end (train +
# predict + AUC) and proves bench.py emits its JSON line with rc=0
python bench.py --rows 300000 --iters 5 --smoke
echo "check.sh: OK"
