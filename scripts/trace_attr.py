#!/usr/bin/env python
"""Trace-level attribution CLI over a ``tpu_profile_dir`` dump.

The promoted form of docs/perf.md's "~20 line raw XSpace parse" (the
tensorboard converter is protobuf-incompatible here): per-op busy
aggregation over the device plane's "XLA Ops" line, the ``%copy``
share the donation pass squeezes, and the per-iteration wall-vs-busy
gap. Parsing lives in ``lightgbm_tpu/obs/trace_attr.py`` (stdlib-only,
no protobuf/jax import) so ``engine.train`` and ``bench.py
--profile-dir`` feed the same numbers into the ``train.copy_share`` /
``train.wall_busy_gap_ms`` gauges that scripts/obs_trend.py guards.

    python scripts/trace_attr.py /tmp/prof                 # whole dump
    python scripts/trace_attr.py /tmp/prof --iters 40      # + gap/iter
    python scripts/trace_attr.py /tmp/prof --iters 40 --wall-ms 1760
    python scripts/trace_attr.py /tmp/prof --json          # machine use

``--wall-ms`` overrides the trace-window wall estimate with a
host-measured one (through a tunneled chip trust host timers for WALL
and the trace for op time — perf.md "Trace-level attribution").
Exit codes: 0 = attributed, 3 = nothing to attribute (no dump / no
device plane — e.g. a CPU-backend trace), 2 = bad invocation.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from lightgbm_tpu.obs.trace_attr import attribute  # noqa: E402


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="per-op busy attribution of a jax.profiler xplane "
                    "dump (see module docstring)")
    ap.add_argument("path", help="a *.xplane.pb file or a "
                                 "tpu_profile_dir tree (newest dump "
                                 "inside is used)")
    ap.add_argument("--iters", type=int, default=0,
                    help="boosting iterations the traced window "
                         "covered (enables the per-iter gap)")
    ap.add_argument("--wall-ms", type=float, default=None,
                    help="host-measured wall ms of the traced window "
                         "(default: trace span)")
    ap.add_argument("--top", type=int, default=12,
                    help="ops to print (default 12)")
    ap.add_argument("--json", action="store_true",
                    help="emit the full attribution dict as JSON")
    args = ap.parse_args(argv)

    res = attribute(args.path, iters=args.iters or None,
                    wall_ms=args.wall_ms)
    if args.json:
        print(json.dumps(res, indent=2))
        return 0 if res.get("found") else 3
    if not res.get("found"):
        print(f"trace_attr: {res.get('reason')}")
        return 3
    print(f"source: {res['source']}")
    print(f"device plane: {res['device_plane']}")
    print(f"{'op':<44} {'total ms':>10} {'calls':>8} {'share':>7}")
    for op in res["ops"][:args.top]:
        print(f"{op['name'][:44]:<44} {op['ms']:>10.3f} "
              f"{op['calls']:>8d} {op['share']:>6.1%}")
    print(f"{'device busy':<44} {res['busy_ms']:>10.3f}")
    print(f"{'%copy (loop-state copies)':<44} {res['copy_ms']:>10.3f} "
          f"{'':>8} {res['copy_share']:>6.1%}")
    print(f"{'collectives (all-reduce et al.)':<44} "
          f"{res['comm_ms']:>10.3f} {'':>8} {res['comm_share']:>6.1%}")
    print(f"{'wall (traced window)':<44} {res['wall_ms']:>10.3f}")
    if "wall_busy_gap_ms" in res:
        print(f"wall-vs-busy gap: {res['wall_busy_gap_ms']:.2f} ms/iter "
              f"over {res['iters']} iterations")
    return 0


if __name__ == "__main__":
    sys.exit(main())
