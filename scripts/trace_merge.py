#!/usr/bin/env python
"""Merge per-rank Chrome traces into one Perfetto timeline.

A ``train_distributed`` gang run with ``tpu_trace_dir=DIR`` leaves one
``rank_<r>.trace.json`` per worker, each on its OWN monotonic clock —
loading two of them into Perfetto separately tells you nothing about
relative timing, and loading them together used to interleave garbage
(identical pid/tid before the rank-tagged export). This CLI merges
them into ONE timeline: every rank's timestamps rebase through the
export envelope's wall/monotonic clock pair (the same rebase the
gauge merge in obs/aggregate.py uses), each rank gets its own named
process row, and the zero point is the earliest event across the gang
— so a straggling rank shows up as its ``train/round`` spans visibly
lagging the others in one Perfetto window.

    python scripts/trace_merge.py /tmp/trace              # a trace dir
    python scripts/trace_merge.py /tmp/trace -o gang.json
    python scripts/trace_merge.py rank_0.trace.json rank_1.trace.json

With a directory argument, every ``rank_*.trace.json`` inside is
merged; default output is ``<dir>/merged.trace.json`` (or
``merged.trace.json`` in the cwd for explicit file lists). Open the
output at <https://ui.perfetto.dev>.

Exit codes: 0 = merged, 3 = nothing to merge (no rank trace files),
2 = bad invocation.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from lightgbm_tpu.obs.aggregate import (  # noqa: E402
    merge_chrome_traces, read_rank_traces)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="merge per-rank Chrome traces into one "
                    "Perfetto-loadable timeline (see module docstring)")
    ap.add_argument("paths", nargs="+",
                    help="a tpu_trace_dir (rank_*.trace.json inside "
                         "is merged) or explicit trace files")
    ap.add_argument("-o", "--out", default=None,
                    help="output path (default: merged.trace.json "
                         "next to the inputs)")
    args = ap.parse_args(argv)

    files = []
    for p in args.paths:
        if os.path.isdir(p):
            files.extend(read_rank_traces(p))
        else:
            files.append(p)
    if not files:
        sys.stderr.write("trace_merge: no rank_*.trace.json files "
                         "found\n")
        return 3
    try:
        merged = merge_chrome_traces(files)
    except ValueError as e:
        sys.stderr.write(f"trace_merge: {e}\n")
        return 3
    out = args.out
    if out is None:
        base = args.paths[0] if os.path.isdir(args.paths[0]) \
            else os.path.dirname(os.path.abspath(files[0]))
        out = os.path.join(base, "merged.trace.json")
    tmp = out + ".tmp"
    with open(tmp, "w") as f:
        json.dump(merged, f)
    os.replace(tmp, out)
    other = merged["otherData"]
    n_events = sum(1 for e in merged["traceEvents"]
                   if e.get("ph") != "M")
    print(json.dumps({
        "out": out,
        "ranks": other["merged_from_ranks"],
        "events": n_events,
        "dropped_events": other["dropped_events"],
        "unrebased_ranks": other["unrebased_ranks"],
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
