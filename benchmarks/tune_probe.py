import sys, os; sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import time, numpy as np, jax
import lightgbm_tpu as lgb
from lightgbm_tpu.boosting.gbdt import GBDT
from lightgbm_tpu.config import Config

lb, fuse = int(sys.argv[1]), int(sys.argv[2])
rng = np.random.default_rng(0)
X = rng.normal(size=(1_000_000, 28)); y = (X @ rng.normal(size=28) > 0).astype(np.float64)
cfg = Config({"objective": "binary", "num_leaves": 127, "max_bin": 255,
              "verbosity": -1, "tpu_leaf_batch": lb, "tpu_fuse_iters": fuse})
eng = GBDT(cfg, lgb.Dataset(X, label=y))
eng.train_chunk(fuse); jax.block_until_ready(eng.score)
t0 = time.time(); eng.train_chunk(fuse); jax.block_until_ready(eng.score)
print(f"RESULT leaf_batch={lb} fuse={fuse}: {fuse/(time.time()-t0):.2f} iters/s", flush=True)
