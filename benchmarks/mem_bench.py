import sys, os; sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import sys, time, numpy as np, jax
import lightgbm_tpu as lgb
from lightgbm_tpu.boosting.gbdt import GBDT
from lightgbm_tpu.config import Config

F, n, mode = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
rng = np.random.default_rng(0)
X = rng.normal(size=(n, F)).astype(np.float64)
y = (X @ rng.normal(size=F) > 0).astype(np.float64)
cfg = Config({"objective": "binary", "num_leaves": 127, "max_bin": 255,
              "verbosity": -1, "tpu_hist_mode": mode})
t0 = time.time()
eng = GBDT(cfg, lgb.Dataset(X, label=y))
eng.train_chunk(4); jax.block_until_ready(eng.score)
t_compile = time.time() - t0
t0 = time.time(); eng.train_chunk(8); jax.block_until_ready(eng.score)
dt = time.time() - t0
stats = jax.local_devices()[0].memory_stats() or {}
peak = stats.get("peak_bytes_in_use", 0) / 1e6
print(f"RESULT F={F} n={n} mode={mode}: {8/dt:.2f} iters/s  peak_hbm={peak:.0f}MB  warm+compile={t_compile:.0f}s", flush=True)
