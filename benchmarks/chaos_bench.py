"""Chaos scenario benchmark: continuous training under injected
faults — train -> checkpoint publish -> hot-swap -> serve, N cycles,
while the chaos harness (tpu_fault_inject) interrupts trainers and
corrupts publishes. Reports the two SLO-shaped numbers ROADMAP item 5
asks for: MODEL-FRESHNESS LAG (publish -> serving the new model) and
PREDICT p50/p99 across the swaps, plus dropped-request and staleness
accounting (docs/robustness.md "Chaos harness").

Run:
  python benchmarks/chaos_bench.py                     # 5 cycles
  python benchmarks/chaos_bench.py --cycles 8 --rows 50000
  python benchmarks/chaos_bench.py --gang              # + a true
                                                       # SIGKILL gang
                                                       # cycle
  python benchmarks/chaos_bench.py --smoke             # CI gate:
    streamed kill+resume bit-equality + hot-swap under corruption;
    exit 0 iff every invariant held (scripts/check.sh appends the
    result as chaos_smoke= on the obs line; scripts/obs_trend.py
    fails ABSOLUTELY on chaos_smoke=0)

Each line is one JSON record; the final line aggregates.
"""
import argparse
import json
import sys
import time

import numpy as np

sys.path.insert(0, __file__.rsplit("/", 2)[0])


def _data(n, f=10, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f))
    y = (X[:, 0] + 0.5 * X[:, 1] * X[:, 2]
         + rng.normal(scale=0.3, size=n) > 0).astype(np.float64)
    return X, y


def _corrupt_newest(pub_dir):
    """What the harness's corrupt fault does, driver-side: damage the
    newest rank-0 checkpoint payload and clobber the pointer."""
    from lightgbm_tpu.recovery.checkpoint import CheckpointManager
    mgr = CheckpointManager(pub_dir, rank=0)
    its = mgr.iterations()
    if not its:
        return
    p = mgr.path(its[-1])
    blob = open(p, "rb").read()
    with open(p, "wb") as f:
        f.write(blob[:-64] + bytes(64))
    with open(mgr.latest_pointer, "w") as f:
        f.write("ckpt_99999999.rank0.ckpt\n")


# ---------------------------------------------------------------------------
# full scenario
# ---------------------------------------------------------------------------
def run_cycles(args):
    import tempfile

    import lightgbm_tpu as lgb
    X, y = _data(args.rows, seed=0)
    Xq = X[:args.batch]
    pub = tempfile.mkdtemp(prefix="lgbm_chaos_pub_")
    base = {"objective": "binary", "num_leaves": args.leaves,
            "verbosity": -1}
    server = lgb.train(base, lgb.Dataset(X, label=y),
                       num_boost_round=args.rounds)
    server.watch_checkpoints(pub, interval=0.05)
    server.predict(Xq)                       # warm the padded shapes
    lat, lags, dropped, stale_cycles, records = [], [], 0, 0, []
    for cycle in range(args.cycles):
        corrupt = cycle % 3 == 2             # every third publish torn
        interrupted = cycle % 3 == 1         # every third trainer dies
        Xc, yc = _data(args.rows, seed=100 + cycle)
        p = dict(base, checkpoint_dir=pub,
                 checkpoint_interval=max(args.rounds // 2, 1),
                 seed=100 + cycle)
        if interrupted:
            # the chaos harness kills the trainer mid-run; the retry
            # resumes from the round-boundary checkpoint (bit-exact)
            p["tpu_fault_inject"] = f"exn:iter={args.rounds - 2}"
            try:
                lgb.train(p, lgb.Dataset(Xc, label=yc),
                          num_boost_round=args.rounds)
            except lgb.LightGBMError:
                pass
            lgb.train(p, lgb.Dataset(Xc, label=yc),
                      num_boost_round=args.rounds, resume_from=pub)
        else:
            lgb.train(p, lgb.Dataset(Xc, label=yc),
                      num_boost_round=args.rounds)
        published = time.time()
        if corrupt:
            _corrupt_newest(pub)
        swaps_before = server._model_watch.swaps
        swap_lag = None
        for _ in range(args.requests):
            t0 = time.perf_counter()
            try:
                server.predict(Xq)
            except Exception:
                dropped += 1
            lat.append(time.perf_counter() - t0)
            if swap_lag is None \
                    and server._model_watch.swaps > swaps_before:
                swap_lag = time.time() - published
            time.sleep(args.think)
        stale = server._model_watch.stale
        stale_cycles += bool(stale)
        if swap_lag is not None:
            lags.append(swap_lag)
        rec = {"cycle": cycle, "corrupt_publish": corrupt,
               "trainer_interrupted": interrupted,
               "swapped": swap_lag is not None,
               "freshness_lag_s": (round(swap_lag, 3)
                                   if swap_lag is not None else None),
               "serving_stale": stale}
        records.append(rec)
        print(json.dumps(rec), flush=True)
    if args.gang:
        records.append(run_gang_cycle(args, pub, server, Xq, lat))
    lat_ms = np.asarray(lat) * 1e3
    agg = {
        "aggregate": True, "cycles": args.cycles,
        "swaps": server._model_watch.swaps,
        "swap_failures": server._model_watch.failures,
        "dropped_requests": dropped,
        "stale_cycles": stale_cycles,
        "predict_p50_ms": round(float(np.quantile(lat_ms, 0.5)), 3),
        "predict_p99_ms": round(float(np.quantile(lat_ms, 0.99)), 3),
        "freshness_lag_p50_s": (round(float(np.median(lags)), 3)
                                if lags else None),
        "freshness_lag_max_s": (round(float(np.max(lags)), 3)
                                if lags else None),
    }
    print(json.dumps(agg), flush=True)
    return 0 if dropped == 0 else 1


def run_gang_cycle(args, pub, server, Xq, lat):
    """One TRUE-SIGKILL cycle: a 1-process train_distributed gang with
    an injected kill self-heals (watchdog/backoff path) and publishes;
    the server swaps its model like any other cycle."""
    import lightgbm_tpu as lgb
    t0 = time.time()
    lgb.train_distributed(
        {"objective": "binary", "num_leaves": args.leaves,
         "verbosity": -1, "checkpoint_dir": pub,
         "checkpoint_interval": max(args.rounds // 2, 1),
         "tpu_fault_inject": f"kill:rank=0,iter={args.rounds - 2}"},
        _gang_shard_fn, n_processes=1, num_boost_round=args.rounds,
        timeout=120.0, max_restarts=2, restart_backoff=0.2,
        heartbeat_timeout=30.0)
    published = time.time()
    for _ in range(args.requests):
        t = time.perf_counter()
        server.predict(Xq)
        lat.append(time.perf_counter() - t)
    rec = {"cycle": "gang-kill", "train_s": round(published - t0, 1),
           "swapped": True, "serving_stale": server._model_watch.stale}
    print(json.dumps(rec), flush=True)
    return rec


def _gang_shard_fn(rank, nproc):
    X, y = _data(8_000, seed=7)
    blk = len(X) // nproc
    lo = rank * blk
    hi = len(X) if rank == nproc - 1 else lo + blk
    return {"data": X[lo:hi], "label": y[lo:hi]}


# ---------------------------------------------------------------------------
# CI smoke: the whole loop, fast, with hard assertions
# ---------------------------------------------------------------------------
def run_smoke():
    """Kill + resume + swap in under a minute, exit nonzero on ANY
    broken invariant:

    1. a STREAMED run interrupted by the chaos harness and resumed
       from its round-boundary checkpoint is BIT-IDENTICAL to the
       uninterrupted run;
    2. a warm server hot-swaps the published model with zero dropped
       requests and zero warm-path recompiles (CompileWatch);
    3. a corrupted publish keeps the previous model serving and flips
       serve.model_stale;
    4. an ELASTIC RESIZE cycle (docs/robustness.md "Elastic
       topology"): a 4-shard streamed×sharded run killed mid-run
       resumes at 2 shards through the topology re-cut path,
       BIT-IDENTICAL (quantized gradients) to the uninterrupted
       4-shard run, and the narrower publish hot-swaps into a warm
       server with zero dropped predicts — reported as
       ``elastic_smoke`` in the final record (scripts/check.sh puts
       it on the obs line; scripts/obs_trend.py fails absolutely on
       ``elastic_smoke=0``);
    5. a SERVING-FLEET kill/join cycle (docs/serving.md "Fleet
       deployment"): 3 replicas behind the elastic router, one
       SIGKILLed mid-load (zero dropped requests, relaunch admitted
       only after /readyz), a second killed under a host-gone marker
       (degrade to 2, still zero drops) — reported as
       ``fleet_smoke`` (check.sh exit 9; obs_trend absolute pin).

    (The true-SIGKILL + watchdog variants live in tests/test_chaos.py
    gang tests; this smoke stays in-process for speed — except the
    fleet cycle, whose replicas are real spawned processes.)
    """
    import os
    import tempfile

    # the resize cycle shards a 4-wide mesh: give XLA fake host
    # devices when the environment has none (check.sh runs this on a
    # bare CPU; a real multi-chip host keeps its real devices)
    flags = os.environ.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=4").strip()

    import lightgbm_tpu as lgb
    from lightgbm_tpu import obs
    from lightgbm_tpu.utils.debug import CompileWatch
    t0 = time.time()
    X, y = _data(6_000, seed=1)
    base = {"objective": "binary", "num_leaves": 8, "max_depth": 3,
            "verbosity": -1, "tpu_streaming": "true",
            "tpu_stream_block_rows": 2_048, "checkpoint_interval": 2}
    d1 = tempfile.mkdtemp(prefix="lgbm_chaos_a_")
    pub = tempfile.mkdtemp(prefix="lgbm_chaos_pub_")
    straight = lgb.train(dict(base, checkpoint_dir=d1),
                         lgb.Dataset(X, label=y), num_boost_round=6)
    chaos = dict(base, checkpoint_dir=pub,
                 tpu_fault_inject="exn:iter=4")
    try:
        lgb.train(chaos, lgb.Dataset(X, label=y), num_boost_round=6)
        raise AssertionError("injected fault never fired")
    except lgb.LightGBMError:
        pass
    resumed = lgb.train(chaos, lgb.Dataset(X, label=y),
                        num_boost_round=6, resume_from=pub)
    assert resumed.model_to_string() == straight.model_to_string(), \
        "streamed kill+resume lost bit-equality with the straight run"

    # hot-swap: a warm resident server adopts the streamed publish
    server = lgb.train({"objective": "binary", "num_leaves": 8,
                        "max_depth": 3, "verbosity": -1},
                       lgb.Dataset(X, label=y), num_boost_round=6)
    server.watch_checkpoints(pub, interval=0.0)
    Xq = X[:512]
    server.predict(Xq)
    server.predict(Xq)                      # warm
    with CompileWatch("chaos-swap") as w:
        p_swapped = server.predict(Xq)
    w.assert_compiles(0)
    assert server._model_watch.swaps == 1, "hot-swap never happened"
    np.testing.assert_allclose(p_swapped, resumed.predict(Xq),
                               rtol=1e-5, atol=1e-6)
    _corrupt_newest(pub)
    server._model_watch._last_sig = None
    with CompileWatch("chaos-degrade") as w2:
        p_stale = server.predict(Xq)
    w2.assert_compiles(0)
    np.testing.assert_allclose(p_stale, p_swapped)
    assert server._model_watch.stale, "corrupt publish not flagged"
    g = obs.registry().get("serve.model_stale")
    assert g is not None and g.value == 1.0

    # 4) elastic resize cycle: kill a 4-shard streamed run, resume the
    # SAME checkpoint at 2 shards (the score re-cut path), verify the
    # continued trees are bit-equal to the uninterrupted 4-shard run,
    # and serve through the narrower publish with zero dropped predicts
    e4 = tempfile.mkdtemp(prefix="lgbm_chaos_e4_")
    epub = tempfile.mkdtemp(prefix="lgbm_chaos_epub_")
    ebase = {"objective": "binary", "num_leaves": 8, "max_depth": 3,
             "verbosity": -1, "tpu_streaming": "true",
             "tpu_stream_block_rows": 1_024, "tree_learner": "data",
             "use_quantized_grad": True, "checkpoint_interval": 2}
    straight4 = lgb.train(dict(ebase, tpu_mesh_shape=4,
                               checkpoint_dir=e4),
                          lgb.Dataset(X, label=y), num_boost_round=6)
    try:
        lgb.train(dict(ebase, tpu_mesh_shape=4, checkpoint_dir=epub,
                       tpu_fault_inject="exn:iter=4"),
                  lgb.Dataset(X, label=y), num_boost_round=6)
        raise AssertionError("elastic-cycle fault never fired")
    except lgb.LightGBMError:
        pass
    eserver = lgb.train({"objective": "binary", "num_leaves": 8,
                         "max_depth": 3, "verbosity": -1},
                        lgb.Dataset(X, label=y), num_boost_round=2)
    eserver.watch_checkpoints(epub, interval=0.0)
    edropped = 0
    try:
        eserver.predict(Xq)     # adopts the dying trainer's publish
    except Exception:
        edropped += 1
    resized = lgb.train(dict(ebase, tpu_mesh_shape=2,
                             checkpoint_dir=epub),
                        lgb.Dataset(X, label=y), num_boost_round=6,
                        resume_from=epub)
    assert resized.model_to_string() == straight4.model_to_string(), \
        "elastic resize (4 -> 2 shards) lost bit-equality with the " \
        "uninterrupted 4-shard run"
    p_narrow = None
    for _ in range(3):
        try:
            p_narrow = eserver.predict(Xq)
        except Exception:
            edropped += 1
    assert edropped == 0, f"{edropped} predict(s) dropped across the " \
        f"resize cycle"
    assert eserver._model_watch.swaps >= 2, \
        "narrower publish was never adopted"
    np.testing.assert_allclose(p_narrow, resized.predict(Xq),
                               rtol=1e-5, atol=1e-6)

    # 5) serving-fleet kill/join cycle (docs/serving.md "Fleet
    # deployment"): 3 replica processes behind the elastic router, one
    # SIGKILLed mid-load — its in-flight work re-dispatches to
    # siblings (ZERO dropped requests), the slot relaunches and is
    # admitted only after /readyz — then a second kill under a
    # host-gone marker degrades the fleet to 2, still zero drops.
    # Reported as ``fleet_smoke`` (check.sh exit 9; obs_trend fails
    # absolutely on fleet_smoke=0)
    from lightgbm_tpu.serve import (FleetRouter, FleetSupervisor,
                                    ReplicaModel)
    fspec = [ReplicaModel(model_id="m",
                          model_str=straight.model_to_string(),
                          warmup_row=X[0])]
    fsup = FleetSupervisor(
        {"tpu_serve_max_batch_rows": 128,
         "tpu_serve_batch_budget_ms": 2.0},
        fspec, 3, heartbeat_timeout=8.0, max_restarts=2)
    fdropped = 0
    fref = straight.predict(Xq[:8])
    fsup.start()
    frouter = None
    try:
        assert fsup.wait_ready(3, timeout=180) == 3, \
            "fleet never turned ready"
        frouter = FleetRouter(fsup, request_timeout_s=120.0)
        futs = [frouter.submit("m", Xq[:8]) for _ in range(60)]
        fsup.kill_replica(0)                 # crash -> relaunch path
        futs += [frouter.submit("m", Xq[:8]) for _ in range(60)]
        fsup.kill_replica(1, host_gone=True)  # host gone -> degrade
        for f in futs:
            try:
                np.testing.assert_allclose(f.result(timeout=120),
                                           fref, rtol=1e-5, atol=1e-6)
            except Exception:
                fdropped += 1
        assert fdropped == 0, f"{fdropped} request(s) dropped " \
            f"across the fleet kill cycle"
        fdeadline = time.time() + 120
        while fsup.live_count() < 2 and time.time() < fdeadline:
            time.sleep(0.2)
        assert fsup.live_count() == 2 and fsup.relaunches >= 1, \
            "SIGKILLed replica never rejoined the fleet"
        assert fsup.degrades == 1 and fsup.handles[1].retired, \
            "host-gone slot did not degrade to N-1"
        np.testing.assert_allclose(
            frouter.predict("m", Xq[:8], timeout=60), fref,
            rtol=1e-5, atol=1e-6)
    finally:
        if frouter is not None:
            frouter.close()
        fsup.stop()

    print(json.dumps({
        "chaos_smoke": 1, "elastic_smoke": 1, "fleet_smoke": 1,
        "secs": round(time.time() - t0, 1),
        "resume_bit_exact": True, "swap_compiles": w.compiles,
        "stale_flagged": True, "elastic_recut_bit_exact": True,
        "elastic_dropped_predicts": edropped,
        "fleet_dropped_requests": fdropped,
        "fleet_relaunches": fsup.relaunches,
        "fleet_degrades": fsup.degrades}), flush=True)
    return 0


def main():
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--cycles", type=int, default=5)
    ap.add_argument("--rows", type=int, default=20_000)
    ap.add_argument("--rounds", type=int, default=10)
    ap.add_argument("--leaves", type=int, default=16)
    ap.add_argument("--batch", type=int, default=512,
                    help="rows per predict request")
    ap.add_argument("--requests", type=int, default=20,
                    help="predict requests per cycle")
    ap.add_argument("--think", type=float, default=0.0,
                    help="sleep between requests (s)")
    ap.add_argument("--gang", action="store_true",
                    help="add a true-SIGKILL train_distributed cycle")
    ap.add_argument("--smoke", action="store_true",
                    help="fast CI gate (see run_smoke)")
    args = ap.parse_args()
    if args.smoke:
        return run_smoke()
    return run_cycles(args)


if __name__ == "__main__":
    try:
        sys.exit(main())
    except Exception as e:
        import traceback
        traceback.print_exc()
        print(json.dumps({"chaos_smoke": 0, "elastic_smoke": 0,
                          "fleet_smoke": 0,
                          "error": f"{type(e).__name__}: {e}"}),
              flush=True)
        sys.exit(1)
