"""Probe: nibble-decomposed one-hot generation for the histogram kernel.

The profiled floor of the Pallas histogram kernel is the [F*B, R] one-hot
generation (docs/perf.md): repeat + int32 compare + astype(bf16) per row
block, fused by Mosaic into the matmul operand but still ~3 VPU passes of
F*B*R work. Idea: with b = NB*u + v,

    onehot[(NB*u+v)*F + f, r] = (bins_hi[f,r] == u) * lo_arr[v*F+f, r]

Unrolling u (B/NB steps): per step the lhs is repeat(hi_sel[F,R], NB) *
lo_arr[NB*F, R] — ONE bf16 multiply pass over F*B*R total, plus
(NB + B/NB)*F*R nibble compares (~12% of full-width compares at NB=16).
"""
import sys, os
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import time, functools
import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from lightgbm_tpu.ops.pallas_histogram import multi_leaf_histogram

F, n, B, K, C = 28, 1_048_576, 256, 32, 3
rng = np.random.default_rng(0)
bins_np = rng.integers(0, 255, size=(F, n)).astype(np.int8)
bins_t = jnp.asarray(bins_np)
vals_t = jnp.asarray(rng.normal(size=(C, n)).astype(np.float32))
leaf_id = jnp.asarray(rng.integers(0, K, size=n).astype(np.int32))
small = jnp.arange(K, dtype=jnp.int32)


def bench(fn, tag):
    out = fn()
    jax.block_until_ready(out)
    t0 = time.time()
    for _ in range(5):
        out = fn()
    jax.block_until_ready(out)
    print(f"{tag}: {(time.time()-t0)/5*1000:.1f} ms/scan", flush=True)
    return out


def _nibble_kernel(bins_ref, vals_ref, leaf_ref, small_ref, out_ref, *,
                   num_bins, n_feat, n_leaves, n_chan, nb):
    i = pl.program_id(1)
    bins_blk = bins_ref[...].astype(jnp.int32) & 0xFF    # [F, R]
    vals_blk = vals_ref[...]
    lid = leaf_ref[...]
    sm = small_ref[...]
    mask = (lid == sm).astype(jnp.float32)
    rhs = (mask[:, None, :] * vals_blk[None, :, :]) \
        .reshape(n_leaves * n_chan, -1).astype(jnp.bfloat16)

    n_hi = num_bins // nb
    hi_nib = bins_blk // nb                              # [F, R]
    lo_nib = bins_blk - hi_nib * nb
    lo_rep = pltpu.repeat(lo_nib, nb, axis=0)            # [nb*F, R]
    iota_lo = (jax.lax.broadcasted_iota(jnp.int32, (nb * n_feat, 1), 0)
               // n_feat)
    lo_arr = (lo_rep == iota_lo).astype(jnp.bfloat16)    # [nb*F, R]

    for u in range(n_hi):
        hi_sel = (hi_nib == u).astype(jnp.bfloat16)      # [F, R]
        oh_u = pltpu.repeat(hi_sel, nb, axis=0) * lo_arr
        contrib = jax.lax.dot_general(
            oh_u, rhs, dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)          # [nb*F, K*C]
        sl = slice(u * nb * n_feat, (u + 1) * nb * n_feat)

        @pl.when(i == 0)
        def _():
            out_ref[sl, :] = contrib

        @pl.when(i > 0)
        def _():
            out_ref[sl, :] += contrib


@functools.partial(jax.jit,
                   static_argnames=("num_bins", "rows_per_block", "nb"))
def hist_nibble(bins_t, vals_t, leaf_id, small_ids, *, num_bins,
                rows_per_block=2048, nb=16):
    F, n = bins_t.shape
    C = vals_t.shape[0]
    K = small_ids.shape[0]
    R = rows_per_block
    kernel = functools.partial(_nibble_kernel, num_bins=num_bins, n_feat=F,
                               n_leaves=K, n_chan=C, nb=nb)
    out = pl.pallas_call(
        kernel,
        grid=(1, n // R),
        in_specs=[
            pl.BlockSpec((F, R), lambda j, i: (0, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((C, R), lambda j, i: (0, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, R), lambda j, i: (0, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((K, 1), lambda j, i: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((num_bins * F, K * C), lambda j, i: (0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((num_bins * F, K * C), jnp.float32),
        cost_estimate=pl.CostEstimate(
            flops=2 * F * num_bins * n * K * C,
            bytes_accessed=bins_t.size + vals_t.size * 4 + leaf_id.size * 4,
            transcendentals=0),
    )(bins_t, vals_t, leaf_id.reshape(1, n), small_ids.reshape(K, 1))
    out = out.reshape(num_bins, F, K, C)
    return out.transpose(2, 1, 0, 3)


if __name__ == "__main__":
    print("devices:", jax.devices(), flush=True)
    ref = bench(lambda: multi_leaf_histogram(
        bins_t, vals_t, leaf_id, small, num_bins=B, rows_per_block=2048),
        "current K=32 R=2048")
    got = bench(lambda: hist_nibble(
        bins_t, vals_t, leaf_id, small, num_bins=B, rows_per_block=2048),
        "nibble16 K=32 R=2048")
    err = float(jnp.max(jnp.abs(ref - got)))
    print("max abs diff vs current:", err, flush=True)
    for nb in (32, 64):
        bench(lambda: hist_nibble(bins_t, vals_t, leaf_id, small,
                                  num_bins=B, rows_per_block=2048, nb=nb),
              f"nibble{nb} K=32 R=2048")
    for R in (1024, 4096):
        bench(lambda: hist_nibble(bins_t, vals_t, leaf_id, small,
                                  num_bins=B, rows_per_block=R),
              f"nibble16 K=32 R={R}")
