import sys, os; sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import time, numpy as np, jax
import lightgbm_tpu as lgb
from lightgbm_tpu.boosting.gbdt import GBDT
from lightgbm_tpu.config import Config

rng = np.random.default_rng(0)
X = rng.normal(size=(1_000_000, 28)); y = (X @ rng.normal(size=28) > 0).astype(np.float64)
cfg = Config({"objective": "binary", "num_leaves": 127, "max_bin": 255,
              "verbosity": -1, "tpu_fuse_iters": 1})  # UNFUSED
eng = GBDT(cfg, lgb.Dataset(X, label=y))
eng.train_one_iter(); jax.block_until_ready(eng.score)
t0 = time.time()
for _ in range(5): eng.train_one_iter()
jax.block_until_ready(eng.score)
print(f"unfused: {5/(time.time()-t0):.2f} iters/s", flush=True)

cfg2 = Config({"objective": "binary", "num_leaves": 127, "max_bin": 255,
               "verbosity": -1})
eng2 = GBDT(cfg2, lgb.Dataset(X, label=y))
eng2.train_chunk(10); jax.block_until_ready(eng2.score)
t0 = time.time(); eng2.train_chunk(10); jax.block_until_ready(eng2.score)
print(f"fused(10): {10/(time.time()-t0):.2f} iters/s", flush=True)
