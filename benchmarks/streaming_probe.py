"""Out-of-core (tpu_streaming) throughput probe — VERDICT r4 item 3.

Builds a synthetic dataset whose BINNED matrix can exceed device HBM
(v5e: 16 GiB; --gib 32 is the 2x-over-HBM proof shape), ingests it via
the streaming push_rows path (raw floats are dropped chunk by chunk —
host RAM holds only the uint8 bins + per-row f32 state), trains a few
trees with the streaming engine, and prints one JSON line:

  rows, binned_gib, s_per_tree, iters_per_sec, stream_gib_s (effective
  host->device bandwidth achieved during sweeps), sweeps_per_tree.

Context for reading the numbers: through this environment's tunneled
chip, raw device_put bandwidth measures ~1.4 GiB/s (a co-located v5e
host does ~10-20x that), so s_per_tree here is tunnel-bound — the
probe reports stream_gib_s precisely so the co-located projection is
arithmetic, not faith.

With ``--shards "1,2"`` the probe re-trains the SAME rows at each
shard count (sharded streamed training, one packed collective per
level — docs/perf.md "Streamed x sharded") and prints one JSON line
per point, including ``stream_rows_per_sec`` and the comm counters.
Shard counts above the platform's device count force fake CPU host
devices, so the grid runs anywhere (scaling numbers on fake devices
measure the orchestration, not real ICI — read them as overhead
bounds; on real hardware each shard is a chip).

Usage:
  python benchmarks/streaming_probe.py --gib 2 --trees 3   # quick
  python benchmarks/streaming_probe.py --gib 32 --trees 2  # >HBM proof
  python benchmarks/streaming_probe.py --gib 1 --shards 1,2,4
  python benchmarks/streaming_probe.py --gib 1 --shards 2 --no-overlap
                                  # A/B arm: synchronous dispatch
"""
import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, __file__.rsplit("/", 2)[0])
# amortize TPU compiles across probe runs (the level sweeps compile
# one specialization per power-of-two frontier size)
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                      "/tmp/lgbm_tpu_compile_cache")

F = 28


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--gib", type=float, default=2.0,
                    help="target binned size in GiB (rows = gib/F)")
    ap.add_argument("--trees", type=int, default=3)
    ap.add_argument("--leaves", type=int, default=32)
    ap.add_argument("--chunk", type=int, default=20_000_000)
    ap.add_argument("--shards", type=str, default="1",
                    help="comma list of shard counts to grid over the "
                         "SAME total rows (tree_learner=data + "
                         "tpu_mesh_shape); >1 on a single-device "
                         "platform uses fake CPU host devices")
    ap.add_argument("--no-overlap", action="store_true",
                    help="train with tpu_stream_overlap=false (fully "
                         "synchronous per-block dispatch) — the A/B "
                         "arm for docs/perf.md 'Communication/compute "
                         "overlap'")
    args = ap.parse_args()
    shard_grid = [max(1, int(s)) for s in args.shards.split(",") if s]
    if max(shard_grid) > 1:
        # fake host devices ONLY when the real platform cannot seat the
        # grid — probed in a subprocess so this process's backend is
        # still uninitialized when the flags must land. A real
        # multi-chip host keeps its real devices (those are the
        # numbers the probe exists to publish).
        import subprocess
        try:
            real = int(subprocess.run(
                [sys.executable, "-c",
                 "import jax; print(jax.device_count())"],
                capture_output=True, text=True, timeout=120
            ).stdout.strip() or "1")
        except Exception:
            real = 1
        if real < max(shard_grid):
            flags = os.environ.get("XLA_FLAGS", "")
            if "host_platform_device_count" not in flags:
                os.environ["XLA_FLAGS"] = (
                    flags + f" --xla_force_host_platform_device_count="
                    f"{max(shard_grid)}").strip()
                os.environ.setdefault("JAX_PLATFORMS", "cpu")
            print(f"# streaming_probe: platform has {real} device(s) < "
                  f"{max(shard_grid)} shards -> FAKE CPU host devices; "
                  f"scaling numbers measure orchestration overhead, "
                  f"not real multi-chip throughput", file=sys.stderr)

    import lightgbm_tpu as lgb

    n = int(args.gib * 2**30 / F)
    rng = np.random.default_rng(0)
    params = {"objective": "binary", "num_leaves": args.leaves,
              "max_bin": 255, "verbosity": 1, "tpu_streaming": "true",
              "learning_rate": 0.1,
              "tpu_stream_overlap":
                  "false" if args.no_overlap else "auto"}

    t0 = time.time()
    # reference dataset: bin mappers from a 2M-row sample of the
    # generator (the loader-level sample the reference would take)
    w = rng.normal(size=F).astype(np.float32)

    def gen(m, seed):
        r = np.random.default_rng(seed)
        X = r.random(size=(m, F), dtype=np.float32)
        logit = (X - 0.5) @ w * 3.0 + 2.0 * (X[:, 0] - 0.5) * (X[:, 1] - 0.5)
        y = (logit + r.normal(scale=0.5, size=m).astype(np.float32)
             > 0).astype(np.float64)
        return X, y

    Xs, ys = gen(min(n, 2_000_000), 1)
    ref = lgb.Dataset(Xs, label=ys, params=dict(params))
    ref.construct()
    ds = lgb.Dataset(None, reference=ref, params=dict(params))
    done = 0
    ci = 0
    while done < n:
        m = min(args.chunk, n - done)
        Xc, yc = gen(m, 100 + ci)
        ds.push_rows(Xc, label=yc)
        done += m
        ci += 1
    ds.construct()
    build_s = time.time() - t0
    binned_gib = ds.binned.nbytes / 2**30

    for shards in shard_grid:
        p = dict(params)
        if shards > 1:
            p["tree_learner"] = "data"
            p["tpu_mesh_shape"] = shards
        t0 = time.time()
        bst = lgb.train(p, ds, num_boost_round=args.trees)
        train_s = time.time() - t0
        eng = bst.engine
        # sweeps per tree = depth levels + final; measure from depth
        depth = int(np.ceil(np.log2(max(args.leaves, 2))))
        sweeps = depth + 1      # level sweeps (incl. root) + final
        gib_swept = binned_gib * sweeps * args.trees
        cs = eng.comm_stats
        out = {
            "rows": n,
            "binned_gib": round(binned_gib, 2),
            "build_s": round(build_s, 1),
            "s_per_tree": round(train_s / args.trees, 2),
            "iters_per_sec": round(args.trees / train_s, 4),
            "stream_gib_s": round(gib_swept / train_s, 2),
            "sweeps_per_tree": sweeps,
            "n_blocks": eng.n_blocks,
            "stream_shards": shards,
            "overlap": "off" if args.no_overlap else "on",
            "stream_rows_per_sec": round(n * args.trees / train_s, 1),
            "allreduce_calls": cs["allreduce_calls"],
            "allreduce_bytes": cs["allreduce_bytes"],
            "acc_proxy": round(float(np.mean(
                (bst.predict(Xs) > 0.5) == ys)), 4),
        }
        print(json.dumps(out))


if __name__ == "__main__":
    main()
