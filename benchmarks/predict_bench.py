"""Serving benchmark: prediction rows/sec and per-call latency.

Grid: batch sizes {1, 128, 10k, 1M} x forest sizes {50, 500} trees
(overridable), reporting FIRST-CALL latency (compile + stack + upload)
separately from STEADY-STATE per-call latency and rows/sec — the
serving numbers docs/perf.md's "Serving" section records. ``--legacy``
times the pre-PR path (per-tree scan traversal, no bucketing, no
stacked-forest cache) for the speedup ratio. ``--shap-batches`` adds
pred_contrib (SHAP) cells on the same grid — device engine path, plus
the host rows-vectorized path and a per-cell speedup under
``--compare`` (docs/perf.md "Device SHAP").

Run:
  python benchmarks/predict_bench.py                 # full grid
  python benchmarks/predict_bench.py --trees 200 --batches 10000
  python benchmarks/predict_bench.py --legacy        # pre-PR baseline
  python benchmarks/predict_bench.py --compare       # both paths, one
                                                     # trained model,
                                                     # speedup ratios

Each line is one JSON record; the final line aggregates.
"""
import argparse
import json
import sys
import time

import numpy as np

sys.path.insert(0, __file__.rsplit("/", 2)[0])


def _train_booster(n_rows, n_feat, trees, num_leaves, seed=0):
    import lightgbm_tpu as lgb
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n_rows, n_feat))
    w = rng.normal(size=n_feat)
    y = ((X @ w + 0.5 * X[:, 0] * X[:, 1]
          + rng.normal(scale=0.5, size=n_rows)) > 0).astype(np.float64)
    ds = lgb.Dataset(X, label=y)
    return lgb.train({"objective": "binary", "num_leaves": num_leaves,
                      "learning_rate": 0.1, "verbosity": -1},
                     ds, num_boost_round=trees)


def bench_batch(bst, X, batch, legacy, min_steady_s=1.0, max_calls=50):
    """One (model, batch) cell: first call, then timed steady calls."""
    rng = np.random.default_rng(1)
    Xb = X[rng.integers(0, len(X), size=batch)]
    kwargs = ({"tpu_predict_parallel_trees": False,
               "tpu_predict_buckets": False} if legacy else {})
    if legacy:
        # the pre-PR path also re-stacked the forest every call
        bst.engine.config.tpu_predict_cache = False
    t0 = time.time()
    bst.predict(Xb, raw_score=True, **kwargs)
    first_s = time.time() - t0
    lat = []
    t_all = 0.0
    for _ in range(max_calls):
        t0 = time.time()
        bst.predict(Xb, raw_score=True, **kwargs)
        dt = time.time() - t0
        lat.append(dt)
        t_all += dt
        if t_all > min_steady_s and len(lat) >= 3:
            break
    if legacy:
        bst.engine.config.tpu_predict_cache = True
    med = sorted(lat)[len(lat) // 2]
    return {"first_call_s": round(first_s, 4),
            "steady_latency_s": round(med, 5),
            "steady_rows_per_sec": round(batch / med, 1),
            "steady_calls": len(lat)}


def bench_shap(bst, X, batch, host, min_steady_s=1.0, max_calls=50):
    """One SHAP (pred_contrib) cell: device engine path vs the host
    rows-vectorized path (``--compare``). First call carries the path
    table build + compile; steady state is the serving number."""
    rng = np.random.default_rng(1)
    Xb = X[rng.integers(0, len(X), size=batch)]
    if host:
        hm = bst._to_host_model()
        call = lambda: hm.predict(Xb, pred_contrib=True)  # noqa: E731
    else:
        call = lambda: bst.predict(Xb, pred_contrib=True)  # noqa: E731
    t0 = time.time()
    call()
    first_s = time.time() - t0
    lat = []
    t_all = 0.0
    for _ in range(max_calls):
        t0 = time.time()
        call()
        dt = time.time() - t0
        lat.append(dt)
        t_all += dt
        if t_all > min_steady_s and len(lat) >= 3:
            break
    med = sorted(lat)[len(lat) // 2]
    return {"first_call_s": round(first_s, 4),
            "steady_latency_s": round(med, 5),
            "steady_rows_per_sec": round(batch / med, 1),
            "steady_calls": len(lat)}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--trees", type=str, default="50,500")
    ap.add_argument("--batches", type=str, default="1,128,10000,1000000")
    ap.add_argument("--shap-batches", type=str, default="128,10000",
                    help="pred_contrib (SHAP) batch sizes; '' skips "
                         "the SHAP cells")
    ap.add_argument("--rows-train", type=int, default=20000)
    ap.add_argument("--features", type=int, default=28)
    ap.add_argument("--num-leaves", type=int, default=31)
    ap.add_argument("--legacy", action="store_true",
                    help="pre-PR path: per-tree scan traversal, no "
                         "bucketing, no stacked-forest cache")
    ap.add_argument("--compare", action="store_true",
                    help="bench BOTH paths on one trained model and "
                         "report per-cell speedup ratios")
    ap.add_argument("--metrics-json", type=str, default="",
                    help="append one obs metrics-snapshot JSONL line "
                         "(docs/observability.md schema) to PATH; also "
                         "enables tpu_metrics for the run, so the "
                         "snapshot carries the predict latency "
                         "histograms and cache-hit counters")
    args = ap.parse_args()
    from lightgbm_tpu import obs
    if args.metrics_json:
        obs.enable(metrics=True)
    trees_list = [int(t) for t in args.trees.split(",")]
    batches = [int(b) for b in args.batches.split(",")]
    paths = ([False, True] if args.compare
             else [bool(args.legacy)])      # legacy flag per path

    rng = np.random.default_rng(2)
    X_pool = rng.normal(size=(max(min(max(batches), 100000), 1000),
                              args.features))

    results = []
    shap_results = []
    for trees in trees_list:
        t0 = time.time()
        bst = _train_booster(args.rows_train, args.features, trees,
                             args.num_leaves)
        train_s = time.time() - t0
        for batch in batches:
            cells = {}
            for legacy in paths:
                name = "legacy-scan" if legacy else "tree-parallel"
                cell = bench_batch(bst, X_pool, batch, legacy)
                cells[name] = cell
                rec = {"trees": trees, "batch": batch, "path": name,
                       **cell}
                results.append(rec)
                print(json.dumps(rec), flush=True)
            if len(cells) == 2:
                ratio = (cells["tree-parallel"]["steady_rows_per_sec"]
                         / cells["legacy-scan"]["steady_rows_per_sec"])
                print(json.dumps({"trees": trees, "batch": batch,
                                  "speedup_vs_legacy":
                                  round(ratio, 2)}), flush=True)
        for batch in [int(b) for b in args.shap_batches.split(",") if b]:
            cell = bench_shap(bst, X_pool, batch, host=False)
            rec = {"trees": trees, "batch": batch, "path": "device-shap",
                   **cell}
            shap_results.append(rec)
            print(json.dumps(rec), flush=True)
            if args.compare:
                hcell = bench_shap(bst, X_pool, batch, host=True)
                print(json.dumps({"trees": trees, "batch": batch,
                                  "path": "host-shap", **hcell}),
                      flush=True)
                ratio = (cell["steady_rows_per_sec"]
                         / hcell["steady_rows_per_sec"])
                print(json.dumps({"trees": trees, "batch": batch,
                                  "shap_speedup_vs_host":
                                  round(ratio, 2)}), flush=True)
        print(json.dumps({"trees": trees, "train_s": round(train_s, 1)}),
              flush=True)
    # the aggregate line reads from an obs snapshot (the snapshot is
    # authoritative; --metrics-json dumps the same one)
    best = max(results, key=lambda r: r["steady_rows_per_sec"])
    obs.set_gauge("bench.predict_rows_per_sec_best",
                  best["steady_rows_per_sec"], force=True)
    if shap_results:
        sbest = max(shap_results,
                    key=lambda r: r["steady_rows_per_sec"])
        obs.set_gauge("bench.shap_rows_per_sec",
                      sbest["steady_rows_per_sec"], force=True)
    snap = obs.snapshot()
    if args.metrics_json:
        obs.dump_jsonl(args.metrics_json, snap)
    val = next(m["value"] for m in snap["metrics"]
               if m["name"] == "bench.predict_rows_per_sec_best")
    print(json.dumps({"metric": "predict_rows_per_sec_best",
                      "value": val,
                      "path": best["path"]}))


if __name__ == "__main__":
    main()
