"""Ingest benchmark: raw matrix -> binned matrix, host vs device.

Grid: (rows, features, max_bin) cells, timing four bin-ASSIGNMENT
paths over identical pre-built BinMappers (boundary finding is excluded
— it is sample-sized and shared by every path):

  host-loop     the serial per-column numpy fallback (native binner off)
  host-threaded the thread-pooled per-column fallback (tpu_ingest_threads)
  host-native   the one-pass C++ row-major binner (the pre-PR fast path)
  device        ops/ingest.py chunked on-accelerator assignment
                (first call = compile-inclusive; steady = cached kernel)

Run:
  python benchmarks/ingest_bench.py                      # default grid
  python benchmarks/ingest_bench.py --rows 2000000 --features 28
  python benchmarks/ingest_bench.py --compare            # speedup line
                                                         # per cell

Each line is one JSON record; ``--compare`` adds a
``speedup_device_vs_best_host`` record per cell and a final aggregate.
"""
import argparse
import json
import sys
import time

import numpy as np

sys.path.insert(0, __file__.rsplit("/", 2)[0])


def _synth(rows, features, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(rows, features)).astype(np.float32) \
        .astype(np.float64)
    if features >= 3:
        X[:, 1] = np.where(rng.uniform(size=rows) < 0.2, 0.0, X[:, 1])
        X[rng.uniform(size=rows) < 0.05, 2] = np.nan
    return np.ascontiguousarray(X)


def _dataset_for(X, y, mode, threads):
    import lightgbm_tpu as lgb
    dev = {"device": "true"}.get(mode, "false")
    return lgb.Dataset(X, label=y, params={
        "tpu_ingest_device": dev,
        "tpu_ingest_threads": threads,
        "verbosity": -1})


def time_mode(X, mappers, mode, threads=0, repeats=2):
    """Median construct-side assignment time for one path. Mapper
    finding is done by the caller once; here the Dataset is pre-seeded
    with those mappers so only bin ASSIGNMENT is on the clock."""
    from lightgbm_tpu.io import binning as binning_mod
    native_fn = binning_mod._native
    if mode in ("host-loop", "host-threaded"):
        binning_mod._native = lambda: None      # force the numpy path
    try:
        times = []
        first_s = None
        for r in range(repeats + (1 if mode == "device" else 0)):
            ds = _dataset_for(X, None, mode,
                              threads if mode == "host-threaded" else 1)
            ds.bin_mappers = list(mappers)      # pre-seeded: construct
            t0 = time.time()                    # keeps them verbatim
            ds.construct()
            ing = ds.device_ingested()
            if ing is not None:
                ing.bins.block_until_ready()
            else:
                _ = ds.binned.shape
            dt = time.time() - t0
            if mode == "device" and r == 0:
                first_s = dt                    # compile-inclusive
            else:
                times.append(dt)
        return sorted(times)[len(times) // 2], first_s
    finally:
        binning_mod._native = native_fn


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=str, default="200000,1000000")
    ap.add_argument("--features", type=str, default="28")
    ap.add_argument("--max-bin", type=str, default="255")
    ap.add_argument("--threads", type=int, default=0,
                    help="host-threaded pool size (0 = auto)")
    ap.add_argument("--repeats", type=int, default=2)
    ap.add_argument("--modes", type=str,
                    default="host-loop,host-threaded,host-native,device")
    ap.add_argument("--compare", action="store_true",
                    help="print a device-vs-best-host speedup line per "
                         "cell")
    ap.add_argument("--metrics-json", type=str, default="",
                    help="append one obs metrics-snapshot JSONL line "
                         "(docs/observability.md schema) to PATH; also "
                         "enables tpu_metrics for the run, so the "
                         "snapshot carries ingest H2D-bytes/chunk "
                         "counters and construct timings")
    args = ap.parse_args()
    from lightgbm_tpu import obs
    if args.metrics_json:
        obs.enable(metrics=True)
    from lightgbm_tpu.io.binning import find_bin_mappers

    rows_list = [int(r) for r in args.rows.split(",")]
    feat_list = [int(f) for f in args.features.split(",")]
    mb_list = [int(b) for b in args.max_bin.split(",")]
    modes = [m.strip() for m in args.modes.split(",") if m.strip()]

    best_speedup = None
    for rows in rows_list:
        for features in feat_list:
            X = _synth(rows, features)
            for max_bin in mb_list:
                mappers = find_bin_mappers(X, max_bin=max_bin)
                cell = {}
                for mode in modes:
                    med, first = time_mode(X, mappers, mode,
                                           args.threads, args.repeats)
                    rec = {"rows": rows, "features": features,
                           "max_bin": max_bin, "mode": mode,
                           "assign_s": round(med, 4),
                           "mrows_per_s": round(rows / med / 1e6, 2)}
                    if first is not None:
                        rec["first_call_s"] = round(first, 4)
                    cell[mode] = med
                    print(json.dumps(rec), flush=True)
                if args.compare and "device" in cell:
                    hosts = {m: t for m, t in cell.items()
                             if m != "device"}
                    if hosts:
                        best_host = min(hosts, key=hosts.get)
                        ratio = hosts[best_host] / cell["device"]
                        best_speedup = max(best_speedup or 0.0, ratio)
                        print(json.dumps({
                            "rows": rows, "features": features,
                            "max_bin": max_bin,
                            "speedup_device_vs_best_host":
                                round(ratio, 2),
                            "best_host": best_host}), flush=True)
    # aggregate from an obs snapshot (authoritative; --metrics-json
    # dumps the same one)
    if best_speedup is not None:
        obs.set_gauge("bench.ingest_speedup_best",
                      round(best_speedup, 2), force=True)
    snap = obs.snapshot()
    if args.metrics_json:
        obs.dump_jsonl(args.metrics_json, snap)
    if args.compare and best_speedup is not None:
        val = next(m["value"] for m in snap["metrics"]
                   if m["name"] == "bench.ingest_speedup_best")
        print(json.dumps({"metric": "ingest_speedup_best",
                          "value": val}))


if __name__ == "__main__":
    main()
