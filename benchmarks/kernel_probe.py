import sys, os; sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import time, functools, numpy as np, jax, jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from lightgbm_tpu.ops.pallas_histogram import multi_leaf_histogram, _hist_kernel

F, n, B, K, C, R = 28, 1_048_576, 256, 16, 3, 2048
rng = np.random.default_rng(0)
bins_t = jnp.asarray(rng.integers(0, 255, size=(F, n)).astype(np.int8))
vals_t = jnp.asarray(rng.normal(size=(C, n)).astype(np.float32))
leaf_id = jnp.zeros(n, jnp.int32)
small = jnp.arange(K, dtype=jnp.int32)

def bench(fn, tag):
    out = fn(); jax.block_until_ready(out)
    t0 = time.time()
    for _ in range(5):
        out = fn()
    jax.block_until_ready(out)
    print(f"{tag}: {(time.time()-t0)/5*1000:.1f} ms/scan")

bench(lambda: multi_leaf_histogram(bins_t, vals_t, leaf_id, small, num_bins=B, rows_per_block=R), "2D-grid current")

# old 1-D grid formulation
def _kernel1d(bins_ref, vals_ref, leaf_ref, small_ref, out_ref, *, num_bins, n_feat, n_leaves, n_chan):
    i = pl.program_id(0)
    bins_blk = bins_ref[...].astype(jnp.int32) & 0xFF
    vals_blk = vals_ref[...]
    lid = leaf_ref[...]
    sm = small_ref[...]
    mask = (lid == sm).astype(jnp.float32)
    rhs = (mask[:, None, :] * vals_blk[None, :, :]).reshape(n_leaves * n_chan, -1).astype(jnp.bfloat16)
    big = pltpu.repeat(bins_blk, num_bins, axis=0)
    iota_b = (jax.lax.broadcasted_iota(jnp.int32, (n_feat * num_bins, 1), 0) // n_feat)
    onehot = (big == iota_b).astype(jnp.bfloat16)
    contrib = jax.lax.dot_general(onehot, rhs, dimension_numbers=(((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
    @pl.when(i == 0)
    def _(): out_ref[...] = contrib
    @pl.when(i > 0)
    def _(): out_ref[...] += contrib

@functools.partial(jax.jit, static_argnames=("num_bins", "rows_per_block"))
def hist1d(bins_t, vals_t, leaf_id, small_ids, *, num_bins, rows_per_block=2048):
    F, n = bins_t.shape; C = vals_t.shape[0]; K = small_ids.shape[0]; R = rows_per_block
    kernel = functools.partial(_kernel1d, num_bins=num_bins, n_feat=F, n_leaves=K, n_chan=C)
    out = pl.pallas_call(kernel, grid=(n // R,),
        in_specs=[pl.BlockSpec((F, R), lambda i: (0, i), memory_space=pltpu.VMEM),
                  pl.BlockSpec((C, R), lambda i: (0, i), memory_space=pltpu.VMEM),
                  pl.BlockSpec((1, R), lambda i: (0, i), memory_space=pltpu.VMEM),
                  pl.BlockSpec((K, 1), lambda i: (0, 0), memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec((num_bins * F, K * C), lambda i: (0, 0), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((num_bins * F, K * C), jnp.float32),
        cost_estimate=pl.CostEstimate(flops=2*F*num_bins*n*K*C, bytes_accessed=bins_t.size + vals_t.size*4 + leaf_id.size*4, transcendentals=0),
    )(bins_t, vals_t, leaf_id.reshape(1, n), small_ids.reshape(K, 1))
    return out.reshape(num_bins, F, K, C).transpose(2, 1, 0, 3)

bench(lambda: hist1d(bins_t, vals_t, leaf_id, small, num_bins=B, rows_per_block=R), "1D-grid old")
