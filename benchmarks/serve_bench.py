"""Serving-service load harness: sustained RPS at a p99 target.

Drives the async serving stack end to end — N client threads submit
concurrent requests for M tenants' models through one
``PredictService`` (micro-batching queue + LRU registry + optional
tree-sharded predict) — and reports the SLO-shaped numbers ROADMAP
item 1 asks for: sustained requests/sec, predict p50/p99 against a
target, live queue depth, batch fill ratio, and cache hit/eviction
accounting. Exit status is the SLO verdict: nonzero when the measured
p99 misses ``--p99-target-ms`` or any request dropped.

Run:
  python benchmarks/serve_bench.py                      # 4 models,
                                                        # 8 clients, 10 s
  python benchmarks/serve_bench.py --models 8 --clients 16 --seconds 30
  python benchmarks/serve_bench.py --cache-models 2     # force LRU churn
  python benchmarks/serve_bench.py --smoke              # CI gate:
    sub-minute — concurrent clients, one LRU eviction, one mid-traffic
    hot-swap; exit 0 iff zero requests dropped AND zero warm-path
    compiles (scripts/check.sh appends the result as serve_smoke= on
    the obs line; scripts/obs_trend.py fails ABSOLUTELY on
    serve_smoke=0)

Each line is one JSON record; the final line aggregates.
"""
import argparse
import json
import os
import shutil
import sys
import threading
import time

import numpy as np

sys.path.insert(0, __file__.rsplit("/", 2)[0])


def _data(n, f=10, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f))
    y = (X[:, 0] + 0.5 * X[:, 1] * X[:, 2]
         + rng.normal(scale=0.3, size=n) > 0).astype(np.float64)
    return X, y


def _train(X, y, rounds, leaves, seed=0):
    import lightgbm_tpu as lgb
    return lgb.train({"objective": "binary", "num_leaves": leaves,
                      "verbosity": -1, "seed": seed},
                     lgb.Dataset(X, label=y), num_boost_round=rounds)


def _client(svc, model_ids, X_pool, batch, stop, lat, drops, seed):
    rng = np.random.default_rng(seed)
    while not stop.is_set():
        mid = model_ids[int(rng.integers(0, len(model_ids)))]
        rows = X_pool[rng.integers(0, len(X_pool), size=batch)]
        t0 = time.perf_counter()
        try:
            svc.predict(mid, rows, timeout=30.0)
            lat.append(time.perf_counter() - t0)
        except Exception:
            drops.append(mid)


def _quantile(sorted_lat, q):
    if not sorted_lat:
        return None
    i = min(int(q * len(sorted_lat)), len(sorted_lat) - 1)
    return sorted_lat[i]


# ---------------------------------------------------------------------------
# full load run
# ---------------------------------------------------------------------------
def run_load(args):
    from lightgbm_tpu import obs
    from lightgbm_tpu.obs import slo as _slo
    from lightgbm_tpu.serve import PredictService
    obs.enable(metrics=True, slo=True)
    X, y = _data(args.rows)
    svc = PredictService({
        "tpu_serve_batch_budget_ms": args.budget_ms,
        "tpu_serve_max_batch_rows": args.max_batch_rows,
        "tpu_serve_cache_models": args.cache_models,
        "tpu_serve_shard_trees": args.shard_trees,
        # expose GET /metrics (+ /readyz) mid-run so slo.queue_depth /
        # serve.cache_hits can be scraped live while the load runs
        "tpu_metrics_port": args.metrics_port,
    })
    model_ids = []
    for m in range(args.models):
        bst = _train(X, y, args.rounds, args.leaves, seed=m)
        mid = f"tenant{m}"
        svc.add_model(mid, bst)
        svc.warmup(mid, X[:1])
        model_ids.append(mid)
    print(json.dumps({"models": args.models, "warmed": True}),
          flush=True)

    lat, drops = [], []
    stop = threading.Event()
    threads = [threading.Thread(
        target=_client, args=(svc, model_ids, X, args.batch, stop, lat,
                              drops, 100 + i), daemon=True)
        for i in range(args.clients)]
    t0 = time.time()
    for t in threads:
        t.start()
    depth_max = 0
    while time.time() - t0 < args.seconds:
        depth_max = max(depth_max, svc.queue.depth())
        time.sleep(0.05)
    stop.set()
    for t in threads:
        t.join(timeout=30)
    elapsed = time.time() - t0

    slat = sorted(lat)
    p50, p99 = _quantile(slat, 0.50), _quantile(slat, 0.99)
    rps = len(lat) / elapsed
    reg = obs.registry()

    def metric(name):
        m = reg.get(name)
        return getattr(m, "value", None)

    slis = (_slo.tracker().compute() if _slo.tracker() else {})
    met = (p99 is not None and p99 * 1000.0 <= args.p99_target_ms
           and not drops)
    obs.set_gauge("bench.serve_rps", round(rps, 1), force=True)
    obs.set_gauge("bench.serve_p99_ms",
                  round((p99 or 0.0) * 1000.0, 3), force=True)
    rec = {
        "clients": args.clients, "models": args.models,
        "seconds": round(elapsed, 1), "requests": len(lat),
        "rps": round(rps, 1),
        "p50_ms": round((p50 or 0.0) * 1e3, 2),
        "p99_ms": round((p99 or 0.0) * 1e3, 2),
        "p99_target_ms": args.p99_target_ms, "met_target": bool(met),
        "dropped": len(drops),
        "queue_depth_max": depth_max,
        "slo_queue_depth": slis.get("slo.queue_depth"),
        "dispatches": metric("serve.dispatches"),
        "coalesced_requests": metric("serve.coalesced_requests"),
        "batch_fill_ratio": metric("serve.batch_fill_ratio"),
        "cache_hits": metric("serve.cache_hits"),
        "evictions": metric("serve.evictions"),
    }
    svc.close()
    if args.metrics_json:
        obs.dump_jsonl(args.metrics_json)
    print(json.dumps(rec), flush=True)
    return 0 if met else 1


# ---------------------------------------------------------------------------
# CI smoke: clients + one eviction + one mid-traffic swap, hard asserts
# ---------------------------------------------------------------------------
def _publish(staging, pub):
    """Land a pre-trained checkpoint mid-traffic: payloads first, the
    ``latest.rank*`` pointers last (the order the atomic publisher
    guarantees)."""
    names = sorted(os.listdir(staging))
    pointers = [n for n in names if n.startswith("latest.")]
    for name in names:
        if name not in pointers:
            shutil.copy(os.path.join(staging, name),
                        os.path.join(pub, name))
    for name in pointers:
        shutil.copy(os.path.join(staging, name),
                    os.path.join(pub, name))


def run_smoke():
    """Sub-minute serving gate, exit nonzero on ANY broken invariant:

    1. N concurrent clients over 2 tenants with a 1-model LRU — every
       request resolves (ZERO drops) through eviction churn;
    2. a checkpoint published MID-TRAFFIC hot-swaps in (watcher swap
       under the swap lock) without dropping or corrupting a request;
    3. the whole loaded phase — coalescing, evictions, re-admissions,
       the swap — compiles ZERO XLA programs after warmup
       (CompileWatch);
    4. the live plane is real: slo.queue_depth sampled, cache
       hits/evictions counted, heartbeat.serve stamped.
    """
    import tempfile

    import lightgbm_tpu as lgb
    from lightgbm_tpu import obs
    from lightgbm_tpu.utils.debug import CompileWatch
    t0 = time.time()
    obs.enable(metrics=True, slo=True)
    X, y = _data(4_000, seed=1)
    rounds, leaves = 4, 8
    bA = _train(X, y, rounds, leaves, seed=0)
    bB = _train(X, y, rounds, leaves, seed=1)
    # v2 of tenant A, published mid-traffic below (pre-trained so the
    # CompileWatch window sees serving compiles only). Same tree count
    # and leaf cap as bA — the swap must reuse every compiled program —
    # but a different learning rate, so its PREDICTIONS visibly differ
    # and the post-swap equality assert below has teeth
    staging = tempfile.mkdtemp(prefix="lgbm_serve_stage_")
    pub = tempfile.mkdtemp(prefix="lgbm_serve_pub_")
    try:
        return _run_smoke_body(lgb, obs, CompileWatch, t0, X, y,
                               rounds, leaves, bA, bB, staging, pub)
    finally:
        # check.sh runs this every invocation: leaked checkpoint dirs
        # would accumulate unbounded /tmp disk across CI runs
        shutil.rmtree(staging, ignore_errors=True)
        shutil.rmtree(pub, ignore_errors=True)


def _run_smoke_body(lgb, obs, CompileWatch, t0, X, y, rounds, leaves,
                    bA, bB, staging, pub):
    v2 = lgb.train({"objective": "binary", "num_leaves": leaves,
                    "verbosity": -1, "learning_rate": 0.05,
                    "checkpoint_dir": staging,
                    "checkpoint_interval": rounds},
                   lgb.Dataset(X, label=y), num_boost_round=rounds)

    svc = lgb.PredictService({"tpu_serve_batch_budget_ms": 2.0,
                              "tpu_serve_max_batch_rows": 512,
                              "tpu_serve_cache_models": 1,
                              "tpu_serve_shard_trees": "false"})
    svc.add_model("a", bA, watch_dir=pub, watch_interval=0.0)
    svc.add_model("b", bB)
    svc.warmup("a", X[:1])
    svc.warmup("b", X[:1])
    Xq = X[:64]
    pre_swap = bA.predict(Xq)

    lat, drops = [], []
    stop = threading.Event()
    threads = [threading.Thread(
        target=_client, args=(svc, ["a", "b"], X, 64, stop, lat, drops,
                              100 + i), daemon=True)
        for i in range(4)]
    depth_max = 0
    with CompileWatch("serve-smoke") as w:
        for t in threads:
            t.start()
        time.sleep(1.0)
        _publish(staging, pub)          # the mid-traffic swap
        t1 = time.time()
        while time.time() - t1 < 2.0:
            depth_max = max(depth_max, svc.queue.depth())
            time.sleep(0.02)
        stop.set()
        for t in threads:
            t.join(timeout=30)
    watcher = bA._model_watch
    reg = obs.registry()

    def metric(name):
        m = reg.get(name)
        return getattr(m, "value", 0.0) or 0.0

    assert not drops, f"{len(drops)} request(s) dropped under load"
    assert watcher.swaps >= 1, "mid-traffic publish never swapped in"
    assert metric("serve.evictions") >= 1, "1-model LRU never evicted"
    assert metric("serve.cache_hits") >= 1, "no warm cache hits"
    w.assert_compiles(0)                # zero warm-path programs
    assert reg.get("heartbeat.serve") is not None, \
        "dispatch loop never stamped heartbeat.serve"
    # post-swap serving must match the published model EXACTLY — a
    # swap that leaves a stale stack (or truncates adoption) serves
    # wrong values with the right shape, which only this catches
    swapped = svc.predict("a", Xq, timeout=10.0)
    expected = v2.predict(Xq)
    assert np.array_equal(swapped, expected), \
        "post-swap serving diverged from the published model"
    assert not np.array_equal(expected, pre_swap), \
        "v2 indistinguishable from v1 — the swap assert has no teeth"
    svc.close()
    print(json.dumps({
        "serve_smoke": 1, "secs": round(time.time() - t0, 1),
        "requests": len(lat), "dropped": 0,
        "swaps": watcher.swaps,
        "evictions": metric("serve.evictions"),
        "cache_hits": metric("serve.cache_hits"),
        "queue_depth_max": depth_max,
        "warm_compiles": w.compiles,
        "post_swap_rows": int(np.shape(swapped)[0]),
    }), flush=True)
    return 0


def main():
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--models", type=int, default=4)
    ap.add_argument("--clients", type=int, default=8)
    ap.add_argument("--seconds", type=float, default=10.0)
    ap.add_argument("--rows", type=int, default=20_000)
    ap.add_argument("--rounds", type=int, default=10)
    ap.add_argument("--leaves", type=int, default=16)
    ap.add_argument("--batch", type=int, default=64,
                    help="rows per client request")
    ap.add_argument("--budget-ms", type=float, default=5.0)
    ap.add_argument("--max-batch-rows", type=int, default=4096)
    ap.add_argument("--cache-models", type=int, default=8)
    ap.add_argument("--shard-trees", type=str, default="auto")
    ap.add_argument("--p99-target-ms", type=float, default=250.0)
    ap.add_argument("--metrics-port", type=int, default=0,
                    help="serve live GET /metrics//readyz on "
                         "127.0.0.1:PORT for the duration of the run")
    ap.add_argument("--metrics-json", type=str, default="",
                    help="append one obs metrics-snapshot JSONL line")
    ap.add_argument("--smoke", action="store_true",
                    help="fast CI gate (see run_smoke)")
    args = ap.parse_args()
    if args.smoke:
        return run_smoke()
    return run_load(args)


if __name__ == "__main__":
    sys.exit(main())
