"""Serving-service load harness: sustained RPS at a p99 target.

Drives the async serving stack end to end — N client threads submit
concurrent requests for M tenants' models through one
``PredictService`` (micro-batching queue + LRU registry + optional
tree-sharded predict) — and reports the SLO-shaped numbers ROADMAP
item 1 asks for: sustained requests/sec, predict p50/p99 against a
target, live queue depth, batch fill ratio, and cache hit/eviction
accounting. Exit status is the SLO verdict: nonzero when the measured
p99 misses ``--p99-target-ms`` or any request dropped.

Run:
  python benchmarks/serve_bench.py                      # 4 models,
                                                        # 8 clients, 10 s
  python benchmarks/serve_bench.py --models 8 --clients 16 --seconds 30
  python benchmarks/serve_bench.py --cache-models 2     # force LRU churn
  python benchmarks/serve_bench.py --trace-dir /tmp/t   # request-
    lifecycle tracing ON (docs/observability.md "Request tracing"):
    the final record adds the per-stage latency decomposition
    (queue-wait / coalesce / checkout / dispatch / postprocess p50+p99
    from the SLO windows, slo.device_share, flush-cause counts) and a
    Chrome trace of the run is exported for Perfetto
  python benchmarks/serve_bench.py --fleet 3            # replicated:
    3 PredictService replica processes behind the elastic FleetRouter
    (docs/serving.md "Fleet deployment") — aggregate RPS + POOLED
    p50/p99 across the fleet; run at --fleet 1/2/3 for the scaling
    curve
  python benchmarks/serve_bench.py --fleet 3 --kill-cycle
    # failover drill under sustained load: SIGKILL one replica
    # (drain -> relaunch -> /readyz-gated rejoin), then a host-gone
    # kill (degrade to N-1); exit 0 iff ZERO requests dropped, both
    # cycles complete, and the pooled p99 holds --p99-target-ms
  python benchmarks/serve_bench.py --explain-share 0.25 # mixed
    predict+explain load: a quarter of requests submit as
    ``kind="contrib"`` (device SHAP) on their own queue lanes; the
    final record adds the POOLED explain p50/p99 (separate pool from
    predicts — explain runs heavier programs) and both pools must
    hold --p99-target-ms
  python benchmarks/serve_bench.py --smoke              # CI gate:
    sub-minute — concurrent clients, one LRU eviction, one mid-traffic
    hot-swap, tracing flipped ON mid-traffic, then a mixed
    predict+explain leg (zero drops + zero warm SHAP compiles); exit 0
    iff zero requests dropped, zero warm-path compiles (tracing and
    explain included), the traced/untraced RPS overhead stays under
    3%, and the traced per-stage decomposition sums to the measured
    end-to-end p50 within 10% (scripts/check.sh appends the result as
    serve_smoke= / shap_smoke= and the windowed queue_wait_p99_ms= on
    the obs line; scripts/obs_trend.py fails ABSOLUTELY on
    serve_smoke=0 or shap_smoke=0 and on queue-wait p99 regressing
    past its trailing median)

Each line is one JSON record; the final line aggregates.
"""
import argparse
import json
import os
import shutil
import sys
import threading
import time

import numpy as np

sys.path.insert(0, __file__.rsplit("/", 2)[0])


def _data(n, f=10, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f))
    y = (X[:, 0] + 0.5 * X[:, 1] * X[:, 2]
         + rng.normal(scale=0.3, size=n) > 0).astype(np.float64)
    return X, y


def _train(X, y, rounds, leaves, seed=0):
    import lightgbm_tpu as lgb
    return lgb.train({"objective": "binary", "num_leaves": leaves,
                      "verbosity": -1, "seed": seed},
                     lgb.Dataset(X, label=y), num_boost_round=rounds)


def _client(svc, model_ids, X_pool, batch, stop, lat, drops, seed,
            explain_share=0.0, elat=None):
    """One load thread. ``explain_share`` turns the fraction of
    requests into ``kind="contrib"`` (SHAP) submits — their latencies
    pool into ``elat`` so explain p99 is separable from predict p99."""
    rng = np.random.default_rng(seed)
    while not stop.is_set():
        mid = model_ids[int(rng.integers(0, len(model_ids)))]
        rows = X_pool[rng.integers(0, len(X_pool), size=batch)]
        explain = explain_share > 0.0 and rng.uniform() < explain_share
        t0 = time.perf_counter()
        try:
            if explain:
                svc.submit(mid, rows, kind="contrib").result(
                    timeout=30.0)
                (elat if elat is not None else lat).append(
                    time.perf_counter() - t0)
            else:
                svc.predict(mid, rows, timeout=30.0)
                lat.append(time.perf_counter() - t0)
        except Exception:
            drops.append(mid)


def _quantile(sorted_lat, q):
    if not sorted_lat:
        return None
    i = min(int(q * len(sorted_lat)), len(sorted_lat) - 1)
    return sorted_lat[i]


# the per-batch stage spans the dispatch loop records, in lifecycle
# order (docs/observability.md "Request tracing")
STAGES = ("serve/queue_wait", "serve/coalesce",
          "serve/registry_checkout", "serve/dispatch",
          "serve/postprocess")


def _ms(v):
    return None if v is None else round(v * 1000.0, 3)


def _window_decomposition(slo_mod):
    """Per-stage p50/p99 (ms) from the live SLO sliding windows — the
    same windows the ``slo.queue_wait_*``/``slo.dispatch_p99_ms``
    gauges derive from (bucket-interpolated estimates)."""
    t = slo_mod.tracker()
    if t is None:
        return {}
    out = {}
    for name in STAGES + ("serve/e2e",):
        h = t.hists.get(name)
        p50, p99 = (h.quantiles((0.50, 0.99)) if h is not None
                    else (None, None))
        key = name.split("/", 1)[1]
        out[f"{key}_p50_ms"] = _ms(p50)
        out[f"{key}_p99_ms"] = _ms(p99)
    return out


def _flush_causes(reg):
    """Observed ``serve.flush_cause{cause=...}`` counter values."""
    out = {}
    for c in ("fill", "freeze", "deadline", "close"):
        m = reg.get("serve.flush_cause", cause=c)
        if m is not None:
            out[c] = m.value
    return out


def _trace_decomposition(evs):
    """EXACT per-stage p50s from raw trace events of a sequential
    (1-rider-per-batch) window: per-request end-to-end is the gap
    from the queue-wait event's start (enqueue) to its batch span's
    end (resolve). Events group by scanning in buffer order — each
    group CLOSES at its ``serve/batch`` event (the batch span exits
    last) and must carry every stage exactly once with the queue
    wait's request id matching the batch's, so a straggler event from
    an earlier window (the dispatch thread records the batch AFTER
    the caller's future resolves) yields one dropped partial group,
    never an off-by-one pairing of every later request. Returns None
    when the window caught no complete batch."""
    groups, cur = [], {}
    for e in evs:
        name = e["name"]
        if name in STAGES:
            cur[name] = e
        elif name == "serve/batch":
            qw = cur.get("serve/queue_wait")
            if (len(cur) == len(STAGES) and qw is not None
                    and qw["args"].get("req") == e["args"].get("req")):
                groups.append((cur, e))
            cur = {}
    if not groups:
        return None

    def p50(vals):
        return _quantile(sorted(vals), 0.50)
    e2e = [b["ts"] + b["dur"] - g["serve/queue_wait"]["ts"]
           for g, b in groups]
    sums = [sum(g[s]["dur"] for s in STAGES) for g, _b in groups]
    out = {f"{s.split('/', 1)[1]}_p50_ms":
           round(p50([g[s]["dur"] for g, _b in groups]) / 1e3, 3)
           for s in STAGES}
    out.update({
        "requests": len(groups),
        "e2e_p50_ms": round(p50(e2e) / 1e3, 3),
        "stage_sum_p50_ms": round(p50(sums) / 1e3, 3),
    })
    return out


# ---------------------------------------------------------------------------
# full load run
# ---------------------------------------------------------------------------
def run_fleet(args):
    """Fleet load (docs/serving.md "Fleet deployment"): the same
    client pool driven through ``FleetRouter`` over ``--fleet N``
    replica processes. Reports AGGREGATE RPS plus POOLED p50/p99 —
    one latency pool across every replica, per the re-anchor note
    (pooled medians, not windowed RPS: scheduler noise makes
    windowed numbers lie by ±5-10%). ``--kill-cycle`` additionally
    SIGKILLs one replica mid-load (relaunch cycle: the slot must
    rejoin through /readyz) and then kills another under a host-gone
    marker (degrade cycle: the fleet must retire it and keep
    serving) — exit nonzero unless BOTH cycles complete with ZERO
    dropped requests and the pooled p99 holds ``--p99-target-ms``."""
    from lightgbm_tpu import obs
    from lightgbm_tpu.serve import (FleetRouter, FleetSupervisor,
                                    ReplicaModel)
    obs.enable(metrics=True)
    if args.kill_cycle and args.fleet < 2:
        print(json.dumps({"error": "--kill-cycle needs --fleet >= 2 "
                          "(one replica to kill, one to survive)"}),
              flush=True)
        return 2
    X, y = _data(args.rows)
    specs = []
    model_ids = []
    for m in range(args.models):
        bst = _train(X, y, args.rounds, args.leaves, seed=m)
        mid = f"tenant{m}"
        specs.append(ReplicaModel(model_id=mid,
                                  model_str=bst.model_to_string(),
                                  warmup_row=X[0]))
        model_ids.append(mid)
    sup = FleetSupervisor(
        {"tpu_serve_batch_budget_ms": args.budget_ms,
         "tpu_serve_max_batch_rows": args.max_batch_rows,
         "tpu_serve_cache_models": args.cache_models,
         "tpu_serve_shard_trees": args.shard_trees},
        specs, args.fleet, max_restarts=2, heartbeat_timeout=10.0)
    t_up = time.time()
    sup.start()
    router = None
    kill_cycle = {}
    try:
        ready = sup.wait_ready(args.fleet, timeout=240.0)
        if ready < args.fleet:
            print(json.dumps({"error": f"only {ready}/{args.fleet} "
                              f"replicas turned ready"}), flush=True)
            return 1
        print(json.dumps({"fleet": args.fleet, "models": args.models,
                          "warmed": True,
                          "spinup_secs": round(time.time() - t_up, 1)}),
              flush=True)
        router = FleetRouter(sup, request_timeout_s=60.0)
        lat, drops = [], []
        stop = threading.Event()
        threads = [threading.Thread(
            target=_client, args=(router, model_ids, X, args.batch,
                                  stop, lat, drops, 100 + i),
            daemon=True) for i in range(args.clients)]
        t0 = time.time()
        for t in threads:
            t.start()
        if args.kill_cycle:
            phase = max(args.seconds / 3.0, 2.0)
            # phase 1: steady at full width
            time.sleep(phase)
            # phase 2: SIGKILL -> drain to siblings -> relaunch ->
            # /readyz-gated rejoin, all under load
            sup.kill_replica(0)
            t_kill = time.time()
            # the kill lands asynchronously: first watch the slot
            # actually LEAVE the ready set (ready+alive both lag a
            # SIGKILL by a beat), then wait for the relaunch to warm
            # up and re-admit — otherwise this "rejoin" would be the
            # stale pre-kill flags
            while (sup.live_count() >= args.fleet
                   and time.time() - t_kill < 30.0):
                time.sleep(0.05)
            while (sup.live_count() < args.fleet
                   and time.time() - t_kill < 180.0):
                time.sleep(0.1)
            kill_cycle["relaunch_rejoin_secs"] = \
                round(time.time() - t_kill, 1)
            kill_cycle["rejoined"] = sup.live_count() == args.fleet
            time.sleep(phase)
            # phase 3: host-gone kill -> degrade to N-1, still serving
            victim = args.fleet - 1
            sup.kill_replica(victim, host_gone=True)
            t_kill = time.time()
            while (not sup.handles[victim].retired
                   and time.time() - t_kill < 60.0):
                time.sleep(0.1)
            # settle to the degraded steady state before sampling:
            # every non-retired slot back in the ready set
            while (sup.live_count() < args.fleet - 1
                   and time.time() - t_kill < 180.0):
                time.sleep(0.1)
            kill_cycle["degraded"] = sup.handles[victim].retired
            kill_cycle["live_after_degrade"] = sup.live_count()
            time.sleep(phase)
        else:
            time.sleep(args.seconds)
        stop.set()
        for t in threads:
            t.join(timeout=60)
        elapsed = time.time() - t0
    finally:
        if router is not None:
            router.close()
        sup.stop()

    slat = sorted(lat)
    p50, p99 = _quantile(slat, 0.50), _quantile(slat, 0.99)
    rps = len(lat) / elapsed
    cycles_ok = (not args.kill_cycle
                 or (bool(kill_cycle.get("rejoined"))
                     and bool(kill_cycle.get("degraded"))
                     and kill_cycle.get("live_after_degrade")
                     == args.fleet - 1))
    met = (not drops and cycles_ok
           and (p99 is None or p99 * 1000.0 <= args.p99_target_ms))
    obs.set_gauge("bench.fleet_rps", round(rps, 1), force=True)
    rec = {
        "fleet": args.fleet, "clients": args.clients,
        "seconds": round(elapsed, 1), "requests": len(lat),
        "rps": round(rps, 1),
        "pooled_p50_ms": _ms(p50), "pooled_p99_ms": _ms(p99),
        "p99_target_ms": args.p99_target_ms,
        "dropped": len(drops),
        "relaunches": sup.relaunches, "degrades": sup.degrades,
        "kill_cycle": kill_cycle or None,
        "fleet_ok": 1 if met else 0,
    }
    print(json.dumps(rec), flush=True)
    if args.metrics_json:
        obs.dump_jsonl(args.metrics_json)
    return 0 if met else 1


def run_load(args):
    from lightgbm_tpu import obs
    from lightgbm_tpu.obs import slo as _slo
    from lightgbm_tpu.serve import PredictService
    obs.enable(metrics=True, slo=True)
    if args.trace_dir:
        # request-lifecycle tracing: per-batch span trees + rider
        # flows, exported as a Chrome trace at the end of the run
        obs.enable(metrics=False, trace_dir=args.trace_dir)
    X, y = _data(args.rows)
    svc = PredictService({
        "tpu_serve_batch_budget_ms": args.budget_ms,
        "tpu_serve_max_batch_rows": args.max_batch_rows,
        "tpu_serve_cache_models": args.cache_models,
        "tpu_serve_shard_trees": args.shard_trees,
        # expose GET /metrics (+ /readyz) mid-run so slo.queue_depth /
        # serve.cache_hits can be scraped live while the load runs
        "tpu_metrics_port": args.metrics_port,
    })
    kinds = (("predict", "contrib") if args.explain_share > 0.0
             else ("predict",))
    model_ids = []
    for m in range(args.models):
        bst = _train(X, y, args.rounds, args.leaves, seed=m)
        mid = f"tenant{m}"
        svc.add_model(mid, bst)
        svc.warmup(mid, X[:1], kinds=kinds)
        model_ids.append(mid)
    print(json.dumps({"models": args.models, "warmed": True,
                      "kinds": list(kinds)}), flush=True)

    lat, elat, drops = [], [], []
    stop = threading.Event()
    threads = [threading.Thread(
        target=_client, args=(svc, model_ids, X, args.batch, stop, lat,
                              drops, 100 + i),
        kwargs={"explain_share": args.explain_share, "elat": elat},
        daemon=True)
        for i in range(args.clients)]
    t0 = time.time()
    for t in threads:
        t.start()
    depth_max = 0
    while time.time() - t0 < args.seconds:
        depth_max = max(depth_max, svc.queue.depth())
        time.sleep(0.05)
    stop.set()
    for t in threads:
        t.join(timeout=30)
    elapsed = time.time() - t0

    slat = sorted(lat)
    p50, p99 = _quantile(slat, 0.50), _quantile(slat, 0.99)
    selat = sorted(elat)
    e50, e99 = _quantile(selat, 0.50), _quantile(selat, 0.99)
    rps = (len(lat) + len(elat)) / elapsed
    reg = obs.registry()

    def metric(name):
        m = reg.get(name)
        return getattr(m, "value", None)

    slis = (_slo.tracker().compute() if _slo.tracker() else {})
    # POOLED per-request percentiles, per the re-anchor protocol —
    # windowed RPS on a loaded box carries ±5-10% scheduler noise.
    # With --explain-share both pools must hold the target: explain
    # runs heavier programs, so its p99 would hide in a merged pool
    met = (p99 is not None and p99 * 1000.0 <= args.p99_target_ms
           and not drops
           and (args.explain_share <= 0.0
                or (e99 is not None
                    and e99 * 1000.0 <= args.p99_target_ms)))
    obs.set_gauge("bench.serve_rps", round(rps, 1), force=True)
    obs.set_gauge("bench.serve_p99_ms",
                  round((p99 or 0.0) * 1000.0, 3), force=True)
    rec = {
        "clients": args.clients, "models": args.models,
        "seconds": round(elapsed, 1),
        "requests": len(lat) + len(elat),
        "rps": round(rps, 1),
        "p50_ms": round((p50 or 0.0) * 1e3, 2),
        "p99_ms": round((p99 or 0.0) * 1e3, 2),
        "p99_target_ms": args.p99_target_ms, "met_target": bool(met),
        "dropped": len(drops),
        "queue_depth_max": depth_max,
        "slo_queue_depth": slis.get("slo.queue_depth"),
        "queue_wait_p50_ms": slis.get("slo.queue_wait_p50_ms"),
        "queue_wait_p99_ms": slis.get("slo.queue_wait_p99_ms"),
        "dispatch_p99_ms": slis.get("slo.dispatch_p99_ms"),
        "device_share": slis.get("slo.device_share"),
        "decomposition": _window_decomposition(_slo),
        "flush_causes": _flush_causes(reg),
        "dispatches": metric("serve.dispatches"),
        "coalesced_requests": metric("serve.coalesced_requests"),
        "batch_fill_ratio": metric("serve.batch_fill_ratio"),
        "cache_hits": metric("serve.cache_hits"),
        "evictions": metric("serve.evictions"),
    }
    if args.explain_share > 0.0:
        rec.update({
            "explain_share": args.explain_share,
            "explain_requests": len(elat),
            "explain_p50_ms": _ms(e50), "explain_p99_ms": _ms(e99),
            "slo_explain_p99_ms": slis.get("slo.explain_p99_ms"),
            "serve_explain_requests":
                metric("serve.explain_requests"),
        })
    svc.close()
    if args.trace_dir:
        rec["trace"] = obs.export_chrome_trace()
    if args.metrics_json:
        obs.dump_jsonl(args.metrics_json)
    print(json.dumps(rec), flush=True)
    return 0 if met else 1


# ---------------------------------------------------------------------------
# CI smoke: clients + one eviction + one mid-traffic swap, hard asserts
# ---------------------------------------------------------------------------
def _publish(staging, pub):
    """Land a pre-trained checkpoint mid-traffic: payloads first, the
    ``latest.rank*`` pointers last (the order the atomic publisher
    guarantees)."""
    names = sorted(os.listdir(staging))
    pointers = [n for n in names if n.startswith("latest.")]
    for name in names:
        if name not in pointers:
            shutil.copy(os.path.join(staging, name),
                        os.path.join(pub, name))
    for name in pointers:
        shutil.copy(os.path.join(staging, name),
                    os.path.join(pub, name))


def run_smoke(args=None):
    """Sub-minute serving gate, exit nonzero on ANY broken invariant:

    1. N concurrent clients over 2 tenants with a 1-model LRU — every
       request resolves (ZERO drops) through eviction churn;
    2. a checkpoint published MID-TRAFFIC hot-swaps in (watcher swap
       under the swap lock) without dropping or corrupting a request;
    3. the whole loaded phase — coalescing, evictions, re-admissions,
       the swap, AND request tracing flipped ON mid-traffic — compiles
       ZERO XLA programs after warmup (CompileWatch: enabling tracing
       must add zero programs on the warm serve path);
    4. the live plane is real: slo.queue_depth sampled, cache
       hits/evictions counted, heartbeat.serve stamped, flush causes
       counted, and the queue-wait/dispatch/device-share decomposition
       gauges derived from live windows;
    5. tracing is affordable and honest: traced steady-state RPS
       within 3% of the untraced window of the SAME run, and the
       traced per-stage decomposition (queue-wait / coalesce /
       checkout / dispatch / postprocess) sums to the measured
       end-to-end p50 within 10%;
    6. mixed predict+explain traffic holds: after a contrib warmup, a
       half-explain loaded window drops ZERO requests and compiles
       ZERO programs (device SHAP rides the same pow2 buckets), the
       served contributions match the published model exactly, and
       the explain SLO window (``slo.explain_p99_ms``) is live —
       the ``shap_smoke=`` verdict on check.sh's obs line.
    """
    import tempfile

    import lightgbm_tpu as lgb
    from lightgbm_tpu import obs
    from lightgbm_tpu.utils.debug import CompileWatch
    t0 = time.time()
    obs.enable(metrics=True, slo=True)
    X, y = _data(4_000, seed=1)
    rounds, leaves = 4, 8
    bA = _train(X, y, rounds, leaves, seed=0)
    bB = _train(X, y, rounds, leaves, seed=1)
    # v2 of tenant A, published mid-traffic below (pre-trained so the
    # CompileWatch window sees serving compiles only). Same tree count
    # and leaf cap as bA — the swap must reuse every compiled program —
    # but a different learning rate, so its PREDICTIONS visibly differ
    # and the post-swap equality assert below has teeth
    staging = tempfile.mkdtemp(prefix="lgbm_serve_stage_")
    pub = tempfile.mkdtemp(prefix="lgbm_serve_pub_")
    tdir = getattr(args, "trace_dir", "") if args is not None else ""
    keep_trace = bool(tdir)
    tdir = tdir or tempfile.mkdtemp(prefix="lgbm_serve_trace_")
    try:
        return _run_smoke_body(lgb, obs, CompileWatch, t0, X, y,
                               rounds, leaves, bA, bB, staging, pub,
                               tdir)
    finally:
        # check.sh runs this every invocation: leaked checkpoint dirs
        # would accumulate unbounded /tmp disk across CI runs
        shutil.rmtree(staging, ignore_errors=True)
        shutil.rmtree(pub, ignore_errors=True)
        if not keep_trace:
            shutil.rmtree(tdir, ignore_errors=True)


def _steady_rps(lat, secs, svc, depth_box):
    """Completed requests/sec over a ``secs`` window of the running
    client load (the clients append to ``lat``), sampling queue depth
    along the way."""
    n0, t0 = len(lat), time.perf_counter()
    end = t0 + secs
    while time.perf_counter() < end:
        depth_box[0] = max(depth_box[0], svc.queue.depth())
        time.sleep(0.02)
    return (len(lat) - n0) / (time.perf_counter() - t0)


def _trace_overhead(svc, Xq, tracing_mod, alts=3, n=100):
    """The tracing tax on steady-state serving: interleaved traced /
    untraced windows of sequential requests on the SAME warm service,
    compared on POOLED MEDIAN latency. (Windowed RPS on a loaded CI
    box carries ±5-10% scheduler noise — far above the 3% bar the
    gate enforces — while the per-request median is stable to ~1%,
    and interleaving cancels slow drift.) Returns ``(overhead,
    rps_untraced, rps_traced)`` where the RPS numbers are the
    median-latency equivalents (1/median). Tracing is left ENABLED."""
    import statistics

    def window():
        out = []
        for _ in range(n):
            t0 = time.perf_counter()
            svc.predict("a", Xq, timeout=10.0)
            out.append(time.perf_counter() - t0)
        return out

    untraced, traced = [], []
    for _ in range(alts):
        tracing_mod.disable_tracing()
        untraced += window()
        tracing_mod.enable_tracing()
        traced += window()
    mu = statistics.median(untraced)
    mt = statistics.median(traced)
    return mt / mu - 1.0, 1.0 / mu, 1.0 / mt


def _run_smoke_body(lgb, obs, CompileWatch, t0, X, y, rounds, leaves,
                    bA, bB, staging, pub, tdir):
    from lightgbm_tpu.obs import slo as _slo
    from lightgbm_tpu.obs import tracing as _tracing
    v2 = lgb.train({"objective": "binary", "num_leaves": leaves,
                    "verbosity": -1, "learning_rate": 0.05,
                    "checkpoint_dir": staging,
                    "checkpoint_interval": rounds},
                   lgb.Dataset(X, label=y), num_boost_round=rounds)

    svc = lgb.PredictService({"tpu_serve_batch_budget_ms": 2.0,
                              "tpu_serve_max_batch_rows": 512,
                              "tpu_serve_cache_models": 1,
                              "tpu_serve_shard_trees": "false"})
    svc.add_model("a", bA, watch_dir=pub, watch_interval=0.0)
    svc.add_model("b", bB)
    svc.warmup("a", X[:1])
    svc.warmup("b", X[:1])
    Xq = X[:64]
    pre_swap = bA.predict(Xq)

    lat, drops = [], []
    stop = threading.Event()
    threads = [threading.Thread(
        target=_client, args=(svc, ["a", "b"], X, 64, stop, lat, drops,
                              100 + i), daemon=True)
        for i in range(4)]
    depth_box = [0]
    with CompileWatch("serve-smoke") as w:
        for t in threads:
            t.start()
        time.sleep(0.5)
        _publish(staging, pub)          # the mid-traffic swap
        # loaded window, then request tracing flips ON mid-traffic
        # (inside the CompileWatch window — enabling it must add zero
        # programs) and the load keeps running traced
        rps_loaded = _steady_rps(lat, 1.0, svc, depth_box)
        obs.enable(metrics=False, trace_dir=tdir)
        rps_loaded_traced = _steady_rps(lat, 1.0, svc, depth_box)
        stop.set()
        for t in threads:
            t.join(timeout=30)
        # tracing tax on the same warm service (sequential interleaved
        # median-latency windows; one re-measure before failing — a
        # REAL >3% tax reproduces, scheduler noise does not)
        overhead, rps_untraced, rps_traced = \
            _trace_overhead(svc, Xq, _tracing)
        if overhead >= 0.03:
            overhead, rps_untraced, rps_traced = \
                _trace_overhead(svc, Xq, _tracing)
        # sequential decomposition window (still traced, still inside
        # the compile watch): one rider per batch, so stage durations
        # pair 1:1 with requests and the trace yields EXACT per-stage
        # p50s to check against end-to-end
        n_ev = len(_tracing.events())
        for _ in range(120):
            svc.predict("a", Xq, timeout=10.0)
        deco = _trace_decomposition(_tracing.events()[n_ev:])
    watcher = bA._model_watch
    reg = obs.registry()

    def metric(name):
        m = reg.get(name)
        return getattr(m, "value", 0.0) or 0.0

    assert not drops, f"{len(drops)} request(s) dropped under load"
    assert watcher.swaps >= 1, "mid-traffic publish never swapped in"
    assert metric("serve.evictions") >= 1, "1-model LRU never evicted"
    assert metric("serve.cache_hits") >= 1, "no warm cache hits"
    w.assert_compiles(0)                # zero warm-path programs
    assert reg.get("heartbeat.serve") is not None, \
        "dispatch loop never stamped heartbeat.serve"
    # the request-lifecycle plane (docs/observability.md "Request
    # tracing"): decomposition stages must SUM to what the caller
    # experiences — a stage the spans miss would silently eat p99
    # budget postmortems
    assert deco is not None, "traced window recorded no complete batch"
    e2e, ssum = deco["e2e_p50_ms"], deco["stage_sum_p50_ms"]
    assert abs(ssum - e2e) <= 0.10 * e2e, \
        f"stage p50s sum to {ssum}ms vs end-to-end {e2e}ms (>10% gap)"
    assert overhead < 0.03, \
        f"tracing overhead {overhead:.1%} >= 3% " \
        f"({rps_traced:.0f} traced vs {rps_untraced:.0f} untraced RPS)"
    causes = _flush_causes(reg)
    assert causes and sum(causes.values()) >= 1, \
        "no serve.flush_cause{cause=...} counters recorded"
    slis = _slo.tracker().compute()
    assert slis.get("slo.queue_wait_p99_ms") is not None, \
        "queue-wait window empty: the decomposition gauges are dead"
    assert slis.get("slo.device_share") is not None
    # post-swap serving must match the published model EXACTLY — a
    # swap that leaves a stale stack (or truncates adoption) serves
    # wrong values with the right shape, which only this catches
    swapped = svc.predict("a", Xq, timeout=10.0)
    expected = v2.predict(Xq)
    assert np.array_equal(swapped, expected), \
        "post-swap serving diverged from the published model"
    assert not np.array_equal(expected, pre_swap), \
        "v2 indistinguishable from v1 — the swap assert has no teeth"
    # ---- mixed predict+explain leg (docs/serving.md "Mixed predict +
    # explain workloads"): warm the contrib bucket ladder, then a
    # loaded half-explain window must drop NOTHING and compile
    # NOTHING — device SHAP rides the same pow2 buckets as predict.
    # check.sh carries the verdict as shap_smoke= on the obs line
    svc.warmup("a", X[:1], kinds=("contrib",))
    svc.warmup("b", X[:1], kinds=("contrib",))
    plat, elat, edrops = [], [], []
    estop = threading.Event()
    ethreads = [threading.Thread(
        target=_client, args=(svc, ["a", "b"], X, 64, estop, plat,
                              edrops, 200 + i),
        kwargs={"explain_share": 0.5, "elat": elat}, daemon=True)
        for i in range(4)]
    with CompileWatch("serve-smoke-explain") as w2:
        for t in ethreads:
            t.start()
        time.sleep(1.2)
        estop.set()
        for t in ethreads:
            t.join(timeout=30)
        contrib = svc.submit("a", Xq, kind="contrib").result(
            timeout=10.0)
    assert not edrops, \
        f"{len(edrops)} mixed predict+explain request(s) dropped"
    w2.assert_compiles(0)           # warm explain = zero programs
    assert elat and plat, "mixed window ran only one kind"
    assert metric("serve.explain_requests") >= len(elat), \
        "serve.explain_requests undercounts explain riders"
    # explain THROUGH the service must match the booster's own
    # pred_contrib on the swapped-in model (f64-exact on CPU; the
    # batch it coalesced into must not leak padding or other riders)
    expected_c = v2.predict(Xq, pred_contrib=True)
    assert np.allclose(contrib, expected_c, rtol=1e-9, atol=1e-9), \
        "served pred_contrib diverged from the published model"
    slis = _slo.tracker().compute()
    assert slis.get("slo.explain_p99_ms") is not None, \
        "serve/explain window empty: explain SLO gauge is dead"
    svc.close()
    trace_path = obs.export_chrome_trace()
    print(json.dumps({
        "serve_smoke": 1, "shap_smoke": 1,
        "secs": round(time.time() - t0, 1),
        "requests": len(lat), "dropped": 0,
        "explain_requests": len(elat),
        "explain_warm_compiles": w2.compiles,
        "explain_p99_ms": _ms(_quantile(sorted(elat), 0.99)),
        "slo_explain_p99_ms": round(slis["slo.explain_p99_ms"], 3),
        "swaps": watcher.swaps,
        "evictions": metric("serve.evictions"),
        "cache_hits": metric("serve.cache_hits"),
        "queue_depth_max": depth_box[0],
        "warm_compiles": w.compiles,
        "rps_loaded": round(rps_loaded, 1),
        "rps_loaded_traced": round(rps_loaded_traced, 1),
        "rps_untraced": round(rps_untraced, 1),
        "rps_traced": round(rps_traced, 1),
        "trace_overhead": round(max(overhead, 0.0), 4),
        "queue_wait_p99_ms": round(
            slis["slo.queue_wait_p99_ms"], 3),
        "dispatch_p99_ms": slis.get("slo.dispatch_p99_ms"),
        "device_share": round(slis["slo.device_share"], 4),
        "flush_causes": causes,
        "decomposition": deco,
        "trace": trace_path,
        "post_swap_rows": int(np.shape(swapped)[0]),
    }), flush=True)
    return 0


def main():
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--models", type=int, default=4)
    ap.add_argument("--clients", type=int, default=8)
    ap.add_argument("--seconds", type=float, default=10.0)
    ap.add_argument("--rows", type=int, default=20_000)
    ap.add_argument("--rounds", type=int, default=10)
    ap.add_argument("--leaves", type=int, default=16)
    ap.add_argument("--batch", type=int, default=64,
                    help="rows per client request")
    ap.add_argument("--budget-ms", type=float, default=5.0)
    ap.add_argument("--max-batch-rows", type=int, default=4096)
    ap.add_argument("--cache-models", type=int, default=8)
    ap.add_argument("--shard-trees", type=str, default="auto")
    ap.add_argument("--p99-target-ms", type=float, default=250.0)
    ap.add_argument("--explain-share", type=float, default=0.0,
                    metavar="P",
                    help="mixed workload: fraction of requests "
                         "submitted as kind='contrib' (SHAP). Both "
                         "POOLED p99s — predict and explain, separate "
                         "pools — must hold --p99-target-ms "
                         "(docs/serving.md 'Mixed predict + explain "
                         "workloads')")
    ap.add_argument("--metrics-port", type=int, default=0,
                    help="serve live GET /metrics//readyz on "
                         "127.0.0.1:PORT for the duration of the run")
    ap.add_argument("--metrics-json", type=str, default="",
                    help="append one obs metrics-snapshot JSONL line")
    ap.add_argument("--trace-dir", type=str, default="",
                    help="enable request-lifecycle tracing and export "
                         "a Chrome trace of the run there "
                         "(docs/observability.md 'Request tracing')")
    ap.add_argument("--fleet", type=int, default=0, metavar="N",
                    help="drive N replica PROCESSES through the "
                         "elastic FleetRouter instead of one "
                         "in-process service; reports aggregate RPS "
                         "+ pooled p50/p99 (docs/serving.md 'Fleet "
                         "deployment')")
    ap.add_argument("--kill-cycle", action="store_true",
                    help="with --fleet: SIGKILL one replica mid-load "
                         "(relaunch + /readyz rejoin) then host-gone "
                         "kill another (degrade to N-1); exit nonzero "
                         "on any dropped request")
    ap.add_argument("--smoke", action="store_true",
                    help="fast CI gate (see run_smoke)")
    args = ap.parse_args()
    if args.smoke:
        return run_smoke(args)
    if args.fleet:
        return run_fleet(args)
    return run_load(args)


if __name__ == "__main__":
    sys.exit(main())
