"""Secondary benchmark suite: the non-flagship BASELINE.json configs.

Each config is a synthetic stand-in with the SHAPE of the named public
dataset (no network in this environment — see BASELINE.md): the point is
iters/sec + a sanity quality metric per capability combination, not
dataset-accurate AUC. The flagship (Higgs-1M plain hist) lives in
bench.py; the driver records only that one line. Results are pasted into
docs/perf.md.

Run: python benchmarks/suite.py [config ...]   (default: all)
"""
import json
import sys
import time

import numpy as np

sys.path.insert(0, __file__.rsplit("/", 2)[0])


def _time_chunks(eng, warm, timed):
    import jax
    eng.train_chunk(warm)
    jax.block_until_ready(eng.score)
    t0 = time.time()
    eng.train_chunk(timed)
    jax.block_until_ready(eng.score)
    return timed / (time.time() - t0)


def bench_mslr():
    """MSLR-Web30K shape: LambdaRank, 136 dense features, ~120-doc
    queries. 500k rows."""
    import lightgbm_tpu as lgb
    from lightgbm_tpu.boosting.gbdt import GBDT
    from lightgbm_tpu.config import Config
    rng = np.random.default_rng(0)
    n_q, per_q, F = 4096, 122, 136
    n = n_q * per_q
    X = rng.normal(size=(n, F)).astype(np.float32)
    w = rng.normal(size=F) * (rng.random(F) < 0.3)
    rel = np.clip((X @ w) * 0.35 + rng.normal(scale=0.8, size=n) + 1.2,
                  0, 4).astype(int).astype(float)
    ds = lgb.Dataset(X.astype(np.float64), label=rel,
                     group=np.full(n_q, per_q))
    cfg = Config({"objective": "lambdarank", "num_leaves": 127,
                  "max_bin": 255, "learning_rate": 0.1, "verbosity": -1})
    eng = GBDT(cfg, ds)
    ips = _time_chunks(eng, 10, 20)
    from lightgbm_tpu.metric import NDCGMetric
    ndcg = NDCGMetric(cfg).eval(eng.predict(X), rel, None,
                                ds.metadata.query_boundaries)[0][1]
    return {"config": "mslr-synth lambdarank (500k x 136, q=122)",
            "iters_per_sec": round(ips, 3),
            "quality": {"train_ndcg": round(float(ndcg), 4)}}


def bench_bosch():
    """Bosch/M5 shape: GOSS + DART + monotone constraints, 300k x 200
    regression."""
    import lightgbm_tpu as lgb
    rng = np.random.default_rng(1)
    n, F = 300_000, 200
    X = rng.normal(size=(n, F))
    y = (X[:, 0] * 2.0 + np.abs(X[:, 1]) + 0.3 * X[:, 2] ** 2
         + rng.normal(scale=0.5, size=n))
    mono = [0] * F
    mono[0] = 1
    ds = lgb.Dataset(X, label=y)
    from lightgbm_tpu.boosting.dart import DART
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.ops.predict import forest_predict_binned
    import jax
    eng = DART(Config({"objective": "regression",
                       "data_sample_strategy": "goss", "num_leaves": 127,
                       "max_bin": 255, "monotone_constraints": mono,
                       "max_drop": 4,
                       "learning_rate": 0.1, "verbosity": -1}), ds)
    # warm 14 rounds (GOSS switch-over + the training step), then
    # FORCE-compile every power-of-two dropped-stack bucket (max_drop=4
    # -> 1, 2, 4) — bucket occurrence during warm rounds is random, so
    # relying on it would let a first-time forest_predict compile land
    # in the timed window
    for _ in range(14):
        eng.train_one_iter()
    for pc in (1, 2, 4):
        stacked, ci = eng._stack_model_list(
            list(range(pc)), pad_count=pc,
            pad_leaves=eng.config.num_leaves)
        out, _ = forest_predict_binned(
            stacked, eng.data.bins, eng.feat_num_bin,
            eng.feat_has_nan, ci, eng.num_class)
        jax.block_until_ready(out)
    jax.block_until_ready(eng.score)
    t0 = time.time()
    n_timed = 15
    for _ in range(n_timed):
        eng.train_one_iter()
    jax.block_until_ready(eng.score)
    dt = time.time() - t0
    pred = eng.predict(X)
    rmse = float(np.sqrt(np.mean((pred - y) ** 2)))
    return {"config": "bosch-synth goss+dart+monotone (300k x 200)",
            "iters_per_sec": round(n_timed / dt, 3),
            "quality": {"train_rmse": round(rmse, 4),
                        "label_std": round(float(y.std()), 4)}}


def bench_criteo():
    """Criteo shape: 13 dense + 26 categorical + 160 sparse binaries
    with EFB, binary CTR, 1M rows."""
    import lightgbm_tpu as lgb
    from lightgbm_tpu.boosting.gbdt import GBDT
    from lightgbm_tpu.config import Config
    rng = np.random.default_rng(2)
    n = 1_000_000
    dense = rng.lognormal(size=(n, 13)).astype(np.float32)
    cats = np.stack([rng.integers(0, c, size=n) for c in
                     ([8, 16, 32, 64, 128, 256] * 5)[:26]], axis=1)
    # 20 groups of 8 mutually-exclusive indicators (one-hot-expanded
    # categoricals, the Criteo-CTR shape EFB exists for) — plus rows
    # where the whole group is absent, so columns stay sparse
    groups = []
    for gi in range(20):
        sel = rng.integers(0, 9, size=n)          # 8 = absent
        oh = (sel[:, None] == np.arange(8)[None, :]).astype(np.float32)
        groups.append(oh)                         # 0/1 indicators: 2-3
    sparse = np.concatenate(groups, axis=1)       # bins each -> EFB
    X = np.concatenate([dense, cats.astype(np.float32), sparse], axis=1)
    logit = (0.4 * np.log1p(dense[:, 0]) + 0.3 * (cats[:, 0] % 3 == 0)
             + sparse[:, 0] - 0.8)
    y = (logit + rng.normal(scale=1.0, size=n) > 0).astype(np.float64)
    t_bin = time.time()
    ds = lgb.Dataset(X.astype(np.float64), label=y,
                     categorical_feature=list(range(13, 39)),
                     params={"enable_bundle": True})
    cfg = Config({"objective": "binary", "num_leaves": 127,
                  "max_bin": 255, "enable_bundle": True,
                  "learning_rate": 0.1, "verbosity": -1})
    eng = GBDT(cfg, ds)
    bin_s = time.time() - t_bin
    ips = _time_chunks(eng, 10, 20)
    from lightgbm_tpu.metric import AUCMetric
    auc = AUCMetric(cfg).eval(eng.predict(X[:100_000]), y[:100_000],
                              None)[0][1]
    nb = eng.data.bins.shape[1]     # physical (bundled) column count
    return {"config": "criteo-synth efb+categorical (1M x 199)",
            "iters_per_sec": round(ips, 3),
            "quality": {"train_auc_100k": round(float(auc), 4)},
            "efb": {"physical_columns": int(nb), "logical_features": 199,
                    "binning_s": round(bin_s, 1)}}


ALL = {"mslr": bench_mslr, "bosch": bench_bosch, "criteo": bench_criteo}

if __name__ == "__main__":
    picks = sys.argv[1:] or list(ALL)
    for name in picks:
        try:
            print(json.dumps(ALL[name]()), flush=True)
        except Exception as e:
            print(json.dumps({"config": name,
                              "error": f"{type(e).__name__}: {e}"[:300]}),
                  flush=True)
