"""Cross-rank Chrome-trace merge (obs/aggregate.merge_chrome_traces +
scripts/trace_merge.py + the rank-tagged export in obs/tracing.py).

What these tests pin:

* **Rank-tagged export** — with a trace rank set, the export writes
  ``rank_<r>.trace.json``, keys every event's pid by the RANK (not
  the per-host pid that collides across hosts), emits
  ``process_name``/``process_sort_index`` metadata rows, and records
  the wall/monotonic envelope pair the merge rebases on.
* **Clock rebase** — two ranks whose monotonic clocks disagree by
  1000 s but whose events happened 50 ms apart in WALL time merge to
  a 50 ms offset (the straggler-visibility contract): same envelope
  contract the gauge merge uses.
* **CLI** — ``scripts/trace_merge.py DIR`` produces one
  Perfetto-loadable document with rank-named process rows; an empty
  dir exits 3.
* **1-rank end-to-end** (in-container; the 2-rank gang run is
  capability-gated in test_multihost_trace.py) — a traced training
  run with a rank set exports a mergeable file.
"""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu import obs
from lightgbm_tpu.obs import tracing as obs_tracing
from lightgbm_tpu.obs.aggregate import merge_chrome_traces

SCRIPT = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "scripts", "trace_merge.py")

pytestmark = pytest.mark.filterwarnings("ignore")


@pytest.fixture(autouse=True)
def _isolated_obs(monkeypatch):
    obs.disable()
    obs.reset()
    obs.set_trace_rank(None)
    # the export dir is process-global and sticky by design (one
    # stream per process); tests that each configure their own tmp
    # dir must not inherit a previous test's
    monkeypatch.setattr(obs_tracing, "_dir", None)
    yield
    obs.disable()
    obs.reset()
    obs.set_trace_rank(None)


def _rank_doc(rank, wall, mono, events):
    """A synthetic per-rank export: ``events`` are (name, ts_s, dur_s)
    on that rank's OWN monotonic clock."""
    return {
        "displayTimeUnit": "ms",
        "traceEvents": [
            {"name": "process_name", "ph": "M", "pid": rank,
             "args": {"name": f"rank {rank} (pid {4000 + rank})"}},
        ] + [
            {"name": n, "ph": "X", "ts": ts * 1e6, "dur": dur * 1e6,
             "pid": rank, "tid": 1}
            for n, ts, dur in events
        ],
        "otherData": {"producer": "test", "dropped_events": rank,
                      "pid": 4000 + rank, "rank": rank,
                      "ts": wall, "monotonic": mono},
    }


def test_rank_tagged_export(tmp_path):
    obs.enable(trace=True, metrics=False, trace_dir=str(tmp_path))
    obs.set_trace_rank(3)
    with obs.span("train/round", round=0):
        pass
    out = obs.export_chrome_trace()
    assert os.path.basename(out) == "rank_3.trace.json"
    doc = json.load(open(out))
    other = doc["otherData"]
    assert other["rank"] == 3 and other["pid"] == os.getpid()
    assert other["monotonic"] <= other["ts"] or True  # both present
    assert {"ts", "monotonic"} <= set(other)
    evs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    assert evs and all(e["pid"] == 3 for e in evs)
    meta = {e["name"]: e for e in doc["traceEvents"]
            if e["ph"] == "M" and "tid" not in e}
    assert meta["process_name"]["args"]["name"].startswith("rank 3")
    assert meta["process_sort_index"]["args"]["sort_index"] == 3


def test_merge_rebases_cross_rank_monotonic_clocks(tmp_path):
    # rank 0: booted long ago (monotonic 5000 at wall 1e9); rank 1:
    # freshly booted (monotonic 17). Rank 1's round happened 50 ms
    # AFTER rank 0's in wall time — the straggler signal the merged
    # timeline must preserve.
    d0 = _rank_doc(0, wall=1e9, mono=5000.0,
                   events=[("train/round", 5000.0, 0.010)])
    d1 = _rank_doc(1, wall=1e9, mono=17.0,
                   events=[("train/round", 17.050, 0.010)])
    p0, p1 = str(tmp_path / "rank_0.trace.json"), \
        str(tmp_path / "rank_1.trace.json")
    json.dump(d0, open(p0, "w"))
    json.dump(d1, open(p1, "w"))
    merged = merge_chrome_traces([p0, p1])
    evs = sorted((e for e in merged["traceEvents"]
                  if e.get("ph") == "X"), key=lambda e: e["ts"])
    assert [e["pid"] for e in evs] == [0, 1]
    assert evs[0]["ts"] == pytest.approx(0.0)
    assert evs[1]["ts"] == pytest.approx(50_000.0)   # 50 ms, in us
    other = merged["otherData"]
    assert other["merged_from_ranks"] == [0, 1]
    assert other["dropped_events"] == 1              # 0 + 1
    assert other["unrebased_ranks"] == []
    # rank-named process rows survive the merge
    names = [e["args"]["name"] for e in merged["traceEvents"]
             if e.get("ph") == "M" and e["name"] == "process_name"]
    assert any(n.startswith("rank 0") for n in names)
    assert any(n.startswith("rank 1") for n in names)


def test_merge_without_envelope_degrades_visibly(tmp_path):
    d0 = _rank_doc(0, wall=1e9, mono=100.0,
                   events=[("train/round", 100.0, 0.010)])
    # the envelope-less rank carries a huge per-boot monotonic stamp:
    # it must NOT anchor the zero base (which would shove the rebased
    # rank's wall-epoch events decades off-screen) — it overlays from
    # the zero point instead
    d1 = _rank_doc(1, wall=1e9, mono=100.0,
                   events=[("train/round", 3_000_000.0, 0.010)])
    del d1["otherData"]["ts"]          # pre-envelope export
    p0, p1 = str(tmp_path / "rank_0.trace.json"), \
        str(tmp_path / "rank_1.trace.json")
    json.dump(d0, open(p0, "w"))
    json.dump(d1, open(p1, "w"))
    merged = merge_chrome_traces([p0, p1])
    assert merged["otherData"]["unrebased_ranks"] == [1]
    by_pid = {e["pid"]: e for e in merged["traceEvents"]
              if e.get("ph") == "X"}
    assert by_pid[0]["ts"] == pytest.approx(0.0)   # rebased anchor
    assert by_pid[1]["ts"] == pytest.approx(0.0)   # overlaid, not
    with pytest.raises(ValueError):                # 50 years out
        merge_chrome_traces([str(tmp_path / "missing.trace.json")])


def test_trace_merge_cli(tmp_path):
    for r in range(2):
        json.dump(_rank_doc(r, wall=1e9, mono=10.0 + r,
                            events=[("train/round", 10.0 + r, 0.005)]),
                  open(tmp_path / f"rank_{r}.trace.json", "w"))
    proc = subprocess.run(
        [sys.executable, SCRIPT, str(tmp_path)],
        capture_output=True, text=True)
    assert proc.returncode == 0, proc.stderr
    rec = json.loads(proc.stdout)
    assert rec["ranks"] == [0, 1] and rec["events"] == 2
    doc = json.load(open(tmp_path / "merged.trace.json"))
    assert isinstance(doc["traceEvents"], list)
    # nothing to merge -> exit 3, not a crash
    empty = tmp_path / "empty"
    empty.mkdir()
    proc = subprocess.run(
        [sys.executable, SCRIPT, str(empty)],
        capture_output=True, text=True)
    assert proc.returncode == 3


def test_one_rank_train_trace_merges(tmp_path):
    """In-container 1-rank path of the gang contract: a traced
    training run with a rank set exports rank_0.trace.json, and the
    CLI merges it into a loadable timeline with a rank-named row."""
    X = np.random.default_rng(0).normal(size=(400, 5))
    y = (X[:, 0] > 0).astype(np.float64)
    tdir = tmp_path / "trace"
    obs.set_trace_rank(0)
    lgb.train({"objective": "binary", "num_leaves": 4,
               "verbosity": -1, "tpu_trace_dir": str(tdir)},
              lgb.Dataset(X, label=y), num_boost_round=2)
    assert (tdir / "rank_0.trace.json").exists()
    proc = subprocess.run(
        [sys.executable, SCRIPT, str(tdir)],
        capture_output=True, text=True)
    assert proc.returncode == 0, proc.stderr
    doc = json.load(open(tdir / "merged.trace.json"))
    spans = {e["name"] for e in doc["traceEvents"]
             if e.get("ph") == "X"}
    # the fused path replaces train/round with train/fused — either
    # way the setup span is always there
    assert "train/setup" in spans
    names = [e["args"]["name"] for e in doc["traceEvents"]
             if e.get("ph") == "M" and e["name"] == "process_name"]
    assert any(n.startswith("rank 0") for n in names)
