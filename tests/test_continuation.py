"""Training continuation (init_model), refit, and snapshots
(reference: gbdt.cpp ResetTrainingData/RefitTree, Application
snapshot_freq; python-package engine.py init_model semantics)."""
import numpy as np

import lightgbm_tpu as lgb


def _binary_data(n=4000, f=10, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f))
    w = rng.normal(size=f)
    logit = X @ w + 0.5 * X[:, 0] * X[:, 1]
    y = (logit + rng.normal(scale=0.5, size=n) > 0).astype(np.float64)
    return X, y


PARAMS = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
          "learning_rate": 0.1}


def test_continue_equals_straight_training():
    """train 10 then continue 10 == train 20 (same data, no sampling)."""
    X, y = _binary_data()
    p20 = lgb.train(PARAMS, lgb.Dataset(X, label=y),
                    num_boost_round=20).predict(X, raw_score=True)
    bst10 = lgb.train(PARAMS, lgb.Dataset(X, label=y), num_boost_round=10)
    cont = lgb.train(PARAMS, lgb.Dataset(X, label=y), num_boost_round=10,
                     init_model=bst10)
    assert cont.num_trees() == 20
    np.testing.assert_allclose(cont.predict(X, raw_score=True), p20,
                               rtol=1e-4, atol=1e-4)


def test_continue_from_file(tmp_path):
    X, y = _binary_data(n=2000)
    bst = lgb.train(PARAMS, lgb.Dataset(X, label=y), num_boost_round=5)
    path = str(tmp_path / "m.txt")
    bst.save_model(path)
    cont = lgb.train(PARAMS, lgb.Dataset(X, label=y), num_boost_round=5,
                     init_model=path)
    assert cont.num_trees() == 10
    # the continued model's first-5-iteration predictions match the
    # original (loaded trees adopted verbatim)
    p5 = bst.predict(X, raw_score=True)
    p5b = cont.predict(X, raw_score=True, num_iteration=5)
    np.testing.assert_allclose(p5, p5b, rtol=1e-5, atol=1e-5)


def test_continue_multiclass():
    rng = np.random.default_rng(2)
    X = rng.normal(size=(2000, 8))
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(float) \
        + (X[:, 2] > 0.5).astype(float)
    params = {"objective": "multiclass", "num_class": 3, "num_leaves": 7,
              "verbosity": -1}
    p10 = lgb.train(params, lgb.Dataset(X, label=y),
                    num_boost_round=10).predict(X)
    b5 = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=5)
    cont = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=5,
                     init_model=b5)
    np.testing.assert_allclose(cont.predict(X), p10, rtol=1e-3, atol=1e-3)


def test_refit_decay():
    X, y = _binary_data(n=2000, seed=3)
    bst = lgb.train(PARAMS, lgb.Dataset(X, label=y), num_boost_round=10)
    # refit on label-flipped data with decay 0: leaf values re-derived
    # from the new gradients — predictions must change direction
    y_flip = 1.0 - y
    ref0 = bst.refit(X, y_flip, decay_rate=0.0)
    p_orig = bst.predict(X, raw_score=True)
    p_ref = ref0.predict(X, raw_score=True)
    assert np.corrcoef(p_orig, p_ref)[0, 1] < 0
    # decay 1.0 keeps the old leaf values exactly
    ref1 = bst.refit(X, y_flip, decay_rate=1.0)
    np.testing.assert_allclose(ref1.predict(X, raw_score=True), p_orig,
                               rtol=1e-6, atol=1e-6)


def test_snapshot_freq(tmp_path):
    X, y = _binary_data(n=1500, seed=4)
    out = str(tmp_path / "model.txt")
    params = dict(PARAMS, snapshot_freq=3, output_model=out)
    bst = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=7)
    import os
    snaps = sorted(p for p in os.listdir(tmp_path)
                   if ".snapshot_iter_" in p)
    assert snaps == ["model.txt.snapshot_iter_3", "model.txt.snapshot_iter_6"]
    snap6 = lgb.Booster(model_file=str(tmp_path / snaps[1]))
    np.testing.assert_allclose(
        snap6.predict(X), bst.predict(X, num_iteration=6),
        rtol=1e-5, atol=1e-5)


def test_refit_same_data_decay0_preserves_fit():
    """Sequential refit (GBDT::RefitTree order) on the training data with
    decay 0 re-derives ~the same leaf values — NOT zeros (which the
    broken all-at-final-score formulation would produce)."""
    X, y = _binary_data(n=2000, seed=5)
    bst = lgb.train(PARAMS, lgb.Dataset(X, label=y), num_boost_round=10)
    ref = bst.refit(X, y, decay_rate=0.0)
    p0 = bst.predict(X, raw_score=True)
    p1 = ref.predict(X, raw_score=True)
    assert np.corrcoef(p0, p1)[0, 1] > 0.99
    assert np.std(p1) > 0.5 * np.std(p0)


def test_refit_from_model_file_uses_stored_objective(tmp_path):
    """A Booster loaded from file (empty params) refits with the model's
    stored objective, not the regression default."""
    X, y = _binary_data(n=2000, seed=6)
    bst = lgb.train(PARAMS, lgb.Dataset(X, label=y), num_boost_round=10)
    path = str(tmp_path / "m.txt")
    bst.save_model(path)
    loaded = lgb.Booster(model_file=path)
    ref = loaded.refit(X, y, decay_rate=0.0)
    p0 = bst.predict(X, raw_score=True)
    p1 = ref.predict(X, raw_score=True)
    # binary log-loss gradients keep raw scores on the logit scale; the
    # regression default would collapse them toward [0, 1] residual fits
    assert np.corrcoef(p0, p1)[0, 1] > 0.99
    assert p1.max() > 0.7 * p0.max()


def test_continuation_mode_mismatch_errors():
    X, y = _binary_data(n=1000, seed=7)
    rf = lgb.train({"objective": "binary", "boosting": "rf",
                    "num_leaves": 7, "bagging_freq": 1,
                    "bagging_fraction": 0.7, "verbosity": -1},
                   lgb.Dataset(X, label=y), num_boost_round=3)
    import pytest
    with pytest.raises(lgb.LightGBMError):
        lgb.train(PARAMS, lgb.Dataset(X, label=y), num_boost_round=2,
                  init_model=rf)


def test_rf_continuation_keeps_bias():
    """RF-to-RF continuation: new trees must carry the init bias like the
    loaded ones (rf.hpp computes BoostFromAverage regardless of existing
    models), so continued-10 == straight-10 on imbalanced data."""
    rng = np.random.default_rng(8)
    X = rng.normal(size=(2000, 8))
    y = (X[:, 0] + rng.normal(scale=0.5, size=2000) > 1.0).astype(float)
    rf_params = {"objective": "binary", "boosting": "rf", "num_leaves": 15,
                 "bagging_freq": 1, "bagging_fraction": 0.7,
                 "verbosity": -1}
    straight = lgb.train(rf_params, lgb.Dataset(X, label=y),
                         num_boost_round=10)
    b5 = lgb.train(rf_params, lgb.Dataset(X, label=y), num_boost_round=5)
    cont = lgb.train(rf_params, lgb.Dataset(X, label=y), num_boost_round=5,
                     init_model=b5)
    assert cont.num_trees() == 10
    p_straight = straight.predict(X, raw_score=True)
    p_cont = cont.predict(X, raw_score=True)
    # same bagging RNG stream restarts, so trees differ — but the biased
    # averages must sit on the same scale (a dropped bias would shift
    # the mean by the init logit, ~-1.9 here)
    assert abs(p_cont.mean() - p_straight.mean()) < 0.15
