"""GOSS physical row compaction (tpu_goss_compact).

The reference's GOSS trains each tree on the sampled subset only
(goss.hpp bag_data_indices_); the default masked formulation here scans
every row with zero weights. Compaction gathers the sampled rows into a
fixed-size buffer — the SAME sample (same RNG stream), so models must
match the masked path up to float accumulation order.
"""
import numpy as np

import lightgbm_tpu as lgb


def _data(n=6000, f=10, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f))
    y = (X @ rng.normal(size=f) + rng.normal(scale=0.5, size=n) > 0)
    return X, y.astype(float)


def _train(compact, n_iter=12, extra=None):
    X, y = _data()
    params = {"objective": "binary", "num_leaves": 15,
              "data_sample_strategy": "goss", "learning_rate": 0.5,
              "top_rate": 0.2, "other_rate": 0.1, "verbosity": -1,
              "tpu_goss_compact": compact}
    params.update(extra or {})
    bst = lgb.train(params, lgb.Dataset(X, label=y),
                    num_boost_round=n_iter)
    return bst, X, y


def test_compact_matches_masked_goss():
    b_mask, X, y = _train(False)
    b_comp, _, _ = _train(True)
    pm = b_mask.predict(X)
    pc = b_comp.predict(X)
    # identical sample; only histogram accumulation order differs
    np.testing.assert_allclose(pc, pm, rtol=2e-2, atol=2e-3)
    # quality must be preserved, not just close pointwise
    from lightgbm_tpu.metric import AUCMetric
    from lightgbm_tpu.config import Config
    cfg = Config({"objective": "binary"})
    am = AUCMetric(cfg).eval(pm, y, None)[0][1]
    ac = AUCMetric(cfg).eval(pc, y, None)[0][1]
    assert abs(am - ac) < 5e-3


def test_compact_with_multiclass_and_quantized():
    rng = np.random.default_rng(3)
    X = rng.normal(size=(4000, 8))
    y = (X[:, 0] > 0).astype(int) + (X[:, 1] > 0.3).astype(int)
    params = {"objective": "multiclass", "num_class": 3,
              "num_leaves": 15, "data_sample_strategy": "goss",
              "learning_rate": 0.5, "verbosity": -1,
              "use_quantized_grad": True,
              "tpu_goss_compact": True}
    bst = lgb.train(params, lgb.Dataset(X, label=y.astype(float)),
                    num_boost_round=8)
    pred = bst.predict(X)
    assert pred.shape == (4000, 3)
    assert np.isfinite(pred).all()
    assert (pred.argmax(1) == y).mean() > 0.7


def test_compact_engine_flag_and_fallbacks():
    from lightgbm_tpu.boosting.gbdt import GBDT
    from lightgbm_tpu.config import Config
    # large enough that the compacted buffer (sampled rows + write
    # slack) genuinely shrinks the scan
    X, y = _data(20000, 6)
    ds = lgb.Dataset(X, label=y)
    eng = GBDT(Config({"objective": "binary",
                       "data_sample_strategy": "goss",
                       "tpu_goss_compact": True, "verbosity": -1}), ds)
    assert eng._use_goss_compact
    # linear trees force the masked path (leaf refit needs full rows)
    ds2 = lgb.Dataset(X, label=y, params={"linear_tree": True})
    eng2 = GBDT(Config({"objective": "binary", "linear_tree": True,
                        "data_sample_strategy": "goss",
                        "tpu_goss_compact": True, "verbosity": -1}), ds2)
    assert not eng2._use_goss_compact
    # tiny datasets: the buffer bound exceeds the data -> masked path
    # (round-4 guard; the kernel's write windows can then never clamp)
    Xs, ys = _data(2000, 6)
    eng3 = GBDT(Config({"objective": "binary",
                        "data_sample_strategy": "goss",
                        "tpu_goss_compact": True, "verbosity": -1}),
                lgb.Dataset(Xs, label=ys))
    assert not eng3._use_goss_compact


def test_goss_selects_exact_counts():
    """GOSS parity property (goss.hpp): exactly floor(a*n_valid) top
    rows and exactly floor(b*n_valid) random rows are selected every
    iteration (the reference static_casts, i.e. truncates), even with
    heavily tied |g*h| metrics."""
    from lightgbm_tpu.boosting.gbdt import GBDT
    from lightgbm_tpu.config import Config
    rng = np.random.default_rng(5)
    n = 4096
    X = rng.normal(size=(n, 4))
    # many duplicated rows -> tied gradients/hessians
    X[2000:] = X[:2096]
    y = (X[:, 0] > 0).astype(float)
    ds = lgb.Dataset(X, label=y)
    cfg = Config({"objective": "binary", "num_leaves": 7,
                  "data_sample_strategy": "goss", "learning_rate": 0.5,
                  "top_rate": 0.25, "other_rate": 0.15, "verbosity": -1})
    eng = GBDT(cfg, ds)
    for _ in range(3):
        eng.train_one_iter()
    n_valid = int(np.asarray(eng.data.valid_mask).sum())
    k_top = int(0.25 * n_valid)
    k_rand = int(0.15 * n_valid)    # engine truncates, then caps
    # engine-level check: run a GOSS iteration and inspect leaf counts
    eng.train_one_iter()
    t = eng.models[-1]
    total = float(np.sum(t.leaf_count))
    assert total == k_top + k_rand, (total, k_top, k_rand)


def test_wide_tree_matmul_and_gather_traversals_agree():
    """The num_leaves>512 gather fallback and the matmul formulation
    must route rows identically (incl. NaN-bin default direction)."""
    import jax.numpy as jnp
    from lightgbm_tpu.ops.predict import (tree_predict_binned,
                                          _tree_predict_binned_gather)
    rng = np.random.default_rng(7)
    n, F, L = 5000, 6, 64
    bins = jnp.asarray(rng.integers(0, 16, size=(n, F)).astype(np.uint8))
    # random consistent tree: node i children either deeper nodes or
    # leaves; build a left-spine tree with random features/thresholds
    lc = np.concatenate([np.arange(1, L - 1), [-L]]).astype(np.int32)
    rc = (-np.arange(1, L)).astype(np.int32)
    tree = {
        "num_leaves": jnp.asarray(L),
        "split_feature": jnp.asarray(
            rng.integers(0, F, L - 1).astype(np.int32)),
        "threshold_bin": jnp.asarray(
            rng.integers(0, 15, L - 1).astype(np.int32)),
        "default_left": jnp.asarray(rng.random(L - 1) < 0.5),
        "left_child": jnp.asarray(lc),
        "right_child": jnp.asarray(rc),
        "leaf_value": jnp.asarray(rng.normal(size=L).astype(np.float32)),
    }
    fnb = jnp.full(F, 16, jnp.int32)
    fhn = jnp.asarray(rng.random(F) < 0.5)   # some NaN-bin features
    v1, l1 = tree_predict_binned(tree, bins, fnb, fhn)
    node0 = jnp.zeros(n, jnp.int32)
    v2, l2 = _tree_predict_binned_gather(tree, bins, fnb, fhn, node0)
    np.testing.assert_array_equal(np.asarray(l1), np.asarray(l2))
    np.testing.assert_allclose(np.asarray(v1), np.asarray(v2), rtol=0,
                               atol=0)
