"""GOSS physical row compaction (tpu_goss_compact).

The reference's GOSS trains each tree on the sampled subset only
(goss.hpp bag_data_indices_); the default masked formulation here scans
every row with zero weights. Compaction gathers the sampled rows into a
fixed-size buffer — the SAME sample (same RNG stream), so models must
match the masked path up to float accumulation order.
"""
import numpy as np

import lightgbm_tpu as lgb


def _data(n=6000, f=10, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f))
    y = (X @ rng.normal(size=f) + rng.normal(scale=0.5, size=n) > 0)
    return X, y.astype(float)


def _train(compact, n_iter=12, extra=None):
    X, y = _data()
    params = {"objective": "binary", "num_leaves": 15,
              "data_sample_strategy": "goss", "learning_rate": 0.5,
              "top_rate": 0.2, "other_rate": 0.1, "verbosity": -1,
              "tpu_goss_compact": compact}
    params.update(extra or {})
    bst = lgb.train(params, lgb.Dataset(X, label=y),
                    num_boost_round=n_iter)
    return bst, X, y


def test_compact_matches_masked_goss():
    b_mask, X, y = _train(False)
    b_comp, _, _ = _train(True)
    pm = b_mask.predict(X)
    pc = b_comp.predict(X)
    # identical sample; only histogram accumulation order differs
    np.testing.assert_allclose(pc, pm, rtol=2e-2, atol=2e-3)
    # quality must be preserved, not just close pointwise
    from lightgbm_tpu.metric import AUCMetric
    from lightgbm_tpu.config import Config
    cfg = Config({"objective": "binary"})
    am = AUCMetric(cfg).eval(pm, y, None)[0][1]
    ac = AUCMetric(cfg).eval(pc, y, None)[0][1]
    assert abs(am - ac) < 5e-3


def test_compact_with_multiclass_and_quantized():
    rng = np.random.default_rng(3)
    X = rng.normal(size=(4000, 8))
    y = (X[:, 0] > 0).astype(int) + (X[:, 1] > 0.3).astype(int)
    params = {"objective": "multiclass", "num_class": 3,
              "num_leaves": 15, "data_sample_strategy": "goss",
              "learning_rate": 0.5, "verbosity": -1,
              "use_quantized_grad": True,
              "tpu_goss_compact": True}
    bst = lgb.train(params, lgb.Dataset(X, label=y.astype(float)),
                    num_boost_round=8)
    pred = bst.predict(X)
    assert pred.shape == (4000, 3)
    assert np.isfinite(pred).all()
    assert (pred.argmax(1) == y).mean() > 0.7


def test_compact_engine_flag_and_fallbacks():
    from lightgbm_tpu.boosting.gbdt import GBDT
    from lightgbm_tpu.config import Config
    X, y = _data(2000, 6)
    ds = lgb.Dataset(X, label=y)
    eng = GBDT(Config({"objective": "binary",
                       "data_sample_strategy": "goss",
                       "tpu_goss_compact": True, "verbosity": -1}), ds)
    assert eng._use_goss_compact
    # linear trees force the masked path (leaf refit needs full rows)
    ds2 = lgb.Dataset(X, label=y, params={"linear_tree": True})
    eng2 = GBDT(Config({"objective": "binary", "linear_tree": True,
                        "data_sample_strategy": "goss",
                        "tpu_goss_compact": True, "verbosity": -1}), ds2)
    assert not eng2._use_goss_compact
