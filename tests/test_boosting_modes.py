"""DART and RF boosting modes (reference: src/boosting/dart.hpp, rf.hpp
semantics; test style mirrors reference test_engine.py's mode tests)."""
import numpy as np
import pytest

import lightgbm_tpu as lgb


def _binary_data(n=4000, f=10, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f))
    w = rng.normal(size=f)
    logit = X @ w + 0.5 * X[:, 0] * X[:, 1]
    y = (logit + rng.normal(scale=0.5, size=n) > 0).astype(np.float64)
    return X, y


def _regression_data(n=3000, f=8, seed=1):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f))
    w = rng.normal(size=f)
    y = X @ w + np.sin(2 * X[:, 0]) + rng.normal(scale=0.1, size=n)
    return X, y


# ---------------------------------------------------------------------------
# DART
# ---------------------------------------------------------------------------
def test_dart_trains_and_predicts():
    X, y = _binary_data()
    ds = lgb.Dataset(X[:3000], label=y[:3000])
    vs = ds.create_valid(X[3000:], label=y[3000:])
    res = {}
    bst = lgb.train(
        {"objective": "binary", "boosting": "dart", "num_leaves": 31,
         "drop_rate": 0.3, "skip_drop": 0.25, "metric": "auc",
         "verbosity": -1}, ds, num_boost_round=30, valid_sets=[vs],
        callbacks=[lgb.record_evaluation(res)])
    auc = res["valid_0"]["auc"][-1]
    assert auc > 0.9
    # eval-score and predict() agree: the per-iteration renormalization
    # bookkeeping (device scores vs host tree shrinks) is consistent
    pred = bst.predict(X[3000:], raw_score=True)
    from lightgbm_tpu.metric import AUCMetric
    from lightgbm_tpu.config import Config
    auc2 = AUCMetric(Config({})).eval(pred, y[3000:], None)[0][1]
    assert abs(auc - auc2) < 1e-5


def test_dart_score_matches_stored_trees():
    """Internal train score == sum of stored (renormalized) trees."""
    X, y = _regression_data(n=1500)
    ds = lgb.Dataset(X, label=y)
    bst = lgb.train(
        {"objective": "regression", "boosting": "dart", "num_leaves": 15,
         "drop_rate": 0.5, "skip_drop": 0.0, "uniform_drop": True,
         "verbosity": -1}, ds, num_boost_round=15)
    eng = bst.engine
    internal = np.asarray(eng.score)[:eng.data.n, 0]
    manual = np.full(len(y), eng.init_scores[0])
    for t in eng.models:
        manual += t.predict_raw(X[:, eng.train_set.used_features])
    np.testing.assert_allclose(internal, manual, rtol=2e-4, atol=2e-4)


def test_dart_xgboost_mode():
    X, y = _binary_data(n=2000)
    ds = lgb.Dataset(X, label=y)
    bst = lgb.train(
        {"objective": "binary", "boosting": "dart", "num_leaves": 15,
         "xgboost_dart_mode": True, "drop_rate": 0.3, "skip_drop": 0.0,
         "verbosity": -1}, ds, num_boost_round=10)
    pred = bst.predict(X)
    assert pred.shape == (2000,)
    assert np.all((pred >= 0) & (pred <= 1))


def test_dart_model_text_roundtrip():
    X, y = _regression_data(n=1200)
    ds = lgb.Dataset(X, label=y)
    bst = lgb.train(
        {"objective": "regression", "boosting": "dart", "num_leaves": 15,
         "drop_rate": 0.4, "skip_drop": 0.1, "verbosity": -1}, ds,
        num_boost_round=12)
    s = bst.model_to_string()
    bst2 = lgb.Booster(model_str=s)
    np.testing.assert_allclose(bst.predict(X), bst2.predict(X),
                               rtol=1e-5, atol=1e-5)


# ---------------------------------------------------------------------------
# RF
# ---------------------------------------------------------------------------
def test_rf_requires_bagging():
    X, y = _binary_data(n=500)
    ds = lgb.Dataset(X, label=y)
    with pytest.raises(lgb.LightGBMError):
        lgb.train({"objective": "binary", "boosting": "rf",
                   "verbosity": -1}, ds, num_boost_round=2)


def test_rf_trains_and_averages():
    X, y = _binary_data()
    ds = lgb.Dataset(X[:3000], label=y[:3000])
    vs = ds.create_valid(X[3000:], label=y[3000:])
    res = {}
    bst = lgb.train(
        {"objective": "binary", "boosting": "rf", "num_leaves": 63,
         "bagging_freq": 1, "bagging_fraction": 0.6,
         "feature_fraction": 0.8, "metric": "auc", "verbosity": -1},
        ds, num_boost_round=20, valid_sets=[vs],
        callbacks=[lgb.record_evaluation(res)])
    auc = res["valid_0"]["auc"][-1]
    assert auc > 0.88
    # predict() averages: raw score bounded by the deepest single tree,
    # not growing with the number of trees
    raw = bst.predict(X[3000:], raw_score=True)
    pred = bst.predict(X[3000:])
    from lightgbm_tpu.metric import AUCMetric
    from lightgbm_tpu.config import Config
    auc2 = AUCMetric(Config({})).eval(pred, y[3000:], None)[0][1]
    assert abs(auc - auc2) < 1e-5
    # averaged output equals the mean of per-tree predictions (host check)
    eng = bst.engine
    Xu = X[3000:][:, eng.train_set.used_features]
    manual = np.mean([t.predict_raw(Xu) for t in eng.models], axis=0)
    np.testing.assert_allclose(raw, manual, rtol=2e-4, atol=2e-4)


def test_rf_trees_independent_of_order():
    """RF gradients are evaluated at the constant init score, so every
    tree fits the full target — not a residual: later trees have the
    same output scale as early trees."""
    X, y = _regression_data(n=1500)
    ds = lgb.Dataset(X, label=y)
    bst = lgb.train(
        {"objective": "regression", "boosting": "rf", "num_leaves": 31,
         "bagging_freq": 1, "bagging_fraction": 0.7, "verbosity": -1},
        ds, num_boost_round=10)
    eng = bst.engine
    Xu = X[:, eng.train_set.used_features]
    spans = [np.std(t.predict_raw(Xu)) for t in eng.models]
    # in boosted GBDT spans decay sharply; in RF they stay comparable
    assert spans[-1] > 0.5 * spans[0]


def test_rf_model_text_roundtrip_average_output():
    X, y = _binary_data(n=1500)
    ds = lgb.Dataset(X, label=y)
    bst = lgb.train(
        {"objective": "binary", "boosting": "rf", "num_leaves": 15,
         "bagging_freq": 1, "bagging_fraction": 0.7, "verbosity": -1},
        ds, num_boost_round=8)
    s = bst.model_to_string()
    assert "average_output" in s
    bst2 = lgb.Booster(model_str=s)
    np.testing.assert_allclose(bst.predict(X), bst2.predict(X),
                               rtol=1e-5, atol=1e-5)
