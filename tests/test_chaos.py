"""Chaos-hardened continuous training (ISSUE 9): the fault matrix,
the heartbeat watchdog, and zero-downtime serve-side hot-swap — the
pieces that turn train → checkpoint → hot-swap → serve into one loop
that survives injected kills, hangs, corruption and port races.

Tiers:

* in-process units: fault-spec grammar, deterministic corrupt seeds,
  marker hygiene, slow/hang/corrupt semantics, hot-swap + degradation
  (CompileWatch-pinned), the e2e train/publish/swap cycle under
  injected corruption;
* 1-process gangs (always runnable, SIGALRM-guarded like
  test_fault_tolerance.py): a hung rank detected by the heartbeat
  watchdog and relaunched to completion; a kill mid-STREAMED-run
  self-healing bit-exactly; an injected port conflict absorbed by the
  bind-retry path without consuming a restart attempt.
"""
import json
import os
import signal
import time

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.recovery.checkpoint import CheckpointManager
from lightgbm_tpu.recovery.faults import (FaultPlan, clear_fault_markers,
                                          parse_fault_spec,
                                          parse_fault_specs, spec_seed)
from lightgbm_tpu.recovery.restart import backoff_seconds, is_bind_failure
from lightgbm_tpu.utils.debug import CompileWatch


def _data(n=3_000, f=8, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f))
    y = (X[:, 0] + 0.4 * X[:, 1] + rng.normal(scale=0.3, size=n)
         > 0).astype(np.float64)
    return X, y


PARAMS = {"objective": "binary", "num_leaves": 8, "max_depth": 3,
          "verbosity": -1}


class _Watchdog:
    """SIGALRM in-test guard (same shape as test_fault_tolerance.py):
    a hung gang loop fails fast instead of eating the suite budget."""

    def __init__(self, seconds):
        self.seconds = seconds

    def __enter__(self):
        def _on_alarm(signum, frame):
            raise TimeoutError(f"chaos test exceeded its "
                               f"{self.seconds}s in-test watchdog")
        self._old = signal.signal(signal.SIGALRM, _on_alarm)
        signal.alarm(self.seconds)
        return self

    def __exit__(self, *exc):
        signal.alarm(0)
        signal.signal(signal.SIGALRM, self._old)
        return False


# ---------------------------------------------------------------------------
# fault-spec grammar: the matrix, per-kind keys, multi-spec lists
# ---------------------------------------------------------------------------
def test_fault_matrix_grammar():
    plan = parse_fault_spec("hang:rank=1,iter=10")
    assert (plan.kind, plan.rank, plan.iteration) == ("hang", 1, 10)
    plan = parse_fault_spec("slow:iter=3,ms=250")
    assert (plan.kind, plan.ms) == ("slow", 250)
    plan = parse_fault_spec("corrupt:iter=5,target=both,nbytes=16")
    assert (plan.kind, plan.target, plan.nbytes) == ("corrupt", "both",
                                                     16)
    plan = parse_fault_spec("port:iter=2")
    assert plan.kind == "port"
    # multi-spec lists parse in order
    plans = parse_fault_specs("slow:iter=1,ms=50;exn:iter=4")
    assert [p.kind for p in plans] == ["slow", "exn"]
    # per-kind key validation: keys a kind does not take are typos
    for bad in ("exn:iter=1,ms=5", "kill:iter=1,target=ckpt",
                "corrupt:iter=1,target=everything", "slow:iter=1,x=2",
                "wedge:iter=1"):
        with pytest.raises(lgb.LightGBMError):
            parse_fault_spec(bad)


def test_spec_seed_is_deterministic_and_spec_keyed():
    assert spec_seed("corrupt:iter=5") == spec_seed("corrupt:iter=5")
    assert spec_seed("corrupt:iter=5") != spec_seed("corrupt:iter=6")


def test_clear_fault_markers_is_rank_scoped(tmp_path):
    for name in (".fault_fired.aaaa.rank0", ".fault_fired.aaaa.rank1",
                 ".fault_fired.bbbb.rank0", "keepme.txt"):
        (tmp_path / name).write_text("x")
    assert clear_fault_markers(tmp_path, rank=0) == 2
    left = sorted(os.listdir(tmp_path))
    assert left == [".fault_fired.aaaa.rank1", "keepme.txt"]
    assert clear_fault_markers(tmp_path) == 1        # rank=None: all


def test_fresh_run_clears_stale_markers_but_relaunch_keeps_them(
        tmp_path, monkeypatch):
    """Satellite: yesterday's fire-once marker must not suppress
    today's injected fault — a FRESH run clears its rank's markers at
    setup. A gang RELAUNCH (LGBM_TPU_GANG_RELAUNCH set by the
    launcher) keeps them, so a from-scratch relaunch replaying the
    fault iteration does not re-die on it."""
    X, y = _data(n=1_000)
    spec = "exn:iter=2"
    params = dict(PARAMS, checkpoint_dir=str(tmp_path),
                  checkpoint_interval=10, tpu_fault_inject=spec)
    # plant the marker a previous run would have left
    plan = parse_fault_spec(spec, marker_dir=str(tmp_path))
    mp = plan.marker_path(0)
    open(mp, "w").write(spec)
    # fresh run: marker cleared -> the fault FIRES
    with pytest.raises(lgb.LightGBMError, match="injected failure"):
        lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=4)
    assert os.path.exists(mp)              # re-written by the firing
    # relaunch: marker kept -> the fault is skipped, training finishes
    monkeypatch.setenv("LGBM_TPU_GANG_RELAUNCH", "1")
    bst = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=4)
    assert bst.num_trees() == 4


# ---------------------------------------------------------------------------
# slow / hang / corrupt / port semantics
# ---------------------------------------------------------------------------
def test_slow_fault_delays_without_changing_the_model():
    """A straggler rank is SLOW, not wrong: the injected delay must
    cost wall clock and change nothing else."""
    X, y = _data(n=1_000)
    t0 = time.monotonic()
    clean = lgb.train(dict(PARAMS), lgb.Dataset(X, label=y),
                      num_boost_round=4)
    t_clean = time.monotonic() - t0
    t0 = time.monotonic()
    slowed = lgb.train(dict(PARAMS, tpu_fault_inject="slow:iter=1,ms=200"),
                       lgb.Dataset(X, label=y), num_boost_round=4)
    t_slow = time.monotonic() - t0
    assert slowed.model_to_string() == clean.model_to_string()
    # fires before iterations 1, 2, 3 -> >= 0.6s of injected delay
    assert t_slow >= t_clean + 0.5


def test_hang_fault_wedges_until_cap():
    """Without a watchdog the ms cap (tests only) releases the wedge;
    the marker makes it fire-once like every terminal fault."""
    plan = parse_fault_spec("hang:iter=3,ms=300")
    t0 = time.monotonic()
    with pytest.raises(lgb.LightGBMError, match="hang released"):
        plan.maybe_fire(3)
    assert time.monotonic() - t0 >= 0.3


def test_corrupt_fault_damages_newest_checkpoint_deterministically(
        tmp_path):
    """corrupt:target=both flips payload bytes in the newest rank-0
    checkpoint AND clobbers the latest pointer mid-training; training
    itself survives (corrupt is damage, not death), the damaged file
    fails verification, and the loader walks back to the previous
    valid checkpoint."""
    X, y = _data(n=1_500)
    params = dict(PARAMS, checkpoint_dir=str(tmp_path),
                  checkpoint_interval=2,
                  tpu_fault_inject="corrupt:iter=5,target=both")
    bst = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=6)
    assert bst.num_trees() == 6            # the run itself completed
    mgr = CheckpointManager(str(tmp_path), rank=0)
    # fired before iteration 5: the then-newest checkpoint (iter 4) is
    # damaged, the pointer is garbage; iter 6 landed valid afterwards
    from lightgbm_tpu.recovery.checkpoint import CheckpointError
    with pytest.raises(CheckpointError):
        mgr.load_file(mgr.path(4))
    assert mgr.latest_valid_iteration() == 6
    st = mgr.load()                        # pointer garbage -> scan
    assert st["iteration"] == 6


def test_port_fault_matches_bind_failure_classifier():
    plan = parse_fault_spec("port:iter=1")
    with pytest.raises(lgb.LightGBMError) as ei:
        plan.maybe_fire(1)
    assert is_bind_failure(str(ei.value))


# ---------------------------------------------------------------------------
# restart backoff: decorrelated jitter (satellite)
# ---------------------------------------------------------------------------
def test_backoff_jitter_bounds_and_determinism():
    import random
    # no rng: the original deterministic exponential
    assert backoff_seconds(2, base=0.5) == 1.0
    # seeded rng: deterministic replay, bounded by [base, cap], and
    # decorrelated (depends on prev, not on attempt alone)
    a = backoff_seconds(1, base=0.5, cap=30.0,
                        rng=random.Random(7), prev=0.0)
    b = backoff_seconds(1, base=0.5, cap=30.0,
                        rng=random.Random(7), prev=0.0)
    assert a == b
    assert 0.5 <= a <= 1.5                 # uniform(base, 3*base)
    c = backoff_seconds(2, base=0.5, cap=30.0,
                        rng=random.Random(7), prev=10.0)
    assert 0.5 <= c <= 30.0
    # two seeds diverge — the whole point is ranks NOT sleeping in
    # lockstep
    vals = {backoff_seconds(1, base=0.5, rng=random.Random(s))
            for s in range(20)}
    assert len(vals) > 10
    # cap always wins
    assert backoff_seconds(9, base=1.0, cap=3.0,
                           rng=random.Random(1), prev=100.0) <= 3.0


# ---------------------------------------------------------------------------
# heartbeat files (obs <-> launcher watchdog contract)
# ---------------------------------------------------------------------------
def test_heartbeat_file_stamps_and_retires(tmp_path):
    from lightgbm_tpu import obs
    path = str(tmp_path / "heartbeat.train.rank0")
    obs.set_heartbeat_file("train", path, min_interval=0.0)
    try:
        assert not os.path.exists(path)    # lazily created: no stamp,
        obs.heartbeat("train")             # no file (startup != stale)
        assert os.path.exists(path)
        m0 = os.stat(path).st_mtime
        time.sleep(0.05)
        obs.heartbeat("train")
        assert os.stat(path).st_mtime >= m0
    finally:
        obs.retire_heartbeat("train")
    assert not os.path.exists(path)        # clean finish = absent


def test_stale_heartbeat_detection(tmp_path):
    from lightgbm_tpu.parallel.launch import _stale_heartbeats
    p = tmp_path / "heartbeat.train.rank2"
    p.write_text("")
    old = time.time() - 60
    os.utime(p, (old, old))
    stale = _stale_heartbeats(str(tmp_path), 5.0)
    assert stale and stale[0][0] == 2 and stale[0][1] > 50
    # a fresh stamp is not stale; a missing dir is never stale
    os.utime(p)
    assert _stale_heartbeats(str(tmp_path), 5.0) == []
    assert _stale_heartbeats(str(tmp_path / "nope"), 5.0) == []


# ---------------------------------------------------------------------------
# serve-side hot-swap: warm adoption, zero recompiles, degradation
# ---------------------------------------------------------------------------
def _publish(pub_dir, rounds=8, seed=7, **extra):
    """One trainer cycle: train a fresh model publishing checkpoints
    into pub_dir (cleared fresh each time by train()'s hygiene is NOT
    wanted here — successive cycles resume_from=None would clear, so
    each cycle uses the callback directly via params on a fresh
    Booster; the checkpoint files accumulate/prune per keep_n)."""
    X, y = _data(n=2_000, seed=seed)
    p = dict(PARAMS, checkpoint_dir=str(pub_dir), checkpoint_interval=rounds,
             seed=seed, feature_fraction=0.9)
    return lgb.train(p, lgb.Dataset(X, label=y), num_boost_round=rounds)


def test_hot_swap_zero_recompiles_and_degradation(tmp_path):
    """The acceptance pin: N publish/swap cycles with ZERO warm-path
    recompiles (CompileWatch), zero dropped requests, atomic swaps;
    an injected corrupt publish keeps the previous model serving with
    serve.model_stale flipped, and the next good publish recovers."""
    from lightgbm_tpu import obs
    X, y = _data(n=2_000)
    pub = tmp_path / "pub"
    server = lgb.train(dict(PARAMS), lgb.Dataset(X, label=y),
                       num_boost_round=8)
    server.watch_checkpoints(str(pub), interval=0.0)
    Xq = X[:400]
    p_prev = server.predict(Xq)            # warm-up: compiles the
    server.predict(Xq)                     # bucketed padded shapes
    preds = {0: p_prev}
    for cycle in range(1, 4):
        _publish(pub, seed=100 + cycle)
        with CompileWatch() as w:
            preds[cycle] = server.predict(Xq)
        w.assert_compiles(0)               # warm path across the swap
        assert not np.allclose(preds[cycle], preds[cycle - 1])
    watch = server._model_watch
    assert watch.swaps == 3 and not watch.stale
    it_gauge = obs.registry().get("serve.model_iteration")
    assert it_gauge is not None and it_gauge.value == 8
    # corrupt publish: flip payload bytes in the newest checkpoint and
    # clobber the pointer (the chaos harness's own corrupt fault does
    # exactly this mid-training)
    mgr = CheckpointManager(str(pub), rank=0)
    newest = mgr.path(mgr.iterations()[-1])
    blob = open(newest, "rb").read()
    open(newest, "wb").write(blob[:-64] + bytes(64))
    open(mgr.latest_pointer, "w").write("ckpt_garbage")
    watch._last_sig = None                 # force the next poll to look
    with CompileWatch() as w:
        p_stale = server.predict(Xq)
    w.assert_compiles(0)
    assert np.allclose(p_stale, preds[3])  # previous model kept serving
    assert watch.stale
    assert obs.registry().get("serve.model_stale").value == 1.0
    assert obs.registry().get("serve.swap_failures").value >= 1.0
    # freshness lag is visible while pinned on the old model
    lag = obs.registry().get("train.freshness_lag_s")
    assert lag is not None and lag.value >= 0.0
    # the next GOOD publish recovers
    _publish(pub, seed=999)
    p_new = server.predict(Xq)
    assert not np.allclose(p_new, p_stale)
    assert not watch.stale and watch.swaps == 4
    assert obs.registry().get("serve.model_stale").value == 0.0


def test_watch_never_downgrades_a_newer_in_memory_model(tmp_path):
    """A trainer serving its OWN model finds its last round-boundary
    checkpoint in the watched dir — a PREFIX of the model in memory
    (the final iterations are rarely on a checkpoint boundary).
    Adopting it would silently drop trees; the first-adoption baseline
    refuses the downgrade and flags staleness instead, while anything
    published AFTER the watch started still swaps."""
    X, y = _data(n=1_500)
    p = dict(PARAMS, checkpoint_dir=str(tmp_path), checkpoint_interval=4)
    bst = lgb.train(p, lgb.Dataset(X, label=y), num_boost_round=6)
    assert bst.num_trees() == 6            # newest checkpoint is iter 4
    pred = bst.predict(X[:200])
    bst.watch_checkpoints(str(tmp_path), interval=0.0)
    p2 = bst.predict(X[:200])
    assert bst._model_watch.swaps == 0     # refused the iter-4 prefix
    assert bst._model_watch.stale          # ...and said so
    assert bst.num_trees() == 6
    np.testing.assert_array_equal(pred, p2)
    # a publish AFTER the watch started adopts normally
    _publish(tmp_path, seed=77, rounds=4)
    p3 = bst.predict(X[:200])
    assert bst._model_watch.swaps == 1
    assert not np.allclose(p2, p3)


def test_hot_swap_host_model_booster(tmp_path):
    """A model-file-loaded Booster (no engine) swaps via model_str —
    the load-model-and-serve pod shape."""
    X, y = _data(n=1_500)
    pub = tmp_path / "pub"
    base = lgb.train(dict(PARAMS), lgb.Dataset(X, label=y),
                     num_boost_round=6)
    server = lgb.Booster(model_str=base.model_to_string())
    server.watch_checkpoints(str(pub), interval=0.0)
    p0 = server.predict(X[:200])
    _publish(pub, seed=42, rounds=6)
    p1 = server.predict(X[:200])
    assert not np.allclose(p0, p1)
    assert server._model_watch.swaps == 1


def test_hot_swap_streamed_trainer_to_resident_server(tmp_path):
    """The continuous-training composition: the STREAMED engine
    publishes, a resident server adopts (same binning pipeline — same
    data/params). The checkpointed streamed trees carry real-valued
    thresholds in model_str AND exact pickled trees, so either path
    serves them."""
    X, y = _data(n=4_000)
    pub = tmp_path / "pub"
    server = lgb.train(dict(PARAMS), lgb.Dataset(X, label=y),
                       num_boost_round=6)
    server.watch_checkpoints(str(pub), interval=0.0)
    server.predict(X[:200])
    streamed = lgb.train(
        dict(PARAMS, tpu_streaming="true", tpu_stream_block_rows=1_024,
             checkpoint_dir=str(pub), checkpoint_interval=6),
        lgb.Dataset(X, label=y), num_boost_round=6)
    p = server.predict(X[:200])
    assert server._model_watch.swaps == 1
    # the swapped-in forest serves the streamed model's predictions
    np.testing.assert_allclose(p, streamed.predict(X[:200]), rtol=1e-6)


def test_e2e_chaos_cycle_freshness_and_zero_drops(tmp_path):
    """Capstone (in-process): N train -> publish -> swap -> serve
    cycles with a corrupt publish injected mid-sequence via the chaos
    harness's own corrupt fault. Zero dropped requests (every predict
    returns), swaps land, staleness is visible then clears, and the
    freshness-lag gauge tracks the served checkpoint's age."""
    from lightgbm_tpu import obs
    X, y = _data(n=2_000)
    pub = tmp_path / "pub"
    server = lgb.train(dict(PARAMS), lgb.Dataset(X, label=y),
                       num_boost_round=8)
    server.watch_checkpoints(str(pub), interval=0.0)
    Xq = X[:256]
    server.predict(Xq)
    dropped = 0
    stale_seen = False
    latencies = []
    for cycle in range(4):
        if cycle == 2:
            # chaos: the trainer's OWN publish gets corrupted by the
            # injected fault right after the checkpoint lands
            Xc, yc = _data(n=2_000, seed=50 + cycle)
            p = dict(PARAMS, checkpoint_dir=str(pub),
                     checkpoint_interval=4, seed=50 + cycle,
                     tpu_fault_inject="corrupt:iter=4,target=both")
            lgb.train(p, lgb.Dataset(Xc, label=yc), num_boost_round=5)
            # only the corrupted iter-4 publish exists this cycle: the
            # server must keep serving and flag staleness
        else:
            _publish(pub, seed=50 + cycle, rounds=8)
        for _ in range(5):                 # serve traffic through it
            t0 = time.perf_counter()
            try:
                out = server.predict(Xq)
                assert out.shape == (len(Xq),)
            except Exception:
                dropped += 1
            latencies.append(time.perf_counter() - t0)
        stale_seen = stale_seen or server._model_watch.stale
    assert dropped == 0
    assert stale_seen                      # the corrupt cycle showed up
    assert not server._model_watch.stale   # ...and the next one healed
    assert server._model_watch.swaps >= 3
    lag = obs.registry().get("train.freshness_lag_s")
    assert lag is not None and 0.0 <= lag.value < 300.0
    p99 = float(np.quantile(latencies, 0.99))
    assert p99 < 30.0                      # sane, not a perf pin


# ---------------------------------------------------------------------------
# 1-process gangs: watchdog hang relaunch, streamed kill self-heal,
# port-fault bind retry (SIGALRM-guarded)
# ---------------------------------------------------------------------------
def chaos_shard_fn(rank, nproc):
    """Module-level so spawned workers can unpickle it."""
    rng = np.random.default_rng(3)
    X = rng.normal(size=(2_000, 6))
    y = (X[:, 0] + 0.3 * X[:, 1] > 0).astype(np.float64)
    blk = len(X) // nproc
    lo = rank * blk
    hi = len(X) if rank == nproc - 1 else lo + blk
    return {"data": X[lo:hi], "label": y[lo:hi]}


GANG_PARAMS = {"objective": "binary", "num_leaves": 7, "verbosity": -1}


def test_hung_rank_detected_and_gang_relaunched(tmp_path):
    """Acceptance: an injected hang (which previously wedged forever —
    no exit code, no queue result) is detected via its stale heartbeat
    file within tpu_heartbeat_timeout, the gang is killed and
    relaunched through the normal backoff path, and the job completes
    within max_restarts without human intervention."""
    from lightgbm_tpu import obs
    d = str(tmp_path / "ck")
    params = dict(GANG_PARAMS, checkpoint_dir=d, checkpoint_interval=4,
                  tpu_fault_inject="hang:rank=0,iter=9")
    before = getattr(obs.registry().get("watchdog.restarts"), "value",
                     0.0)
    with _Watchdog(115):
        bst = lgb.train_distributed(
            params, chaos_shard_fn, n_processes=1, num_boost_round=12,
            timeout=90.0, max_restarts=2, restart_backoff=0.2,
            heartbeat_timeout=4.0)
    assert bst.num_trees() == 12
    after = obs.registry().get("watchdog.restarts").value
    assert after >= before + 1             # the watchdog, not the
    #                                        blunt timeout, caught it
    assert CheckpointManager(d, rank=0).latest_valid_iteration() == 12


def test_streamed_gang_kill_self_heals_bit_exact(tmp_path):
    """Acceptance: a kill injected by the chaos harness mid-STREAMED-
    run; the relaunched gang resumes streamed training from the newest
    checkpoint and the healed model is bit-identical to the fault-free
    gang's."""
    d_ok = str(tmp_path / "ok")
    d_fault = str(tmp_path / "fault")
    stream = dict(GANG_PARAMS, tpu_streaming="true",
                  tpu_stream_block_rows=512, checkpoint_interval=4)
    with _Watchdog(115):
        baseline = lgb.train_distributed(
            dict(stream, checkpoint_dir=d_ok), chaos_shard_fn,
            n_processes=1, num_boost_round=10, timeout=90.0)
        healed = lgb.train_distributed(
            dict(stream, checkpoint_dir=d_fault,
                 tpu_fault_inject="kill:rank=0,iter=6"),
            chaos_shard_fn, n_processes=1, num_boost_round=10,
            timeout=90.0, max_restarts=2, restart_backoff=0.2)
    assert [n for n in os.listdir(d_fault)
            if n.startswith(".fault_fired.")], "kill was never injected"
    assert healed.num_trees() == 10
    assert healed.model_to_string() == baseline.model_to_string()


def test_injected_port_conflict_absorbed_by_bind_retry(tmp_path):
    """A port fault raises the bind-conflict shape mid-run; the
    launcher's bind-retry path relaunches on a fresh port WITHOUT
    consuming a restart attempt (max_restarts=0 still succeeds), and
    the fire-once marker keeps the retry from re-dying."""
    from lightgbm_tpu import obs
    d = str(tmp_path / "ck")
    params = dict(GANG_PARAMS, checkpoint_dir=d, checkpoint_interval=2,
                  tpu_fault_inject="port:iter=3")
    before = getattr(obs.registry().get("restart.bind_retries"),
                     "value", 0.0)
    with _Watchdog(115):
        bst = lgb.train_distributed(params, chaos_shard_fn,
                                    n_processes=1, num_boost_round=6,
                                    timeout=90.0, max_restarts=0)
    assert bst.num_trees() == 6
    assert obs.registry().get("restart.bind_retries").value \
        >= before + 1
