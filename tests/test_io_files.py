"""File loaders (CSV/TSV/LibSVM, native C++ parser), binary dataset
format, and the CLI task runner (reference: src/io/parser.cpp,
dataset_loader.cpp, src/application/)."""
import os

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.io.text_loader import load_text, sniff_format


def _write_csv(path, X, y, header=True, delim=","):
    names = ["target"] + [f"f{i}" for i in range(X.shape[1])]
    with open(path, "w") as f:
        if header:
            f.write(delim.join(names) + "\n")
        for i in range(len(X)):
            row = [f"{y[i]:g}"] + [f"{v:.8g}" for v in X[i]]
            f.write(delim.join(row) + "\n")


def _data(n=600, f=5, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f))
    y = (X @ rng.normal(size=f) > 0).astype(float)
    return X, y


def test_native_parser_compiles():
    from lightgbm_tpu.native import text_parser
    lib = text_parser()
    assert lib is not None, "g++ is in the image; native parser must build"


def test_csv_with_header_roundtrip(tmp_path):
    X, y = _data()
    path = str(tmp_path / "train.csv")
    _write_csv(path, X, y)
    kind, delim, header = sniff_format(path)
    assert (kind, delim, header) == ("csv", ",", True)
    loaded = load_text(path)
    np.testing.assert_allclose(loaded.label, y)
    np.testing.assert_allclose(loaded.X, X, rtol=1e-6)
    assert loaded.feature_names == [f"f{i}" for i in range(5)]


def test_tsv_no_header_with_nan(tmp_path):
    X, y = _data(n=100)
    X[3, 2] = np.nan
    path = str(tmp_path / "train.tsv")
    with open(path, "w") as f:
        for i in range(len(X)):
            vals = [f"{y[i]:g}"] + [
                "NA" if np.isnan(v) else f"{v:.8g}" for v in X[i]]
            f.write("\t".join(vals) + "\n")
    kind, delim, header = sniff_format(path)
    assert (kind, delim, header) == ("csv", "\t", False)
    loaded = load_text(path)
    assert np.isnan(loaded.X[3, 2])
    np.testing.assert_allclose(loaded.label, y)


def test_libsvm_roundtrip(tmp_path):
    rng = np.random.default_rng(2)
    n, F = 300, 8
    X = np.zeros((n, F))
    y = rng.integers(0, 2, n).astype(float)
    for i in range(n):
        for j in rng.choice(F, size=3, replace=False):
            X[i, j] = round(float(rng.normal()), 6)
    path = str(tmp_path / "train.svm")
    with open(path, "w") as f:
        for i in range(n):
            nz = np.flatnonzero(X[i])
            f.write(f"{y[i]:g} " + " ".join(
                f"{j}:{X[i, j]:.6g}" for j in nz) + "\n")
    kind, _, _ = sniff_format(path)
    assert kind == "libsvm"
    loaded = load_text(path)
    np.testing.assert_allclose(loaded.label, y)
    np.testing.assert_allclose(loaded.X, X[:, :loaded.X.shape[1]],
                               rtol=1e-5, atol=1e-8)


def test_sidecar_weight_query(tmp_path):
    X, y = _data(n=200)
    path = str(tmp_path / "rank.tsv")
    _write_csv(path, X, y, header=False, delim="\t")
    np.savetxt(path + ".weight", np.linspace(0.5, 1.5, 200))
    np.savetxt(path + ".query", np.full(10, 20), fmt="%d")
    loaded = load_text(path)
    assert loaded.weight is not None and len(loaded.weight) == 200
    assert loaded.group is not None and loaded.group.sum() == 200


def test_train_from_csv_file(tmp_path):
    X, y = _data(n=1000)
    path = str(tmp_path / "train.csv")
    _write_csv(path, X, y)
    ds = lgb.Dataset(path)
    bst = lgb.train({"objective": "binary", "num_leaves": 15,
                     "verbosity": -1}, ds, num_boost_round=10)
    pred = bst.predict(X)
    acc = np.mean((pred > 0.5) == y)
    assert acc > 0.85


def test_binary_dataset_roundtrip(tmp_path):
    X, y = _data(n=800)
    ds = lgb.Dataset(X, label=y)
    ds.construct()
    bin_path = str(tmp_path / "train.bin")
    ds.save_binary(bin_path)
    ds2 = lgb.Dataset(bin_path)
    assert ds2.num_data == 800
    np.testing.assert_array_equal(ds2.binned, ds.binned)
    b1 = lgb.train({"objective": "binary", "num_leaves": 15,
                    "verbosity": -1}, lgb.Dataset(X, label=y),
                   num_boost_round=5)
    b2 = lgb.train({"objective": "binary", "num_leaves": 15,
                    "verbosity": -1}, ds2, num_boost_round=5)
    np.testing.assert_allclose(b1.predict(X), b2.predict(X),
                               rtol=1e-6, atol=1e-6)


def test_cli_train_and_predict(tmp_path):
    from lightgbm_tpu.app import run
    X, y = _data(n=1000)
    train_path = str(tmp_path / "train.csv")
    valid_path = str(tmp_path / "valid.csv")
    _write_csv(train_path, X[:800], y[:800])
    _write_csv(valid_path, X[800:], y[800:])
    model_path = str(tmp_path / "model.txt")
    conf = tmp_path / "train.conf"
    conf.write_text(
        f"task = train\n"
        f"objective = binary\n"
        f"data = {train_path}\n"
        f"valid = {valid_path}\n"
        f"num_iterations = 10   # comment\n"
        f"num_leaves = 15\n"
        f"verbosity = -1\n"
        f"output_model = {model_path}\n")
    assert run([f"config={conf}"]) == 0
    assert os.path.exists(model_path)

    out_path = str(tmp_path / "preds.txt")
    assert run([f"task=predict", f"data={valid_path}",
                f"input_model={model_path}",
                f"output_result={out_path}", "verbosity=-1"]) == 0
    preds = np.loadtxt(out_path)
    assert preds.shape == (200,)
    assert np.mean((preds > 0.5) == y[800:]) > 0.8


def test_cli_refit_matches_python_refit(tmp_path):
    """task=refit must call Booster.refit (gbdt.cpp::RefitTree — re-fit
    existing leaf values, NOT training continuation): tree count is
    unchanged and output equals the Python refit path."""
    from lightgbm_tpu.app import run
    X, y = _data(n=1000)
    train_path = str(tmp_path / "train.csv")
    _write_csv(train_path, X[:700], y[:700])
    refit_path = str(tmp_path / "refit.csv")
    _write_csv(refit_path, X[700:], y[700:])
    model_path = str(tmp_path / "model.txt")
    bst = lgb.train({"objective": "binary", "num_leaves": 15,
                     "verbosity": -1}, lgb.Dataset(X[:700], label=y[:700]),
                    num_boost_round=8)
    bst.save_model(model_path)
    out_path = str(tmp_path / "refitted.txt")
    assert run(["task=refit", f"data={refit_path}",
                f"input_model={model_path}", f"output_model={out_path}",
                "refit_decay_rate=0.8", "verbosity=-1"]) == 0
    cli_bst = lgb.Booster(model_file=out_path)
    # same number of trees — refit never adds iterations
    assert cli_bst.num_trees() == bst.num_trees()
    py_bst = lgb.Booster(model_file=model_path).refit(
        X[700:], y[700:], decay_rate=0.8)
    np.testing.assert_allclose(cli_bst.predict(X), py_bst.predict(X),
                               rtol=1e-6, atol=1e-6)
    # and it actually changed the leaves vs the original model
    assert not np.allclose(cli_bst.predict(X), bst.predict(X))


def test_two_round_streamed_load_matches_one_round(tmp_path):
    """two_round=true streams the file twice (sample pass + binning
    pass) without materializing the raw matrix (dataset_loader.cpp
    two-round path). Same mappers + binned matrix + model as one-round
    when the sample covers all rows."""
    X, y = _data(n=3000)
    path = str(tmp_path / "train.csv")
    _write_csv(path, X, y)
    one = lgb.Dataset(path, params={"max_bin": 63})
    one.construct()
    two = lgb.Dataset(path, params={"max_bin": 63, "two_round": True,
                                    "tpu_stream_chunk_rows": 1000})
    two.construct()
    assert two.num_data == one.num_data
    np.testing.assert_array_equal(two.binned, one.binned)
    np.testing.assert_array_equal(two.metadata.label, one.metadata.label)
    b1 = lgb.train({"objective": "binary", "num_leaves": 15,
                    "verbosity": -1}, one, num_boost_round=5)
    b2 = lgb.train({"objective": "binary", "num_leaves": 15,
                    "verbosity": -1}, two, num_boost_round=5)
    np.testing.assert_allclose(b1.predict(X), b2.predict(X),
                               rtol=1e-6, atol=1e-6)


def test_two_round_valid_set_adopts_reference_mappers(tmp_path):
    """A two_round valid set must bin against the TRAINING mappers
    (reference), exactly like the one-round path."""
    X, y = _data(n=2000)
    tp, vp = str(tmp_path / "t.csv"), str(tmp_path / "v.csv")
    _write_csv(tp, X[:1500], y[:1500])
    _write_csv(vp, X[1500:], y[1500:])
    params = {"two_round": True, "max_bin": 31,
              "tpu_stream_chunk_rows": 1000}
    ds = lgb.Dataset(tp, params=dict(params))
    vs = lgb.Dataset(vp, reference=ds, params=dict(params))
    vs.construct()
    ds.construct()
    for m1, m2 in zip(ds.bin_mappers, vs.bin_mappers):
        np.testing.assert_array_equal(m1.bin_upper_bound,
                                      m2.bin_upper_bound)
    res = {}
    lgb.train({"objective": "binary", "num_leaves": 15, "metric": "auc",
               "verbosity": -1}, ds, num_boost_round=8, valid_sets=[vs],
              callbacks=[lgb.record_evaluation(res)])
    assert res["valid_0"]["auc"][-1] > 0.85


def test_two_round_sidecar_query_file(tmp_path):
    """two_round must honor <data>.query sidecars like the one-round
    loader (metadata.cpp)."""
    rng = np.random.default_rng(5)
    n_q, per_q = 40, 25
    X = rng.normal(size=(n_q * per_q, 5))
    y = np.clip(X[:, 0] + rng.normal(scale=0.5, size=len(X)),
                0, 3).astype(int).astype(float)
    p = str(tmp_path / "rank.csv")
    _write_csv(p, X, y)
    np.savetxt(p + ".query", np.full(n_q, per_q, dtype=np.int64),
               fmt="%d")
    ds = lgb.Dataset(p, params={"two_round": True,
                                "tpu_stream_chunk_rows": 300})
    ds.construct()
    assert ds.metadata.query_boundaries is not None
    assert len(ds.metadata.query_boundaries) == n_q + 1
    bst = lgb.train({"objective": "lambdarank", "num_leaves": 15,
                     "verbosity": -1}, ds, num_boost_round=3)
    assert np.isfinite(bst.predict(X)).all()


def test_two_round_subsampled_mappers_trains(tmp_path):
    """When rows exceed the bin sample cap, the streamed sample is a
    bottom-k uniform draw; the model still trains fine."""
    X, y = _data(n=4000)
    path = str(tmp_path / "train.csv")
    _write_csv(path, X, y)
    ds = lgb.Dataset(path, params={"two_round": True,
                                   "bin_construct_sample_cnt": 500,
                                   "tpu_stream_chunk_rows": 1000})
    bst = lgb.train({"objective": "binary", "num_leaves": 15,
                     "verbosity": -1}, ds, num_boost_round=8)
    acc = np.mean((bst.predict(X) > 0.5) == y)
    assert acc > 0.85


def test_cli_save_binary(tmp_path):
    from lightgbm_tpu.app import run
    X, y = _data(n=300)
    p = str(tmp_path / "d.csv")
    _write_csv(p, X, y)
    out = str(tmp_path / "d.bin")
    assert run(["task=save_binary", f"data={p}",
                f"output_data={out}", "verbosity=-1"]) == 0
    ds = lgb.Dataset(out)
    assert ds.num_data == 300


def test_cli_distributed_train_uneven_shards(tmp_path,
                                             multiprocess_collectives):
    """VERDICT r4 item 10: ``task=train num_machines=4`` from a config
    file drives the fork/join launcher. Row count 4097 makes the last
    rank's shard cross a pad-block boundary, exercising the
    globally-agreed pad layout (shapes would diverge across processes
    without the counts allgather). Needs REAL multi-process
    collectives, which this container's jaxlib CPU backend lacks — the
    conftest capability probe skips it there (known-red since the PR-1
    seed) instead of leaving tier-1 with an expected failure."""
    from lightgbm_tpu.app import run
    X, y = _data(n=4097)
    train_path = str(tmp_path / "train.csv")
    _write_csv(train_path, X, y)
    model_path = str(tmp_path / "model.txt")
    conf = tmp_path / "dist.conf"
    conf.write_text(
        f"task = train\n"
        f"objective = binary\n"
        f"data = {train_path}\n"
        f"num_machines = 4\n"
        f"num_iterations = 5\n"
        f"num_leaves = 15\n"
        f"min_data_in_leaf = 20\n"
        f"verbosity = -1\n"
        f"output_model = {model_path}\n")
    assert run([f"config={conf}"]) == 0
    assert os.path.exists(model_path)
    bst = lgb.Booster(model_file=model_path)
    assert np.mean((bst.predict(X) > 0.5) == y) > 0.8


def test_two_round_streams_peak_rss(tmp_path):
    """The streamed loader must never hold the full raw float64 matrix:
    its peak traced allocation while loading has to stay well under
    both the one-round loader's (which materializes the parse buffer +
    X) and the raw matrix size itself — the chunked-parse-into-ingest
    memory contract. tracemalloc (numpy buffers are tracked) gives a
    deterministic high-water mark where process RSS cannot (the jax
    import already dwarfs a small load's RSS delta)."""
    import tracemalloc
    rng = np.random.default_rng(5)
    n, f = 250_000, 20
    X = rng.normal(size=(n, f))
    y = (X[:, 0] > 0).astype(float)
    path = str(tmp_path / "big.csv")
    try:
        import pandas as pd
        cols = {"target": y}
        cols.update({f"f{i}": X[:, i] for i in range(f)})
        pd.DataFrame(cols).to_csv(path, index=False,
                                  float_format="%.8g")
    except ImportError:
        _write_csv(path, X, y)
    del X, y
    raw_bytes = n * f * 8

    def peak_load(stream: bool) -> tuple:
        params = {"max_bin": 63, "bin_construct_sample_cnt": 20000,
                  "verbosity": -1}
        if stream:
            params.update({"two_round": True,
                           "tpu_stream_chunk_rows": 20000})
        tracemalloc.start()
        try:
            ds = lgb.Dataset(path, params=params).construct()
            peak = tracemalloc.get_traced_memory()[1]
        finally:
            tracemalloc.stop()
        return peak, ds.num_data

    one_peak, n_one = peak_load(stream=False)
    stream_peak, n_stream = peak_load(stream=True)
    assert n_one == n_stream == n
    # the streamed path must beat one-round by a wide margin AND stay
    # below the raw matrix size itself (chunk + binned + sample pool)
    assert stream_peak < 0.6 * one_peak, (stream_peak, one_peak)
    assert stream_peak < raw_bytes, (stream_peak, raw_bytes)


def test_cli_file_shard_rejects_too_few_rows(tmp_path):
    """num_machines exceeding the file's row count must fatal with a
    clear message instead of silently emitting 0-row shards (whose
    empty datasets fail much later and much more cryptically)."""
    import pytest as _pytest

    from lightgbm_tpu.app import _cli_file_shard
    from lightgbm_tpu.utils.log import LightGBMError
    X, y = _data(n=3)
    path = str(tmp_path / "tiny.csv")
    _write_csv(path, X, y)
    with _pytest.raises(LightGBMError, match="num_machines"):
        _cli_file_shard(path, {}, rank=0, nproc=8)
    # a row count >= nproc shards fine (last rank takes the remainder)
    shard = _cli_file_shard(path, {}, rank=1, nproc=2)
    assert len(shard["data"]) == 2
