"""Multi-tenant LRU of device forests (serve/registry.py).

What these tests pin (the registry satellite checklist):

* **LRU order** — admission past ``tpu_serve_cache_models`` evicts the
  least-recently-CHECKED-OUT model, and a checkout refreshes recency.
* **Byte cap** — an explicit ``tpu_serve_cache_bytes`` (and the auto
  cap derived from a mocked ``hbm_bytes_limit``) evicts by the shared
  utils/hbm.py ``stacked_forest_bytes`` estimate; one model alone over
  the cap still serves.
* **Buffer release** — eviction actually releases device buffers:
  the process live-buffer count drops once the stacked forest is
  dropped and collected.
* **Zero-recompile re-admission** — a predict after evict+checkout
  re-stacks (CompileWatch sees ZERO compile requests, the stack-build
  counter moves).
* **Hot-swap identity** — a ModelWatcher swap bumps the stack key and
  the entry is re-costed on its next checkout, not trusted stale.
"""
import gc

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu import obs
from lightgbm_tpu.serve import ModelRegistry
from lightgbm_tpu.utils.debug import CompileWatch

pytestmark = pytest.mark.filterwarnings("ignore")


@pytest.fixture(autouse=True)
def _isolated_obs():
    obs.disable()
    obs.reset()
    yield
    obs.disable()
    obs.reset()


PARAMS = {"objective": "binary", "num_leaves": 8, "verbosity": -1}


def _data(n=1500, f=6, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f))
    y = (X[:, 0] > 0).astype(np.float64)
    return X, y


def _boosters(k, rounds=3):
    X, y = _data()
    return X, [lgb.train(dict(PARAMS, seed=i),
                         lgb.Dataset(X, label=y),
                         num_boost_round=rounds) for i in range(k)]


def _registry(**over):
    p = {"tpu_serve_shard_trees": "false"}
    p.update(over)
    return ModelRegistry(p)


def test_lru_eviction_order():
    obs.enable(metrics=True)
    X, (a, b, c) = _boosters(3)
    reg = _registry(tpu_serve_cache_models=2)
    for mid, bst in (("a", a), ("b", b), ("c", c)):
        reg.register(mid, bst)
    reg.checkout("a")
    reg.checkout("b")
    assert sorted(reg.resident_ids()) == ["a", "b"]
    reg.checkout("c")                       # a is LRU -> evicted
    assert sorted(reg.resident_ids()) == ["b", "c"]
    reg.checkout("b")                       # refresh b's recency
    reg.checkout("a")                       # now c is LRU
    assert sorted(reg.resident_ids()) == ["a", "b"]
    assert obs.registry().get("serve.evictions").value == 2.0
    # hits only where the forest was already resident
    assert obs.registry().get("serve.cache_hits").value == 1.0
    assert obs.registry().get("serve.cache_models").value == 2.0


def test_byte_cap_explicit():
    X, (a, b) = _boosters(2)
    reg0 = _registry()
    reg0.register("a", a)
    reg0.checkout("a")
    est = reg0.resident_bytes()
    assert est > 0
    # cap fits ONE model, not two
    reg = _registry(tpu_serve_cache_bytes=int(est * 1.5),
                    tpu_serve_cache_models=8)
    reg.register("a", a)
    reg.register("b", b)
    reg.checkout("a")
    reg.checkout("b")
    assert reg.resident_ids() == ["b"]
    assert reg.resident_bytes() <= int(est * 1.5)


def test_byte_cap_auto_from_mocked_hbm_limit(monkeypatch):
    X, (a, b) = _boosters(2)
    probe = _registry()
    probe.register("a", a)
    probe.checkout("a")
    est = probe.resident_bytes()
    # auto cap = SERVE_HBM_FRACTION * limit; mock the limit so the
    # fraction admits exactly one model
    from lightgbm_tpu.serve import registry as reg_mod
    from lightgbm_tpu.utils.hbm import SERVE_HBM_FRACTION
    monkeypatch.setattr(reg_mod, "hbm_bytes_limit",
                        lambda: int(est * 1.5 / SERVE_HBM_FRACTION))
    reg = _registry(tpu_serve_cache_bytes=0)
    assert reg.max_bytes == pytest.approx(est * 1.5, rel=0.01)
    reg.register("a", a)
    reg.register("b", b)
    reg.checkout("a")
    reg.checkout("b")
    assert reg.resident_ids() == ["b"]


def test_single_model_over_cap_still_serves():
    X, (a,) = _boosters(1)
    reg = _registry(tpu_serve_cache_bytes=16)   # absurdly small
    reg.register("a", a)
    bst = reg.checkout("a")
    np.testing.assert_array_equal(bst.predict(X[:32]),
                                  a.predict(X[:32]))
    assert reg.resident_ids() == ["a"]


def test_eviction_releases_device_buffers():
    import jax
    X, (a,) = _boosters(1, rounds=4)
    reg = _registry()
    reg.register("a", a)
    reg.checkout("a").predict(X[:128])      # stack resident + warm
    gc.collect()
    n_before = len(jax.live_arrays())
    assert a.engine._stack_cache is not None
    reg.evict("a")
    gc.collect()
    n_after = len(jax.live_arrays())
    assert a.engine._stack_cache is None
    # the stacked forest is >= 7 arrays; require a real drop, with
    # slack for unrelated churn
    assert n_after <= n_before - 5, \
        f"live buffers {n_before} -> {n_after}: eviction leaked"


def test_readmission_recompiles_nothing():
    obs.enable(metrics=True)
    X, (a,) = _boosters(1)
    reg = _registry(tpu_serve_cache_models=1)
    reg.register("a", a)
    reg.checkout("a").predict(X[:128])
    reg.checkout("a").predict(X[:128])      # warm
    builds_before = a.engine._stack_builds
    reg.evict("a")
    with CompileWatch("readmit") as w:
        out = reg.checkout("a").predict(X[:128])
    w.assert_compiles(0)
    assert a.engine._stack_builds == builds_before + 1  # re-stack, yes
    np.testing.assert_array_equal(out, a.predict(X[:128]))


def test_swap_bumps_identity_and_recosts():
    X, (a,) = _boosters(1)
    reg = _registry()
    reg.register("a", a)
    reg.checkout("a")
    entry = reg._entries["a"]
    key0, bytes0 = entry.key, entry.bytes
    # a hot-swap path mutates the model list + version
    eng = a.engine
    eng.models = eng.models + eng.models          # pretend bigger model
    eng._invalidate_forest_cache()
    reg.checkout("a")
    assert entry.key != key0
    assert entry.bytes > bytes0


def test_swap_reruns_shard_policy(monkeypatch):
    """A hot-swap can grow a forest past the single-device auto
    threshold: admission must re-run ``auto_shard_mesh``, not trust
    the placement decided at register() time (hits must not)."""
    from lightgbm_tpu.serve import registry as reg_mod
    calls = []
    monkeypatch.setattr(reg_mod, "auto_shard_mesh",
                        lambda bst, cfg: calls.append(1))
    X, (a,) = _boosters(1)
    reg = _registry()
    reg.register("a", a)
    assert len(calls) == 1                  # register-time policy
    reg.checkout("a")
    assert len(calls) == 2                  # first admission
    reg.checkout("a")
    assert len(calls) == 2                  # cache hit: no re-eval
    eng = a.engine
    eng.models = eng.models + eng.models    # hot-swap grows the model
    eng._invalidate_forest_cache()
    reg.checkout("a")
    assert len(calls) == 3                  # version bump: re-eval
    reg.evict("a")
    reg.checkout("a")
    assert len(calls) == 4                  # re-admission: re-eval


def test_register_replacing_resident_releases_old():
    obs.enable(metrics=True)
    X, (a, b) = _boosters(2)
    reg = _registry()
    reg.register("m", a)
    reg.checkout("m").predict(X[:128])
    assert a.engine._stack_cache is not None
    reg.register("m", b)                    # tenant republished
    assert a.engine._stack_cache is None    # old device forest freed
    # a deploy refresh is NOT cache pressure: no eviction counted
    ev = obs.registry().get("serve.evictions")
    assert ev is None or ev.value == 0.0
    np.testing.assert_array_equal(reg.checkout("m").predict(X[:32]),
                                  b.predict(X[:32]))


def test_register_refresh_lands_most_recent():
    """Re-registering a model must not leave it at its OLD LRU slot
    where the next admission would evict the fresh deploy first."""
    X, (a, b, c) = _boosters(3)
    reg = _registry(tpu_serve_cache_models=2)
    reg.register("a", a)
    reg.register("b", b)
    reg.checkout("a")
    reg.checkout("b")
    reg.register("a", c)                    # refresh tenant a
    reg.checkout("a")                       # re-admit the refresh
    reg.register("c", c)
    reg.checkout("c")                       # b is LRU now, not a
    assert sorted(reg.resident_ids()) == ["a", "c"]
