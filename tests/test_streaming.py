"""Out-of-core streaming engine (boosting/streaming.py, VERDICT r4
item 3): host-resident bins, level-wise streamed growth.

The reference trains any dataset that fits host RAM
(``dataset_loader.cpp`` two-round + row-wise bin storage, SURVEY §2.1,
UNVERIFIED); the streaming engine is this framework's equivalent for
data whose binned matrix exceeds HBM.
"""
import numpy as np
import pytest

import lightgbm_tpu as lgb


def _data(n=20_000, f=10, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f))
    y = (X[:, 0] + 0.5 * X[:, 1] * X[:, 2]
         + rng.normal(scale=0.3, size=n) > 0).astype(np.float64)
    return X, y


BASE = {"objective": "binary", "num_leaves": 16, "max_depth": 4,
        "verbosity": -1, "min_data_in_leaf": 20}


def test_streaming_block_count_invariant():
    """Training must be BIT-identical no matter how the rows are cut
    into streamed blocks — the accumulated histograms are exact sums."""
    X, y = _data()
    texts = []
    for blk in (30_000, 2_048):
        bst = lgb.train(dict(BASE, tpu_streaming="true",
                             tpu_stream_block_rows=blk),
                        lgb.Dataset(X, label=y), num_boost_round=8)
        texts.append(bst.model_to_string())
    assert texts[0] == texts[1]


def test_streaming_close_to_resident():
    """At a complete depth (num_leaves = 2^max_depth) level-wise and
    best-first growth choose from the same split sets; models may
    differ on float near-ties but quality must match the resident
    engine."""
    X, y = _data(seed=3)
    accs = {}
    for mode in ("true", "false"):
        bst = lgb.train(dict(BASE, tpu_streaming=mode),
                        lgb.Dataset(X, label=y), num_boost_round=10)
        pred = bst.predict(X)
        accs[mode] = np.mean((pred > 0.5) == y)
        assert np.isfinite(pred).all()
    assert abs(accs["true"] - accs["false"]) < 0.01


def test_streaming_model_roundtrip_and_missing(tmp_path):
    """NaN routing (default_left) + v4 text round-trip from the
    streaming engine."""
    X, y = _data(seed=5)
    X[::7, 0] = np.nan          # informative missingness on the main
    y[::7] = 1.0                 # feature
    bst = lgb.train(dict(BASE, tpu_streaming="true"),
                    lgb.Dataset(X, label=y), num_boost_round=6)
    p = bst.predict(X)
    mf = tmp_path / "m.txt"
    bst.save_model(str(mf))
    p2 = lgb.Booster(model_file=str(mf)).predict(X)
    np.testing.assert_allclose(p, p2, rtol=1e-6, atol=1e-9)
    assert np.mean((p > 0.5) == y) > 0.8


def test_streaming_regression_weighted():
    rng = np.random.default_rng(9)
    X = rng.normal(size=(8_000, 6))
    y = X[:, 0] * 2 + X[:, 1] ** 2 + rng.normal(scale=0.1, size=8_000)
    w = rng.uniform(0.5, 2.0, size=8_000)
    bst = lgb.train(dict(BASE, objective="regression",
                         tpu_streaming="true",
                         tpu_stream_block_rows=2_048),
                    lgb.Dataset(X, label=y, weight=w),
                    num_boost_round=20)
    mse = float(np.mean((bst.predict(X) - y) ** 2))
    assert mse < np.var(y) * 0.3


def test_streaming_feature_fraction_and_l1():
    X, y = _data(seed=11)
    bst = lgb.train(dict(BASE, tpu_streaming="true",
                         feature_fraction=0.6, lambda_l1=0.5,
                         lambda_l2=2.0),
                    lgb.Dataset(X, label=y), num_boost_round=10)
    assert np.mean((bst.predict(X) > 0.5) == y) > 0.8


def test_streaming_rejects_unsupported():
    # GOSS / bagging / quantized gradients are streaming-supported now
    # (PR 7, the sharded streamed path); the structured-constraint
    # features and non-row-sharding learners still gate out
    X, y = _data(n=2_000)
    from lightgbm_tpu.utils.log import LightGBMError
    for extra in ({"num_class": 3, "objective": "multiclass"},
                  {"linear_tree": True},
                  {"boosting": "dart"},
                  {"tree_learner": "voting"},
                  {"monotone_constraints": [1] * 10}):
        with pytest.raises(LightGBMError):
            lgb.train(dict(BASE, tpu_streaming="true", **extra),
                      lgb.Dataset(X, label=y.astype(float)),
                      num_boost_round=2)


def test_streaming_sklearn_surface():
    """The sklearn wrapper composes with the streaming engine (predict
    goes through the host model path)."""
    X, y = _data(seed=13)
    clf = lgb.LGBMClassifier(n_estimators=8, num_leaves=16, max_depth=4,
                             verbosity=-1, tpu_streaming="true")
    clf.fit(X, y)
    assert (clf.predict(X) == y).mean() > 0.8


def test_streaming_valid_eval_and_early_stopping():
    """Valid-set metrics + early-stopping callbacks compose with the
    streaming engine (valid sets evaluate via the host model over raw
    features; training metric reads the device-resident score)."""
    X, y = _data(n=30_000, seed=21)
    ds = lgb.Dataset(X[:24_000], label=y[:24_000])
    vs = ds.create_valid(X[24_000:], label=y[24_000:])
    evals = {}
    bst = lgb.train(dict(BASE, metric="auc", tpu_streaming="true",
                         is_provide_training_metric=True),
                    ds, num_boost_round=10,
                    valid_sets=[vs], valid_names=["val"],
                    callbacks=[lgb.record_evaluation(evals),
                               lgb.early_stopping(5, verbose=False)])
    aucs = evals["val"]["auc"]
    assert len(aucs) == 10 and aucs[-1] > aucs[0] > 0.5
    assert "training" in evals           # device-score train metric
    assert bst.best_iteration >= 1


def test_streaming_compatible_never_routes_fatal_configs():
    """_streaming_compatible must be a SUBSET of what StreamingGBDT
    accepts: auto-routing a config into its _no() fatals would turn a
    train() that the resident engine handles into a crash (ADVICE r5:
    use_quantized_grad and bare cegb_tradeoff were missing gates;
    PR 7 lifted the quantization gate — explicit use_quantized_grad is
    now streaming-compatible and must construct, not fatal)."""
    from lightgbm_tpu.boosting import _streaming_compatible
    from lightgbm_tpu.config import Config
    cfg = Config(dict(BASE, cegb_tradeoff=2.0))
    assert not _streaming_compatible(cfg)
    assert _streaming_compatible(Config(dict(BASE,
                                             use_quantized_grad=True)))
    # the resident engine still trains the incompatible config fine,
    # and the now-compatible one trains on the STREAMING engine
    X, y = _data(n=2_000)
    lgb.train(dict(BASE, cegb_tradeoff=2.0), lgb.Dataset(X, label=y),
              num_boost_round=2)
    lgb.train(dict(BASE, use_quantized_grad=True, tpu_streaming="true"),
              lgb.Dataset(X, label=y), num_boost_round=2)


def test_streaming_extra_trees_binds():
    """extra_trees must actually randomize streamed thresholds (it
    used to silently fall back to plain GBDT: find_best_split skips
    the filter when extra_u is None — ADVICE r5)."""
    X, y = _data(n=8_000, seed=5)
    def train(extra_trees, seed=1):
        return lgb.train(dict(BASE, tpu_streaming="true",
                              extra_trees=extra_trees, seed=seed),
                         lgb.Dataset(X, label=y),
                         num_boost_round=4).model_to_string()
    plain = train(False)
    extra = train(True)
    # one random threshold per (node, feature) must change the trees
    assert extra != plain
    # and a different seed draws different thresholds
    assert train(True, seed=2) != extra
    # while the same seed reproduces exactly
    assert train(True, seed=2) == train(True, seed=2)


def test_streaming_sparse_valid_rejected():
    """scipy-sparse raw valid features fail early with the standard
    unsupported message instead of crashing mid-eval on len(sparse)
    (ADVICE r5)."""
    pytest.importorskip("scipy")
    import scipy.sparse as sp
    from lightgbm_tpu.utils.log import LightGBMError
    X, y = _data(n=4_000)
    ds = lgb.Dataset(X[:3_000], label=y[:3_000])
    vs = lgb.Dataset(sp.csr_matrix(X[3_000:]), label=y[3_000:],
                     reference=ds)
    with pytest.raises(LightGBMError, match="sparse"):
        lgb.train(dict(BASE, tpu_streaming="true"), ds,
                  num_boost_round=2, valid_sets=[vs],
                  valid_names=["val"])
