"""Pre-snapshot smoke gate (VERDICT r4 item 1).

Round 4 shipped a one-line NameError in ``GBDT.predict`` that failed
111/249 tests and blanked the round's benchmark because no end-to-end
train+predict ran before the snapshot. This file is the cheap gate:
train + predict on dense AND scipy-sparse input in-session, model
round-trip through the v4 text format, and sklearn predict — the four
surfaces that NameError took down. It runs in seconds; ``make check``
(scripts/check.sh) runs it before every snapshot.

Reference behavior being pinned: ``Booster.predict`` over dense/CSR
inputs (upstream ``python-package/lightgbm/basic.py`` predict paths,
SURVEY.md §3.5).
"""
import numpy as np
import pytest

import lightgbm_tpu as lgb


def _toy(n=400, f=12, seed=3):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f))
    y = (X[:, 0] + 0.5 * X[:, 1] ** 2 + rng.normal(scale=0.1, size=n)
         > 0.3).astype(np.float64)
    return X, y


def test_train_predict_dense_and_sparse_in_session(tmp_path):
    scipy_sparse = pytest.importorskip("scipy.sparse")
    X, y = _toy()
    ds = lgb.Dataset(X, label=y)
    bst = lgb.train({"objective": "binary", "num_leaves": 7,
                     "verbosity": -1}, ds, num_boost_round=5)

    p_dense = bst.predict(X)
    assert p_dense.shape == (X.shape[0],)
    assert np.all((p_dense >= 0) & (p_dense <= 1))

    Xs = scipy_sparse.csr_matrix(X)
    p_sparse = bst.predict(Xs)
    np.testing.assert_allclose(p_sparse, p_dense, rtol=1e-6)

    # raw_score + pred_leaf surfaces (both crashed at r4 HEAD)
    raw = bst.predict(X, raw_score=True)
    assert raw.shape == (X.shape[0],)
    leaves = bst.predict(X, pred_leaf=True)
    assert leaves.shape[0] == X.shape[0] and leaves.dtype == np.int32

    # model round-trip: text-format predict must match in-session
    mf = tmp_path / "model.txt"
    bst.save_model(str(mf))
    bst2 = lgb.Booster(model_file=str(mf))
    np.testing.assert_allclose(bst2.predict(X), p_dense, rtol=1e-5,
                               atol=1e-7)


def test_sklearn_predict_in_session():
    X, y = _toy(seed=5)
    clf = lgb.LGBMClassifier(n_estimators=5, num_leaves=7, verbosity=-1)
    clf.fit(X, y)
    proba = clf.predict_proba(X)
    assert proba.shape == (X.shape[0], 2)
    acc = (clf.predict(X) == y).mean()
    assert acc > 0.7
