"""Fault-tolerant training: durable checkpoint/resume (recovery
subsystem; docs/robustness.md).

The contract under test is STRONGER than init_model continuation: a
checkpoint persists the complete training state — model text, RNG
streams (bagging / feature fraction / DART drop), the exact score
arrays, early-stopping best-score state — so an
interrupted-then-resumed run reproduces the uninterrupted run's model
BIT-EXACTLY (``model_to_string`` equality, not allclose).
"""
import os
import pickle

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.recovery.checkpoint import (CheckpointError,
                                              CheckpointManager)
from lightgbm_tpu.recovery.faults import parse_fault_spec
from lightgbm_tpu.recovery.restart import (backoff_seconds,
                                           has_resumable_checkpoint,
                                           is_bind_failure)


def _binary_data(n=2500, f=10, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f))
    w = rng.normal(size=f)
    y = (X @ w + rng.normal(scale=0.5, size=n) > 0).astype(np.float64)
    return X, y


# bagging + GOSS + feature sampling + early stopping all enabled so the
# RNG/best-score state the checkpoint must carry is actually exercised
# (GOSS activates at iteration 1/learning_rate = 10, so bagging governs
# iterations 0-9 and GOSS the rest)
FULL_PARAMS = {
    "objective": "binary", "num_leaves": 15, "verbosity": -1,
    "learning_rate": 0.1, "data_sample_strategy": "goss",
    "top_rate": 0.3, "other_rate": 0.2,
    "bagging_freq": 2, "bagging_fraction": 0.8,
    "feature_fraction": 0.8, "metric": "auc",
    "early_stopping_round": 25,
}


def _train_val():
    X, y = _binary_data()
    ds = lgb.Dataset(X[:2000], label=y[:2000])
    vs = ds.create_valid(X[2000:], label=y[2000:])
    return ds, vs


# ---------------------------------------------------------------------------
# CheckpointManager: atomicity, checksum, retention, latest pointer
# ---------------------------------------------------------------------------
def test_checkpoint_roundtrip_and_latest_pointer(tmp_path):
    mgr = CheckpointManager(tmp_path, keep_n=3, rank=0)
    for it in (5, 10, 15):
        mgr.save({"version": 1, "payload": it * 11}, it)
    assert mgr.iterations() == [5, 10, 15]
    with open(mgr.latest_pointer) as f:
        assert f.read().strip() == mgr.filename(15)
    st = mgr.load()
    assert st["payload"] == 165 and st["iteration"] == 15
    assert mgr.load(iteration=5)["payload"] == 55


def test_checkpoint_keep_n_prunes_oldest(tmp_path):
    mgr = CheckpointManager(tmp_path, keep_n=2, rank=0)
    for it in range(1, 6):
        mgr.save({"version": 1, "n": it}, it)
    assert mgr.iterations() == [4, 5]
    assert mgr.load()["n"] == 5


def test_prune_removes_stale_higher_iterations(tmp_path):
    """A reused directory with a previous run's higher-iteration
    checkpoints: the first save of the new run must evict them (they
    would otherwise win every resume) and must never prune itself."""
    mgr = CheckpointManager(tmp_path, keep_n=3, rank=0)
    for it in (12, 16, 20):
        mgr.save({"version": 1, "n": it}, it)
    mgr.save({"version": 1, "n": 4}, 4)
    assert mgr.iterations() == [4]
    assert mgr.load()["n"] == 4


def test_fresh_train_run_clears_stale_checkpoint_dir(tmp_path):
    """A fresh (non-resume) train() into a dir holding another run's
    checkpoints must clear them — a later restart would otherwise
    silently continue the OLD run's state."""
    X, y = _binary_data(n=1000, seed=9)
    params = {"objective": "binary", "num_leaves": 7, "verbosity": -1,
              "checkpoint_dir": str(tmp_path), "checkpoint_interval": 2}
    lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=6)
    mgr = CheckpointManager(tmp_path, rank=0)
    assert mgr.latest_valid_iteration() == 6
    lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=2)
    assert mgr.iterations() == [2]          # 4/6 from the old run gone


def test_truncated_checkpoint_rejected_and_falls_back(tmp_path):
    """Acceptance: a checkpoint truncated mid-write is rejected by
    checksum and resume falls back to the previous valid one."""
    mgr = CheckpointManager(tmp_path, keep_n=5, rank=0)
    mgr.save({"version": 1, "n": 10}, 10)
    p20 = mgr.save({"version": 1, "n": 20}, 20)
    blob = open(p20, "rb").read()
    with open(p20, "wb") as f:          # simulate a torn write
        f.write(blob[:len(blob) // 2])
    with pytest.raises(CheckpointError, match="truncated|checksum"):
        mgr.load_file(p20)
    st = mgr.load()                     # pointer names 20 -> falls back
    assert st["n"] == 10
    assert mgr.latest_valid_iteration() == 10
    # corrupted-in-place (same length, flipped bytes) fails the sha256
    corrupt = blob[:-8] + bytes(8)
    with open(p20, "wb") as f:
        f.write(corrupt)
    with pytest.raises(CheckpointError, match="checksum"):
        mgr.load_file(p20)


def test_multi_corruption_walks_back_to_oldest_valid_and_gang_agrees(
        tmp_path):
    """Satellite: corrupt the newest TWO checkpoints AND the latest
    pointer — load() must walk back to the oldest valid file, and a
    gang whose ranks saw different damage must still agree (min over
    per-rank newest-valid iterations, the load_for_resume rule)."""
    mgr0 = CheckpointManager(tmp_path, keep_n=5, rank=0)
    mgr1 = CheckpointManager(tmp_path, keep_n=5, rank=1)
    for it in (10, 20, 30):
        mgr0.save({"version": 1, "n": it}, it)
        mgr1.save({"version": 1, "n": it + 1}, it)

    def corrupt(path):
        blob = open(path, "rb").read()
        with open(path, "wb") as f:
            f.write(blob[:-16] + bytes(16))

    corrupt(mgr0.path(30))
    corrupt(mgr0.path(20))
    with open(mgr0.latest_pointer, "w") as f:
        f.write("ckpt_99999999.rank0.ckpt\n")
    # rank 0: pointer garbage, 30 and 20 corrupt -> walks back to 10
    st = mgr0.load()
    assert st["n"] == 10
    assert mgr0.latest_valid_iteration() == 10
    # rank 1 is undamaged, but the gang rule is min over ranks: both
    # ranks must resume from 10, and BOTH can load that exact iteration
    target = min(m.latest_valid_iteration() for m in (mgr0, mgr1))
    assert target == 10
    assert mgr0.load(iteration=target)["n"] == 10
    assert mgr1.load(iteration=target)["n"] == 11


def test_checkpoint_bad_magic_and_version(tmp_path):
    mgr = CheckpointManager(tmp_path, rank=0)
    p = tmp_path / "ckpt_00000001.rank0.ckpt"
    p.write_bytes(b"not a checkpoint at all")
    with pytest.raises(CheckpointError, match="magic"):
        mgr.load_file(str(p))
    mgr.save({"version": 99}, 2)
    with pytest.raises(CheckpointError, match="version"):
        mgr.load_file(mgr.path(2))


def test_checkpoint_write_leaves_no_temp_litter(tmp_path):
    mgr = CheckpointManager(tmp_path, rank=0)
    mgr.save({"version": 1, "big": np.zeros(4096)}, 1)
    assert not [n for n in os.listdir(tmp_path) if n.startswith(".tmp.")]


# ---------------------------------------------------------------------------
# fault-injection spec parsing + fire-once markers
# ---------------------------------------------------------------------------
def test_fault_spec_parse_and_validation():
    plan = parse_fault_spec("kill:rank=1,iter=10")
    assert (plan.kind, plan.rank, plan.iteration) == ("kill", 1, 10)
    plan = parse_fault_spec("exn:iter=5")
    assert (plan.kind, plan.rank, plan.iteration) == ("exn", None, 5)
    for bad in ("boom:iter=1", "exn:rank=1", "exn:iter=x", "exn:foo=1"):
        with pytest.raises(lgb.LightGBMError):
            parse_fault_spec(bad)


def test_fault_exn_fires_once_with_marker(tmp_path):
    plan = parse_fault_spec("exn:iter=3", marker_dir=str(tmp_path))
    plan.maybe_fire(2)                      # wrong iteration: no-op
    with pytest.raises(lgb.LightGBMError, match="injected failure"):
        plan.maybe_fire(3)
    plan.maybe_fire(3)                      # marker written: skipped
    # without a marker dir the fault fires on every matching pass
    plan2 = parse_fault_spec("exn:iter=3")
    for _ in range(2):
        with pytest.raises(lgb.LightGBMError):
            plan2.maybe_fire(3)


# ---------------------------------------------------------------------------
# restart policy helpers
# ---------------------------------------------------------------------------
def test_restart_policy_helpers(tmp_path):
    assert backoff_seconds(1, base=0.5) == 0.5
    assert backoff_seconds(3, base=0.5) == 2.0
    assert backoff_seconds(30, base=1.0) == 30.0          # capped
    assert is_bind_failure("RuntimeError: Failed to bind any address")
    assert is_bind_failure("bind: Address already in use (errno 98)")
    assert not is_bind_failure("rank 2: ValueError: shapes mismatch")
    assert not has_resumable_checkpoint(tmp_path)          # empty dir
    CheckpointManager(tmp_path, rank=0).save({"version": 1}, 4)
    assert has_resumable_checkpoint(tmp_path)
    assert not has_resumable_checkpoint(tmp_path / "missing")


# ---------------------------------------------------------------------------
# the tentpole acceptance test: interrupted-then-resumed == uninterrupted,
# bit-exact, with bagging + GOSS + early stopping in play
# ---------------------------------------------------------------------------
def test_bit_exact_resume_after_midtraining_kill(tmp_path):
    d_straight = str(tmp_path / "straight")
    d_faulted = str(tmp_path / "faulted")

    ds, vs = _train_val()
    params = dict(FULL_PARAMS, checkpoint_dir=d_straight,
                  checkpoint_interval=10)
    straight = lgb.train(params, ds, num_boost_round=30, valid_sets=[vs])
    m_straight = straight.model_to_string()
    assert straight.num_trees() == 30

    # interrupted run: injected failure before iteration 17 (checkpoint
    # at 10 exists, 20 does not)
    ds, vs = _train_val()
    params = dict(FULL_PARAMS, checkpoint_dir=d_faulted,
                  checkpoint_interval=10, tpu_fault_inject="exn:iter=17")
    with pytest.raises(lgb.LightGBMError, match="injected failure"):
        lgb.train(params, ds, num_boost_round=30, valid_sets=[vs])
    assert CheckpointManager(d_faulted, rank=0).latest_valid_iteration() \
        == 10

    # resume with the SAME params (the fire-once marker in the
    # checkpoint dir keeps the fault from replaying) — the total-round
    # target semantics run iterations 10..29
    ds, vs = _train_val()
    resumed = lgb.train(params, ds, num_boost_round=30, valid_sets=[vs],
                        resume_from=d_faulted)
    assert resumed.num_trees() == 30
    assert resumed.model_to_string() == m_straight
    assert resumed.best_iteration == straight.best_iteration
    assert resumed.best_score == straight.best_score


def test_resume_falls_back_past_corrupt_newest_checkpoint(tmp_path):
    """Kill after the 20-checkpoint, corrupt it, and resume: the loader
    must fall back to 10 and still reproduce the straight run exactly."""
    d1, d2 = str(tmp_path / "a"), str(tmp_path / "b")
    ds, vs = _train_val()
    straight = lgb.train(dict(FULL_PARAMS, checkpoint_dir=d1,
                              checkpoint_interval=10),
                         ds, num_boost_round=30, valid_sets=[vs])
    ds, vs = _train_val()
    params = dict(FULL_PARAMS, checkpoint_dir=d2, checkpoint_interval=10,
                  tpu_fault_inject="exn:iter=25")
    with pytest.raises(lgb.LightGBMError):
        lgb.train(params, ds, num_boost_round=30, valid_sets=[vs])
    mgr = CheckpointManager(d2, rank=0)
    assert mgr.latest_valid_iteration() == 20
    blob = open(mgr.path(20), "rb").read()
    with open(mgr.path(20), "wb") as f:
        f.write(blob[:len(blob) - 64])       # torn tail
    ds, vs = _train_val()
    resumed = lgb.train(params, ds, num_boost_round=30, valid_sets=[vs],
                        resume_from=d2)
    assert resumed.model_to_string() == straight.model_to_string()


def test_resume_from_empty_dir_starts_fresh(tmp_path):
    ds, _ = _train_val()
    params = {"objective": "binary", "num_leaves": 7, "verbosity": -1}
    bst = lgb.train(params, ds, num_boost_round=3,
                    resume_from=str(tmp_path))
    assert bst.num_trees() == 3


def test_resume_from_mistyped_checkpoint_file_raises(tmp_path):
    """A nonexistent path that LOOKS like a checkpoint file is a typo,
    not a fresh start — silently retraining would discard the run the
    user asked to continue."""
    ds, _ = _train_val()
    params = {"objective": "binary", "num_leaves": 7, "verbosity": -1}
    with pytest.raises(CheckpointError, match="does not exist"):
        lgb.train(params, ds, num_boost_round=3,
                  resume_from=str(tmp_path / "ckpt_00000010.rank0.ckpt"))
    assert not (tmp_path / "ckpt_00000010.rank0.ckpt").exists()


def test_resume_with_changed_metric_layout_degrades_gracefully(tmp_path):
    """Resuming with a different metric list must not crash the
    restored early-stopping state (best-effort reinit, like the score
    rebuild fallback)."""
    ds, vs = _train_val()
    params = dict(FULL_PARAMS, checkpoint_dir=str(tmp_path),
                  checkpoint_interval=5, tpu_fault_inject="exn:iter=8")
    with pytest.raises(lgb.LightGBMError):
        lgb.train(params, ds, num_boost_round=12, valid_sets=[vs])
    ds, vs = _train_val()
    changed = dict(params, metric=["auc", "binary_logloss"])
    bst = lgb.train(changed, ds, num_boost_round=12, valid_sets=[vs],
                    resume_from=str(tmp_path))
    assert bst.num_trees() == 12


def test_dart_resume_bit_exact(tmp_path):
    """DART drop-RNG + per-iteration weights survive the checkpoint (the
    lossy lr-seeding of plain init_model continuation would diverge)."""
    d1, d2 = str(tmp_path / "a"), str(tmp_path / "b")
    base = {"objective": "binary", "boosting": "dart", "num_leaves": 15,
            "verbosity": -1, "drop_rate": 0.5, "skip_drop": 0.2,
            "checkpoint_interval": 5}
    X, y = _binary_data(n=1500, seed=3)
    straight = lgb.train(dict(base, checkpoint_dir=d1),
                         lgb.Dataset(X, label=y), num_boost_round=14)
    params = dict(base, checkpoint_dir=d2, tpu_fault_inject="exn:iter=8")
    with pytest.raises(lgb.LightGBMError):
        lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=14)
    resumed = lgb.train(params, lgb.Dataset(X, label=y),
                        num_boost_round=14, resume_from=d2)
    assert resumed.model_to_string() == straight.model_to_string()


def test_rf_resume_bit_exact(tmp_path):
    """RF keeps running prediction-sum accumulators next to the bagging
    RNG; both must survive the checkpoint."""
    d1, d2 = str(tmp_path / "a"), str(tmp_path / "b")
    base = {"objective": "binary", "boosting": "rf", "num_leaves": 15,
            "bagging_freq": 1, "bagging_fraction": 0.7, "verbosity": -1,
            "checkpoint_interval": 4}
    X, y = _binary_data(n=1500, seed=4)
    straight = lgb.train(dict(base, checkpoint_dir=d1),
                         lgb.Dataset(X, label=y), num_boost_round=10)
    params = dict(base, checkpoint_dir=d2, tpu_fault_inject="exn:iter=6")
    with pytest.raises(lgb.LightGBMError):
        lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=10)
    resumed = lgb.train(params, lgb.Dataset(X, label=y),
                        num_boost_round=10, resume_from=d2)
    assert resumed.model_to_string() == straight.model_to_string()


def test_early_stopping_metric_freq_gap_not_mistaken_for_mismatch():
    """Non-eval iterations (metric_freq > 1) produce empty evaluation
    lists; the checkpoint-layout-mismatch reinit must not fire on them
    (it would clear best-score tracking every other iteration)."""
    from lightgbm_tpu.utils import log as _log
    lines = []
    _log.register_callback(lines.append)
    try:
        ds, vs = _train_val()
        bst = lgb.train({"objective": "binary", "num_leaves": 7,
                         "verbosity": 1, "metric": "auc",
                         "metric_freq": 2, "early_stopping_round": 3},
                        ds, num_boost_round=8, valid_sets=[vs])
    finally:
        _log.register_callback(None)
    assert bst.num_trees() >= 1
    assert not [ln for ln in lines if "does not match" in ln], lines


def test_resume_rejects_engine_type_mismatch(tmp_path):
    """A DART checkpoint resumed with boosting=gbdt must fatal (the
    DART drop state would be silently dropped otherwise)."""
    X, y = _binary_data(n=1500, seed=5)
    params = {"objective": "binary", "boosting": "dart", "num_leaves": 7,
              "verbosity": -1, "checkpoint_dir": str(tmp_path),
              "checkpoint_interval": 2}
    lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=4)
    wrong = dict(params)
    del wrong["boosting"]
    with pytest.raises(lgb.LightGBMError, match="DART engine"):
        lgb.train(wrong, lgb.Dataset(X, label=y), num_boost_round=6,
                  resume_from=str(tmp_path))


def test_checkpoint_state_is_picklable_and_complete(tmp_path):
    """The saved engine state names every piece the resume contract
    advertises (guards against silently dropping a field later)."""
    ds, vs = _train_val()
    params = dict(FULL_PARAMS, checkpoint_dir=str(tmp_path),
                  checkpoint_interval=5)
    # NB: 10 rounds, not 5 — the early-stopping callback raises its
    # "did not meet" EarlyStopException ON the final iteration before
    # the (later-ordered) checkpoint callback runs, so the final
    # iteration of a completed run is deliberately not checkpointed
    lgb.train(params, ds, num_boost_round=10, valid_sets=[vs])
    st = CheckpointManager(str(tmp_path), rank=0).load()
    assert st["iteration"] == 5
    assert "Tree=0" in st["model_str"]
    eng = st["engine"]
    for key in ("iteration", "init_scores", "rng_feature", "rng_bagging",
                "score", "valid_scores", "bag_mask"):
        assert key in eng, key
    assert eng["score"].dtype == np.float32
    assert len(eng["valid_scores"]) == 1
    assert "early_stopping" in st["callbacks"]
    pickle.dumps(st)                         # full payload round-trips


# ---------------------------------------------------------------------------
# init_multihost: transient-connect retries + broad error wrapping
# ---------------------------------------------------------------------------
def test_init_multihost_retries_transient_connect(monkeypatch):
    import jax

    from lightgbm_tpu.parallel.multihost import init_multihost
    calls = {"n": 0}

    def flaky_initialize(**kwargs):
        calls["n"] += 1
        if calls["n"] < 3:
            raise ConnectionError("connection refused: coordinator "
                                  "not up yet")

    monkeypatch.setattr(jax.distributed, "initialize", flaky_initialize)
    init_multihost("localhost:1", 1, 0, connect_retries=3,
                   retry_backoff=0.01)
    assert calls["n"] == 3


def test_init_multihost_wraps_timeout_errors(monkeypatch):
    import jax

    from lightgbm_tpu.parallel.multihost import init_multihost

    def timeout_initialize(**kwargs):
        raise TimeoutError("deadline exceeded waiting for coordinator")

    monkeypatch.setattr(jax.distributed, "initialize", timeout_initialize)
    with pytest.raises(lgb.LightGBMError, match="initialize failed"):
        init_multihost("localhost:1", 1, 0, connect_retries=1,
                       retry_backoff=0.01)


def test_init_multihost_no_retry_on_non_transient(monkeypatch):
    import jax

    from lightgbm_tpu.parallel.multihost import init_multihost
    calls = {"n": 0}

    def misuse_initialize(**kwargs):
        calls["n"] += 1
        raise RuntimeError("jax.distributed.initialize was already "
                           "called")

    monkeypatch.setattr(jax.distributed, "initialize", misuse_initialize)
    with pytest.raises(lgb.LightGBMError):
        init_multihost("localhost:1", 1, 0, connect_retries=3,
                       retry_backoff=0.01)
    assert calls["n"] == 1
