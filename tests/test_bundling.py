"""Exclusive Feature Bundling (EFB) tests (reference:
dataset_loader.cpp FindGroups/FastFeatureBundling semantics)."""
import numpy as np

import lightgbm_tpu as lgb
from lightgbm_tpu.io.bundling import (apply_bundles, build_expand_maps,
                                      find_bundles, plan_bundles)


def _onehot_data(n=3000, groups=3, cards=(8, 6, 4), seed=0):
    """One-hot blocks: within a block exactly one column is 1 per row —
    perfectly exclusive, the EFB sweet spot (Criteo shape)."""
    rng = np.random.default_rng(seed)
    cols = []
    ids = []
    for c in cards:
        g = rng.integers(0, c, size=n)
        ids.append(g)
        block = np.zeros((n, c))
        block[np.arange(n), g] = 1.0
        cols.append(block)
    dense = rng.normal(size=(n, 2))
    X = np.column_stack(cols + [dense])
    w = [rng.normal(size=c) for c in cards]
    logit = sum(w[i][ids[i]] for i in range(len(cards))) + dense[:, 0]
    y = (logit + rng.normal(scale=0.5, size=n) > 0).astype(float)
    return X, y


def test_find_bundles_onehot_exclusive():
    X, y = _onehot_data()
    binned = (X[:, :18] != 0).astype(np.uint8)   # one-hot cols as bins
    num_bins = np.full(18, 2)
    eligible = np.ones(18, dtype=bool)
    bundles = find_bundles(binned, num_bins, eligible, np.zeros(18, int))
    # the three one-hot blocks are mutually exclusive within themselves:
    # everything packs into few bundles with zero conflicts
    assert len(bundles) >= 1
    bundled_feats = {f for b in bundles for f in b}
    assert len(bundled_feats) >= 12


def test_bundle_roundtrip_exact():
    rng = np.random.default_rng(1)
    n, F = 500, 6
    binned = np.zeros((n, F), dtype=np.uint8)
    # exclusive pattern: each row has at most one non-zero among 0..3
    which = rng.integers(0, 5, size=n)          # 4 == none
    for f in range(4):
        rows = which == f
        binned[rows, f] = rng.integers(1, 4, size=int(rows.sum()))
    binned[:, 4] = rng.integers(0, 4, size=n)   # dense, not bundled
    binned[:, 5] = rng.integers(0, 4, size=n)
    num_bins = np.full(F, 4)
    defaults = np.zeros(F, dtype=int)
    bundles = find_bundles(binned, num_bins, np.ones(F, bool), defaults)
    assert bundles and set(bundles[0]) <= {0, 1, 2, 3}
    plan = plan_bundles(num_bins, defaults, bundles)
    phys = apply_bundles(binned, plan)
    assert phys.shape[1] == plan.n_phys < F
    # recover every logical bin from the physical matrix
    for f in range(F):
        col = phys[:, plan.phys_col[f]].astype(int)
        if plan.bundled[f]:
            d = plan.default_bin[f]
            idx = col - plan.start[f]
            in_r = (idx >= 0) & (idx <= num_bins[f] - 2)
            rec = np.where(in_r, idx + (idx >= d), d)
        else:
            rec = col
        np.testing.assert_array_equal(rec, binned[:, f])


def test_bundled_training_equals_unbundled():
    """The oracle: with zero conflicts EFB must produce the same model
    as unbundled training. Precise (f32) histograms isolate the bundling
    semantics from the default bf16 accumulation, whose error the two
    layouts distribute differently."""
    X, y = _onehot_data()
    preds = {}
    for enable in (True, False):
        bst = lgb.train(
            {"objective": "binary", "num_leaves": 15, "verbosity": -1,
             "enable_bundle": enable, "min_data_in_leaf": 5,
             "tpu_double_precision_hist": True},
            lgb.Dataset(X, label=y), num_boost_round=10)
        if enable:
            assert bst.engine.has_bundles, "EFB should trigger here"
            assert bst.engine.bundle_plan.n_phys < 20
        preds[enable] = bst.predict(X, raw_score=True)
    np.testing.assert_allclose(preds[True], preds[False],
                               rtol=1e-3, atol=1e-3)


def test_bundled_model_text_and_holdout():
    X, y = _onehot_data(seed=3)
    bst = lgb.train(
        {"objective": "binary", "num_leaves": 15, "verbosity": -1,
         "min_data_in_leaf": 5},
        lgb.Dataset(X[:2400], label=y[:2400]), num_boost_round=10)
    s = bst.model_to_string()
    p1 = bst.predict(X[2400:])
    p2 = lgb.Booster(model_str=s).predict(X[2400:])
    np.testing.assert_allclose(p1, p2, rtol=1e-5, atol=1e-6)


def test_bundling_with_valid_and_data_parallel():
    X, y = _onehot_data(seed=4)
    ds = lgb.Dataset(X[:2400], label=y[:2400])
    vs = ds.create_valid(X[2400:], label=y[2400:])
    res = {}
    bst = lgb.train(
        {"objective": "binary", "num_leaves": 15, "verbosity": -1,
         "tree_learner": "data", "metric": "auc", "min_data_in_leaf": 5},
        ds, num_boost_round=15, valid_sets=[vs],
        callbacks=[lgb.record_evaluation(res)])
    assert bst.engine.has_bundles
    assert res["valid_0"]["auc"][-1] > 0.85


def test_rollback_with_bundles():
    """Score rebuild must use the LOGICAL matrix, not the bundled one."""
    X, y = _onehot_data(seed=5, n=1200)
    ds = lgb.Dataset(X, label=y)
    bst = lgb.train({"objective": "binary", "num_leaves": 15,
                     "verbosity": -1, "min_data_in_leaf": 5},
                    ds, num_boost_round=5)
    eng = bst.engine
    assert eng.has_bundles
    score5 = np.asarray(eng.score)[:eng.data.n, 0].copy()
    eng.train_one_iter()
    eng.rollback_one_iter()
    score5b = np.asarray(eng.score)[:eng.data.n, 0]
    np.testing.assert_allclose(score5, score5b, rtol=1e-4, atol=1e-4)


def test_sparse_input_binning_matches_dense():
    """scipy input is binned column-by-column from CSC without ever
    densifying the raw matrix; models must match the dense-input run
    exactly (same bin mappers, same binned matrix)."""
    import scipy.sparse as sp
    rng = np.random.default_rng(7)
    X = rng.normal(size=(3000, 12))
    X[rng.random(X.shape) < 0.85] = 0.0          # sparse-ish
    y = (X[:, 0] + X[:, 3] > 0).astype(float)
    params = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
              "enable_bundle": False}
    ds_d = lgb.Dataset(X, label=y)
    ds_s = lgb.Dataset(sp.csr_matrix(X), label=y)
    ds_d.construct(); ds_s.construct()
    np.testing.assert_array_equal(ds_d.binned, ds_s.binned)
    bst_d = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=6)
    bst_s = lgb.train(params, lgb.Dataset(sp.csc_matrix(X), label=y),
                      num_boost_round=6)
    np.testing.assert_allclose(bst_d.predict(X), bst_s.predict(X),
                               rtol=0, atol=0)


def test_dart_under_efb_matches_unbundled():
    """DART's dropped-tree recomputation must traverse LOGICAL bins —
    the resident train matrix under EFB is the bundled physical layout
    (regression test: it used to read self.data.bins directly)."""
    rng = np.random.default_rng(11)
    n = 4000
    dense = rng.normal(size=(n, 4))
    oh = (rng.integers(0, 6, size=n)[:, None]
          == np.arange(5)[None, :]).astype(float)
    X = np.concatenate([dense, oh], axis=1)
    y = (X[:, 0] + X[:, 4] > 0.3).astype(float)
    params = {"objective": "binary", "boosting": "dart", "num_leaves": 15,
              "drop_rate": 0.3, "skip_drop": 0.0, "verbosity": -1,
              "learning_rate": 0.3}
    b_plain = lgb.train({**params, "enable_bundle": False},
                        lgb.Dataset(X, label=y), num_boost_round=10)
    b_efb = lgb.train({**params, "enable_bundle": True},
                      lgb.Dataset(X, label=y, params={"enable_bundle":
                                                      True}),
                      num_boost_round=10)
    np.testing.assert_allclose(b_efb.predict(X), b_plain.predict(X),
                               rtol=1e-4, atol=1e-5)
