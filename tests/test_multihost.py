"""Multi-process distributed training via the public launcher API.

Round 4 (VERDICT r3 item 2): the hand-wired worker recipe became
``lightgbm_tpu.train_distributed`` — fork/join localhost processes,
automatic cross-process bin-boundary sync, rank-0 model collection
(the dask.py analog; SURVEY.md §2.2). These tests are the reference's
own localhost-distributed strategy (N processes against 127.0.0.1,
tests/distributed/_test_distributed.py per SURVEY.md §4):

- a REAL 4-process ``jax.distributed`` job through the public API,
  checked against a single-process 4-fake-device run of the same SPMD
  program (prediction equivalence);
- the bin-sync helper alone (union-sample determinism).
"""
import os
import subprocess
import sys

import numpy as np
import pytest

import lightgbm_tpu as lgb

WORKER = os.path.join(os.path.dirname(__file__), "_multihost_worker.py")

# data + params shared with the subprocess baseline (single source of
# truth — a drifted copy would compare models from different setups)
from _multihost_worker import PARAMS, make_data  # noqa: E402


def shard_fn(rank, nproc):
    """Module-level so the spawned workers can unpickle it — the
    partition->worker alignment step (dask.py _train's partition
    mapping)."""
    X, y = make_data()
    blk = len(X) // nproc
    lo, hi = rank * blk, (rank + 1) * blk
    return {"data": X[lo:hi], "label": y[lo:hi]}


def test_train_distributed_four_processes(tmp_path):
    bst = lgb.train_distributed(PARAMS, shard_fn, n_processes=4,
                                num_boost_round=5)
    X, y = make_data()
    p_mh = bst.predict(X)
    assert np.mean((p_mh > 0.5) == y) > 0.8

    # single-process baseline: the same SPMD program on 4 FAKE devices
    # (multi-node-without-a-cluster, SURVEY.md §4) — predictions match
    base_model = str(tmp_path / "base.txt")
    env = {k: v for k, v in os.environ.items()
           if not k.startswith(("PYTEST", "XLA_", "JAX_"))}
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    base = subprocess.run(
        [sys.executable, WORKER, "-1", "4", "0", base_model],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        timeout=600)
    assert base.returncode == 0, base.stdout.decode(errors="replace")
    p_base = lgb.Booster(model_file=base_model).predict(X)
    np.testing.assert_allclose(p_mh, p_base, rtol=1e-5, atol=1e-6)


def test_sync_bin_mappers_single_process_matches_local():
    """With one process the union sample IS the local sample, so the
    synced mappers equal plain find_bin_mappers on the same rows."""
    from lightgbm_tpu.io.binning import find_bin_mappers
    from lightgbm_tpu.parallel.launch import sync_bin_mappers
    X, _ = make_data()
    synced = sync_bin_mappers(X, {"max_bin": 63})
    local = find_bin_mappers(X, max_bin=63, sample_cnt=len(X))
    assert len(synced) == len(local)
    for ms, ml in zip(synced, local):
        np.testing.assert_array_equal(ms.bin_upper_bound,
                                      ml.bin_upper_bound)
        assert ms.num_bin == ml.num_bin
        assert ms.missing_type == ml.missing_type


def test_preset_mappers_dataset_roundtrip():
    """Dataset honors pre-injected bin mappers (the launcher's sync
    hook) instead of re-deriving its own."""
    from lightgbm_tpu.io.binning import find_bin_mappers
    X, y = make_data()
    mappers = find_bin_mappers(X, max_bin=31, sample_cnt=len(X))
    ds = lgb.Dataset(X, label=y, params={"verbosity": -1})
    ds.bin_mappers = mappers
    ds.construct()
    assert max(m.num_bin for m in ds.bin_mappers) <= 32
    bst = lgb.train({"objective": "binary", "num_leaves": 7,
                     "verbosity": -1}, ds, num_boost_round=3)
    assert np.mean((bst.predict(X) > 0.5) == y) > 0.7
