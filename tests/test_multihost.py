"""Multi-process distributed training via the public launcher API.

Round 4 (VERDICT r3 item 2): the hand-wired worker recipe became
``lightgbm_tpu.train_distributed`` — fork/join localhost processes,
automatic cross-process bin-boundary sync, rank-0 model collection
(the dask.py analog; SURVEY.md §2.2). These tests are the reference's
own localhost-distributed strategy (N processes against 127.0.0.1,
tests/distributed/_test_distributed.py per SURVEY.md §4):

- a REAL 4-process ``jax.distributed`` job through the public API,
  checked against a single-process 4-fake-device run of the same SPMD
  program (prediction equivalence);
- the bin-sync helper alone (union-sample determinism).
"""
import os
import subprocess
import sys

import numpy as np
import pytest

import lightgbm_tpu as lgb

WORKER = os.path.join(os.path.dirname(__file__), "_multihost_worker.py")

# data + params shared with the subprocess baseline (single source of
# truth — a drifted copy would compare models from different setups)
from _multihost_worker import GOSS_PARAMS, PARAMS, make_data  # noqa: E402


def shard_fn(rank, nproc):
    """Module-level so the spawned workers can unpickle it — the
    partition->worker alignment step (dask.py _train's partition
    mapping)."""
    X, y = make_data()
    blk = len(X) // nproc
    lo, hi = rank * blk, (rank + 1) * blk
    return {"data": X[lo:hi], "label": y[lo:hi]}


def test_train_distributed_four_processes(tmp_path,
                                          multiprocess_collectives):
    bst = lgb.train_distributed(PARAMS, shard_fn, n_processes=4,
                                num_boost_round=5)
    X, y = make_data()
    p_mh = bst.predict(X)
    assert np.mean((p_mh > 0.5) == y) > 0.8

    # single-process baseline: the same SPMD program on 4 FAKE devices
    # (multi-node-without-a-cluster, SURVEY.md §4) — predictions match
    base_model = str(tmp_path / "base.txt")
    env = {k: v for k, v in os.environ.items()
           if not k.startswith(("PYTEST", "XLA_", "JAX_"))}
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    base = subprocess.run(
        [sys.executable, WORKER, "-1", "4", "0", base_model],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        timeout=600)
    assert base.returncode == 0, base.stdout.decode(errors="replace")
    p_base = lgb.Booster(model_file=base_model).predict(X)
    np.testing.assert_allclose(p_mh, p_base, rtol=1e-5, atol=1e-6)


def test_train_distributed_goss_matches_single_process(
        tmp_path, multiprocess_collectives):
    """VERDICT r4 item 7: exact GOSS subset counts at ANY process
    count — the 4-process GOSS run must produce the same model as the
    single-process 4-fake-device run of the same SPMD program (which
    only holds when both derive identical per-shard k_top/k_rand).
    Needs REAL multi-process collectives (the conftest probe skips
    where jaxlib's CPU backend lacks them, known-red since seed)."""
    bst = lgb.train_distributed(GOSS_PARAMS, shard_fn, n_processes=4,
                                num_boost_round=5)
    X, y = make_data()
    p_mh = bst.predict(X)
    base_model = str(tmp_path / "base_goss.txt")
    env = {k: v for k, v in os.environ.items()
           if not k.startswith(("PYTEST", "XLA_", "JAX_"))}
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    base = subprocess.run(
        [sys.executable, WORKER, "-1", "4", "0", base_model, "goss"],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        timeout=600)
    assert base.returncode == 0, base.stdout.decode(errors="replace")
    p_base = lgb.Booster(model_file=base_model).predict(X)
    np.testing.assert_allclose(p_mh, p_base, rtol=1e-5, atol=1e-6)


def test_goss_shard_valid_counts_multiprocess_table():
    """The multi-host exact-count table: inject a fake allgather and
    check per-global-shard counts equal the single-process layout of
    the concatenated rows."""
    from lightgbm_tpu.boosting.gbdt import goss_shard_valid_counts

    # 2 processes x 2 local devices, uneven local valid rows
    # (n_pad_local identical across processes, as the placement
    # contract requires)
    n_pad_local, blk = 1024, 512
    locals_ = {0: 900, 1: 700}   # valid rows per process

    def fake_allgather(x):
        out = []
        for p in range(2):
            n = locals_[p]
            out.append([max(0, min(n - s * blk, blk)) for s in range(2)])
        return np.asarray(out, np.int64)

    got = goss_shard_valid_counts(900, n_pad_local, 4, 2,
                                  allgather=fake_allgather)
    assert got == [512, 388, 512, 188]
    # single-process path: same layout semantics per shard
    assert goss_shard_valid_counts(900, 1024, 2, 1) == [512, 388]


def test_sync_bin_mappers_single_process_matches_local():
    """With one process the union sample IS the local sample, so the
    synced mappers equal plain find_bin_mappers on the same rows."""
    from lightgbm_tpu.io.binning import find_bin_mappers
    from lightgbm_tpu.parallel.launch import sync_bin_mappers
    X, _ = make_data()
    synced = sync_bin_mappers(X, {"max_bin": 63})
    local = find_bin_mappers(X, max_bin=63, sample_cnt=len(X))
    assert len(synced) == len(local)
    for ms, ml in zip(synced, local):
        np.testing.assert_array_equal(ms.bin_upper_bound,
                                      ml.bin_upper_bound)
        assert ms.num_bin == ml.num_bin
        assert ms.missing_type == ml.missing_type


def _run_sync_uneven(shards, params, monkeypatch):
    """Simulate an ``len(shards)``-process sync_bin_mappers in ONE
    process: fake ``jax.process_count/index`` and
    ``process_allgather``, record every rank's sample contribution in
    a first pass, then combine them for rank 0's final run. Exercises
    the real function body (both allgathers) without a cluster."""
    import jax
    from jax.experimental import multihost_utils

    from lightgbm_tpu.parallel.launch import sync_bin_mappers

    nproc = len(shards)
    n_all = np.array([len(s) for s in shards], np.int64)
    recorded = {}          # rank -> its padded sample contribution

    class _Abort(Exception):
        pass

    state = {"rank": 0, "mode": "record"}

    def fake_allgather(x):
        x = np.asarray(x)
        if x.dtype == np.int64 and x.size == 1:      # counts gather
            return n_all.reshape(nproc, 1)
        if state["mode"] == "record":                # sample gather
            recorded[state["rank"]] = x.copy()
            raise _Abort()
        stacked = [x if r == 0 else recorded[r] for r in range(nproc)]
        return np.stack(stacked)

    monkeypatch.setattr(jax, "process_count", lambda: nproc)
    monkeypatch.setattr(multihost_utils, "process_allgather",
                        fake_allgather)
    for r in range(1, nproc):
        state.update(rank=r, mode="record")
        monkeypatch.setattr(jax, "process_index", lambda r=r: r)
        with pytest.raises(_Abort):
            sync_bin_mappers(shards[r], params)
    state.update(rank=0, mode="combine")
    monkeypatch.setattr(jax, "process_index", lambda: 0)
    mappers = sync_bin_mappers(shards[0], params)
    sizes = {r: int(np.sum(~np.isnan(recorded[r][:, 0])))
             for r in recorded}
    return mappers, sizes


def test_sync_bin_mappers_uneven_shards_weighted(monkeypatch):
    """VERDICT r4 item 4: with a 10:1 row skew across shards drawn
    from DIFFERENT distributions, sample quotas must be proportional
    to shard size and the synced boundaries must match a
    single-process build of the concatenated data."""
    from lightgbm_tpu.io.binning import find_bin_mappers
    rng = np.random.default_rng(11)
    big = rng.normal(0.0, 1.0, size=(50_000, 3))
    small = rng.normal(5.0, 0.3, size=(5_000, 3))     # shifted dist
    params = {"max_bin": 63, "bin_construct_sample_cnt": 5_000}
    _, sizes = _run_sync_uneven([big, small], params, monkeypatch)
    # proportional allocation: the small shard (1/11 of rows) must get
    # ~1/11 of the budget, NOT the old equal half
    assert sizes[1] <= 600, sizes     # equal split would give 2500
    # exact path: budget >= total rows -> union IS the concatenation,
    # so boundaries equal a single-process build bit-for-bit
    params_full = {"max_bin": 63,
                   "bin_construct_sample_cnt": 100_000}
    mappers, _ = _run_sync_uneven([big, small], params_full,
                                  monkeypatch)
    concat = np.concatenate([big, small])
    local = find_bin_mappers(concat, max_bin=63, sample_cnt=len(concat))
    for ms, ml in zip(mappers, local):
        np.testing.assert_array_equal(ms.bin_upper_bound,
                                      ml.bin_upper_bound)


def test_preset_mappers_dataset_roundtrip():
    """Dataset honors pre-injected bin mappers (the launcher's sync
    hook) instead of re-deriving its own."""
    from lightgbm_tpu.io.binning import find_bin_mappers
    X, y = make_data()
    mappers = find_bin_mappers(X, max_bin=31, sample_cnt=len(X))
    ds = lgb.Dataset(X, label=y, params={"verbosity": -1})
    ds.bin_mappers = mappers
    ds.construct()
    assert max(m.num_bin for m in ds.bin_mappers) <= 32
    bst = lgb.train({"objective": "binary", "num_leaves": 7,
                     "verbosity": -1}, ds, num_boost_round=3)
    assert np.mean((bst.predict(X) > 0.5) == y) > 0.7


def test_train_distributed_rank_traces_merge(tmp_path,
                                             multiprocess_collectives):
    """Request-lifecycle tracing across a gang (ISSUE 13 acceptance):
    a 2-rank ``train_distributed`` run with ``tpu_trace_dir`` leaves
    one rank-tagged trace file per worker, and scripts/trace_merge.py
    merges them into ONE Perfetto-loadable timeline with rebased
    clocks and rank-named process rows (the straggler-visibility
    contract; the 1-rank in-container path is pinned in
    test_trace_merge.py)."""
    import json
    import subprocess

    tdir = str(tmp_path / "trace")
    lgb.train_distributed(dict(PARAMS, tpu_trace_dir=tdir), shard_fn,
                          n_processes=2, num_boost_round=3)
    names = sorted(os.listdir(tdir))
    assert "rank_0.trace.json" in names and "rank_1.trace.json" in names
    script = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "scripts", "trace_merge.py")
    proc = subprocess.run(
        [sys.executable, script, tdir],
        capture_output=True, text=True)
    assert proc.returncode == 0, proc.stderr
    rec = json.loads(proc.stdout)
    assert rec["ranks"] == [0, 1]
    assert rec["unrebased_ranks"] == []
    doc = json.load(open(os.path.join(tdir, "merged.trace.json")))
    rows = [e["args"]["name"] for e in doc["traceEvents"]
            if e.get("ph") == "M" and e["name"] == "process_name"]
    assert any(n.startswith("rank 0") for n in rows)
    assert any(n.startswith("rank 1") for n in rows)
    # both ranks' spans share the one rebased timeline, keyed by rank
    pids = {e["pid"] for e in doc["traceEvents"] if e.get("ph") == "X"}
    assert pids == {0, 1}
