"""Real 2-process multi-host training (VERDICT r2 item 3).

Spawns two localhost processes that join one ``jax.distributed`` job on
the CPU backend, each ingesting its OWN row shard via
``jax.make_array_from_process_local_data`` (parallel/multihost.py), and
asserts the trained model matches a single-process data-parallel run on
the same global data — the reference's own localhost-distributed test
strategy (SURVEY.md §4)."""
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

import lightgbm_tpu as lgb

WORKER = os.path.join(os.path.dirname(__file__), "_multihost_worker.py")


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _clean_env(**extra):
    env = {k: v for k, v in os.environ.items()
           if not k.startswith(("PYTEST", "XLA_", "JAX_"))}
    env.update(extra)
    return env


def test_two_process_data_parallel_matches_single_process(tmp_path):
    port = _free_port()
    mh_model = str(tmp_path / "mh.txt")
    base_model = str(tmp_path / "base.txt")

    # two real processes, one jax.distributed job, 1 CPU device each
    procs = [subprocess.Popen(
        [sys.executable, WORKER, str(rank), "2", str(port), mh_model],
        env=_clean_env(), stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT) for rank in (0, 1)]
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=600)
        outs.append(out.decode(errors="replace"))
    for rank, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {rank} failed:\n{out[-4000:]}"
    assert os.path.exists(mh_model)

    # single-process baseline: same SPMD program on 2 FAKE devices
    base = subprocess.run(
        [sys.executable, WORKER, "-1", "2", str(port), base_model],
        env=_clean_env(
            XLA_FLAGS="--xla_force_host_platform_device_count=2"),
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, timeout=600)
    assert base.returncode == 0, base.stdout.decode(errors="replace")

    # compare via host-side prediction of both saved models
    from _multihost_worker import make_data
    X, y = make_data()
    p_mh = lgb.Booster(model_file=mh_model).predict(X)
    p_base = lgb.Booster(model_file=base_model).predict(X)
    np.testing.assert_allclose(p_mh, p_base, rtol=1e-5, atol=1e-6)
    # and the model actually learned
    auc_ok = np.mean((p_mh > 0.5) == y)
    assert auc_ok > 0.8, auc_ok
