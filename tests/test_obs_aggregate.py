"""Per-rank metrics aggregation (lightgbm_tpu/obs/aggregate.py).

What these tests pin:

* **Associativity** — the snapshot merge is a fold that must converge
  regardless of grouping: ``(A ⊕ B) ⊕ C == A ⊕ (B ⊕ C)`` across
  counters, gauges, labeled metrics and histograms (including the
  mismatched-bucket-layout degradation).
* **Semantics** — counters SUM across ranks; gauges keep the latest
  stamp (ties break deterministically on value); histograms bucket-add
  with min-of-mins/max-of-maxes.
* **Straggler gauge** — ``dist.round_time_spread`` = max/min of
  per-rank mean round time; an even gang reads 1.0.
* **Rank-file plumbing** — rank dumps land in rank_<r>.jsonl, corrupt
  files are skipped, the merged view lands in merged.jsonl.
* **End-to-end** (capability-gated like every multi-process gang) — a
  2-process ``train_distributed`` run whose merged counters equal the
  sum of per-rank counters and whose spread gauge is finite.
"""
import copy
import json
import math
import os

import numpy as np
import pytest

from lightgbm_tpu.obs import aggregate as agg


def _counter(name, value, updated=1.0, **labels):
    m = {"name": name, "type": "counter", "value": value,
         "updated_monotonic": updated}
    if labels:
        m["labels"] = {k: str(v) for k, v in labels.items()}
    return m


def _gauge(name, value, updated=1.0):
    return {"name": name, "type": "gauge", "value": value,
            "updated_monotonic": updated}


def _hist(name, buckets, sum_=None, mn=None, mx=None, updated=1.0):
    count = sum(c for _b, c in buckets)
    return {"name": name, "type": "histogram", "count": count,
            "sum": count * 0.1 if sum_ is None else sum_,
            "min": mn, "max": mx, "buckets": [list(b) for b in buckets],
            "updated_monotonic": updated}


def _snap(rank, metrics, ts=100.0):
    return {"schema": "lightgbm-tpu-metrics-v1", "ts": ts,
            "rank": rank, "metrics": copy.deepcopy(metrics)}


def _canon(snap):
    """Comparable form: metrics keyed by identity, envelope ranks."""
    out = {}
    for m in snap["metrics"]:
        key = (m["name"], m.get("type"),
               tuple(sorted((m.get("labels") or {}).items())))
        out[key] = {k: v for k, v in m.items()
                    if k != "updated_monotonic"}
    return out, sorted(snap.get("merged_from_ranks", []))


A = _snap(0, [
    _counter("train.iterations", 10, updated=5.0),
    _counter("predict.requests", 3, updated=2.0, model="a"),
    _gauge("hbm.bytes_in_use", 100.0, updated=1.0),
    _hist("train/round", [[0.1, 2], [1.0, 1], ["+Inf", 0]],
          sum_=0.5, mn=0.05, mx=0.9),
])
B = _snap(1, [
    _counter("train.iterations", 20, updated=6.0),
    _gauge("hbm.bytes_in_use", 300.0, updated=9.0),
    _hist("train/round", [[0.1, 1], [1.0, 3], ["+Inf", 1]],
          sum_=3.0, mn=0.02, mx=5.0),
    _counter("checkpoint.saves", 2, updated=1.0),
])
C = _snap(2, [
    _counter("train.iterations", 5, updated=2.5),
    _counter("predict.requests", 4, updated=9.0, model="a"),
    _gauge("hbm.bytes_in_use", 200.0, updated=9.0),
    _hist("train/round", [[0.1, 0], [1.0, 2], ["+Inf", 0]],
          sum_=1.0, mn=0.4, mx=0.6),
])


def test_merge_is_associative_across_groupings():
    left = agg.merge_snapshots([agg.merge_snapshots([A, B]), C])
    right = agg.merge_snapshots([A, agg.merge_snapshots([B, C])])
    flat = agg.merge_snapshots([A, B, C])
    assert _canon(left) == _canon(right) == _canon(flat)
    assert _canon(left)[1] == [0, 1, 2]


def test_merge_semantics_counters_gauges_histograms():
    merged, _ranks = _canon(agg.merge_snapshots([A, B, C]))
    cnt = merged[("train.iterations", "counter", ())]
    assert cnt["value"] == 35                      # counters SUM
    lab = merged[("predict.requests", "counter", (("model", "a"),))]
    assert lab["value"] == 7                       # per-label-set sums
    g = merged[("hbm.bytes_in_use", "gauge", ())]
    # latest updated wins; the B-vs-C tie at updated=9.0 breaks on the
    # larger value (deterministic total order keeps the fold a fold)
    assert g["value"] == 300.0
    h = merged[("train/round", "histogram", ())]
    assert h["count"] == 10 and h["sum"] == pytest.approx(4.5)
    assert h["min"] == 0.02 and h["max"] == 5.0
    assert h["buckets"] == [[0.1, 3], [1.0, 6], ["+Inf", 1]]
    # a metric present on one rank only passes through
    assert merged[("checkpoint.saves", "counter", ())]["value"] == 2


def test_mismatched_histogram_layouts_degrade_associatively():
    D = _snap(3, [_hist("train/round", [[0.5, 4], ["+Inf", 0]],
                        sum_=1.0, mn=0.1, mx=0.4)])
    left = agg.merge_snapshots([agg.merge_snapshots([A, D]), C])
    right = agg.merge_snapshots([A, agg.merge_snapshots([D, C])])
    assert _canon(left) == _canon(right)
    h = _canon(left)[0][("train/round", "histogram", ())]
    assert h["buckets"] is None                    # layout conflict
    assert h["count"] == 9                         # scalars still fold


def test_gauge_latest_uses_wall_rebased_stamps_across_hosts():
    """Per-process monotonic clocks are per-boot epochs: a host up 30
    days must not win every latest-gauge tie against a freshly
    rebooted one. Leaf snapshots rebase updated stamps to wall clock
    via their ts/monotonic envelope pair before folding."""
    # host A: booted long ago (monotonic ~2.6e6), stamped 100 s before
    # its snapshot; host B: fresh boot (monotonic 50), stamped 1 s
    # before its LATER snapshot — B's value is genuinely newer
    host_a = {"schema": "lightgbm-tpu-metrics-v1", "ts": 1000.0,
              "monotonic": 2_600_000.0, "rank": 0,
              "metrics": [_gauge("hbm.bytes_in_use", 111.0,
                                 updated=2_599_900.0)]}
    host_b = {"schema": "lightgbm-tpu-metrics-v1", "ts": 1050.0,
              "monotonic": 50.0, "rank": 1,
              "metrics": [_gauge("hbm.bytes_in_use", 222.0,
                                 updated=49.0)]}
    merged, _ = _canon(agg.merge_snapshots([host_a, host_b]))
    # raw monotonic compare would keep host A's stale 111.0
    assert merged[("hbm.bytes_in_use", "gauge", ())]["value"] == 222.0
    # grouping order doesn't change the outcome (rebase stays a fold)
    one = agg.merge_snapshots([agg.merge_snapshots([host_a]), host_b])
    assert _canon(one)[0][("hbm.bytes_in_use", "gauge",
                           ())]["value"] == 222.0


def test_degraded_histogram_renders_in_prometheus_exposition():
    """A merged snapshot with buckets:null (layout-mismatch
    degradation) must still render its scalar _sum/_count lines —
    task=dump_metrics on a merged.jsonl must not crash."""
    from lightgbm_tpu.obs.metrics import prometheus_from_snapshot
    D = _snap(3, [_hist("train/round", [[0.5, 4], ["+Inf", 0]],
                        sum_=1.0)])
    merged = agg.merge_snapshots([A, D])
    h = next(m for m in merged["metrics"]
             if m["name"] == "train/round")
    assert h["buckets"] is None
    text = prometheus_from_snapshot(merged)
    assert "train_round_count 7" in text
    assert "train_round_sum 1.5" in text
    assert "train_round_bucket" not in text


def test_round_time_spread_and_even_gang():
    # rank means: A 0.5/3, B 3.0/5, C 1.0/2 -> max/min = 0.6/(1/6)
    spread = agg.round_time_spread([A, B, C])
    assert spread == pytest.approx(0.6 / (0.5 / 3))
    even = [_snap(r, [_hist("train/round", [[1.0, 4], ["+Inf", 0]],
                            sum_=2.0)]) for r in range(3)]
    assert agg.round_time_spread(even) == pytest.approx(1.0)
    assert agg.round_time_spread([_snap(0, [])]) is None


def test_rank_dir_dump_merge_and_corrupt_file_skip(tmp_path):
    d = str(tmp_path)
    for snap in (A, B, C):
        agg.dump_rank_snapshot(d, snap["rank"], snap)
    # a rank killed mid-write leaves garbage: skipped, not fatal
    (tmp_path / "rank_7.jsonl").write_text("{truncated")
    merged = agg.merge_rank_dir(d)
    assert merged["merged_from_ranks"] == [0, 1, 2]
    by = {m["name"]: m for m in merged["metrics"]
          if not m.get("labels")}
    assert by["train.iterations"]["value"] == 35
    assert math.isfinite(by["dist.round_time_spread"]["value"])
    # merged.jsonl written and parseable
    lines = (tmp_path / "merged.jsonl").read_text().splitlines()
    assert json.loads(lines[-1])["merged_from_ranks"] == [0, 1, 2]
    # newest-line semantics: a re-dump supersedes the old rank line
    A2 = copy.deepcopy(A)
    A2["metrics"][0]["value"] = 100
    agg.dump_rank_snapshot(d, 0, A2)
    merged2 = agg.merge_rank_dir(d, write=False)
    by2 = {m["name"]: m for m in merged2["metrics"]
           if not m.get("labels")}
    assert by2["train.iterations"]["value"] == 125


def test_empty_rank_dir_returns_none(tmp_path):
    assert agg.merge_rank_dir(str(tmp_path)) is None


# ---------------------------------------------------------------------------
# end-to-end 2-process gang (capability-gated like test_multihost)
# ---------------------------------------------------------------------------
def _agg_shard(rank, nproc):
    rng = np.random.default_rng(100 + rank)
    X = rng.normal(size=(600, 6))
    y = (X[:, 0] + 0.3 * X[:, 1] > 0).astype(np.float64)
    return {"data": X, "label": y}


def test_two_process_gang_merges_rank_counters(
        tmp_path, multiprocess_collectives):
    from lightgbm_tpu.parallel.launch import train_distributed
    d = str(tmp_path / "ranks")
    os.makedirs(d)
    # a stale rank file from a previous (larger) gang must NOT merge
    # as a live member: the fresh-run driver clears the dir
    agg.dump_rank_snapshot(d, 7, _snap(7, [
        _counter("train.iterations", 999)]))
    params = {"objective": "binary", "num_leaves": 7, "verbosity": -1,
              "tpu_metrics": True, "tpu_metrics_rank_dir": d,
              "tpu_fuse_iters": 1}
    bst = train_distributed(params, _agg_shard, n_processes=2,
                            num_boost_round=4, timeout=600.0)
    assert bst.num_trees() == 4
    snaps = agg.read_rank_snapshots(d)
    assert {s["rank"] for s in snaps} == {0, 1}
    merged = json.loads(
        (tmp_path / "ranks" / "merged.jsonl").read_text()
        .splitlines()[-1])
    assert sorted(merged["merged_from_ranks"]) == [0, 1]

    def counter_of(snap, name):
        for m in snap["metrics"]:
            if m["name"] == name and not m.get("labels"):
                return m["value"]
        return 0.0
    per_rank = [counter_of(s, "train.iterations")
                for s in snaps if s.get("rank") in (0, 1)]
    assert per_rank and all(v == 4 for v in per_rank)
    # merged counters == sum of per-rank counters (ISSUE acceptance)
    assert counter_of(merged, "train.iterations") == sum(per_rank)
    spread = next(m["value"] for m in merged["metrics"]
                  if m["name"] == "dist.round_time_spread")
    assert math.isfinite(spread) and spread >= 1.0
