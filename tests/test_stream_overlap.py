"""Async pipelined streamed×sharded training (ISSUE 17): block H2D
prefetch, overlapped level reduce, deferred final sweep — all behind
``tpu_stream_overlap``, bit-identical on/off BY CONSTRUCTION.

The contract pinned here: overlap moves HOST BLOCKING only —
accumulation order, reduce payloads, and score arithmetic are
untouched — so models with ``tpu_stream_overlap`` on vs off are
bit-identical at 1/2/4 shards × {plain, quantized, GOSS, bagging};
the one-collective-per-level invariant (``comm.allreduce_calls ==
levels``) survives the async dispatch; checkpoint exports drain the
in-flight windows first (the PR 13 contract), so a streamed resume and
an elastic re-cut taken while a final sweep was pending stay
bit-exact; and the utils/prefetch.py primitives (the shared window /
prefetcher the trainer and predict both ride) keep their ordering,
drain, and loud-schedule-drift semantics.
"""
import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.utils.prefetch import BlockPrefetcher, InflightWindow


def _data(n=6_000, f=10, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f))
    y = (X[:, 0] + 0.5 * X[:, 1] * X[:, 2]
         + rng.normal(scale=0.3, size=n) > 0).astype(np.float64)
    return X, y


# same shape family as tests/test_streaming_resume.py BASE so the
# modules share jit compiles (block 2048, leaves 16, depth 4)
BASE = {"objective": "binary", "num_leaves": 16, "max_depth": 4,
        "verbosity": -1, "min_data_in_leaf": 20,
        "tpu_streaming": "true", "tpu_stream_block_rows": 2_048}


def _params(shards, overlap, **extra):
    p = dict(BASE, tpu_stream_overlap="true" if overlap else "false",
             **extra)
    if shards > 1:
        p["tree_learner"] = "data"
        p["tpu_mesh_shape"] = shards
    return p


def _train(shards, overlap, X, y, rounds=3, **extra):
    return lgb.train(_params(shards, overlap, **extra),
                     lgb.Dataset(X, label=y), num_boost_round=rounds)


# ---------------------------------------------------------------------------
# the acceptance matrix: overlap on == overlap off, bit for bit
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("shards", [1, 2, 4])
@pytest.mark.parametrize("extra", [
    {},
    {"use_quantized_grad": True},
    {"data_sample_strategy": "goss"},
    {"bagging_fraction": 0.6, "bagging_freq": 2},
], ids=["plain", "quant", "goss", "bagging"])
def test_overlap_bit_identical(extra, shards):
    X, y = _data()
    off = _train(shards, False, X, y, **extra)
    on = _train(shards, True, X, y, **extra)
    assert on.model_to_string() == off.model_to_string(), \
        f"tpu_stream_overlap changed the model at {shards} shard(s)"


def test_one_collective_per_level_under_overlap():
    """The overlapped reduce must not split, repeat, or drop the
    per-level packed collective: exactly ONE allreduce per grown
    level, same as the synchronous path."""
    X, y = _data()
    on = _train(2, True, X, y)
    off = _train(2, False, X, y)
    cs_on, cs_off = on.engine.comm_stats, off.engine.comm_stats
    assert cs_on["levels"] > 0
    assert cs_on["allreduce_calls"] == cs_on["levels"]
    assert (cs_on["allreduce_calls"], cs_on["allreduce_bytes"]) == \
        (cs_off["allreduce_calls"], cs_off["allreduce_bytes"])


def test_overlap_defaults_on_and_rejects_garbage():
    X, y = _data(n=4_000)
    bst = lgb.train(dict(BASE), lgb.Dataset(X, label=y),
                    num_boost_round=1)
    assert bst.engine._overlap          # auto == on
    eng_off = _train(1, False, X, y, rounds=1).engine
    assert not eng_off._overlap
    with pytest.raises(lgb.LightGBMError, match="tpu_stream_overlap"):
        lgb.train(dict(BASE, tpu_stream_overlap="sideways"),
                  lgb.Dataset(X, label=y), num_boost_round=1)


# ---------------------------------------------------------------------------
# checkpoint contract: export drains pending device work first
# ---------------------------------------------------------------------------
def test_export_drains_pending_final_sweep():
    """After a round the deferred final sweep is still in flight (the
    windows deliberately hold work across the round seam); exporting
    train state must drain every window first — a checkpoint is a
    barrier, not a snapshot of a moving target."""
    X, y = _data()
    eng = _train(1, True, X, y).engine
    assert any(len(w) for w in eng._inflight), \
        "expected a pending deferred final sweep under overlap"
    state = eng.export_train_state()
    assert all(len(w) == 0 for w in eng._inflight)
    assert state["iteration"] == 3


@pytest.mark.parametrize("extra", [
    {},
    {"use_quantized_grad": True},
], ids=["plain", "quant"])
def test_streamed_resume_across_overlap_modes(extra, tmp_path):
    """Checkpoints written while final sweeps were pending (interval 2,
    kill before iter 3) resume bit-equal — and the straight arm runs
    overlap OFF while the chaos+resume arms run overlap ON, so the
    equality also crosses the modes."""
    X, y = _data(n=8_000)
    rounds, kill_at = 5, 3
    straight = lgb.train(
        _params(2, False, checkpoint_dir=str(tmp_path / "s"),
                checkpoint_interval=2, **extra),
        lgb.Dataset(X, label=y), num_boost_round=rounds)
    p = _params(2, True, checkpoint_dir=str(tmp_path / "c"),
                checkpoint_interval=2,
                tpu_fault_inject=f"exn:iter={kill_at}", **extra)
    with pytest.raises(lgb.LightGBMError, match="injected failure"):
        lgb.train(p, lgb.Dataset(X, label=y), num_boost_round=rounds)
    resumed = lgb.train(p, lgb.Dataset(X, label=y),
                        num_boost_round=rounds,
                        resume_from=str(tmp_path / "c"))
    assert resumed.num_trees() == rounds
    assert resumed.model_to_string() == straight.model_to_string()


def test_elastic_recut_with_overlap(tmp_path):
    """PR 18's topology re-cut on top of the pipeline: a 4-shard
    overlapped run killed mid-training resumes at 2 shards (scores
    re-cut via _replay_score_blocks) still overlapped, bit-equal to
    the uninterrupted synchronous 4-shard run."""
    X, y = _data(n=8_000)
    extra = {"use_quantized_grad": True}    # quant makes re-cut exact
    rounds, kill_at = 5, 3
    straight = lgb.train(
        _params(4, False, checkpoint_dir=str(tmp_path / "s"),
                checkpoint_interval=2, **extra),
        lgb.Dataset(X, label=y), num_boost_round=rounds)
    with pytest.raises(lgb.LightGBMError, match="injected failure"):
        lgb.train(_params(4, True, checkpoint_dir=str(tmp_path / "c"),
                          checkpoint_interval=2,
                          tpu_fault_inject=f"exn:iter={kill_at}",
                          **extra),
                  lgb.Dataset(X, label=y), num_boost_round=rounds)
    resumed = lgb.train(_params(2, True,
                                checkpoint_dir=str(tmp_path / "c"),
                                checkpoint_interval=2, **extra),
                        lgb.Dataset(X, label=y), num_boost_round=rounds,
                        resume_from=str(tmp_path / "c"))
    assert resumed.model_to_string() == straight.model_to_string()


# ---------------------------------------------------------------------------
# utils/prefetch.py primitives
# ---------------------------------------------------------------------------
def test_inflight_window_order_depth_drain():
    done = []
    win = InflightWindow(1, done.append)
    win.push("a")
    assert done == [] and len(win) == 1     # depth 1: nothing completes
    win.push("b")
    assert done == ["a"]                    # oldest-first
    win.push("c")
    assert done == ["a", "b"]
    win.drain()
    assert done == ["a", "b", "c"] and len(win) == 0
    win.drain()                             # idempotent
    assert done == ["a", "b", "c"]


def test_inflight_window_depth_zero_is_synchronous():
    done = []
    win = InflightWindow(0, done.append)
    win.push("a")
    assert done == ["a"]                    # completes at every push


@pytest.mark.parametrize("threaded", [False, True],
                         ids=["inline", "threaded"])
def test_prefetcher_cyclic_order_and_drift(threaded):
    staged = []

    def stage(item):
        staged.append(item)
        return item * 10

    pf = BlockPrefetcher(stage, [1, 2, 3], threaded=threaded)
    try:
        # two full cycles: the schedule wraps (next sweep = same order)
        got = [pf.take(expect=e) for e in (1, 2, 3, 1, 2, 3)]
        assert got == [10, 20, 30, 10, 20, 30]
        # consumer iterating out of schedule order is a loud error,
        # not a silently wrong block
        with pytest.raises(RuntimeError, match="schedule drift"):
            pf.take(expect=3)
    finally:
        pf.close()


def test_prefetcher_threaded_stages_ahead():
    import threading
    names = []

    def stage(item):
        names.append(threading.current_thread().name)
        return item

    pf = BlockPrefetcher(stage, ["x", "y"], threaded=True)
    try:
        assert pf.take(expect="x") == "x"
        assert all(n.startswith("h2d-prefetch") for n in names)
    finally:
        pf.close()


def test_prefetcher_close_frees_staged_buffers():
    class Buf:
        def __init__(self):
            self.deleted = False

        def delete(self):
            self.deleted = True

    bufs = []

    def stage(_item):
        b = Buf()
        bufs.append(b)
        return b

    pf = BlockPrefetcher(stage, [1, 2, 3], threaded=True)
    pf.take(expect=1)
    pf.close()
    # everything staged-but-unconsumed was freed; the consumed buffer
    # is the caller's to manage
    assert all(b.deleted for b in bufs[1:])
    assert not bufs[0].deleted


def test_prefetcher_rejects_empty_schedule():
    with pytest.raises(ValueError, match="non-empty"):
        BlockPrefetcher(lambda x: x, [])
