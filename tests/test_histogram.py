"""Histogram op vs NumPy oracle."""
import numpy as np
import pytest

from lightgbm_tpu.ops.histogram import build_histogram, pad_rows


def _oracle(bins, vals, B):
    n, F = bins.shape
    C = vals.shape[1]
    out = np.zeros((F, B, C), dtype=np.float64)
    for f in range(F):
        for r in range(n):
            out[f, bins[r, f]] += vals[r]
    return out


@pytest.mark.parametrize("n,F,B,block", [(512, 4, 16, 128),
                                         (1024, 7, 64, 256)])
def test_histogram_matches_oracle(n, F, B, block):
    rng = np.random.default_rng(0)
    bins = rng.integers(0, B, size=(n, F)).astype(np.uint8)
    vals = rng.normal(size=(n, 3)).astype(np.float32)
    vals[:, 2] = 1.0
    hist = np.asarray(build_histogram(bins, vals, num_bins=B,
                                      rows_per_block=block))
    oracle = _oracle(bins, vals, B)
    # bf16 inputs with f32 accumulation: tolerance scales with leaf size
    np.testing.assert_allclose(hist, oracle, rtol=2e-2, atol=2e-2 * np.sqrt(n))


def test_histogram_precise_mode():
    rng = np.random.default_rng(1)
    n, F, B = 256, 3, 8
    bins = rng.integers(0, B, size=(n, F)).astype(np.uint8)
    vals = rng.normal(size=(n, 3)).astype(np.float32)
    hist = np.asarray(build_histogram(bins, vals, num_bins=B,
                                      rows_per_block=n, precise=True))
    oracle = _oracle(bins, vals, B)
    np.testing.assert_allclose(hist, oracle, rtol=1e-5, atol=1e-4)


def test_count_channel_exact():
    rng = np.random.default_rng(2)
    n, F, B = 2048, 5, 32
    bins = rng.integers(0, B, size=(n, F)).astype(np.uint8)
    mask = (rng.uniform(size=n) < 0.5).astype(np.float32)
    vals = np.stack([mask, mask, mask], axis=1)
    hist = np.asarray(build_histogram(bins, vals, num_bins=B,
                                      rows_per_block=512))
    # counts are sums of exact 1.0s: must be exact in f32 accumulation
    for f in range(F):
        expected = np.bincount(bins[mask > 0, f], minlength=B)
        np.testing.assert_array_equal(hist[f, :, 2], expected)


def test_pad_rows():
    assert pad_rows(1000, 256) == 1024
    assert pad_rows(1024, 256) == 1024
    assert pad_rows(1, 256) == 256
