"""Categorical split tests: one-hot + sorted many-vs-many end-to-end.

Reference semantics (feature_histogram.hpp FindBestThresholdCategorical,
UNVERIFIED — empty mount): few categories scan one-vs-rest; many
categories sort by grad/(hess+cat_smooth) and scan sorted prefixes from
both directions; decisions are bitset membership over category values.
"""
import numpy as np

import lightgbm_tpu as lgb


def _cat_data(n=4000, n_cats=24, seed=0):
    """Target depends on a random per-category effect: ordinal thresholds
    on the category ID are provably weak; set-splits are needed."""
    rng = np.random.default_rng(seed)
    cat = rng.integers(0, n_cats, size=n)
    effect = rng.permutation(n_cats) >= n_cats // 2   # random half is +
    noise = rng.normal(scale=0.3, size=n)
    y = (effect[cat].astype(float) * 2.0 - 1.0 + noise > 0).astype(float)
    X = np.column_stack([cat.astype(float), rng.normal(size=n)])
    return X, y, effect


def _auc(y, p):
    order = np.argsort(p)
    ranks = np.empty(len(p)); ranks[order] = np.arange(1, len(p) + 1)
    pos = y > 0
    n1, n0 = pos.sum(), (~pos).sum()
    return (ranks[pos].sum() - n1 * (n1 + 1) / 2) / (n1 * n0)


def test_sorted_categorical_beats_ordinal():
    X, y, _ = _cat_data()
    Xtr, Xte, ytr, yte = X[:3000], X[3000:], y[:3000], y[3000:]
    params = {"objective": "binary", "num_leaves": 8, "verbosity": -1,
              "min_data_per_group": 5, "cat_smooth": 1.0,
              "max_cat_to_onehot": 4}
    # one depth of tree: ordinal needs many splits to carve random halves,
    # a single categorical set-split nails it
    bst_cat = lgb.train(params, lgb.Dataset(Xtr, label=ytr,
                                            categorical_feature=[0]),
                        num_boost_round=5)
    bst_num = lgb.train(params, lgb.Dataset(Xtr, label=ytr),
                        num_boost_round=5)
    auc_cat = _auc(yte, bst_cat.predict(Xte))
    auc_num = _auc(yte, bst_num.predict(Xte))
    assert auc_cat > 0.93
    assert auc_cat > auc_num + 0.02, (auc_cat, auc_num)


def test_onehot_categorical_small_cardinality():
    rng = np.random.default_rng(3)
    n = 2000
    cat = rng.integers(0, 3, size=n)   # 3 cats <= max_cat_to_onehot
    y = (cat == 1).astype(float)
    X = np.column_stack([cat.astype(float), rng.normal(size=n)])
    bst = lgb.train({"objective": "binary", "num_leaves": 4,
                     "verbosity": -1, "min_data_in_leaf": 5,
                     "min_data_per_group": 5},
                    lgb.Dataset(X, label=y, categorical_feature=[0]),
                    num_boost_round=25)
    p = bst.predict(X)
    assert p[cat == 1].min() > 0.8
    assert p[cat != 1].max() < 0.2


def test_categorical_model_text_roundtrip(tmp_path):
    X, y, _ = _cat_data(seed=5)
    bst = lgb.train({"objective": "binary", "num_leaves": 8,
                     "verbosity": -1, "min_data_per_group": 5,
                     "cat_smooth": 1.0},
                    lgb.Dataset(X, label=y, categorical_feature=[0]),
                    num_boost_round=5)
    path = str(tmp_path / "cat_model.txt")
    bst.save_model(path)
    text = open(path).read()
    assert "cat_threshold=" in text and "cat_boundaries=" in text
    # decision_type carries the categorical bit
    assert any(int(v) & 1 for line in text.splitlines()
               if line.startswith("decision_type=")
               for v in line.split("=", 1)[1].split())
    loaded = lgb.Booster(model_file=path)
    p0 = bst.predict(X)
    p1 = loaded.predict(X)
    np.testing.assert_allclose(p0, p1, rtol=1e-5, atol=1e-6)


def test_unseen_category_routes_right_not_crash():
    X, y, _ = _cat_data(n_cats=10, seed=7)
    bst = lgb.train({"objective": "binary", "num_leaves": 8,
                     "verbosity": -1, "min_data_per_group": 5,
                     "cat_smooth": 1.0},
                    lgb.Dataset(X, label=y, categorical_feature=[0]),
                    num_boost_round=3)
    X_new = X[:10].copy()
    X_new[:, 0] = 999.0       # unseen category
    X_nan = X[:10].copy()
    X_nan[:, 0] = np.nan
    p_new = bst.predict(X_new)
    p_nan = bst.predict(X_nan)
    assert np.all(np.isfinite(p_new)) and np.all(np.isfinite(p_nan))
    # unseen and NaN categories take the same (bitset-miss) route
    np.testing.assert_allclose(p_new, p_nan, rtol=1e-6)


def test_max_cat_threshold_limits_group_size():
    X, y, _ = _cat_data(n_cats=20, seed=9)
    params = {"objective": "binary", "num_leaves": 4, "verbosity": -1,
              "min_data_per_group": 5, "cat_smooth": 1.0,
              "max_cat_threshold": 2}
    bst = lgb.train(params, lgb.Dataset(X, label=y,
                                        categorical_feature=[0]),
                    num_boost_round=1)
    s = bst.model_to_string()
    # every cat node's bitset has at most 2 set bits
    import re
    for tree_part in s.split("Tree=")[1:]:
        kv = dict(line.split("=", 1) for line in tree_part.splitlines()
                  if "=" in line)
        if int(kv.get("num_cat", 0)) == 0:
            continue
        words = np.array(kv["cat_threshold"].split(), dtype=np.uint64)
        bounds = np.array(kv["cat_boundaries"].split(), dtype=np.int64)
        dt = np.array(kv["decision_type"].split(), dtype=np.int64)
        thr = np.array(kv["threshold"].split(), dtype=np.float64)
        for nd in np.flatnonzero(dt & 1):
            ci = int(thr[nd])
            w = words[bounds[ci]:bounds[ci + 1]]
            bits = sum(bin(int(x)).count("1") for x in w)
            assert 1 <= bits <= 2
