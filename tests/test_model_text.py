"""Model text serialization round-trip + SHAP contributions."""
import numpy as np
import pytest

import lightgbm_tpu as lgb


def _train_binary(n=2000, f=6, rounds=10, **extra):
    rng = np.random.default_rng(42)
    X = rng.normal(size=(n, f))
    y = (X[:, 0] + 0.5 * X[:, 1] * X[:, 2]
         + rng.normal(scale=0.3, size=n) > 0).astype(np.float64)
    params = {"objective": "binary", "num_leaves": 15, "verbosity": -1}
    params.update(extra)
    ds = lgb.Dataset(X, label=y)
    return lgb.train(params, ds, num_boost_round=rounds), X, y


def test_roundtrip_predictions_match():
    bst, X, y = _train_binary()
    s = bst.model_to_string()
    assert s.startswith("tree")
    assert "version=v4" in s
    assert "end of trees" in s
    bst2 = lgb.Booster(model_str=s)
    p1 = bst.predict(X)
    p2 = bst2.predict(X)
    np.testing.assert_allclose(p1, p2, rtol=1e-5, atol=1e-6)


def test_roundtrip_via_file(tmp_path):
    bst, X, _ = _train_binary(rounds=5)
    path = str(tmp_path / "model.txt")
    bst.save_model(path)
    bst2 = lgb.Booster(model_file=path)
    np.testing.assert_allclose(bst.predict(X), bst2.predict(X),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(
        bst.predict(X, raw_score=True), bst2.predict(X, raw_score=True),
        rtol=1e-5, atol=1e-6)


def test_model_text_regression():
    rng = np.random.default_rng(0)
    X = rng.normal(size=(1000, 4))
    y = X @ rng.normal(size=4)
    ds = lgb.Dataset(X, label=y)
    bst = lgb.train({"objective": "regression", "num_leaves": 7,
                     "verbosity": -1}, ds, num_boost_round=8)
    bst2 = lgb.Booster(model_str=bst.model_to_string())
    np.testing.assert_allclose(bst.predict(X), bst2.predict(X),
                               rtol=1e-5, atol=1e-6)


def test_model_text_multiclass():
    rng = np.random.default_rng(1)
    X = rng.normal(size=(1500, 5))
    y = (np.abs(X[:, 0]) * 2 + np.abs(X[:, 1])).astype(np.int64) % 3
    ds = lgb.Dataset(X, label=y)
    bst = lgb.train({"objective": "multiclass", "num_class": 3,
                     "num_leaves": 7, "verbosity": -1},
                    ds, num_boost_round=5)
    bst2 = lgb.Booster(model_str=bst.model_to_string())
    np.testing.assert_allclose(bst.predict(X), bst2.predict(X),
                               rtol=1e-5, atol=1e-6)


def test_pred_leaf():
    bst, X, _ = _train_binary(rounds=5)
    leaves = bst.predict(X[:50], pred_leaf=True)
    assert leaves.shape == (50, 5)
    assert leaves.dtype == np.int32
    assert leaves.max() < 15


def test_shap_sums_to_prediction():
    bst, X, _ = _train_binary(rounds=5)
    contrib = bst.predict(X[:30], pred_contrib=True)
    raw = bst.predict(X[:30], raw_score=True)
    assert contrib.shape == (30, X.shape[1] + 1)
    np.testing.assert_allclose(contrib.sum(axis=1), raw, rtol=1e-4,
                               atol=1e-5)


def test_feature_importance_in_model_text():
    bst, X, _ = _train_binary(rounds=5)
    s = bst.model_to_string()
    assert "feature_importances:" in s
    assert "Column_0=" in s
