"""Serving service (lightgbm_tpu/serve/; docs/serving.md): queue,
micro-batching, SLO wiring, and the hot-swap threading contract.

What these tests pin:

* **Coalescing** — concurrent submits for one model ride ONE bucketed
  dispatch under the latency budget; per-request results are sliced
  back exactly (bit-equal to a direct ``Booster.predict``).
* **Flush rules** — the budget cutoff dispatches a lone request
  promptly; the row cap flushes a filling batch early.
* **SLO plane** — ``slo.queue_depth`` is the REAL queue depth via the
  registered provider (not the PR 11 placeholder), and ``/readyz``
  turns green only after the service's warmup predict (the PR 13
  readiness-by-warmup contract).
* **Swap lock** — serving.ModelWatcher.swap_lock serializes swaps
  against predicts for real: a mid-traffic publish under concurrent
  client threads yields every request bit-equal to the OLD or the NEW
  model, never a mid-swap hybrid, with zero dropped requests — also
  under LRU eviction churn through the service.
"""
import os
import shutil
import threading
import time

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu import obs
from lightgbm_tpu.obs import slo as _slo
from lightgbm_tpu.obs.server import health_payload
from lightgbm_tpu.serve import PredictService

pytestmark = pytest.mark.filterwarnings("ignore")


@pytest.fixture(autouse=True)
def _isolated_obs():
    obs.disable()
    obs.reset()
    yield
    obs.disable()
    obs.reset()


def _data(n=2000, f=8, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f))
    y = (X[:, 0] + 0.5 * X[:, 1] * X[:, 2] > 0).astype(np.float64)
    return X, y


PARAMS = {"objective": "binary", "num_leaves": 8, "verbosity": -1}


@pytest.fixture(scope="module")
def trained():
    X, y = _data()
    bst = lgb.train(PARAMS, lgb.Dataset(X, label=y), num_boost_round=4)
    return bst, X


def _service(start=True, **over):
    p = {"tpu_serve_batch_budget_ms": 200.0,
         "tpu_serve_max_batch_rows": 1024,
         "tpu_serve_shard_trees": "false"}
    p.update(over)
    return PredictService(p, start=start)


def test_coalesce_one_dispatch_exact_results(trained):
    bst, X = trained
    obs.enable(metrics=True)
    svc = _service()
    try:
        svc.add_model("m", bst)
        Xq = X[:96]
        direct = bst.predict(Xq)
        futs = [svc.submit("m", Xq) for _ in range(5)]
        outs = [f.result(timeout=20) for f in futs]
        for out in outs:
            np.testing.assert_array_equal(out, direct)
        reg = obs.registry()
        assert reg.get("serve.dispatches").value == 1.0
        assert reg.get("serve.coalesced_requests").value == 5.0
        # 5 x 96 = 480 rows in a 512 bucket
        assert reg.get("serve.batch_fill_ratio").value == \
            pytest.approx(480 / 512)
    finally:
        svc.close()


def test_budget_flush_lone_request(trained):
    bst, X = trained
    svc = _service(tpu_serve_batch_budget_ms=10.0)
    try:
        svc.add_model("m", bst)
        t0 = time.monotonic()
        out = svc.predict("m", X[:10], timeout=20)
        assert time.monotonic() - t0 < 15.0
        np.testing.assert_array_equal(out, bst.predict(X[:10]))
    finally:
        svc.close()


def test_row_cap_flushes_early(trained):
    bst, X = trained
    obs.enable(metrics=True)
    # a 10 s budget would stall the test if fill did not flush
    svc = _service(tpu_serve_batch_budget_ms=10_000.0,
                   tpu_serve_max_batch_rows=256)
    try:
        svc.add_model("m", bst)
        futs = [svc.submit("m", X[:128]) for _ in range(4)]
        for f in futs:
            f.result(timeout=20)
        assert obs.registry().get("serve.dispatches").value == 2.0
        assert obs.registry().get(
            "serve.coalesced_requests").value == 4.0
    finally:
        svc.close()


def test_oversized_request_dispatches_alone(trained):
    bst, X = trained
    svc = _service(tpu_serve_max_batch_rows=128)
    try:
        svc.add_model("m", bst)
        out = svc.predict("m", X[:300], timeout=30)
        np.testing.assert_array_equal(out, bst.predict(X[:300]))
    finally:
        svc.close()


def test_malformed_rider_does_not_poison_batchmates(trained):
    """A rider whose payload cannot even concatenate (wrong column
    count) fails ALONE — its well-formed batchmates still resolve."""
    bst, X = trained
    svc = _service(tpu_serve_batch_budget_ms=200.0)
    try:
        svc.add_model("m", bst)
        good = svc.submit("m", X[:16])
        bad = svc.submit("m", X[:8, :4])
        np.testing.assert_array_equal(good.result(timeout=20),
                                      bst.predict(X[:16]))
        with pytest.raises(Exception):
            bad.result(timeout=20)
    finally:
        svc.close()


def test_prefix_pop_strict_fifo_with_oversize():
    """A request that does not fit the cap ENDS the batch — later
    same-model requests never overtake it (pure queue, no engine)."""
    from lightgbm_tpu.serve.queue import MicroBatchQueue
    q = MicroBatchQueue(budget_s=0.0, max_batch_rows=1024)
    q.submit("m", np.zeros((100, 2)))
    q.submit("m", np.zeros((2000, 2)))
    q.submit("m", np.zeros((50, 2)))
    assert q.depth() == 3
    _, b1 = q.next_batch()
    assert [r.rows for r in b1] == [100]    # prefix ends at r2
    _, b2 = q.next_batch()
    assert [r.rows for r in b2] == [2000]   # oversize dispatches alone
    _, b3 = q.next_batch()
    assert [r.rows for r in b3] == [50]     # ... and r3 never overtook
    assert q.depth() == 0


def test_frozen_prefix_flushes_without_waiting_budget():
    """Once a non-fitting request freezes the prefix, strict FIFO
    means nothing can ever join the batch — dispatch immediately
    instead of burning the whole latency budget (and delaying the
    blocked request behind it)."""
    from lightgbm_tpu.serve.queue import MicroBatchQueue
    q = MicroBatchQueue(budget_s=30.0, max_batch_rows=1024)
    q.submit("m", np.zeros((100, 2)))
    q.submit("m", np.zeros((2000, 2)))   # freezes the prefix at 100
    t0 = time.monotonic()
    _, batch = q.next_batch(poll_s=0.05)
    assert time.monotonic() - t0 < 5.0   # nowhere near the 30s budget
    assert [r.rows for r in batch] == [100]


def test_unknown_model_fails_future_not_silently(trained):
    bst, X = trained
    svc = _service(tpu_serve_batch_budget_ms=0.0)
    try:
        fut = svc.submit("nope", X[:8])
        with pytest.raises(KeyError):
            fut.result(timeout=20)
    finally:
        svc.close()


def test_cancelled_rider_does_not_poison_batchmates(trained):
    """A client that cancels its queued future (e.g. after a timeout)
    must not break the batchmates coalesced with it — their results
    still land."""
    bst, X = trained
    svc = _service(start=False, tpu_serve_batch_budget_ms=50.0)
    try:
        svc.add_model("m", bst)
        doomed = svc.submit("m", X[:16])
        keeper = svc.submit("m", X[:16])
        assert doomed.cancel()          # still queued: cancellable
        svc.start()
        out = keeper.result(timeout=20)
        np.testing.assert_array_equal(out, bst.predict(X[:16]))
    finally:
        svc.close()


def test_close_only_clears_own_slo_provider(trained):
    """Blue/green in one process: closing the OLD service must not
    zero the queue-depth provider the NEW service registered."""
    bst, X = trained
    obs.enable(metrics=True, slo=True)
    old = _service(start=False)
    new = _service(start=False)
    try:
        new.add_model("m", bst)
        new.submit("m", X[:8])
        old.close()
        assert _slo.tracker().compute()["slo.queue_depth"] == 1.0
    finally:
        new.close()
    assert _slo.tracker().compute()["slo.queue_depth"] == 0.0


def test_close_fails_queued_futures(trained):
    bst, X = trained
    svc = _service(start=False)
    svc.add_model("m", bst)
    fut = svc.submit("m", X[:8])
    svc.close()
    with pytest.raises(RuntimeError):
        fut.result(timeout=5)
    with pytest.raises(RuntimeError):
        svc.submit("m", X[:8])


def test_queue_depth_feeds_slo_gauge(trained):
    bst, X = trained
    obs.enable(metrics=True, slo=True)
    svc = _service(start=False)   # no dispatcher: depth stays visible
    try:
        svc.add_model("m", bst)
        for _ in range(3):
            svc.submit("m", X[:8])
        slis = _slo.tracker().compute()
        assert slis["slo.queue_depth"] == 3.0
        # the gauge lands in the registry through evaluate()
        _slo.tracker().evaluate()
        assert obs.registry().get("slo.queue_depth").value == 3.0
    finally:
        svc.close()
    # provider unregistered on close: back to the empty-queue reading
    assert _slo.tracker().compute()["slo.queue_depth"] == 0.0


def test_readyz_green_after_warmup(trained):
    bst, X = trained
    obs.enable(metrics=True)
    code, body = health_payload(ready=True, timeout_s=60.0)
    assert code == 503      # no heartbeat yet: not ready
    svc = _service()
    try:
        svc.add_model("m", bst)
        svc.warmup("m", X[:1])
        code, body = health_payload(ready=True, timeout_s=60.0)
        assert code == 200
        assert "serve" in body["heartbeats"]
    finally:
        svc.close()


# ---------------------------------------------------------------------------
# the hot-swap threading contract (satellite: a REAL swap lock)
# ---------------------------------------------------------------------------
def _stage_checkpoint(X, y, tmp_path, rounds=6):
    """Pre-train a publishable v2 checkpoint into a staging dir (more
    rounds than the serving model, so the swap visibly changes
    predictions — deterministic training makes a same-round retrain
    identical)."""
    stage = str(tmp_path / "stage")
    lgb.train(dict(PARAMS, checkpoint_dir=stage,
                   checkpoint_interval=rounds),
              lgb.Dataset(X, label=y), num_boost_round=rounds)
    return stage


def _publish(stage, pub):
    os.makedirs(pub, exist_ok=True)
    names = sorted(os.listdir(stage))
    for name in names:
        if not name.startswith("latest."):
            shutil.copy(os.path.join(stage, name),
                        os.path.join(pub, name))
    for name in names:
        if name.startswith("latest."):
            shutil.copy(os.path.join(stage, name),
                        os.path.join(pub, name))


def test_swap_lock_exists_and_reentrant(trained, tmp_path):
    bst_, _X = trained
    from lightgbm_tpu.serving import ModelWatcher
    w = ModelWatcher(str(tmp_path), interval=0.0)
    assert w.swap_lock.acquire(blocking=False)
    assert w.swap_lock.acquire(blocking=False)   # reentrant
    w.swap_lock.release()
    w.swap_lock.release()


def test_concurrent_swap_under_load_old_or_new_only(tmp_path):
    """N threads hammer Booster.predict while a checkpoint publishes:
    with the swap lock, every result is bit-equal to the OLD or the
    NEW model's output — never a mid-swap hybrid — and nothing drops."""
    X, y = _data(seed=3)
    server = lgb.train(PARAMS, lgb.Dataset(X, label=y),
                       num_boost_round=4)
    v2 = lgb.train(PARAMS, lgb.Dataset(X, label=y),
                   num_boost_round=6)
    stage = _stage_checkpoint(X, y, tmp_path, rounds=6)
    pub = str(tmp_path / "pub")
    os.makedirs(pub)
    server.watch_checkpoints(pub, interval=0.0)
    Xq = X[:64]
    old = server.predict(Xq)
    new = v2.predict(Xq)
    assert not np.array_equal(old, new)   # the swap must be visible

    results, errors = [], []
    stop = threading.Event()

    def worker():
        while not stop.is_set():
            try:
                results.append(server.predict(Xq))
            except Exception as e:      # noqa: BLE001 - a drop IS the bug
                errors.append(e)

    threads = [threading.Thread(target=worker, daemon=True)
               for _ in range(4)]
    for t in threads:
        t.start()
    time.sleep(0.2)
    _publish(stage, pub)
    deadline = time.monotonic() + 10.0
    while (server._model_watch.swaps < 1
           and time.monotonic() < deadline):
        time.sleep(0.02)
    time.sleep(0.2)
    stop.set()
    for t in threads:
        t.join(timeout=20)

    assert not errors, f"dropped {len(errors)} request(s): {errors[:3]}"
    assert server._model_watch.swaps >= 1
    for r in results:
        assert (np.array_equal(r, old) or np.array_equal(r, new)), \
            "a predict observed a mid-swap engine"
    # and post-swap serving equals the published model exactly
    np.testing.assert_array_equal(server.predict(Xq), new)


def test_service_swap_plus_eviction_race_zero_drops(tmp_path):
    """The satellite race: mid-traffic hot-swap AND LRU eviction churn
    (1-model cache, two tenants) through the service — every future
    resolves, the swap lands, evictions happen."""
    X, y = _data(seed=4)
    obs.enable(metrics=True)
    bA = lgb.train(PARAMS, lgb.Dataset(X, label=y), num_boost_round=4)
    bB = lgb.train(dict(PARAMS, seed=1), lgb.Dataset(X, label=y),
                   num_boost_round=4)
    stage = _stage_checkpoint(X, y, tmp_path)
    pub = str(tmp_path / "pub")
    os.makedirs(pub)
    svc = _service(tpu_serve_batch_budget_ms=1.0,
                   tpu_serve_cache_models=1)
    try:
        svc.add_model("a", bA, watch_dir=pub, watch_interval=0.0)
        svc.add_model("b", bB)
        svc.warmup("a", X[:1])
        svc.warmup("b", X[:1])
        done, errors = [], []
        stop = threading.Event()

        def client(seed):
            rng = np.random.default_rng(seed)
            while not stop.is_set():
                mid = "a" if rng.integers(0, 2) else "b"
                try:
                    done.append(svc.predict(mid, X[:32], timeout=30))
                except Exception as e:  # noqa: BLE001 - a drop IS the bug
                    errors.append(e)

        threads = [threading.Thread(target=client, args=(i,),
                                    daemon=True) for i in range(3)]
        for t in threads:
            t.start()
        time.sleep(0.2)
        _publish(stage, pub)
        time.sleep(0.5)
        stop.set()
        for t in threads:
            t.join(timeout=20)
        assert not errors, f"dropped {len(errors)}: {errors[:3]}"
        assert done and all(np.shape(d)[0] == 32 for d in done)
        assert bA._model_watch.swaps >= 1
        assert obs.registry().get("serve.evictions").value >= 1.0
    finally:
        svc.close()
