"""End-to-end training tests (mirrors reference test_engine.py style:
metric-threshold assertions on synthetic data)."""
import numpy as np
import pytest

import lightgbm_tpu as lgb


def _binary_data(n=4000, f=10, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f))
    w = rng.normal(size=f)
    logit = X @ w + 0.5 * X[:, 0] * X[:, 1]
    y = (logit + rng.normal(scale=0.5, size=n) > 0).astype(np.float64)
    return X, y


def _regression_data(n=4000, f=10, seed=1):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f))
    w = rng.normal(size=f)
    y = X @ w + np.sin(2 * X[:, 0]) + rng.normal(scale=0.1, size=n)
    return X, y


def test_binary_auc_threshold():
    X, y = _binary_data()
    ds = lgb.Dataset(X[:3000], label=y[:3000])
    vs = ds.create_valid(X[3000:], label=y[3000:])
    res = {}
    bst = lgb.train(
        {"objective": "binary", "num_leaves": 31, "metric": "auc",
         "verbosity": -1}, ds, num_boost_round=30, valid_sets=[vs],
        callbacks=[lgb.record_evaluation(res)])
    auc = res["valid_0"]["auc"][-1]
    assert auc > 0.92
    # AUC improves over training
    assert res["valid_0"]["auc"][-1] > res["valid_0"]["auc"][0]


def test_regression_l2_threshold():
    X, y = _regression_data()
    ds = lgb.Dataset(X[:3000], label=y[:3000])
    vs = ds.create_valid(X[3000:], label=y[3000:])
    res = {}
    lgb.train({"objective": "regression", "num_leaves": 31,
               "metric": "l2", "verbosity": -1}, ds, num_boost_round=50,
              valid_sets=[vs], callbacks=[lgb.record_evaluation(res)])
    l2 = res["valid_0"]["l2"]
    assert l2[-1] < l2[0] * 0.3
    assert l2[-1] < np.var(y) * 0.3


def test_predict_matches_eval_score():
    X, y = _binary_data(n=2000)
    ds = lgb.Dataset(X, label=y)
    bst = lgb.train({"objective": "binary", "num_leaves": 15,
                     "verbosity": -1}, ds, num_boost_round=10)
    pred = bst.predict(X)
    raw = bst.predict(X, raw_score=True)
    np.testing.assert_allclose(pred, 1.0 / (1.0 + np.exp(-raw)), rtol=1e-5)
    assert pred.shape == (2000,)
    assert np.all((pred >= 0) & (pred <= 1))


def test_early_stopping():
    X, y = _binary_data()
    ds = lgb.Dataset(X[:3000], label=y[:3000])
    vs = ds.create_valid(X[3000:], label=y[3000:])
    bst = lgb.train(
        {"objective": "binary", "num_leaves": 127, "metric": "auc",
         "verbosity": -1, "early_stopping_round": 3, "learning_rate": 0.5},
        ds, num_boost_round=200, valid_sets=[vs])
    assert bst.best_iteration < 200
    assert bst.num_trees() <= 200


def test_multiclass():
    rng = np.random.default_rng(7)
    n = 3000
    X = rng.normal(size=(n, 8))
    y = (np.abs(X[:, 0]) + np.abs(X[:, 1]) * 2).astype(np.int64) % 3
    ds = lgb.Dataset(X[:2000], label=y[:2000])
    vs = ds.create_valid(X[2000:], label=y[2000:])
    res = {}
    bst = lgb.train({"objective": "multiclass", "num_class": 3,
                     "num_leaves": 15, "verbosity": -1},
                    ds, num_boost_round=20, valid_sets=[vs],
                    callbacks=[lgb.record_evaluation(res)])
    pred = bst.predict(X[2000:])
    assert pred.shape == (1000, 3)
    np.testing.assert_allclose(pred.sum(axis=1), 1.0, rtol=1e-4)
    acc = (np.argmax(pred, axis=1) == y[2000:]).mean()
    assert acc > 0.55
    assert res["valid_0"]["multi_logloss"][-1] < np.log(3)


def test_feature_importance():
    X, y = _regression_data(n=2000, f=6)
    # make feature 0 dominant
    y = y + 5 * X[:, 0]
    ds = lgb.Dataset(X, label=y)
    bst = lgb.train({"objective": "regression", "num_leaves": 15,
                     "verbosity": -1}, ds, num_boost_round=10)
    imp_split = bst.feature_importance("split")
    imp_gain = bst.feature_importance("gain")
    assert imp_split.shape == (6,)
    assert imp_gain.argmax() == 0
    assert imp_split.sum() > 0


def test_bagging_and_feature_fraction():
    X, y = _binary_data(n=3000)
    ds = lgb.Dataset(X[:2000], label=y[:2000])
    vs = ds.create_valid(X[2000:], label=y[2000:])
    res = {}
    lgb.train({"objective": "binary", "num_leaves": 31, "metric": "auc",
               "bagging_fraction": 0.5, "bagging_freq": 1,
               "feature_fraction": 0.7, "verbosity": -1},
              ds, num_boost_round=30, valid_sets=[vs],
              callbacks=[lgb.record_evaluation(res)])
    assert res["valid_0"]["auc"][-1] > 0.88


def test_weights_respected():
    rng = np.random.default_rng(9)
    n = 2000
    X = rng.normal(size=(n, 4))
    y = (X[:, 0] > 0).astype(np.float64)
    # weight only the first half; second half labels are flipped noise
    y[n // 2:] = 1 - y[n // 2:]
    w = np.concatenate([np.ones(n // 2), np.zeros(n // 2) + 1e-6])
    ds = lgb.Dataset(X, label=y, weight=w)
    bst = lgb.train({"objective": "binary", "num_leaves": 7,
                     "verbosity": -1, "min_sum_hessian_in_leaf": 1e-9},
                    ds, num_boost_round=10)
    pred = bst.predict(X[:n // 2])
    acc = ((pred > 0.5) == (y[:n // 2] > 0)).mean()
    assert acc > 0.95


def test_rollback_one_iter():
    X, y = _binary_data(n=1000)
    ds = lgb.Dataset(X, label=y)
    bst = lgb.train({"objective": "binary", "num_leaves": 7,
                     "verbosity": -1}, ds, num_boost_round=5)
    assert bst.num_trees() == 5
    bst.rollback_one_iter()
    assert bst.num_trees() == 4


def test_regression_l1_and_huber_objectives():
    X, y = _regression_data(n=2000)
    for obj in ("regression_l1", "huber", "fair"):
        ds = lgb.Dataset(X[:1500], label=y[:1500])
        vs = ds.create_valid(X[1500:], label=y[1500:])
        res = {}
        lgb.train({"objective": obj, "num_leaves": 15, "metric": "l1",
                   "verbosity": -1}, ds, num_boost_round=40,
                  valid_sets=[vs], callbacks=[lgb.record_evaluation(res)])
        l1 = res["valid_0"]["l1"]
        assert l1[-1] < l1[0], obj


def test_poisson_objective():
    rng = np.random.default_rng(11)
    n = 2000
    X = rng.normal(size=(n, 5))
    lam = np.exp(0.5 * X[:, 0] - 0.3 * X[:, 1])
    y = rng.poisson(lam).astype(np.float64)
    ds = lgb.Dataset(X, label=y)
    bst = lgb.train({"objective": "poisson", "num_leaves": 15,
                     "verbosity": -1}, ds, num_boost_round=30)
    pred = bst.predict(X)
    assert np.all(pred > 0)
    assert np.corrcoef(pred, lam)[0, 1] > 0.7


def test_cv():
    X, y = _binary_data(n=1500)
    ds = lgb.Dataset(X, label=y)
    res = lgb.cv({"objective": "binary", "num_leaves": 15, "metric": "auc",
                  "verbosity": -1}, ds, num_boost_round=10, nfold=3)
    assert len(res["valid auc-mean"]) == 10
    assert res["valid auc-mean"][-1] > 0.85


def test_init_score_offset():
    X, y = _regression_data(n=1000)
    init = np.full(len(y), 100.0)
    ds = lgb.Dataset(X, label=y + 100.0, init_score=init)
    bst = lgb.train({"objective": "regression", "num_leaves": 15,
                     "boost_from_average": False, "verbosity": -1},
                    ds, num_boost_round=20)
    # prediction does not include user init_score (reference semantics),
    # so preds approximate y (the residual target)
    pred = bst.predict(X)
    assert np.mean((pred - y) ** 2) < np.var(y)


def test_nan_handling():
    X, y = _binary_data(n=2000)
    X = X.copy()
    X[::5, 0] = np.nan
    ds = lgb.Dataset(X[:1500], label=y[:1500])
    vs = ds.create_valid(X[1500:], label=y[1500:])
    res = {}
    lgb.train({"objective": "binary", "num_leaves": 15, "metric": "auc",
               "verbosity": -1}, ds, num_boost_round=20, valid_sets=[vs],
              callbacks=[lgb.record_evaluation(res)])
    assert res["valid_0"]["auc"][-1] > 0.85


def test_lambdarank_end_to_end():
    """Pins the engine-level lambdarank path (a setup_queries/prepare
    ordering bug once silently cleared the label-gain table)."""
    rng = np.random.default_rng(11)
    n_q, per_q = 50, 20
    X = rng.normal(size=(n_q * per_q, 6))
    y = np.minimum(np.clip(X[:, 0] * 1.5
                           + rng.normal(scale=0.4, size=len(X)),
                           0, None).astype(int), 4)
    group = np.full(n_q, per_q)
    n_tr = 40 * per_q
    ds = lgb.Dataset(X[:n_tr], label=y[:n_tr], group=group[:40])
    vs = ds.create_valid(X[n_tr:], label=y[n_tr:], group=group[40:])
    res = {}
    lgb.train({"objective": "lambdarank", "num_leaves": 15,
               "metric": "ndcg", "ndcg_eval_at": [5], "verbosity": -1},
              ds, num_boost_round=30, valid_sets=[vs],
              callbacks=[lgb.record_evaluation(res)])
    ndcg = res["valid_0"]["ndcg@5"]
    assert ndcg[-1] > 0.7
    assert ndcg[-1] > ndcg[0]
