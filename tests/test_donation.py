"""Buffer donation (``tpu_donate``; docs/perf.md "Iteration floor").

The donation pass aliases the boosting carries in place
(``jax.jit(donate_argnums=...)`` on the per-step / fused-chunk /
valid-update / streamed-final-sweep jits) instead of copying them
through every dispatch. Donation changes WHERE the output lives, never
what it is — so the whole pass is pinned by bit-identity:

- donation-on vs donation-off models are BIT-IDENTICAL across
  {per-iter, fused-chunk, sharded, streamed} x {plain, GOSS,
  quantized};
- valid-set score carries donate too: eval trajectories and the
  early-stop decision match exactly;
- enabling donation adds ZERO XLA programs (CompileWatch: warm
  donated iterations compile nothing, and a donated cold train
  requests no more compiles than an undonated one);
- the ``tpu_debug_checks`` use-after-donate guard turns the latent
  "Array has been deleted" crash of a stale score reference into a
  LightGBMError naming the donating site (the runtime twin of the
  donation-discipline linter, docs/static-analysis.md).

PROCESS SPLIT (the shape of this file): every donate-TRUE arm runs in
ONE fresh subprocess (tests/_donation_worker.py — no persistent
compilation cache, 8 fake CPU devices like conftest) whose artifacts
come back through a pickle; this process trains only the cache-safe
donate-FALSE arms and compares. Rationale in the worker's docstring:
donation + persistent compile cache corrupts this jaxlib's CPU client
natively, and even toggling the cache config around in-process
donating dispatches proved crashy — so no donating dispatch ever runs
in the pytest process. ``donation_enabled`` enforces the same rule for
production (the stand-down test below).
"""
import os
import pathlib
import pickle
import subprocess
import sys
import tempfile

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.boosting.gbdt import GBDT
from lightgbm_tpu.config import Config
from lightgbm_tpu.utils.debug import CompileWatch, donation_enabled

from _donation_worker import (MODES, N_ROUNDS, VALID_ROUNDS, VARIANTS,
                              make_data, params_for)

_WORKER = str(pathlib.Path(__file__).resolve().parent
              / "_donation_worker.py")


@pytest.fixture(scope="module")
def donated(tmp_path_factory):
    """Artifacts of every donate-true arm, from ONE clean worker run."""
    out = tmp_path_factory.mktemp("donation") / "worker.pkl"
    env = dict(os.environ)
    env.pop("JAX_COMPILATION_CACHE_DIR", None)  # the unsafe combination
    flags = env.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    proc = subprocess.run(
        [sys.executable, _WORKER, str(out)],
        capture_output=True, text=True, env=env, timeout=560)
    assert proc.returncode == 0, (
        f"donation worker failed (rc={proc.returncode}) — a crash here "
        f"is the donated-dispatch instability this split exists to "
        f"contain:\n{proc.stderr[-3000:]}")
    with open(out, "rb") as f:
        return pickle.load(f)


def test_worker_ran_with_donation_live(donated):
    """The A/B is only real if the worker actually donated: the config
    resolved to enabled and the client deleted a donated input."""
    assert donated["donation_enabled_true"]
    assert donated["probe_input_deleted"]


def test_true_stands_down_under_persistent_cache_off_tpu():
    """The known-bad combo is refused, not crashed on: forcing
    donation on a non-TPU backend while a persistent compilation cache
    is configured (as it is for this very test suite, via conftest)
    warns and stays off — which is why the donate-true arms live in
    the cache-less worker subprocess."""
    import jax
    assert jax.default_backend() != "tpu"
    assert jax.config.jax_compilation_cache_dir  # conftest set it
    cfg = Config({"objective": "binary", "tpu_donate": "true",
                  "verbosity": -1})
    assert not donation_enabled(cfg)
    cfg_off = Config({"objective": "binary", "tpu_donate": "false",
                      "verbosity": -1})
    assert not donation_enabled(cfg_off)


@pytest.mark.parametrize("variant", sorted(VARIANTS))
@pytest.mark.parametrize("mode", sorted(MODES))
def test_bit_identical_donation_on_off(donated, mode, variant):
    X, y = make_data()
    p = params_for({**MODES[mode], **VARIANTS[variant]}, "false")
    m_off = lgb.train(p, lgb.Dataset(X, label=y),
                      num_boost_round=N_ROUNDS)
    ref = donated["combos"][f"{mode}-{variant}"]
    assert np.array_equal(ref["pred"],
                          m_off.predict(X, raw_score=True))
    assert ref["model"] == m_off.model_to_string()


def test_valid_scores_donation_matches_eval_trajectory(donated):
    # valid carries ride _valid_update_j's donated list (the per-iter
    # path: valid sets disable fusion); the recorded eval trajectory
    # and the early-stop decision must be unchanged
    Xt, yt = make_data(seed=3)
    Xv, yv = make_data(n=1024, seed=4)
    rec = {}
    ds = lgb.Dataset(Xt, label=yt)
    bst = lgb.train(
        params_for({"metric": "binary_logloss"}, "false"), ds,
        num_boost_round=VALID_ROUNDS,
        valid_sets=[lgb.Dataset(Xv, label=yv, reference=ds)],
        valid_names=["v"],
        callbacks=[lgb.record_evaluation(rec),
                   lgb.early_stopping(5, verbose=False)])
    assert donated["valid"]["record"] == rec
    assert donated["valid"]["best_iteration"] == bst.best_iteration


def test_donation_adds_zero_programs(donated):
    """The compile pin, both halves: warm donated iterations compiled
    NOTHING in the worker, and the donated cold train requested no
    more compiles than this process's undonated twin (donation aliases
    buffers inside the same programs — it must never introduce one;
    compile REQUESTS count persistent-cache hits too, so the two
    processes' counts compare like-for-like)."""
    assert donated["compile_true_warm"] == 0
    X, y = make_data(seed=5)
    eng = GBDT(Config(params_for({"tpu_fuse_iters": 4}, "false")),
               lgb.Dataset(X, label=y))
    with CompileWatch("cold undonated") as w:
        eng.train_chunk(8)
    assert donated["compile_true_cold"] <= w.compiles, (
        f"enabling donation added programs: "
        f"{donated['compile_true_cold']} compile request(s) donated "
        f"vs {w.compiles} undonated")


def test_use_after_donate_guard_fires_on_stale_score(donated):
    """tpu_debug_checks turned the stale-reference crash into an error
    naming the donating site (observed in the worker): re-feeding a
    score buffer the previous iteration already donated failed with
    the guard's message, not XLA's generic deleted-array error."""
    assert donated["stale_deleted"]
    assert donated["guard_fired"]
    assert "use-after-donate" in donated["guard_message"]
    assert "the step's donated score" in donated["guard_message"]


def test_guard_silent_without_donate():
    # tpu_donate=false: the same stale-rebind is harmless (no buffer
    # was deleted), so training proceeds — the no-donate arm keeps
    # today's copy semantics
    X, y = make_data(seed=7)
    p = params_for({"tpu_debug_checks": True}, "false")
    eng = GBDT(Config(p), lgb.Dataset(X, label=y))
    s0 = eng.score
    eng.train_one_iter()
    assert not s0.is_deleted()
    eng.score = s0
    eng.train_one_iter()          # re-boosting from the old score is
    assert eng.num_trees() == 2   # numerically odd but not a crash
