"""Linear trees (reference: linear_tree_learner.cpp semantics — tree
structure from the standard learner, leaves refined to ridge-regularized
linear models over path features)."""
import numpy as np

import lightgbm_tpu as lgb


def _piecewise_linear(n=4000, seed=0):
    """Target is piecewise LINEAR in x0 — constant leaves need many
    splits, linear leaves nail it with a few."""
    rng = np.random.default_rng(seed)
    X = rng.uniform(-2, 2, size=(n, 4))
    y = (np.where(X[:, 0] > 0, 2.0 * X[:, 0], -1.0 * X[:, 0])
         + 0.5 * X[:, 1] + rng.normal(scale=0.05, size=n))
    return X, y


def test_linear_tree_beats_constant_leaves():
    X, y = _piecewise_linear()
    Xtr, Xte, ytr, yte = X[:3000], X[3000:], y[:3000], y[3000:]
    mses = {}
    for lin in (False, True):
        bst = lgb.train(
            {"objective": "regression", "num_leaves": 7,
             "verbosity": -1, "linear_tree": lin, "linear_lambda": 0.01,
             "learning_rate": 0.3},
            lgb.Dataset(Xtr, label=ytr), num_boost_round=30)
        mses[lin] = float(np.mean((bst.predict(Xte) - yte) ** 2))
    # on piecewise-linear data, linear leaves at 7-leaf trees must beat
    # constant leaves decisively
    assert mses[True] < 0.5 * mses[False], mses
    assert mses[True] < 0.02


def test_linear_tree_model_text_roundtrip(tmp_path):
    X, y = _piecewise_linear(n=2000, seed=1)
    bst = lgb.train(
        {"objective": "regression", "num_leaves": 7, "verbosity": -1,
         "linear_tree": True}, lgb.Dataset(X, label=y),
        num_boost_round=5)
    s = bst.model_to_string()
    assert "is_linear=1" in s
    assert "leaf_coeff=" in s
    bst2 = lgb.Booster(model_str=s)
    np.testing.assert_allclose(bst.predict(X), bst2.predict(X),
                               rtol=1e-5, atol=1e-6)


def test_linear_tree_nan_falls_back_to_constant():
    X, y = _piecewise_linear(n=2000, seed=2)
    # make feature 0 sometimes-NaN so mappers keep a NaN bin
    X[::17, 0] = np.nan
    bst = lgb.train(
        {"objective": "regression", "num_leaves": 7, "verbosity": -1,
         "linear_tree": True}, lgb.Dataset(X, label=y),
        num_boost_round=5)
    Xq = X[:50].copy()
    Xq[:, 0] = np.nan
    p = bst.predict(Xq)
    assert np.all(np.isfinite(p))


def test_linear_tree_valid_eval_consistent():
    X, y = _piecewise_linear(n=3000, seed=3)
    ds = lgb.Dataset(X[:2400], label=y[:2400],
                     params={"linear_tree": True})
    vs = ds.create_valid(X[2400:], label=y[2400:])
    res = {}
    bst = lgb.train(
        {"objective": "regression", "num_leaves": 7, "metric": "l2",
         "verbosity": -1, "linear_tree": True, "learning_rate": 0.3},
        ds, num_boost_round=30,
        valid_sets=[vs], callbacks=[lgb.record_evaluation(res)])
    # recorded valid l2 must match a fresh predict on the same rows
    pred = bst.predict(X[2400:])
    l2 = float(np.mean((pred - y[2400:]) ** 2))
    assert abs(res["valid_0"]["l2"][-1] - l2) < 1e-3
    assert l2 < 0.02


def test_linear_tree_binary():
    rng = np.random.default_rng(4)
    X = rng.uniform(-2, 2, size=(3000, 5))
    y = (1.5 * X[:, 0] + X[:, 1] + rng.normal(scale=0.3, size=3000) > 0)
    bst = lgb.train(
        {"objective": "binary", "num_leaves": 7, "verbosity": -1,
         "linear_tree": True}, lgb.Dataset(X, label=y.astype(float)),
        num_boost_round=10)
    assert np.mean((bst.predict(X) > 0.5) == y) > 0.93


def test_linear_tree_continuation(tmp_path):
    """init_model continuation keeps the linear leaf payload (Tree.rebin
    carries coefficients; the base score is rebuilt host-side)."""
    X, y = _piecewise_linear(n=2000, seed=5)
    params = {"objective": "regression", "num_leaves": 7,
              "verbosity": -1, "linear_tree": True,
              "learning_rate": 0.3}
    p = str(tmp_path / "lin.txt")
    lgb.train(params, lgb.Dataset(X, label=y),
              num_boost_round=5).save_model(p)
    cont = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=5,
                     init_model=p)
    straight = lgb.train(params, lgb.Dataset(X, label=y),
                         num_boost_round=10)
    np.testing.assert_allclose(
        cont.predict(X), straight.predict(X), rtol=1e-3, atol=1e-3)
