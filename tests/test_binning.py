"""BinMapper oracle tests (reference behavior: src/io/bin.cpp)."""
import numpy as np
import pytest

from lightgbm_tpu.io.binning import (BIN_TYPE_CATEGORICAL, BinMapper,
                                     MISSING_NAN, MISSING_NONE,
                                     find_bin_mappers)


def test_uniform_bins_cover_all_values():
    rng = np.random.default_rng(0)
    v = rng.normal(size=10000)
    m = BinMapper.from_sample(v, len(v), max_bin=255)
    bins = m.values_to_bins(v)
    assert bins.min() >= 0
    assert bins.max() < m.num_bin
    # bins are monotone in value
    order = np.argsort(v)
    assert np.all(np.diff(bins[order]) >= 0)


def test_bin_counts_roughly_equal():
    rng = np.random.default_rng(1)
    v = rng.uniform(size=100000)
    m = BinMapper.from_sample(v, len(v), max_bin=64)
    bins = m.values_to_bins(v)
    counts = np.bincount(bins, minlength=m.num_bin)
    nonzero = counts[counts > 0]
    # greedy equal-mass binning: no bin more than ~4x the mean
    assert nonzero.max() < 4 * nonzero.mean()
    assert m.num_bin <= 64


def test_distinct_values_get_own_bins():
    v = np.repeat([1.0, 2.0, 5.0, 9.0], 100)
    m = BinMapper.from_sample(v, len(v), max_bin=255, min_data_in_bin=3)
    bins = m.values_to_bins(np.array([1.0, 2.0, 5.0, 9.0]))
    assert len(set(bins.tolist())) == 4


def test_zero_gets_own_bin():
    rng = np.random.default_rng(2)
    v = np.where(rng.uniform(size=5000) < 0.5, 0.0,
                 rng.normal(size=5000))
    m = BinMapper.from_sample(v, len(v), max_bin=32)
    zb = m.value_to_bin(0.0)
    # tiny values on either side of zero bin separate from it
    assert m.value_to_bin(-0.5) < zb or m.value_to_bin(0.5) > zb
    bins = m.values_to_bins(v)
    zero_rows = np.abs(v) <= 1e-35
    assert np.all(bins[zero_rows] == zb)


def test_nan_bin_is_last():
    rng = np.random.default_rng(3)
    v = rng.normal(size=1000)
    v[::7] = np.nan
    m = BinMapper.from_sample(v, len(v), max_bin=32)
    assert m.missing_type == MISSING_NAN
    bins = m.values_to_bins(v)
    assert np.all(bins[::7] == m.num_bin - 1)
    assert np.all(bins[~np.isnan(v)] < m.num_bin - 1)


def test_no_nan_no_nan_bin():
    v = np.arange(100, dtype=np.float64)
    m = BinMapper.from_sample(v, len(v), max_bin=300, min_data_in_bin=1)
    assert m.missing_type == MISSING_NONE


def test_trivial_constant_feature():
    v = np.full(100, 3.0)
    m = BinMapper.from_sample(v, len(v), max_bin=32)
    assert m.is_trivial


def test_categorical_mapping():
    rng = np.random.default_rng(4)
    v = rng.choice([2, 5, 7, 11], size=1000,
                   p=[0.5, 0.3, 0.15, 0.05]).astype(np.float64)
    m = BinMapper.from_sample(v, len(v), max_bin=32, is_categorical=True)
    assert m.bin_type == BIN_TYPE_CATEGORICAL
    bins = m.values_to_bins(v)
    # most frequent category gets bin 1
    assert m.cat_to_bin[2] == 1
    assert np.all(bins > 0)
    # unseen category and nan -> bin 0
    assert m.values_to_bins(np.array([999.0]))[0] == 0
    assert m.values_to_bins(np.array([np.nan]))[0] == 0


def test_categorical_rare_tail_pruned():
    rng = np.random.default_rng(5)
    common = rng.choice([1, 2, 3], size=990).astype(np.float64)
    rare = np.arange(100, 110, dtype=np.float64)
    v = np.concatenate([common, rare])
    m = BinMapper.from_sample(v, len(v), max_bin=256, is_categorical=True)
    # 99% mass cut prunes the singleton tail
    assert m.num_bin <= 5


def test_find_bin_mappers_sampling():
    rng = np.random.default_rng(6)
    X = rng.normal(size=(5000, 3))
    mappers = find_bin_mappers(X, max_bin=64, sample_cnt=1000, seed=7)
    assert len(mappers) == 3
    for m in mappers:
        assert 2 <= m.num_bin <= 64


def test_max_bin_by_feature():
    rng = np.random.default_rng(8)
    X = rng.normal(size=(2000, 2))
    mappers = find_bin_mappers(X, max_bin=64,
                               max_bin_by_feature=[8, 0])
    assert mappers[0].num_bin <= 8
    assert mappers[1].num_bin <= 64


def test_wide_bins_1000_end_to_end():
    """VERDICT r4 item 8: max_bin > 255 (uint16 bin storage, B > 256
    histograms) must train, predict and round-trip end to end. On TPU
    this shape takes the XLA einsum histogram path — the Pallas kernel
    is a documented <=256-bin fast path (README capability matrix) —
    and the compaction kernel's uint16 variant runs off-TPU; semantics
    must be identical either way."""
    import lightgbm_tpu as lgb
    rng = np.random.default_rng(17)
    n = 30_000
    X = rng.normal(size=(n, 6))
    X[:, 0] = rng.integers(0, 3000, size=n) / 3.0   # >255 distinct
    y = ((X[:, 0] > 400) ^ (X[:, 1] > 0)).astype(np.float64)
    ds = lgb.Dataset(X, label=y, params={"max_bin": 1000})
    ds.construct()
    assert ds.binned.dtype == np.uint16
    assert max(m.num_bin for m in ds.bin_mappers) > 256
    bst = lgb.train({"objective": "binary", "num_leaves": 31,
                     "max_bin": 1000, "verbosity": -1},
                    ds, num_boost_round=10)
    pred = bst.predict(X)
    assert np.mean((pred > 0.5) == y) > 0.9
    s = bst.model_to_string()
    np.testing.assert_allclose(
        lgb.Booster(model_str=s).predict(X), pred, rtol=1e-5,
        atol=1e-7)


def test_wide_bins_1000_with_goss_and_quantized():
    """The sampling and quantized paths compose with uint16 bins."""
    import lightgbm_tpu as lgb
    rng = np.random.default_rng(18)
    n = 20_000
    X = rng.normal(size=(n, 5))
    X[:, 0] = rng.integers(0, 2000, size=n).astype(float)
    y = (X[:, 0] > 1000).astype(np.float64)
    bst = lgb.train({"objective": "binary", "num_leaves": 15,
                     "max_bin": 1000, "verbosity": -1,
                     "data_sample_strategy": "goss",
                     "use_quantized_grad": True},
                    lgb.Dataset(X, label=y, params={"max_bin": 1000}),
                    num_boost_round=8)
    assert np.mean((bst.predict(X) > 0.5) == y) > 0.95
