"""Worker process for the 2-process localhost multi-host test.

Usage: python _multihost_worker.py RANK NPROC PORT OUT_MODEL

RANK >= 0: join a ``jax.distributed`` job of NPROC localhost processes
(the reference's own distributed test strategy — spawning local CLI
processes against 127.0.0.1 sockets, tests/distributed/_test_distributed
.py per SURVEY.md §4) and train ``tree_learner=data`` with THIS
process's row shard only, binned against a shared reference dataset.
RANK == -1: single-process baseline on NPROC fake CPU devices (set via
XLA_FLAGS by the caller) over the full data — the same SPMD program on
the same global array, so results must match the multi-process run.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))          # repo root -> lightgbm_tpu


# single source of truth for the multihost tests (test_multihost.py
# imports both, so the worker baseline and the launcher run can never
# drift onto different data/configs)
PARAMS = {"objective": "binary", "num_leaves": 15,
          "min_data_in_leaf": 20, "verbosity": -1,
          "tree_learner": "data", "tpu_double_precision_hist": True}

# GOSS variant (VERDICT r4 item 7: exact subset counts at any process
# count) — the same SPMD program must produce identical models multi-
# process vs single-process-fake-devices, which only holds if the
# per-shard GOSS k_top/k_rand tables agree exactly
GOSS_PARAMS = dict(PARAMS, data_sample_strategy="goss",
                   top_rate=0.35, other_rate=0.25)


def make_data():
    import numpy as np
    rng = np.random.default_rng(0)
    n, f = 4096, 8
    X = rng.normal(size=(n, f))
    y = (X[:, 0] + 0.5 * X[:, 1]
         + rng.normal(scale=0.3, size=n) > 0).astype(float)
    return X, y


def collectives_probe_child(port, q):
    """Child body for the multiprocess-collectives capability probe
    (conftest.py's ``multiprocess_collectives`` fixture): join a bare
    2-process ``jax.distributed`` job and run one allgather. Lives in
    this side-effect-free module so ``spawn`` can re-import it without
    dragging pytest/conftest (whose import would initialize the jax
    backend BEFORE ``jax.distributed.initialize``) into the child."""
    try:
        import jax
        jax.config.update("jax_platforms", "cpu")
        rank = int(os.environ.pop("_LGBM_PROBE_RANK"))
        jax.distributed.initialize(f"localhost:{port}", 2, rank)
        import numpy as np
        from jax.experimental import multihost_utils
        got = np.asarray(multihost_utils.process_allgather(
            np.asarray([rank], np.int64))).reshape(-1)
        q.put(("ok", sorted(got.tolist())))
    except Exception as e:
        q.put(("err", f"{type(e).__name__}: {e}"))


def main():
    rank = int(sys.argv[1])
    nproc = int(sys.argv[2])
    port = int(sys.argv[3])
    out_model = sys.argv[4]
    use_goss = len(sys.argv) > 5 and sys.argv[5] == "goss"

    import jax
    jax.config.update("jax_platforms", "cpu")   # env alone is ignored
    if rank >= 0:
        from lightgbm_tpu.parallel.multihost import init_multihost
        init_multihost(f"localhost:{port}", nproc, rank)

    import numpy as np
    import lightgbm_tpu as lgb

    X, y = make_data()
    params = dict(GOSS_PARAMS if use_goss else PARAMS)

    if rank >= 0:
        # consistent binning across processes: every process builds the
        # SAME reference dataset from the same (deterministic) sample,
        # then bins its own row shard against it — the documented
        # bin-mapper-sharing recipe (parallel/multihost.py)
        ref = lgb.Dataset(X, params=dict(params))
        ref.construct()
        n = len(X)
        blk = n // nproc
        lo, hi = rank * blk, (rank + 1) * blk
        ds = lgb.Dataset(X[lo:hi], label=y[lo:hi], reference=ref,
                         params=dict(params))
    else:
        ds = lgb.Dataset(X, label=y, params=dict(params))

    bst = lgb.train(params, ds, num_boost_round=5)
    if rank <= 0:
        with open(out_model, "w") as fh:
            fh.write(bst.model_to_string())
    print(f"worker rank={rank} done", flush=True)


if __name__ == "__main__":
    main()
