"""Quantized gradients (use_quantized_grad; reference:
cuda_gradient_discretizer.cu semantics — int grad/hess levels with
stochastic rounding, histograms in integer units, rescale at use)."""
import numpy as np

import lightgbm_tpu as lgb


def _binary_data(n=5000, f=10, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f))
    w = rng.normal(size=f)
    y = (X @ w + rng.normal(scale=0.5, size=n) > 0).astype(np.float64)
    return X, y


def _auc(y, p):
    order = np.argsort(p)
    ranks = np.empty(len(p)); ranks[order] = np.arange(1, len(p) + 1)
    pos = y > 0
    return (ranks[pos].sum() - pos.sum() * (pos.sum() + 1) / 2) \
        / (pos.sum() * (~pos).sum())


def test_quantized_close_to_full_precision():
    X, y = _binary_data()
    Xtr, Xte, ytr, yte = X[:4000], X[4000:], y[:4000], y[4000:]
    aucs = {}
    for quant in (False, True):
        bst = lgb.train(
            {"objective": "binary", "num_leaves": 31, "verbosity": -1,
             "use_quantized_grad": quant, "num_grad_quant_bins": 8},
            lgb.Dataset(Xtr, label=ytr), num_boost_round=30)
        aucs[quant] = _auc(yte, bst.predict(Xte))
    assert aucs[True] > 0.9
    assert abs(aucs[True] - aucs[False]) < 0.01


def test_quantized_4bins_still_learns():
    X, y = _binary_data(n=3000, seed=1)
    bst = lgb.train(
        {"objective": "binary", "num_leaves": 15, "verbosity": -1,
         "use_quantized_grad": True, "num_grad_quant_bins": 4},
        lgb.Dataset(X, label=y), num_boost_round=25)
    assert _auc(y, bst.predict(X)) > 0.9


def test_quantized_renew_leaf_exact_outputs():
    """quant_train_renew_leaf re-derives leaf outputs from FULL-precision
    gradients: first-iteration leaf values must equal the unquantized
    optimum -sum(g)/sum(h) * lr exactly (not the quantized estimate)."""
    rng = np.random.default_rng(2)
    X = rng.normal(size=(3000, 8))
    y = X @ rng.normal(size=8) + rng.normal(scale=0.1, size=3000)
    bst = lgb.train(
        {"objective": "regression", "num_leaves": 15, "verbosity": -1,
         "use_quantized_grad": True, "num_grad_quant_bins": 4,
         "quant_train_renew_leaf": True},
        lgb.Dataset(X, label=y), num_boost_round=1)
    eng = bst.engine
    t = eng.models[0]
    g = eng.init_scores[0] - y            # L2 gradient at the init score
    leaf = t.predict_leaf_raw(X[:, eng.train_set.used_features])
    for lf in range(t.num_leaves):
        m = leaf == lf
        opt = -g[m].sum() / m.sum() * 0.1
        assert abs(float(t.leaf_value[lf]) - opt) < 1e-4, lf
    # and 4-bin quantized + renewal still trains a usable model
    bst30 = lgb.train(
        {"objective": "regression", "num_leaves": 31, "verbosity": -1,
         "use_quantized_grad": True, "num_grad_quant_bins": 4,
         "quant_train_renew_leaf": True},
        lgb.Dataset(X, label=y), num_boost_round=30)
    assert float(np.mean((bst30.predict(X) - y) ** 2)) < np.var(y) * 0.2


def test_quantized_data_parallel_consistent():
    """Global pmax scaling: distributed quantized training stays close
    to single-device quantized training."""
    X, y = _binary_data(n=3000, seed=3)
    aucs = {}
    for learner in ("serial", "data"):
        bst = lgb.train(
            {"objective": "binary", "num_leaves": 15, "verbosity": -1,
             "use_quantized_grad": True, "num_grad_quant_bins": 8,
             "tree_learner": learner},
            lgb.Dataset(X, label=y), num_boost_round=15)
        aucs[learner] = _auc(y, bst.predict(X))
    assert abs(aucs["serial"] - aucs["data"]) < 0.01


def test_quantized_with_goss_and_multiclass():
    rng = np.random.default_rng(4)
    X = rng.normal(size=(3000, 8))
    y = (X[:, 0] > 0).astype(int) + (X[:, 1] > 0.5).astype(int)
    bst = lgb.train(
        {"objective": "multiclass", "num_class": 3, "num_leaves": 15,
         "verbosity": -1, "use_quantized_grad": True,
         "data_sample_strategy": "goss"},
        lgb.Dataset(X, label=y.astype(float)), num_boost_round=20)
    pred = bst.predict(X)
    assert np.mean(np.argmax(pred, axis=1) == y) > 0.85


def test_packed_wire_bit_identical_to_f32_reduce():
    """VERDICT r4 item 9: the packed int32 (g,h) collective wire must
    be BIT-IDENTICAL to the f32 reduction — integer level sums are
    exact in both, so every tree must agree. Covers both reduce
    layouts (scatter + psum) on the 8-device CPU mesh."""
    X, y = _binary_data(n=4000, seed=9)
    for reduce_mode in ("scatter", "psum"):
        models = {}
        for packed in (True, False):
            bst = lgb.train(
                {"objective": "binary", "num_leaves": 15,
                 "verbosity": -1, "use_quantized_grad": True,
                 "num_grad_quant_bins": 8, "tree_learner": "data",
                 "tpu_hist_reduce": reduce_mode,
                 "tpu_hist_packed_wire": packed},
                lgb.Dataset(X, label=y), num_boost_round=8)
            models[packed] = bst.model_to_string()
        assert models[True] == models[False], \
            f"packed wire diverged under {reduce_mode}"


def test_packed_wire_overflow_guard_falls_back():
    """When global level sums could exceed int16 the guard must route
    the round through the f32 reduce — training with a huge
    num_grad_quant_bins (level sums >> 2^15) must still match its own
    f32-wire twin and stay finite."""
    X, y = _binary_data(n=6000, seed=10)
    models = {}
    for packed in (True, False):
        bst = lgb.train(
            {"objective": "binary", "num_leaves": 7, "verbosity": -1,
             "use_quantized_grad": True,
             # 16k levels x thousands of rows per bin: guard trips
             "num_grad_quant_bins": 16384, "tree_learner": "data",
             "tpu_hist_reduce": "psum",
             "tpu_hist_packed_wire": packed},
            lgb.Dataset(X, label=y), num_boost_round=5)
        models[packed] = bst.model_to_string()
        assert np.isfinite(bst.predict(X)).all()
    assert models[True] == models[False]


def test_auto_quantize_policy(monkeypatch):
    """tpu_auto_quantize (VERDICT r4 item 2): quantized gradients turn
    on automatically in the validated regime (>=500k rows, safe
    objective), never below the size gate, and an explicit
    use_quantized_grad=false always wins."""
    from lightgbm_tpu.boosting import gbdt as gbdt_mod
    X, y = _binary_data(n=3000, seed=21)
    ds = lambda: lgb.Dataset(X, label=y)
    base = {"objective": "binary", "num_leaves": 7, "verbosity": -1}

    # below the gate: stays f32
    bst = lgb.train(dict(base), ds(), num_boost_round=2)
    assert not bst.engine.config.use_quantized_grad

    # shrink the gate: auto-quantize engages
    monkeypatch.setattr(gbdt_mod, "AUTO_QUANT_MIN_ROWS", 1000)
    bst = lgb.train(dict(base), ds(), num_boost_round=2)
    assert bst.engine.config.use_quantized_grad
    assert bst.engine.config._quantize_auto

    # explicit user setting wins over auto
    bst = lgb.train(dict(base, use_quantized_grad=False), ds(),
                    num_boost_round=2)
    assert not bst.engine.config.use_quantized_grad

    # unvalidated objective (L1 renews leaves from raw grads): stays f32
    bst = lgb.train(dict(base, objective="regression_l1"), ds(),
                    num_boost_round=2)
    assert not bst.engine.config.use_quantized_grad

    # tpu_auto_quantize=false opts out entirely
    bst = lgb.train(dict(base, tpu_auto_quantize=False), ds(),
                    num_boost_round=2)
    assert not bst.engine.config.use_quantized_grad
