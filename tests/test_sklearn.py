"""sklearn estimator API tests (mirrors reference test_sklearn.py style)."""
import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu import LGBMClassifier, LGBMRanker, LGBMRegressor


def _binary_data(n=3000, f=10, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f))
    w = rng.normal(size=f)
    y = ((X @ w + rng.normal(scale=0.5, size=n)) > 0).astype(int)
    return X, y


def test_classifier_fit_predict():
    X, y = _binary_data()
    clf = LGBMClassifier(n_estimators=20, num_leaves=15)
    clf.fit(X[:2400], y[:2400])
    pred = clf.predict(X[2400:])
    proba = clf.predict_proba(X[2400:])
    assert set(np.unique(pred)) <= {0, 1}
    assert proba.shape == (600, 2)
    np.testing.assert_allclose(proba.sum(axis=1), 1.0, rtol=1e-6)
    acc = np.mean(pred == y[2400:])
    assert acc > 0.85
    assert clf.n_features_ == 10
    assert len(clf.feature_importances_) == 10


def test_classifier_string_labels():
    X, y = _binary_data(n=1500)
    labels = np.array(["neg", "pos"])[y]
    clf = LGBMClassifier(n_estimators=10, num_leaves=7)
    clf.fit(X, labels)
    assert list(clf.classes_) == ["neg", "pos"]
    pred = clf.predict(X)
    assert set(np.unique(pred)) <= {"neg", "pos"}
    assert np.mean(pred == labels) > 0.85


def test_classifier_multiclass_auto():
    rng = np.random.default_rng(1)
    X = rng.normal(size=(2000, 8))
    y = (X[:, 0] > 0).astype(int) + (X[:, 1] > 0.5).astype(int)
    clf = LGBMClassifier(n_estimators=10, num_leaves=7)
    clf.fit(X, y)
    assert clf.n_classes_ == 3
    proba = clf.predict_proba(X)
    assert proba.shape == (2000, 3)
    assert np.mean(clf.predict(X) == y) > 0.8


def test_regressor_fit_predict_eval_set():
    rng = np.random.default_rng(2)
    X = rng.normal(size=(3000, 8))
    w = rng.normal(size=8)
    y = X @ w + rng.normal(scale=0.1, size=3000)
    reg = LGBMRegressor(n_estimators=30, num_leaves=31)
    reg.fit(X[:2400], y[:2400], eval_set=[(X[2400:], y[2400:])],
            eval_metric="l2")
    assert "valid_0" in reg.evals_result_
    l2 = reg.evals_result_["valid_0"]["l2"]
    assert l2[-1] < l2[0] * 0.3
    pred = reg.predict(X[2400:])
    assert np.mean((pred - y[2400:]) ** 2) < np.var(y) * 0.2


def test_regressor_sklearn_params_map():
    """subsample/reg_alpha/etc resolve through the alias table."""
    X, y = _binary_data(n=1000)
    reg = LGBMRegressor(n_estimators=5, num_leaves=7, subsample=0.8,
                        subsample_freq=1, colsample_bytree=0.7,
                        reg_alpha=0.1, reg_lambda=0.2, random_state=7)
    reg.fit(X, y.astype(float))
    cfg = reg.booster_.config
    assert cfg.bagging_fraction == 0.8
    assert cfg.feature_fraction == 0.7
    assert cfg.lambda_l1 == 0.1 and cfg.lambda_l2 == 0.2


def test_ranker_group():
    rng = np.random.default_rng(3)
    n_q, per_q = 40, 25
    X = rng.normal(size=(n_q * per_q, 6))
    rel = np.clip((X[:, 0] * 1.5 + rng.normal(scale=0.4,
                                              size=len(X))), 0, None)
    y = np.minimum(rel.astype(int), 4)
    group = np.full(n_q, per_q)
    n_tr = 30 * per_q
    rk = LGBMRanker(n_estimators=15, num_leaves=15)
    rk.fit(X[:n_tr], y[:n_tr], group=group[:30],
           eval_set=[(X[n_tr:], y[n_tr:])], eval_group=[group[30:]],
           eval_metric="ndcg")
    assert any(k.startswith("ndcg") for k in rk.evals_result_["valid_0"])
    with pytest.raises(lgb.LightGBMError):
        LGBMRanker().fit(X, y)   # no group


def test_class_weight_balanced():
    rng = np.random.default_rng(4)
    X = rng.normal(size=(2000, 6))
    y = (X[:, 0] + rng.normal(scale=0.3, size=2000) > 1.2).astype(int)
    clf = LGBMClassifier(n_estimators=10, num_leaves=7,
                         class_weight="balanced")
    clf.fit(X, y)
    # balanced weighting pushes the minority-class probabilities up
    clf2 = LGBMClassifier(n_estimators=10, num_leaves=7)
    clf2.fit(X, y)
    assert clf.predict_proba(X)[:, 1].mean() \
        > clf2.predict_proba(X)[:, 1].mean()


def test_sklearn_clone_and_get_params():
    from sklearn.base import clone
    clf = LGBMClassifier(n_estimators=7, num_leaves=9, min_child_samples=5)
    c2 = clone(clf)
    assert c2.get_params()["n_estimators"] == 7
    assert c2.get_params()["num_leaves"] == 9


def test_plotting_smoke(tmp_path):
    import matplotlib
    matplotlib.use("Agg")
    X, y = _binary_data(n=1000)
    clf = LGBMClassifier(n_estimators=5, num_leaves=7)
    clf.fit(X, y, eval_set=[(X, y)], eval_metric="auc")
    ax = lgb.plot_importance(clf)
    assert ax is not None
    ax2 = lgb.plot_metric(clf.evals_result_, metric="auc")
    assert ax2 is not None
