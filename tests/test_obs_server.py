"""Live metrics endpoint (lightgbm_tpu/obs/server.py).

What these tests pin:

* **Route smoke** — /metrics serves parseable Prometheus text of the
  live registry, /metrics.json the v1 snapshot schema, unknown paths
  404; all bound to 127.0.0.1 only.
* **Health semantics** — /readyz is 503 until a heartbeat is stamped,
  200 while one is fresh, 503 again when every stamp is stale (the
  wedged-loop signal); /healthz tolerates "no heartbeat yet" but fails
  on staleness.
* **Robustness** — a port already in use logs-and-disables instead of
  crashing the run; the serve thread is a daemon (cannot hang process
  exit); start_server is idempotent and process-global.
* **Acceptance** — a warm serving loop scraped mid-run reports a
  rolling slo.predict_p99_ms within one histogram-bucket width of the
  offline percentile of the same run's recorded latencies, and a
  forced breach (threshold below the observed p99) flips slo.breached
  within one evaluation period (== one scrape).
"""
import json
import socket
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu import obs
from lightgbm_tpu.obs import server as obs_server
from lightgbm_tpu.obs import slo as obs_slo


def _get(url):
    """(status, body_text) without raising on 4xx/5xx."""
    try:
        with urllib.request.urlopen(url, timeout=10) as r:
            return r.status, r.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


@pytest.fixture()
def live_server():
    obs.enable(metrics=True)
    srv = obs_server.start_server(0)     # ephemeral localhost port
    assert srv is not None
    yield srv
    obs_server.stop_server()


def test_metrics_routes_smoke(live_server):
    obs.inc("train.iterations", 7)
    obs.observe("predict/call", 0.004)

    code, text = _get(live_server.url + "/metrics")
    assert code == 200
    assert "# TYPE train_iterations counter" in text
    assert "train_iterations 7" in text
    assert "predict_call_count 1" in text

    code, body = _get(live_server.url + "/metrics.json")
    assert code == 200
    snap = json.loads(body)
    assert snap["schema"] == "lightgbm-tpu-metrics-v1"
    assert any(m["name"] == "train.iterations"
               for m in snap["metrics"])

    code, _ = _get(live_server.url + "/nope")
    assert code == 404


def test_health_and_ready_follow_heartbeats(live_server):
    # no heartbeat yet: live (the reply proves it) but NOT ready
    code, body = _get(live_server.url + "/healthz")
    assert code == 200 and json.loads(body)["status"] == "ok"
    code, body = _get(live_server.url + "/readyz")
    assert code == 503
    assert json.loads(body)["status"] == "no_heartbeat"

    obs.heartbeat("train")
    assert _get(live_server.url + "/healthz")[0] == 200
    code, body = _get(live_server.url + "/readyz")
    assert code == 200
    assert "train" in json.loads(body)["heartbeats"]

    # stale: back-date the stamp past the staleness timeout
    obs.registry().gauge("heartbeat.train").set(
        time.monotonic() - 10 * obs_server.DEFAULT_HEARTBEAT_TIMEOUT_S)
    code, body = _get(live_server.url + "/healthz")
    assert code == 503 and json.loads(body)["status"] == "stale"
    assert _get(live_server.url + "/readyz")[0] == 503
    # a fresh stamp on ANY heartbeat recovers both probes
    obs.heartbeat("serve")
    assert _get(live_server.url + "/healthz")[0] == 200
    assert _get(live_server.url + "/readyz")[0] == 200


def test_port_in_use_disables_instead_of_crashing():
    blocker = socket.socket()
    blocker.bind(("127.0.0.1", 0))
    blocker.listen(1)
    port = blocker.getsockname()[1]
    try:
        assert obs_server.start_server(port) is None
        assert obs_server.server() is None
    finally:
        blocker.close()


def test_port_in_use_raises_when_required():
    """The fleet path (serve/fleet.py) asks for required=True: a
    replica whose /metrics+/readyz cannot bind is invisible to its
    router — it must fail its launch loudly, not serve blind."""
    blocker = socket.socket()
    blocker.bind(("127.0.0.1", 0))
    blocker.listen(1)
    port = blocker.getsockname()[1]
    try:
        with pytest.raises(RuntimeError, match="REQUIRED"):
            obs_server.start_server(port, required=True)
        assert obs_server.server() is None
    finally:
        blocker.close()


def test_ephemeral_port_exposes_actually_bound_port():
    """port=0 binds an ephemeral port and the returned server's .port
    is the real one — fleet replicas bind 0 and publish what they
    got."""
    srv = obs_server.start_server(0, required=True)
    try:
        assert srv is not None and srv.port > 0
        code, _body = _get(srv.url + "/healthz")
        assert code in (200, 503)     # answering proves the port
    finally:
        obs_server.stop_server()


def test_start_server_is_idempotent_and_daemonized():
    srv = obs_server.start_server(0)
    assert srv._thread.daemon            # cannot hang process exit
    again = obs_server.start_server(srv.port + 1)   # warns, keeps first
    assert again is srv
    assert obs_server.start_server(0) is srv
    obs_server.stop_server()
    assert obs_server.server() is None
    obs_server.stop_server()             # idempotent


def _bucket_width_at(bounds, v):
    lo = 0.0
    for hi in bounds:
        if v <= hi:
            return (hi - lo) if hi != float("inf") else float("inf")
        lo = hi
    return float("inf")


def _prom_value(text, name):
    for line in text.splitlines():
        if line.startswith(name) and " " in line:
            head, val = line.rsplit(" ", 1)
            if head == name or head.startswith(name + "{"):
                return float(val)
    return None


def test_model_file_booster_serving_turns_ready(tmp_path):
    """The documented load-model-and-serve deployment: a Booster built
    from a model FILE routes predicts through the host model, which
    must carry the same serve instrumentation as the engine path —
    otherwise /readyz never turns 200 for exactly the pod /readyz was
    built for."""
    rng = np.random.default_rng(9)
    X = rng.normal(size=(800, 6))
    y = (X[:, 0] > 0).astype(np.float64)
    ds = lgb.Dataset(X, label=y)
    bst = lgb.train({"objective": "binary", "num_leaves": 7,
                     "verbosity": -1}, ds, num_boost_round=3)
    path = str(tmp_path / "m.txt")
    bst.save_model(path)

    obs.enable(metrics=True, slo=True)
    loaded = lgb.Booster(model_file=path)
    srv = obs_server.start_server(0)
    try:
        assert _get(srv.url + "/readyz")[0] == 503
        loaded.predict(X[:64])           # the documented warmup call
        assert _get(srv.url + "/readyz")[0] == 200
        assert obs.counter("predict.requests").value >= 1
        assert obs.registry().get("predict/call").count >= 1
        # pred_contrib detours through the host model on a TRAINED
        # booster too — same instrumentation
        before = obs.counter("predict.requests").value
        bst.predict(X[:16], pred_contrib=True)
        assert obs.counter("predict.requests").value == before + 1
    finally:
        obs_server.stop_server()


def test_warm_serving_scrape_reports_rolling_p99_and_breach(tmp_path):
    """ISSUE acceptance: mid-run /metrics scrape vs offline percentile
    of the same run's recorded latencies, plus a forced breach."""
    rng = np.random.default_rng(5)
    X = rng.normal(size=(1500, 8))
    y = (X[:, 0] > 0).astype(np.float64)
    ds = lgb.Dataset(X, label=y)
    # threshold far below any real predict latency -> guaranteed breach
    params = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
              "tpu_metrics": True, "tpu_slo_predict_p99_ms": 1e-6}
    bst = lgb.train(params, ds, num_boost_round=5)
    assert obs.slo_enabled()             # the threshold knob engaged it
    bst.predict(X[:256])                 # cold call: compiles
    # restart the rolling window at steady state so the one-off compile
    # latency is not in the window the offline percentile can't see
    obs_slo.reset()
    obs.enable(slo=True, slo_thresholds={"predict_p99_ms": 1e-6})
    srv = obs_server.start_server(0)
    assert srv is not None
    try:
        latencies = []
        for _ in range(40):              # warm serving loop
            t0 = time.monotonic()
            bst.predict(X[:256])
            latencies.append(time.monotonic() - t0)

        code, text = _get(srv.url + "/metrics")
        assert code == 200
        p99_ms = _prom_value(text, "slo_predict_p99_ms")
        assert p99_ms is not None
        offline_ms = float(np.percentile(latencies, 99)) * 1000.0
        bounds_ms = [b * 1000.0
                     for b in obs_slo.tracker()
                     .hists["predict/call"].bounds]
        tol = max(_bucket_width_at(bounds_ms, offline_ms),
                  _bucket_width_at(bounds_ms, p99_ms))
        assert p99_ms == pytest.approx(offline_ms, abs=tol)
        # the scrape WAS an evaluation period: the forced breach is up
        assert _prom_value(
            text, 'slo_breached{slo="predict_p99_ms"}') == 1.0
        assert _prom_value(
            text, 'slo_breaches{slo="predict_p99_ms"}') >= 1.0
        # heartbeat.serve was stamped by the predict path
        assert _get(srv.url + "/readyz")[0] == 200
    finally:
        obs_server.stop_server()
