"""Subprocess worker for tests/test_donation.py: every donate-TRUE arm.

Why a subprocess: ``tpu_donate=true`` on this jaxlib's (0.4.37) CPU
client is only stable in a process that has NEVER mixed donation with
a persistent compilation cache — warm-cache donating runs, and even
long pytest processes that toggled the cache config around donating
dispatches, intermittently corrupt the native heap (segfaults/aborts
detonating later in unrelated code: numpy binning, jit tracing,
``Config.__init__``). Cold, cache-less, donation-only processes pass
100% (reproduced at length — docs/perf.md "Iteration floor"). So the
donate-true half of every A/B runs HERE, in a fresh interpreter with
the cache env stripped by the spawner, and ships its artifacts
(model texts, raw predictions, eval trajectories, compile counts, the
use-after-donate guard observation) back through one pickle; the
pytest process trains only the cache-safe donate-false arms and
compares. A worker crash fails the donation tests loudly without
taking the other ~600 tests down with it.

Shared definitions (data synthesis, the mode x variant matrix, params)
live in this module and are imported BY the test module — one source,
no drift; this file's import side effects are numpy-only.
"""
import os
import pickle
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

N_ROUNDS = 8
VALID_ROUNDS = 12

# learning_rate 0.5 -> GOSS activates at iteration 2 of 8, so the GOSS
# variants exercise BOTH the plain and the sampled step under donation
VARIANTS = {
    "plain": {},
    "goss": {"data_sample_strategy": "goss", "learning_rate": 0.5,
             "top_rate": 0.3, "other_rate": 0.3},
    "quantized": {"use_quantized_grad": True},
}

MODES = {
    "per_iter": {"tpu_fuse_iters": 1},
    "fused_chunk": {"tpu_fuse_iters": 4},
    "sharded": {"tree_learner": "data"},
    "streamed": {"tpu_streaming": "true",
                 "tpu_stream_block_rows": 1024},
}


def make_data(n=2048, f=8, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f))
    w = rng.normal(size=f)
    y = ((X @ w + 0.6 * X[:, 0] * X[:, 1]
          + rng.normal(scale=0.5, size=n)) > 0).astype(np.float64)
    return X, y


def params_for(extra, donate):
    return {"objective": "binary", "num_leaves": 15, "verbosity": -1,
            "min_data_in_leaf": 5, "tpu_donate": donate, **extra}


def main(out_path: str) -> int:
    # the spawner strips JAX_COMPILATION_CACHE_DIR and forces the
    # 8-fake-device CPU platform (same mesh shape as tests/conftest.py,
    # so sharded-mode numerics match the in-process donate-false arm)
    assert not os.environ.get("JAX_COMPILATION_CACHE_DIR"), \
        "worker must run WITHOUT a persistent compilation cache"
    import jax
    jax.config.update("jax_platforms", "cpu")
    assert jax.device_count() == 8, jax.devices()

    import lightgbm_tpu as lgb
    from lightgbm_tpu.boosting.gbdt import GBDT
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.utils.debug import CompileWatch, donation_enabled
    from lightgbm_tpu.utils.log import LightGBMError

    out = {}

    # donation really is LIVE in this process: enabled by the config
    # resolution AND the client deletes a donated input at dispatch
    out["donation_enabled_true"] = donation_enabled(
        Config({"objective": "binary", "tpu_donate": "true",
                "verbosity": -1}))
    import jax.numpy as jnp
    probe = jax.jit(lambda x: x + 1, donate_argnums=(0,))
    a = jnp.ones((8, 8))
    probe(a)
    out["probe_input_deleted"] = bool(a.is_deleted())

    # the bit-identity matrix, donate-true halves
    X, y = make_data()
    combos = {}
    for mode, mextra in MODES.items():
        for variant, vextra in VARIANTS.items():
            p = params_for({**mextra, **vextra}, "true")
            m = lgb.train(p, lgb.Dataset(X, label=y),
                          num_boost_round=N_ROUNDS)
            combos[f"{mode}-{variant}"] = {
                "model": m.model_to_string(),
                "pred": np.asarray(m.predict(X, raw_score=True)),
            }
    out["combos"] = combos

    # valid-score donation: eval trajectory + early-stop decision
    Xt, yt = make_data(seed=3)
    Xv, yv = make_data(n=1024, seed=4)
    rec = {}
    ds = lgb.Dataset(Xt, label=yt)
    bst = lgb.train(
        params_for({"metric": "binary_logloss"}, "true"), ds,
        num_boost_round=VALID_ROUNDS,
        valid_sets=[lgb.Dataset(Xv, label=yv, reference=ds)],
        valid_names=["v"],
        callbacks=[lgb.record_evaluation(rec),
                   lgb.early_stopping(5, verbose=False)])
    out["valid"] = {"record": rec, "best_iteration": bst.best_iteration}

    # compile accounting for the zero-added-programs pin
    X5, y5 = make_data(seed=5)
    eng = GBDT(Config(params_for({"tpu_fuse_iters": 4}, "true")),
               lgb.Dataset(X5, label=y5))
    with CompileWatch("cold donated") as w_cold:
        eng.train_chunk(8)
    with CompileWatch("warm donated") as w_warm:
        eng.train_chunk(8)
    out["compile_true_cold"] = w_cold.compiles
    out["compile_true_warm"] = w_warm.compiles

    # use-after-donate guard: the stale-score read must raise the
    # guard's error, not XLA's generic deleted-array RuntimeError
    X6, y6 = make_data(seed=6)
    eng = GBDT(Config(params_for({"tpu_debug_checks": True}, "true")),
               lgb.Dataset(X6, label=y6))
    stale = eng.score
    eng.train_one_iter()
    out["stale_deleted"] = bool(stale.is_deleted())
    eng.score = stale
    try:
        eng.train_one_iter()
        out["guard_fired"] = False
        out["guard_message"] = ""
    except LightGBMError as e:
        out["guard_fired"] = True
        out["guard_message"] = str(e)

    with open(out_path, "wb") as f:
        pickle.dump(out, f)
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1]))
