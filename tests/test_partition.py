"""Leaf-ordered device row partition (tpu_hist_partition; ops/partition.py).

Contract (mirroring the GOSS-compaction one): the partitioned path
elects and applies the SAME splits as the masked full-scan path — its
span histograms sum the same per-row terms in a different accumulation
order, so trees are bit-identical under quantized gradients (integer
sums are order-free) and prediction-close under f32. Partition tables
must stay a valid leaf-contiguous layout after every split batch:
spans disjoint, counts summing to n, within-leaf source order stable.
"""
import dataclasses
import json

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu import obs
from lightgbm_tpu.ops import partition as part_ops

jnp = pytest.importorskip("jax.numpy")
import jax  # noqa: E402


@pytest.fixture(autouse=True)
def _isolated_obs():
    obs.disable()
    obs.reset()
    yield
    obs.disable()
    obs.reset()


# ---------------------------------------------------------------------------
# unit: the stable front/back move + table updates vs a numpy reference
# ---------------------------------------------------------------------------

def _np_reference_move(leaf, parents, rights):
    """Reference semantics in plain numpy: rows whose leaf id is a
    right child of this round move stably to the back; everything else
    packs stably to the front."""
    moved = np.isin(leaf, rights)
    order = np.concatenate([np.flatnonzero(~moved),
                            np.flatnonzero(moved)])
    return order, int((~moved).sum())


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_move_and_tables_invariants(seed):
    """Property-style pin over several random split batches: dest is a
    permutation, per-leaf spans stay contiguous/disjoint, offsets match
    the (offset, count) tables, counts sum to n, and within-leaf source
    order is preserved (stability)."""
    rng = np.random.default_rng(seed)
    n, L, Kb = 512, 31, 4
    leaf = np.zeros(n, np.int32)
    off = np.zeros(L + 1, np.int32)
    cnt = np.zeros(L + 1, np.int32)
    cnt[0] = n
    # a source tag per row to verify stability across rounds
    tag = np.arange(n, dtype=np.int32)
    num_leaves = 1
    for _ in range(6):
        active = [lf for lf in range(num_leaves) if cnt[lf] > 1]
        if not active or num_leaves >= L - Kb:
            break
        k = min(Kb, len(active), L - num_leaves)
        parents = np.asarray(rng.choice(active, size=k, replace=False),
                             np.int32)
        new_ids = np.arange(num_leaves, num_leaves + k, dtype=np.int32)
        valid = np.ones(k, bool)
        # route a random subset of each parent's rows to its right child
        new_leaf = leaf.copy()
        for p, nid in zip(parents, new_ids):
            rows = np.flatnonzero(leaf == p)
            take = rng.random(len(rows)) < rng.uniform(0.2, 0.8)
            new_leaf[rows[take]] = nid
        moved = new_leaf != leaf
        dest, n_front, cum = part_ops.plan_split_move(
            jnp.asarray(moved))
        dest = np.asarray(dest)
        n_front = int(n_front)
        # dest is a permutation and matches the stable reference order
        assert sorted(dest.tolist()) == list(range(n))
        order, ref_front = _np_reference_move(new_leaf, parents,
                                              new_ids.tolist())
        assert n_front == ref_front
        inv = np.empty(n, np.int64)
        inv[dest] = np.arange(n)
        np.testing.assert_array_equal(inv, order)
        off2, cnt2 = part_ops.update_tables(
            jnp.asarray(off), jnp.asarray(cnt), cum,
            jnp.asarray(n_front, jnp.int32), jnp.asarray(parents),
            jnp.asarray(new_ids), jnp.asarray(valid))
        off, cnt = np.asarray(off2).copy(), np.asarray(cnt2).copy()
        leaf = new_leaf[order]
        tag = tag[order]
        num_leaves += k
        # invariants: counts sum to n; every leaf's rows contiguous at
        # its table offset; stability (tags increasing within a leaf)
        assert int(cnt[:num_leaves].sum()) == n
        for lf in range(num_leaves):
            rows = np.flatnonzero(leaf == lf)
            assert len(rows) == cnt[lf]
            if len(rows):
                assert rows[0] == off[lf]
                assert rows[-1] == off[lf] + cnt[lf] - 1
                assert np.all(np.diff(tag[rows]) > 0)


def test_slice_spans_masks_neighbours():
    """Rows sliced from a neighbouring leaf inside a padded span get
    leaf id -1, so each row contributes to exactly one histogram lane."""
    n, F = 64, 3
    rng = np.random.default_rng(7)
    bins = jnp.asarray(rng.integers(0, 8, size=(n, F)), jnp.uint8)
    vals = jnp.asarray(rng.normal(size=(n, 2)), jnp.float32)
    leaf = np.repeat(np.asarray([0, 1, 2, 3], np.int32), 16)
    offs = jnp.asarray([16, 48], jnp.int32)      # leaves 1 and 3
    cnts = jnp.asarray([16, 16], jnp.int32)
    S = 32
    bs, vs, ls = part_ops.slice_spans(bins, vals, jnp.asarray(leaf),
                                      offs, cnts, S, False)
    assert bs.shape == (2 * S, F) and vs.shape == (2 * S, 2)
    ls = np.asarray(ls)
    # span 0 covers positions 16..47: leaf-1 rows keep their id, the
    # leaf-2 padding is sentinel-masked
    np.testing.assert_array_equal(ls[:16], 1)
    np.testing.assert_array_equal(ls[16:32], -1)
    # span 1 was clamped into range (48 + 32 > 64 -> start 32)
    np.testing.assert_array_equal(ls[32:48], -1)
    np.testing.assert_array_equal(ls[48:], 3)


def test_span_budgets_never_exceed_full_scan():
    for n in (1024, 4096, 100000):
        for m in (1, 8, 32):
            budgets = part_ops.span_budgets(n, m)
            assert all(m * s < n for s in budgets)
            assert list(budgets) == sorted(budgets)


# ---------------------------------------------------------------------------
# grow_tree: partitioned == masked, bit-for-bit
# ---------------------------------------------------------------------------

def _grow_pair(cfg_kw, n=2048, f=6, seed=0):
    from lightgbm_tpu.learner.serial import GrowConfig, grow_tree
    rng = np.random.default_rng(seed)
    bins = jnp.asarray(rng.integers(0, 32, size=(n, f)), jnp.uint8)
    g = rng.normal(size=n).astype(np.float32)
    vals = jnp.asarray(np.stack([g, np.ones(n, np.float32),
                                 np.ones(n, np.float32)], axis=1))
    nb = jnp.full(f, 32, jnp.int32)
    hn = jnp.zeros(f, bool)
    al = jnp.ones(f, bool)
    base = dict(num_leaves=31, num_bins=32, rows_per_block=256,
                min_data_in_leaf=5)
    base.update(cfg_kw)
    cfg = GrowConfig(**base)
    outs = []
    for part in (False, True):
        t, lid = grow_tree(bins, vals, nb, hn, al,
                           dataclasses.replace(cfg, partition=part))
        outs.append((jax.tree.map(np.asarray, t), np.asarray(lid)))
    return outs


@pytest.mark.parametrize("cfg_kw", [
    {"leaf_batch": 1},
    {"leaf_batch": 8},
    {"leaf_batch": 8, "hist_rebuild": True},
    {"leaf_batch": 4, "max_depth": 4},
])
def test_grow_tree_partitioned_bit_identical(cfg_kw):
    (t0, lid0), (t1, lid1) = _grow_pair(cfg_kw)
    for k in t0:
        if k == "hist_rows":
            continue
        np.testing.assert_array_equal(t0[k], t1[k], err_msg=k)
    np.testing.assert_array_equal(lid0, lid1)
    # the structural win: the partitioned tree scanned fewer rows
    assert int(t1["hist_rows"]) <= int(t0["hist_rows"])


# ---------------------------------------------------------------------------
# engine: model-text equality across the interop matrix
# ---------------------------------------------------------------------------

def _data(n=4000, f=8, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f))
    y = (X @ rng.normal(size=f)
         + rng.normal(scale=0.3, size=n) > 0).astype(np.float64)
    return X, y


def _model_text(X, y, extra, rounds=6):
    params = {"objective": "binary", "num_leaves": 31, "verbosity": -1,
              "learning_rate": 0.3}
    params.update(extra)
    bst = lgb.train(params, lgb.Dataset(X, label=y),
                    num_boost_round=rounds)
    return bst, bst.model_to_string()


QUANT_MATRIX = [
    ("pool", {"use_quantized_grad": True}),
    ("rebuild", {"tpu_hist_mode": "rebuild", "use_quantized_grad": True}),
    ("goss", {"data_sample_strategy": "goss", "top_rate": 0.3,
              "other_rate": 0.2, "use_quantized_grad": True}),
    ("goss_compact", {"data_sample_strategy": "goss", "top_rate": 0.3,
                      "other_rate": 0.2, "use_quantized_grad": True,
                      "tpu_goss_compact": True}),
]


@pytest.mark.parametrize("name,extra", QUANT_MATRIX,
                         ids=[m[0] for m in QUANT_MATRIX])
def test_partition_bit_exact_quantized(name, extra):
    """Quantized gradients make histogram sums integer-valued, so the
    span accumulation order cannot perturb them: model text must match
    the masked path byte-for-byte."""
    X, y = _data()
    _, m0 = _model_text(X, y, {**extra, "tpu_hist_partition": "false"})
    _, m1 = _model_text(X, y, {**extra, "tpu_hist_partition": "true"})
    assert m0 == m1


def test_partition_close_under_f32():
    """f32 histograms may differ in accumulation order only: the GOSS
    compaction closeness contract applies."""
    X, y = _data(seed=5)
    b0, _ = _model_text(X, y, {"tpu_hist_partition": "false"})
    b1, _ = _model_text(X, y, {"tpu_hist_partition": "true"})
    np.testing.assert_allclose(b1.predict(X), b0.predict(X),
                               rtol=2e-2, atol=2e-3)


def test_partition_with_forced_splits(tmp_path):
    """Forced-split rounds bypass the scan (pool gathers) but the
    partition must keep routing their children; the whole model still
    matches the masked path exactly under quantized gradients."""
    rng = np.random.default_rng(13)
    X = rng.uniform(-1, 1, size=(3000, 4))
    y = (3.0 * X[:, 0] + 0.2 * X[:, 1]
         + rng.normal(scale=0.1, size=3000) > 0).astype(np.float64)
    fs = str(tmp_path / "forced.json")
    with open(fs, "w") as f:
        json.dump({"feature": 1, "threshold": 0.25,
                   "left": {"feature": 2, "threshold": -0.5}}, f)
    extra = {"forcedsplits_filename": fs, "use_quantized_grad": True}
    b0, m0 = _model_text(X, y, {**extra, "tpu_hist_partition": "false"},
                         rounds=4)
    _, m1 = _model_text(X, y, {**extra, "tpu_hist_partition": "true"},
                        rounds=4)
    assert m0 == m1
    used = b0.engine.train_set.used_features
    for t in b0.engine.models:
        assert used[int(np.asarray(t.split_feature)[0])] == 1


def test_partition_multiclass_quantized():
    rng = np.random.default_rng(3)
    X = rng.normal(size=(3000, 8))
    y = ((X[:, 0] > 0).astype(int)
         + (X[:, 1] > 0.3).astype(int)).astype(np.float64)
    extra = {"objective": "multiclass", "num_class": 3,
             "use_quantized_grad": True}
    X2, y2 = X, y
    params0 = {**extra, "tpu_hist_partition": "false"}
    params1 = {**extra, "tpu_hist_partition": "true"}
    _, m0 = _model_text(X2, y2, params0, rounds=4)
    _, m1 = _model_text(X2, y2, params1, rounds=4)
    assert m0 == m1


@pytest.mark.parametrize("learner", ["data", "voting", "feature"])
def test_partition_parallel_learners(learner):
    """All three parallel learners keep per-shard partitions (tables
    and spans are local; histogram reductions stay outside the span
    switch) — quantized trees match the masked path bit-for-bit on the
    8-device CPU mesh."""
    X, y = _data(n=3072, seed=9)
    extra = {"tree_learner": learner, "min_data_in_leaf": 5,
             "use_quantized_grad": True}
    _, m0 = _model_text(X, y, {**extra, "tpu_hist_partition": "false"},
                        rounds=4)
    _, m1 = _model_text(X, y, {**extra, "tpu_hist_partition": "true"},
                        rounds=4)
    assert m0 == m1


# ---------------------------------------------------------------------------
# observability + compile behavior
# ---------------------------------------------------------------------------

def test_rows_scanned_metric():
    """hist.rows_scanned: masked = n_pad x realized rounds; the
    partitioned path must record strictly fewer once spans engage.
    (leaf_batch is kept small so the pow2 ladder has budgets under
    n/Kb at this test size — with the 32-lane default the spans only
    shrink million-row inputs.)"""
    X, y = _data(n=6000)
    obs.enable(metrics=True)
    obs.reset()
    extra = {"tpu_leaf_batch": 2, "tpu_metrics": True}
    b0, _ = _model_text(X, y, {**extra, "tpu_hist_partition": "false"},
                        rounds=3)
    masked = obs.registry().counter("hist.rows_scanned").value
    obs.reset()
    b1, _ = _model_text(X, y, {**extra, "tpu_hist_partition": "true"},
                        rounds=3)
    part = obs.registry().counter("hist.rows_scanned").value
    assert masked > 0 and part > 0
    assert part < masked
    n_pad = b0.engine.data.n_pad
    # masked path scans the whole padded buffer every round
    assert masked % n_pad == 0


def test_partition_budget_ladder_no_recompiles():
    """pow2 span budgets keep shapes static: once the step program is
    built, further same-shape training compiles ZERO fresh programs —
    span sizes shrinking round over round select lax.switch branches
    inside the one compiled program, never new specializations."""
    from lightgbm_tpu.boosting.gbdt import GBDT
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.utils.debug import CompileWatch
    X, y = _data(n=2500, seed=11)
    cfg = Config({"objective": "binary", "num_leaves": 31,
                  "verbosity": -1, "tpu_leaf_batch": 2,
                  "tpu_hist_partition": "true",
                  "use_quantized_grad": True})
    eng = GBDT(cfg, lgb.Dataset(X, label=y))
    eng.train_chunk(3)
    with CompileWatch("warm partitioned training") as w:
        eng.train_chunk(3)
    w.assert_compiles(0)
