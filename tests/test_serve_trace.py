"""Request-lifecycle tracing through the serving stack
(docs/observability.md "Request tracing"; serve/service.py +
serve/queue.py + obs/tracing.py + obs/slo.py).

What these tests pin:

* **Span tree** — one dispatched batch records ONE ``serve/batch``
  parent whose children decompose it (queue_wait / coalesce /
  registry_checkout / dispatch / postprocess), riders attach as flow
  events (submit point -> carrying batch), and the checkout span says
  hit vs re-admission.
* **Flush causes** — the queue classifies WHY each batch left
  (fill / freeze / deadline) onto the popped requests, and the
  dispatch counts ``serve.flush_cause{cause=...}``.
* **Live decomposition** — the same stage durations feed the SLO
  windows: ``slo.queue_wait_p50|p99_ms``, ``slo.dispatch_p99_ms``
  and ``slo.device_share`` derive on evaluate().
* **Bounded buffer under load** — sustained traced serving overflows
  the (shrunken) buffer: the dropped-event gauge increments,
  oldest-dropped semantics hold (the newest requests' events remain),
  and a drained buffer's next export is well-formed.
"""
import json

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu import obs
from lightgbm_tpu.obs import slo as _slo
from lightgbm_tpu.obs import tracing as obs_tracing

pytestmark = pytest.mark.filterwarnings("ignore")


@pytest.fixture(autouse=True)
def _isolated_obs():
    obs.disable()
    obs.reset()
    obs.set_trace_rank(None)
    yield
    obs.disable()
    obs.reset()
    obs.set_trace_rank(None)


def _data(n=2000, f=8, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f))
    y = (X[:, 0] + 0.5 * X[:, 1] * X[:, 2] > 0).astype(np.float64)
    return X, y


PARAMS = {"objective": "binary", "num_leaves": 8, "verbosity": -1}


@pytest.fixture(scope="module")
def trained():
    X, y = _data()
    bst = lgb.train(PARAMS, lgb.Dataset(X, label=y), num_boost_round=4)
    return bst, X


def _service(start=True, **over):
    from lightgbm_tpu.serve import PredictService
    p = {"tpu_serve_batch_budget_ms": 200.0,
         "tpu_serve_max_batch_rows": 1024,
         "tpu_serve_shard_trees": "false"}
    p.update(over)
    return PredictService(p, start=start)


# ---------------------------------------------------------------------------
# the per-batch span tree + rider flows
# ---------------------------------------------------------------------------
def test_batch_span_tree_and_rider_flows(trained, tmp_path):
    bst, X = trained
    obs.enable(metrics=True, trace_dir=str(tmp_path))
    svc = _service()
    try:
        svc.add_model("m", bst)
        futs = [svc.submit("m", X[:96]) for _ in range(3)]
        for f in futs:
            f.result(timeout=20)
    finally:
        svc.close()
    evs = obs_tracing.events()
    by_name = {}
    for e in evs:
        by_name.setdefault(e["name"], []).append(e)

    # one coalesced dispatch: one batch span, the stage children under
    # it, one queue-wait event per rider
    batches = by_name["serve/batch"]
    assert len(batches) == 1
    b = batches[0]
    assert b["args"]["riders"] == 3 and b["args"]["rows"] == 288
    assert b["args"]["cause"] in ("fill", "freeze", "deadline")
    for stage in ("serve/coalesce", "serve/registry_checkout",
                  "serve/dispatch", "serve/postprocess"):
        (ev,) = by_name[stage]
        assert ev["args"]["parent"] == "serve/batch"
        # containment: children render inside the batch slice
        assert ev["ts"] >= b["ts"] - 1.0
        assert ev["ts"] + ev["dur"] <= b["ts"] + b["dur"] + 1.0
    assert by_name["serve/coalesce"][0]["args"]["cause"] == \
        b["args"]["cause"]
    assert "fill" in by_name["serve/coalesce"][0]["args"]
    # first touch of the model: a re-admission re-stack, not a hit
    assert by_name["serve/registry_checkout"][0]["args"]["hit"] is False

    waits = by_name["serve/queue_wait"]
    assert len(waits) == 3
    qtid = obs_tracing.track_tid("serve queue")
    for wv in waits:
        assert wv["args"]["parent"] == "serve/batch"
        assert wv["tid"] == qtid          # the virtual queue row
        # retroactive: the wait STARTS at enqueue, before the batch
        assert wv["ts"] <= b["ts"] + 1.0

    # flow events: one start per submit (caller thread), one finish
    # per rider inside the batch, matched on the request id
    starts = {e["id"] for e in by_name["serve/req"]
              if e["ph"] == "s"}
    finishes = {e["id"] for e in by_name["serve/req"]
                if e["ph"] == "f"}
    assert len(starts) == 3 and starts == finishes
    assert {w["args"]["req"] for w in waits} == starts


def test_checkout_hit_attr_tracks_residency(trained, tmp_path):
    """hit=False on first admission and after an eviction, hit=True on
    the warm path — the trace attr that separates an LRU-thrash p99
    breach from a device-time one."""
    bst, X = trained
    obs.enable(metrics=True, trace_dir=str(tmp_path))
    svc = _service(tpu_serve_batch_budget_ms=1.0)
    try:
        svc.add_model("m", bst)
        svc.predict("m", X[:16], timeout=20)
        svc.predict("m", X[:16], timeout=20)
        svc.registry.evict("m")
        svc.predict("m", X[:16], timeout=20)
    finally:
        svc.close()
    hits = [e["args"]["hit"] for e in obs_tracing.events()
            if e["name"] == "serve/registry_checkout"]
    assert hits == [False, True, False]


# ---------------------------------------------------------------------------
# flush-cause taxonomy
# ---------------------------------------------------------------------------
def test_queue_stamps_flush_causes():
    """Pure queue (no engine): each pop carries WHY it flushed."""
    from lightgbm_tpu.serve.queue import MicroBatchQueue
    q = MicroBatchQueue(budget_s=30.0, max_batch_rows=256)
    q.submit("m", np.zeros((128, 2)))
    q.submit("m", np.zeros((128, 2)))     # prefix reaches the cap
    _, b = q.next_batch()
    assert [r.flush_cause for r in b] == ["fill", "fill"]

    q.submit("m", np.zeros((100, 2)))
    q.submit("m", np.zeros((2000, 2)))    # freezes the prefix at 100
    _, b = q.next_batch()
    assert [r.flush_cause for r in b] == ["freeze"]
    _, b = q.next_batch()                 # the oversize: its own full
    assert [r.flush_cause for r in b] == ["fill"]

    q2 = MicroBatchQueue(budget_s=0.01, max_batch_rows=256)
    q2.submit("m", np.zeros((8, 2)))      # lone request: budget flush
    _, b = q2.next_batch()
    assert [r.flush_cause for r in b] == ["deadline"]


def test_shattered_batch_records_queue_wait_once(trained):
    """A malformed rider shatters its batch into per-rider
    re-dispatches — admission must NOT re-record: one queue-wait
    observation per rider, or the slo.queue_wait_* windows double-feed
    exactly when batches go bad."""
    bst, X = trained
    obs.enable(metrics=True)
    svc = _service(tpu_serve_batch_budget_ms=200.0)
    try:
        svc.add_model("m", bst)
        good = svc.submit("m", X[:16])
        bad = svc.submit("m", X[:8, :4])      # wrong column count
        good.result(timeout=20)
        with pytest.raises(Exception):
            bad.result(timeout=20)
    finally:
        svc.close()
    assert obs.registry().get("serve/queue_wait").count == 2


def test_flush_cause_counters_recorded(trained):
    bst, X = trained
    obs.enable(metrics=True)
    svc = _service(tpu_serve_batch_budget_ms=5.0)
    try:
        svc.add_model("m", bst)
        for _ in range(3):
            svc.predict("m", X[:16], timeout=20)
    finally:
        svc.close()
    reg = obs.registry()
    total = sum((reg.get("serve.flush_cause", cause=c).value
                 if reg.get("serve.flush_cause", cause=c) else 0.0)
                for c in ("fill", "freeze", "deadline", "close"))
    assert total >= 3.0


# ---------------------------------------------------------------------------
# the live decomposition gauges
# ---------------------------------------------------------------------------
def test_slo_decomposition_gauges_derive_from_serve_traffic(trained):
    bst, X = trained
    obs.enable(metrics=True, slo=True)
    svc = _service(tpu_serve_batch_budget_ms=2.0)
    try:
        svc.add_model("m", bst)
        for _ in range(5):
            svc.predict("m", X[:32], timeout=20)
    finally:
        svc.close()
    slis = _slo.tracker().evaluate()
    reg = obs.registry()
    for name in ("slo.queue_wait_p50_ms", "slo.queue_wait_p99_ms",
                 "slo.dispatch_p99_ms", "slo.device_share"):
        assert slis[name] is not None, name
        assert reg.get(name) is not None, name
    assert slis["slo.queue_wait_p99_ms"] >= \
        slis["slo.queue_wait_p50_ms"]
    assert 0.0 < slis["slo.device_share"] <= 1.0


def test_sliding_histogram_windowed_total():
    """The exact windowed sum the device-share ratio is built on."""
    from lightgbm_tpu.obs.slo import SlidingHistogram
    h = SlidingHistogram(window_s=100.0, slots=10)
    h.observe(1.5, now=1000.0)
    h.observe(2.5, now=1050.0)
    assert h.total(now=1060.0) == pytest.approx(4.0)
    # the early slot ages out of a narrower window
    assert h.total(window_s=20.0, now=1060.0) == pytest.approx(2.5)
    # ... and of the full window once the clock advances past it
    assert h.total(now=1101.0) == pytest.approx(2.5)


# ---------------------------------------------------------------------------
# bounded buffer under sustained serving load (ISSUE satellite)
# ---------------------------------------------------------------------------
def test_bounded_buffer_under_serving_load(trained, tmp_path,
                                           monkeypatch):
    bst, X = trained
    monkeypatch.setattr(obs_tracing, "MAX_EVENTS", 60)
    obs.enable(metrics=True, trace_dir=str(tmp_path))
    svc = _service(tpu_serve_batch_budget_ms=0.5)
    try:
        svc.add_model("m", bst)
        for _ in range(40):               # ~9 events per request
            svc.predict("m", X[:16], timeout=20)

        assert obs_tracing.dropped_events() > 0
        # the dropped count is a LIVE gauge on the snapshot/scrape path
        snap = obs.snapshot()
        (g,) = [m for m in snap["metrics"]
                if m["name"] == "trace.dropped_events"]
        assert g["value"] == obs_tracing.dropped_events() > 0

        # oldest-dropped: the surviving queue-wait events belong to
        # the NEWEST requests (early request ids were evicted)
        req_ids = [e["args"]["req"] for e in obs_tracing.events()
                   if e["name"] == "serve/queue_wait"]
        assert req_ids == sorted(req_ids)
        assert min(req_ids) > 1
        assert len(obs_tracing.events()) <= 60

        # a drained buffer's next export is well-formed
        obs_tracing.reset_events()
        assert obs_tracing.dropped_events() == 0
        svc.predict("m", X[:16], timeout=20)
    finally:
        svc.close()
    out = obs.export_chrome_trace()
    doc = json.load(open(out))
    assert doc["otherData"]["dropped_events"] == 0
    names = {e["name"] for e in doc["traceEvents"] if e["ph"] == "X"}
    assert "serve/batch" in names and "serve/dispatch" in names


def test_tracing_off_leaves_no_serve_events(trained):
    """Off-by-default: metrics-only serving records histograms but no
    trace events and no flow points (the zero-cost-off bar)."""
    bst, X = trained
    obs.enable(metrics=True)
    svc = _service(tpu_serve_batch_budget_ms=1.0)
    try:
        svc.add_model("m", bst)
        svc.predict("m", X[:16], timeout=20)
    finally:
        svc.close()
    assert obs_tracing.events() == []
    assert obs.registry().get("serve/batch") is not None
