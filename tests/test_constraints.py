"""Monotone + interaction constraints (reference:
monotone_constraints.hpp basic mode; ColSampler interaction
constraints)."""
import numpy as np

import lightgbm_tpu as lgb


def _data(n=4000, seed=0):
    """y depends monotonically on f0 plus a NON-monotone bump, so an
    unconstrained model learns a non-monotone response."""
    rng = np.random.default_rng(seed)
    X = rng.uniform(-2, 2, size=(n, 4))
    y = (0.8 * X[:, 0]
         - 2.0 * np.exp(-((X[:, 0] - 0.5) ** 2) / 0.05)   # dip at 0.5
         + 0.5 * X[:, 1] + rng.normal(scale=0.1, size=n))
    return X, y


def _response_curve(bst, base_row, f, grid):
    rows = np.tile(base_row, (len(grid), 1))
    rows[:, f] = grid
    return bst.predict(rows)


def test_monotone_increasing_enforced():
    X, y = _data()
    grid = np.linspace(-2, 2, 201)
    base = np.array([0.0, 0.0, 0.0, 0.0])

    unconstrained = lgb.train(
        {"objective": "regression", "num_leaves": 31, "verbosity": -1},
        lgb.Dataset(X, label=y), num_boost_round=40)
    r_un = _response_curve(unconstrained, base, 0, grid)
    assert np.min(np.diff(r_un)) < -1e-3   # the dip is really learned

    constrained = lgb.train(
        {"objective": "regression", "num_leaves": 31, "verbosity": -1,
         "monotone_constraints": [1, 0, 0, 0]},
        lgb.Dataset(X, label=y), num_boost_round=40)
    r_c = _response_curve(constrained, base, 0, grid)
    assert np.min(np.diff(r_c)) >= -1e-6   # non-decreasing everywhere
    # and for several random contexts, not just the base row
    rng = np.random.default_rng(1)
    for _ in range(5):
        row = rng.uniform(-2, 2, size=4)
        r = _response_curve(constrained, row, 0, grid)
        assert np.min(np.diff(r)) >= -1e-6
    # the constrained model still fits the monotone part
    assert np.corrcoef(constrained.predict(X), y)[0, 1] > 0.8


def test_monotone_decreasing_enforced():
    X, y = _data(seed=2)
    y = -y
    bst = lgb.train(
        {"objective": "regression", "num_leaves": 31, "verbosity": -1,
         "monotone_constraints": "-1,0,0,0"},
        lgb.Dataset(X, label=y), num_boost_round=30)
    grid = np.linspace(-2, 2, 101)
    r = _response_curve(bst, np.zeros(4), 0, grid)
    assert np.max(np.diff(r)) <= 1e-6      # non-increasing


def _paths_features(tree):
    """All root->leaf paths as feature sets."""
    out = []

    def walk(node, used):
        if node < 0:
            out.append(used)
            return
        u2 = used | {int(tree.split_feature[node])}
        walk(int(tree.left_child[node]), u2)
        walk(int(tree.right_child[node]), u2)

    if tree.num_nodes:
        walk(0, set())
    return out


def test_interaction_constraints_paths():
    rng = np.random.default_rng(3)
    X = rng.normal(size=(4000, 6))
    # y needs cross-group interactions the constraint forbids
    y = (X[:, 0] * X[:, 2] + X[:, 1] + X[:, 4]
         + rng.normal(scale=0.1, size=4000))
    groups = [[0, 1], [2, 3], [4, 5]]
    bst = lgb.train(
        {"objective": "regression", "num_leaves": 31, "verbosity": -1,
         "interaction_constraints": "[0,1],[2,3],[4,5]"},
        lgb.Dataset(X, label=y), num_boost_round=15)
    eng = bst.engine
    used_map = eng.train_set.used_features
    for t in eng.models:
        for path in _paths_features(t):
            orig = {used_map[f] for f in path}
            assert any(orig <= set(g) for g in groups), \
                f"path {sorted(orig)} crosses constraint groups"


def test_interaction_constraints_list_form():
    rng = np.random.default_rng(4)
    X = rng.normal(size=(1500, 4))
    y = X @ rng.normal(size=4)
    bst = lgb.train(
        {"objective": "regression", "num_leaves": 15, "verbosity": -1,
         "interaction_constraints": [[0, 1], [2, 3]]},
        lgb.Dataset(X, label=y), num_boost_round=5)
    assert bst.num_trees() == 5


def test_monotone_intermediate_enforced_and_tighter_fit():
    """IntermediateLeafConstraints: same monotonicity guarantee as
    basic, but sibling-output bounds (recomputed per round from current
    outputs) are looser than basic's midpoint — the constrained fit must
    not get worse, and typically improves."""
    X, y = _data(n=6000, seed=7)
    grid = np.linspace(-2, 2, 201)
    params = {"objective": "regression", "num_leaves": 31,
              "verbosity": -1, "monotone_constraints": [1, 0, 0, 0]}
    basic = lgb.train({**params, "monotone_constraints_method": "basic"},
                      lgb.Dataset(X, label=y), num_boost_round=60)
    inter = lgb.train({**params,
                       "monotone_constraints_method": "intermediate"},
                      lgb.Dataset(X, label=y), num_boost_round=60)
    rng = np.random.default_rng(8)
    for _ in range(8):
        row = rng.uniform(-2, 2, size=4)
        r = _response_curve(inter, row, 0, grid)
        assert np.min(np.diff(r)) >= -1e-6, "intermediate violates"
    mse_b = float(np.mean((basic.predict(X) - y) ** 2))
    mse_i = float(np.mean((inter.predict(X) - y) ** 2))
    # looser bounds can only help the training fit (tolerance for ties)
    assert mse_i <= mse_b * 1.02, (mse_i, mse_b)


def test_monotone_advanced_enforced_and_at_least_intermediate():
    """AdvancedLeafConstraints analog: boundary-adjacent strip bounds
    are looser than intermediate's whole-subtree min/max, so the
    constrained fit must not get worse — while every response curve
    stays monotone."""
    X, y = _data(n=6000, seed=11)
    grid = np.linspace(-2, 2, 201)
    params = {"objective": "regression", "num_leaves": 31,
              "verbosity": -1, "monotone_constraints": [1, 0, 0, 0]}
    inter = lgb.train({**params,
                       "monotone_constraints_method": "intermediate"},
                      lgb.Dataset(X, label=y), num_boost_round=60)
    adv = lgb.train({**params,
                     "monotone_constraints_method": "advanced"},
                    lgb.Dataset(X, label=y), num_boost_round=60)
    rng = np.random.default_rng(12)
    for _ in range(8):
        row = rng.uniform(-2, 2, size=4)
        r = _response_curve(adv, row, 0, grid)
        assert np.min(np.diff(r)) >= -1e-6, "advanced violates"
    mse_i = float(np.mean((inter.predict(X) - y) ** 2))
    mse_a = float(np.mean((adv.predict(X) - y) ** 2))
    # looser (but sound) bounds can only help the fit (tie tolerance)
    assert mse_a <= mse_i * 1.02, (mse_a, mse_i)


def test_monotone_advanced_both_directions_multifeature():
    """Advanced with two constrained features of opposite directions
    keeps both response monotonicities."""
    rng = np.random.default_rng(13)
    X = rng.uniform(-2, 2, size=(5000, 4))
    y = (0.9 * X[:, 0] - 0.7 * X[:, 1]
         - 1.5 * np.exp(-((X[:, 0] - 0.3) ** 2) / 0.05)
         + 1.2 * np.exp(-((X[:, 1] + 0.4) ** 2) / 0.05)
         + 0.4 * X[:, 2] + rng.normal(scale=0.1, size=5000))
    bst = lgb.train(
        {"objective": "regression", "num_leaves": 31, "verbosity": -1,
         "monotone_constraints": [1, -1, 0, 0],
         "monotone_constraints_method": "advanced"},
        lgb.Dataset(X, label=y), num_boost_round=40)
    grid = np.linspace(-2, 2, 151)
    for _ in range(5):
        row = rng.uniform(-2, 2, size=4)
        assert np.min(np.diff(_response_curve(bst, row, 0, grid))) \
            >= -1e-6
        assert np.max(np.diff(_response_curve(bst, row, 1, grid))) \
            <= 1e-6


def test_monotone_penalty_pushes_constrained_splits_down():
    """ComputeMonotoneSplitGainPenalty: a large penalty makes the
    constrained feature unusable near the root."""
    X, y = _data(n=4000, seed=9)
    base = {"objective": "regression", "num_leaves": 31, "verbosity": -1,
            "monotone_constraints": [1, 0, 0, 0]}
    plain = lgb.train(base, lgb.Dataset(X, label=y), num_boost_round=10)
    pen = lgb.train({**base, "monotone_penalty": 2.0},
                    lgb.Dataset(X, label=y), num_boost_round=10)

    def min_depth_of_feature(bst, feat):
        """Shallowest depth (root=0) at which `feat` splits, across
        trees."""
        best = np.inf
        used_map = bst.engine.train_set.used_features

        def walk(t, node, d):
            nonlocal best
            if node < 0:
                return
            if used_map[int(t.split_feature[node])] == feat:
                best = min(best, d)
            walk(t, int(t.left_child[node]), d + 1)
            walk(t, int(t.right_child[node]), d + 1)

        for t in bst.engine.models:
            if t.num_nodes:
                walk(t, 0, 0)
        return best

    d_plain = min_depth_of_feature(plain, 0)
    d_pen = min_depth_of_feature(pen, 0)
    # penalty 2.0 zeroes gains at depths 0 (factor ~eps while
    # penalization >= depth+1), so f0 cannot be the root split
    assert d_plain == 0
    assert d_pen >= 1, (d_plain, d_pen)
    # monotonicity still holds under the penalty
    grid = np.linspace(-2, 2, 101)
    r = _response_curve(pen, np.zeros(4), 0, grid)
    assert np.min(np.diff(r)) >= -1e-6


def test_monotone_with_data_parallel():
    X, y = _data(seed=5)
    bst = lgb.train(
        {"objective": "regression", "num_leaves": 15, "verbosity": -1,
         "monotone_constraints": [1, 0, 0, 0], "tree_learner": "data"},
        lgb.Dataset(X, label=y), num_boost_round=20)
    grid = np.linspace(-2, 2, 101)
    r = _response_curve(bst, np.zeros(4), 0, grid)
    assert np.min(np.diff(r)) >= -1e-6
