"""Batched multi-leaf histogram + leaf_batch growth equivalence tests.

Covers the round-1 gap: the batched learner path (leaf_batch > 1) and the
``multi_leaf_histogram*`` kernels had no coverage, which is how the
regression shipped. The Pallas variant is asserted equal to the XLA
variant when a real TPU is present, and skipped otherwise (the suite runs
on the fake 8-device CPU mesh, see conftest.py).
"""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

from lightgbm_tpu.learner.serial import GrowConfig, grow_tree
from lightgbm_tpu.ops.histogram import build_histogram
from lightgbm_tpu.ops.pallas_histogram import (multi_leaf_histogram,
                                               multi_leaf_histogram_xla)
from lightgbm_tpu.ops.predict import tree_predict_binned


def _data(n=2048, F=6, B=32, n_leaves=5, seed=0):
    rng = np.random.default_rng(seed)
    bins = rng.integers(0, B, size=(n, F)).astype(np.uint8)
    vals = rng.normal(size=(n, 3)).astype(np.float32)
    vals[:, 2] = 1.0
    leaf_id = rng.integers(0, n_leaves, size=n).astype(np.int32)
    return bins, vals, leaf_id


def test_multi_leaf_xla_matches_single_leaf_oracle():
    """Each slot of the K-leaf batched histogram must equal the masked
    single-leaf build_histogram (the oracle-tested op)."""
    B = 32
    bins, vals, leaf_id = _data(B=B)
    small_ids = np.array([3, 0, -1, 4], dtype=np.int32)  # incl. inactive
    out = np.asarray(multi_leaf_histogram_xla(
        jnp.asarray(bins), jnp.asarray(vals), jnp.asarray(leaf_id),
        jnp.asarray(small_ids), num_bins=B, rows_per_block=512))
    assert out.shape == (4, bins.shape[1], B, 3)
    for k, leaf in enumerate(small_ids):
        mask = (leaf_id == leaf).astype(np.float32)[:, None]
        ref = np.asarray(build_histogram(
            jnp.asarray(bins), jnp.asarray(vals * mask), num_bins=B,
            rows_per_block=512))
        np.testing.assert_allclose(out[k], ref, rtol=2e-2, atol=0.5)
        # count channel is exact (sums of exact 1.0s)
        np.testing.assert_array_equal(out[k, :, :, 2], ref[:, :, 2])
    # inactive slot (-1) matches no row -> zero histogram
    assert np.all(out[2] == 0.0)


requires_tpu = pytest.mark.skipif(
    jax.default_backend() != "tpu",
    reason="Pallas TPU kernel needs a TPU backend (run with "
           "LGBM_TPU_TESTS=1 on the chip)")


@requires_tpu
@pytest.mark.parametrize(
    "F,B,rpb",
    [
        (40, 256, 2048),   # F*B = 10240 > 8192: feature-blocked grid,
                           # at the B=256 int8-roundtrip boundary
        (8, 256, 4096),    # B=256 boundary on the single-block path at
                           # the R=4096 cap
        (64, 128, 2048),   # wide-F grid at the reduced R cap
        (6, 32, 4096),     # narrow shape at the full R cap
    ])
def test_pallas_matches_xla_boundary_shapes(F, B, rpb):
    """The exact VMEM cliffs docs/perf.md documents: the feature-blocked
    grid (F*B > 8192), the 256-bin int8 round-trip boundary, and both
    rows-per-block caps — each must agree with the XLA reference."""
    bins, vals, leaf_id = _data(n=4096, F=F, B=B, seed=B + F)
    small_ids = np.array([0, 3, -1, 1], dtype=np.int32)
    bins_t = np.ascontiguousarray(bins.T).astype(np.int8)
    h_pl = np.asarray(multi_leaf_histogram(
        jnp.asarray(bins_t), jnp.asarray(vals.T), jnp.asarray(leaf_id),
        jnp.asarray(small_ids), num_bins=B, rows_per_block=rpb))
    h_xla = np.asarray(multi_leaf_histogram_xla(
        jnp.asarray(bins), jnp.asarray(vals), jnp.asarray(leaf_id),
        jnp.asarray(small_ids), num_bins=B, rows_per_block=rpb))
    np.testing.assert_allclose(h_pl, h_xla, rtol=2e-2, atol=0.5)
    np.testing.assert_array_equal(h_pl[..., 2], h_xla[..., 2])


@requires_tpu
def test_pallas_matches_xla():
    B = 64
    bins, vals, leaf_id = _data(n=4096, F=8, B=B, seed=1)
    small_ids = np.array([0, 2, -1, 1, 4, -1, 3, -1], dtype=np.int32)
    bins_t = np.ascontiguousarray(bins.T).astype(np.int8)
    h_pl = np.asarray(multi_leaf_histogram(
        jnp.asarray(bins_t), jnp.asarray(vals.T), jnp.asarray(leaf_id),
        jnp.asarray(small_ids), num_bins=B, rows_per_block=1024))
    h_xla = np.asarray(multi_leaf_histogram_xla(
        jnp.asarray(bins), jnp.asarray(vals), jnp.asarray(leaf_id),
        jnp.asarray(small_ids), num_bins=B, rows_per_block=1024))
    np.testing.assert_allclose(h_pl, h_xla, rtol=2e-2, atol=0.5)
    np.testing.assert_array_equal(h_pl[..., 2], h_xla[..., 2])


def _grow(bins, g, h, cfg):
    n, F = bins.shape
    mask = np.ones(n, dtype=np.float32)
    vals = np.stack([g * mask, h * mask, mask], axis=1).astype(np.float32)
    num_bin = np.full(F, int(bins.max()) + 1, dtype=np.int32)
    has_nan = np.zeros(F, dtype=bool)
    tree, leaf_id = grow_tree(
        jnp.asarray(bins), jnp.asarray(vals), jnp.asarray(num_bin),
        jnp.asarray(has_nan), jnp.ones(F, dtype=bool), cfg)
    return ({k: np.asarray(v) for k, v in tree.items()},
            np.asarray(leaf_id), num_bin, has_nan)


@pytest.mark.parametrize("kb", [4, 16])
def test_leaf_batch_equivalent_fully_grown(kb):
    """When growth stops by min_data/gain (not the leaf cap), the batched
    expansion must find the same tree as exact leaf-wise order: same split
    multiset, same per-row leaf values."""
    n = 1024
    rng = np.random.default_rng(7)
    bins = rng.integers(0, 8, size=(n, 4)).astype(np.uint8)
    g = (bins[:, 0] * 0.5 - bins[:, 1] + 0.1 * rng.normal(size=n)) \
        .astype(np.float32)
    h = np.ones(n, dtype=np.float32)
    base = dict(num_leaves=63, min_data_in_leaf=50, num_bins=8,
                rows_per_block=256, min_gain_to_split=1e-3)
    t1, l1, num_bin, has_nan = _grow(bins, g, h,
                                     GrowConfig(leaf_batch=1, **base))
    tk, lk, _, _ = _grow(bins, g, h, GrowConfig(leaf_batch=kb, **base))
    assert int(t1["num_leaves"]) == int(tk["num_leaves"])
    nl = int(t1["num_leaves"])
    splits1 = sorted(zip(t1["split_feature"][:nl - 1],
                         t1["threshold_bin"][:nl - 1]))
    splitsk = sorted(zip(tk["split_feature"][:nl - 1],
                         tk["threshold_bin"][:nl - 1]))
    assert splits1 == splitsk
    # per-row predicted values identical up to bf16 histogram noise
    np.testing.assert_allclose(t1["leaf_value"][l1], tk["leaf_value"][lk],
                               rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("kb", [1, 4, 16])
def test_leaf_batch_counts_partition(kb):
    n = 2048
    rng = np.random.default_rng(8)
    bins = rng.integers(0, 16, size=(n, 5)).astype(np.uint8)
    g = rng.normal(size=n).astype(np.float32)
    h = np.ones(n, dtype=np.float32)
    cfg = GrowConfig(num_leaves=31, min_data_in_leaf=5, num_bins=16,
                     rows_per_block=512, leaf_batch=kb)
    tree, leaf_id, num_bin, has_nan = _grow(bins, g, h, cfg)
    nl = int(tree["num_leaves"])
    counts = np.bincount(leaf_id, minlength=cfg.num_leaves)
    np.testing.assert_array_equal(
        counts[:nl], tree["leaf_count"][:nl].astype(np.int64))
    assert counts[nl:].sum() == 0
    assert counts[:nl].min() >= 5
    # leaf_id agrees with traversal of the emitted tree
    dev_tree = {k: jnp.asarray(v) for k, v in tree.items()}
    _, leaf_via_tree = tree_predict_binned(
        dev_tree, jnp.asarray(bins), jnp.asarray(num_bin),
        jnp.asarray(has_nan))
    np.testing.assert_array_equal(leaf_id, np.asarray(leaf_via_tree))


def test_gbdt_quality_stable_across_leaf_batch():
    """End-to-end: tpu_leaf_batch in {1, 16} reach the same held-out AUC
    band on a fixed binary dataset."""
    import lightgbm_tpu as lgb
    rng = np.random.default_rng(11)
    n, f = 3000, 10
    X = rng.normal(size=(n, f))
    w = rng.normal(size=f)
    y = ((X @ w + 0.5 * X[:, 0] * X[:, 1]
          + rng.normal(scale=0.5, size=n)) > 0).astype(np.float64)
    aucs = {}
    for kb in (1, 16):
        ds = lgb.Dataset(X[:2400], label=y[:2400])
        vs = ds.create_valid(X[2400:], label=y[2400:])
        res = {}
        lgb.train({"objective": "binary", "num_leaves": 31,
                   "metric": "auc", "tpu_leaf_batch": kb,
                   "verbosity": -1}, ds, num_boost_round=20,
                  valid_sets=[vs], callbacks=[lgb.record_evaluation(res)])
        aucs[kb] = res["valid_0"]["auc"][-1]
    assert aucs[1] > 0.9 and aucs[16] > 0.9
    assert abs(aucs[1] - aucs[16]) < 0.02
