"""Static analysis suite (tools/analyze) + the capability-table sweep.

Three layers (ISSUE 10 acceptance):

1. per-checker FIXTURES — for each of the six drift linters, a
   snippet that MUST flag and a snippet that MUST pass, including the
   three historical drift-bug classes: a gate literal outside the
   capability table, a raw ``tpu_*`` param read, and a
   ``lax.switch``-wrapped collective (the PR 12 deadlock class), plus
   the use-after-donate class the ``tpu_donate`` pass introduces
   (donation-discipline);
2. allowlist hygiene — unexplained and stale entries are findings;
3. the extended drift-guard sweep — for EVERY engine, the capability
   table's verdicts agree with what the constructor actually does
   (table says fatal ⇒ constructor raises; base config ⇒ constructs),
   driven by the table's own ``example`` witnesses so a new row
   without a witness fails here;

plus the gate the whole PR exists for: ``python -m tools.analyze``
reports ZERO findings at HEAD.
"""
import pathlib
import sys

import numpy as np
import pytest

REPO_ROOT = pathlib.Path(__file__).resolve().parent.parent
if str(REPO_ROOT) not in sys.path:
    sys.path.insert(0, str(REPO_ROOT))

import lightgbm_tpu as lgb                                  # noqa: E402
from lightgbm_tpu import capabilities                       # noqa: E402
from lightgbm_tpu.config import Config                      # noqa: E402
from lightgbm_tpu.utils.log import LightGBMError            # noqa: E402
from tools.analyze import run, run_checker_on_source        # noqa: E402
from tools.analyze.core import Allowlist                    # noqa: E402


def _keys(findings):
    return {f.key for f in findings}


# ---------------------------------------------------------------------------
# the flagship gate: zero findings at HEAD, under the CI time budget
# ---------------------------------------------------------------------------
def test_suite_clean_at_head():
    """`python -m tools.analyze` must be green on the tree as
    committed — check.sh exits 6 and obs_trend.py fails absolutely
    otherwise, so this test failing means CI would too."""
    findings = run()
    assert findings == [], "\n".join(f.render() for f in findings)


# ---------------------------------------------------------------------------
# checker 1: capability-gate — eligibility literals live in the table
# ---------------------------------------------------------------------------
def test_capability_gate_flags_inline_eligibility_literal():
    # the PR-5/PR-10/PR-12 drift class: a private copy of an
    # eligibility list (historical bug #1 re-introduced)
    src = (
        "def _my_gate(config):\n"
        "    return (config.objective in ('binary', 'regression')\n"
        "            and config.tree_learner not in ('voting',))\n")
    ks = _keys(run_checker_on_source("capability-gate", src))
    assert "objective@_my_gate" in ks
    assert "tree_learner@_my_gate" in ks
    # str()-wrapped reads are still reads
    src2 = ("def g(c):\n"
            "    return str(c.boosting) in ('dart', 'rf')\n")
    assert _keys(run_checker_on_source("capability-gate", src2)) \
        == {"boosting@g"}


def test_capability_gate_passes_table_driven_code():
    src = (
        "from lightgbm_tpu import capabilities\n"
        "def _my_gate(config):\n"
        "    # named constant from the table: fine\n"
        "    ok = config.objective in capabilities.AUTO_QUANTIZE_OBJECTIVES\n"
        "    # equality dispatch (not an eligibility list): fine\n"
        "    return ok and config.boosting == 'dart'\n"
        "def other(x):\n"
        "    # non-gate attributes are out of scope\n"
        "    return x.color in ('red', 'green')\n")
    assert run_checker_on_source("capability-gate", src) == []


# ---------------------------------------------------------------------------
# checker 2: config-knobs — raw reads, undeclared knobs, docs
# ---------------------------------------------------------------------------
def test_config_knobs_flags_raw_read_and_undeclared():
    # historical bug #2 re-introduced: a raw params.get with an inline
    # default — plus an undeclared (typo'd) knob read
    src = (
        "def f(params, cfg):\n"
        "    a = params.get('tpu_streaming', 'auto')\n"
        "    b = getattr(cfg, 'tpu_streming', 'auto')  # typo\n"
        "    return a, b\n")
    ks = _keys(run_checker_on_source("config-knobs", src))
    assert "raw-read:tpu_streaming" in ks
    assert "undeclared:tpu_streming" in ks


def test_config_knobs_passes_sanctioned_reads():
    src = (
        "from lightgbm_tpu.config import get_param\n"
        "def f(params, cfg):\n"
        "    a = get_param(params, 'tpu_streaming')\n"
        "    b = getattr(cfg, 'tpu_metrics', False)\n"
        "    c = cfg.tpu_fuse_iters\n"
        "    d = params.get('max_bin', 255)   # non-tpu: out of scope\n"
        "    return a, b, c, d\n")
    assert run_checker_on_source("config-knobs", src) == []


def test_every_declared_tpu_knob_is_documented():
    """The satellite audit, kept green forever: ~48 tpu_* knobs in
    config._PARAMS each appear in README.md or docs/*.md (checker 2's
    doc rule — run here without allowlists so a future allowlist
    cannot quietly mute it)."""
    from tools.analyze import config_knobs
    from tools.analyze.core import SourceSet
    sources = SourceSet(str(REPO_ROOT), [config_knobs.CONFIG_FILE])
    undocumented = [f for f in config_knobs.check(sources)
                    if f.key.startswith("undocumented:")]
    assert undocumented == [], "\n".join(f.render() for f in undocumented)
    # sanity: the rule actually sees the declaration table
    assert len([k for k in config_knobs.declared_knobs(sources)
                if k.startswith("tpu_")]) >= 40


# ---------------------------------------------------------------------------
# checker 3: obs-names — catalogue drift, both directions
# ---------------------------------------------------------------------------
def test_obs_names_flags_undocumented_metric():
    src = ("from lightgbm_tpu import obs\n"
           "def f():\n"
           "    obs.inc('totally.unknown_metric')\n")
    ks = _keys(run_checker_on_source("obs-names", src))
    assert "undocumented:totally.unknown_metric" in ks


def test_obs_names_passes_catalogued_names_and_wildcards():
    src = ("from lightgbm_tpu import obs\n"
           "def f():\n"
           "    obs.inc('train.iterations')\n"
           "    obs.set_gauge('bench.something_new', 1.0)  # bench.*\n"
           "    obs.span('train/round')\n")
    assert run_checker_on_source("obs-names", src) == []


def test_obs_names_doc_parsing_and_unemitted_direction():
    from tools.analyze.obs_names import _covered, documented_names
    exact, wild = documented_names(str(REPO_ROOT))
    # catalogue parsing: real names in, API/file tokens out
    assert "train.iterations" in exact
    assert "predict.stack_cache_misses" in exact
    assert "obs/rank_merge" in exact          # slash-named span kept
    assert "bench" in wild                    # `bench.*`
    assert _covered("bench.iters_per_sec", exact, wild)
    assert not any(t.endswith(".py") for t in exact)
    # docs→code: a catalogued name nothing emits is a finding (the
    # heartbeat gauges are exactly this shape — dynamic f-string
    # emission — and are allowlisted with that reason)
    al = Allowlist.load("obs-names")
    assert ("docs/observability.md", "unemitted:heartbeat.train") \
        in al.entries


# ---------------------------------------------------------------------------
# checker 4: collective-safety — the PR 12 deadlock class
# ---------------------------------------------------------------------------
def test_collective_safety_flags_switch_wrapped_collective():
    # historical bug #3 re-introduced: a collective inside a
    # lax.switch branch (direct, via branches-list, and transitive)
    src = (
        "import jax\n"
        "def _br(x):\n"
        "    return jax.lax.psum(x, 'd')\n"
        "def _helper(x):\n"
        "    return _br(x)          # transitive reach\n"
        "def f(i, x):\n"
        "    branches = []\n"
        "    branches.append(_helper)\n"
        "    return jax.lax.switch(i, branches, x)\n"
        "def g(p, x):\n"
        "    return jax.lax.cond(p, _br, lambda v: v, x)\n")
    ks = _keys(run_checker_on_source("collective-safety", src))
    assert "branch:_helper@f" in ks
    assert "branch:_br@g" in ks


def test_collective_safety_flags_rank_divergent_conditional():
    src = (
        "import jax\n"
        "def f(x):\n"
        "    if jax.process_index() == 0:\n"
        "        return jax.lax.psum(x, 'd')\n"
        "    return x\n")
    ks = _keys(run_checker_on_source("collective-safety", src))
    assert "rank-if:psum@f" in ks
    # the else/elif suites of a rank test are just as divergent
    src2 = (
        "import jax\n"
        "def g(x, rank):\n"
        "    if rank == 0:\n"
        "        x = x + 1\n"
        "    elif rank == 1:\n"
        "        x = x + 2\n"
        "    else:\n"
        "        x = jax.lax.psum(x, 'd')\n"
        "    return x\n")
    assert "rank-if:psum@g" in _keys(
        run_checker_on_source("collective-safety", src2))


def test_collective_safety_flags_thread_dispatched_collective():
    """The ISSUE 17 staging contract: a callable handed to a
    background thread (executor.submit / Thread(target=) / a
    BlockPrefetcher staging slot) must not reach a collective —
    per-rank launch order would become a thread-scheduling accident
    (gang deadlock). Bound-method references (`self._stage`) resolve
    by attr name like the module-local call graph does."""
    src = (
        "import jax\n"
        "from concurrent.futures import ThreadPoolExecutor\n"
        "import threading\n"
        "from lightgbm_tpu.utils.prefetch import BlockPrefetcher\n"
        "def _reduce(x):\n"
        "    return jax.lax.psum(x, 'd')\n"
        "def _stage(x):\n"
        "    return _reduce(x)       # transitive reach\n"
        "def f(pool, x):\n"
        "    return pool.submit(_reduce, x)\n"
        "def g(x):\n"
        "    t = threading.Thread(target=_stage, args=(x,))\n"
        "    t.start()\n"
        "class Eng:\n"
        "    def _stage(self, x):\n"
        "        return _reduce(x)\n"
        "    def h(self):\n"
        "        return BlockPrefetcher(self._stage, [1, 2])\n")
    ks = _keys(run_checker_on_source("collective-safety", src))
    assert "thread:_reduce@f" in ks
    assert "thread:_stage@g" in ks
    assert "thread:_stage@h" in ks


def test_collective_safety_passes_pure_staging_threads():
    # the shape streaming.py actually uses: the staged callable only
    # slices/pads/device_puts; the collective dispatches from the main
    # thread after the window push
    src = (
        "import jax\n"
        "from lightgbm_tpu.utils.prefetch import BlockPrefetcher\n"
        "def _stage(item):\n"
        "    return jax.device_put(item)\n"
        "def f(pool, sched, x):\n"
        "    pf = BlockPrefetcher(_stage, sched)\n"
        "    pool.submit(_stage, x)\n"
        "    h = pf.take()\n"
        "    return jax.lax.psum(h, 'd')   # main thread: fine\n")
    assert run_checker_on_source("collective-safety", src) == []


def test_collective_safety_passes_hoisted_collectives():
    # the shape serial.py actually uses: branches histogram locally,
    # the reduction wraps the switch RESULT
    src = (
        "import jax\n"
        "def _hist(x):\n"
        "    return x * 2\n"
        "def f(i, x):\n"
        "    branches = [_hist, _hist]\n"
        "    h = jax.lax.switch(i, branches, x)\n"
        "    return jax.lax.psum(h, 'd')\n"
        "def g(rank, x):\n"
        "    h = jax.lax.psum(x, 'd')   # outside the if: fine\n"
        "    if rank == 0:\n"
        "        h = h + 1\n"
        "    return h\n")
    assert run_checker_on_source("collective-safety", src) == []


# ---------------------------------------------------------------------------
# checker 5: lock-discipline — obs shared state under self._lock
# ---------------------------------------------------------------------------
_LOCK_REL = "lightgbm_tpu/obs/_fixture.py"


def test_lock_discipline_flags_unlocked_mutation():
    src = (
        "import threading\n"
        "class Tracker:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self.items = []\n"
        "        self.count = 0\n"
        "    def bad_append(self, x):\n"
        "        self.items.append(x)\n"
        "    def bad_assign(self):\n"
        "        self.count += 1\n")
    ks = _keys(run_checker_on_source("lock-discipline", src,
                                     rel=_LOCK_REL))
    assert ks == {"Tracker.bad_append:items", "Tracker.bad_assign:count"}


def test_lock_discipline_passes_locked_and_declared_helpers():
    src = (
        "import threading\n"
        "class Tracker:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self.items = []\n"
        "    def good(self, x):\n"
        "        with self._lock:\n"
        "            self.items.append(x)\n"
        "    def _clear(self):\n"
        "        \"\"\"Caller holds the lock.\"\"\"\n"
        "        self.items.clear()\n"
        "    def read_only(self):\n"
        "        return len(self.items)\n"
        "class NoLock:\n"
        "    def __init__(self):\n"
        "        self.items = []\n"
        "    def fine(self, x):\n"
        "        self.items.append(x)   # class has no lock protocol\n")
    assert run_checker_on_source("lock-discipline", src,
                                 rel=_LOCK_REL) == []


def test_lock_discipline_scope_is_obs_only():
    src = ("import threading\n"
           "class T:\n"
           "    def __init__(self):\n"
           "        self._lock = threading.Lock()\n"
           "        self.x = 0\n"
           "    def bad(self):\n"
           "        self.x = 1\n")
    assert run_checker_on_source("lock-discipline", src,
                                 rel="lightgbm_tpu/engine_fixture.py") \
        == []


# ---------------------------------------------------------------------------
# checker 6: donation-discipline — donated references rebind before reads
# ---------------------------------------------------------------------------
def test_donation_discipline_flags_read_after_donate():
    # the use-after-donate class the tpu_donate pass introduces: the
    # jit deletes its donated argument buffer at dispatch, so the
    # later `score.sum()` reads a deleted array
    src = (
        "import jax\n"
        "_j = jax.jit(lambda s: s + 1, donate_argnums=(0,))\n"
        "def train(score):\n"
        "    out = _j(score)\n"
        "    return out + score.sum()\n")
    ks = _keys(run_checker_on_source("donation-discipline", src))
    assert ks == {"train._j:score"}


def test_donation_discipline_flags_unrebound_loop_carry():
    # a donating call in a loop whose carry is never reassigned in the
    # body re-reads the deleted buffer on the NEXT iteration
    src = (
        "import jax\n"
        "def train(score, keys):\n"
        "    _j = jax.jit(lambda s, k: s + k, donate_argnums=(0,))\n"
        "    for k in keys:\n"
        "        out = _j(score, k)\n"
        "    return out\n")
    ks = _keys(run_checker_on_source("donation-discipline", src))
    assert ks == {"train._j:score"}


def test_donation_discipline_flags_read_after_branch_and_self_attr():
    # reads in the continuation AFTER an `if` that donated, and the
    # __init__-builds / step-calls split on self attributes (the
    # class-scope pre-pass)
    src_if = (
        "import jax\n"
        "def f(score, c):\n"
        "    _j = jax.jit(lambda s: s + 1, donate_argnums=(0,))\n"
        "    if c:\n"
        "        out = _j(score)\n"
        "    return score.sum()\n")
    assert _keys(run_checker_on_source(
        "donation-discipline", src_if)) == {"f._j:score"}
    src_self = (
        "import jax\n"
        "class E:\n"
        "    def __init__(self):\n"
        "        self._j = jax.jit(lambda s: s + 1,\n"
        "                          donate_argnums=(0,))\n"
        "    def step(self):\n"
        "        out = self._j(self.score)\n"
        "        return out + self.score\n")
    assert _keys(run_checker_on_source(
        "donation-discipline", src_self)) == {"step.self._j:self.score"}


def test_donation_discipline_passes_rebound_carries():
    # the sanctioned shapes: `score = step(score)` loop carries,
    # return-only wrapper call sites (boosting/gbdt.py's closures),
    # conditional donate_argnums resolved through a local name, and
    # jits that do not donate at all
    src = (
        "import jax\n"
        "def train(score, keys):\n"
        "    _j = jax.jit(lambda s, k: s + k, donate_argnums=(0,))\n"
        "    for k in keys:\n"
        "        score = _j(score, k)\n"
        "    return score\n"
        "def make(guard, flag):\n"
        "    _don = (4,) if flag else ()\n"
        "    _j2 = guard(jax.jit(lambda *a: a[4],\n"
        "                        donate_argnums=_don), 'site')\n"
        "    def step(score):\n"
        "        return _j2(0, 1, 2, 3, score)\n"
        "    return step\n"
        "def plain(score):\n"
        "    _nj = jax.jit(lambda s: s + 1)\n"
        "    out = _nj(score)\n"
        "    return out + score\n"
        "class E:\n"
        "    def __init__(self):\n"
        "        self._j = jax.jit(lambda s: s + 1,\n"
        "                          donate_argnums=(0,))\n"
        "    def step(self):\n"
        "        self.score = self._j(self.score)\n"
        "        return self.score\n")
    assert run_checker_on_source("donation-discipline", src) == []


# ---------------------------------------------------------------------------
# allowlist hygiene: exceptions must be explained AND alive
# ---------------------------------------------------------------------------
def test_allowlist_unexplained_and_stale_entries_are_findings(tmp_path):
    path = tmp_path / "demo.txt"
    path.write_text(
        "# demo\n"
        "a.py:key-with-reason  the reason\n"
        "b.py:key-without-reason\n")
    al = Allowlist.load("demo", str(path))
    # nothing filtered -> both entries unmatched; the reasoned one is
    # "stale", the bare one "unexplained"
    al.filter([])
    msgs = [f.message for f in al.hygiene_findings()]
    assert any("no reason" in m for m in msgs)
    assert any("stale" in m for m in msgs)


def test_live_allowlists_are_all_explained():
    from tools.analyze import CHECKERS
    for name in CHECKERS:
        al = Allowlist.load(name)
        assert al.unexplained == [], name


# ---------------------------------------------------------------------------
# the extended drift-guard sweep: table ⟺ constructor, EVERY engine
# ---------------------------------------------------------------------------
def _data(n=640, f=4, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f)).astype(np.float64)
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float64)
    return X, y


_BASE = {"objective": "binary", "num_leaves": 8, "verbosity": -1,
         "min_data_in_leaf": 5, "tpu_stream_block_rows": 64}
# per-engine params that make the PLAIN base construct
_ENGINE_BASE = {
    "gbdt": {},
    "dart": {"boosting": "dart"},
    "rf": {"boosting": "rf", "bagging_freq": 1, "bagging_fraction": 0.8},
    "streaming": {},
}


def _engine_cls(engine):
    from lightgbm_tpu.boosting.dart import DART
    from lightgbm_tpu.boosting.gbdt import GBDT
    from lightgbm_tpu.boosting.rf import RandomForest
    from lightgbm_tpu.boosting.streaming import StreamingGBDT
    return {"gbdt": GBDT, "dart": DART, "rf": RandomForest,
            "streaming": StreamingGBDT}[engine]


@pytest.mark.parametrize("engine", capabilities.ENGINES)
def test_engine_base_config_constructs(engine):
    """supported ⇒ constructs: every engine accepts its base config
    (the sweep's positive control)."""
    X, y = _data()
    cfg = Config({**_BASE, **_ENGINE_BASE[engine]})
    assert capabilities.supports(engine, cfg)
    eng = _engine_cls(engine)(cfg, lgb.Dataset(X, label=y))
    assert eng is not None


_FATAL_CASES = [
    (feature, engine)
    for feature, cap in capabilities.CAPABILITIES.items()
    for engine, v in cap.verdicts.items()
    if v == capabilities.FATAL and cap.example is not None
]


@pytest.mark.parametrize("feature,engine", _FATAL_CASES,
                         ids=[f"{e}-{f}" for f, e in _FATAL_CASES])
def test_table_fatal_means_constructor_refuses(feature, engine):
    """fatal ⇒ raises: every FATAL (feature, engine) cell, witnessed
    by the table's own example params, must make that engine's
    constructor raise — re-introducing a gate on one side without the
    other goes red here (the drift that produced the PR-5 bugs)."""
    cap = capabilities.CAPABILITIES[feature]
    params = {**_BASE, **_ENGINE_BASE[engine], **cap.example}
    cfg = Config(params)
    assert cap.requested(cfg), (feature, "example does not witness")
    assert not capabilities.supports(engine, cfg)
    X, y = _data()
    with pytest.raises(LightGBMError):
        _engine_cls(engine)(cfg, lgb.Dataset(X, label=y))


def test_every_fatal_row_has_a_witness():
    """A FATAL cell without example params cannot ride the sweep —
    only the runtime-only features (constructor kwargs, covered
    below) are exempt."""
    runtime_only = {"continuation"}
    missing = [f for f, cap in capabilities.CAPABILITIES.items()
               if capabilities.FATAL in cap.verdicts.values()
               and cap.example is None and f not in runtime_only]
    assert missing == []


def test_streaming_runtime_extras_fatal():
    """The runtime-only features (a custom fobj, init_forest
    continuation) fatal through the same table walk."""
    from lightgbm_tpu.boosting.streaming import StreamingGBDT
    X, y = _data()
    cfg = Config(dict(_BASE))
    with pytest.raises(LightGBMError):
        StreamingGBDT(cfg, lgb.Dataset(X, label=y),
                      fobj=lambda preds, ds: (preds, preds))
    with pytest.raises(LightGBMError):
        StreamingGBDT(cfg, lgb.Dataset(X, label=y),
                      init_forest=[object()])


def test_streaming_demote_drops_auto_quantize_only():
    """DEMOTE semantics: auto-enabled quantization is quietly dropped
    by the streaming engine; an EXPLICIT use_quantized_grad survives."""
    from lightgbm_tpu.boosting.streaming import StreamingGBDT
    X, y = _data()
    cfg = Config(dict(_BASE))
    cfg.use_quantized_grad = True
    cfg._quantize_auto = True            # as GBDT's auto switch sets it
    StreamingGBDT(cfg, lgb.Dataset(X, label=y))
    assert cfg.use_quantized_grad is False
    cfg2 = Config(dict(_BASE, use_quantized_grad=True))
    StreamingGBDT(cfg2, lgb.Dataset(X, label=y))
    assert cfg2.use_quantized_grad is True


def test_unhandled_demote_row_fails_loudly(monkeypatch):
    """A DEMOTE table row without a demotion action in StreamingGBDT
    must fatal, not silently no-op — the one-side-edited drift class."""
    from lightgbm_tpu.boosting.streaming import StreamingGBDT
    fake = capabilities.Capability(
        "a future demotable feature", lambda c: True,
        {"streaming": capabilities.DEMOTE})
    monkeypatch.setitem(capabilities.CAPABILITIES, "future_demote", fake)
    X, y = _data()
    with pytest.raises(LightGBMError, match="no.*demotion action"):
        StreamingGBDT(Config(dict(_BASE)), lgb.Dataset(X, label=y))


def test_streaming_compatible_is_the_table():
    """_streaming_compatible (the auto-router's gate) IS the table's
    streaming column — spot-check both polarities so the indirection
    cannot quietly break."""
    from lightgbm_tpu.boosting import _streaming_compatible
    ok = Config(dict(_BASE, tree_learner="data",
                     use_quantized_grad=True))
    assert _streaming_compatible(ok)
    assert capabilities.supports("streaming", ok)
    bad = Config(dict(_BASE, linear_tree=True))
    assert not _streaming_compatible(bad)
    assert "linear_tree" in capabilities.fatal_features("streaming", bad)
