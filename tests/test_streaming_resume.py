"""Streamed (out-of-core) checkpoint/resume — the one training engine
that had NO recovery integration before this PR, in exactly the regime
(long out-of-core runs) where preemption is the norm.

The contract pinned here (ISSUE 9 acceptance): a streamed×sharded run
interrupted by an injected fault and resumed from its newest
round-boundary checkpoint is BIT-IDENTICAL to the uninterrupted run —
at 1/2/4 shards × {plain, quantized, GOSS, bagging} — because
everything nondeterministic is either checkpointed (scores, host RNG,
pending round statistics) or a pure counter-hash of (seed, iteration,
global row index) that needs no state at all. Plus: mid-bagging-window
resume (the iter//freq salt makes it free), layout-change hard errors,
and checkpoint-state completeness.
"""
import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.recovery.checkpoint import CheckpointManager


def _data(n=8_000, f=10, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f))
    y = (X[:, 0] + 0.5 * X[:, 1] * X[:, 2]
         + rng.normal(scale=0.3, size=n) > 0).astype(np.float64)
    return X, y


# same shape family as tests/test_streaming_sharded.py BASE so the two
# modules share jit compiles (block 2048, leaves 16, depth 4)
BASE = {"objective": "binary", "num_leaves": 16, "max_depth": 4,
        "verbosity": -1, "min_data_in_leaf": 20,
        "tpu_streaming": "true", "tpu_stream_block_rows": 2_048}

ROUNDS = 5
KILL_AT = 3          # checkpoints at 2 and 4; the fault fires before 3
INTERVAL = 2


def _params(shards, ckpt_dir, **extra):
    p = dict(BASE, checkpoint_dir=str(ckpt_dir),
             checkpoint_interval=INTERVAL, **extra)
    if shards > 1:
        p["tree_learner"] = "data"
        p["tpu_mesh_shape"] = shards
    return p


def _interrupt_and_resume(X, y, shards, tmp_path, rounds=ROUNDS,
                          kill_at=KILL_AT, **extra):
    """Straight run, chaos-interrupted run, resumed run — returns the
    (straight, resumed) model texts."""
    straight = lgb.train(_params(shards, tmp_path / "straight", **extra),
                         lgb.Dataset(X, label=y), num_boost_round=rounds)
    p = _params(shards, tmp_path / "chaos",
                tpu_fault_inject=f"exn:iter={kill_at}", **extra)
    with pytest.raises(lgb.LightGBMError, match="injected failure"):
        lgb.train(p, lgb.Dataset(X, label=y), num_boost_round=rounds)
    resumed = lgb.train(p, lgb.Dataset(X, label=y),
                        num_boost_round=rounds,
                        resume_from=str(tmp_path / "chaos"))
    assert resumed.num_trees() == rounds
    return straight.model_to_string(), resumed.model_to_string()


# ---------------------------------------------------------------------------
# the acceptance matrix: 1/2/4 shards x plain/quantized/GOSS/bagging
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("shards", [1, 2, 4])
@pytest.mark.parametrize("extra", [
    {},
    {"use_quantized_grad": True},
    {"data_sample_strategy": "goss"},
    {"bagging_fraction": 0.6, "bagging_freq": 2},
], ids=["plain", "quant", "goss", "bagging"])
def test_streamed_resume_bit_identical(extra, shards, tmp_path):
    X, y = _data()
    m_straight, m_resumed = _interrupt_and_resume(X, y, shards,
                                                  tmp_path, **extra)
    assert m_resumed == m_straight


def test_streamed_resume_mid_bagging_window(tmp_path):
    """Kill INSIDE a bagging_freq window (freq=3: window k=1 spans
    iterations 3-5; the checkpoint at 4 resumes at iteration 4, mid-
    window). The bagging salt is a hash of (bagging_seed, iter//freq)
    — no host RNG stream to land mid-sequence in — so the resumed draw
    for iterations 4 and 5 is identical by construction."""
    X, y = _data(seed=3)
    m_straight, m_resumed = _interrupt_and_resume(
        X, y, 1, tmp_path, rounds=7, kill_at=5,
        bagging_fraction=0.6, bagging_freq=3)
    assert m_resumed == m_straight


def test_streamed_resume_with_valid_set_and_early_stopping(tmp_path):
    """The incremental valid-set raw cache and early-stopping state
    ride the checkpoint: resumed eval decisions match bit-for-bit."""
    X, y = _data(seed=5)
    Xv, yv = X[6_000:], y[6_000:]
    X, y = X[:6_000], y[:6_000]

    def run(ckpt_dir, fault=None, resume=False):
        ds = lgb.Dataset(X, label=y)
        vs = ds.create_valid(Xv, label=yv)
        p = _params(1, ckpt_dir, metric="auc", early_stopping_round=20)
        if fault:
            p["tpu_fault_inject"] = fault
        if resume:
            return lgb.train(p, ds, num_boost_round=8, valid_sets=[vs],
                             resume_from=str(ckpt_dir))
        return lgb.train(p, ds, num_boost_round=8, valid_sets=[vs])

    straight = run(tmp_path / "s")
    with pytest.raises(lgb.LightGBMError):
        run(tmp_path / "c", fault="exn:iter=5")
    resumed = run(tmp_path / "c", fault="exn:iter=5", resume=True)
    assert resumed.model_to_string() == straight.model_to_string()
    assert resumed.best_iteration == straight.best_iteration
    assert resumed.best_score == straight.best_score


# ---------------------------------------------------------------------------
# guard rails
# ---------------------------------------------------------------------------
def test_streamed_resume_rejects_layout_change(tmp_path):
    """Streamed scores are cut by the shard/block layout; resuming
    under a different block size (or mesh) must be a hard error naming
    what moved — there is no re-streaming score rebuild."""
    X, y = _data(n=4_000)
    p = _params(1, tmp_path)
    lgb.train(p, lgb.Dataset(X, label=y), num_boost_round=4)
    changed = dict(p, tpu_stream_block_rows=1_024)
    with pytest.raises(lgb.LightGBMError, match="layout|block"):
        lgb.train(changed, lgb.Dataset(X, label=y), num_boost_round=6,
                  resume_from=str(tmp_path))


def test_streamed_resume_rejects_engine_mismatch(tmp_path):
    """A resident-engine checkpoint resumed onto the streaming engine
    (or vice versa) must fatal, not silently adopt half a state."""
    X, y = _data(n=4_000)
    resident = {"objective": "binary", "num_leaves": 16, "verbosity": -1,
                "checkpoint_dir": str(tmp_path), "checkpoint_interval": 2}
    lgb.train(resident, lgb.Dataset(X, label=y), num_boost_round=4)
    streamed = dict(resident, tpu_streaming="true",
                    tpu_stream_block_rows=2_048)
    with pytest.raises(lgb.LightGBMError, match="GBDT engine"):
        lgb.train(streamed, lgb.Dataset(X, label=y), num_boost_round=6,
                  resume_from=str(tmp_path))


def test_streamed_checkpoint_state_is_complete(tmp_path):
    """The saved streamed engine state names every piece the resume
    contract advertises (guards against silently dropping a field)."""
    X, y = _data(n=4_000)
    p = _params(2, tmp_path, data_sample_strategy="goss",
                use_quantized_grad=True)
    lgb.train(p, lgb.Dataset(X, label=y), num_boost_round=4)
    st = CheckpointManager(str(tmp_path), rank=0).load()
    assert st["iteration"] == 4
    eng = st["engine"]
    assert eng["engine"] == "StreamingGBDT"
    for key in ("iteration", "models", "init_scores", "rng", "layout",
                "scores", "pending_stats", "valid_raw_cache"):
        assert key in eng, key
    lay = eng["layout"]
    assert lay["R"] == 2 and len(lay["ranks"]) == 2
    # per-(rank, block) score slots, padded to block_rows
    assert len(eng["scores"]) == 2
    assert all(s.dtype == np.float32 and len(s) == lay["block_rows"]
               for per_rank in eng["scores"] for s in per_rank)
    # GOSS+quant track round statistics; the fold from the final sweep
    # must travel (recomputing could fuse differently under XLA)
    assert eng["pending_stats"] is not None


def test_streamed_fresh_run_still_clears_stale_checkpoints(tmp_path):
    """The PR-6 fresh-run hygiene applies to the streamed engine too:
    a non-resume streamed run claiming a used dir clears it."""
    X, y = _data(n=4_000)
    p = _params(1, tmp_path)
    lgb.train(p, lgb.Dataset(X, label=y), num_boost_round=4)
    mgr = CheckpointManager(str(tmp_path), rank=0)
    assert mgr.latest_valid_iteration() == 4
    lgb.train(p, lgb.Dataset(X, label=y), num_boost_round=2)
    assert mgr.iterations() == [2]
