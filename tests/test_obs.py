"""Observability subsystem (lightgbm_tpu/obs/; docs/observability.md).

What these tests pin, per pillar:

* **Metrics registry** — thread-safety under concurrent increments
  (serving is threaded), label fan-out, kind-collision errors, and the
  JSONL / Prometheus exporters' formats.
* **Tracing** — span nesting (per-thread stack, parent/depth args) and
  Chrome-trace export schema validity: the file must be loadable by
  Perfetto, i.e. ``traceEvents`` of ``ph:"X"`` complete events with
  microsecond ``ts``/``dur`` and child spans contained in their parent.
* **Persistence** — metrics survive checkpoint/restore: a
  ``resume_from=`` cycle CONTINUES the interrupted run's counters
  (train.iterations reaches the total round count, the resume counter
  increments) instead of restarting them at zero.
* **Device telemetry** — the CompileWatch signal as a continuous
  metric: warm serving increments ``compile.requests`` by ZERO, and
  the stack-cache hit counter proves the warm path was taken.
* **Off-by-default** — a run without ``tpu_metrics`` records nothing
  (the registry stays empty; spans are the shared no-op context).
"""
import json
import threading

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu import obs
from lightgbm_tpu.obs import metrics as obs_metrics
from lightgbm_tpu.obs import tracing as obs_tracing


@pytest.fixture(autouse=True)
def _isolated_obs(monkeypatch):
    """Every test gets a clean, DISABLED obs world and cannot leak an
    enabled registry (or a pinned process-global trace dir) into the
    rest of tier-1 — the off-by-default guarantee the suite relies on
    for its timing."""
    obs.disable()
    obs.reset()
    monkeypatch.setattr(obs_tracing, "_dir", None)
    yield
    obs.disable()
    obs.reset()
    monkeypatch.setattr(obs_tracing, "_dir", None)


def _data(n=1200, f=8, seed=3):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f))
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float64)
    return X, y


PARAMS = {"objective": "binary", "num_leaves": 7, "verbosity": -1,
          "min_data_in_leaf": 20}


# ---------------------------------------------------------------------------
# metrics registry
# ---------------------------------------------------------------------------
def test_registry_thread_safety_under_concurrent_increments():
    reg = obs_metrics.MetricsRegistry()
    threads, per_thread, n_threads = [], 5000, 8

    def hammer(i):
        # same counter from every thread + get-or-create races on a
        # per-thread labeled one + histogram observes
        c = reg.counter("stress.total")
        mine = reg.counter("stress.labeled", thread=i % 2)
        h = reg.histogram("stress.lat")
        for _ in range(per_thread):
            c.inc()
            mine.inc()
            h.observe(0.001)

    for i in range(n_threads):
        t = threading.Thread(target=hammer, args=(i,))
        threads.append(t)
        t.start()
    for t in threads:
        t.join()

    total = n_threads * per_thread
    assert reg.get("stress.total").value == total
    assert (reg.get("stress.labeled", thread=0).value
            + reg.get("stress.labeled", thread=1).value) == total
    h = reg.get("stress.lat")
    assert h.count == total
    assert sum(h.bucket_counts) == total


def test_registry_labels_kinds_and_exporters():
    reg = obs_metrics.MetricsRegistry()
    reg.counter("req", model="a").inc(3)
    reg.counter("req", model="b").inc()
    reg.gauge("hbm.bytes_limit").set(1e9)
    h = reg.histogram("lat", buckets=(0.1, 1.0))
    h.observe(0.05)
    h.observe(0.5)
    h.observe(99.0)

    # same name, different labels -> distinct metrics; kind collision
    # on the same (name, labels) key is an error, not silent reuse
    assert reg.get("req", model="a").value == 3
    with pytest.raises(TypeError, match="already registered"):
        reg.gauge("req", model="a")

    snap = reg.snapshot()
    assert snap["schema"] == "lightgbm-tpu-metrics-v1"
    by_name = {}
    for m in snap["metrics"]:
        by_name.setdefault(m["name"], []).append(m)
    assert len(by_name["req"]) == 2
    lat = by_name["lat"][0]
    assert lat["count"] == 3 and lat["min"] == 0.05 and lat["max"] == 99.0
    # +inf auto-appended, cumulative export is per-bucket counts here
    assert [b for b, _c in lat["buckets"]] == [0.1, 1.0, "+Inf"]
    assert [c for _b, c in lat["buckets"]] == [1, 1, 1]
    # the whole snapshot must be JSON-able (the JSONL dump contract)
    json.dumps(snap)

    prom = reg.prometheus_text()
    assert '# TYPE req counter' in prom
    assert 'req{model="a"} 3' in prom
    # Prometheus histogram semantics: cumulative buckets + sum/count
    assert 'lat_bucket{le="0.1"} 1' in prom
    assert 'lat_bucket{le="1"} 2' in prom
    assert 'lat_bucket{le="+Inf"} 3' in prom
    assert 'lat_count 3' in prom


def test_dump_jsonl_appends_parseable_lines(tmp_path):
    path = str(tmp_path / "m" / "metrics.jsonl")
    obs.enable(metrics=True)
    obs.inc("x")
    obs.dump_jsonl(path)
    obs.inc("x")
    obs.dump_jsonl(path)
    lines = open(path).read().splitlines()
    assert len(lines) == 2
    snaps = [json.loads(ln) for ln in lines]
    vals = [[m["value"] for m in s["metrics"] if m["name"] == "x"][0]
            for s in snaps]
    assert vals == [1, 2]


# ---------------------------------------------------------------------------
# tracing
# ---------------------------------------------------------------------------
def test_span_nesting_and_chrome_trace_schema(tmp_path):
    obs.enable(metrics=True, trace_dir=str(tmp_path))
    with obs.span("outer", phase="test"):
        assert obs.span_stack() == ["outer"]
        with obs.span("inner"):
            assert obs.span_stack() == ["outer", "inner"]
    assert obs.span_stack() == []

    out = obs.export_chrome_trace()
    assert out is not None and out.endswith(".json")
    doc = json.load(open(out))
    # Perfetto/chrome://tracing JSON object form: a traceEvents list of
    # complete events with microsecond ts/dur, plus the process/thread
    # naming metadata rows the export prepends
    meta = [e for e in doc["traceEvents"] if e["ph"] == "M"]
    assert any(e["name"] == "process_name" for e in meta)
    # the export envelope carries the wall/monotonic pair the
    # cross-rank merger rebases on (scripts/trace_merge.py)
    assert {"ts", "monotonic"} <= set(doc["otherData"])
    events = {e["name"]: e for e in doc["traceEvents"]
              if e["ph"] == "X"}
    assert set(events) == {"outer", "inner"}
    for e in events.values():
        assert e["ph"] == "X"
        assert isinstance(e["ts"], float) and isinstance(e["dur"], float)
        assert isinstance(e["pid"], int) and isinstance(e["tid"], int)
    inner, outer = events["inner"], events["outer"]
    assert inner["args"]["parent"] == "outer"
    assert inner["args"]["depth"] == 1
    assert outer["args"]["phase"] == "test"
    # containment: the child renders inside the parent on the timeline
    assert inner["ts"] >= outer["ts"]
    assert inner["ts"] + inner["dur"] <= outer["ts"] + outer["dur"] + 1.0
    # spans double as duration histograms in the registry
    assert obs.registry().get("outer").count == 1


def test_trace_buffer_bounded_and_dropped_counted(monkeypatch):
    monkeypatch.setattr(obs_tracing, "MAX_EVENTS", 4)
    obs.enable(trace=True, metrics=False)
    for i in range(9):
        with obs.span(f"s{i}"):
            pass
    # oldest-dropped: a long-lived process keeps its most RECENT
    # window (the one a p99 postmortem needs), counting the evictions
    assert [e["name"] for e in obs_tracing.events()] == \
        ["s5", "s6", "s7", "s8"]
    assert obs_tracing.dropped_events() == 5


def test_span_is_shared_noop_when_disabled():
    # off-by-default hot-path cost: one bool check, one shared
    # nullcontext instance — no per-call allocation
    assert obs.span("a") is obs.span("b")
    with obs.span("a"):
        pass
    assert obs.registry().get("a") is None
    # force=True measures regardless (the utils/timer shim contract)
    with obs.span("forced", force=True):
        pass
    assert obs.registry().get("forced").count == 1


def test_timer_shim_records_into_registry():
    from lightgbm_tpu.utils.timer import (log_timers, reset_timers,
                                          timed, timer_totals)
    with timed("phase_a"):
        pass
    with timed("phase_a"):
        pass
    totals = timer_totals()
    assert "phase_a" in totals and totals["phase_a"] >= 0.0
    assert obs.registry().get("phase_a").count == 2
    log_timers()                      # smoke: reads the same registry
    # reset_timers clears TIMERS (histograms) only — cumulative
    # counters/gauges (compile, restart telemetry) are not timers
    obs.counter("compile.requests").inc(5)
    obs.gauge("hbm.bytes_limit").set(1.0)
    reset_timers()
    assert obs.registry().get("phase_a") is None
    assert obs.counter("compile.requests").value == 5
    assert obs.gauge("hbm.bytes_limit").value == 1.0


# ---------------------------------------------------------------------------
# end-to-end: train + warm predict with tpu_metrics=true
# ---------------------------------------------------------------------------
def test_train_and_warm_predict_populate_metrics(tmp_path):
    dump = str(tmp_path / "metrics.jsonl")
    X, y = _data()
    ds = lgb.Dataset(X, label=y)
    # fuse disabled so the PER-ROUND loop (train/round, train/update,
    # train/step spans) is the path under test; fused-chunk training
    # records train/fused instead
    params = dict(PARAMS, tpu_metrics=True, tpu_metrics_dump=dump,
                  tpu_trace_dir=str(tmp_path / "tr"), tpu_fuse_iters=1)
    bst = lgb.train(params, ds, num_boost_round=5)
    p1 = bst.predict(X[:256])
    p2 = bst.predict(X[:256])         # warm: same shape bucket
    np.testing.assert_allclose(p1, p2)

    snap = bst.metrics()
    names = {m["name"] for m in snap["metrics"]}
    # per-round phase timings, predict latency histogram, cache-hit
    # counters, compile-count and cache-size gauges (ISSUE acceptance)
    assert {"train/round", "train/update", "train/step",
            "dataset/construct", "predict/call",
            "predict.requests", "predict.rows",
            "train.iterations", "compile.requests",
            "compile.predict_programs"} <= names
    get = {m["name"]: m for m in snap["metrics"]}
    assert get["train.iterations"]["value"] == 5
    assert get["train/round"]["count"] == 5
    assert get["predict.requests"]["value"] == 2
    assert get["predict.rows"]["value"] == 512
    assert get["predict/call"]["count"] == 2
    assert get["compile.predict_programs"]["value"] >= 1
    # second predict hit the stacked-forest cache
    assert get["predict.stack_cache_hits"]["value"] >= 1

    # the run's end wrote the JSONL dump + the Chrome trace
    lines = [ln for ln in open(dump).read().splitlines() if ln.strip()]
    assert lines and json.loads(lines[-1])["schema"] \
        == "lightgbm-tpu-metrics-v1"
    trace = obs.export_chrome_trace()
    assert trace is not None
    tnames = {e["name"] for e in json.load(open(trace))["traceEvents"]}
    assert {"train/round", "train/update", "predict/call"} <= tnames


def test_warm_serving_compiles_zero_as_metric():
    """The CompileWatch signal as a gauge: after the cold call, repeat
    predicts at the same bucketed shape add ZERO compile requests."""
    X, y = _data()
    ds = lgb.Dataset(X, label=y)
    bst = lgb.train(dict(PARAMS, tpu_metrics=True), ds,
                    num_boost_round=4)
    bst.predict(X[:200])              # cold: traces + compiles
    cold = obs.counter("compile.requests").value
    hits = obs.counter("predict.stack_cache_hits").value
    for _ in range(3):
        bst.predict(X[:200])
    assert obs.counter("compile.requests").value == cold
    assert obs.counter("predict.stack_cache_hits").value == hits + 3


def test_booster_metrics_on_streaming_and_file_boosters(tmp_path):
    """Booster.metrics() works on every booster flavor: the streaming
    engine (no GBDT.metrics_snapshot) and a model-file booster (no
    engine at all) fall back to the process-wide snapshot."""
    X, y = _data()
    ds = lgb.Dataset(X, label=y)
    bst = lgb.train(dict(PARAMS, tpu_metrics=True, tpu_streaming=True),
                    ds, num_boost_round=3)
    assert bst.metrics()["schema"] == "lightgbm-tpu-metrics-v1"
    path = str(tmp_path / "m.txt")
    bst.save_model(path)
    loaded = lgb.Booster(model_file=path)
    assert loaded.metrics()["schema"] == "lightgbm-tpu-metrics-v1"


def test_metrics_off_by_default_records_nothing():
    X, y = _data()
    ds = lgb.Dataset(X, label=y)
    bst = lgb.train(dict(PARAMS), ds, num_boost_round=3)
    bst.predict(X[:100])
    assert obs.registry().metrics() == []
    assert not obs.enabled()


def test_record_metrics_callback_sink():
    X, y = _data()
    ds = lgb.Dataset(X, label=y)
    sink = []
    lgb.train(dict(PARAMS), ds, num_boost_round=4,
              callbacks=[lgb.record_metrics(sink, period=2)])
    assert [s["iteration"] for s in sink] == [1, 3]
    names = {m["name"] for m in sink[-1]["metrics"]}
    assert "train/update" in names
    it = [m for m in sink[-1]["metrics"]
          if m["name"] == "train.iterations"][0]
    assert it["value"] == 4
    with pytest.raises(TypeError, match="list or a callable"):
        lgb.record_metrics(sink=42)


def test_crashed_run_still_writes_exports(tmp_path):
    """The observability artifacts matter MOST on runs that die: a
    training run that raises mid-loop must still write the configured
    metrics dump and Chrome trace."""
    dump = str(tmp_path / "crash.jsonl")
    X, y = _data()
    ds = lgb.Dataset(X, label=y)
    params = dict(PARAMS, tpu_metrics=True, tpu_metrics_dump=dump,
                  tpu_trace_dir=str(tmp_path / "tr"),
                  checkpoint_dir=str(tmp_path / "ck"),
                  checkpoint_interval=2,
                  tpu_fault_inject="exn:iter=3")
    with pytest.raises(lgb.LightGBMError, match="injected failure"):
        lgb.train(params, ds, num_boost_round=10)
    snap = json.loads(open(dump).read().splitlines()[-1])
    names = {m["name"] for m in snap["metrics"]}
    assert "train/round" in names
    import glob
    traces = glob.glob(str(tmp_path / "tr" / "trace_*.json"))
    assert traces and json.load(open(traces[0]))["traceEvents"]


# ---------------------------------------------------------------------------
# persistence: metrics survive checkpoint/restore
# ---------------------------------------------------------------------------
def test_metrics_survive_checkpoint_restore_cycle(tmp_path):
    """Interrupt at iteration 17 (checkpoint at 10), wipe the registry
    (a restarted process starts empty), resume: the restored counters
    CONTINUE — train.iterations ends at the full round total and the
    resume counter increments across the cycle."""
    ckdir = str(tmp_path / "ck")
    X, y = _data(n=2000)
    params = dict(PARAMS, tpu_metrics=True, checkpoint_dir=ckdir,
                  checkpoint_interval=10,
                  tpu_fault_inject="exn:iter=17")

    ds = lgb.Dataset(X, label=y)
    with pytest.raises(lgb.LightGBMError, match="injected failure"):
        lgb.train(params, ds, num_boost_round=30)
    assert obs.counter("train.iterations").value == 17
    assert obs.counter("checkpoint.saves").value >= 1

    # simulate the restarted process: empty registry, metrics off until
    # the resuming run's Config re-enables them
    obs.disable()
    obs.reset()
    assert obs.registry().metrics() == []

    ds = lgb.Dataset(X, label=y)
    resumed = lgb.train(params, ds, num_boost_round=30,
                        resume_from=ckdir)
    assert resumed.num_trees() == 30
    # 10 iterations adopted from the checkpoint's obs state + 20 run
    # here — a fresh-start registry would read 20
    assert obs.counter("train.iterations").value == 30
    assert obs.counter("train.resumes").value == 1
    # the restore that powered THIS resume survives the state import —
    # EXACTLY once (the interrupted run never restored, so its saved
    # state lacks the metric; folding live values back on top of an
    # absent saved metric must not double-count)
    assert obs.counter("checkpoint.restores").value == 1
    assert obs.registry().get("checkpoint/restore").count == 1

    # a resume with metrics OFF must not repopulate the registry from
    # the checkpoint (off-by-default means empty, forced counters aside)
    obs.disable()
    obs.reset()
    ds = lgb.Dataset(X, label=y)
    off = {k: v for k, v in params.items() if k != "tpu_metrics"}
    lgb.train(off, ds, num_boost_round=30, resume_from=ckdir)
    assert obs.registry().get("train.iterations") is None
    assert obs.counter("train.resumes").value == 1      # forced


def test_registry_state_roundtrip_overwrites_not_merges():
    obs.enable(metrics=True)
    obs.inc("a", 7)
    obs.observe("h", 0.3)
    state = obs.export_state()
    obs.reset()
    obs.inc("a", 100)                 # pre-restore noise
    assert obs.import_state(state) == 2
    assert obs.counter("a").value == 7          # overwritten, not 107
    h = obs.registry().get("h")
    assert h.count == 1 and h.sum == pytest.approx(0.3)
    assert obs.import_state(None) == 0


# ---------------------------------------------------------------------------
# CLI: task=dump_metrics
# ---------------------------------------------------------------------------
def test_cli_dump_metrics_reads_jsonl(tmp_path, capsys):
    from lightgbm_tpu.app import run
    path = str(tmp_path / "m.jsonl")
    obs.enable(metrics=True)
    obs.inc("train.iterations", 12)
    obs.dump_jsonl(path)
    assert run([f"task=dump_metrics", f"data={path}",
                "verbosity=-1"]) == 0
    out = capsys.readouterr().out
    assert "# TYPE train_iterations counter" in out
    assert "train_iterations 12" in out
    assert run([f"task=dump_metrics", f"data={path}", "format=json",
                "verbosity=-1"]) == 0
    snap = json.loads(capsys.readouterr().out)
    assert snap["schema"] == "lightgbm-tpu-metrics-v1"

    bad = tmp_path / "bad.jsonl"
    bad.write_text("{not json\n")
    with pytest.raises(lgb.LightGBMError, match="not valid JSON"):
        run([f"task=dump_metrics", f"data={bad}", "verbosity=-1"])
