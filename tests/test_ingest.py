"""Device-accelerated ingest (ops/ingest.py): bit-equality + warm-start.

The contract under test:
1. Device-assigned bins are BIT-IDENTICAL to the host
   ``BinMapper.values_to_bins`` path across numerical/categorical
   features, every ``missing_type`` (none/zero/nan), ``zero_as_missing``,
   forced bounds, and >256-bin uint16 layouts.
2. The feature-major ``bins_t`` tile matches the host
   ``binned.T.astype(int8)`` wraparound layout exactly.
3. Fixed-shape chunking + the jit cache mean a SECOND same-shape
   ``Dataset.construct`` (and engine build) compiles ZERO new XLA
   programs — the warm-start serving metric.
4. End-to-end: training on a device-ingested dataset produces the same
   model text as the host path.
"""
import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.io.binning import BinMapper, find_bin_mappers
from lightgbm_tpu.ops.ingest import (build_tables, device_ingest,
                                     ingest_program_cache_size)
from lightgbm_tpu.utils.debug import CompileWatch


def _f32_matrix(n, f, seed=0, nan_cols=(), zero_cols=(), cat_cols=(),
                cat_card=20):
    """f32-representable float64 matrix (the exactness contract's
    domain) with missing values, exact zeros and categorical columns."""
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f)).astype(np.float32).astype(np.float64)
    for c in zero_cols:
        X[:, c] = np.where(rng.uniform(size=n) < 0.3, 0.0, X[:, c])
    for c in cat_cols:
        X[:, c] = rng.integers(0, cat_card, size=n).astype(np.float64)
    for c in nan_cols:
        X[rng.uniform(size=n) < 0.1, c] = np.nan
    return X


def _host_bins(X, mappers, used, dtype=np.uint8):
    return np.stack([mappers[f].values_to_bins(X[:, f]).astype(dtype)
                     for f in used], axis=1)


def _device_vs_host(X, mappers, chunk_rows=1024, dtype=np.uint8,
                    transposed=True):
    used = [i for i, m in enumerate(mappers) if not m.is_trivial]
    host = _host_bins(X, mappers, used, dtype)
    res = device_ingest(X, mappers, used, dtype, chunk_rows=chunk_rows,
                        emit_transposed=transposed)
    dev = np.asarray(res.bins)
    np.testing.assert_array_equal(host, dev)
    if transposed:
        np.testing.assert_array_equal(host.T.astype(np.int8),
                                      np.asarray(res.bins_t))


# ---------------------------------------------------------------------------
# 1. bit-equality across the mapping semantics
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("use_missing", [True, False])
@pytest.mark.parametrize("zero_as_missing", [False, True])
def test_bit_equality_missing_semantics(use_missing, zero_as_missing):
    X = _f32_matrix(7013, 6, seed=3, nan_cols=(1, 2), zero_cols=(2, 4))
    mappers = find_bin_mappers(X, max_bin=64, use_missing=use_missing,
                               zero_as_missing=zero_as_missing)
    _device_vs_host(X, mappers)


def test_bit_equality_categorical():
    X = _f32_matrix(5003, 5, seed=4, nan_cols=(1,), cat_cols=(2, 3),
                    cat_card=40)
    # category 3 also gets out-of-range/negative raw values (must map
    # to the NaN/unseen bin 0, like the host path)
    rng = np.random.default_rng(9)
    X[rng.uniform(size=len(X)) < 0.05, 3] = -7.0
    X[rng.uniform(size=len(X)) < 0.05, 3] = 10_000.0
    mappers = find_bin_mappers(X, max_bin=32,
                               categorical_features=[2, 3])
    _device_vs_host(X, mappers)


def test_bit_equality_high_cardinality_categorical():
    # large id space exercises the sorted-table binary search (the
    # kernel must stay O(R*Fu*log C) — no [R, Fu, C] broadcast)
    X = _f32_matrix(6007, 4, seed=21, cat_cols=(1,), cat_card=1500)
    mappers = find_bin_mappers(X, max_bin=255, categorical_features=[1])
    _device_vs_host(X, mappers, dtype=np.uint16)


def test_large_categorical_ids_fall_back_to_host():
    # 64-bit hash-style ids sit outside the exact float32/int32 window
    # (the f32 chunk stream cannot represent them): build_tables must
    # refuse, and even forced tpu_ingest_device=true must stand down to
    # the host int64 path — with bins identical to a plain host run
    from lightgbm_tpu.ops.ingest import cat_device_safe
    X = _f32_matrix(2003, 3, seed=22, cat_cols=(2,), cat_card=10)
    X[::7, 2] = float(2**32 + 5)    # exact in f64, wraps int32 to 5
    mappers = find_bin_mappers(X, max_bin=32, categorical_features=[2])
    used = [i for i, m in enumerate(mappers) if not m.is_trivial]
    assert not cat_device_safe(mappers, used)
    with pytest.raises(ValueError):
        build_tables(mappers, used, np.uint8)
    y = (X[:, 0] > 0).astype(float)
    dsd = lgb.Dataset(X, label=y, categorical_feature=[2],
                      params={"tpu_ingest_device": True,
                              "verbosity": -1}).construct()
    assert dsd.device_ingested() is None
    dsh = lgb.Dataset(X, label=y, categorical_feature=[2],
                      params={"tpu_ingest_device": False,
                              "verbosity": -1}).construct()
    np.testing.assert_array_equal(np.asarray(dsd.binned),
                                  np.asarray(dsh.binned))


def test_bit_equality_float32_input_and_odd_chunks():
    X64 = _f32_matrix(4999, 4, seed=5, nan_cols=(0,), zero_cols=(1,))
    X32 = X64.astype(np.float32)
    mappers = find_bin_mappers(X64, max_bin=255)
    # chunk size that never divides the row count: the padded tail
    # chunk must slice away cleanly
    _device_vs_host(X32, mappers, chunk_rows=777)
    _device_vs_host(X64, mappers, chunk_rows=777)


def test_bit_equality_forced_bounds():
    X = _f32_matrix(3001, 3, seed=6, zero_cols=(1,))
    mappers = find_bin_mappers(
        X, max_bin=32, forced_bins={0: [-1.0, 0.25, 1.5],
                                    2: [0.0, 0.5]})
    _device_vs_host(X, mappers)


def test_bit_equality_inf_values():
    X = _f32_matrix(2003, 3, seed=7)
    X[5, 0] = np.inf
    X[6, 0] = -np.inf
    mappers = find_bin_mappers(X, max_bin=32)
    _device_vs_host(X, mappers)


def test_bit_equality_uint16_wide_bins():
    rng = np.random.default_rng(8)
    n = 9000
    # >256 distinct values so max_bin=600 genuinely exceeds uint8
    X = np.round(rng.normal(size=(n, 2)) * 500).astype(np.float32) \
        .astype(np.float64)
    mappers = find_bin_mappers(X, max_bin=600, min_data_in_bin=1)
    used = [i for i, m in enumerate(mappers) if not m.is_trivial]
    assert max(mappers[f].num_bin for f in used) > 256
    _device_vs_host(X, mappers, dtype=np.uint16, transposed=False)


def test_f32_exclusive_bounds_edge():
    """The boundary trick itself: a float64 bound that is NOT f32-
    representable must bin every f32 value exactly as the f64 compare
    does — including the f32 neighbors bracketing the bound."""
    from lightgbm_tpu.ops.ingest import _f32_exclusive
    b64 = np.float64(0.1) + 1e-12        # not f32-representable
    lo = np.float32(b64)                 # f32 just below/at
    hi = np.nextafter(lo, np.float32(np.inf), dtype=np.float32)
    m = BinMapper(bin_type="numerical", num_bin=2, missing_type="none",
                  bin_upper_bound=np.array([b64, np.inf]))
    for v in (lo, hi, np.float32(0.0), np.float32(1.0)):
        host = m.values_to_bins(np.array([np.float64(v)]))[0]
        excl = _f32_exclusive(m.bin_upper_bound)
        dev = int(np.searchsorted(excl, np.float32(v), side="right"))
        dev = min(dev, len(m.bin_upper_bound) - 1)
        assert dev == host, (v, host, dev)


# ---------------------------------------------------------------------------
# 2. Dataset-level wiring
# ---------------------------------------------------------------------------

def _mk_ds(X, y, dev, **extra):
    p = {"tpu_ingest_device": dev, **extra}
    return lgb.Dataset(X, label=y, params=p)


def test_dataset_device_resident_lazy_host():
    X = _f32_matrix(4096, 5, seed=11)
    y = (X[:, 0] > 0).astype(np.float64)
    ds = _mk_ds(X, y, "true").construct()
    assert ds.device_ingested() is not None
    assert ds._binned is None            # host copy NOT materialized
    assert ds.binned_dtype() == np.uint8  # ...and dtype probe keeps it so
    assert ds._binned is None
    host = _mk_ds(X, y, "false").construct().binned
    np.testing.assert_array_equal(ds.binned, host)   # lazy materialize


def test_device_data_layouts_match_host_upload():
    from lightgbm_tpu.boosting.gbdt import _DeviceData
    X = _f32_matrix(3000, 6, seed=12, nan_cols=(1,))
    y = (X[:, 0] > 0).astype(np.float64)
    dd_dev = _DeviceData(_mk_ds(X, y, "true").construct(), 512, None,
                         transposed=True)
    dd_host = _DeviceData(_mk_ds(X, y, "false").construct(), 512, None,
                          transposed=True)
    np.testing.assert_array_equal(np.asarray(dd_dev.bins),
                                  np.asarray(dd_host.bins))
    assert dd_dev.bins_t.dtype == np.int8
    np.testing.assert_array_equal(np.asarray(dd_dev.bins_t),
                                  np.asarray(dd_host.bins_t))


def test_train_bit_identical_and_subset():
    X = _f32_matrix(4000, 8, seed=13, nan_cols=(2,), cat_cols=(6,))
    rng = np.random.default_rng(13)
    y = (X[:, 0] + rng.normal(size=len(X)) * 0.3 > 0).astype(np.float64)
    params = {"objective": "binary", "num_leaves": 15, "verbosity": -1}
    models = {}
    for dev in ("false", "true"):
        ds = lgb.Dataset(X, label=y, categorical_feature=[6],
                         params={"tpu_ingest_device": dev})
        bst = lgb.train({**params, "tpu_ingest_device": dev}, ds,
                        num_boost_round=6)
        models[dev] = bst.model_to_string()
    assert models["true"] == models["false"]
    # subset (cv fold path) materializes the host copy lazily
    ds = _mk_ds(X, y, "true").construct()
    sub = ds.subset(np.arange(0, 4000, 3))
    assert sub.binned.shape[0] == len(np.arange(0, 4000, 3))


def test_training_never_materializes_host_copy():
    # the full train path — including the default-on EFB bundle probe —
    # must leave a device-resident dataset device-resident: the lazy
    # host copy stays unmaterialized for the whole run
    X = _f32_matrix(4003, 6, seed=23, nan_cols=(1,))
    y = (X[:, 0] > 0).astype(np.float64)
    ds = _mk_ds(X, y, "true")
    lgb.train({"objective": "binary", "num_leaves": 15, "verbosity": -1,
               "tpu_ingest_device": "true"}, ds, num_boost_round=2)
    assert ds.device_ingested() is not None
    assert ds._binned is None


def test_tristate_spellings_shared_accept_list():
    # Config validation and the Dataset-side gate accept the same
    # spellings: 'on'/'1'/'yes' == true, 'off'/'0'/'no' == false
    from lightgbm_tpu.config import Config, coerce_tristate
    assert coerce_tristate("on") == "true"
    assert coerce_tristate("OFF") == "false"
    assert coerce_tristate(True) == "true"
    cfg = Config({"tpu_ingest_device": "on", "tpu_streaming": "0"})
    assert cfg.tpu_ingest_device == "true"
    assert cfg.tpu_streaming == "false"
    X = _f32_matrix(1031, 3, seed=24)
    y = (X[:, 0] > 0).astype(np.float64)
    ds = lgb.Dataset(X, label=y,
                     params={"tpu_ingest_device": "on",
                             "verbosity": -1}).construct()
    assert ds.device_ingested() is not None


# ---------------------------------------------------------------------------
# 3. warm start: second same-shape construct compiles nothing
# ---------------------------------------------------------------------------

def test_second_construct_zero_compiles():
    X = _f32_matrix(4096, 5, seed=14, nan_cols=(1,))
    y = (X[:, 0] > 0).astype(np.float64)
    _mk_ds(X, y, "true").construct()     # cold: compiles the kernel
    progs = ingest_program_cache_size()
    assert progs >= 1
    with CompileWatch("second construct") as w:
        ds2 = _mk_ds(X, y, "true").construct()
        np.asarray(ds2.device_ingested().bins)[0]  # force execution
    w.assert_compiles(0)
    assert ingest_program_cache_size() == progs


def test_second_construct_and_engine_init_zero_compiles():
    from lightgbm_tpu.boosting.gbdt import GBDT
    from lightgbm_tpu.config import Config
    X = _f32_matrix(4096, 5, seed=15)
    y = (X[:, 0] > 0).astype(np.float64)
    params = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
              "tree_learner": "serial", "tpu_ingest_device": "true"}
    GBDT(Config(params), _mk_ds(X, y, "true"))      # cold
    with CompileWatch("construct+init") as w:
        GBDT(Config(params), _mk_ds(X, y, "true"))  # same shapes
    w.assert_compiles(0)


def test_chunking_is_shape_stable():
    """Different row counts with the same chunk size reuse one compiled
    program (the padded fixed-shape chunk contract)."""
    mappers = find_bin_mappers(_f32_matrix(2048, 4, seed=16), max_bin=32)
    used = list(range(4))
    before = ingest_program_cache_size()
    for n in (1500, 2048, 3000):
        X = _f32_matrix(n, 4, seed=17)
        res = device_ingest(X, mappers, used, np.uint8, chunk_rows=1024)
        np.asarray(res.bins)
    assert ingest_program_cache_size() <= before + 1


# ---------------------------------------------------------------------------
# 4. threaded host fallback
# ---------------------------------------------------------------------------

def test_threaded_host_fallback_matches_serial(monkeypatch):
    from lightgbm_tpu.io import binning as binning_mod
    monkeypatch.setattr(binning_mod, "_native", lambda: None)
    X = _f32_matrix(250_000, 9, seed=18, nan_cols=(1,), cat_cols=(7,))
    y = (X[:, 0] > 0).astype(np.float64)
    serial = lgb.Dataset(X, label=y, categorical_feature=[7],
                         params={"tpu_ingest_device": "false",
                                 "tpu_ingest_threads": 1}) \
        .construct().binned
    threaded = lgb.Dataset(X, label=y, categorical_feature=[7],
                           params={"tpu_ingest_device": "false",
                                   "tpu_ingest_threads": 4}) \
        .construct().binned
    np.testing.assert_array_equal(serial, threaded)
