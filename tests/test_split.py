"""Split finder vs brute-force NumPy oracle."""
import numpy as np
import jax.numpy as jnp

from lightgbm_tpu.ops.split import (SplitConfig, calc_leaf_output,
                                    find_best_split, leaf_gain)


def _oracle_best(hist, num_bin, has_nan, cfg):
    """Brute force over (feature, threshold, direction)."""
    F, B, _ = hist.shape
    parent = hist[0].sum(axis=0)  # any feature's bins sum to the totals
    def lg(g, h):
        t = np.sign(g) * max(abs(g) - cfg.lambda_l1, 0.0) \
            if cfg.lambda_l1 > 0 else g
        return t * t / (h + cfg.lambda_l2) if h + cfg.lambda_l2 > 0 else 0.0
    pg = lg(parent[0], parent[1])
    best = (-np.inf, None)
    for f in range(F):
        nb = num_bin[f]
        nv = nb - (1 if has_nan[f] else 0)
        nan_vals = hist[f, nb - 1] if has_nan[f] else np.zeros(3)
        for t in range(nv - 1 + (1 if has_nan[f] else 0)):
            base_left = hist[f, :t + 1].sum(axis=0)
            if has_nan[f] and t >= nb - 1:
                continue
            for dl in (False, True):
                left = base_left + (nan_vals if dl else 0)
                right = parent - left
                if left[2] < cfg.min_data_in_leaf or \
                        right[2] < cfg.min_data_in_leaf:
                    continue
                if left[1] < cfg.min_sum_hessian_in_leaf or \
                        right[1] < cfg.min_sum_hessian_in_leaf:
                    continue
                gain = lg(left[0], left[1]) + lg(right[0], right[1]) - pg
                if gain > cfg.min_gain_to_split and gain > best[0]:
                    best = (gain, (f, t, dl))
    return best


def _random_case(seed, F=5, B=16, l1=0.0, l2=0.0, min_data=1):
    rng = np.random.default_rng(seed)
    num_bin = rng.integers(4, B + 1, size=F).astype(np.int32)
    has_nan = rng.uniform(size=F) < 0.5
    hist = np.zeros((F, B, 3), dtype=np.float64)
    n = 500
    g = rng.normal(size=n)
    h = rng.uniform(0.1, 1.0, size=n)
    for f in range(F):
        b = rng.integers(0, num_bin[f], size=n)
        np.add.at(hist[f], b, np.stack([g, h, np.ones(n)], axis=1))
    cfg = SplitConfig(lambda_l1=l1, lambda_l2=l2, min_data_in_leaf=min_data,
                      min_sum_hessian_in_leaf=1e-3, min_gain_to_split=0.0)
    return hist, num_bin, has_nan, cfg


def test_matches_oracle():
    for seed in range(8):
        hist, num_bin, has_nan, cfg = _random_case(seed)
        parent = hist[0].sum(axis=0)
        res = find_best_split(
            jnp.asarray(hist, jnp.float32), jnp.asarray(parent, jnp.float32),
            jnp.asarray(num_bin), jnp.asarray(has_nan),
            jnp.ones(len(num_bin), dtype=bool), cfg)
        o_gain, o_split = _oracle_best(hist, num_bin, has_nan, cfg)
        if o_split is None:
            assert not np.isfinite(float(res["gain"]))
            continue
        np.testing.assert_allclose(float(res["gain"]), o_gain, rtol=1e-4)
        # the chosen split must achieve the oracle gain (ties allowed)
        f, t, dl = (int(res["feature"]), int(res["threshold_bin"]),
                    bool(res["default_left"]))
        # recompute gain of the returned split via oracle formula
        hist2 = hist.copy()
        nb = num_bin[f]
        nan_vals = hist2[f, nb - 1] if has_nan[f] else np.zeros(3)
        left = hist2[f, :t + 1].sum(axis=0)
        if has_nan[f]:
            left = left - (nan_vals if t >= nb - 1 else 0)
            if dl:
                left = left + nan_vals
        right = hist2[0].sum(axis=0) - left
        np.testing.assert_allclose(
            float(res["left_sums"][2]), left[2], rtol=1e-5)


def test_constraints_block_all():
    hist, num_bin, has_nan, _ = _random_case(99)
    cfg = SplitConfig(min_data_in_leaf=10**6)
    parent = hist[0].sum(axis=0)
    res = find_best_split(
        jnp.asarray(hist, jnp.float32), jnp.asarray(parent, jnp.float32),
        jnp.asarray(num_bin), jnp.asarray(has_nan),
        jnp.ones(len(num_bin), dtype=bool), cfg)
    assert not np.isfinite(float(res["gain"]))


def test_feature_mask_respected():
    hist, num_bin, has_nan, cfg = _random_case(3)
    parent = hist[0].sum(axis=0)
    allowed = np.zeros(len(num_bin), dtype=bool)
    allowed[2] = True
    res = find_best_split(
        jnp.asarray(hist, jnp.float32), jnp.asarray(parent, jnp.float32),
        jnp.asarray(num_bin), jnp.asarray(has_nan), jnp.asarray(allowed),
        cfg)
    if np.isfinite(float(res["gain"])):
        assert int(res["feature"]) == 2


def test_l1_l2_regularization_reduces_gain():
    hist, num_bin, has_nan, cfg0 = _random_case(5)
    parent = hist[0].sum(axis=0)
    args = (jnp.asarray(hist, jnp.float32), jnp.asarray(parent, jnp.float32),
            jnp.asarray(num_bin), jnp.asarray(has_nan),
            jnp.ones(len(num_bin), dtype=bool))
    g0 = float(find_best_split(*args, cfg0)["gain"])
    g_l2 = float(find_best_split(
        *args, SplitConfig(lambda_l2=10.0, min_data_in_leaf=1))["gain"])
    assert g_l2 < g0


def test_leaf_output_formula():
    out = float(calc_leaf_output(jnp.float32(10.0), jnp.float32(5.0),
                                 0.0, 1.0))
    np.testing.assert_allclose(out, -10.0 / 6.0, rtol=1e-6)
    out_l1 = float(calc_leaf_output(jnp.float32(10.0), jnp.float32(5.0),
                                    2.0, 1.0))
    np.testing.assert_allclose(out_l1, -8.0 / 6.0, rtol=1e-6)
    out_clip = float(calc_leaf_output(jnp.float32(10.0), jnp.float32(5.0),
                                      0.0, 0.0, max_delta_step=0.5))
    np.testing.assert_allclose(out_clip, -0.5, rtol=1e-6)
