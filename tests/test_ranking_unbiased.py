"""Unbiased LambdaRank (position-bias debiasing).

Reference: rank_objective.hpp ``lambdarank_unbiased`` (UNVERIFIED — empty
mount); formulation follows Unbiased LambdaMART (Hu et al. 2019):
per-rank propensities estimated from accumulated pairwise costs, applied
as 1/(t+ * t-) pair weights. ``lambdarank_bias_p_norm=0`` makes the
correction an exact no-op, which pins the plumbing end-to-end.
"""
import numpy as np
import pytest

import lightgbm_tpu as lgb


def _rank_data(seed=0, n_query=40, per_q=24):
    rng = np.random.default_rng(seed)
    n = n_query * per_q
    X = rng.normal(size=(n, 6))
    rel = np.clip((X[:, 0] + 0.5 * X[:, 1]
                   + rng.normal(scale=0.5, size=n)) * 1.2 + 1.5,
                  0, 4).astype(int).astype(float)
    group = np.full(n_query, per_q)
    return X, rel, group


def _train(params, n_iter=8):
    X, y, group = _rank_data()
    ds = lgb.Dataset(X, label=y, group=group)
    base = {"objective": "lambdarank", "num_leaves": 15,
            "min_data_in_leaf": 5, "verbosity": -1}
    base.update(params)
    return lgb.train(base, ds, num_boost_round=n_iter), X


def test_p_norm_zero_is_exact_noop():
    bst_plain, X = _train({})
    bst_un, _ = _train({"lambdarank_unbiased": True,
                        "lambdarank_bias_p_norm": 0.0})
    np.testing.assert_allclose(bst_plain.predict(X), bst_un.predict(X),
                               rtol=0, atol=0)


def test_unbiased_trains_and_learns_propensities():
    X, y, group = _rank_data(seed=1)
    ds = lgb.Dataset(X, label=y, group=group)
    from lightgbm_tpu.boosting.gbdt import GBDT
    from lightgbm_tpu.config import Config
    cfg = Config({"objective": "lambdarank", "num_leaves": 15,
                  "min_data_in_leaf": 5, "verbosity": -1,
                  "lambdarank_unbiased": True})
    eng = GBDT(cfg, ds)
    assert eng._pos_state is not None
    assert eng._pos_state.shape == (2, 24)
    for _ in range(6):
        eng.train_one_iter()
    st = np.asarray(eng._pos_state)
    assert np.isfinite(st).all()
    assert (st > 0).all()
    # rank 0 is the normalization anchor; later ranks saw pairs and
    # must have moved off the neutral initialization. The high-side
    # propensity t+ decays with rank (harder to "click" far down) while
    # the low-side ratio may exceed 1 — only anchoring is guaranteed.
    np.testing.assert_allclose(st[:, 0], 1.0, atol=1e-6)
    assert st[0, 1:8].min() < 0.999
    assert st[0, 1] > st[0, 12]      # t+ decreasing overall
    # the model still ranks: predictions correlate with relevance
    pred = eng.predict(X)
    assert np.corrcoef(pred, y)[0, 1] > 0.3


def test_unbiased_under_goss():
    bst, X = _train({"lambdarank_unbiased": True,
                     "data_sample_strategy": "goss",
                     "learning_rate": 0.5, "top_rate": 0.3,
                     "other_rate": 0.2}, n_iter=6)
    assert np.isfinite(bst.predict(X)).all()


def test_unbiased_rejects_distributed():
    import jax
    if jax.device_count() < 2:
        pytest.skip("needs a multi-device mesh")
    X, y, group = _rank_data()
    ds = lgb.Dataset(X, label=y, group=group)
    with pytest.raises(lgb.LightGBMError, match="lambdarank_unbiased"):
        lgb.train({"objective": "lambdarank", "tree_learner": "data",
                   "num_machines": 2, "lambdarank_unbiased": True,
                   "verbosity": -1, "num_leaves": 15}, ds,
                  num_boost_round=2)


def test_positions_auto_enable_debiasing():
    """Reference behavior: a `position` field activates debiasing with
    NO flag (rank_objective.hpp position_bias_ — UNVERIFIED). A config
    ported from the reference with positions set must not silently
    train biased."""
    from lightgbm_tpu.boosting.gbdt import GBDT
    from lightgbm_tpu.config import Config
    X, y, group = _rank_data(seed=3)
    rng = np.random.default_rng(1)
    pos = np.concatenate([rng.permutation(24) for _ in range(40)])
    ds = lgb.Dataset(X, label=y, group=group)
    ds.set_field("position", pos)
    cfg = Config({"objective": "lambdarank", "num_leaves": 15,
                  "min_data_in_leaf": 5, "verbosity": -1})
    eng = GBDT(cfg, ds)                  # no lambdarank_unbiased flag
    assert eng.objective.unbiased
    assert eng._pos_state is not None
    assert eng._pos_state.shape == (2, 24)
    for _ in range(3):
        eng.train_one_iter()
    assert np.isfinite(np.asarray(eng._pos_state)).all()


def test_bias_reg_derives_exponent():
    """Propensity exponent follows the reference's 1/(1+regularization)
    unless lambdarank_bias_p_norm >= 0 overrides it."""
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.objective.ranking import LambdaRank
    o = LambdaRank(Config({"objective": "lambdarank",
                           "lambdarank_position_bias_regularization": 1.0,
                           "verbosity": -1}))
    assert o.bias_p_norm == 0.5
    o2 = LambdaRank(Config({"objective": "lambdarank",
                            "lambdarank_bias_p_norm": 0.25,
                            "verbosity": -1}))
    assert o2.bias_p_norm == 0.25


def test_explicit_positions_consumed():
    """With a `position` field, propensities index by presentation
    position (Metadata::positions, v4.2+) instead of score rank —
    permuting row order within queries while keeping positions fixed
    must not change the propensity table's size anchor."""
    from lightgbm_tpu.boosting.gbdt import GBDT
    from lightgbm_tpu.config import Config
    X, y, group = _rank_data(seed=2)
    rng = np.random.default_rng(0)
    pos = np.concatenate([rng.permutation(24) for _ in range(40)])
    ds = lgb.Dataset(X, label=y, group=group)
    ds.set_field("position", pos)
    cfg = Config({"objective": "lambdarank", "num_leaves": 15,
                  "min_data_in_leaf": 5, "verbosity": -1,
                  "lambdarank_unbiased": True})
    eng = GBDT(cfg, ds)
    assert eng._pos_state.shape == (2, 24)     # max position + 1
    for _ in range(4):
        eng.train_one_iter()
    st = np.asarray(eng._pos_state)
    assert np.isfinite(st).all() and (st > 0).all()
    np.testing.assert_allclose(st[:, 0], 1.0, atol=1e-6)
    assert np.isfinite(eng.predict(X)).all()
