"""The zero-silently-ignored-params contract (VERDICT r2 item 6).

Every entry in the config table must be one of:
  1. consumed somewhere in package source (outside config.py's table),
  2. declared UNIMPLEMENTED (warns when set to a non-default value), or
  3. declared DISSOLVED (an implementation hint whose correct TPU/XLA
     behavior is "no action", with a recorded rationale).

Reference: upstream honors every documented param via config_auto.cpp
(SURVEY.md:88) — this test is the enforcement mechanism for that parity
claim at param granularity."""
import inspect
import pathlib
import re

import lightgbm_tpu.config as C


def _package_source_without_param_table() -> str:
    pkg = pathlib.Path(C.__file__).parent
    src = []
    for p in sorted(pkg.rglob("*.py")):
        if p.name == "config.py":
            continue
        src.append(p.read_text())
    # config.py consumes some params itself (CheckParamConflict fixups),
    # but its _PARAMS table mentions every name — include only the
    # consuming code, not the table
    src.append(inspect.getsource(C.Config._post_process))
    src.append(inspect.getsource(type(C.Config(
        {"verbosity": -1})).num_tree_per_iteration.fget))
    return "\n".join(src)


def test_every_param_consumed_warned_or_dissolved():
    src = _package_source_without_param_table()
    unaccounted = []
    for name in C.Config.param_names():
        if name in C.UNIMPLEMENTED_PARAMS:
            continue
        if name in C.DISSOLVED_PARAMS:
            continue
        if not re.search(rf"\b{name}\b", src):
            unaccounted.append(name)
    assert not unaccounted, (
        f"params neither consumed in source nor declared in "
        f"UNIMPLEMENTED_PARAMS/DISSOLVED_PARAMS: {unaccounted}")


def test_tables_are_disjoint_and_valid():
    names = set(C.Config.param_names())
    unimp = set(C.UNIMPLEMENTED_PARAMS)
    diss = set(C.DISSOLVED_PARAMS)
    assert unimp <= names, unimp - names
    assert diss <= names, diss - names
    assert not (unimp & diss)
    # every dissolved rationale is a real sentence, not a stub
    for k, v in {**C.UNIMPLEMENTED_PARAMS, **C.DISSOLVED_PARAMS}.items():
        assert len(v) > 15, (k, v)
