"""The zero-silently-ignored-params contract (VERDICT r2 item 6).

Every entry in the config table must be one of:
  1. consumed somewhere in package source (outside config.py's table),
  2. declared UNIMPLEMENTED (warns when set to a non-default value), or
  3. declared DISSOLVED (an implementation hint whose correct TPU/XLA
     behavior is "no action", with a recorded rationale).

Reference: upstream honors every documented param via config_auto.cpp
(SURVEY.md:88) — this test is the enforcement mechanism for that parity
claim at param granularity."""
import inspect
import io
import pathlib
import re
import tokenize

import lightgbm_tpu.config as C


def _strip_comments_and_docstrings(source: str) -> str:
    """Drop COMMENT tokens and statement-level strings (docstrings) so a
    param mentioned only in prose cannot pass the audit. String literals
    inside expressions survive — ``params["max_bin"]`` /
    ``getattr(cfg, "max_bin")`` are real consumption."""
    out = []
    prev = None
    in_docstring = False
    toks = tokenize.generate_tokens(io.StringIO(source).readline)
    for tok in toks:
        if tok.type == tokenize.COMMENT:
            continue
        if tok.type == tokenize.STRING:
            # statement-level string, or a continuation segment of one
            # (implicit concatenation: "a" "b" tokenizes as two STRINGs)
            if in_docstring or prev in (
                    None, tokenize.NEWLINE, tokenize.NL, tokenize.INDENT,
                    tokenize.DEDENT):
                in_docstring = True
                continue
        elif tok.type != tokenize.NL:
            in_docstring = False
        if tok.type != tokenize.NL:
            prev = tok.type
        out.append(tok.string)
    return " ".join(out)


def _package_source_without_param_table() -> str:
    pkg = pathlib.Path(C.__file__).parent
    src = []
    for p in sorted(pkg.rglob("*.py")):
        if p.name == "config.py":
            continue
        src.append(_strip_comments_and_docstrings(p.read_text()))
    # config.py consumes some params itself (CheckParamConflict fixups),
    # but its _PARAMS table mentions every name — include only the
    # consuming code, not the table
    src.append(_strip_comments_and_docstrings(
        inspect.getsource(C.Config._post_process)))
    src.append(_strip_comments_and_docstrings(inspect.getsource(
        type(C.Config({"verbosity": -1})).num_tree_per_iteration.fget)))
    return "\n".join(src)


def test_every_param_consumed_warned_or_dissolved():
    src = _package_source_without_param_table()
    unaccounted = []
    for name in C.Config.param_names():
        if name in C.UNIMPLEMENTED_PARAMS:
            continue
        if name in C.DISSOLVED_PARAMS:
            continue
        if not re.search(rf"\b{name}\b", src):
            unaccounted.append(name)
    assert not unaccounted, (
        f"params neither consumed in source nor declared in "
        f"UNIMPLEMENTED_PARAMS/DISSOLVED_PARAMS: {unaccounted}")


def test_tables_are_disjoint_and_valid():
    names = set(C.Config.param_names())
    unimp = set(C.UNIMPLEMENTED_PARAMS)
    diss = set(C.DISSOLVED_PARAMS)
    assert unimp <= names, unimp - names
    assert diss <= names, diss - names
    assert not (unimp & diss)
    # every dissolved rationale is a real sentence, not a stub
    for k, v in {**C.UNIMPLEMENTED_PARAMS, **C.DISSOLVED_PARAMS}.items():
        assert len(v) > 15, (k, v)
