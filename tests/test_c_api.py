"""Native C ABI (LGBMTPU_*): parity with the Python predict path.

The reference's C API (src/c_api.cpp, SURVEY.md L7, UNVERIFIED) is the
seam every binding funnels through. Here the seam is the predict/model
surface only (docs/design.md records why); these tests drive the real
shared object through ctypes the way an external C caller would and pin
bit-level agreement with HostModel.predict across every traversal
semantic: missing types, categorical bitsets, linear leaves, multiclass
transform, RF averaging, iteration slicing.
"""
import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.native import CBooster, c_api

pytestmark = pytest.mark.skipif(c_api() is None,
                                reason="no native toolchain")


def _with_nans(X, frac=0.1, seed=0):
    rng = np.random.default_rng(seed)
    X = X.copy()
    mask = rng.random(X.shape) < frac
    X[mask] = np.nan
    return X


def _train(params, X, y, rounds=10, **dskw):
    ds = lgb.Dataset(X, label=y, **dskw)
    p = {"verbosity": -1, "num_leaves": 15}
    p.update(params)
    return lgb.train(p, ds, num_boost_round=rounds)


def _pair(bst):
    """CBooster + Python Booster over the SAME text model (both traverse
    the f64 text model; the live engine Booster predicts via the binned
    device path and differs at ~1e-7)."""
    s = bst.model_to_string()
    return CBooster(model_str=s), lgb.Booster(model_str=s)


def _binary_data(n=2000, f=6, seed=42):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f))
    y = (X[:, 0] + 0.5 * X[:, 1] * X[:, 2]
         + rng.normal(scale=0.3, size=n) > 0).astype(np.float64)
    return X, y


def test_binary_normal_raw_leaf_parity():
    X, y = _binary_data()
    bst0 = _train({"objective": "binary"}, X, y)
    cb, bst = _pair(bst0)
    Xq = _with_nans(X[:500])
    # live engine Booster agrees at float32-threshold tolerance
    np.testing.assert_allclose(cb.predict(Xq), bst0.predict(Xq),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(cb.predict(Xq), bst.predict(Xq),
                               rtol=1e-12, atol=1e-12)
    np.testing.assert_allclose(
        cb.predict(Xq, CBooster.PREDICT_RAW),
        bst.predict(Xq, raw_score=True), rtol=1e-12, atol=1e-12)
    leaf_c = cb.predict(Xq, CBooster.PREDICT_LEAF)
    leaf_py = bst.predict(Xq, pred_leaf=True)
    np.testing.assert_array_equal(leaf_c.astype(np.int64), leaf_py)


def test_metadata_accessors():
    X, y = _binary_data()
    bst = _train({"objective": "binary"}, X, y, rounds=7)
    cb = CBooster(model_str=bst.model_to_string())
    assert cb.num_iterations == 7
    assert cb.num_classes == 1
    assert cb.num_feature == X.shape[1]


def test_model_file_and_save_roundtrip(tmp_path):
    X, y = _binary_data(n=800)
    bst = _train({"objective": "binary"}, X, y, rounds=5)
    p1 = str(tmp_path / "m1.txt")
    p2 = str(tmp_path / "m2.txt")
    bst.save_model(p1)
    cb = CBooster(model_file=p1)
    cb.save_model(p2)
    # C-saved file loads back in the PYTHON Booster with equal output
    bst1 = lgb.Booster(model_file=p1)
    bst2 = lgb.Booster(model_file=p2)
    np.testing.assert_allclose(bst1.predict(X), bst2.predict(X),
                               rtol=1e-12, atol=1e-12)
    np.testing.assert_allclose(bst.predict(X), bst2.predict(X),
                               rtol=1e-5, atol=1e-6)
    assert cb.model_to_string() == open(p1).read()


def test_multiclass_softmax_parity():
    rng = np.random.default_rng(1)
    X = rng.normal(size=(1500, 5))
    y = (np.abs(X[:, 0]) * 2 + np.abs(X[:, 1])).astype(np.int64) % 3
    bst = _train({"objective": "multiclass", "num_class": 3}, X, y)
    cb, bst = _pair(bst)
    Xq = _with_nans(X[:400], seed=3)
    np.testing.assert_allclose(cb.predict(Xq), bst.predict(Xq),
                               rtol=1e-12, atol=1e-12)
    # leaf width = trees = iters * num_class
    leaves = cb.predict(Xq, CBooster.PREDICT_LEAF)
    assert leaves.shape == (400, 10 * 3)
    np.testing.assert_array_equal(leaves, bst.predict(Xq, pred_leaf=True))


def test_categorical_bitset_parity():
    rng = np.random.default_rng(7)
    n = 3000
    cat = rng.integers(0, 40, size=n).astype(np.float64)
    num = rng.normal(size=(n, 3))
    X = np.column_stack([cat, num])
    y = ((cat % 7 < 3).astype(np.float64) + num[:, 0]
         + rng.normal(scale=0.2, size=n) > 0.5).astype(np.float64)
    bst = _train({"objective": "binary"}, X, y,
                 categorical_feature=[0])
    cb, bst = _pair(bst)
    # unseen categories (>=40), negative and NaN values all go right
    Xq = X[:500].copy()
    Xq[:50, 0] = 99.0
    Xq[50:100, 0] = -3.0
    Xq[100:150, 0] = np.nan
    np.testing.assert_allclose(cb.predict(Xq), bst.predict(Xq),
                               rtol=1e-12, atol=1e-12)


def test_linear_tree_parity():
    rng = np.random.default_rng(5)
    X = rng.normal(size=(2000, 4))
    y = X @ np.array([1.0, -2.0, 0.5, 0.0]) + np.sin(X[:, 0] * 3)
    bst = _train({"objective": "regression", "linear_tree": True},
                 X, y)
    cb, bst = _pair(bst)
    Xq = _with_nans(X[:500], frac=0.15, seed=9)  # exercises nan_found
    np.testing.assert_allclose(cb.predict(Xq), bst.predict(Xq),
                               rtol=1e-12, atol=1e-12)


def test_rf_average_output_parity():
    X, y = _binary_data(n=1500, seed=11)
    bst = _train({"objective": "binary", "boosting": "rf",
                  "bagging_fraction": 0.7, "bagging_freq": 1},
                 X, y, rounds=8)
    cb, bst = _pair(bst)
    np.testing.assert_allclose(cb.predict(X), bst.predict(X),
                               rtol=1e-12, atol=1e-12)


def test_iteration_slicing_parity():
    X, y = _binary_data(n=1200, seed=13)
    bst = _train({"objective": "binary"}, X, y, rounds=12)
    cb, bst = _pair(bst)
    for start, num in [(0, 5), (3, 4), (2, -1)]:
        np.testing.assert_allclose(
            cb.predict(X, CBooster.PREDICT_RAW, start_iteration=start,
                       num_iteration=num),
            bst.predict(X, raw_score=True, start_iteration=start,
                        num_iteration=num),
            rtol=1e-12, atol=1e-12)


def test_regression_objectives_transform():
    rng = np.random.default_rng(17)
    X = rng.normal(size=(1500, 4))
    y = np.exp(0.5 * X[:, 0] + 0.2 * X[:, 1])  # positive target
    for obj in ("regression", "poisson", "tweedie"):
        bst = _train({"objective": obj}, X, y, rounds=6)
        cb, bst = _pair(bst)
        np.testing.assert_allclose(cb.predict(X), bst.predict(X),
                                   rtol=1e-12, atol=1e-12)


def test_error_paths():
    X, y = _binary_data(n=500)
    bst = _train({"objective": "binary"}, X, y, rounds=3)
    cb = CBooster(model_str=bst.model_to_string())
    with pytest.raises(ValueError, match="columns"):
        cb.predict(X[:, :3])          # too few features
    with pytest.raises(ValueError):
        CBooster(model_str="not a model")
    with pytest.raises(ValueError):
        CBooster(model_file="/nonexistent/model.txt")
    # malformed models must fail the parse-time structural check, not
    # read out of bounds at predict time
    s = bst.model_to_string()
    nfeat = X.shape[1]
    bad_feat = s.replace("split_feature=", "split_feature=100 ", 1)
    assert f"max_feature_idx={nfeat - 1}" in s
    with pytest.raises(ValueError, match="Malformed"):
        CBooster(model_str=bad_feat)
    bad_child = s.replace("left_child=", "left_child=9999 ", 1)
    with pytest.raises(ValueError, match="Malformed"):
        CBooster(model_str=bad_child)
    # self-loop (node 0 -> node 0) must be rejected at parse time, not
    # spin forever at predict time
    import re
    cyc = re.sub(r"left_child=-?\d+", "left_child=0", s, count=1)
    with pytest.raises(ValueError, match="Malformed"):
        CBooster(model_str=cyc)
    # garbage tokens must error, not silently zero-fill
    garb = s.replace("threshold=", "threshold=zzz ", 1)
    with pytest.raises(ValueError, match="Malformed"):
        CBooster(model_str=garb)


def test_col_major_input():
    X, y = _binary_data(n=600, seed=19)
    bst = _train({"objective": "binary"}, X, y, rounds=5)
    cb, bst = _pair(bst)
    import ctypes
    lib = c_api()
    Xf = np.asfortranarray(X)
    out = np.zeros(len(X), dtype=np.float64)
    out_len = ctypes.c_int64()
    rc = lib.LGBMTPU_BoosterPredictForMat(
        cb._h, Xf.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        len(X), X.shape[1], 0, 1, 0, -1,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        ctypes.byref(out_len))
    assert rc == 0 and out_len.value == len(X)
    np.testing.assert_allclose(out, bst.predict(X, raw_score=True),
                               rtol=1e-12, atol=1e-12)


def test_fuzz_truncated_and_bitflipped_models():
    """VERDICT r4 item 5: a truncated or bit-flipped model file must
    come back as an error code (ValueError through ctypes) or a clean
    parse — never an OOB read/crash. The same corpus runs under ASAN
    via scripts/fuzz_c_api.sh (g++ -fsanitize=address on the
    standalone driver native/fuzz_main.cpp)."""
    X, y = _binary_data(n=400, f=5, seed=23)
    # categorical + linear paths have the most index arithmetic
    Xc = X.copy()
    Xc[:, 4] = np.floor(np.abs(Xc[:, 4]) * 7) % 12
    bst = _train({"objective": "binary"}, Xc, y, rounds=4,
                 categorical_feature=[4])
    s = bst.model_to_string()
    rng = np.random.default_rng(99)
    corpus = []
    # truncations: byte offsets spread over the file, plus the tail
    for cut in np.linspace(10, len(s) - 1, 40).astype(int):
        corpus.append(s[:cut])
    # bit flips / char swaps inside the tree blocks
    body_start = s.find("Tree=")
    for _ in range(120):
        pos = int(rng.integers(body_start, len(s)))
        ch = chr(int(rng.integers(32, 127)))
        corpus.append(s[:pos] + ch + s[pos + 1:])
    # digit-to-huge-number splices (the SIZE_MAX cast class of bug)
    for tok in ("threshold=", "cat_boundaries=", "left_child=",
                "split_feature=", "num_leaves="):
        corpus.append(s.replace(tok, tok + "1e300 ", 1))
        corpus.append(s.replace(tok, tok + "-999999999 ", 1))
    n_ok = n_err = 0
    for m in corpus:
        try:
            cb = CBooster(model_str=m)
            cb.predict(Xc[:8])    # parse survived -> predict must too
            n_ok += 1
        except ValueError:
            n_err += 1
    # every case accounted for, and the corpus actually exercised the
    # reject path (a corpus of accidental no-ops proves nothing)
    assert n_ok + n_err == len(corpus)
    assert n_err > len(corpus) // 2
