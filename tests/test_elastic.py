"""Elastic topology (ISSUE 15): topology-free streamed checkpoints,
score re-cut on resume, and degrade-and-continue gangs.

The contract pinned here: a streamed×sharded run killed mid-training
resumes at a DIFFERENT shard count (4 → 2 and 4 → 8) with trees
BIT-IDENTICAL (quantized path — integer level histograms are
shard/block-cut-invariant) to the uninterrupted 4-shard run, including
a mid-bagging-window cut and the GOSS pending-statistics re-reduction;
rows whose saved slots are unreachable replay bit-exactly from the
pickled trees; re-cut eligibility is a capability-table verdict
(`capabilities.stream_recut_verdict`) whose refusal names the blocking
feature, the table cell, and the override knob; and the launcher
degrades-and-continues past a permanently-lost host (the `resize`
chaos fault's `.host_gone.rank<r>` markers) at reduced width without
consuming `max_restarts`, counting `watchdog.degrades`.
"""
import json
import os

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu import capabilities, obs
from lightgbm_tpu.config import Config
from lightgbm_tpu.parallel import launch
from lightgbm_tpu.recovery.checkpoint import (CheckpointManager,
                                              latest_complete_iteration)
from lightgbm_tpu.recovery.faults import (clear_host_gone_markers,
                                          host_gone_ranks,
                                          parse_fault_spec)


def _data(n=8_000, f=10, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f))
    y = (X[:, 0] + 0.5 * X[:, 1] * X[:, 2]
         + rng.normal(scale=0.3, size=n) > 0).astype(np.float64)
    return X, y


# same shape family as tests/test_streaming_resume.py BASE so the
# modules share jit compiles (block 2048, leaves 16, depth 4); the
# QUANTIZED path is what makes cross-topology resume bit-exact
BASE = {"objective": "binary", "num_leaves": 16, "max_depth": 4,
        "verbosity": -1, "min_data_in_leaf": 20,
        "tpu_streaming": "true", "tpu_stream_block_rows": 2_048,
        "use_quantized_grad": True}

ROUNDS = 5
KILL_AT = 3          # checkpoints at 2 and 4; the fault fires before 3


def _params(shards, ckpt_dir, **extra):
    p = dict(BASE, checkpoint_dir=str(ckpt_dir),
             checkpoint_interval=2, **extra)
    if shards > 1:
        p["tree_learner"] = "data"
        p["tpu_mesh_shape"] = shards
    else:
        p.pop("tpu_mesh_shape", None)
    return p


def _kill_mid_run(X, y, shards, ckpt_dir, rounds=ROUNDS,
                  kill_at=KILL_AT, **extra):
    p = _params(shards, ckpt_dir, tpu_fault_inject=f"exn:iter={kill_at}",
                **extra)
    with pytest.raises(lgb.LightGBMError, match="injected failure"):
        lgb.train(p, lgb.Dataset(X, label=y), num_boost_round=rounds)


# ---------------------------------------------------------------------------
# the acceptance matrix: 4-shard training killed mid-run resumes at 2
# AND at 8 shards bit-equal to the uninterrupted 4-shard run
# ---------------------------------------------------------------------------
def test_elastic_resume_4_to_2_and_8_bit_equal(tmp_path):
    X, y = _data()
    straight = lgb.train(_params(4, tmp_path / "s"),
                         lgb.Dataset(X, label=y),
                         num_boost_round=ROUNDS)
    _kill_mid_run(X, y, 4, tmp_path / "c")
    before = getattr(obs.registry().get("train.topology_changes"),
                     "value", 0.0)
    for new_shards in (2, 8):
        resumed = lgb.train(_params(new_shards, tmp_path / "c"),
                            lgb.Dataset(X, label=y),
                            num_boost_round=ROUNDS,
                            resume_from=str(tmp_path / "c"))
        assert resumed.num_trees() == ROUNDS
        assert resumed.model_to_string() == straight.model_to_string(), \
            f"4 -> {new_shards} elastic resume lost bit-equality"
    after = obs.registry().get("train.topology_changes").value
    assert after >= before + 2        # each re-cut resume counted


def test_elastic_resume_mid_bagging_window(tmp_path):
    """Kill INSIDE a bagging_freq window, resume NARROWER: the bagging
    salt is a counter-hash of (bagging_seed, iter//freq, GLOBAL row
    index), so the re-cut shards redraw the identical mid-window mask
    at the new width."""
    X, y = _data(seed=3)
    extra = {"bagging_fraction": 0.6, "bagging_freq": 3}
    straight = lgb.train(_params(4, tmp_path / "s", **extra),
                         lgb.Dataset(X, label=y), num_boost_round=7)
    _kill_mid_run(X, y, 4, tmp_path / "c", rounds=7, kill_at=5, **extra)
    resumed = lgb.train(_params(2, tmp_path / "c", **extra),
                        lgb.Dataset(X, label=y), num_boost_round=7,
                        resume_from=str(tmp_path / "c"))
    assert resumed.model_to_string() == straight.model_to_string()


def test_elastic_resume_goss_pending_stats_re_reduce(tmp_path):
    """GOSS + quantized tracks pending round statistics; on a re-cut
    they re-reduce (element-wise max / integer sum — grouping-
    invariant) instead of travelling per-rank, and the continued
    trees stay bit-equal."""
    X, y = _data(seed=5)
    extra = {"data_sample_strategy": "goss"}
    straight = lgb.train(_params(4, tmp_path / "s", **extra),
                         lgb.Dataset(X, label=y),
                         num_boost_round=ROUNDS)
    _kill_mid_run(X, y, 4, tmp_path / "c", **extra)
    resumed = lgb.train(_params(2, tmp_path / "c", **extra),
                        lgb.Dataset(X, label=y),
                        num_boost_round=ROUNDS,
                        resume_from=str(tmp_path / "c"))
    assert resumed.model_to_string() == straight.model_to_string()


def test_replay_from_trees_is_bit_exact(tmp_path):
    """Rows with no reachable saved slot recompute from the pickled
    trees — the replay runs the final sweep's exact f32 arithmetic, so
    continuing from replayed scores is bit-equal to continuing from
    the saved ones."""
    X, y = _data(seed=7)
    straight = lgb.train(_params(1, tmp_path / "s"),
                         lgb.Dataset(X, label=y),
                         num_boost_round=ROUNDS)
    _kill_mid_run(X, y, 1, tmp_path / "c")
    mgr = CheckpointManager(str(tmp_path / "c"), rank=0)
    st = mgr.load()
    st["engine"]["scores"] = None          # lose every saved slot
    st.pop("_checkpoint_path", None)
    mgr.save(st, int(st["iteration"]))
    resumed = lgb.train(_params(1, tmp_path / "c"),
                        lgb.Dataset(X, label=y),
                        num_boost_round=ROUNDS,
                        resume_from=str(tmp_path / "c"))
    assert resumed.model_to_string() == straight.model_to_string()


# ---------------------------------------------------------------------------
# eligibility: a capability-table verdict, not an inline engine gate
# ---------------------------------------------------------------------------
def test_recut_verdict_table():
    quant = Config({"objective": "binary", "use_quantized_grad": True,
                    "verbosity": -1})
    assert capabilities.stream_recut_verdict(quant)[0] \
        == capabilities.SUPPORTED
    f32 = Config({"objective": "binary", "verbosity": -1})
    v, why = capabilities.stream_recut_verdict(f32)
    assert v == capabilities.FATAL
    assert "tpu_elastic_recut" in why and "STREAM_RECUT" in why
    forced = Config({"objective": "binary", "verbosity": -1,
                     "tpu_elastic_recut": "true"})
    assert capabilities.stream_recut_verdict(forced)[0] \
        == capabilities.DEMOTE
    pinned = Config({"objective": "binary", "use_quantized_grad": True,
                     "verbosity": -1, "tpu_elastic_recut": "false"})
    assert capabilities.stream_recut_verdict(pinned)[0] \
        == capabilities.FATAL


def test_recut_refused_f32_names_feature_cell_and_knob(tmp_path):
    """The exact-f32 refusal must tell the operator WHAT blocks (f32
    accumulation), WHERE the judgment lives (the table cell) and HOW
    to override (the knob) — not just that a layout moved."""
    X, y = _data(n=4_000, seed=9)
    f32 = {k: v for k, v in BASE.items() if k != "use_quantized_grad"}
    p = dict(f32, checkpoint_dir=str(tmp_path),
             checkpoint_interval=2)
    lgb.train(p, lgb.Dataset(X, label=y), num_boost_round=4)
    changed = dict(p, tpu_stream_block_rows=1_024)
    with pytest.raises(lgb.LightGBMError) as ei:
        lgb.train(changed, lgb.Dataset(X, label=y), num_boost_round=6,
                  resume_from=str(tmp_path))
    msg = str(ei.value)
    assert "layout" in msg
    assert "tpu_elastic_recut" in msg
    assert "STREAM_RECUT" in msg


def test_recut_forced_f32_trains_with_divergence_warning(tmp_path):
    X, y = _data(n=4_000, seed=9)
    f32 = {k: v for k, v in BASE.items() if k != "use_quantized_grad"}
    p = dict(f32, checkpoint_dir=str(tmp_path), checkpoint_interval=2)
    lgb.train(p, lgb.Dataset(X, label=y), num_boost_round=4)
    forced = dict(p, tpu_stream_block_rows=1_024,
                  tpu_elastic_recut="true")
    bst = lgb.train(forced, lgb.Dataset(X, label=y), num_boost_round=6,
                    resume_from=str(tmp_path))
    assert bst.num_trees() == 6            # documented-close, completes


def test_recut_false_pins_strict_contract(tmp_path):
    """tpu_elastic_recut=false restores the PR-13 any-change-fatals
    behavior even on the otherwise-eligible quantized path."""
    X, y = _data(n=4_000, seed=11)
    p = _params(1, tmp_path)
    lgb.train(p, lgb.Dataset(X, label=y), num_boost_round=4)
    pinned = dict(p, tpu_stream_block_rows=1_024,
                  tpu_elastic_recut="false")
    with pytest.raises(lgb.LightGBMError, match="layout"):
        lgb.train(pinned, lgb.Dataset(X, label=y), num_boost_round=6,
                  resume_from=str(tmp_path))


def test_changed_data_is_genuinely_incompatible(tmp_path):
    """Elastic resume re-cuts the SAME rows across topologies; a
    different global row count is a different dataset and must stay a
    hard error naming what moved."""
    X, y = _data(n=4_000, seed=13)
    p = _params(1, tmp_path)
    lgb.train(p, lgb.Dataset(X, label=y), num_boost_round=4)
    X2, y2 = _data(n=6_000, seed=13)
    with pytest.raises(lgb.LightGBMError, match="row count"):
        lgb.train(p, lgb.Dataset(X2, label=y2), num_boost_round=6,
                  resume_from=str(tmp_path))


# ---------------------------------------------------------------------------
# the resize fault kind
# ---------------------------------------------------------------------------
def test_resize_fault_spec_parsing():
    plan = parse_fault_spec("resize:iter=4,ranks=1+3")
    assert plan.kind == "resize"
    assert plan.iteration == 4
    assert plan.ranks == (1, 3)
    with pytest.raises(lgb.LightGBMError, match="ranks="):
        parse_fault_spec("resize:iter=4")          # ranks required
    with pytest.raises(lgb.LightGBMError, match="cannot parse"):
        parse_fault_spec("resize:iter=4,ranks=a+b")
    with pytest.raises(lgb.LightGBMError, match="takes"):
        parse_fault_spec("resize:iter=4,ranks=1,ms=5")  # wrong key


def test_resize_fault_writes_host_gone_markers(tmp_path):
    """A firing resize fault leaves one .host_gone.rank<r> marker per
    named rank (the launcher's degrade signal) and a fire-once marker
    so a relaunch replaying the iteration skips it. This process is
    rank 0 and NOT in ranks, so it survives to assert."""
    d = str(tmp_path)
    plan = parse_fault_spec("resize:iter=2,ranks=1+2", marker_dir=d)
    plan.maybe_fire(1)                     # not the target iteration
    assert host_gone_ranks(d) == []
    plan.maybe_fire(2)
    assert host_gone_ranks(d) == [1, 2]
    assert os.path.exists(plan.marker_path(0))      # fire-once
    plan.maybe_fire(2)                     # marker-gated: no refire
    assert clear_host_gone_markers(d, ranks=[1]) == 1
    assert host_gone_ranks(d) == [2]
    assert clear_host_gone_markers(d) == 1
    assert host_gone_ranks(d) == []


# ---------------------------------------------------------------------------
# degrade-and-continue: the launcher loop (gang simulated — real
# multi-process gangs are capability-gated below)
# ---------------------------------------------------------------------------
def _model_str():
    rng = np.random.default_rng(0)
    X = rng.normal(size=(1_000, 6))
    y = (X[:, 0] > 0).astype(np.float64)
    return lgb.train({"objective": "binary", "num_leaves": 7,
                      "verbosity": -1}, lgb.Dataset(X, label=y),
                     num_boost_round=3).model_to_string()


def test_degrade_and_continue_without_consuming_restarts(
        tmp_path, monkeypatch):
    """A rank's host goes away mid-gang (resize marker): the launcher
    relaunches at width-1 through the SAME loop — with max_restarts=0,
    so the narrower relaunch provably consumed no restart attempt —
    counts watchdog.degrades, and consumes the marker."""
    model = _model_str()
    d = str(tmp_path / "ck")
    os.makedirs(d)
    calls = []

    def fake_gang_once(params, data_fn, n, *a, **kw):
        calls.append(n)
        if len(calls) == 1:
            with open(os.path.join(d, ".host_gone.rank1"), "w") as f:
                f.write("resize\n")
            return ("err", "rank 1: connection lost"), [(1, -9)], \
                [(1, -9)]
        return ("ok", model), [], []

    monkeypatch.setattr(launch, "_gang_once", fake_gang_once)
    before = getattr(obs.registry().get("watchdog.degrades"),
                     "value", 0.0)
    bst = lgb.train_distributed(
        {"objective": "binary", "verbosity": -1, "checkpoint_dir": d},
        _model_str, n_processes=2, num_boost_round=3, max_restarts=0)
    assert calls == [2, 1]                 # full width, then degraded
    assert bst.num_trees() == 3
    assert obs.registry().get("watchdog.degrades").value >= before + 1
    assert host_gone_ranks(d) == []        # marker consumed


def test_degrade_predicts_refused_recut_and_restarts_fresh(
        tmp_path, monkeypatch):
    """A forced-streaming f32 job (re-cut verdict FATAL) that loses a
    host must NOT resume the narrower gang into a checkpoint the
    engine is guaranteed to refuse — the degrade path predicts the
    verdict and restarts from scratch at the reduced width instead of
    burning restarts on a refused resume."""
    model = _model_str()
    d = str(tmp_path / "ck")
    os.makedirs(d)
    CheckpointManager(d, rank=0).save({"engine": {}, "iteration": 2}, 2)
    seen = []

    def fake_gang_once(params, data_fn, n, rounds, platform, cat,
                       timeout, resume_from, **kw):
        seen.append((n, resume_from))
        if len(seen) == 1:
            with open(os.path.join(d, ".host_gone.rank1"), "w") as f:
                f.write("resize\n")
            return ("err", "rank 1: host lost"), [(1, -9)], [(1, -9)]
        return ("ok", model), [], []

    monkeypatch.setattr(launch, "_gang_once", fake_gang_once)
    lgb.train_distributed(
        {"objective": "binary", "verbosity": -1, "checkpoint_dir": d,
         "tpu_streaming": "true"},
        _model_str, n_processes=2, num_boost_round=3, max_restarts=0,
        resume="auto")
    # the wide launch resumed (valid checkpoint on disk); the narrow
    # relaunch did NOT — the f32 re-cut would have been refused
    assert seen[0] == (2, d)
    assert seen[1] == (1, None)


def test_degrade_refuses_to_drop_every_rank(tmp_path, monkeypatch):
    d = str(tmp_path / "ck")
    os.makedirs(d)

    def fake_gang_once(params, data_fn, n, *a, **kw):
        for r in range(n):
            with open(os.path.join(d, f".host_gone.rank{r}"),
                      "w") as f:
                f.write("resize\n")
        return ("err", "all hosts lost"), [(0, -9), (1, -9)], \
            [(0, -9), (1, -9)]

    monkeypatch.setattr(launch, "_gang_once", fake_gang_once)
    with pytest.raises(lgb.LightGBMError, match="gone"):
        lgb.train_distributed(
            {"objective": "binary", "verbosity": -1,
             "checkpoint_dir": d},
            _model_str, n_processes=2, num_boost_round=3,
            max_restarts=3)


def test_stale_rank_snapshots_cleared_beyond_live_width(
        tmp_path, monkeypatch):
    """The PR-11 aggregation leak, pinned: a gang relaunched NARROWER
    (here resumed at width 1 after a width-2 run) must not merge the
    old topology's rank_1 snapshot into merged.jsonl — rank files
    beyond the live width are cleared on any (re)launch, resume
    included."""
    from lightgbm_tpu.obs.aggregate import dump_rank_snapshot
    model = _model_str()
    d = str(tmp_path / "ck")
    rank_dir = str(tmp_path / "ranks")
    os.makedirs(rank_dir)
    # a resumable checkpoint so the relaunch takes the RESUME path
    # (the fresh-run full clear would mask the beyond-width clear)
    CheckpointManager(d, rank=0).save(
        {"engine": {}, "iteration": 2}, 2)
    # yesterday's 2-rank gang left both snapshots behind
    dump_rank_snapshot(rank_dir, 0)
    dump_rank_snapshot(rank_dir, 1)

    def fake_gang_once(params, data_fn, n, *a, **kw):
        dump_rank_snapshot(rank_dir, 0)    # the live rank reports
        return ("ok", model), [], []

    monkeypatch.setattr(launch, "_gang_once", fake_gang_once)
    lgb.train_distributed(
        {"objective": "binary", "verbosity": -1, "checkpoint_dir": d,
         "tpu_metrics_rank_dir": rank_dir},
        _model_str, n_processes=1, num_boost_round=3, resume="auto")
    assert not os.path.exists(os.path.join(rank_dir, "rank_1.jsonl"))
    with open(os.path.join(rank_dir, "merged.jsonl")) as f:
        merged = json.loads(f.read().splitlines()[-1])
    assert merged["merged_from_ranks"] == [0]


# ---------------------------------------------------------------------------
# topology-aware rank agreement
# ---------------------------------------------------------------------------
def test_latest_complete_iteration(tmp_path):
    d = str(tmp_path)
    for rank in (0, 1):
        mgr = CheckpointManager(d, rank=rank)
        for it in (2, 4):
            mgr.save({"engine": {}, "iteration": it}, it)
    assert latest_complete_iteration(d) == 4
    # corrupt rank 1's newest -> the agreement walks back to 2
    p = CheckpointManager(d, rank=1).path(4)
    with open(p, "r+b") as f:
        f.seek(-32, os.SEEK_END)
        f.write(b"\0" * 32)
    assert latest_complete_iteration(d) == 2
    # a rank-gapped iteration (rank 0 only of {0, 2}) never qualifies
    CheckpointManager(d, rank=2).save({"engine": {}, "iteration": 6}, 6)
    assert latest_complete_iteration(d) == 2
    assert latest_complete_iteration(str(tmp_path / "void")) is None


# ---------------------------------------------------------------------------
# real multi-process degrade gang (capability-gated: this container's
# jaxlib cannot run cross-process collectives)
# ---------------------------------------------------------------------------
def elastic_shard_fn(rank, nproc):
    """Module-level so spawned workers can unpickle it."""
    rng = np.random.default_rng(3)
    X = rng.normal(size=(2_000, 6))
    y = (X[:, 0] + 0.3 * X[:, 1] > 0).astype(np.float64)
    blk = len(X) // nproc
    lo = rank * blk
    hi = len(X) if rank == nproc - 1 else lo + blk
    return {"data": X[lo:hi], "label": y[lo:hi]}


def test_gang_degrades_past_permanently_dead_host(
        tmp_path, multiprocess_collectives):
    """Acceptance: a 2-process gang whose rank-1 host vanishes
    (resize fault) completes at width 1 without exhausting
    max_restarts, with watchdog.degrades counted."""
    d = str(tmp_path / "ck")
    before = getattr(obs.registry().get("watchdog.degrades"),
                     "value", 0.0)
    bst = lgb.train_distributed(
        {"objective": "binary", "num_leaves": 7, "verbosity": -1,
         "checkpoint_dir": d, "checkpoint_interval": 2,
         "use_quantized_grad": True,
         "tpu_fault_inject": "resize:iter=3,ranks=1"},
        elastic_shard_fn, n_processes=2, num_boost_round=6,
        timeout=120.0, max_restarts=0, restart_backoff=0.2)
    assert bst.num_trees() == 6
    assert obs.registry().get("watchdog.degrades").value >= before + 1
