"""Fused multi-iteration training (train_chunk) equivalence tests."""
import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.boosting.gbdt import GBDT
from lightgbm_tpu.config import Config


def _data(n=2000, f=8, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f))
    w = rng.normal(size=f)
    y = ((X @ w + rng.normal(scale=0.5, size=n)) > 0).astype(np.float64)
    return X, y


def test_chunked_equals_per_iter():
    X, y = _data()
    params = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
              "tpu_fuse_iters": 4}
    eng_a = GBDT(Config(params), lgb.Dataset(X, label=y))
    for _ in range(9):
        eng_a.train_one_iter()
    eng_b = GBDT(Config(params), lgb.Dataset(X, label=y))
    eng_b.train_chunk(9)          # 2 chunks of 4 + 1 per-iter remainder
    assert eng_b.num_trees() == eng_a.num_trees() == 9
    pa = eng_a.predict(X, raw_score=True)
    pb = eng_b.predict(X, raw_score=True)
    np.testing.assert_allclose(pa, pb, rtol=1e-5, atol=1e-6)


def test_chunked_goss_boundary():
    X, y = _data(seed=1)
    # lr=0.5 -> GOSS kicks in at iter 2; chunking must split there
    params = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
              "learning_rate": 0.5, "data_sample_strategy": "goss",
              "top_rate": 0.3, "other_rate": 0.3, "tpu_fuse_iters": 3}
    eng_a = GBDT(Config(params), lgb.Dataset(X, label=y))
    for _ in range(8):
        eng_a.train_one_iter()
    eng_b = GBDT(Config(params), lgb.Dataset(X, label=y))
    eng_b.train_chunk(8)
    pa = eng_a.predict(X, raw_score=True)
    pb = eng_b.predict(X, raw_score=True)
    np.testing.assert_allclose(pa, pb, rtol=1e-5, atol=1e-6)


def test_train_uses_fused_path_same_result():
    X, y = _data(seed=2)
    ds1 = lgb.Dataset(X, label=y)
    ds2 = lgb.Dataset(X, label=y)
    p = {"objective": "binary", "num_leaves": 15, "verbosity": -1}
    # fused (no callbacks/valid sets) vs explicitly disabled fusion
    b1 = lgb.train(dict(p, tpu_fuse_iters=5), ds1, num_boost_round=10)
    b2 = lgb.train(dict(p, tpu_fuse_iters=1), ds2, num_boost_round=10)
    np.testing.assert_allclose(b1.predict(X, raw_score=True),
                               b2.predict(X, raw_score=True),
                               rtol=1e-5, atol=1e-6)


def test_fallback_when_ineligible():
    X, y = _data(seed=3)
    # bagging forces the per-iter path; train_chunk must still work
    params = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
              "bagging_fraction": 0.5, "bagging_freq": 1}
    eng = GBDT(Config(params), lgb.Dataset(X, label=y))
    assert not eng.can_fuse_iters()
    eng.train_chunk(5)
    assert eng.num_trees() == 5
