"""Perf-regression sentinel (scripts/obs_trend.py).

What these tests pin (ISSUE acceptance): the sentinel exits non-zero
on a synthetic 20% iters/sec regression, zero on flat history, zero on
empty/first-run history (so wiring it into scripts/check.sh can never
redden a fresh clone), and skips — not crashes on — malformed lines
and missing signals. Runs the script as a subprocess: the exit code IS
the contract check.sh consumes.
"""
import json
import pathlib
import subprocess
import sys

SCRIPT = str(pathlib.Path(__file__).resolve().parent.parent
             / "scripts" / "obs_trend.py")


def _obs_line(ips=10.0, compile_requests=50, peak=2.0, secs=300,
              dots=38, mode="smoke"):
    return "obs " + json.dumps({
        "ts": "2026-08-03T00:00:00Z", "rev": "abc1234", "mode": mode,
        "dots": dots, "secs": secs, "compile_requests": compile_requests,
        "peak_hbm_gib": peak, "bench_iters_per_sec": ips,
        "predict_programs": 3, "hist_rows_scanned": 1e8,
        "hist_partition": 0})


def _run(tmp_path, lines, *extra):
    log = tmp_path / "check_timings.log"
    log.write_text("\n".join(lines) + "\n")
    proc = subprocess.run(
        [sys.executable, SCRIPT, "--log", str(log), *extra],
        capture_output=True, text=True)
    return proc.returncode, proc.stdout + proc.stderr


def test_flat_history_is_green(tmp_path):
    rc, out = _run(tmp_path, [_obs_line(ips=10.0 + 0.02 * i)
                              for i in range(6)])
    assert rc == 0, out
    assert "OK" in out


def test_twenty_percent_ips_regression_fails(tmp_path):
    lines = [_obs_line(ips=10.0) for _ in range(5)]
    lines.append(_obs_line(ips=8.0))          # -20%
    rc, out = _run(tmp_path, lines)
    assert rc == 1, out
    assert "bench_iters_per_sec regressed" in out


def test_empty_and_first_run_history_stay_green(tmp_path):
    # plain timing lines only — no obs lines at all (pre-PR-4 logs)
    rc, out = _run(tmp_path, [
        "2026-08-03T00:00:00Z abc1234 smoke dots=38 secs=300 rc=0"])
    assert rc == 0, out
    # exactly one obs line: nothing to compare against
    rc, out = _run(tmp_path, [_obs_line()])
    assert rc == 0, out
    # missing log file entirely (default path semantics via --log to a
    # nonexistent explicit path is an invocation error instead)
    proc = subprocess.run(
        [sys.executable, SCRIPT, "--log", str(tmp_path / "nope.log")],
        capture_output=True, text=True)
    assert proc.returncode == 2


def test_chaos_smoke_failure_fails_even_without_history(tmp_path):
    """The chaos-smoke pin is ABSOLUTE like stream_dryrun: a
    chaos_smoke=0 newest entry fails with no baseline at all, and a
    1 (or an absent key, for pre-chaos logs) stays green."""
    bad = _obs_line()
    bad = "obs " + json.dumps(
        dict(json.loads(bad[len("obs "):]), chaos_smoke=0))
    rc, out = _run(tmp_path, [bad])
    assert rc == 1
    assert "chaos" in out
    good = "obs " + json.dumps(
        dict(json.loads(_obs_line()[len("obs "):]), chaos_smoke=1))
    rc, out = _run(tmp_path, [good])
    assert rc == 0, out
    rc, out = _run(tmp_path, [_obs_line()])   # key absent: pre-chaos
    assert rc == 0, out


def test_elastic_smoke_failure_fails_even_without_history(tmp_path):
    """The elastic-resume pin is ABSOLUTE like chaos_smoke: an
    elastic_smoke=0 newest entry (resize cycle lost bit-equality or
    dropped a predict) fails with no baseline at all, and a 1 (or an
    absent key, for pre-elastic logs) stays green."""
    bad = "obs " + json.dumps(
        dict(json.loads(_obs_line()[len("obs "):]), elastic_smoke=0))
    rc, out = _run(tmp_path, [bad])
    assert rc == 1
    assert "elastic_smoke" in out
    good = "obs " + json.dumps(
        dict(json.loads(_obs_line()[len("obs "):]), elastic_smoke=1))
    rc, out = _run(tmp_path, [good])
    assert rc == 0, out
    rc, out = _run(tmp_path, [_obs_line()])   # key absent: old logs
    assert rc == 0, out


def test_fleet_smoke_failure_fails_even_without_history(tmp_path):
    """The serving-fleet pin is ABSOLUTE like elastic_smoke: a
    fleet_smoke=0 newest entry (the kill/join cycle dropped a request
    or routed at an unready replica) fails with no baseline at all,
    and a 1 (or an absent key, for pre-fleet logs) stays green."""
    bad = "obs " + json.dumps(
        dict(json.loads(_obs_line()[len("obs "):]), fleet_smoke=0))
    rc, out = _run(tmp_path, [bad])
    assert rc == 1
    assert "fleet_smoke" in out
    good = "obs " + json.dumps(
        dict(json.loads(_obs_line()[len("obs "):]), fleet_smoke=1))
    rc, out = _run(tmp_path, [good])
    assert rc == 0, out
    rc, out = _run(tmp_path, [_obs_line()])   # key absent: old logs
    assert rc == 0, out


def test_shap_smoke_failure_fails_even_without_history(tmp_path):
    """The mixed predict+explain pin is ABSOLUTE like serve_smoke: a
    shap_smoke=0 newest entry (the explain leg dropped a request,
    compiled a warm SHAP program, or served wrong contributions)
    fails with no baseline at all, and a 1 (or an absent key, for
    pre-SHAP logs) stays green."""
    bad = "obs " + json.dumps(
        dict(json.loads(_obs_line()[len("obs "):]), shap_smoke=0))
    rc, out = _run(tmp_path, [bad])
    assert rc == 1
    assert "shap_smoke" in out
    good = "obs " + json.dumps(
        dict(json.loads(_obs_line()[len("obs "):]), shap_smoke=1))
    rc, out = _run(tmp_path, [good])
    assert rc == 0, out
    rc, out = _run(tmp_path, [_obs_line()])   # key absent: old logs
    assert rc == 0, out


def test_compile_and_hbm_regressions_fail(tmp_path):
    base = [_obs_line() for _ in range(4)]
    rc, out = _run(tmp_path, base + [_obs_line(compile_requests=200)])
    assert rc == 1 and "compile_requests" in out
    rc, out = _run(tmp_path, base + [_obs_line(peak=3.5)])
    assert rc == 1 and "peak_hbm_gib" in out
    # small jitter within thresholds stays green
    rc, out = _run(tmp_path, base + [_obs_line(
        ips=9.2, compile_requests=51, peak=2.2, secs=330)])
    assert rc == 0, out


def test_copy_share_regression_fails(tmp_path):
    """The donation sentinel (docs/perf.md "Iteration floor"): the
    loop-state %copy share creeping back above its trailing median
    (ratio + absolute slack) fails like an iters/sec drop; jitter
    inside the slack and histories without the signal stay green."""
    def _with_cs(cs):
        e = json.loads(_obs_line()[len("obs "):])
        e["copy_share"] = cs
        return "obs " + json.dumps(e)

    base = [_with_cs(0.02) for _ in range(4)]
    # 0.02 * 1.5 + 0.005 = 0.035 ceiling: 0.09 (a dropped donation
    # gate re-copying the carry) must fail
    rc, out = _run(tmp_path, base + [_with_cs(0.09)])
    assert rc == 1 and "copy_share regressed" in out
    # within ratio+slack stays green
    rc, out = _run(tmp_path, base + [_with_cs(0.03)])
    assert rc == 0, out
    # signal absent on either side -> skipped, like the other gauges
    rc, out = _run(tmp_path, base + [_obs_line()])
    assert rc == 0, out
    rc, out = _run(tmp_path, [_obs_line() for _ in range(4)]
                   + [_with_cs(0.09)])
    assert rc == 0, out


def test_wall_busy_gap_regression_fails(tmp_path):
    """The overlap sentinel (docs/perf.md "Communication/compute
    overlap"): the per-iter wall-vs-busy gap regressing past its
    trailing median (ratio + absolute ms slack, the copy_share guard's
    shape) fails — a host sync creeping back into the overlapped
    stream path; jitter inside the slack and histories without the
    signal stay green."""
    def _with_gap(ms):
        e = json.loads(_obs_line()[len("obs "):])
        e["wall_busy_gap_ms"] = ms
        return "obs " + json.dumps(e)

    base = [_with_gap(4.0) for _ in range(4)]
    # 4.0 * 1.5 + 3.0 = 9.0 ceiling: a gap jumping past double (a
    # blocking sync snuck back between sweep and reduce) must fail
    rc, out = _run(tmp_path, base + [_with_gap(12.0)])
    assert rc == 1 and "wall_busy_gap_ms regressed" in out
    # within ratio+slack stays green (host-timer jitter near zero)
    rc, out = _run(tmp_path, base + [_with_gap(6.0)])
    assert rc == 0, out
    # signal absent on either side -> skipped (pre-overlap logs)
    rc, out = _run(tmp_path, base + [_obs_line()])
    assert rc == 0, out
    rc, out = _run(tmp_path, [_obs_line() for _ in range(4)]
                   + [_with_gap(12.0)])
    assert rc == 0, out


def test_queue_wait_p99_regression_fails(tmp_path):
    """The serving queue-pressure sentinel (docs/observability.md
    "Request tracing"): the smoke's windowed queue-wait p99 regressing
    past its trailing median (ratio + absolute ms slack, the same
    shape as the copy_share guard) fails; jitter within the slack and
    histories without the signal stay green."""
    def _with_qw(ms):
        e = json.loads(_obs_line()[len("obs "):])
        e["queue_wait_p99_ms"] = ms
        return "obs " + json.dumps(e)

    base = [_with_qw(5.0) for _ in range(4)]
    # 5.0 * 1.5 + 2.0 = 9.5 ceiling: a doubled-plus p99 (budget
    # misconfig / dispatch slowdown / LRU thrash) must fail
    rc, out = _run(tmp_path, base + [_with_qw(12.0)])
    assert rc == 1 and "queue_wait_p99_ms regressed" in out
    # within ratio+slack stays green (near-budget timer jitter)
    rc, out = _run(tmp_path, base + [_with_qw(7.0)])
    assert rc == 0, out
    # signal absent on either side -> skipped, like the other gauges
    rc, out = _run(tmp_path, base + [_obs_line()])
    assert rc == 0, out
    rc, out = _run(tmp_path, [_obs_line() for _ in range(4)]
                   + [_with_qw(12.0)])
    assert rc == 0, out


def test_wall_clock_regression_needs_same_or_more_dots(tmp_path):
    base = [_obs_line(secs=300, dots=38) for _ in range(4)]
    rc, out = _run(tmp_path, base + [_obs_line(secs=600, dots=38)])
    assert rc == 1 and "wall clock" in out
    # fewer dots = a different (partial) suite, not a slowdown
    rc, out = _run(tmp_path, base + [_obs_line(secs=600, dots=20)])
    assert rc == 0, out


def test_malformed_lines_and_missing_signals_are_skipped(tmp_path):
    lines = [_obs_line() for _ in range(3)]
    lines.insert(1, "obs {not json at all")
    # newest line lacks the bench signal (e.g. a bench-less run)
    newest = json.loads(lines[-1][len("obs "):])
    del newest["bench_iters_per_sec"]
    lines.append("obs " + json.dumps(newest))
    rc, out = _run(tmp_path, lines)
    assert rc == 0, out
    assert "malformed" in out


def test_failed_runs_cannot_launder_into_the_baseline(tmp_path):
    """A persistent regression re-run N times must keep failing
    against the last GREEN history: each failing run writes a
    trend-reject marker and rejected entries never join the median."""
    log = tmp_path / "check_timings.log"
    lines = [_obs_line(ips=10.0) for _ in range(5)]
    log.write_text("\n".join(lines) + "\n")
    # regressed entries need distinct keys (ts differs per real run)
    for i in range(4):
        bad = json.loads(_obs_line(ips=8.0)[len("obs "):])
        bad["ts"] = f"2026-08-03T01:00:0{i}Z"
        with open(log, "a") as f:
            f.write("obs " + json.dumps(bad) + "\n")
        proc = subprocess.run(
            [sys.executable, SCRIPT, "--log", str(log)],
            capture_output=True, text=True)
        # run i sees only the green 10.0 baseline — fails every time
        assert proc.returncode == 1, (i, proc.stdout + proc.stderr)
    assert log.read_text().count("trend-reject ") == 4
    # a genuinely recovered run goes green again
    with open(log, "a") as f:
        f.write(_obs_line(ips=9.8) + "\n")
    proc = subprocess.run([sys.executable, SCRIPT, "--log", str(log)],
                          capture_output=True, text=True)
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_modes_compare_separately(tmp_path):
    # full-suite runs must not drag the smoke baseline (different secs
    # scale); a smoke run is compared against smoke history only
    lines = [_obs_line(mode="full", secs=3000, dots=96)
             for _ in range(4)]
    lines += [_obs_line(mode="smoke", secs=300, dots=38)]
    lines += [_obs_line(mode="smoke", secs=310, dots=38)]
    rc, out = _run(tmp_path, lines)
    assert rc == 0, out


def test_stream_dryrun_failure_fails_even_without_history(tmp_path):
    """The streamed-sharded dryrun pin is ABSOLUTE: stream_dryrun=0 in
    the newest entry fails the sentinel with or without a baseline
    (sharded-vs-single-shard divergence is never a 'trend')."""
    bad = _obs_line()
    bad = "obs " + json.dumps(
        dict(json.loads(bad[len("obs "):]), stream_dryrun=0))
    # no history at all
    rc, out = _run(tmp_path, [bad])
    assert rc == 1, out
    assert "stream_dryrun" in out
    # with healthy history it still fails
    rc, out = _run(tmp_path, [_obs_line() for _ in range(4)] + [bad])
    assert rc == 1, out
    # and a passing dryrun (or an old line without the key) stays green
    ok = "obs " + json.dumps(
        dict(json.loads(_obs_line()[len("obs "):]), stream_dryrun=1))
    rc, out = _run(tmp_path, [_obs_line() for _ in range(4)] + [ok])
    assert rc == 0, out


def test_lint_findings_fail_even_without_history(tmp_path):
    """The static-analysis pin is ABSOLUTE like stream_dryrun/
    chaos_smoke: lint_findings>0 (drift findings) or -1 (the analyzer
    crashed) in the newest entry fails the sentinel with or without a
    baseline; 0 — or an old line without the key — stays green."""
    def with_lint(v):
        return "obs " + json.dumps(
            dict(json.loads(_obs_line()[len("obs "):]),
                 lint_findings=v))
    # findings, no history at all
    rc, out = _run(tmp_path, [with_lint(3)])
    assert rc == 1, out
    assert "lint_findings" in out
    # an analyzer crash (-1) is also a failure
    rc, out = _run(tmp_path, [with_lint(-1)])
    assert rc == 1, out
    # with healthy history it still fails
    rc, out = _run(tmp_path, [_obs_line() for _ in range(4)]
                   + [with_lint(2)])
    assert rc == 1, out
    # a clean run — and a pre-suite line without the key — stay green
    rc, out = _run(tmp_path, [_obs_line() for _ in range(4)]
                   + [with_lint(0)])
    assert rc == 0, out
    rc, out = _run(tmp_path, [_obs_line()])
    assert rc == 0, out
