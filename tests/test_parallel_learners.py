"""Feature-parallel + voting-parallel learners on a fake 8-device CPU
mesh, and scatter-vs-psum data-parallel equivalence.

Reference semantics (SURVEY.md §3.4, UNVERIFIED):
- feature_parallel_tree_learner.cpp: full rows everywhere, split search
  sharded by feature, SyncUpGlobalBestSplit election
- voting_parallel_tree_learner.cpp (PV-Tree): local top-k votes, global
  top-2k elected, only elected features' histograms reduced
- data_parallel_tree_learner.cpp: ReduceScatter feature ownership
"""
import numpy as np
import pytest

import lightgbm_tpu as lgb


def _binary_data(n=3000, f=12, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f))
    w = rng.normal(size=f)
    y = (X @ w + rng.normal(scale=0.5, size=n) > 0).astype(np.float64)
    return X, y


def _train_pred(X, y, learner, extra=None):
    params = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
              "min_data_in_leaf": 5, "tree_learner": learner}
    params.update(extra or {})
    ds = lgb.Dataset(X, label=y)
    bst = lgb.train(params, ds, num_boost_round=10)
    return bst, bst.predict(X)


def _auc(y, p):
    order = np.argsort(p)
    ranks = np.empty(len(p)); ranks[order] = np.arange(1, len(p) + 1)
    pos = y > 0
    return (ranks[pos].sum() - pos.sum() * (pos.sum() + 1) / 2) \
        / (pos.sum() * (~pos).sum())


def test_feature_parallel_matches_serial():
    """Feature-parallel elects the same splits as serial (identical
    histograms, just sharded search) — predictions near-identical."""
    X, y = _binary_data(seed=3)
    bst_s, p_s = _train_pred(X, y, "serial")
    bst_f, p_f = _train_pred(X, y, "feature")
    assert bst_f.engine.mesh is not None
    assert bst_f.engine.learner_type == "feature"
    np.testing.assert_allclose(p_s, p_f, rtol=2e-2, atol=2e-3)
    assert abs(_auc(y, p_s) - _auc(y, p_f)) < 0.005


def test_feature_parallel_uneven_features():
    """F=10 on 8 devices: padded feature slots must never win splits."""
    X, y = _binary_data(f=10, seed=4)
    bst, p = _train_pred(X, y, "feature")
    assert _auc(y, p) > 0.9
    for t in bst.engine.models:
        assert np.all(t.split_feature < 10)


def test_voting_parallel_trains_well():
    X, y = _binary_data(n=4000, f=16, seed=5)
    bst, p = _train_pred(X, y, "voting", {"top_k": 5})
    assert bst.engine.learner_type == "voting"
    assert _auc(y, p) > 0.9


def test_voting_matches_data_parallel_when_topk_covers_all():
    """With top_k >= F every feature is elected, so voting degenerates to
    exact data-parallel — predictions must match serial closely."""
    X, y = _binary_data(n=2000, f=6, seed=6)
    _, p_s = _train_pred(X, y, "serial")
    _, p_v = _train_pred(X, y, "voting", {"top_k": 6})
    np.testing.assert_allclose(p_s, p_v, rtol=2e-2, atol=2e-3)
    assert abs(_auc(y, p_s) - _auc(y, p_v)) < 0.005


def test_scatter_matches_psum():
    """ReduceScatter feature-ownership reduce == full-psum reduce."""
    X, y = _binary_data(n=2000, f=7, seed=7)
    _, p_scatter = _train_pred(X, y, "data",
                               {"tpu_hist_reduce": "scatter"})
    _, p_psum = _train_pred(X, y, "data", {"tpu_hist_reduce": "psum"})
    np.testing.assert_allclose(p_scatter, p_psum, rtol=2e-2, atol=2e-3)
    assert abs(_auc(y, p_scatter) - _auc(y, p_psum)) < 0.005


def test_feature_parallel_with_goss_and_valid():
    X, y = _binary_data(n=3000, f=9, seed=8)
    ds = lgb.Dataset(X[:2400], label=y[:2400])
    vs = ds.create_valid(X[2400:], label=y[2400:])
    res = {}
    bst = lgb.train(
        {"objective": "binary", "num_leaves": 15, "verbosity": -1,
         "tree_learner": "feature", "data_sample_strategy": "goss",
         "metric": "auc"},
        ds, num_boost_round=15, valid_sets=[vs],
        callbacks=[lgb.record_evaluation(res)])
    assert res["valid_0"]["auc"][-1] > 0.88


def test_voting_with_categorical():
    rng = np.random.default_rng(9)
    n, n_cats = 3000, 12
    cat = rng.integers(0, n_cats, size=n)
    effect = rng.permutation(n_cats) >= n_cats // 2
    y = (effect[cat].astype(float) * 2 - 1
         + rng.normal(scale=0.5, size=n) > 0).astype(float)
    X = np.column_stack([cat.astype(float), rng.normal(size=(n, 3))])
    bst = lgb.train(
        {"objective": "binary", "num_leaves": 8, "verbosity": -1,
         "tree_learner": "voting", "top_k": 3, "min_data_per_group": 5,
         "cat_smooth": 1.0},
        lgb.Dataset(X, label=y, categorical_feature=[0]),
        num_boost_round=8)
    assert _auc(y, bst.predict(X)) > 0.85


def test_multihost_helpers_single_process():
    """Single-process degenerate behavior of the multi-host entry."""
    from lightgbm_tpu.parallel.mesh import create_data_mesh
    from lightgbm_tpu.parallel.multihost import is_multihost
    assert is_multihost() is False
    m = create_data_mesh()
    assert m.devices.size == 8
    assert m.axis_names == ("data",)
