"""Vectorized forest TreeSHAP vs the per-row recursive oracle.

VERDICT r3 item 5: ``pred_contrib`` was a pure-Python per-row
recursion; ops/shap.py::forest_shap_batch is the rows-vectorized
device formulation. These tests pin equality on real trained models
(including NaN routing and categorical splits) and the SHAP
local-accuracy invariant (contributions sum to the raw prediction).
"""
import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.ops.shap import forest_shap_batch, tree_shap_batch


def _train(n=3000, f=8, with_cat=False, with_nan=False, seed=0,
           num_leaves=15, rounds=8, objective="regression"):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f))
    logit = X[:, 0] * 1.2 - 0.8 * X[:, 1] + 0.5 * X[:, 2] * X[:, 3]
    cat_idx = []
    if with_cat:
        c = rng.integers(0, 9, size=n)
        X[:, f - 1] = c
        logit = logit + np.where(c % 3 == 0, 1.0, -0.4)
        cat_idx = [f - 1]
    if with_nan:
        miss = rng.uniform(size=n) < 0.15
        X[miss, 0] = np.nan
    if objective == "binary":
        y = (logit + rng.normal(scale=0.5, size=n) > 0).astype(float)
    else:
        y = logit + rng.normal(scale=0.3, size=n)
    num_class = 1
    params = {"objective": objective, "num_leaves": num_leaves,
              "verbosity": -1}
    if objective == "multiclass":
        y = rng.integers(0, 3, size=n).astype(float)
        params["num_class"] = 3
        num_class = 3
    bst = lgb.train(params, lgb.Dataset(X, label=y,
                                        categorical_feature=cat_idx),
                    num_boost_round=rounds)
    return bst, X, num_class


@pytest.mark.parametrize("with_cat,with_nan,objective", [
    (False, False, "regression"),
    (True, False, "regression"),
    (False, True, "binary"),
    (True, True, "binary"),
    (False, False, "multiclass"),
])
def test_vectorized_matches_recursive(with_cat, with_nan, objective):
    bst, X, K = _train(with_cat=with_cat, with_nan=with_nan,
                       objective=objective)
    hm = bst._to_host_model()
    trees = hm.trees
    n_feat = hm.max_feature_idx + 1
    Xs = X[:64]
    got = forest_shap_batch(trees, Xs, n_feat, K=K)
    want = np.zeros_like(got)
    for i, t in enumerate(trees):
        want[:, i % K, :] += tree_shap_batch(t, Xs, n_feat)
    np.testing.assert_allclose(got, want, rtol=1e-8, atol=1e-10)


def test_local_accuracy_through_public_api():
    """sum(contribs) == raw prediction, via Booster.predict."""
    bst, X, _ = _train(with_cat=True, with_nan=True,
                       objective="binary", rounds=12)
    contrib = bst.predict(X[:500], pred_contrib=True)
    raw = bst.predict(X[:500], raw_score=True)
    # raw predictions ride the f32 device path; SHAP sums are f64
    np.testing.assert_allclose(contrib.sum(axis=1), raw,
                               rtol=1e-5, atol=1e-7)


def test_stump_only_forest():
    """Constant trees contribute only the bias column."""
    rng = np.random.default_rng(1)
    X = rng.normal(size=(200, 3))
    y = np.full(200, 2.5)
    bst = lgb.train({"objective": "regression", "num_leaves": 4,
                     "verbosity": -1, "boost_from_average": True},
                    lgb.Dataset(X, label=y), num_boost_round=2)
    c = bst.predict(X[:10], pred_contrib=True)
    np.testing.assert_allclose(c[:, :-1], 0.0, atol=1e-12)
    np.testing.assert_allclose(c[:, -1], bst.predict(X[:10],
                                                     raw_score=True))
