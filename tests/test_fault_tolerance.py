"""Distributed worker recovery: ``train_distributed`` gangs with a
fault-injected worker death must self-heal — terminate the gang, back
off, relaunch on a fresh port, resume every rank from its newest
per-rank checkpoint — and finish with the SAME model as the fault-free
run (docs/robustness.md).

Two tiers:

* a 1-process gang (always runnable): the full launcher recovery loop
  — death detection, backoff, fresh-port relaunch, checkpoint resume,
  model collection — end to end;
* the REAL 4-process gang with rank 1 SIGKILLed mid-training — the
  acceptance check — which needs a jaxlib whose CPU backend supports
  cross-process collectives (this container's does not: the seed's own
  ``test_multihost`` 4-process runs fail on it), so it probes once and
  skips cleanly where the platform cannot run ANY multi-process job.

Not marked ``slow`` (this is the recovery subsystem's key CI check),
but guarded by an in-test SIGALRM watchdog so a hung restart loop
fails in under 120 s instead of eating the tier-1 budget.
"""
import os
import signal

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.recovery.checkpoint import CheckpointManager

# single source of truth with the other multihost tests (see
# test_multihost.py): same data/base params -> shared compile cache
from _multihost_worker import PARAMS, make_data  # noqa: E402

ROUNDS = 12
INTERVAL = 4


def shard_fn(rank, nproc):
    """Module-level so the spawned workers can unpickle it."""
    X, y = make_data()
    blk = len(X) // nproc
    lo, hi = rank * blk, (rank + 1) * blk
    return {"data": X[lo:hi], "label": y[lo:hi]}


class _Watchdog:
    """In-test timeout guard: SIGALRM after ``seconds`` raises instead
    of letting a hung gang/restart loop run into the suite timeout."""

    def __init__(self, seconds):
        self.seconds = seconds

    def __enter__(self):
        def _on_alarm(signum, frame):
            raise TimeoutError(
                f"fault-tolerance test exceeded its {self.seconds}s "
                f"in-test watchdog (hung restart loop?)")
        self._old = signal.signal(signal.SIGALRM, _on_alarm)
        signal.alarm(self.seconds)
        return self

    def __exit__(self, *exc):
        signal.alarm(0)
        signal.signal(signal.SIGALRM, self._old)
        return False


# the multiprocess_collectives capability probe that used to live here
# is now the session-scoped conftest.py fixture (shared with the other
# real-gang tests, one probe per pytest session)


# ---------------------------------------------------------------------------
# 1-process gang: the full launcher recovery loop, runnable everywhere
# ---------------------------------------------------------------------------
def test_single_process_gang_kill_self_heals(tmp_path):
    d_ok = str(tmp_path / "ok")
    d_fault = str(tmp_path / "fault")

    with _Watchdog(115):
        baseline = lgb.train_distributed(
            dict(PARAMS, checkpoint_dir=d_ok,
                 checkpoint_interval=INTERVAL),
            shard_fn, n_processes=1, num_boost_round=ROUNDS,
            timeout=90.0)
        params = dict(PARAMS, checkpoint_dir=d_fault,
                      checkpoint_interval=INTERVAL,
                      tpu_fault_inject="kill:rank=0,iter=9")
        healed = lgb.train_distributed(
            params, shard_fn, n_processes=1, num_boost_round=ROUNDS,
            timeout=90.0, max_restarts=2, restart_backoff=0.2)

    # the kill really happened (fire-once marker written by rank 0) and
    # an automatic restart resumed from the iteration-8 checkpoint
    assert [n for n in os.listdir(d_fault)
            if n.startswith(".fault_fired.")], "fault was never injected"
    assert healed.num_trees() == ROUNDS
    assert CheckpointManager(d_fault, rank=0).latest_valid_iteration() \
        == ROUNDS
    # bit-exact self-heal: exact score restore makes the resumed gang's
    # model identical to the fault-free run's
    assert healed.model_to_string() == baseline.model_to_string()
    X, y = make_data()
    assert np.mean((healed.predict(X) > 0.5) == y) > 0.8


def test_cross_driver_resume_continues_previous_job(tmp_path):
    """Re-running the SAME train_distributed call after a whole-driver
    crash must resume from the surviving checkpoints (resume='auto'),
    not clear them and retrain from iteration 0."""
    ckdir = str(tmp_path / "job")
    params = dict(PARAMS, checkpoint_dir=ckdir,
                  checkpoint_interval=INTERVAL,
                  tpu_fault_inject="kill:rank=0,iter=9")
    with _Watchdog(115):
        # "driver 1": dies with the gang (no restart budget)
        with pytest.raises(lgb.LightGBMError):
            lgb.train_distributed(params, shard_fn, n_processes=1,
                                  num_boost_round=ROUNDS, timeout=90.0)
        assert CheckpointManager(ckdir, rank=0) \
            .latest_valid_iteration() == 8
        # "driver 2": same call again — auto-resumes at 8, runs 8..11
        bst = lgb.train_distributed(params, shard_fn, n_processes=1,
                                    num_boost_round=ROUNDS, timeout=90.0)
    assert bst.num_trees() == ROUNDS
    assert CheckpointManager(ckdir, rank=0).latest_valid_iteration() \
        == ROUNDS
    # resume=True on a dir with no checkpoints must raise up front
    with pytest.raises(lgb.LightGBMError, match="no valid rank-0"):
        lgb.train_distributed(dict(PARAMS,
                                   checkpoint_dir=str(tmp_path / "x")),
                              shard_fn, n_processes=1,
                              num_boost_round=2, resume=True)


def test_no_restart_budget_surfaces_worker_death(tmp_path):
    """max_restarts=0 keeps the old fail-fast contract: a killed worker
    raises instead of silently retrying."""
    with _Watchdog(115):
        with pytest.raises(lgb.LightGBMError,
                           match="no result|worker failed"):
            lgb.train_distributed(
                dict(PARAMS, tpu_fault_inject="kill:rank=0,iter=2"),
                shard_fn, n_processes=1, num_boost_round=6,
                timeout=90.0)


# ---------------------------------------------------------------------------
# the 4-process acceptance run (needs real multi-process collectives)
# ---------------------------------------------------------------------------
def test_worker_kill_4proc_self_heals_and_matches_fault_free(
        tmp_path, multiprocess_collectives):
    d_ok = str(tmp_path / "ok")
    d_fault = str(tmp_path / "fault")

    with _Watchdog(115):
        baseline = lgb.train_distributed(
            dict(PARAMS, checkpoint_dir=d_ok,
                 checkpoint_interval=INTERVAL),
            shard_fn, n_processes=4, num_boost_round=ROUNDS,
            timeout=90.0)

    with _Watchdog(115):
        # rank 1 is SIGKILLed before iteration 9; checkpoints exist at
        # 4 and 8, so the restarted gang resumes from 8 and runs 8..11
        params = dict(PARAMS, checkpoint_dir=d_fault,
                      checkpoint_interval=INTERVAL,
                      tpu_fault_inject="kill:rank=1,iter=9")
        healed = lgb.train_distributed(
            params, shard_fn, n_processes=4, num_boost_round=ROUNDS,
            timeout=90.0, max_restarts=2, restart_backoff=0.2)

    assert [n for n in os.listdir(d_fault)
            if n.startswith(".fault_fired.")], "fault was never injected"
    assert healed.num_trees() == ROUNDS
    # every rank checkpointed past the resume point after the restart
    for rank in range(4):
        assert CheckpointManager(d_fault, rank=rank) \
            .latest_valid_iteration() == ROUNDS
    # bit-exact self-heal: per-rank exact score restore makes the
    # resumed gang's model identical to the fault-free run's
    assert healed.model_to_string() == baseline.model_to_string()
    X, y = make_data()
    assert np.mean((healed.predict(X) > 0.5) == y) > 0.8
