"""Serial tree learner: structural and recovery tests."""
import numpy as np
import jax.numpy as jnp

from lightgbm_tpu.learner.serial import GrowConfig, grow_tree
from lightgbm_tpu.ops.predict import tree_predict_binned


def _grow(bins, g, h, cfg, mask=None):
    n = bins.shape[0]
    if mask is None:
        mask = np.ones(n, dtype=np.float32)
    vals = np.stack([g * mask, h * mask, mask], axis=1).astype(np.float32)
    F = bins.shape[1]
    num_bin = np.full(F, int(bins.max()) + 1, dtype=np.int32)
    has_nan = np.zeros(F, dtype=bool)
    tree, leaf_id = grow_tree(
        jnp.asarray(bins), jnp.asarray(vals), jnp.asarray(num_bin),
        jnp.asarray(has_nan), jnp.ones(F, dtype=bool), cfg)
    return ({k: np.asarray(v) for k, v in tree.items()},
            np.asarray(leaf_id), num_bin, has_nan)


def test_perfect_split_recovery():
    # one feature perfectly separates the gradient signal
    n = 512
    rng = np.random.default_rng(0)
    bins = rng.integers(0, 8, size=(n, 3)).astype(np.uint8)
    g = np.where(bins[:, 1] <= 3, -1.0, 1.0).astype(np.float32)
    h = np.ones(n, dtype=np.float32)
    cfg = GrowConfig(num_leaves=2, min_data_in_leaf=1, num_bins=8,
                     rows_per_block=256, min_sum_hessian_in_leaf=0.0)
    tree, leaf_id, _, _ = _grow(bins, g, h, cfg)
    assert int(tree["num_leaves"]) == 2
    assert int(tree["split_feature"][0]) == 1
    assert int(tree["threshold_bin"][0]) == 3
    # left rows got -1 grads -> positive leaf value
    assert tree["leaf_value"][0] > 0
    assert tree["leaf_value"][1] < 0
    np.testing.assert_array_equal(leaf_id, np.where(bins[:, 1] <= 3, 0, 1))


def test_leaf_counts_partition_rows():
    n = 1024
    rng = np.random.default_rng(1)
    bins = rng.integers(0, 32, size=(n, 6)).astype(np.uint8)
    g = rng.normal(size=n).astype(np.float32)
    h = np.ones(n, dtype=np.float32)
    cfg = GrowConfig(num_leaves=15, min_data_in_leaf=5, num_bins=32,
                     rows_per_block=256)
    tree, leaf_id, _, _ = _grow(bins, g, h, cfg)
    nl = int(tree["num_leaves"])
    counts = np.bincount(leaf_id, minlength=cfg.num_leaves)
    np.testing.assert_array_equal(counts[:nl],
                                  tree["leaf_count"][:nl].astype(np.int64))
    assert counts[nl:].sum() == 0
    assert counts.sum() == n
    # every used leaf respects min_data_in_leaf
    assert counts[:nl].min() >= 5


def test_leaf_id_matches_tree_traversal():
    n = 2048
    rng = np.random.default_rng(2)
    bins = rng.integers(0, 16, size=(n, 4)).astype(np.uint8)
    g = rng.normal(size=n).astype(np.float32)
    h = np.ones(n, dtype=np.float32)
    cfg = GrowConfig(num_leaves=31, min_data_in_leaf=2, num_bins=16,
                     rows_per_block=512)
    tree, leaf_id, num_bin, has_nan = _grow(bins, g, h, cfg)
    dev_tree = {k: jnp.asarray(v) for k, v in tree.items()}
    _, leaf_via_tree = tree_predict_binned(
        dev_tree, jnp.asarray(bins), jnp.asarray(num_bin),
        jnp.asarray(has_nan))
    np.testing.assert_array_equal(leaf_id, np.asarray(leaf_via_tree))


def test_max_depth_respected():
    n = 1024
    rng = np.random.default_rng(3)
    bins = rng.integers(0, 16, size=(n, 4)).astype(np.uint8)
    g = rng.normal(size=n).astype(np.float32)
    h = np.ones(n, dtype=np.float32)
    cfg = GrowConfig(num_leaves=31, max_depth=3, min_data_in_leaf=1,
                     num_bins=16, rows_per_block=256)
    tree, _, _, _ = _grow(bins, g, h, cfg)
    # depth-3 binary tree has at most 8 leaves
    assert int(tree["num_leaves"]) <= 8


def test_gain_monotone_decreasing_split_order():
    n = 2048
    rng = np.random.default_rng(4)
    bins = rng.integers(0, 16, size=(n, 4)).astype(np.uint8)
    g = rng.normal(size=n).astype(np.float32)
    h = np.ones(n, dtype=np.float32)
    cfg = GrowConfig(num_leaves=15, min_data_in_leaf=1, num_bins=16,
                     rows_per_block=512)
    tree, _, _, _ = _grow(bins, g, h, cfg)
    nl = int(tree["num_leaves"])
    gains = tree["split_gain"][:nl - 1]
    # every executed split must have strictly positive gain (the stop
    # criterion); note best-first does NOT imply globally decreasing gains
    # (a child's split can out-gain its parent's)
    assert np.all(gains > 0)


def test_bagging_mask_excludes_rows():
    n = 512
    rng = np.random.default_rng(5)
    bins = rng.integers(0, 8, size=(n, 2)).astype(np.uint8)
    g = rng.normal(size=n).astype(np.float32)
    h = np.ones(n, dtype=np.float32)
    mask = np.zeros(n, dtype=np.float32)
    mask[:256] = 1.0
    cfg = GrowConfig(num_leaves=7, min_data_in_leaf=1, num_bins=8,
                     rows_per_block=256)
    tree, leaf_id, _, _ = _grow(bins, g, h, cfg, mask=mask)
    nl = int(tree["num_leaves"])
    # leaf counts only count masked-in rows
    assert tree["leaf_count"][:nl].sum() == 256
    # but all rows get routed to leaves
    assert leaf_id.shape[0] == n


def test_hist_rebuild_equals_pool():
    """tpu_hist_mode=rebuild (no histogram pool, both children direct)
    must produce the same model as the subtraction pool, and its jitted
    step must reserve far less memory at MSLR-ish widths."""
    import lightgbm_tpu as lgb
    rng = np.random.default_rng(42)
    X = rng.normal(size=(3000, 12))
    y = (X @ rng.normal(size=12) + rng.normal(scale=0.5, size=3000) > 0)
    preds = {}
    for mode in ("pool", "rebuild"):
        bst = lgb.train(
            {"objective": "binary", "num_leaves": 31, "verbosity": -1,
             "min_data_in_leaf": 5, "tpu_hist_mode": mode,
             "tpu_double_precision_hist": True},
            lgb.Dataset(X, label=y.astype(float)), num_boost_round=8)
        preds[mode] = bst.predict(X, raw_score=True)
    np.testing.assert_allclose(preds["pool"], preds["rebuild"],
                               rtol=1e-4, atol=1e-4)
