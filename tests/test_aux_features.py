"""Aux features: auc_mu, prediction early-stop, JSON dump, C export,
feature_fraction_bynode, CEGB, timers."""
import json

import numpy as np

import lightgbm_tpu as lgb


def _binary_data(n=2000, f=8, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f))
    y = (X @ rng.normal(size=f) + rng.normal(scale=0.5, size=n) > 0)
    return X, y.astype(float)


def test_auc_mu_metric():
    rng = np.random.default_rng(1)
    X = rng.normal(size=(2000, 6))
    y = (X[:, 0] > 0).astype(int) + (X[:, 1] > 0.5).astype(int)
    res = {}
    lgb.train({"objective": "multiclass", "num_class": 3,
               "metric": "auc_mu", "num_leaves": 15, "verbosity": -1},
              lgb.Dataset(X[:1500], label=y[:1500].astype(float)),
              num_boost_round=15,
              valid_sets=[lgb.Dataset(X[:1500],
                                      label=y[:1500].astype(float))
                          .create_valid(X[1500:],
                                        label=y[1500:].astype(float))],
              callbacks=[lgb.record_evaluation(res)])
    mu = res["valid_0"]["auc_mu"]
    assert mu[-1] > 0.9
    assert mu[-1] >= mu[0] - 0.02


def test_pred_early_stop_matches_full():
    X, y = _binary_data()
    bst = lgb.train({"objective": "binary", "num_leaves": 15,
                     "verbosity": -1}, lgb.Dataset(X, label=y),
                    num_boost_round=40)
    full = bst.predict(X)
    es = bst.predict(X, pred_early_stop=True, pred_early_stop_freq=5,
                     pred_early_stop_margin=8.0)
    # confident rows freeze early; class decisions must agree
    assert np.mean((full > 0.5) == (es > 0.5)) > 0.995


def test_dump_model_json():
    X, y = _binary_data(n=1200)
    bst = lgb.train({"objective": "binary", "num_leaves": 7,
                     "verbosity": -1}, lgb.Dataset(X, label=y),
                    num_boost_round=3)
    d = bst.dump_model()
    json.dumps(d)              # JSON-serializable
    assert d["num_class"] == 1
    assert len(d["tree_info"]) == 3
    t0 = d["tree_info"][0]["tree_structure"]
    assert "split_feature" in t0 and "left_child" in t0
    # walk to a leaf
    node = t0
    while "leaf_value" not in node:
        node = node["left_child"]
    assert isinstance(node["leaf_value"], float)


def test_model_to_c_compiles_and_matches():
    import ctypes, subprocess, tempfile, os
    X, y = _binary_data(n=1000)
    bst = lgb.train({"objective": "binary", "num_leaves": 7,
                     "verbosity": -1}, lgb.Dataset(X, label=y),
                    num_boost_round=5)
    code = bst.model_to_c()
    d = tempfile.mkdtemp()
    src = os.path.join(d, "model.c")
    so = os.path.join(d, "model.so")
    open(src, "w").write(code)
    subprocess.run(["gcc", "-O2", "-shared", "-fPIC", "-o", so, src],
                   check=True)
    lib = ctypes.CDLL(so)
    lib.Predict.argtypes = [ctypes.POINTER(ctypes.c_double),
                            ctypes.POINTER(ctypes.c_double)]
    out = np.zeros(1)
    raws = []
    for row in X[:50]:
        row = np.ascontiguousarray(row, dtype=np.float64)
        lib.Predict(row.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
                    out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)))
        raws.append(out[0])
    np.testing.assert_allclose(
        raws, bst.predict(X[:50], raw_score=True), rtol=1e-6, atol=1e-6)


def test_feature_fraction_bynode():
    X, y = _binary_data(n=2500, f=12, seed=3)
    bst = lgb.train({"objective": "binary", "num_leaves": 31,
                     "verbosity": -1, "feature_fraction_bynode": 0.5},
                    lgb.Dataset(X, label=y), num_boost_round=15)
    p = bst.predict(X)
    assert np.mean((p > 0.5) == y) > 0.85
    # different nodes saw different feature subsets -> more distinct
    # features used than a single 0.5 subset would allow
    used = set()
    for t in bst.engine.models:
        used.update(t.split_feature[:t.num_nodes].tolist())
    assert len(used) > 6


def test_cegb_penalties_shrink_feature_set():
    rng = np.random.default_rng(4)
    X = rng.normal(size=(3000, 10))
    w = np.linspace(1.5, 0.5, 10)     # every feature informative
    y = ((X * w).sum(axis=1) + rng.normal(scale=0.3, size=3000) > 0)
    base = lgb.train({"objective": "binary", "num_leaves": 15,
                      "verbosity": -1},
                     lgb.Dataset(X, label=y.astype(float)),
                     num_boost_round=10)
    pen = lgb.train({"objective": "binary", "num_leaves": 15,
                     "verbosity": -1, "cegb_tradeoff": 1.0,
                     "cegb_penalty_feature_coupled": [50.0] * 10},
                    lgb.Dataset(X, label=y.astype(float)),
                    num_boost_round=10)
    def n_used(b):
        used = set()
        for t in b.engine.models:
            used.update(t.split_feature[:t.num_nodes].tolist())
        return len(used)
    assert n_used(pen) < n_used(base)
    assert np.mean((pen.predict(X) > 0.5) == y) > 0.8


def test_cegb_split_penalty_prunes():
    X, y = _binary_data(n=2000, seed=5)
    free = lgb.train({"objective": "binary", "num_leaves": 63,
                      "verbosity": -1}, lgb.Dataset(X, label=y),
                     num_boost_round=5)
    pen = lgb.train({"objective": "binary", "num_leaves": 63,
                     "verbosity": -1, "cegb_penalty_split": 2.0},
                    lgb.Dataset(X, label=y), num_boost_round=5)
    leaves_free = sum(t.num_leaves for t in free.engine.models)
    leaves_pen = sum(t.num_leaves for t in pen.engine.models)
    assert leaves_pen < leaves_free


def test_timers():
    from lightgbm_tpu.utils.timer import reset_timers, timed, timer_totals
    reset_timers()
    with timed("phase_a"):
        x = sum(range(1000))
    assert timer_totals()["phase_a"] >= 0


def test_trees_to_dataframe_and_debug_checks():
    X, y = _binary_data(n=1000)
    bst = lgb.train({"objective": "binary", "num_leaves": 7,
                     "verbosity": -1, "tpu_debug_checks": True},
                    lgb.Dataset(X, label=y), num_boost_round=3)
    df = bst.trees_to_dataframe()
    assert len(df) == sum(2 * t.num_leaves - 1
                          for t in bst.engine.models)
    assert set(df["tree_index"]) == {0, 1, 2}
    leaves = df[df["split_feature"].isna()]
    assert (leaves["value"].abs() > 0).any()


def test_pyarrow_table_ingestion():
    """Arrow ingestion (reference: LGBM_DatasetCreateFromArrow /
    basic.py pyarrow Table support)."""
    import pytest
    pa = pytest.importorskip("pyarrow")
    import numpy as np
    import lightgbm_tpu as lgb
    rng = np.random.default_rng(3)
    X = rng.normal(size=(600, 4))
    y = (X[:, 0] + X[:, 1] > 0).astype(np.float64)
    tbl = pa.table({f"f{i}": X[:, i] for i in range(4)})
    ds = lgb.Dataset(tbl, label=pa.array(y))
    bst = lgb.train({"objective": "binary", "num_leaves": 7,
                     "verbosity": -1}, ds, num_boost_round=5)
    assert ds.feature_names == ["f0", "f1", "f2", "f3"]
    ref = lgb.train({"objective": "binary", "num_leaves": 7,
                     "verbosity": -1}, lgb.Dataset(X, label=y),
                    num_boost_round=5)
    np.testing.assert_allclose(bst.predict(X), ref.predict(X), rtol=1e-6)


def test_tpu_profile_dir_writes_trace(tmp_path):
    """tpu_profile_dir wraps training in a jax.profiler trace (the §5
    tracing subsystem); a trace directory must appear."""
    X, y = _binary_data(500, 4)
    d = str(tmp_path / "prof")
    lgb.train({"objective": "binary", "num_leaves": 7, "verbosity": -1,
               "tpu_profile_dir": d}, lgb.Dataset(X, label=y),
              num_boost_round=3)
    import os
    found = []
    for root, _dirs, files in os.walk(d):
        found.extend(files)
    assert found, "profiler trace produced no files"


def test_push_rows_streaming():
    """Streamed chunk ingestion (LGBM_DatasetPushRows analog): pushing
    row chunks against a reference bins immediately and must equal the
    one-shot dataset; valid-set evaluation on the streamed set matches."""
    X, y = _binary_data(3000, 6, seed=9)
    train = lgb.Dataset(X[:2000], label=y[:2000])
    train.construct()
    streamed = lgb.Dataset(None, reference=train)
    for lo in range(2000, 3000, 256):
        hi = min(lo + 256, 3000)
        streamed.push_rows(X[lo:hi], label=y[lo:hi])
    streamed.construct()
    oneshot = train.create_valid(X[2000:], label=y[2000:])
    oneshot.construct()
    np.testing.assert_array_equal(streamed.binned, oneshot.binned)
    np.testing.assert_array_equal(streamed.get_label(), y[2000:])
    # trains + evals through the engine
    bst = lgb.train({"objective": "binary", "num_leaves": 7,
                     "metric": "auc", "verbosity": -1}, train,
                    num_boost_round=5, valid_sets=[streamed])
    assert np.isfinite(bst.predict(X[2000:])).all()


def test_push_rows_without_reference():
    X, y = _binary_data(1200, 5, seed=11)
    ds = lgb.Dataset(None)
    ds.push_rows(X[:600], label=y[:600])
    ds.push_rows(X[600:], label=y[600:])
    ref = lgb.Dataset(X, label=y)
    ds.construct(); ref.construct()
    np.testing.assert_array_equal(ds.binned, ref.binned)
