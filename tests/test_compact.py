"""Row-compaction primitive (ops/compact.py).

Covers: plan_compaction's destinations/positions, the XLA fallback's
exact-packing contract, width-independence (F=200 Bosch shape), the
end-to-end compacted-histogram equivalence, and — in TPU mode
(LGBM_TPU_TESTS=1) — Pallas-vs-XLA equality.
"""
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lightgbm_tpu.ops.compact import (compact_rows, compact_rows_xla,
                                      compaction_out_cols,
                                      plan_compaction)

TPU_MODE = os.environ.get("LGBM_TPU_TESTS", "") == "1"


def _reference_compact(bins_t, vals_t, mask, out_cols):
    """NumPy oracle: exact contiguous left-pack of kept columns."""
    sel = np.nonzero(mask)[0]
    ob = np.zeros((bins_t.shape[0], out_cols), bins_t.dtype)
    ov = np.zeros((vals_t.shape[0], out_cols), np.float32)
    ob[:, :len(sel)] = bins_t[:, sel]
    ov[:, :len(sel)] = vals_t[:, sel]
    return ob, ov


def _mk(n, F, C, frac, seed=0, R=256, multiple=256):
    rng = np.random.default_rng(seed)
    bins_t = rng.integers(0, 256, size=(F, n)).astype(np.uint8) \
        .astype(np.int8)
    vals_t = rng.normal(size=(C, n)).astype(np.float32)
    mask = rng.uniform(size=n) < frac
    out_cols = compaction_out_cols(int(mask.sum()), R, multiple)
    return bins_t, vals_t, mask, out_cols


@pytest.mark.parametrize("frac", [0.0, 0.3, 1.0])
def test_plan_compaction(frac):
    n, R = 2048, 256
    rng = np.random.default_rng(1)
    mask = rng.uniform(size=n) < frac
    out_cols = compaction_out_cols(int(mask.sum()), R, 256)
    dest, algn, rem = jax.tree.map(np.asarray, plan_compaction(
        jnp.asarray(mask), R, out_cols))
    stream = 0
    for b in range(n // R):
        blk = mask[b * R:(b + 1) * R]
        assert algn[b] * 128 + rem[b] == min(
            stream, (out_cols - R - 128) // 128 * 128 + rem[b])
        assert algn[b] == min(stream // 128,
                              (out_cols - R - 128) // 128)
        stream += int(blk.sum())
        expect = np.where(blk, np.cumsum(blk) - 1, -1)
        np.testing.assert_array_equal(dest[b * R:(b + 1) * R], expect)
    assert stream + R + 128 <= out_cols + R  # out_cols bound holds


@pytest.mark.parametrize("n,F,C,frac,R", [
    (2048, 28, 3, 0.3, 256),
    (2048, 200, 4, 0.25, 256),     # Bosch width: beyond the old sort gate
    (1024, 7, 3, 0.0, 128),        # nothing kept
    (1024, 7, 3, 1.0, 128),        # everything kept
])
def test_xla_compact_matches_oracle(n, F, C, frac, R):
    bins_t, vals_t, mask, out_cols = _mk(n, F, C, frac, R=R)
    dest, algn, rem = plan_compaction(jnp.asarray(mask), R, out_cols)
    ob, ov = compact_rows_xla(jnp.asarray(bins_t), jnp.asarray(vals_t),
                              dest, algn, rem, out_cols=out_cols,
                              rows_per_block=R)
    eb, ev = _reference_compact(bins_t, vals_t, mask, out_cols)
    np.testing.assert_array_equal(np.asarray(ob), eb)
    np.testing.assert_array_equal(np.asarray(ov), ev)


def test_uint16_bins_supported_off_tpu():
    """The XLA fallback compacts uint16 binned matrices (max_bin>256),
    which the sort path used to cover — dtype-generic contract."""
    n, R = 1024, 128
    rng = np.random.default_rng(5)
    bins_t = rng.integers(0, 1000, size=(5, n)).astype(np.uint16)
    vals_t = rng.normal(size=(3, n)).astype(np.float32)
    mask = rng.uniform(size=n) < 0.5
    out_cols = compaction_out_cols(int(mask.sum()), R, 128)
    dest, algn, rem = plan_compaction(jnp.asarray(mask), R, out_cols)
    ob, _ = compact_rows_xla(jnp.asarray(bins_t), jnp.asarray(vals_t),
                             dest, algn, rem, out_cols=out_cols,
                             rows_per_block=R)
    eb, _ = _reference_compact(bins_t, vals_t, mask, out_cols)
    np.testing.assert_array_equal(np.asarray(ob), eb)


def test_compacted_histogram_equals_masked():
    """The compaction contract end-to-end: histogramming the compacted
    buffer (kept rows' leaf ids riding as a +1 channel) reproduces the
    masked full-scan histogram of the kept rows exactly."""
    from lightgbm_tpu.ops.pallas_histogram import multi_leaf_histogram_xla
    n, F, R, B = 2048, 6, 256, 16
    rng = np.random.default_rng(3)
    bins = rng.integers(0, B, size=(n, F)).astype(np.uint8)
    g = rng.normal(size=n).astype(np.float32)
    h = rng.uniform(0.1, 1.0, size=n).astype(np.float32)
    leaf = rng.integers(0, 4, size=n).astype(np.int32)
    mask = rng.uniform(size=n) < 0.4
    small = jnp.asarray([0, 2], jnp.int32)

    vals = np.stack([g * mask, h * mask, mask.astype(np.float32)], 1)
    ref = multi_leaf_histogram_xla(
        jnp.asarray(bins), jnp.asarray(vals), jnp.asarray(leaf), small,
        num_bins=B, rows_per_block=R)

    out_cols = compaction_out_cols(int(mask.sum()), R, 256)
    vals_t = np.stack([g, h, np.ones(n, np.float32),
                       (leaf + 1).astype(np.float32)])
    dest, algn, rem = plan_compaction(jnp.asarray(mask), R, out_cols)
    ob, ov = compact_rows_xla(
        jnp.asarray(bins.astype(np.int8)).T, jnp.asarray(vals_t),
        dest, algn, rem, out_cols=out_cols, rows_per_block=R)
    leaf_c = (np.asarray(ov[3]) - 1).astype(np.int32)   # tail -> -1
    vals_c = np.array(ov[:3]).T
    got = multi_leaf_histogram_xla(
        jnp.asarray(np.asarray(ob).astype(np.uint8)).T,
        jnp.asarray(vals_c), jnp.asarray(leaf_c), small,
        num_bins=B, rows_per_block=256)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-5, atol=1e-4)


@pytest.mark.skipif(not TPU_MODE, reason="Pallas kernel needs the TPU")
@pytest.mark.parametrize("n,F,C,frac", [
    (8192, 28, 3, 0.3),
    (8192, 200, 4, 0.25),
    (8192, 28, 3, 0.0),
    (8192, 28, 3, 1.0),
])
def test_pallas_matches_xla(n, F, C, frac):
    R = 1024
    # arbitrary f32 values: the kernel's bf16x3 significand-split moves
    # them BIT-EXACTLY, comparable with the f32 XLA fallback
    bins_t, vals_t, mask, out_cols = _mk(n, F, C, frac, R=R,
                                         multiple=1024)
    dest, algn, rem = plan_compaction(jnp.asarray(mask), R, out_cols)
    args = (jnp.asarray(bins_t), jnp.asarray(vals_t), dest, algn, rem)
    ob, ov = compact_rows(*args, out_cols=out_cols, rows_per_block=R)
    eb, ev = compact_rows_xla(*args, out_cols=out_cols,
                              rows_per_block=R)
    np.testing.assert_array_equal(np.asarray(ob), np.asarray(eb))
    np.testing.assert_array_equal(np.asarray(ov), np.asarray(ev))
