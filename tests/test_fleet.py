"""Serving fleet (lightgbm_tpu/serve/fleet.py + serve/router.py).

What these tests pin (the ISSUE's router failover invariants):

* **Exactly-once across a mid-traffic SIGKILL** — every request
  submitted while one replica is SIGKILLed resolves exactly once with
  CORRECT rows (verified against the offline ``Booster.predict`` of
  the same rows): no drop, no double-dispatch observable through a
  future, no wrong answer from a torn reply.
* **Readiness-gated admission** — a joining replica that has not
  passed ``/readyz`` (its bucketed warmup has not completed) receives
  ZERO routed requests; the router's per-rank dispatch counters are
  the witness.
* **Degrade-and-continue** — a host-gone marker retires the slot
  permanently (no relaunch burn) and the remaining fleet keeps
  answering correctly.
* **Watcher jitter** — N replicas polling one checkpoint dir draw
  per-poll intervals in ±20% of the configured one (thundering-herd
  satellite; unit-level, no processes).

Replica processes spawn with a JAX import each — the module is marked
slow-ish but stays minutes-not-hours by using one tiny model, a
128-row batch cap (warmup = one bucket), and 2-replica fleets.
"""
import threading
import time

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.serve import FleetRouter, FleetSupervisor, ReplicaModel
from lightgbm_tpu.serving import ModelWatcher

# serving params every fleet in this module uses: the smallest legal
# batch cap makes warmup a single pow2 bucket (fast readiness), and a
# short budget keeps micro-batching latency out of test wall-clock
_PARAMS = {"tpu_serve_max_batch_rows": 128,
           "tpu_serve_batch_budget_ms": 2.0}


@pytest.fixture(scope="module")
def tiny_model():
    rng = np.random.default_rng(7)
    X = rng.normal(size=(300, 6))
    y = (X[:, 0] - X[:, 2] > 0).astype(np.float64)
    bst = lgb.train({"objective": "binary", "num_leaves": 7,
                     "verbose": -1}, lgb.Dataset(X, label=y),
                    num_boost_round=5)
    return bst, X


def _fleet(tiny_model, n, **kw):
    bst, X = tiny_model
    spec = [ReplicaModel(model_id="m", model_str=bst.model_to_string(),
                         warmup_row=X[0])]
    return FleetSupervisor(_PARAMS, spec, n, heartbeat_timeout=8.0,
                           **kw)


def test_exactly_once_with_correct_rows_across_sigkill(tiny_model):
    bst, X = tiny_model
    sup = _fleet(tiny_model, 2, max_restarts=2)
    sup.start()
    try:
        assert sup.wait_ready(2, timeout=120) == 2
        with FleetRouter(sup, request_timeout_s=90.0) as router:
            # concurrent slices, each with its own offline reference
            slices = [X[i:i + 8] for i in range(0, 240, 8)]
            futs = [router.submit("m", s) for s in slices]
            # kill one replica while those are in flight
            sup.kill_replica(0)
            results = [f.result(timeout=120) for f in futs]
            # exactly once: every future resolved, with ITS rows
            assert len(results) == len(slices)
            for s, out in zip(slices, results):
                np.testing.assert_allclose(out, bst.predict(s),
                                           atol=1e-6)
            # and the fleet healed: the slot relaunches and re-admits
            deadline = time.monotonic() + 120
            while sup.live_count() < 2 and time.monotonic() < deadline:
                time.sleep(0.2)
            assert sup.live_count() == 2
            assert sup.relaunches >= 1
            out = router.predict("m", X[:16], timeout=60)
            np.testing.assert_allclose(out, bst.predict(X[:16]),
                                       atol=1e-6)
    finally:
        sup.stop()


def test_joining_replica_gets_zero_traffic_before_readyz(tiny_model):
    bst, X = tiny_model
    # rank 1 publishes its endpoint, then sleeps BEFORE warmup: it is
    # discoverable but /readyz-503 for several seconds — the admission
    # window the invariant is about
    sup = _fleet(tiny_model, 2, warmup_delay_s=6.0,
                 slow_warmup_ranks=(1,))
    sup.start()
    try:
        assert sup.wait_ready(1, timeout=120) >= 1
        with FleetRouter(sup, request_timeout_s=60.0) as router:
            stop = threading.Event()

            def _traffic(out):
                while not stop.is_set():
                    out.append(router.submit("m", X[:4]))
                    time.sleep(0.01)

            futs = []
            t = threading.Thread(target=_traffic, args=(futs,))
            t.start()
            # while rank 1 is alive-but-unready, it gets NOTHING
            deadline = time.monotonic() + 30
            admitted = False
            while time.monotonic() < deadline:
                # read the counter BEFORE the flag: admission requires
                # ready=True, so a nonzero count with ready still False
                # at the LATER read is a real violation, while a count
                # that raced the flag flipping true is not
                count = router.dispatch_counts.get(1, 0)
                if sup.handles[1].ready:
                    admitted = True
                    break
                assert count == 0, (
                    "router dispatched at a replica that never "
                    "passed /readyz")
                time.sleep(0.05)
            stop.set()
            t.join(timeout=10)
            assert admitted, "slow joiner never turned ready"
            for f in futs:
                np.testing.assert_allclose(
                    f.result(timeout=60), bst.predict(X[:4]), atol=1e-6)
    finally:
        sup.stop()


def test_host_gone_marker_degrades_to_n_minus_1(tiny_model):
    bst, X = tiny_model
    sup = _fleet(tiny_model, 2, max_restarts=2)
    sup.start()
    try:
        assert sup.wait_ready(2, timeout=120) == 2
        with FleetRouter(sup, request_timeout_s=90.0) as router:
            futs = [router.submit("m", X[i:i + 4])
                    for i in range(0, 80, 4)]
            sup.kill_replica(1, host_gone=True)
            for i, f in enumerate(futs):
                np.testing.assert_allclose(
                    f.result(timeout=120),
                    bst.predict(X[4 * i:4 * i + 4]), atol=1e-6)
            deadline = time.monotonic() + 60
            while sup.degrades < 1 and time.monotonic() < deadline:
                time.sleep(0.2)
            # retired, not relaunched: the marker is consumed, the
            # slot stays down, the survivor answers
            assert sup.degrades == 1
            assert sup.handles[1].retired
            out = router.predict("m", X[:16], timeout=60)
            np.testing.assert_allclose(out, bst.predict(X[:16]),
                                       atol=1e-6)
            assert sup.live_count() == 1
    finally:
        sup.stop()


def test_watcher_poll_jitter_stays_within_20pct(tmp_path):
    w = ModelWatcher(str(tmp_path), interval=1.0)
    draws = set()
    for _ in range(200):
        w._last_poll = 0.0          # defeat the rate limit, keep the
        w.maybe_swap(booster=None)  # draw path honest
        assert 0.8 <= w._next_wait <= 1.2
        draws.add(round(w._next_wait, 6))
    assert len(draws) > 10, "jitter draws look constant"
    # interval=0 (tests poll every call) must stay exactly 0
    w0 = ModelWatcher(str(tmp_path), interval=0.0)
    w0.maybe_swap(booster=None)
    assert w0._next_wait == 0.0
