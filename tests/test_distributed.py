"""Distributed (data-parallel) learner tests on a fake 8-device CPU mesh.

This is the TPU analog of the reference's localhost-process distributed
tests (tests/distributed/_test_distributed.py, SURVEY.md §4): train with
``tree_learner=data`` over 8 virtual devices and assert equivalence with
single-device training.
"""
import numpy as np
import jax
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.parallel.mesh import create_data_mesh


def _binary_data(n=4000, f=8, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f))
    w = rng.normal(size=f)
    y = (X @ w + rng.normal(scale=0.5, size=n) > 0).astype(np.float64)
    return X, y


def test_mesh_has_8_devices():
    assert jax.device_count() == 8


def test_data_parallel_trains():
    X, y = _binary_data()
    ds = lgb.Dataset(X[:3000], label=y[:3000])
    vs = ds.create_valid(X[3000:], label=y[3000:])
    res = {}
    bst = lgb.train(
        {"objective": "binary", "num_leaves": 31, "metric": "auc",
         "tree_learner": "data", "verbosity": -1},
        ds, num_boost_round=15, valid_sets=[vs],
        callbacks=[lgb.record_evaluation(res)])
    assert bst.engine.mesh is not None
    assert res["valid_0"]["auc"][-1] > 0.9


def test_data_parallel_matches_serial():
    """Distributed-vs-serial equivalence (the reference's key invariant)."""
    X, y = _binary_data(n=2000, f=5, seed=3)
    preds = {}
    for learner in ("serial", "data"):
        ds = lgb.Dataset(X, label=y)
        bst = lgb.train(
            {"objective": "binary", "num_leaves": 15,
             "tree_learner": learner, "verbosity": -1,
             "min_data_in_leaf": 5},
            ds, num_boost_round=10)
        preds[learner] = bst.predict(X)
    # same histograms (up to psum reduction order) -> same trees; allow
    # small float drift from different reduction orders
    np.testing.assert_allclose(preds["serial"], preds["data"],
                               rtol=5e-2, atol=5e-3)
    # AUC agreement is the distribution-level check
    from lightgbm_tpu.metric import AUCMetric
    from lightgbm_tpu.config import Config
    m = AUCMetric(Config({}))
    auc_s = m.eval(preds["serial"], y, None)[0][1]
    auc_d = m.eval(preds["data"], y, None)[0][1]
    assert abs(auc_s - auc_d) < 0.01


def test_data_parallel_regression():
    rng = np.random.default_rng(5)
    X = rng.normal(size=(3000, 6))
    y = X @ rng.normal(size=6) + rng.normal(scale=0.1, size=3000)
    ds = lgb.Dataset(X[:2000], label=y[:2000])
    vs = ds.create_valid(X[2000:], label=y[2000:])
    res = {}
    lgb.train({"objective": "regression", "num_leaves": 31, "metric": "l2",
               "tree_learner": "data", "verbosity": -1},
              ds, num_boost_round=20, valid_sets=[vs],
              callbacks=[lgb.record_evaluation(res)])
    assert res["valid_0"]["l2"][-1] < res["valid_0"]["l2"][0] * 0.5


def test_explicit_mesh_subset():
    """A 4-device mesh out of the 8 available."""
    from lightgbm_tpu.boosting.gbdt import GBDT
    from lightgbm_tpu.config import Config
    X, y = _binary_data(n=1000, f=4, seed=7)
    ds = lgb.Dataset(X, label=y)
    cfg = Config({"objective": "binary", "num_leaves": 7,
                  "tree_learner": "data", "verbosity": -1})
    eng = GBDT(cfg, ds, mesh=create_data_mesh(4))
    for _ in range(3):
        eng.train_one_iter()
    assert eng.num_trees() == 3


def test_data_parallel_exact_with_precise_hist():
    """With f32 histograms the psum/scatter reduction differs from the
    serial sum only by float reduction order — predictions must agree to
    tight tolerance, not the loose 5e-2 of the smoke test."""
    X, y = _binary_data(n=2000, f=6, seed=11)
    preds = {}
    for learner in ("serial", "data"):
        bst = lgb.train(
            {"objective": "binary", "num_leaves": 15, "verbosity": -1,
             "tree_learner": learner, "min_data_in_leaf": 5,
             "tpu_double_precision_hist": True},
            lgb.Dataset(X, label=y), num_boost_round=10)
        preds[learner] = bst.predict(X, raw_score=True)
    np.testing.assert_allclose(preds["serial"], preds["data"],
                               rtol=1e-4, atol=1e-4)


def test_multiclass_under_data_parallel():
    rng = np.random.default_rng(12)
    X = rng.normal(size=(3000, 8))
    y = (X[:, 0] > 0).astype(int) + (X[:, 1] > 0.5).astype(int)
    preds = {}
    for learner in ("serial", "data"):
        bst = lgb.train(
            {"objective": "multiclass", "num_class": 3, "num_leaves": 15,
             "verbosity": -1, "tree_learner": learner,
             "tpu_double_precision_hist": True},
            lgb.Dataset(X, label=y.astype(float)), num_boost_round=10)
        preds[learner] = bst.predict(X)
    acc_s = np.mean(np.argmax(preds["serial"], 1) == y)
    acc_d = np.mean(np.argmax(preds["data"], 1) == y)
    assert acc_d > 0.85
    assert abs(acc_s - acc_d) < 0.01


def test_lambdarank_under_data_parallel():
    rng = np.random.default_rng(13)
    n_q, per_q = 60, 20
    X = rng.normal(size=(n_q * per_q, 6))
    y = np.minimum(np.clip(X[:, 0] * 1.5
                           + rng.normal(scale=0.4, size=len(X)),
                           0, None).astype(int), 4)
    group = np.full(n_q, per_q)
    res = {}
    ds = lgb.Dataset(X, label=y, group=group)
    bst = lgb.train(
        {"objective": "lambdarank", "num_leaves": 15, "metric": "ndcg",
         "ndcg_eval_at": [5], "verbosity": -1, "tree_learner": "data"},
        ds, num_boost_round=20,
        valid_sets=[ds.create_valid(X, label=y, group=group)],
        callbacks=[lgb.record_evaluation(res)])
    assert res["valid_0"]["ndcg@5"][-1] > 0.75


def test_histogram_count_channel_exact_under_psum():
    """VERDICT r2 weak #5: the count channel is integer-valued, so the
    psum reduction order is irrelevant and sharded == single-device must
    hold EXACTLY (not within tolerance)."""
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P
    from lightgbm_tpu.ops.pallas_histogram import multi_leaf_histogram_xla
    from lightgbm_tpu.parallel.mesh import shard_map

    rng = np.random.default_rng(21)
    n, F, B, K = 4096, 6, 32, 4
    bins = rng.integers(0, B, size=(n, F)).astype(np.uint8)
    vals = rng.normal(size=(n, 3)).astype(np.float32)
    vals[:, 2] = 1.0
    leaf_id = rng.integers(0, K, size=n).astype(np.int32)
    small = np.arange(K, dtype=np.int32)

    full = np.asarray(multi_leaf_histogram_xla(
        jnp.asarray(bins), jnp.asarray(vals), jnp.asarray(leaf_id),
        jnp.asarray(small), num_bins=B, rows_per_block=512))

    mesh = create_data_mesh()

    def sharded(b, v, l, s):
        h = multi_leaf_histogram_xla(b, v, l, s, num_bins=B,
                                     rows_per_block=512)
        return jax.lax.psum(h, "data")

    fn = shard_map(sharded, mesh=mesh,
                   in_specs=(P("data", None), P("data", None),
                             P("data"), P()),
                   out_specs=P(), check_vma=False)
    dist = np.asarray(fn(
        jax.device_put(bins, NamedSharding(mesh, P("data", None))),
        jax.device_put(vals, NamedSharding(mesh, P("data", None))),
        jax.device_put(leaf_id, NamedSharding(mesh, P("data"))),
        jax.device_put(small, NamedSharding(mesh, P()))))
    # count channel: EXACT
    np.testing.assert_array_equal(dist[..., 2], full[..., 2])
    # float channels agree within reduction-order noise
    np.testing.assert_allclose(dist[..., :2], full[..., :2],
                               rtol=1e-4, atol=1e-4)


def test_quantized_distributed_exactly_matches_serial():
    """VERDICT r2 weak #5: quantized (integer) histograms make the psum
    reduction exact, so with deterministic rounding the data-parallel
    model must equal the serial one exactly — not within tolerance."""
    X, y = _binary_data(n=2000, f=6, seed=22)
    preds = {}
    for learner in ("serial", "data"):
        bst = lgb.train(
            {"objective": "binary", "num_leaves": 15, "verbosity": -1,
             "tree_learner": learner, "min_data_in_leaf": 5,
             "use_quantized_grad": True, "stochastic_rounding": False},
            lgb.Dataset(X, label=y), num_boost_round=10)
        preds[learner] = bst.predict(X, raw_score=True)
    np.testing.assert_array_equal(preds["serial"], preds["data"])


def test_goss_under_data_parallel():
    X, y = _binary_data(n=4000, f=8, seed=14)
    bst = lgb.train(
        {"objective": "binary", "num_leaves": 15, "verbosity": -1,
         "tree_learner": "data", "data_sample_strategy": "goss",
         "learning_rate": 0.2},
        lgb.Dataset(X, label=y), num_boost_round=25)
    from lightgbm_tpu.metric import AUCMetric
    from lightgbm_tpu.config import Config
    auc = AUCMetric(Config({})).eval(bst.predict(X), y, None)[0][1]
    assert auc > 0.9
