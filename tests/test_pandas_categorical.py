"""pandas category-dtype interop (stock lightgbm's pandas_categorical).

Reference: python-package/lightgbm/basic.py _data_from_pandas +
pandas_categorical model-file field (UNVERIFIED — empty mount, see
SURVEY.md banner): category columns train on their integer codes, the
category-value lists are stored in the model, and predict-time frames
are remapped BY VALUE through the stored lists so category order or
new unseen values cannot silently shift codes.
"""
import numpy as np
import pytest

pd = pytest.importorskip("pandas")

import lightgbm_tpu as lgb
from lightgbm_tpu.io.dataset import (apply_pandas_categorical,
                                     extract_pandas_categorical)


def _frame(n=3000, seed=7):
    rng = np.random.default_rng(seed)
    color = rng.choice(["red", "green", "blue", "mauve"], size=n)
    x0 = rng.normal(size=n)
    x1 = rng.normal(size=n)
    y = ((color == "red") * 1.5 + (color == "mauve") * -1.0
         + x0 + rng.normal(scale=0.3, size=n) > 0.5).astype(np.float64)
    df = pd.DataFrame({
        "color": pd.Categorical(color),
        "x0": x0,
        "x1": x1,
    })
    return df, y


def _simple_auc(y, p):
    order = np.argsort(p)
    ranks = np.empty(len(p))
    ranks[order] = np.arange(1, len(p) + 1)
    npos = y.sum()
    nneg = len(y) - npos
    return (ranks[y > 0].sum() - npos * (npos + 1) / 2) / (npos * nneg)


def test_category_column_carries_signal():
    df, y = _frame()
    bst = lgb.train({"objective": "binary", "num_leaves": 15,
                     "verbosity": -1}, lgb.Dataset(df, label=y),
                    num_boost_round=15)
    assert _simple_auc(y, bst.predict(df)) > 0.85
    # auto-detection made the column categorical: some tree splits it
    imp = dict(zip(bst.feature_name(), bst.feature_importance()))
    assert imp.get("color", 0) > 0


def test_predict_reordered_categories_matches():
    df, y = _frame()
    bst = lgb.train({"objective": "binary", "num_leaves": 15,
                     "verbosity": -1}, lgb.Dataset(df, label=y),
                    num_boost_round=10)
    base = bst.predict(df)
    # same VALUES, different category order and dtype declaration —
    # remapping by value must give identical predictions
    df2 = df.copy()
    df2["color"] = pd.Categorical(
        np.asarray(df["color"]),
        categories=["mauve", "blue", "green", "red"])
    np.testing.assert_allclose(bst.predict(df2), base,
                               rtol=1e-12, atol=1e-12)


def test_unseen_category_routes_like_missing():
    df, y = _frame()
    bst = lgb.train({"objective": "binary", "num_leaves": 15,
                     "verbosity": -1}, lgb.Dataset(df, label=y),
                    num_boost_round=10)
    df2 = df.iloc[:200].copy()
    df2["color"] = pd.Categorical(
        ["chartreuse"] * 200,
        categories=list(df["color"].cat.categories) + ["chartreuse"])
    # unseen value -> NaN code -> bitset miss -> same as NaN input
    df3 = df.iloc[:200].copy()
    df3["color"] = pd.Categorical(
        [None] * 200, categories=df["color"].cat.categories)
    np.testing.assert_allclose(bst.predict(df2), bst.predict(df3),
                               rtol=1e-12, atol=1e-12)


def test_model_text_roundtrip_keeps_mapping():
    df, y = _frame()
    bst = lgb.train({"objective": "binary", "num_leaves": 15,
                     "verbosity": -1}, lgb.Dataset(df, label=y),
                    num_boost_round=8)
    s = bst.model_to_string()
    assert "pandas_categorical:[[" in s
    bst2 = lgb.Booster(model_str=s)
    np.testing.assert_allclose(bst2.predict(df), bst.predict(df),
                               rtol=1e-5, atol=1e-6)
    # and the loaded model still remaps reordered frames by value
    df2 = df.copy()
    df2["color"] = pd.Categorical(
        np.asarray(df["color"]),
        categories=["blue", "red", "mauve", "green"])
    np.testing.assert_allclose(bst2.predict(df2), bst2.predict(df),
                               rtol=1e-12, atol=1e-12)


def test_valid_set_shares_training_mapping():
    df, y = _frame()
    tr, va = df.iloc[:2000], df.iloc[2000:]
    ytr, yva = y[:2000], y[2000:]
    # give the valid frame a different category order on purpose
    va = va.copy()
    va["color"] = pd.Categorical(
        np.asarray(va["color"]),
        categories=["green", "mauve", "red", "blue"])
    ds = lgb.Dataset(tr, label=ytr)
    vs = ds.create_valid(va, label=yva)
    evals = {}
    bst = lgb.train({"objective": "binary", "num_leaves": 15,
                     "metric": "auc", "verbosity": -1}, ds,
                    num_boost_round=10, valid_sets=[vs],
                    valid_names=["va"],
                    callbacks=[lgb.record_evaluation(evals)])
    # the valid AUC only makes sense if codes agree across frames
    assert evals["va"]["auc"][-1] > 0.8
    np.testing.assert_allclose(
        bst.predict(va), bst.predict(df.iloc[2000:]),
        rtol=1e-12, atol=1e-12)


def test_mismatched_cat_columns_fatal():
    df, y = _frame()
    bst = lgb.train({"objective": "binary", "num_leaves": 15,
                     "verbosity": -1},
                    lgb.Dataset(df[["x0", "x1"]], label=y),
                    num_boost_round=3)
    bad = df.copy()[["color", "x0"]]
    with pytest.raises(Exception, match="category-dtype"):
        bst.predict(bad)


def test_interval_categories_rejected_at_construct():
    rng = np.random.default_rng(3)
    x = rng.normal(size=500)
    df = pd.DataFrame({"b": pd.cut(x, 4), "x": x})
    y = (x > 0).astype(np.float64)
    with pytest.raises(Exception, match="JSON-serializable"):
        lgb.train({"objective": "binary", "verbosity": -1},
                  lgb.Dataset(df, label=y), num_boost_round=2)


def test_helpers_roundtrip():
    df, _ = _frame(n=50)
    cats = extract_pandas_categorical(df)
    assert cats == [list(df["color"].cat.categories)]
    out = apply_pandas_categorical(df, cats)
    col = np.asarray(out["color"], dtype=np.float64)
    assert np.nanmax(col) <= len(cats[0]) - 1
    # plain arrays pass through untouched
    arr = np.zeros((3, 2))
    assert apply_pandas_categorical(arr, None) is arr
